"""Procedural forest environment with closed-form collision distance queries in JAX.

TPU-native replacement for reference ``example/env_forest.py`` (+ the hppfcl API
subset it uses, SURVEY.md §2.9): a forest of cylinder trees (r = 0.3 m, h = 4 m) on
a spherical-cap "mountain", queried for distance/witness-points against the
system's braking capsule by the controllers' collision CBFs.

Design (vs reference):
- Tree generation (reference ``_generate_trees``, :47-85) runs host-side at setup
  with a seeded numpy RNG — same rejection-sampling semantics — but emits a
  **fixed-size** ``(max_trees, 3)`` array + validity mask so every downstream query
  has static shapes; invalid slots are parked far away (1e6) and masked.
- hppfcl's GJK capsule-vs-cylinder distance (:139-212) is replaced by an *exact*
  closed-form point-to-cylinder distance minimized along the capsule axis: the
  distance from the affine point ``x(t) = a + t (b - a)`` to a convex set is
  convex in ``t``, so a parallel grid evaluation brackets the minimizer in ONE
  batched op and a short golden-section refinement pins it — branch-free,
  vmapped over all trees, with a serial chain of ~7 ops instead of an
  iterative GJK (see ``segment_cylinder_distance``).
- The reference's per-call Python tree loop + ``np.argpartition`` top-k becomes a
  masked ``lax.top_k`` producing the fixed ``n_env_cbfs`` CBF rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from tpu_aerial_transport.control.types import EnvCBF
from tpu_aerial_transport.obs import phases

# Reference constants (env_forest.py:22-31).
MOUNTAIN_CENTER = np.array([30.0, 0.0])
MOUNTAIN_RADIUS = 25.0
MOUNTAIN_HEIGHT = 7.5
BARK_HEIGHT = 4.0
BARK_RADIUS = 0.3
MIN_DIST_BETWEEN_TREES = 3.2
MAX_TREES = 200

_FAR = 1.0e6
# Grid-bracket + refine: _GRID_PTS parallel evaluations localize the convex
# minimizer to a 2/(_GRID_PTS-1) bracket (one wide batched op, no serial
# chain), then _REFINE_ITERS golden-section steps shrink it by 0.618^iters.
# 33 grid points + 12 refinements bracket the minimizer to
# 0.06 * 0.618^12 ~ 2e-4 of the segment (sub-mm even for a multi-metre
# segment; at a kink of the piecewise distance map the error is first-order
# in the bracket) — far below the 0.1 m CBF margin, with a serial chain of
# ~13 ops vs the 28 sequential golden iterations this replaces, which
# dominated the env query's TPU latency.
_GRID_PTS = 33
_REFINE_ITERS = 12

# Braking-time floor [s] for CBF rows of obstacles inside ``dist_eps`` (see
# cbf_rows_from_distance near-contact hardening): keeps the row coefficient
# ``normal * min_time`` usable when the reference's formula degenerates to
# zero at contact. 0.2 s turns a typical near-contact rhs (~0.2-0.6 m/s
# scale) into a 1-3 m/s^2 outward-acceleration demand — firm, and well
# inside the thrust envelope so the agent QPs stay feasible.
NEAR_BRAKE_TIME = 0.2
_INV_PHI = 0.6180339887498949


@struct.dataclass
class Forest:
    """Fixed-shape forest pytree. ``tree_pos[i]`` is the *center* of tree i's
    cylinder (z = mid-height, reference :85); invalid slots sit at ``1e6``."""

    tree_pos: jnp.ndarray  # (max_trees, 3).
    tree_valid: jnp.ndarray  # (max_trees,) bool.
    num_trees: jnp.ndarray  # () int32.
    mountain_sphere_radius: jnp.ndarray  # ().
    mountain_center_depth: jnp.ndarray  # ().

    bark_radius: float = struct.field(pytree_node=False, default=BARK_RADIUS)
    bark_height: float = struct.field(pytree_node=False, default=BARK_HEIGHT)
    # Optional spatial-hash bucketing artifact (envs/spatial.py
    # SpatialGrid, attached by ``spatial.with_grid``): per-cell candidate
    # index slabs over the tree XY plane, consumed by the ``"bucketed"``
    # environment-query tier. None (the default) leaves every existing
    # construction/query path — and the dense query's compiled HLO —
    # untouched; the grid rides the Forest pytree through rollouts, mesh,
    # pods and serving with zero extra plumbing.
    grid: "object | None" = None


def _mountain_geometry():
    ang = np.pi / 2.0 - np.arctan2(MOUNTAIN_RADIUS, MOUNTAIN_HEIGHT)
    sphere_radius = MOUNTAIN_RADIUS / np.sin(ang)
    return sphere_radius, sphere_radius * np.cos(ang)


def _ground_np(sphere_radius, center_depth, d2):
    """Terrain height at squared mountain distance ``d2`` (numpy twin of
    :func:`ground_height`): 0 off the spherical cap — the radicand clip
    matters for city-scale worlds whose trees extend far beyond the
    mountain (the unclipped form is NaN there)."""
    return np.maximum(
        np.sqrt(np.maximum(sphere_radius**2 - d2, 0.0)) - center_depth, 0.0
    )


def make_forest(seed: int = 0, max_trees: int = MAX_TREES,
                dtype=jnp.float32, *, world_size: float | None = None,
                density: float | None = None) -> Forest:
    """Seeded forest generation.

    Default (``world_size=None``): the reference's rejection sampling
    (:47-85) — up to ``max_trees`` trees with min spacing 3.2 m inside the
    25 m mountain disc, the first tree pinned at center + (0.5, 0.5); tree
    base follows the spherical-cap terrain, center
    z = (ground_height + bark_height) / 2.

    City-scale (``world_size`` given, in metres): trees on a seeded
    jittered grid over the ``world_size`` x ``world_size`` square centered
    on the mountain, ``density`` trees/m^2 (default: the tightest packing
    the reference spacing admits, ``1 / MIN_DIST_BETWEEN_TREES^2``). The
    jitter amplitude keeps every pair at least ``MIN_DIST_BETWEEN_TREES``
    apart; a density whose grid pitch falls below that spacing is refused.
    The tree count implied by ``(world_size, density)`` must fit
    ``max_trees`` — a world that would overflow the fixed-shape slot array
    is a clear ``ValueError`` naming the required ``max_trees``, never a
    silent mask truncation. Worlds above the dense-query class
    (``spatial.DENSE_AUTO_MAX_TREES``) should attach a spatial-hash grid
    (``envs.spatial.with_grid``) for the bucketed query tier."""
    rng = np.random.default_rng(seed)
    if density is not None and world_size is None:
        raise ValueError("density= requires world_size=")
    if world_size is not None:
        if density is None:
            density = 1.0 / MIN_DIST_BETWEEN_TREES**2
        pitch = 1.0 / np.sqrt(density)
        if pitch < MIN_DIST_BETWEEN_TREES:
            raise ValueError(
                f"density={density} gives a grid pitch of {pitch:.2f} m, "
                f"below the {MIN_DIST_BETWEEN_TREES} m minimum tree "
                "spacing — reduce density to at most "
                f"{1.0 / MIN_DIST_BETWEEN_TREES**2:.4f} trees/m^2"
            )
        n_side = max(int(np.floor(world_size / pitch)), 1)
        num = n_side * n_side
        if num > max_trees:
            raise ValueError(
                f"world_size={world_size} at density={density} needs "
                f"{num} tree slots but max_trees={max_trees} — pass "
                f"max_trees>={num} (refusing to silently truncate the "
                "world to the first max_trees grid rows)"
            )
        # Jittered grid: cell centers at pitch spacing, uniform jitter
        # bounded so neighboring trees keep the reference min spacing.
        jitter = max((pitch - MIN_DIST_BETWEEN_TREES) / 2.0, 0.0)
        base = (np.arange(n_side) + 0.5) * pitch - world_size / 2.0
        gx, gy = np.meshgrid(base, base, indexing="ij")
        tree_xy = np.stack([gx.ravel(), gy.ravel()], axis=1)
        tree_xy += rng.uniform(-jitter, jitter, size=tree_xy.shape)
        tree_xy += MOUNTAIN_CENTER
    else:
        tree_xy = [MOUNTAIN_CENTER + np.array([0.5, 0.5])]
        for _ in range(max_trees * 50):
            if len(tree_xy) >= max_trees:
                break
            pos = rng.random(2) - 0.5
            norm = np.linalg.norm(pos)
            if norm == 0:
                continue
            pos = pos / norm * rng.random() * MOUNTAIN_RADIUS + MOUNTAIN_CENTER
            if np.min(np.linalg.norm(np.array(tree_xy) - pos, axis=1)) \
                    < MIN_DIST_BETWEEN_TREES:
                continue
            tree_xy.append(pos)
        tree_xy = np.array(tree_xy)
    num = len(tree_xy)

    sphere_radius, center_depth = _mountain_geometry()

    pos3 = np.full((max_trees, 3), _FAR)
    pos3[:num, :2] = tree_xy
    d2 = np.sum((tree_xy - MOUNTAIN_CENTER) ** 2, axis=1)
    ground = _ground_np(sphere_radius, center_depth, d2)
    pos3[:num, 2] = (ground + BARK_HEIGHT) / 2.0
    valid = np.arange(max_trees) < num
    return Forest(
        tree_pos=jnp.asarray(pos3, dtype),
        tree_valid=jnp.asarray(valid),
        num_trees=jnp.asarray(num, jnp.int32),
        mountain_sphere_radius=jnp.asarray(sphere_radius, dtype),
        mountain_center_depth=jnp.asarray(center_depth, dtype),
    )


def forest_from_tree_pos(tree_pos, num_trees, max_trees: int = MAX_TREES,
                         dtype=jnp.float32) -> Forest:
    """Rebuild a Forest from logged tree positions (replay path; reference
    rqp_plots.py:503-505 reconstructs the env from the log the same way).
    Refuses more positions than ``max_trees`` slots — truncating a logged
    world would silently delete obstacles from the replayed queries."""
    tree_pos = np.asarray(tree_pos)
    if tree_pos.shape[0] > max_trees:
        raise ValueError(
            f"{tree_pos.shape[0]} logged tree positions do not fit "
            f"max_trees={max_trees} slots — pass "
            f"max_trees>={tree_pos.shape[0]} (refusing to silently drop "
            "obstacles from the replayed world)"
        )
    pos3 = np.full((max_trees, 3), _FAR)
    pos3[: tree_pos.shape[0]] = tree_pos
    sphere_radius, center_depth = _mountain_geometry()
    return Forest(
        tree_pos=jnp.asarray(pos3, dtype),
        tree_valid=jnp.asarray(np.arange(max_trees) < tree_pos.shape[0]),
        num_trees=jnp.asarray(num_trees, jnp.int32),
        mountain_sphere_radius=jnp.asarray(sphere_radius, dtype),
        mountain_center_depth=jnp.asarray(center_depth, dtype),
    )


def ground_height(forest: Forest, xy: jnp.ndarray) -> jnp.ndarray:
    """Terrain height of the spherical-cap mountain at ``xy (..., 2)`` (0 on flat
    ground). Used by the terrain-following reference trajectory
    (example/rqp_example.py:33-59)."""
    c = jnp.asarray(MOUNTAIN_CENTER, xy.dtype)
    d2 = jnp.sum((xy - c) ** 2, axis=-1)
    r2 = forest.mountain_sphere_radius**2
    h = jnp.sqrt(jnp.maximum(r2 - d2, 0.0)) - forest.mountain_center_depth
    return jnp.maximum(h, 0.0)


def point_cylinder_distance(p, center, radius, half_height):
    """Exact distance from point(s) ``p (..., 3)`` to a z-aligned flat-capped
    cylinder; negative inside (max of the two penetration depths). Also returns
    the closest point on the cylinder surface/volume boundary."""
    dxy = p[..., :2] - center[..., :2]
    rho = jnp.linalg.norm(dxy, axis=-1)
    dz = p[..., 2] - center[..., 2]
    d_rad = rho - radius
    d_ax = jnp.abs(dz) - half_height
    outside = jnp.sqrt(jnp.maximum(d_rad, 0.0) ** 2 + jnp.maximum(d_ax, 0.0) ** 2)
    inside = jnp.maximum(d_rad, d_ax)  # both <= 0 here.
    dist = jnp.where((d_rad <= 0.0) & (d_ax <= 0.0), inside, outside)

    # Closest point on the cylinder SURFACE (witness/normal computation).
    # Interior points project to the nearest boundary (wall or cap,
    # whichever is closer) rather than to themselves: a self-witness would
    # zero the outward normal exactly where penetration-protective CBF rows
    # need it (cbf_rows_from_distance near-contact hardening). Points on
    # the cylinder axis (rho ~ 0) pick an arbitrary but fixed radial
    # direction so the witness stays defined.
    on_axis = rho <= 1e-12
    safe_rho = jnp.where(on_axis, 1.0, rho)
    u = jnp.where(
        on_axis[..., None],
        jnp.broadcast_to(jnp.array([1.0, 0.0], p.dtype), dxy.shape),
        dxy / safe_rho[..., None],
    )
    is_inside = (d_rad <= 0.0) & (d_ax <= 0.0)
    wall_closer = d_rad >= d_ax  # both <= 0 inside: larger = nearer face.
    # Exterior: clamp into the cylinder as before.
    ext_xy = center[..., :2] + u * jnp.minimum(rho, radius)[..., None]
    ext_z = center[..., 2] + jnp.clip(dz, -half_height, half_height)
    # Interior: radial wall or the nearer cap.
    int_xy = jnp.where(wall_closer[..., None],
                       center[..., :2] + u * radius, p[..., :2])
    cap_z = center[..., 2] + jnp.where(dz >= 0.0, half_height, -half_height)
    int_z = jnp.where(wall_closer, p[..., 2], cap_z)
    cp_xy = jnp.where(is_inside[..., None], int_xy, ext_xy)
    cp_z = jnp.where(is_inside, int_z, ext_z)
    closest = jnp.concatenate([cp_xy, cp_z[..., None]], axis=-1)
    return dist, closest


def segment_cylinder_distance(a, b, center, radius, half_height):
    """Distance between segment ``[a, b]`` and a z-aligned cylinder.

    The map ``t -> dist(x(t), cylinder)`` is convex on [0, 1], so a parallel
    ``_GRID_PTS``-point evaluation (one batched op — all grid points and all
    trees at once) brackets the minimizer to the two adjacent cells, and
    ``_REFINE_ITERS`` golden-section steps refine it. Total serial depth
    ~1 + _REFINE_ITERS vs a pure iterative search.
    Returns ``(dist, point_on_segment, point_on_cylinder)``."""
    def dist_at(t):
        p = a + t[..., None] * (b - a)
        d, _ = point_cylinder_distance(p, center, radius, half_height)
        return d

    shape = jnp.broadcast_shapes(a.shape[:-1], center.shape[:-1])
    ts = jnp.linspace(0.0, 1.0, _GRID_PTS)  # (G,)
    # Evaluate on the grid: (..., G).
    grid_d = jax.vmap(dist_at, in_axes=-1, out_axes=-1)(
        jnp.broadcast_to(ts, shape + (_GRID_PTS,))
    )
    i_min = jnp.argmin(grid_d, axis=-1)
    cell = 1.0 / (_GRID_PTS - 1)
    t_lo = jnp.clip(i_min.astype(a.dtype) * cell - cell, 0.0, 1.0)
    t_hi = jnp.clip(i_min.astype(a.dtype) * cell + cell, 0.0, 1.0)

    def body(_, carry):
        lo, hi = carry
        m1 = hi - _INV_PHI * (hi - lo)
        m2 = lo + _INV_PHI * (hi - lo)
        f1, f2 = dist_at(m1), dist_at(m2)
        smaller1 = f1 < f2
        return jnp.where(smaller1, lo, m1), jnp.where(smaller1, m2, hi)

    t_lo, t_hi = lax.fori_loop(0, _REFINE_ITERS, body, (t_lo, t_hi))
    t = 0.5 * (t_lo + t_hi)
    p = a + t[..., None] * (b - a)
    dist, closest = point_cylinder_distance(p, center, radius, half_height)
    return dist, p, closest


@struct.dataclass
class DistanceData:
    """Fixed-shape result of an environment distance sweep (the reference returns
    ragged Python lists, env_forest.py:139-167; we return all ``max_trees`` slots
    with a mask)."""

    dists: jnp.ndarray  # (max_trees,) capsule-to-tree distance; +inf when masked.
    pts_sys: jnp.ndarray  # (max_trees, 3) witness on the system capsule surface.
    pts_env: jnp.ndarray  # (max_trees, 3) witness on the tree.
    # Outward unit normal (obstacle -> system), sign-corrected from the
    # AXIS-level geometry: the surface-witness difference pts_sys - pts_env
    # flips direction when the inflated capsule penetrates the tree (the
    # surface points cross), which would invert a CBF row exactly at
    # contact; this field stays outward through penetration.
    normal_out: jnp.ndarray  # (max_trees, 3)
    mask: jnp.ndarray  # (max_trees,) bool — tree valid & within vision radius.
    collision: jnp.ndarray  # () bool, any dist < 1e-4.
    min_dist: jnp.ndarray  # () min over mask (vision_radius if none).


def capsule_distance_data(
    centers: jnp.ndarray,
    valid: jnp.ndarray,
    bark_radius,
    bark_height,
    cap_a: jnp.ndarray,
    cap_b: jnp.ndarray,
    cap_radius,
    vision_radius,
    vision_mask=None,
) -> DistanceData:
    """Distance sweep from the capsule with axis ``[cap_a, cap_b]`` and
    radius ``cap_radius`` to the trees at ``centers (N, 3)`` with validity
    ``valid (N,)`` — the per-tree math of :func:`capsule_forest_distance`,
    factored over an arbitrary tree set so the bucketed query tier
    (envs/spatial.py) can run the EXACT same ops over a gathered candidate
    slab: every op below is elementwise along the tree axis, so a tree's
    dist/witness/normal values are bitwise identical whether it sits in
    the full ``(max_trees,)`` sweep or a ``(K,)`` candidate slab."""
    dist_axis, p_seg, p_cyl = segment_cylinder_distance(
        cap_a[None, :], cap_b[None, :], centers,
        bark_radius, bark_height / 2.0,
    )
    dists = dist_axis - cap_radius
    # Witness point on the capsule surface: offset from the axis toward the tree.
    normal = p_cyl - p_seg
    nn = jnp.linalg.norm(normal, axis=-1, keepdims=True)
    valid_n = nn[:, 0] > 1e-12
    normal = normal / jnp.where(nn > 1e-12, nn, 1.0)
    pts_sys = p_seg + cap_radius * normal
    # Outward (obstacle -> system) unit normal from the signed axis-level
    # distance: -normal while the capsule axis is outside the tree surface
    # (dist_axis >= 0, the ordinary case — identical to normalizing
    # pts_sys - pts_env), +normal when the axis is inside the bark
    # (dist_axis < 0, where the surface-witness difference would flip).
    # The dist_axis >= 0 -> -1 convention keeps the normal (and so the
    # protecting CBF row) alive at EXACT axis-surface contact, where
    # -sign(0) = 0 used to zero the row at the worst possible moment; when
    # the surface witnesses themselves coincide there (zero witness
    # difference), a surface-consistent fallback direction stands in:
    # the outward RADIAL direction from the tree axis while the witness
    # sits on the lateral (bark) surface, the SIGNED VERTICAL direction
    # when it sits on a flat cap (a horizontal normal there would point
    # the protecting row sideways instead of off the cap).
    radial = p_seg[:, :2] - centers[:, :2]
    rn = jnp.linalg.norm(radial, axis=-1, keepdims=True)
    dz_seg = p_seg[:, 2] - centers[:, 2]
    on_wall = (jnp.abs(dz_seg)[:, None] < bark_height / 2.0) & (
        rn > 1e-12
    )
    radial_dir = jnp.concatenate(
        [radial / jnp.where(rn > 1e-12, rn, 1.0), jnp.zeros_like(rn)],
        axis=-1,
    )
    vertical_dir = jnp.concatenate(
        [jnp.zeros_like(radial), jnp.where(dz_seg >= 0, 1.0, -1.0)[:, None]],
        axis=-1,
    )
    normal_out = jnp.where(
        valid_n[:, None],
        jnp.where(dist_axis >= 0, -1.0, 1.0)[:, None] * normal,
        jnp.where(on_wall, radial_dir, vertical_dir),
    )

    # Vision gating mirrors the reference: the query capsule's hppfcl transform
    # translation is its *midpoint* (rqp_centralized.py:302-305 places the
    # capsule center at xl + (h/2) dir), and env_forest.py:151-154 gates on the
    # distance from that translation to the tree center.
    cap_mid = 0.5 * (cap_a + cap_b)
    in_range = (
        jnp.linalg.norm(centers - cap_mid[None, :], axis=-1)
        <= vision_radius + bark_radius
    )
    mask = valid & in_range
    if vision_mask is not None:
        mask = mask & vision_mask
    dists = jnp.where(mask, dists, jnp.inf)
    collision = jnp.any(jnp.where(mask, dists < 1e-4, False))
    min_dist = jnp.min(jnp.where(mask, dists, vision_radius))
    return DistanceData(
        dists=dists, pts_sys=pts_sys, pts_env=p_cyl, normal_out=normal_out,
        mask=mask, collision=collision, min_dist=min_dist,
    )


def capsule_forest_distance(
    forest: Forest,
    cap_a: jnp.ndarray,
    cap_b: jnp.ndarray,
    cap_radius,
    vision_radius,
    vision_mask=None,
) -> DistanceData:
    """Distance from the capsule with axis ``[cap_a, cap_b]`` and radius
    ``cap_radius`` to every tree (reference ``centralized_distance``; pass
    ``vision_mask`` for the per-agent cone of ``distributed_distance``).
    The dense O(max_trees) sweep; the bucketed tier
    (``envs.spatial.env_query_bucketed``) runs the same
    :func:`capsule_distance_data` core over a grid-gathered candidate
    slab instead."""
    with phases.scope(phases.ENV_QUERY):
        return capsule_distance_data(
            forest.tree_pos, forest.tree_valid, forest.bark_radius,
            forest.bark_height, cap_a, cap_b, cap_radius, vision_radius,
            vision_mask,
        )


def cone_mask_at(centers, camera_pos, direction, half_angle):
    """:func:`vision_cone_mask` over an arbitrary tree set ``centers
    (N, 3)`` — elementwise per tree, so a candidate slab's cone mask is
    bitwise the gathered full-world mask (the bucketed tier's per-agent
    vision-cone reuse)."""
    d = centers[:, :2] - camera_pos[None, :2]
    norm = jnp.linalg.norm(d, axis=-1)
    safe = jnp.where(norm > 0, norm, 1.0)
    cosang = jnp.sum(d / safe[:, None] * direction[None, :2], axis=-1)
    return (norm == 0.0) | (cosang >= jnp.cos(half_angle))


def vision_cone_mask(forest: Forest, camera_pos, direction, half_angle):
    """Per-agent 2-D vision-cone mask (reference ``distributed_distance``,
    env_forest.py:169-212): keep trees whose bearing from ``camera_pos`` (2-D) is
    within ``half_angle`` of ``direction``; trees at zero range are always kept."""
    return cone_mask_at(forest.tree_pos, camera_pos, direction, half_angle)


def braking_capsule(xl, vl, collision_radius, max_deceleration):
    """The system's braking capsule (reference
    ``_set_collision_avoidance_cbf_parameters``, control/rqp_centralized.py:292-305):
    radius = bounding-sphere radius, axis from the payload along the velocity with
    length = stopping distance ``||v||^2 / (2 a_max)``."""
    speed = jnp.linalg.norm(vl)
    height = 0.5 * speed**2 / max_deceleration
    direction = vl / jnp.where(speed > 0, speed, 1.0)
    cap_a = xl
    cap_b = xl + jnp.where(speed > 0, height, 0.0) * direction
    return cap_a, cap_b, height, speed, direction


def collision_cbf_rows(
    forest: Forest | None,
    xl, vl,
    collision_radius,
    max_deceleration,
    vision_radius,
    dist_eps,
    alpha_env_cbf,
    n_rows: int,
    vision_mask=None,
    env_query: str = "dense",
) -> EnvCBF:
    """Backup-CBF rows for the nearest ``n_rows`` obstacles (reference
    :280-337): for each selected tree, row ``(normal * min_time) @ dvl >=
    -alpha (d - eps) - normal . vl`` where ``min_time`` is the remaining braking
    time before closest approach. Fixed shapes via masked ``lax.top_k``.

    ``env_query`` selects the distance-sweep implementation
    (``envs.spatial.resolve_env_query`` vocabulary: "auto" | "dense" |
    "bucketed"): "dense" (the default — byte-identical program to the
    historical call) sweeps all ``max_trees`` slots; "bucketed" gathers
    the forest's spatial-hash candidate slab (``forest.grid``, attached
    by ``spatial.with_grid``) and runs the same per-tree math over
    candidates only — EnvCBF rows bitwise equal to dense wherever the
    grid's coverage radius admits the query (guaranteed at build);
    "auto" picks by static world size at trace time."""
    dtype = xl.dtype
    inactive_rhs = -alpha_env_cbf * (vision_radius - dist_eps)
    if forest is None:
        return EnvCBF(
            lhs=jnp.zeros((n_rows, 3), dtype),
            rhs=jnp.full((n_rows,), inactive_rhs, dtype),
            collision=jnp.zeros((), bool),
            min_dist=jnp.asarray(vision_radius, dtype),
        )

    cap_a, cap_b, cap_h, speed, cap_dir = braking_capsule(
        xl, vl, collision_radius, max_deceleration
    )
    from tpu_aerial_transport.envs import spatial  # cycle: spatial uses us.

    mode = spatial.runtime_env_query(env_query, forest)
    if mode == "bucketed":
        data, _, _ = spatial.bucketed_distance(
            forest, cap_a, cap_b, collision_radius, vision_radius,
            vision_mask=vision_mask, n_rows=n_rows,
        )
    else:
        data = capsule_forest_distance(
            forest, cap_a, cap_b, collision_radius, vision_radius,
            vision_mask,
        )
    return cbf_rows_from_distance(
        data, xl, vl, cap_h, speed, cap_dir, max_deceleration,
        vision_radius, dist_eps, alpha_env_cbf, n_rows,
    )


def cbf_rows_from_distance(
    data: DistanceData,
    xl, vl, cap_h, speed, cap_dir,
    max_deceleration, vision_radius, dist_eps, alpha_env_cbf,
    n_rows: int,
    extra_mask=None,
) -> EnvCBF:
    """Row construction from a precomputed distance sweep. Split out so the
    expensive golden-section sweep can be computed ONCE and reused across agents
    whose queries differ only by vision-cone mask (``extra_mask``) — the
    per-agent distributed queries in rqp_cadmm/rqp_dd all use the same braking
    capsule (reference :319-332)."""
    dtype = xl.dtype
    inactive_rhs = -alpha_env_cbf * (vision_radius - dist_eps)
    mask = data.mask if extra_mask is None else (data.mask & extra_mask)
    dists = jnp.where(mask, data.dists, jnp.inf)
    data = data.replace(
        dists=dists,
        mask=mask,
        collision=jnp.any(jnp.where(mask, dists < 1e-4, False)),
        min_dist=jnp.min(jnp.where(mask, dists, vision_radius)),
    )

    # Top-k nearest (masked): top_k on negated distance.
    neg = jnp.where(data.mask, -data.dists, -jnp.inf)
    _, idx = lax.top_k(neg, n_rows)
    sel_mask = jnp.take(data.mask, idx)
    d = jnp.take(data.dists, idx)
    p1 = jnp.take(data.pts_sys, idx, axis=0)

    # Remaining braking time before the closest-approach point (reference
    # :324-329): proj = clamp(<p1 - xl, dir>, 0, h);
    # min_time = max(0, ||v||/a - sqrt(2 (h - proj) / a)).
    proj = jnp.clip(jnp.sum((p1 - xl[None, :]) * cap_dir[None, :], axis=-1),
                    0.0, cap_h)
    min_time = jnp.maximum(
        0.0,
        speed / max_deceleration
        - jnp.sqrt(jnp.maximum(2.0 * (cap_h - proj) / max_deceleration, 0.0)),
    )
    # Outward normal, sign-corrected through penetration (DistanceData
    # docstring): zero rows only where the direction itself is undefined.
    normal = jnp.take(data.normal_out, idx, axis=0)
    n_valid = jnp.sum(normal * normal, axis=-1) > 0.5

    # Near-contact hardening (DELIBERATE deviation from the reference,
    # which drops rows at dist < 1e-4, :322, and whose braking-time
    # coefficient goes to ZERO for an obstacle reached by the capsule —
    # measured consequence in closed loop: once the system grazes into
    # contact every protecting row vanishes or degenerates, the tracking
    # cost re-accelerates, and the payload punches straight through the
    # obstacle (T=30 forest soak: 518 collision steps, -0.92 m
    # penetration). Inside ``dist_eps`` the braking time is floored at
    # ``NEAR_BRAKE_TIME`` so the row keeps a usable coefficient, and its
    # rhs (positive there) demands outward acceleration along the
    # sign-corrected outward normal.
    near = d < dist_eps
    min_time = jnp.where(
        near, jnp.maximum(min_time, NEAR_BRAKE_TIME), min_time
    )
    # speed > 0 gates only the FAR rows (the braking-capsule construction
    # needs motion, reference semantics); near-contact rows stay active at
    # rest — rhs = -alpha (d - eps) > 0 needs no velocity, and a system
    # resting in contact must still be pushed out, not released until it
    # re-accelerates into the obstacle.
    row_ok = sel_mask & jnp.isfinite(d) & n_valid & (near | (speed > 0))
    rhs_raw = (
        -alpha_env_cbf * (d - dist_eps)
        - jnp.sum(normal * vl[None, :], axis=-1)
    )
    # Row normalization (identical halfspace, radically better ADMM
    # conditioning): the reference writes the row as
    # (normal * min_time) @ dvl >= rhs, whose coefficient norm is
    # min_time (~0.2-0.3 s) against the O(1) rows of the rest of the QP —
    # measured consequence: an ACTIVE near row pushed the f32 ADMM from
    # ~120 iterations to ~3000 for the same solution, so solves failed at
    # production budgets, fell back to equilibrium forces, and the
    # momentum carried the payload through the obstacle. Dividing both
    # sides by min_time (> 0) preserves the constraint exactly and
    # restores unit row scale; min_time == 0 rows keep the reference's
    # degenerate semantics (vacuous when rhs < 0, infeasible-by-design
    # when rhs > 0 — "no braking time left").
    has_time = min_time > 1e-6
    lhs = jnp.where(
        (row_ok & has_time)[:, None], normal, 0.0
    )
    rhs = jnp.where(
        row_ok,
        jnp.where(has_time, rhs_raw / jnp.maximum(min_time, 1e-6), rhs_raw),
        inactive_rhs,
    )
    return EnvCBF(
        lhs=lhs.astype(dtype),
        rhs=rhs.astype(dtype),
        collision=data.collision,
        min_dist=jnp.minimum(data.min_dist, vision_radius).astype(dtype),
    )
