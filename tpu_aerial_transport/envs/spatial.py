"""Spatial-hash bucketed environment queries: city-scale obstacle worlds.

The dense forest query (``envs/forest.py capsule_forest_distance``) pays an
O(max_trees) golden-section sweep over ALL cylinder slots per capsule query
— measured at 40 ms of the 259 ms batched step (~15%) in the round-1
profile — and is the hard cap on world size (``MAX_TREES = 200``). This
module buckets the world instead:

- **Build** (:func:`build_grid`, host-side numpy): a uniform 2-D grid over
  tree XY (trees are vertical cylinders, so 2-D hashing is exact). Cell
  size is derived from the query radius (``vision_radius + bark_radius``)
  so one cell's 3x3 neighborhood conservatively covers every tree within
  range of ANY query point in that cell; each cell stores the
  NEIGHBORHOOD's candidate tree indices as a fixed-shape slab padded to a
  static ``K`` (auto-sized to the measured max occupancy, rounded to the
  sublane tile). Slab overflow is a structured build-time refusal
  (:class:`GridOverflowError`, carrying the measured K needed) — never a
  silent truncation.
- **Query** (:func:`env_query_bucketed`, in-jit): cell index from the
  braking-capsule midpoint -> ONE gather of the neighborhood slab -> the
  EXACT existing per-tree sweep math (``forest.capsule_distance_data``,
  elementwise along the tree axis) over candidates only, returning the
  same ``DistanceData`` contract — so ``cbf_rows_from_distance`` and the
  controllers' per-agent vision-cone reuse are untouched, and the
  resulting EnvCBF rows are BITWISE equal to the dense sweep's (the
  build-time coverage guarantee makes the candidate set complete; slab
  indices are stored ascending so ``lax.top_k`` tie order matches the
  dense sweep's tree-index order).

Gate: :func:`resolve_env_query` at config build time (the
``socp.resolve_fused`` idiom — ``TAT_ENV_QUERY`` env force) +
:func:`runtime_env_query` at trace time ("auto" resolves by the forest's
STATIC world size: dense at <= ``DENSE_AUTO_MAX_TREES`` slots, bucketed
above). ``env_query="dense"`` compiles byte-identical HLO to the
pre-knob program (asserted in tests/test_spatial.py — the
``no_faults()``/``effort="fixed"`` zero-cost contract).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
from flax import struct

from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.obs import phases

# The env-query implementation vocabulary (controllers' ``env_query=``
# knob; see resolve_env_query / runtime_env_query).
ENV_QUERY_IMPLS = ("dense", "bucketed")
ENV_QUERY_MODES = ("auto",) + ENV_QUERY_IMPLS

# "auto" world-size threshold: the MAX_TREES-class worlds the paper's
# forest lives in stay on the dense sweep; anything larger buckets.
# Conservative by design: the CPU tier already measures bucketed AHEAD
# at T=200 (see the flip criterion at resolve_env_query), but dense is
# the historical byte-identical program and CPU gather costs say little
# about TPU gather costs — lowering the threshold below the paper's
# world class is a chip-round decision, not a host-tier one. The
# threshold is a STATIC shape decision, so it resolves at trace time
# with no env read (runtime_env_query). A literal, NOT
# forest_mod.MAX_TREES (pinned equal by tests/test_spatial.py): a
# forest-FIRST import runs forest.py -> control.types ->
# control/__init__ -> cadmm -> spatial before forest's own constants
# bind, so a module-level forest_mod attribute read here raises
# AttributeError on `import tpu_aerial_transport.envs.forest` (measured;
# spatial-first import orders hide it).
DENSE_AUTO_MAX_TREES = 200

# Cell-size safety margin over the guaranteed coverage radius: the build
# assigns trees to cells in f64 while queries compute their cell from f32
# state, so a tree at EXACTLY the coverage radius of a query sitting on a
# cell boundary could straddle the 3x3 neighborhood by one float ulp.
# 1e-3 of the ~6 m query radius (~6 mm) dominates the ~1e-4 m f32 ulp at
# km-scale world coordinates by ~60x.
CELL_MARGIN = 1e-3

# Slab-width floor: K is rounded up to the 8-sublane tile and floored at
# 16 so the fixed n_env_cbfs=10 top_k always has enough candidates.
SLAB_TILE = 8
MIN_SLAB = 16


class GridOverflowError(ValueError):
    """A requested slab width ``k`` cannot hold the densest cell
    neighborhood: the structured build-time refusal (the measured
    ``k_needed`` is the fix — rebuild with ``k=None`` to auto-size, or at
    least ``k_needed``). Queries can then never overflow at runtime: the
    build indexes every valid tree or refuses."""

    def __init__(self, k: int, k_needed: int):
        self.k = k
        self.k_needed = k_needed
        super().__init__(
            f"spatial grid slab width k={k} cannot hold the densest cell "
            f"neighborhood ({k_needed} candidate trees) — rebuild with "
            f"k>={k_needed} (or k=None to auto-size); refusing to "
            "silently truncate the candidate set, which would drop "
            "obstacles from the collision queries"
        )


@struct.dataclass
class SpatialGrid:
    """Fixed-shape spatial-hash artifact (a pytree — rides the
    :class:`~tpu_aerial_transport.envs.forest.Forest` it was built for
    through every jitted query). ``cell_idx[c]`` holds the ascending tree
    indices of flat cell c's 3x3-neighborhood candidates, padded to the
    static slab width K with ``cell_valid`` false."""

    cell_idx: jnp.ndarray  # (nx * ny, K) int32, ascending per cell.
    cell_valid: jnp.ndarray  # (nx * ny, K) bool.
    origin: jnp.ndarray  # (2,) grid lower corner in world XY.
    inv_cell: jnp.ndarray  # () 1 / cell_size.

    nx: int = struct.field(pytree_node=False, default=1)
    ny: int = struct.field(pytree_node=False, default=1)
    k: int = struct.field(pytree_node=False, default=MIN_SLAB)
    # The coverage radius the build GUARANTEES: every tree within this
    # XY distance of any query point is in that point's cell slab.
    query_radius: float = struct.field(pytree_node=False, default=0.0)
    cell_size: float = struct.field(pytree_node=False, default=1.0)


def build_grid(forest: forest_mod.Forest, query_radius: float,
               k: int | None = None) -> SpatialGrid:
    """Host-side grid build over ``forest``'s valid trees.

    ``query_radius`` is the XY range the grid must cover per query —
    callers pass ``vision_radius + bark_radius`` (the dense sweep's
    in-range gate; 3-D distance >= XY distance, so XY coverage at that
    radius is conservative). Cell size = ``query_radius * (1 +
    CELL_MARGIN)``, so a cell's 3x3 neighborhood covers every in-range
    tree of every query point inside it. ``k=None`` auto-sizes the slab
    to the measured max neighborhood occupancy (rounded to the 8-sublane
    tile, floored at :data:`MIN_SLAB`); an explicit ``k`` below the
    measured need raises :class:`GridOverflowError` with the number."""
    if query_radius <= 0:
        raise ValueError(f"query_radius={query_radius} must be positive")
    pos = np.asarray(forest.tree_pos, np.float64)
    valid = np.asarray(forest.tree_valid, bool)
    idxs = np.nonzero(valid)[0]
    cell = float(query_radius) * (1.0 + CELL_MARGIN)
    dtype = forest.tree_pos.dtype

    if idxs.size:
        xy = pos[idxs, :2]
        origin = xy.min(axis=0)
        nx = int(np.floor((xy[:, 0].max() - origin[0]) / cell)) + 1
        ny = int(np.floor((xy[:, 1].max() - origin[1]) / cell)) + 1
        ci = np.clip(np.floor((xy[:, 0] - origin[0]) / cell).astype(int),
                     0, nx - 1)
        cj = np.clip(np.floor((xy[:, 1] - origin[1]) / cell).astype(int),
                     0, ny - 1)
    else:
        origin = np.zeros(2)
        nx = ny = 1
        ci = cj = np.zeros(0, int)

    # Each tree registers into the 9 neighborhoods that can query it;
    # iterating trees in ascending global index keeps every slab sorted
    # ascending — the lax.top_k tie-order discipline (ties in the dense
    # sweep break toward the smaller TREE index, so slab position order
    # must equal tree-index order for bitwise row parity).
    slabs: list[list[int]] = [[] for _ in range(nx * ny)]
    for t, i, j in zip(idxs.tolist(), ci.tolist(), cj.tolist()):
        for di in (-1, 0, 1):
            ii = i + di
            if not 0 <= ii < nx:
                continue
            for dj in (-1, 0, 1):
                jj = j + dj
                if 0 <= jj < ny:
                    slabs[ii * ny + jj].append(t)

    k_needed = max((len(s) for s in slabs), default=0)
    if k is None:
        k = max(-(-max(k_needed, 1) // SLAB_TILE) * SLAB_TILE, MIN_SLAB)
    elif k < k_needed:
        raise GridOverflowError(k=k, k_needed=k_needed)

    cell_idx = np.zeros((nx * ny, k), np.int32)
    cell_valid = np.zeros((nx * ny, k), bool)
    for c, s in enumerate(slabs):
        cell_idx[c, : len(s)] = s
        cell_valid[c, : len(s)] = True

    return SpatialGrid(
        cell_idx=jnp.asarray(cell_idx),
        cell_valid=jnp.asarray(cell_valid),
        origin=jnp.asarray(origin, dtype),
        inv_cell=jnp.asarray(1.0 / cell, dtype),
        nx=nx, ny=ny, k=int(k),
        query_radius=float(query_radius), cell_size=cell,
    )


def with_grid(forest: forest_mod.Forest, query_radius: float,
              k: int | None = None) -> forest_mod.Forest:
    """``forest`` with a freshly built spatial-hash grid attached (the
    bucketed query tier's data dependency — the grid then rides the
    Forest pytree through rollouts/mesh/pods/serving with zero
    plumbing)."""
    return forest.replace(grid=build_grid(forest, query_radius, k=k))


def grid_stats(grid: SpatialGrid) -> dict:
    """Host-side occupancy telemetry for a built grid — the structured
    record bench cells and the city-forest example publish (the
    counterpart of the build-time overflow refusal: occupancy is always
    REPORTED, never silently capped)."""
    occ = np.asarray(grid.cell_valid).sum(axis=1)
    return {
        "n_cells": int(occ.size),
        "k": int(grid.k),
        "cell_size_m": float(grid.cell_size),
        "query_radius_m": float(grid.query_radius),
        "max_occupancy": int(occ.max()) if occ.size else 0,
        "mean_occupancy": float(occ.mean()) if occ.size else 0.0,
        "occupied_cells": int((occ > 0).sum()),
    }


def resolve_env_query(env_query: str | None = "auto") -> str:
    """Resolve the controllers' environment-query knob at CONFIG BUILD
    time (the ``socp.resolve_fused`` idiom): ``"auto"`` (or None)
    consults the ``TAT_ENV_QUERY`` env var (``dense`` | ``bucketed`` |
    ``auto``/unset) and otherwise STAYS ``"auto"`` — unlike the backend
    knobs, the right implementation depends on the WORLD, and the world's
    size is a static shape first known at trace time, where
    :func:`runtime_env_query` finishes the resolution (dense at <=
    :data:`DENSE_AUTO_MAX_TREES` tree slots — the paper's MAX_TREES-class
    forests — bucketed above). Explicit values pass through validated;
    the env read happens HERE only, never under trace.

    **Chip-round flip criterion** (for lowering
    ``DENSE_AUTO_MAX_TREES``, i.e. bucketing the paper-class worlds by
    default; the decision cells are ``env_{dense,bucketed}_T{200,4096,
    65536}`` in BENCH_SWEEP.json): (1) the bucketed arm beats its dense
    twin by >= 15% batched queries/s ON-CHIP at the paper's T=200 class
    — the CPU tier already measures bucketed ahead everywhere (5.2x at
    T=200, ~98x at T=4096, flat ~64k queries/s out to T=65536 where the
    dense arm cannot run at all), but XLA-CPU gather costs say little
    about TPU gather/DMA costs, which is exactly what the chip read
    arbitrates; (2) the bitwise EnvCBF parity suite
    (tests/test_spatial.py) stays green on-chip; and (3) the recorded
    ``grid`` occupancy fields show the slab actually thinning the
    candidate set (K << T) — a near-full slab means the world is too
    dense for the cell size and the win is noise."""
    if env_query is None:
        env_query = "auto"
    if env_query == "auto":
        env = os.environ.get("TAT_ENV_QUERY", "").strip().lower()
        if env in ENV_QUERY_IMPLS:
            return env
        if env not in ("", "auto"):
            raise ValueError(
                f"TAT_ENV_QUERY={env!r}: expected one of "
                f"{ENV_QUERY_IMPLS} or 'auto'"
            )
        return "auto"
    if env_query not in ENV_QUERY_MODES:
        raise ValueError(
            f"env_query={env_query!r}: expected one of {ENV_QUERY_MODES}"
        )
    return env_query


def runtime_env_query(env_query: str, forest: forest_mod.Forest) -> str:
    """The implementation a query with this ``env_query`` mode ACTUALLY
    runs against ``forest`` — the trace-time half of the resolution (the
    ``socp.runtime_fused_mode`` one-resolver rule: dispatch and anything
    that must LABEL a measurement share this decision). "auto" resolves
    by the forest's STATIC slot count (a shape, so this is host-side
    Python at trace time — no env read, no traced value); "bucketed"
    without an attached grid is a structured refusal, not a silent dense
    fallback (a 10^5-tree world silently running the O(T) dense sweep is
    exactly the cost surprise this tier exists to delete)."""
    if env_query not in ENV_QUERY_MODES:
        raise ValueError(
            f"env_query={env_query!r}: expected one of {ENV_QUERY_MODES}"
        )
    if env_query == "auto":
        max_trees = forest.tree_pos.shape[0]
        env_query = (
            "bucketed" if max_trees > DENSE_AUTO_MAX_TREES else "dense"
        )
    if env_query == "bucketed" and forest.grid is None:
        raise ValueError(
            f"env_query resolved to 'bucketed' for a "
            f"{forest.tree_pos.shape[0]}-slot world but the forest "
            "carries no spatial grid — attach one with "
            "envs.spatial.with_grid(forest, vision_radius + bark_radius) "
            "at setup, or force env_query='dense'"
        )
    return env_query


def candidate_slab(forest: forest_mod.Forest, cap_mid: jnp.ndarray):
    """In-jit slab lookup: the candidate tree indices + validity for the
    grid cell containing ``cap_mid``'s XY (clipped into the grid — the
    clip is exact for coverage: trees live inside the grid box, and
    per-axis clipping can only move the query point CLOSER to every
    tree)."""
    grid: SpatialGrid = forest.grid
    ij = jnp.floor((cap_mid[:2] - grid.origin) * grid.inv_cell).astype(
        jnp.int32
    )
    flat = (jnp.clip(ij[0], 0, grid.nx - 1) * grid.ny
            + jnp.clip(ij[1], 0, grid.ny - 1))
    idx = jnp.take(grid.cell_idx, flat, axis=0)
    slab_valid = jnp.take(grid.cell_valid, flat, axis=0)
    return idx, slab_valid


def bucketed_distance(
    forest: forest_mod.Forest,
    cap_a: jnp.ndarray,
    cap_b: jnp.ndarray,
    cap_radius,
    vision_radius,
    vision_mask=None,
    n_rows: int | None = None,
):
    """Bucketed distance sweep: gather the capsule midpoint's candidate
    slab and run the EXACT dense per-tree math over it. Returns
    ``(DistanceData (K,)-shaped, candidate centers (K, 3), candidate
    tree indices (K,))`` — centers feed the controllers' per-agent
    vision-cone masks (``forest.cone_mask_at``), indices let callers map
    rows back to world trees. ``vision_mask``, when given, is a dense
    ``(max_trees,)`` mask gathered at the slab indices."""
    grid: SpatialGrid = forest.grid
    if grid is None:
        raise ValueError(
            "bucketed_distance needs forest.grid — attach one with "
            "envs.spatial.with_grid"
        )
    # Coverage + row-count refusals (static config values — host-side
    # checks at trace time, the build-time guarantee enforced at use).
    if isinstance(vision_radius, (int, float)):
        need = float(vision_radius) + float(forest.bark_radius)
        if grid.query_radius < need - 1e-9:
            raise ValueError(
                f"forest.grid covers query_radius="
                f"{grid.query_radius:.3f} m but this query needs "
                f"vision_radius + bark_radius = {need:.3f} m — rebuild "
                "the grid at the larger radius (spatial.with_grid); a "
                "short grid would silently drop in-range obstacles"
            )
    if n_rows is not None and grid.k < n_rows:
        raise ValueError(
            f"grid slab width k={grid.k} < n_rows={n_rows}: rebuild the "
            f"grid with k>={n_rows} so top_k always has enough "
            "candidates"
        )
    with phases.scope(phases.ENV_QUERY):
        cap_mid = 0.5 * (cap_a + cap_b)
        idx, slab_valid = candidate_slab(forest, cap_mid)
        centers = jnp.take(forest.tree_pos, idx, axis=0)
        valid = slab_valid & jnp.take(forest.tree_valid, idx)
        vm = None if vision_mask is None else jnp.take(vision_mask, idx)
        data = forest_mod.capsule_distance_data(
            centers, valid, forest.bark_radius, forest.bark_height,
            cap_a, cap_b, cap_radius, vision_radius, vm,
        )
    return data, centers, idx


def env_query_bucketed(
    forest: forest_mod.Forest,
    cap_a: jnp.ndarray,
    cap_b: jnp.ndarray,
    cap_radius,
    vision_radius,
    vision_mask=None,
) -> forest_mod.DistanceData:
    """The bucketed twin of :func:`forest.capsule_forest_distance`: same
    ``DistanceData`` contract over the (K,) candidate slab instead of
    all ``(max_trees,)`` slots. The registered jit entrypoint
    (``envs.spatial:env_query_bucketed``)."""
    return bucketed_distance(
        forest, cap_a, cap_b, cap_radius, vision_radius,
        vision_mask=vision_mask,
    )[0]


def env_query_dense(
    forest: forest_mod.Forest,
    cap_a: jnp.ndarray,
    cap_b: jnp.ndarray,
    cap_radius,
    vision_radius,
    vision_mask=None,
) -> forest_mod.DistanceData:
    """The dense sweep under its entrypoint name (the registered twin of
    :func:`env_query_bucketed` — TC106 coverage for the shared sweep
    math at full world width)."""
    return forest_mod.capsule_forest_distance(
        forest, cap_a, cap_b, cap_radius, vision_radius, vision_mask
    )
