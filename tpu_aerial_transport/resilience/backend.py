"""Backend guard: error taxonomy, circuit breaker, deadline watchdogs, and
graceful CPU degradation for every accelerator interaction.

The bench trajectory is the motivation: round 1 measured on-chip, round 2
died at the *first real dispatch* with a backend-init ``UNAVAILABLE``
surfacing under ``convert_element_type`` (BENCH_r02.json — the probe
passed, the run did not), and rounds 3-5 wedged outright. Until this
module, only the *probe* was watchdogged; the measured run, the sweep
cells, and the recovery tier's chunk loop had no timeout, no retry, and no
mid-run degradation. This module makes a flaky, wedged, or absent TPU
runtime a STRUCTURED, survivable event everywhere:

- :func:`classify` / :class:`BackendError` — the error taxonomy
  (``init_unavailable`` / ``wedge_timeout`` / ``compile_error`` /
  ``dtype_lowering`` / ``oom`` / ``device_crash`` / ``unknown``). Pattern
  order matters: the r02 tail contains BOTH ``convert_element_type`` and
  ``Unable to initialize backend … UNAVAILABLE`` — backend-init failure at
  first dispatch, NOT a dtype bug — so init patterns win over dtype ones.
- :class:`BackoffPolicy` — exponential backoff with jitter, shared by the
  circuit breaker and ``tools/bench_retry.py`` (one retry cadence for the
  whole stack; jitter decorrelates a fleet of retriers).
- :class:`CircuitBreaker` — per-backend closed → open → half-open machine:
  K consecutive classified failures open the circuit for a cooldown
  (work routes to the tagged XLA-CPU rung without paying the deadline
  again); after the cooldown a half-open probe either closes it or
  re-opens with a longer cooldown.
- :func:`call_with_deadline` — thread-deadline watchdog for in-process
  dispatch: a wedged runtime becomes a structured
  ``BackendError("wedge_timeout")`` instead of a hung round.
- :func:`probe_subprocess` — subprocess isolation for COLD backend init.
  The probe warms a real device computation (matmul + an explicit
  ``convert_element_type`` round-trip, the exact op class r02 died under),
  so a probe "pass" implies the first real dispatch cannot raise
  ``UNAVAILABLE`` — closing the probe/dispatch gap that produced r02.
- :class:`FaultInjector` — env-triggered fake-backend hook
  (``TAT_BACKEND_FAULTS``) so wedge / init-failure / mid-sweep crash are
  testable end-to-end on any host.
- :class:`BackendGuard` — the orchestration: run work on the primary rung
  under a deadline, classify failures, trip the breaker, journal a
  ``backend_event``, and re-place the work on the CPU rung.

Module contract: NO jax import at module scope (lazy inside the functions
that need it) — ``tools/bench_retry.py`` and ``tools/probe_chip.py`` load
this file by path on hosts where importing jax is exactly the hazard being
watchdogged.
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
import types

# ----------------------------------------------------------------------
# Error taxonomy.
# ----------------------------------------------------------------------

ERROR_KINDS = (
    "init_unavailable",   # backend setup/connect failed (r02's real cause)
    "topology_mismatch",  # backend answered but with the WRONG shape: fewer
                          # visible devices/processes than the expected
                          # topology (MULTICHIP_r01: 1 of 8 devices visible
                          # while the single-device probe passed) — running
                          # on it would silently undershard, so it is an
                          # infra failure, not a measurement
    "wedge_timeout",      # accepted work, never answered (rounds 3-5)
    "compile_error",      # XLA/Mosaic rejected the program
    "dtype_lowering",     # f64/convert_element_type-class lowering bug
    "oom",                # device memory exhausted
    "device_crash",       # runtime died mid-execution
    "bundle_stale",       # AOT bundle fingerprint mismatch: rebuild the
                          # bundle (tools/aot_bundle.py build) — NOT a chip
                          # problem, never trips the breaker
    "unknown",            # unclassified — treated as a CODE bug, not infra
)

# Ordered: first match wins. bundle_stale leads — a stale-bundle refusal
# names its artifact/fingerprint drift and must not be misread as an
# infra failure by the looser patterns below. init_unavailable precedes
# dtype_lowering deliberately — BENCH_r02's tail mentions
# convert_element_type only because backend init surfaced lazily under
# the first dispatched op; the root cause line is "Unable to initialize
# backend ... UNAVAILABLE".
_CLASSIFIERS: tuple[tuple[str, re.Pattern], ...] = (
    ("bundle_stale", re.compile(
        r"(?i)bundle[_ ]stale|stale bundle|bundle.*fingerprint")),
    # Before init_unavailable: a topology report names its counts
    # explicitly and must not be swallowed by the looser init patterns
    # ("no accelerator" etc.) below.
    ("topology_mismatch", re.compile(
        r"(?i)topology[_ ]mismatch|"
        r"visible \d+ of \d+ devices|\d+ of \d+ devices visible")),
    ("init_unavailable", re.compile(
        r"(?i)unable to initialize backend|backend setup|"
        r"failed to connect|\bUNAVAILABLE\b|no accelerator|"
        r"backend '\w+' requested, but it failed")),
    ("wedge_timeout", re.compile(
        r"(?i)timed out|timeout after|deadline exceeded|watchdog|wedged")),
    ("oom", re.compile(
        r"(?i)resource[_ ]exhausted|out of memory|\boom\b|"
        r"failed to allocate")),
    ("dtype_lowering", re.compile(
        r"(?i)convert_element_type|float64|\bf64\b|"
        r"unsupported (element type|dtype)|dtype .* not supported")),
    ("compile_error", re.compile(
        r"(?i)mosaic|compilation (error|failure|failed)|"
        r"compile (error|failed)|lowering (error|failed|rule)|"
        r"invalid_argument.*hlo|xla.*compile")),
    # Anchored to the XLA/gRPC STATUS-CODE forms (case-sensitive
    # INTERNAL/ABORTED/DATA_LOSS) plus device-specific phrases: a
    # lowercase "aborted"/"internal" in an ordinary exception message is
    # a code bug that must classify as unknown and RE-RAISE, not degrade.
    ("device_crash", re.compile(
        r"\bINTERNAL\b|\bABORTED\b|\bDATA[_ ]LOSS\b|"
        r"(?i:device (halt|reset)|device is (gone|dead)|"
        r"execution failed)")),
)


class BackendError(RuntimeError):
    """A classified backend failure. ``kind`` is one of
    :data:`ERROR_KINDS`; ``detail`` keeps the original message (truncated
    by emitters, not here)."""

    def __init__(self, kind: str, detail: str, backend: str = "unknown"):
        if kind not in ERROR_KINDS:
            raise ValueError(f"unknown BackendError kind {kind!r}")
        super().__init__(f"[{kind}] {detail}")
        self.kind = kind
        self.detail = detail
        self.backend = backend


def classify(exc_or_text) -> str:
    """Classify an exception (or message text) into an error kind.

    A :class:`BackendError` keeps its own kind. For anything else the
    message is matched against the ordered pattern table; an unmatched
    ``XlaRuntimeError`` still counts as ``device_crash`` (the runtime
    itself raised — that is a device problem whatever the text says),
    while an unmatched ordinary exception is ``unknown`` — a CODE bug the
    guard must re-raise, not degrade around.
    """
    if isinstance(exc_or_text, BackendError):
        return exc_or_text.kind
    text = (str(exc_or_text) if not isinstance(exc_or_text, str)
            else exc_or_text)
    if not isinstance(exc_or_text, str):
        text = f"{type(exc_or_text).__name__}: {text}"
    for kind, pat in _CLASSIFIERS:
        if pat.search(text):
            return kind
    if not isinstance(exc_or_text, str) and \
            type(exc_or_text).__name__ == "XlaRuntimeError":
        return "device_crash"
    return "unknown"


# ----------------------------------------------------------------------
# Backoff policy (shared with tools/bench_retry.py).
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with jitter: attempt k (0-based) waits
    ``min(initial * factor**k, max) * (1 + jitter * U[-1, 1])``. Jitter
    decorrelates retriers sharing one wedged chip; pass a seeded ``rng``
    for deterministic tests."""

    initial_s: float = 30.0
    factor: float = 2.0
    max_s: float = 600.0
    jitter: float = 0.1

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        base = min(self.initial_s * self.factor ** max(attempt, 0),
                   self.max_s)
        if not self.jitter:
            return base
        u = (rng or random).uniform(-1.0, 1.0)
        return max(0.0, base * (1.0 + self.jitter * u))


# ----------------------------------------------------------------------
# Circuit breaker.
# ----------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-backend circuit breaker.

    closed --(K consecutive classified failures)--> open: primary work is
    refused (``allow()`` False) for a cooldown from the backoff policy.
    open --(cooldown elapsed)--> half_open: ONE probe call is allowed.
    half_open --success--> closed (failure count reset);
    half_open --failure--> open again with the NEXT (longer) cooldown.

    ``transitions`` records every state change (monotonic ts, from, to,
    reason) — the guard journals them as ``backend_event`` rows.
    """

    def __init__(self, failure_threshold: int = 3,
                 policy: BackoffPolicy | None = None,
                 clock=time.monotonic,
                 rng: random.Random | None = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.policy = policy or BackoffPolicy()
        self._clock = clock
        self._rng = rng or random.Random()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.open_count = 0          # how many times the circuit opened.
        self.opened_at: float | None = None
        self.cooldown_s: float = 0.0
        self.transitions: list[dict] = []

    def _transition(self, to: str, reason: str) -> None:
        if to == self.state:
            return
        self.transitions.append({
            "ts": self._clock(), "from": self.state, "to": to,
            "reason": reason,
        })
        self.state = to

    def allow(self) -> bool:
        """May primary work run now? OPEN + cooldown elapsed flips to
        HALF_OPEN (the caller's next run() is the probe)."""
        if self.state == OPEN:
            if self._clock() - self.opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN, "cooldown elapsed")
                return True
            return False
        return True

    def seconds_until_half_open(self) -> float:
        if self.state != OPEN:
            return 0.0
        return max(0.0, self.cooldown_s - (self._clock() - self.opened_at))

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state in (HALF_OPEN, OPEN):
            self._transition(CLOSED, "probe succeeded")

    def record_failure(self, kind: str) -> None:
        self.consecutive_failures += 1
        if self.state == HALF_OPEN:
            self._open(f"half-open probe failed ({kind})")
        elif (self.state == CLOSED
              and self.consecutive_failures >= self.failure_threshold):
            self._open(
                f"{self.consecutive_failures} consecutive failures "
                f"(last: {kind})"
            )

    def _open(self, reason: str) -> None:
        self.cooldown_s = self.policy.delay(self.open_count, self._rng)
        self.open_count += 1
        self.opened_at = self._clock()
        self._transition(OPEN, reason)


# ----------------------------------------------------------------------
# Deadline watchdog (in-process dispatch).
# ----------------------------------------------------------------------

def call_with_deadline(fn, timeout_s: float | None, label: str = ""):
    """Run ``fn()`` under a thread deadline: a wedged runtime becomes a
    structured ``BackendError("wedge_timeout")`` after ``timeout_s``
    instead of a hung process. ``fn`` must block until its device work is
    done (``jax.block_until_ready``) or a wedge inside XLA would escape
    the watchdog.

    The worker thread cannot be killed — on timeout it is abandoned as a
    daemon (the wedged runtime holds it anyway) and the CALLER must not
    touch the backend that wedged except through the circuit breaker.
    ``timeout_s`` None/<=0 disables the watchdog (plain call).
    """
    if not timeout_s or timeout_s <= 0:
        return fn()
    result: list = []
    error: list = []

    def worker():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — forwarded to caller.
            error.append(e)

    t = threading.Thread(target=worker, daemon=True,
                         name=f"backend-guard-{label or 'call'}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise BackendError(
            "wedge_timeout",
            f"{label or 'call'} exceeded the {timeout_s:g}s deadline "
            "(runtime wedged; worker thread abandoned)",
        )
    if error:
        raise error[0]
    return result[0]


# ----------------------------------------------------------------------
# Subprocess probe (cold backend init + first real dispatch).
# ----------------------------------------------------------------------

# The probe's device computation deliberately includes a matmul AND an
# explicit convert_element_type round-trip: r02's probe passed on
# `jax.devices()` alone while the first dispatched op (a convert) raised
# the lazy backend-init UNAVAILABLE. A probe "pass" must mean the first
# REAL dispatch succeeds.
# The BACKEND_OK token line is a positional contract shared by every
# probe body and the parser in probe_subprocess:
#   BACKEND_OK <platform> <n_devices> <n_processes> <checksum> [notes...]
# n_devices/n_processes close the MULTICHIP_r01 gap: the single-device
# probe PASSED while only 1 of 8 devices was visible — the probe now
# reports the topology it actually saw so the caller can refuse to
# measure an undersharded mesh (see expect_devices/expect_processes).
PROBE_CODE = (
    "import os, jax\n"
    "envp = os.environ.get('JAX_PLATFORMS')\n"
    "if envp: jax.config.update('jax_platforms', envp)\n"
    "d = jax.devices()\n"
    "import jax.numpy as jnp\n"
    "from jax import lax\n"
    "x = jnp.ones((128, 128), jnp.float32)\n"
    "y = lax.convert_element_type(x @ x, jnp.bfloat16)\n"
    "s = float(lax.convert_element_type(y, jnp.float32).sum())\n"
    "print('BACKEND_OK', d[0].platform, len(d), jax.process_count(), s)\n"
)

FAULTS_ENV = "TAT_BACKEND_FAULTS"
DEADLINE_ENV = "TAT_BACKEND_DEADLINE_S"
# Expected topology (ints): when set, probe_subprocess compares the
# visible device/process counts against them and a shortfall FAILS the
# probe with a classified topology_mismatch — the r01 failure mode
# (1 of 8 devices visible, probe green) becomes a structured refusal
# instead of an 8x-undersharded measurement. A multi-chip driver sets
# these alongside JAX_PLATFORMS.
EXPECTED_DEVICES_ENV = "TAT_EXPECTED_DEVICES"
EXPECTED_PROCESSES_ENV = "TAT_EXPECTED_PROCESSES"
# AOT bundle the probe prefers: the probe computation loads from the
# bundle's precompiled artifact instead of compiling, so a cold-init
# probe cannot burn its deadline in XLA (tpu_aerial_transport/aot/).
BUNDLE_ENV = "TAT_AOT_BUNDLE_DIR"

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))

# Bundle-warmed probe: same contract as PROBE_CODE, but the device
# computation replays the bundle's precompiled probe entry. A bundle
# failure (stale fingerprint, missing dir, corrupt object) falls back to
# the compile probe IN the subprocess — the chip still gets validated and
# the BACKEND_OK line's trailing note carries the classified bundle
# problem (a rebuild hint, never a probe failure: see BREAKER_KINDS).
def _bundle_probe_code(bundle_dir: str) -> str:
    return (
        "import os, sys, jax\n"
        "envp = os.environ.get('JAX_PLATFORMS')\n"
        "if envp: jax.config.update('jax_platforms', envp)\n"
        f"sys.path.insert(0, {_REPO_DIR!r})\n"
        "d = jax.devices()\n"
        "note = 'bundle'\n"
        "try:\n"
        "    from tpu_aerial_transport.aot import loader as _aot\n"
        f"    b = _aot.load_bundle({bundle_dir!r})\n"
        "    s = float(_aot.call_probe(b))\n"
        "except Exception as e:\n"
        "    note = ('bundle_fallback:' + type(e).__name__ + ':'\n"
        "            + str(e)[:160].replace(' ', '_'))\n"
        "    import jax.numpy as jnp\n"
        "    from jax import lax\n"
        "    x = jnp.ones((128, 128), jnp.float32)\n"
        "    y = lax.convert_element_type(x @ x, jnp.bfloat16)\n"
        "    s = float(lax.convert_element_type(y, jnp.float32).sum())\n"
        "print('BACKEND_OK', d[0].platform, len(d), jax.process_count(), "
        "s, note)\n"
    )


def run_group(cmd: list[str], timeout_s: float,
              env: dict | None = None, cwd: str | None = None):
    """Run ``cmd`` in its OWN session and, on timeout, SIGKILL the whole
    process group before re-raising ``subprocess.TimeoutExpired``.

    ``subprocess.run(timeout=...)`` kills only the direct child: a wedged
    bench's own subprocesses (the backend probe it spawned, a TPU runtime
    helper holding the chip lease) survive as orphans and keep the chip
    wedged for every later attempt. ``start_new_session`` gives the child
    a fresh process group rooted at its pid, so one ``killpg`` reaps the
    whole tree. Returns a ``(returncode, stdout, stderr)`` namespace like
    ``subprocess.run(capture_output=True, text=True)``.
    """
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(env or os.environ), cwd=cwd, start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        raise
    return types.SimpleNamespace(
        returncode=proc.returncode, stdout=out, stderr=err
    )


def _expected_topology(env: dict | None) -> tuple[int | None, int | None]:
    """(expected_devices, expected_processes) from the env knobs; None
    means "no expectation". Garbage values raise — a typo silently
    disabling the topology gate would fake a green probe."""
    src = env or os.environ
    out = []
    for key in (EXPECTED_DEVICES_ENV, EXPECTED_PROCESSES_ENV):
        raw = src.get(key, "")
        if not raw:
            out.append(None)
            continue
        try:
            out.append(int(raw))
        except ValueError:
            raise ValueError(f"{key}={raw!r} is not an integer") from None
    return out[0], out[1]


def probe_subprocess(timeout_s: float = 60.0,
                     env: dict | None = None,
                     bundle_dir: str | None = None,
                     notes: list | None = None,
                     expect_devices: int | None = None,
                     expect_processes: int | None = None,
                     info: dict | None = None) -> tuple[bool, str]:
    """Watchdogged subprocess probe of cold backend init + first dispatch:
    ``(True, platform)`` when the computation ran, ``(False, detail)``
    otherwise. Subprocess isolation because a wedged BACKEND INIT cannot
    be interrupted in-process (the thread watchdog can only abandon it —
    fine for dispatch, fatal before any backend exists).

    ``bundle_dir`` (default: the :data:`BUNDLE_ENV` env var) makes the
    probe prefer the AOT bundle's PRECOMPILED probe executable, so the
    probed dispatch cannot spend the deadline inside an XLA compile; a
    bundle problem (``bundle_stale`` fingerprint drift, missing/corrupt
    artifact) downgrades to the compile probe inside the subprocess and
    is reported through ``notes`` (appended strings) — a rebuild hint,
    never a failed probe and never a circuit-breaker strike.

    ``expect_devices`` / ``expect_processes`` (default: the
    :data:`EXPECTED_DEVICES_ENV` / :data:`EXPECTED_PROCESSES_ENV` env
    vars) arm the topology gate: the probe reports the visible
    device/process counts (``info``, when passed, receives ``platform`` /
    ``n_devices`` / ``n_processes``) and a count BELOW the expectation
    fails the probe with a ``topology_mismatch``-classified detail — the
    MULTICHIP_r01 failure mode (1 of 8 devices visible, single-device
    probe green) becomes a structured refusal instead of a silently
    undersharded measurement. A SURPLUS is not a failure (a bigger slice
    than asked for still runs the asked-for mesh).

    Honors the :class:`FaultInjector` env hook: an ``init_unavailable``
    directive fails the probe in-process (fast), so end-to-end tests can
    simulate the r02 failure mode without a chip.
    """
    inj = FaultInjector.from_env(
        (env or os.environ).get(FAULTS_ENV, ""))
    if inj.init_unavailable:
        return False, (
            "fault-injected: Unable to initialize backend "
            "(TAT_BACKEND_FAULTS=init_unavailable)"
        )
    env_devices, env_processes = _expected_topology(env)
    if expect_devices is None:
        expect_devices = env_devices
    if expect_processes is None:
        expect_processes = env_processes
    if bundle_dir is None:
        bundle_dir = (env or os.environ).get(BUNDLE_ENV, "")
    code = _bundle_probe_code(bundle_dir) if bundle_dir else PROBE_CODE
    try:
        proc = run_group(
            [sys.executable, "-c", code], timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        # Structured prefix contract: tools/bench_retry.py classifies a
        # wedged (retryable) chip by detail.startswith("timeout after").
        return False, (
            f"timeout after {timeout_s:g}s (chip unreachable/wedged)"
        )
    token = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("BACKEND_OK")]
    if proc.returncode == 0 and token:
        # Positional contract (see PROBE_CODE):
        # BACKEND_OK platform n_devices n_processes checksum [notes...]
        parts = token[0].split()
        n_dev, n_proc = int(parts[2]), int(parts[3])
        if info is not None:
            info.update(
                platform=parts[1], n_devices=n_dev, n_processes=n_proc,
            )
        if notes is not None and len(parts) > 5:
            notes.extend(parts[5:])
        if ((expect_devices is not None and n_dev < expect_devices)
                or (expect_processes is not None
                    and n_proc < expect_processes)):
            return False, (
                f"topology_mismatch: visible {n_dev} of "
                f"{expect_devices if expect_devices is not None else n_dev}"
                f" devices, {n_proc} of "
                f"{expect_processes if expect_processes is not None else n_proc}"
                f" processes on {parts[1]} — refusing to measure an "
                "undersharded mesh (MULTICHIP_r01)"
            )
        return True, parts[1]
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    return False, f"probe rc={proc.returncode}: " + " | ".join(tail)


# ----------------------------------------------------------------------
# Fault injection (test hook; env-triggered fake backend).
# ----------------------------------------------------------------------

@dataclasses.dataclass
class FaultInjector:
    """Parsed ``TAT_BACKEND_FAULTS`` directives. Comma-separated:

    - ``init_unavailable`` — the subprocess probe fails fast, as if the
      backend could not initialize (the r02 class);
    - ``wedge=S`` — every guarded PRIMARY call sleeps ``S`` seconds
      before running (exceeding the deadline ⇒ a ``wedge_timeout``);
    - ``crash@N`` — the N-th (1-based) guarded primary call raises a fake
      ``INTERNAL: device crashed`` runtime error (mid-sweep crash);
    - ``crash@LABEL`` — primary calls whose label contains ``LABEL``
      raise it instead.

    Injection applies ONLY to the primary rung — the CPU fallback always
    runs clean, so a fault-injected sweep still produces real (tagged)
    numbers. Parsing is strict: an unknown directive raises, because a
    typo silently disabling fault injection would fake a green test.
    """

    init_unavailable: bool = False
    wedge_s: float = 0.0
    crash_at: int = 0
    crash_label: str = ""
    calls: int = 0

    @classmethod
    def from_env(cls, spec: str | None = None) -> "FaultInjector":
        if spec is None:
            spec = os.environ.get(FAULTS_ENV, "")
        inj = cls()
        for raw in (spec or "").split(","):
            d = raw.strip()
            if not d:
                continue
            if d == "init_unavailable":
                inj.init_unavailable = True
            elif d.startswith("wedge="):
                inj.wedge_s = float(d.split("=", 1)[1])
            elif d.startswith("crash@"):
                tag = d.split("@", 1)[1]
                if tag.isdigit():
                    inj.crash_at = int(tag)
                else:
                    inj.crash_label = tag
            else:
                raise ValueError(
                    f"unknown {FAULTS_ENV} directive {d!r} (known: "
                    "init_unavailable, wedge=S, crash@N, crash@LABEL)"
                )
        return inj

    @property
    def active(self) -> bool:
        return bool(self.init_unavailable or self.wedge_s
                    or self.crash_at or self.crash_label)

    def maybe_fault(self, label: str = "") -> None:
        """Called by the guard before every primary execution."""
        self.calls += 1
        if self.crash_at and self.calls == self.crash_at:
            raise RuntimeError(
                f"INTERNAL: device crashed (fault-injected at call "
                f"{self.calls}, label {label!r})"
            )
        if self.crash_label and self.crash_label in label:
            raise RuntimeError(
                f"INTERNAL: device crashed (fault-injected on label "
                f"{label!r})"
            )
        if self.wedge_s:
            time.sleep(self.wedge_s)
            # The watchdog abandoned this worker long ago (deadline <
            # wedge); raising here makes the abandoned thread exit WITHOUT
            # running real device work inside a dying interpreter (a C++
            # abort at teardown). If the deadline was generous enough to
            # outlast the sleep, the raise is the wedge surfacing.
            raise BackendError(
                "wedge_timeout",
                f"fault-injected wedge ({self.wedge_s:g}s) on {label!r}",
            )


# ----------------------------------------------------------------------
# The guard.
# ----------------------------------------------------------------------

# Rung vocabulary: where a cell/chunk ACTUALLY ran. "on-chip" is the
# accelerator with the default (padded) operator layout, "on-chip-unpadded"
# the deliberate pad_operators=False A/B twin, "cpu-tagged" the XLA-CPU
# fallback rung (a valid measurement on the fallback backend, never
# published as a TPU number).
RUNG_ONCHIP = "on-chip"
RUNG_ONCHIP_UNPADDED = "on-chip-unpadded"
RUNG_CPU = "cpu-tagged"

# Error kinds that indict the BACKEND (and therefore count toward opening
# the circuit). compile_error / dtype_lowering are PROGRAM bugs and
# bundle_stale is a BUILD-ARTIFACT bug (rebuild the AOT bundle): the
# failing cell still degrades to the CPU rung, but three Pallas compile
# failures — or a fleet serving from a bundle built under last week's
# jaxlib — on a healthy chip must not route the rest of the work to CPU.
BREAKER_KINDS = frozenset(
    {"init_unavailable", "topology_mismatch", "wedge_timeout",
     "device_crash", "oom"}
)

# Default deadline for one guarded unit (a sweep cell's compile + measure,
# one recovery chunk). Generous because FIRST execution includes XLA
# compile time; override per-guard or with TAT_BACKEND_DEADLINE_S.
DEFAULT_DEADLINE_S = 600.0


def default_deadline_s(env: dict | None = None) -> float:
    raw = (env or os.environ).get(DEADLINE_ENV, "")
    try:
        return float(raw) if raw else DEFAULT_DEADLINE_S
    except ValueError:
        raise ValueError(f"{DEADLINE_ENV}={raw!r} is not a number")


class BackendGuard:
    """Run units of accelerator work so that a flaky/wedged/absent runtime
    degrades instead of killing the run.

    ``run(label, primary_fn, fallback_fn)``:

    1. circuit OPEN (cooldown pending) → skip the primary entirely, run
       the fallback, tag the result ``cpu-tagged`` (one ``backend_event``
       records the routing);
    2. otherwise run ``primary_fn`` under the deadline watchdog (fault
       injection applies here), ``record_success`` and return the primary
       rung;
    3. a CLASSIFIED failure (anything but ``unknown``) records into the
       breaker, journals a ``backend_event``, and re-runs on the fallback;
       an ``unknown`` failure re-raises — that is a code bug, and routing
       it to CPU would only reproduce it more slowly.

    ``emit`` duck-types over an ``obs.export.MetricsWriter`` (``metrics``)
    and a ``resilience.recovery.RunJournal`` (``journal``) — either or
    both may be None; ``events`` always records in-process.
    """

    def __init__(self, *,
                 deadline_s: float | None = None,
                 breaker: CircuitBreaker | None = None,
                 faults: FaultInjector | None = None,
                 metrics=None,
                 journal=None,
                 tracer=None,
                 primary_rung: str | None = None,
                 clock=time.monotonic,
                 hub=None):
        self.deadline_s = (default_deadline_s() if deadline_s is None
                           else deadline_s)
        self.breaker = breaker or CircuitBreaker()
        self.faults = faults if faults is not None \
            else FaultInjector.from_env()
        self.metrics = metrics
        self.journal = journal
        # Distributed tracing (obs.trace.Tracer, duck-typed begin/end so
        # this module stays importable by file path with no package
        # import): run() wraps the primary in a "guard_dispatch" span and
        # any degradation in a "guard_fallback" span — rung + classified
        # BackendError kind as span attributes. None = zero-cost off.
        self.tracer = tracer
        # Live metrics hub (obs.live.MetricsHub duck-typed: inc /
        # ingest_backend). None = zero-cost off, guarded `is not None`
        # at every touch — same contract as tracer.
        self.hub = hub
        self._primary_rung = primary_rung
        self._clock = clock
        self.events: list[dict] = []
        # Did the LAST run() return a fallback result? (Callers on a
        # CPU-primary host cannot tell from the rung alone.)
        self.last_fell_back = False
        self._seen_transitions = 0

    @property
    def primary_rung(self) -> str:
        """Lazy: "cpu-tagged" when the process default backend IS the
        CPU (an explicit CPU run has no higher rung to fall from),
        "on-chip" otherwise. Resolution touches ``jax.default_backend()``
        — potentially the FIRST in-process backend init, which can wedge
        on a sick runtime — so ``run()`` only resolves it INSIDE the
        deadline watchdog; callers that already know the probed platform
        (bench passes the subprocess-probe result) should construct the
        guard with an explicit ``primary_rung`` and never pay it."""
        if self._primary_rung is None:
            import jax

            self._primary_rung = (
                RUNG_CPU if jax.default_backend() == "cpu" else RUNG_ONCHIP
            )
        return self._primary_rung

    def emit(self, kind: str, label: str, **fields) -> dict:
        event = {"kind": kind, "label": label, **fields}
        self.events.append(event)
        if self.journal is not None:
            self.journal.append({"event": "backend_event", **event})
        if self.metrics is not None:
            self.metrics.emit("backend_event", **event)
        if self.hub is not None:
            self.hub.ingest_backend(event)
        return event

    def _emit_transitions(self, label: str) -> None:
        """Journal breaker transitions that happened since the last emit
        (allow() can transition without a failure being recorded)."""
        new = self.breaker.transitions[self._seen_transitions:]
        self._seen_transitions = len(self.breaker.transitions)
        for t in new:
            self.emit("circuit_" + t["to"], label, reason=t["reason"])

    def _run_fallback(self, fallback_fn, label: str, trace_parent,
                      **attrs):
        """Run the CPU fallback, wrapped in a "guard_fallback" span when
        tracing (the critical-path accountant's "retry" segment)."""
        if self.tracer is None:
            return fallback_fn()
        fspan = self.tracer.begin(
            "guard_fallback", parent=trace_parent, label=label,
            rung=RUNG_CPU, **attrs,
        )
        try:
            return fallback_fn()
        finally:
            self.tracer.end(fspan)

    def run(self, label: str, primary_fn, fallback_fn=None, *,
            rung: str | None = None, deadline_s: float | None = None,
            trace_parent=None):
        """Execute one unit. Returns ``(value, rung_it_ran_at)``.

        ``trace_parent`` (an ``obs.trace.Span`` or None) parents the
        guard's spans under the caller's dispatch span — the serving
        tier passes its ``chunk_dispatch`` span, the chunk driver its
        ``chunk`` span."""
        deadline = self.deadline_s if deadline_s is None else deadline_s
        self.last_fell_back = False
        if self.hub is not None:
            self.hub.inc("guard.runs")
        allowed = self.breaker.allow()
        self._emit_transitions(label)
        if not allowed:
            if fallback_fn is None:
                raise BackendError(
                    "wedge_timeout",
                    f"circuit open ({self.breaker.seconds_until_half_open():.0f}s "
                    f"to half-open) and no fallback for {label!r}",
                )
            self.emit(
                "circuit_routed_cpu", label, rung=RUNG_CPU,
                detail=(f"circuit open; "
                        f"{self.breaker.seconds_until_half_open():.0f}s to "
                        "half-open"),
            )
            self.last_fell_back = True
            return self._run_fallback(
                fallback_fn, label, trace_parent, circuit="open",
            ), RUNG_CPU

        gspan = None
        if self.tracer is not None:
            gspan = self.tracer.begin(
                "guard_dispatch", parent=trace_parent, label=label,
            )
        try:
            def _primary():
                self.faults.maybe_fault(label)
                # Rung resolution INSIDE the watchdog: the first touch of
                # jax.default_backend() is an in-process backend init and
                # can wedge exactly like the work itself (the r02 "probe
                # passed, run did not" window).
                return primary_fn(), (rung or self.primary_rung)

            value, primary_rung = call_with_deadline(
                _primary, deadline, label=label
            )
        except BaseException as e:
            if not isinstance(e, Exception):
                # HL002: KeyboardInterrupt/SystemExit inside the
                # watchdogged dispatch must not leak the open span —
                # end defensively (idempotent) and re-raise unclassified.
                if gspan is not None:
                    self.tracer.end(gspan, kind="interrupted")
                raise
            # Ordinary exceptions: classification decides (device errors
            # have no common base class across backends).
            kind = classify(e)
            if gspan is not None:
                # The classified kind + the rung that failed are the span
                # attributes the trace reader keys on.
                self.tracer.end(
                    gspan, kind=kind,
                    rung=rung or self._primary_rung or "unresolved",
                    detail=f"{type(e).__name__}: {e}"[:160],
                )
            if kind == "unknown":
                raise  # a code bug; degrading would only hide it.
            if kind in BREAKER_KINDS:
                self.breaker.record_failure(kind)
            self.emit(
                kind, label,
                rung=rung or self._primary_rung or "unresolved",
                detail=f"{type(e).__name__}: {e}"[:300],
                circuit=self.breaker.state,
            )
            self._emit_transitions(label)
            if fallback_fn is None:
                if isinstance(e, BackendError):
                    raise
                raise BackendError(kind, f"{type(e).__name__}: {e}"[:300]) \
                    from e
            self.last_fell_back = True
            return self._run_fallback(
                fallback_fn, label, trace_parent, after=kind,
            ), RUNG_CPU
        self.breaker.record_success()
        self._emit_transitions(label)
        if gspan is not None:
            self.tracer.end(gspan, rung=primary_rung)
        return value, primary_rung


def run_on_cpu(fn):
    """Build a fallback thunk executing ``fn`` with the host CPU as the
    default device (uncommitted computations route there; freshly created
    arrays land there). The standard ``fallback_fn`` for
    :meth:`BackendGuard.run`."""
    def thunk():
        import jax

        with jax.default_device(jax.devices("cpu")[0]):
            return fn()

    return thunk
