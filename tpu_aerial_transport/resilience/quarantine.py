"""Per-scenario NaN quarantine utilities.

Under a Monte-Carlo ``vmap``, one diverging scenario would otherwise poison
every batched statistic (NaN min/max/mean/std over the batch axis) and — via
``lax.while_loop``'s batch-max trip count — can even stall the whole batch.
Quarantine freezes a scenario at its last finite state and raises a sticky
``quarantined`` flag; aggregate statistics then exclude flagged lanes
(:func:`utils.stats.compute_aggregate_statistics` with ``valid=``).

Everything here is scalar-per-scenario and composes with ``vmap``: inside
the per-scenario program the predicates are ``()`` booleans, so a vmapped
rollout gets independent per-lane quarantine for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_all_finite(tree) -> jnp.ndarray:
    """() bool — True iff every inexact leaf of ``tree`` is entirely finite.
    Integer/bool leaves (step counters, flags) are ignored: they cannot hold
    NaN/inf and ``isfinite`` rejects exact dtypes."""
    ok = jnp.ones((), bool)
    for leaf in jax.tree.leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def tree_where(pred, on_true, on_false):
    """``jnp.where`` over matching pytrees with a scalar predicate."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)
