"""Fault schedules: scan/vmap/jit-compatible fault injection for rollouts.

A :class:`FaultSchedule` is a pytree of STATIC SHAPE describing, per agent:

- **actuator degradation**: from HL step ``t_degrade[i]`` on, agent i's
  low-level thrust (and moment authority) is scaled by ``thrust_scale[i]``
  (the thrust-cap scaling applied in :mod:`control.lowlevel`);
- **full agent loss**: at HL step ``t_fail[i]`` agent i dies — zero thrust,
  zero moment, its consensus contributions masked and its duals frozen;
- **state-sensor noise**: Gaussian noise of std ``noise_std`` on the payload
  position/velocity and per-quad body rates the *controller* sees (the
  physics integrates the true state);
- **consensus-message dropout/staleness**: per block of ``drop_hold`` HL
  steps, each agent's outgoing consensus message (its ``f^(i)`` copy in
  C-ADMM, its price/violation contribution in DD) is dropped with
  probability ``drop_rate``; while dropped, the other agents hold its LAST
  delivered value (the stale copy from the step start).

All randomness is stateless (``jax.random.fold_in`` of ``key`` with the HL
step index), so the same schedule replayed or resumed mid-rollout produces
identical faults — and a vmapped batch of schedules gives per-scenario
fault draws from per-scenario keys.

``active`` is a STATIC field: with :func:`no_faults` (``active=False``) every
consumer skips the fault branches at trace time, so the compiled nominal
rollout is bit-identical (same HLO) to one built with no schedule at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

# Sentinel HL-step index for "never": comparisons `t < NEVER` are always true
# for any reachable step count.
NEVER = jnp.iinfo(jnp.int32).max


@struct.dataclass
class FaultStep:
    """One HL step's evaluated health state (all leaves length-n over the
    GLOBAL agent axis; replicated under sharding)."""

    alive: jnp.ndarray  # (n,) bool — False once t >= t_fail.
    thrust_scale: jnp.ndarray  # (n,) float — 0 for dead agents.
    msg_ok: jnp.ndarray  # (n,) bool — consensus message delivered this step.


@struct.dataclass
class FaultSchedule:
    """Per-rollout fault description. See module docstring for semantics."""

    t_fail: jnp.ndarray  # (n,) int32 HL step of agent loss; NEVER = none.
    t_degrade: jnp.ndarray  # (n,) int32 onset of actuator degradation.
    thrust_scale: jnp.ndarray  # (n,) float scale once degraded (1 = nominal).
    drop_rate: jnp.ndarray  # () float per-(block, agent) dropout probability.
    drop_hold: jnp.ndarray  # () int32 HL steps a dropout draw persists (K).
    noise_std: jnp.ndarray  # () float sensor-noise std [m, m/s, rad/s].
    key: jnp.ndarray  # PRNG key for dropout/noise draws.
    # STATIC master switch: False compiles the exact nominal program.
    active: bool = struct.field(pytree_node=False, default=True)
    # STATIC noise switch (set by make_schedule from noise_std != 0): False
    # skips the per-step RNG draws of apply_sensor_noise at trace time —
    # noise_std is a traced leaf, so a zero value alone cannot be
    # dead-code-eliminated from the compiled scan. When enabling noise on
    # an existing schedule via .replace(noise_std=...), also pass
    # noisy=True.
    noisy: bool = struct.field(pytree_node=False, default=True)

    @property
    def n(self) -> int:
        return self.t_fail.shape[-1]


def make_schedule(
    n: int,
    *,
    t_fail=None,
    t_degrade=None,
    thrust_scale=None,
    drop_rate: float = 0.0,
    drop_hold: int = 1,
    noise_std: float = 0.0,
    key=None,
    dtype=jnp.float32,
) -> FaultSchedule:
    """Build a schedule. ``t_fail``/``t_degrade`` accept a per-agent array or
    a ``{agent: step}`` dict (unlisted agents never fault); ``thrust_scale``
    accepts an array or a scalar applied to every degraded agent."""

    def _steps(spec):
        if spec is None:
            return jnp.full((n,), NEVER, jnp.int32)
        if isinstance(spec, dict):
            out = jnp.full((n,), NEVER, jnp.int32)
            for i, t in spec.items():
                out = out.at[int(i)].set(int(t))
            return out
        return jnp.asarray(spec, jnp.int32)

    if thrust_scale is None:
        scale = jnp.ones((n,), dtype)
    else:
        scale = jnp.broadcast_to(jnp.asarray(thrust_scale, dtype), (n,))
    return FaultSchedule(
        t_fail=_steps(t_fail),
        t_degrade=_steps(t_degrade),
        thrust_scale=scale,
        drop_rate=jnp.asarray(drop_rate, dtype),
        drop_hold=jnp.asarray(max(int(drop_hold), 1), jnp.int32),
        noise_std=jnp.asarray(noise_std, dtype),
        key=key if key is not None else jax.random.PRNGKey(0),
        active=True,
        noisy=float(noise_std) != 0.0,
    )


def no_faults(n: int, dtype=jnp.float32) -> FaultSchedule:
    """The nominal schedule: ``active=False`` (STATIC), so every consumer
    compiles its fault-free path — same HLO as passing no schedule."""
    return make_schedule(n, dtype=dtype).replace(active=False)


def fault_step(sched: FaultSchedule, t) -> FaultStep:
    """Evaluate the schedule at HL step ``t`` (traced int ok). Dropout draws
    are constant within each block of ``drop_hold`` steps, so a dropped
    agent stays dropped (its last value held) for K consecutive HL steps."""
    n = sched.n
    t = jnp.asarray(t, jnp.int32)
    alive = t < sched.t_fail
    dtype = sched.thrust_scale.dtype
    scale = jnp.where(
        t >= sched.t_degrade, sched.thrust_scale, jnp.ones((), dtype)
    ) * alive.astype(dtype)
    block = t // sched.drop_hold
    drop = jax.random.bernoulli(
        jax.random.fold_in(jax.random.fold_in(sched.key, 1), block),
        sched.drop_rate, (n,),
    )
    return FaultStep(alive=alive, thrust_scale=scale, msg_ok=alive & ~drop)


def apply_sensor_noise(sched: FaultSchedule, t, state):
    """The state the CONTROLLER senses at HL step ``t``: payload position/
    velocity and per-quad body rates perturbed by N(0, noise_std^2). The
    physics keeps integrating the true ``state``."""
    t = jnp.asarray(t, jnp.int32)
    k = jax.random.fold_in(jax.random.fold_in(sched.key, 2), t)
    k1, k2, k3 = jax.random.split(k, 3)
    std = sched.noise_std.astype(state.xl.dtype)
    return state.replace(
        xl=state.xl + std * jax.random.normal(k1, state.xl.shape, state.xl.dtype),
        vl=state.vl + std * jax.random.normal(k2, state.vl.shape, state.vl.dtype),
        w=state.w + std * jax.random.normal(k3, state.w.shape, state.w.dtype),
    )
