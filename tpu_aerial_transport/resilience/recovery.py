"""Crash recovery for chunked rollouts: chunk-completion journal, resumable
runs, and preemption-graceful shutdown.

The paper's receding-horizon structure makes exact mid-run snapshots cheap —
one carry per control step — and ``harness.rollout.make_chunked_rollout`` /
``resilience.rollout.make_chunked_resilient_rollout`` surface that carry at
every chunk boundary through ONE compiled chunk function
``chunk(carry, i0) -> (carry, logs)``. This module is the host-side driver
around that contract:

- :class:`RunJournal` — an append-only, fsync'd, truncation-tolerant jsonl
  record of run metadata and per-chunk completion (the journal a wedged
  bench sweep or a killed rollout is resumed FROM);
- :func:`run_chunks` — drive the chunk function boundary to boundary,
  publishing an atomic versioned carry snapshot (``harness.checkpoint``)
  and a per-chunk log snapshot after every chunk, with an optional
  host-level retry that restores the last boundary carry and requeues the
  surviving work after a device error;
- :func:`resume_run` — pick the newest carry snapshot that passes every
  integrity check (digests, treedef fingerprint, config hash) WITH a
  complete valid log prefix, journal what was skipped and why, and continue
  the run to completion — kill-at-any-chunk followed by ``resume_run``
  reproduces the uninterrupted trajectory bit-exactly
  (tests/test_recovery.py), sticky quarantine flags included (they live in
  the resilient carry);
- :class:`GracefulInterrupt` — a SIGTERM/SIGINT context manager: the first
  signal requests a stop at the next chunk boundary (where
  :func:`run_chunks` flushes a final snapshot and journals ``preempted``),
  a second signal escalates to an immediate ``KeyboardInterrupt``.

Determinism contract: the initial carry must be regenerable from the
journal's recorded seed/meta (``envs.forest.make_forest(seed)`` and the
setup factories are deterministic), so a run directory plus the code that
started it is sufficient to resume — no live process state survives, none
is needed.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.harness import checkpoint
from tpu_aerial_transport.harness.rollout import (
    chunk_index_offset,
    concat_chunk_logs,
)
from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import telemetry as telemetry_mod
from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.resilience import backend as backend_mod

JOURNAL_SCHEMA = 1
CARRY_PREFIX = "carry"
LOGS_PREFIX = "logs"


def host_copy(tree):
    """THE host backup of a device pytree (retry/requeue/snapshot anchor):
    ``np.array(copy=True)`` per leaf, NOT ``np.asarray`` — on the CPU
    backend ``np.asarray`` of a jax array is a zero-copy VIEW of the
    device buffer, which a later donation (or a dying device) silently
    recycles under the "backup". Shared by :func:`run_chunks` and the
    serving tier's boundary bookkeeping (``serving/server.py``) so the
    footgun is documented and dodged in exactly one place. Also a device
    sync: it blocks until the leaves are ready, surfacing device errors
    at the caller."""
    return jax.tree.map(lambda l: np.array(l, copy=True), tree)


@dataclasses.dataclass(frozen=True)
class RunPlan:
    """Static description of a chunked run — journaled at start, re-read by
    :func:`resume_run` so resumption needs only the run directory (plus the
    deterministic setup the ``meta``/``seed`` fields describe)."""

    run_dir: str
    n_hl_steps: int
    n_chunks: int
    seed: int | None = None
    config_hash: str | None = None
    keep_last: int = 3
    # Axis the per-chunk logs concatenate on: 0 for a single-scenario
    # rollout, 1 when the chunk is vmapped over a leading batch axis
    # (parallel.mesh.scenario_rollout_resumable sets 1).
    logs_time_axis: int = 0
    meta: dict = dataclasses.field(default_factory=dict)
    # Snapshot-family / journal names. Defaults are the historical
    # single-process layout; the pods tier (parallel/pods.py) gives each
    # PROCESS its own prefixes (checkpoint.shard_prefix) and journal file
    # inside ONE shared run_dir, so N processes checkpoint concurrently
    # without racing on files while the shard manifest ties the set
    # together.
    carry_prefix: str = CARRY_PREFIX
    logs_prefix: str = LOGS_PREFIX
    journal_filename: str | None = None

    @property
    def chunk_len(self) -> int:
        return self.n_hl_steps // self.n_chunks


@dataclasses.dataclass
class RunResult:
    """Outcome of :func:`run_chunks` / :func:`resume_run`. ``logs`` is the
    full concatenated log pytree over every completed chunk (``None`` when
    zero chunks completed); ``status`` is ``"done"`` or ``"preempted"``;
    ``resumed_from_chunk`` is the chunk index execution (re)started at
    (``None`` for a fresh, uninterrupted run); ``retries`` counts
    host-level device-error requeues."""

    carry: object
    logs: object
    status: str
    chunks_done: int
    resumed_from_chunk: int | None = None
    retries: int = 0


class Preempted(RuntimeError):
    """Raised by drivers that prefer an exception over a ``"preempted"``
    result (kept for callers embedding :func:`run_chunks` in larger jobs)."""


class RunJournal:
    """Append-only jsonl journal. Every append is flushed AND fsync'd
    before returning — a chunk is only "completed" once its journal line is
    durable — and :meth:`read` tolerates a torn final line (the exact state
    a power cut mid-append leaves behind): the partial line is ignored, so
    the run resumes from the last durable chunk instead of refusing."""

    FILENAME = "journal.jsonl"

    def __init__(self, run_dir: str, filename: str | None = None):
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, filename or self.FILENAME)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def append(self, event: dict) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        # The durable-append + torn-tail-tolerant-read primitives are
        # shared with the metrics writer (obs.export) — one copy of the
        # durability contract.
        export_mod.jsonl_append(self.path, event)

    def read(self) -> list[dict]:
        if not self.exists():
            return []
        return export_mod.jsonl_read(self.path)

    def completed_chunks(self) -> set[int]:
        return {e["chunk"] for e in self.read() if e.get("event") == "chunk"}


class GracefulInterrupt:
    """Context manager turning SIGTERM/SIGINT into a chunk-boundary stop.

    First signal: record it and let the in-flight XLA computation finish —
    :func:`run_chunks` sees :attr:`triggered` at the next boundary, flushes
    a final snapshot, journals ``preempted`` and returns. Second signal:
    escalate to ``KeyboardInterrupt`` immediately (the operator insists).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._old: dict = {}
        self.triggered: str | None = None

    def _handle(self, signum, frame):
        del frame
        if self.triggered is not None:
            raise KeyboardInterrupt(f"second signal {signum}")
        self.triggered = signal.Signals(signum).name

    def __enter__(self) -> "GracefulInterrupt":
        for s in self._signals:
            self._old[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            signal.signal(s, old)
        return False


def read_plan(run_dir: str, journal_filename: str | None = None) -> RunPlan:
    """Reconstruct the :class:`RunPlan` from a run directory's journal
    (``journal_filename`` selects a per-process journal in the pods
    layout; default is the single-process journal)."""
    journal = RunJournal(run_dir, filename=journal_filename)
    for e in journal.read():
        if e.get("event") == "run_start":
            return RunPlan(
                run_dir=run_dir,
                n_hl_steps=e["n_hl_steps"],
                n_chunks=e["n_chunks"],
                seed=e.get("seed"),
                config_hash=e.get("config_hash"),
                keep_last=e.get("keep_last", 3),
                logs_time_axis=e.get("logs_time_axis", 0),
                meta=e.get("meta", {}),
                carry_prefix=e.get("carry_prefix", CARRY_PREFIX),
                logs_prefix=e.get("logs_prefix", LOGS_PREFIX),
                journal_filename=journal_filename,
            )
    raise checkpoint.SnapshotError(
        "unreadable", journal.path,
        "no run_start event in journal (not a recovery run directory?)",
    )


def run_chunks(
    plan: RunPlan,
    chunk_jit,
    carry,
    *,
    start_chunk: int = 0,
    prior_logs=(),
    interrupt: GracefulInterrupt | None = None,
    place=None,
    max_retries: int = 0,
    resumed_from_chunk: int | None = None,
    metrics: "export_mod.MetricsWriter | str | None" = None,
    guard: "backend_mod.BackendGuard | None" = None,
    to_host=None,
    tracer: "trace_mod.Tracer | None" = None,
    trace_parent=None,
) -> RunResult:
    """Drive ``chunk_jit(carry, i0) -> (carry, logs)`` from ``start_chunk``
    to ``plan.n_chunks``, snapshotting the carry and the chunk's logs at
    every boundary and journaling completion.

    ``to_host`` (optional) replaces :func:`host_copy` as the
    device-to-host extraction for BOTH the boundary carry and the chunk
    logs. The pods tier needs it: ``np.array`` of a multi-process global
    ``jax.Array`` raises (the process only addresses its own shards), so
    ``parallel.pods`` passes its local-shard extractor and each process
    snapshots exactly the block it owns. When set, the chunk logs are
    ALSO localized before snapshot/concat — the returned ``logs`` are
    then host arrays of the process-local block.

    ``place`` (optional) maps a host carry onto devices (e.g.
    ``parallel.mesh.shard_scenarios``) — applied to the initial carry and
    after every device-error restore. ``max_retries`` > 0 enables the
    host-level retry: a chunk that raises (a device error, a wedged chip
    surfacing as a runtime error) is requeued on the carry restored from
    the last boundary's HOST copy — donation may have consumed the device
    buffers of the failed call, the host copy survives.

    ``guard`` (optional; a ``resilience.backend.BackendGuard``) turns on
    mid-run graceful degradation: each chunk's compile+execute runs under
    the guard's deadline watchdog, classified backend failures (wedge,
    init, crash, oom) journal a ``backend_event`` and re-run the chunk on
    the XLA-CPU rung from the last boundary's host carry — and the run
    CONTINUES on CPU (the degradation is one-way; ``resume_run`` after the
    process dies replays from the failed chunk, not from scratch). Every
    chunk journal/metrics event then records the ``rung`` it actually ran
    at. Degradation is for single-device chunk drivers; it is not applied
    under a mesh ``place`` fn (sharded carries re-place via ``place``, and
    a multi-chip run losing its mesh cannot shrink onto one host CPU).

    ``metrics`` (optional; an ``obs.export.MetricsWriter`` or a jsonl
    path) turns on the flight-recorder export: one schema-versioned
    ``chunk`` event per boundary carrying the chunk wall time, a digest of
    the chunk's logs, and — when the carry threads an
    ``obs.telemetry.TelemetryState`` (the ``telemetry=`` option of the
    chunked-rollout factories) — the cumulative run-health summary; plus
    ``retry``/``preempted``/``done`` events. ``tools/run_health.py``
    renders the file.

    ``tracer`` (optional; an ``obs.trace.Tracer``) turns on distributed
    tracing: a ``run`` root span, one ``chunk`` span per chunk (child
    ``snapshot`` span around the boundary publish; the guard's
    dispatch/fallback spans nest under it), host-level retries as
    ``retry`` instants, preemption/resume boundaries marked.
    ``tracer=None`` is the zero-cost path (every site is a host-level
    ``if``); ``trace_parent`` lets :func:`resume_run` parent the run
    under its ``resume`` span.

    Carry snapshots are pruned to ``plan.keep_last``; per-chunk log
    snapshots are kept for ALL chunks (the full trajectory must be
    reconstructable) and are only removed by the operator deleting the run
    directory.
    """
    journal = RunJournal(plan.run_dir, filename=plan.journal_filename)
    os.makedirs(plan.run_dir, exist_ok=True)
    _host = to_host if to_host is not None else host_copy
    if isinstance(metrics, str):
        metrics = export_mod.MetricsWriter(metrics)
    if metrics is not None and start_chunk == 0:
        metrics.emit(
            "run_start", run_dir=plan.run_dir,
            n_hl_steps=plan.n_hl_steps, n_chunks=plan.n_chunks,
            seed=plan.seed, config_hash=plan.config_hash, meta=plan.meta,
        )
    if start_chunk == 0 and not any(
        e.get("event") == "run_start" for e in journal.read()
    ):
        journal.append({
            "event": "run_start", "schema": JOURNAL_SCHEMA,
            "n_hl_steps": plan.n_hl_steps, "n_chunks": plan.n_chunks,
            "chunk_len": plan.chunk_len, "seed": plan.seed,
            "config_hash": plan.config_hash, "keep_last": plan.keep_last,
            "logs_time_axis": plan.logs_time_axis, "meta": plan.meta,
            "carry_prefix": plan.carry_prefix,
            "logs_prefix": plan.logs_prefix,
        })
    logs_chunks = list(prior_logs)
    # The host copy is the retry/requeue anchor: donation consumes device
    # buffers, a dying device drops them — numpy on the host survives both
    # (host_copy documents why it must be a real copy).
    carry_host = _host(carry)
    carry = place(carry) if place is not None else carry
    retries_total = 0
    attempt = 0
    c = start_chunk
    if guard is not None:
        # The guard's backend_event rows land in THIS run's journal and
        # metrics unless the caller pre-wired its own sinks.
        if guard.journal is None:
            guard.journal = journal
        if guard.metrics is None:
            guard.metrics = metrics
        if guard.tracer is None:
            guard.tracer = tracer
    run_span = None
    if tracer is not None:
        run_span = tracer.begin(
            trace_mod.RUN, parent=trace_parent, run_dir=plan.run_dir,
            start_chunk=start_chunk, n_chunks=plan.n_chunks,
            **({"resumed_from": resumed_from_chunk}
               if resumed_from_chunk is not None else {}),
        )
    rung: str | None = None
    degraded = False  # one-way: a guard fallback pins the run to CPU.

    def _cpu_place(tree):
        cpu = jax.devices("cpu")[0]
        return jax.tree.map(lambda l: jax.device_put(np.asarray(l), cpu),
                            tree)
    while c < plan.n_chunks:
        if interrupt is not None and interrupt.triggered:
            if c > 0:
                # Flush a final snapshot of the boundary carry. Normally a
                # rewrite of the snapshot published right after chunk c-1
                # (atomic, idempotent); it guarantees the preempted state
                # is durable even if that publish predates this process.
                checkpoint.save_snapshot(
                    plan.run_dir, c - 1, carry_host,
                    prefix=plan.carry_prefix, config_hash=plan.config_hash,
                    keep_last=plan.keep_last, meta={"chunk": c - 1},
                )
            journal.append({
                "event": "preempted", "chunk": c,
                "signal": interrupt.triggered,
            })
            if metrics is not None:
                metrics.emit(
                    "preempted", chunk=c, signal=interrupt.triggered
                )
            if tracer is not None:
                tracer.instant("preempted", parent=run_span, chunk=c,
                               signal=interrupt.triggered)
                tracer.end(run_span, status="preempted", chunks_done=c)
            return RunResult(
                carry=carry,
                logs=(concat_chunk_logs(logs_chunks, plan.logs_time_axis)
                      if logs_chunks else None),
                status="preempted", chunks_done=c,
                resumed_from_chunk=resumed_from_chunk,
                retries=retries_total,
            )
        cspan = sspan = None
        if tracer is not None:
            cspan = tracer.begin(trace_mod.CHUNK, parent=run_span, chunk=c)
        try:
            t0 = time.perf_counter()
            offset = chunk_index_offset(c, plan.chunk_len)

            def _exec(chunk_carry):
                out_carry, out_logs = chunk_jit(chunk_carry, offset)
                # The copy both syncs (device errors surface inside this
                # try — and, under the guard, inside the watchdogged
                # primary call) and backs the carry up before the next
                # donation consumes it (see the zero-copy-view note
                # above). It stays a LOCAL until the boundary is fully
                # published: rebinding carry_host here would make a
                # snapshot IO failure retry chunk c from chunk c's own
                # output — applying its dynamics twice.
                out_host = _host(out_carry)
                if to_host is not None:
                    # Pods: logs are multi-process global arrays too —
                    # localize before snapshot/concat (np.asarray of the
                    # global array would raise in save_snapshot).
                    out_logs = to_host(out_logs)
                return out_carry, out_logs, out_host

            if guard is None:
                new_carry, logs, new_carry_host = _exec(carry)
            elif degraded:
                # Already re-placed on CPU: run there directly (paying the
                # primary deadline per chunk against an open/flaky backend
                # would re-wedge every boundary).
                new_carry, logs, new_carry_host = _exec(_cpu_place(carry))
                rung = backend_mod.RUNG_CPU
            else:
                # CPU degradation restores from the last BOUNDARY's host
                # copy (the failed primary may have consumed/poisoned the
                # device buffers); disabled under a mesh `place` fn — the
                # guard then still provides deadline + classification and
                # classified errors fall through to the host-level retry.
                fallback = (None if place is not None
                            else lambda: _exec(_cpu_place(carry_host)))
                (new_carry, logs, new_carry_host), rung = guard.run(
                    f"chunk{c}", lambda: _exec(carry), fallback_fn=fallback,
                    trace_parent=cspan,
                )
                degraded = guard.last_fell_back
            wall_s = time.perf_counter() - t0  # host copy = device sync.
            if tracer is not None:
                sspan = tracer.begin(trace_mod.SNAPSHOT, parent=cspan,
                                     chunk=c)
            checkpoint.save_snapshot(
                plan.run_dir, c, new_carry_host, prefix=plan.carry_prefix,
                config_hash=plan.config_hash, keep_last=plan.keep_last,
                meta={"chunk": c},
            )
            checkpoint.save_snapshot(
                plan.run_dir, c, logs, prefix=plan.logs_prefix,
                config_hash=plan.config_hash, keep_last=0,
                meta={"chunk": c},
            )
            if tracer is not None:
                tracer.end(sspan)
        except checkpoint.SnapshotError:
            if tracer is not None:
                # The span recording the FAILING publish must survive —
                # the server's harvest-span rule (ended before its chunk
                # parent so the trace stays well-ordered).
                if sspan is not None:
                    tracer.end(sspan, error="snapshot")
                tracer.end(cspan, error="snapshot")
                tracer.end(run_span, status="error")
            raise  # a disk-integrity problem; retrying the chunk won't help.
        except Exception as e:  # noqa: BLE001 — device errors have no
            # common base class across backends (XlaRuntimeError,
            # RuntimeError, ValueError from a poisoned transfer...).
            if tracer is not None:
                if sspan is not None and not sspan.ended:
                    tracer.end(sspan, error=f"{type(e).__name__}"[:80])
                tracer.end(cspan, error=f"{type(e).__name__}: {e}"[:160])
            if attempt >= max_retries:
                if tracer is not None:
                    tracer.end(run_span, status="error")
                raise
            attempt += 1
            retries_total += 1
            journal.append({
                "event": "retry", "chunk": c, "attempt": attempt,
                "error": f"{type(e).__name__}: {e}"[:300],
            })
            if metrics is not None:
                metrics.emit(
                    "retry", chunk=c, attempt=attempt,
                    error=f"{type(e).__name__}: {e}"[:300],
                )
            if tracer is not None:
                tracer.instant(trace_mod.RETRY, parent=run_span, chunk=c,
                               attempt=attempt)
            carry = jax.tree.map(jnp.asarray, carry_host)
            carry = place(carry) if place is not None else carry
            continue
        except BaseException:
            # HL002: KeyboardInterrupt/SystemExit mid-chunk must not
            # leak the open spans — end defensively (end() is
            # idempotent) and re-raise. A handler, NOT a finally: the
            # success path below ends cspan WITH its rung attribute,
            # which a finally-side end would preempt.
            if tracer is not None:
                if sspan is not None and not sspan.ended:
                    tracer.end(sspan, error="interrupted")
                tracer.end(cspan, error="interrupted")
                tracer.end(run_span, status="interrupted")
            raise
        if tracer is not None:
            tracer.end(cspan, **({"rung": rung} if rung is not None
                                 else {}))
        journal.append({
            "event": "chunk", "chunk": c,
            "step_end": (c + 1) * plan.chunk_len,
            "carry_snapshot": os.path.basename(
                checkpoint.snapshot_path(plan.run_dir, c, plan.carry_prefix)
            ),
            "retries": attempt,
            # The rung this chunk ACTUALLY ran at (guard runs only).
            **({"rung": rung} if rung is not None else {}),
        })
        if metrics is not None:
            # The telemetry accumulator (if the chunk carry threads one) is
            # CUMULATIVE over the run — the last chunk event holds the
            # whole-run summary; the logs digest covers THIS chunk only.
            tel = telemetry_mod.find_state(new_carry_host)
            metrics.emit(
                "chunk", chunk=c, wall_s=wall_s, retries=attempt,
                step_end=(c + 1) * plan.chunk_len,
                telemetry=export_mod.telemetry_event(tel),
                logs=_logs_digest(logs),
                **({"rung": rung} if rung is not None else {}),
            )
        logs_chunks.append(logs)
        carry = new_carry
        carry_host = new_carry_host  # boundary published: advance the anchor.
        c += 1
        attempt = 0
    journal.append({"event": "done", "chunks": plan.n_chunks})
    if metrics is not None:
        metrics.emit("done", chunks=plan.n_chunks)
    if tracer is not None:
        tracer.end(run_span, status="done", chunks=plan.n_chunks)
    return RunResult(
        carry=carry,
        logs=(concat_chunk_logs(logs_chunks, plan.logs_time_axis)
              if logs_chunks else None),
        status="done", chunks_done=plan.n_chunks,
        resumed_from_chunk=resumed_from_chunk,
        retries=retries_total,
    )


def _logs_digest(logs) -> dict | None:
    """Per-chunk log digest for the metrics export, None when the chunk's
    logs are not rollout-shaped (``run_chunks`` is generic over the chunk
    function — bench sweeps and custom chunk drivers pass other pytrees)."""
    if not all(
        hasattr(logs, k)
        for k in ("fallback_rung", "solve_res", "min_env_dist",
                  "collision", "quarantined")
    ):
        return None
    return export_mod.logs_summary(logs)


def resume_run(
    run_dir: str,
    chunk_jit,
    initial_carry,
    *,
    config_hash: str | None = None,
    interrupt: GracefulInterrupt | None = None,
    place=None,
    max_retries: int = 0,
    metrics: "export_mod.MetricsWriter | str | None" = None,
    guard: "backend_mod.BackendGuard | None" = None,
    journal_filename: str | None = None,
    to_host=None,
    max_start_chunk: int | None = None,
    tracer: "trace_mod.Tracer | None" = None,
) -> RunResult:
    """Resume a journaled run from its newest fully-valid boundary.

    ``journal_filename`` / ``to_host`` mirror :func:`run_chunks` (the
    pods per-process layout). ``max_start_chunk`` caps the resume point:
    the pods tier must restart every process from the SAME boundary —
    a process whose newest shard snapshot is ahead of a peer's (it died
    mid-publish) passes the cross-process minimum here and re-runs the
    chunks its peers lost (parallel.pods agrees on the cap via an
    all-gather before calling this).

    ``initial_carry`` is the chunk-0 carry regenerated DETERMINISTICALLY
    from the journaled seed/meta (``run.init_carry(...)`` on freshly built
    setup state); it doubles as the structure/dtype template every snapshot
    is verified against, and as the restart point when no snapshot survives
    validation. A resume point ``c`` is accepted only when the carry
    snapshot of chunk ``c`` AND the log snapshots of chunks ``0..c`` all
    pass integrity + config checks — otherwise the walk falls back to the
    previous boundary (rejected snapshots are journaled with their
    structured error). ``config_hash`` (when given) must match the
    journaled one — refusing to silently mix configurations is the point.

    Returns the SAME result an uninterrupted run would have produced,
    bit-exactly: restored chunks contribute their stored logs, remaining
    chunks recompute from the restored carry through the one compiled
    chunk function.
    """
    plan = read_plan(run_dir, journal_filename=journal_filename)
    journal = RunJournal(run_dir, filename=journal_filename)
    if (config_hash is not None and plan.config_hash is not None
            and config_hash != plan.config_hash):
        raise checkpoint.SnapshotError(
            "config_mismatch", journal.path,
            f"journal config {plan.config_hash} != current {config_hash}: "
            "the run was started under a different configuration",
        )
    check_hash = config_hash if config_hash is not None else plan.config_hash
    # Shape-only evaluation of the chunk gives the log template without
    # running (or even compiling) anything. Under a pods to_host the
    # SAVED logs are host-local blocks of the same shapes (the chunk is
    # traced at the local batch size), so the template still matches.
    _, logs_template = jax.eval_shape(
        chunk_jit, initial_carry, chunk_index_offset(0, plan.chunk_len)
    )

    # The resume boundary as a span: the walk over candidate snapshots
    # is real recovery time, and the post-resume run's spans parent
    # under it so "what happened at the resume boundary" reads straight
    # off the trace.
    rspan = None
    if tracer is not None:
        rspan = tracer.begin(trace_mod.RESUME, parent=None,
                             run_dir=run_dir)
    try:
        skipped: list[str] = []
        start_chunk = 0
        carry = initial_carry
        prior_logs: list = []
        for step, path in reversed(
            checkpoint.list_snapshots(run_dir, plan.carry_prefix)
        ):
            if max_start_chunk is not None and step + 1 > max_start_chunk:
                skipped.append(
                    f"[beyond_cap] {path}: boundary {step + 1} > agreed "
                    f"start cap {max_start_chunk} (peer processes lost it)"
                )
                continue
            try:
                cand, _ = checkpoint.load_snapshot(
                    path, initial_carry, config_hash=check_hash
                )
                cand_logs = []
                for lc in range(step + 1):
                    lpath = checkpoint.snapshot_path(
                        run_dir, lc, plan.logs_prefix
                    )
                    lg, _ = checkpoint.load_snapshot(
                        lpath, logs_template, config_hash=check_hash
                    )
                    cand_logs.append(lg)
            except checkpoint.SnapshotError as e:
                skipped.append(str(e))
                continue
            start_chunk = step + 1
            carry = cand
            prior_logs = cand_logs
            break
        journal.append({
            "event": "resume", "start_chunk": start_chunk,
            "skipped": skipped[:8],
        })
        if isinstance(metrics, str):
            metrics = export_mod.MetricsWriter(metrics)
        if metrics is not None:
            metrics.emit(
                "resume", start_chunk=start_chunk, skipped=skipped[:8]
            )
    except BaseException:
        # HL002: a snapshot-walk failure (or Ctrl-C during it) must not
        # leak the open resume span.
        if tracer is not None:
            tracer.end(rspan, error="interrupted")
        raise
    if tracer is not None:
        tracer.end(rspan, start_chunk=start_chunk,
                   skipped=len(skipped))
    return run_chunks(
        plan, chunk_jit, carry, start_chunk=start_chunk,
        prior_logs=prior_logs, interrupt=interrupt, place=place,
        max_retries=max_retries, resumed_from_chunk=start_chunk,
        metrics=metrics, guard=guard, to_host=to_host,
        tracer=tracer, trace_parent=rspan,
    )
