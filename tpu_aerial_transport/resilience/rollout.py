"""Fault-aware receding-horizon rollout: the harness rollout threaded with
fault evaluation, an explicit force-fallback ladder, and per-scenario NaN
quarantine. One jit-compiled two-rate ``lax.scan``, vmappable over
Monte-Carlo scenarios exactly like :func:`harness.rollout.rollout`.

**Fallback ladder** (each rung counted in the extended
:class:`control.types.SolverStats` / :class:`harness.rollout.RQPLogStep`
``fallback_rung`` field):

  0. clean warm-started solve (``ok_frac == 1``, finite forces);
  1. the controller retried internally and/or substituted equilibrium
     forces for failed agent solves (``ok_frac < 1``) but returned finite
     forces;
  2. the controller returned non-finite forces — hold the previous step's
     applied forces (and the previous controller state, so the poisoned
     solve does not seed the next warm start);
  3. non-finite forces and no finite previous force exists (first step, or
     the hold itself was poisoned) — fall back to the equilibrium force
     distribution (healthy-mask aware), which is always finite.

**Quarantine**: if a scenario's physics state goes non-finite despite the
ladder, the scenario freezes at its last finite state and its sticky
``quarantined`` flag raises in the log — inside a vmapped batch the other
lanes are untouched (bit-identical to a run without the diverging lane) and
aggregate statistics can exclude flagged lanes via
``utils.stats.compute_aggregate_statistics(..., valid=~quarantined)``.

**Zero-cost when disabled**: ``faults=None`` and
``faults=resilience.faults.no_faults(n)`` compile the IDENTICAL program
(``active`` is a static field and every fault branch is a Python-level
``if``), asserted by tests/test_resilience_faults.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpu_aerial_transport.control import centralized
from tpu_aerial_transport.harness.rollout import RQPLogStep
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.obs import telemetry as telemetry_mod
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.quarantine import (
    tree_all_finite,
    tree_where,
)

RUNG_CLEAN = 0
RUNG_RETRY = 1
RUNG_HOLD = 2
RUNG_EQUILIBRIUM = 3


def make_cadmm_hl_step(params, cfg, forest=None, plan=None) -> Callable:
    """Health-aware C-ADMM high-level step for :func:`resilient_rollout`:
    recomputes the equilibrium force distribution from the healthy-agent
    mask each step (survivors share the dead agents' load) and forwards the
    health mask into the consensus reductions."""
    from tpu_aerial_transport.control import cadmm

    if plan is None:
        plan = cadmm.make_plan(params, cfg)

    def hl_step(cs, state, acc_des, health=None):
        alive = None if health is None else health.alive
        f_eq = centralized.equilibrium_forces(params, alive)
        return cadmm.control(
            params, cfg, f_eq, cs, state, acc_des, forest, plan=plan,
            health=health,
        )

    # Seed the delivered-snapshot carry (CADMMState.held) so the scan carry
    # structure is fixed from step 0; resilient_rollout calls this when
    # fault injection is active.
    hl_step.prepare_ctrl_state = lambda cs: cs.replace(held=cs.f)
    return hl_step


def make_dd_hl_step(params, cfg, forest=None, plan=None) -> Callable:
    """Health-aware DD high-level step (see :func:`make_cadmm_hl_step`)."""
    from tpu_aerial_transport.control import dd

    if plan is None:
        plan = dd.make_dd_plan(params, cfg)

    def hl_step(cs, state, acc_des, health=None):
        alive = None if health is None else health.alive
        f_eq = centralized.equilibrium_forces(params, alive)
        return dd.control(
            params, cfg, f_eq, cs, state, acc_des, forest, plan=plan,
            health=health,
        )

    hl_step.prepare_ctrl_state = lambda cs: cs.replace(
        held_f=cs.f, held_lam_F=cs.lam_F, held_lam_M=cs.lam_M
    )
    return hl_step


def init_resilient_carry(
    hl_step: Callable,
    params: rqp.RQPParams,
    state0: rqp.RQPState,
    ctrl_state0,
    faults: faults_mod.FaultSchedule | None = None,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """The full :func:`resilient_rollout` scan carry — ``(state, ctrl_state,
    prev_applied_force, sticky_quarantine_flag[, telemetry_state])`` — for
    a fresh run. Surfacing it (rather than keeping it internal to the scan)
    is what makes the fault-aware rollout chunkable: a snapshot of this
    tuple at a chunk boundary captures the fallback ladder's hold force,
    the sticky quarantine flag, and the run-health accumulator bit-exactly,
    so a resumed run cannot silently un-freeze a quarantined lane, re-seed
    a poisoned warm start, or forget its telemetry."""
    active = faults is not None and faults.active
    if active and hasattr(hl_step, "prepare_ctrl_state"):
        # Controller adapters seed resilience-only state carries (e.g. the
        # delivered-snapshot ``held`` fields) so the scan carry structure
        # is fixed from step 0.
        ctrl_state0 = hl_step.prepare_ctrl_state(ctrl_state0)
    n = params.n
    dtype = state0.xl.dtype
    carry = (
        state0, ctrl_state0,
        jnp.full((n, 3), jnp.nan, dtype),  # no previous force yet.
        jnp.zeros((), bool),
    )
    if telemetry is not None and telemetry.active:
        carry = carry + (
            telemetry_mod.init_telemetry(telemetry, n, dtype),
        )
    return carry


def resilient_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    state0: rqp.RQPState,
    ctrl_state0,
    n_hl_steps: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable | None = None,
    faults: faults_mod.FaultSchedule | None = None,
    carry0=None,
    step_offset=0,
    return_carry: bool = False,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Run ``n_hl_steps`` high-level control periods with fault injection,
    the fallback ladder, and NaN quarantine.

    Args:
      hl_step: ``(ctrl_state, state, acc_des, health) -> (f_des (n, 3),
        ctrl_state, SolverStats)`` — e.g. :func:`make_cadmm_hl_step`.
        ``health`` is ``None`` whenever fault injection is inactive.
      ll_control: ``(state, f_des[, thrust_scale]) -> (f (n,), M (n, 3))``
        — :meth:`control.lowlevel.LowLevelController.control` qualifies;
        the third argument is only passed when fault injection is active.
      faults: optional :class:`FaultSchedule`. ``None`` or a schedule with
        ``active=False`` compiles the identical nominal program.
      carry0: a full carry from :func:`init_resilient_carry` (or a previous
        ``return_carry=True`` call) — the chunk-resume path. When given,
        ``state0``/``ctrl_state0`` may be ``None`` and ``acc_des_fn`` must
        be explicit (the hover default would re-anchor per chunk).
      step_offset: global index of the first HL step (traced int32 under
        chunking; the per-step fault schedule and sensor-noise RNG are
        indexed by the GLOBAL step, so chunked and unchunked runs draw
        identical faults).
      return_carry: return ``(carry, logs)`` instead of unpacking — the
        uniform chunk contract ``resilience.recovery`` snapshots.
      telemetry: optional :class:`obs.telemetry.TelemetryConfig`; when
        active the run-health accumulator rides the carry (see
        :func:`init_resilient_carry`) and is updated each step with the
        post-ladder stats (so the rung histogram counts the ladder's
        rungs) and the sticky quarantine flag. ``None``/inactive compiles
        the identical telemetry-less program (tests/test_telemetry.py).

    Returns ``(final_state, final_ctrl_state, logs: RQPLogStep)`` (or
    ``(carry, logs)``; with telemetry active and ``return_carry=False``
    the final ``TelemetryState`` is appended as a fourth value); the
    sticky quarantine flag is ``logs.quarantined`` (last entry = final).
    """
    active = faults is not None and faults.active
    tel_on = telemetry is not None and telemetry.active
    if carry0 is None:
        carry0 = init_resilient_carry(
            hl_step, params, state0, ctrl_state0, faults, telemetry
        )
    if acc_des_fn is None:
        if state0 is None:
            raise ValueError(
                "acc_des_fn must be explicit when resuming from carry0: "
                "the hover default anchors at state0"
            )
        x0 = state0.xl

        def acc_des_fn(state, t):
            del t
            dvl_des = -1.0 * state.vl - 1.0 * (state.xl - x0)
            return (dvl_des, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    dtype = carry0[0].xl.dtype
    f_eq_full = centralized.equilibrium_forces(params)

    def hl_body(carry, i):
        if tel_on:
            state, cs, prev_f, quar, tel = carry
        else:
            state, cs, prev_f, quar = carry
        t = i * hl_rel_freq * dt
        if active:
            with phases.scope(phases.FAULTS):
                health = faults_mod.fault_step(faults, i)
                # faults.noisy is static: noise-free schedules (agent kill
                # / dropout only) skip the per-step RNG draws at trace
                # time.
                sensed = (faults_mod.apply_sensor_noise(faults, i, state)
                          if faults.noisy else state)
                # The rung-3 fallback needs the healthy-mask equilibrium
                # even though the hl_step adapters compute their own copy
                # — a pinv of a 3 x n wrench matrix, noise next to one
                # agent QP solve, accepted to keep the hl_step protocol
                # controller-agnostic.
                f_eq_t = centralized.equilibrium_forces(
                    params, health.alive
                )
        else:
            health = None
            sensed = state
            f_eq_t = f_eq_full
        acc_des, x_ref, v_ref = acc_des_fn(sensed, t)
        f_des, cs_new, stats = hl_step(cs, sensed, acc_des, health)

        # --- Fallback ladder (rungs 0-3, module docstring). ---
        with phases.scope(phases.FALLBACK):
            finite_f = jnp.all(jnp.isfinite(f_des))
            if active:
                prev_hold = prev_f * health.alive.astype(dtype)[:, None]
            else:
                prev_hold = prev_f
            prev_ok = jnp.all(jnp.isfinite(prev_hold))
            retried = stats.ok_frac < 1.0
            if active:
                # Consensus blackout: no alive agent delivered a message
                # this step, so the masked consensus residual is vacuously
                # 0 and the controller exits immediately on held values —
                # a degraded step, not a clean one. Surface it on the
                # retry rung so solve_res=0 steps cannot read as the
                # healthiest in the run.
                retried = retried | ~jnp.any(health.alive & health.msg_ok)
            # jnp.where does not propagate NaNs from the unselected branch
            # in the primal computation, so the nested select is NaN-safe.
            f_used = jnp.where(
                finite_f, f_des, jnp.where(prev_ok, prev_hold, f_eq_t)
            )
            rung = jnp.where(
                finite_f,
                jnp.where(retried, RUNG_RETRY, RUNG_CLEAN),
                jnp.where(prev_ok, RUNG_HOLD, RUNG_EQUILIBRIUM),
            ).astype(jnp.int32)
            stats = stats.replace(fallback_rung=rung)
            # A poisoned solve must not seed the next warm start: keep the
            # new controller state only while it is entirely finite.
            cs_next = tree_where(tree_all_finite(cs_new), cs_new, cs)

        def ll_body(s, _):
            if active:
                f, M = ll_control(s, f_used, health.thrust_scale)
            else:
                f, M = ll_control(s, f_used)
            return rqp.integrate(params, s, (f, M), dt), None

        with phases.scope(phases.DYNAMICS):
            new_state, _ = lax.scan(ll_body, state, None, length=hl_rel_freq)

        # --- Per-scenario NaN quarantine (sticky). ---
        with phases.scope(phases.FALLBACK):
            quar_new = quar | ~tree_all_finite(new_state)
            new_state = tree_where(quar_new, state, new_state)
            cs_next = tree_where(quar_new, cs, cs_next)
            prev_next = jnp.where(quar_new, prev_f, f_used)

        log = RQPLogStep(
            xl=new_state.xl,
            vl=new_state.vl,
            Rl=new_state.Rl,
            wl=new_state.wl,
            R=new_state.R,
            w=new_state.w,
            f_des=f_used,
            x_err=jnp.linalg.norm(x_ref - new_state.xl),
            v_err=jnp.linalg.norm(v_ref - new_state.vl),
            iters=stats.iters,
            solve_res=stats.solve_res,
            collision=stats.collision,
            min_env_dist=stats.min_env_dist,
            fallback_rung=stats.fallback_rung,
            quarantined=quar_new,
        )
        if tel_on:
            with phases.scope(phases.TELEMETRY):
                tel = telemetry_mod.update(
                    telemetry, tel, stats, quarantined=quar_new
                )
            return (new_state, cs_next, prev_next, quar_new, tel), log
        return (new_state, cs_next, prev_next, quar_new), log

    steps = jnp.arange(n_hl_steps)
    if not (isinstance(step_offset, int) and step_offset == 0):
        steps = steps + step_offset
    carry, logs = lax.scan(hl_body, carry0, steps)
    if return_carry:
        return carry, logs
    if tel_on:
        state, cs, _, _, tel = carry
        return state, cs, logs, tel
    state, cs, _, _ = carry
    return state, cs, logs


def jit_resilient_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    *,
    n_hl_steps: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable | None = None,
    faults: faults_mod.FaultSchedule | None = None,
    donate: bool = True,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Donation-clean jitted :func:`resilient_rollout` (the fault-aware twin
    of ``harness.rollout.jit_rollout``): ``run(state0, ctrl_state0)`` with
    both carries donated. Note the ``prepare_ctrl_state`` seeding happens
    INSIDE the jitted program, so the ctrl-state argument is always the
    nominal pytree — callers chain ``state, cs, logs = run(state, cs)``
    without tracking the resilience-only carry fields. With telemetry
    active the run returns a fourth value (the final accumulator)."""
    def run(state0, ctrl_state0):
        return resilient_rollout(
            hl_step, ll_control, params, state0, ctrl_state0,
            n_hl_steps, hl_rel_freq, dt, acc_des_fn, faults,
            telemetry=telemetry,
        )

    return jax.jit(run, donate_argnums=(0, 1) if donate else ())


def make_chunked_resilient_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    *,
    n_hl_steps: int,
    n_chunks: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable,
    faults: faults_mod.FaultSchedule | None = None,
    donate: bool = False,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Fault-aware twin of ``harness.rollout.make_chunked_rollout``: the
    resilient rollout split into ``n_chunks`` chunks reusing ONE compiled
    chunk ``chunk(carry, i0) -> (carry, logs)`` whose carry is the FULL
    :func:`init_resilient_carry` tuple — hold force and sticky quarantine
    flag included, so a chunk-boundary snapshot resumes the fallback ladder
    and a quarantined Monte-Carlo lane bit-exactly (tests/test_recovery.py
    asserts identity against an uninterrupted run, quarantined lane and
    all). The fault schedule and sensor-noise RNG index by GLOBAL step via
    ``step_offset``, so chunking never re-draws or shifts faults.
    ``donate`` defaults OFF for the same bit-reproducibility reason as
    ``make_chunked_rollout`` (see its docstring).

    Returns ``run(state0, ctrl_state0, on_boundary=None) -> (final_state,
    final_ctrl_state, logs)`` with ``run.chunk_jit`` / ``run.n_chunks`` /
    ``run.chunk_len`` / ``run.init_carry`` exposed for
    ``resilience.recovery``."""
    from tpu_aerial_transport.harness.rollout import (
        make_chunk_driver,
        validate_chunking,
    )

    chunk_len = validate_chunking(n_hl_steps, n_chunks, acc_des_fn)

    def chunk(carry, i0):
        return resilient_rollout(
            hl_step, ll_control, params, None, None, chunk_len,
            hl_rel_freq, dt, acc_des_fn, faults,
            carry0=carry, step_offset=i0, return_carry=True,
            telemetry=telemetry,
        )

    return make_chunk_driver(
        chunk, n_chunks=n_chunks, chunk_len=chunk_len,
        init_carry=lambda state0, ctrl_state0: init_resilient_carry(
            hl_step, params, state0, ctrl_state0, faults, telemetry
        ),
        unpack=lambda carry: (carry[0], carry[1]), donate=donate,
    )
