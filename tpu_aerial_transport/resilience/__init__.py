"""Fault-injection and graceful-degradation layer.

The reference (and the reproduction until this subsystem) only ever simulates
the nominal case: every agent healthy, every consensus message delivered,
every solve converging. This package turns "a team carries the payload" into
a claim that survives stress:

- :mod:`faults` — :class:`FaultSchedule`, a scan/vmap/jit-compatible pytree
  describing per-HL-step, per-agent faults (actuator degradation, full agent
  loss, sensor noise, consensus-message dropout/staleness), evaluated to a
  per-step :class:`FaultStep` health mask.
- :mod:`quarantine` — per-scenario NaN quarantine for Monte-Carlo batches:
  a diverging scenario is frozen and flagged instead of poisoning batched
  statistics.
- :mod:`rollout` — :func:`resilient_rollout`, the harness rollout threaded
  with fault evaluation, the explicit fallback ladder (warm solve -> retry ->
  hold previous force -> equilibrium forces), and the quarantine, plus
  ``make_cadmm_hl_step`` / ``make_dd_hl_step`` controller adapters that
  recompute the equilibrium force distribution from the healthy-agent mask
  each step.
- :mod:`backend` — the backend guard: structured :class:`backend.BackendError`
  taxonomy, per-backend :class:`backend.CircuitBreaker` (closed → open →
  half-open with exponential backoff + jitter), deadline watchdogs for
  in-process dispatch and subprocess-isolated cold init, the
  ``TAT_BACKEND_FAULTS`` fault-injection hook, and
  :class:`backend.BackendGuard` — mid-run graceful degradation onto the
  tagged XLA-CPU rung for bench cells and recovery chunks.
- :mod:`recovery` — preemption-safe checkpointing and crash recovery:
  chunk-completion journal, :func:`recovery.run_chunks` /
  :func:`recovery.resume_run` over the one-compiled-chunk contract of
  ``harness.rollout.make_chunked_rollout`` /
  :func:`rollout.make_chunked_resilient_rollout`, atomic versioned
  snapshots (``harness.checkpoint``), and :class:`recovery.GracefulInterrupt`
  for SIGTERM/SIGINT-graceful shutdown.
"""

from tpu_aerial_transport.resilience.backend import (  # noqa: F401
    RUNG_CPU,
    RUNG_ONCHIP,
    RUNG_ONCHIP_UNPADDED,
    BackendError,
    BackendGuard,
    BackoffPolicy,
    CircuitBreaker,
    FaultInjector,
    call_with_deadline,
    classify,
    probe_subprocess,
)
from tpu_aerial_transport.resilience.faults import (  # noqa: F401
    NEVER,
    FaultSchedule,
    FaultStep,
    apply_sensor_noise,
    fault_step,
    make_schedule,
    no_faults,
)
from tpu_aerial_transport.resilience.quarantine import (  # noqa: F401
    tree_all_finite,
    tree_where,
)
from tpu_aerial_transport.resilience.recovery import (  # noqa: F401
    GracefulInterrupt,
    RunJournal,
    RunPlan,
    RunResult,
    read_plan,
    resume_run,
    run_chunks,
)
from tpu_aerial_transport.resilience.rollout import (  # noqa: F401
    RUNG_CLEAN,
    RUNG_EQUILIBRIUM,
    RUNG_HOLD,
    RUNG_RETRY,
    init_resilient_carry,
    make_cadmm_hl_step,
    make_chunked_resilient_rollout,
    make_dd_hl_step,
    resilient_rollout,
)
