"""Version-compat shims over the small jax-ecosystem API surface whose
location or keyword names moved across the releases this package supports.

``shard_map``: promoted from ``jax.experimental.shard_map.shard_map`` to
``jax.shard_map`` (and its replication-check kwarg renamed
``check_rep`` -> ``check_vma``) in newer jax. On the installed 0.4.37
only the experimental path and the old kwarg exist. All package/test code
goes through :func:`shard_map` below, which accepts the NEW spelling
(``check_vma``) and translates as needed — so call sites are written
against the modern API and keep working when jax upgrades.

``pytree_io``: orbax's ``PyTreeCheckpointer`` is deprecated in current
orbax in favor of ``StandardCheckpointer`` (and before this shim,
``harness.checkpoint.save_state`` hard-ImportError'd on boxes without
orbax at all). :func:`pytree_io` resolves, in order: modern
``StandardCheckpointer`` -> legacy ``PyTreeCheckpointer`` -> a
dependency-free npz fallback, and returns one ``(save, restore)`` pair so
callers never touch orbax's moving API directly. The orbax pin lives in
the ``checkpoint`` extra of pyproject.toml.
"""

from __future__ import annotations

import inspect
import os

import jax


def _resolve_shard_map():
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore
    return fn


_SHARD_MAP = _resolve_shard_map()
_SHARD_MAP_PARAMS = frozenset(inspect.signature(_SHARD_MAP).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern signature on every supported jax.

    ``check_vma`` maps to the installed implementation's replication-check
    kwarg (``check_vma`` on new jax, ``check_rep`` on <= 0.4.x); ``None``
    leaves the implementation default. Usable exactly like the real one,
    including ``functools.partial(compat.shard_map, mesh=..., ...)`` as a
    decorator.
    """
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def _import_orbax():
    """Import hook for :func:`pytree_io`, separated so tests (and boxes
    that want the npz path deliberately) can monkeypatch orbax away."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return None
    return ocp


def pytree_io():
    """Resolve the installed pytree-checkpoint backend.

    Returns ``(save, restore, backend_name)`` where ``save(path, state)``
    persists an arbitrary pytree and ``restore(path, template)`` loads it
    back with ``template`` supplying structure/dtypes. Backends, in
    preference order:

    - ``"orbax-standard"``: ``ocp.StandardCheckpointer`` (the maintained
      API; ``PyTreeCheckpointer`` is deprecated in current orbax);
    - ``"orbax-pytree"``: legacy ``PyTreeCheckpointer`` on old orbax;
    - ``"npz"``: flat-leaf ``np.savez`` fallback when orbax is absent —
      a plain file at ``path + ".npz"`` (orbax writes directories), so the
      two backends never shadow each other's artifacts.
    """
    import numpy as np

    ocp = _import_orbax()
    if ocp is not None and hasattr(ocp, "StandardCheckpointer"):
        ckptr = ocp.StandardCheckpointer()

        def save(path, state):
            ckptr.save(os.path.abspath(path), state, force=True)
            # Async checkpointers return before the write is durable.
            getattr(ckptr, "wait_until_finished", lambda: None)()

        def restore(path, template):
            return ckptr.restore(os.path.abspath(path), template)

        return save, restore, "orbax-standard"
    if ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()

        def save(path, state):
            ckptr.save(os.path.abspath(path), state, force=True)

        def restore(path, template):
            return ckptr.restore(os.path.abspath(path), item=template)

        return save, restore, "orbax-pytree"

    def save(path, state):
        leaves = jax.tree.leaves(state)
        arrs = {f"leaf_{i:06d}": np.asarray(l) for i, l in enumerate(leaves)}
        tmp = path + f".tmp.{os.getpid()}.npz"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrs)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path + ".npz")

    def restore(path, template):
        with np.load(path + ".npz", allow_pickle=False) as raw:
            leaves = [raw[f"leaf_{i:06d}"]
                      for i in range(len(raw.files))]
        treedef = jax.tree.structure(template)
        return jax.tree.unflatten(treedef, leaves)

    return save, restore, "npz"
