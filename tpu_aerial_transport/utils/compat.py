"""Version-compat shims over the small jax API surface whose location or
keyword names moved across the jax releases this package supports.

``shard_map``: promoted from ``jax.experimental.shard_map.shard_map`` to
``jax.shard_map`` (and its replication-check kwarg renamed
``check_rep`` -> ``check_vma``) in newer jax. On the installed 0.4.37
only the experimental path and the old kwarg exist. All package/test code
goes through :func:`shard_map` below, which accepts the NEW spelling
(``check_vma``) and translates as needed — so call sites are written
against the modern API and keep working when jax upgrades.
"""

from __future__ import annotations

import inspect

import jax


def _resolve_shard_map():
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore
    return fn


_SHARD_MAP = _resolve_shard_map()
_SHARD_MAP_PARAMS = frozenset(inspect.signature(_SHARD_MAP).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern signature on every supported jax.

    ``check_vma`` maps to the installed implementation's replication-check
    kwarg (``check_vma`` on new jax, ``check_rep`` on <= 0.4.x); ``None``
    leaves the implementation default. Usable exactly like the real one,
    including ``functools.partial(compat.shard_map, mesh=..., ...)`` as a
    decorator.
    """
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
