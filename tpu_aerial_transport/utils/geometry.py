"""Host-side geometry helpers (reference ``utils/geometry_utils.py``).

These run at setup/visualization time only — never inside the compiled path — so they
use numpy/scipy directly.
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import ConvexHull


def faces_from_vertex_rep(vertices: np.ndarray) -> np.ndarray:
    """Convex-hull faces (index triplets) from a (m, 3) vertex array."""
    assert vertices.ndim == 2 and vertices.shape[1] == 3
    hull = ConvexHull(vertices)
    return hull.simplices


def mesh_from_halfspace_rep(A: np.ndarray, b: np.ndarray):
    """H-rep ``{x : A x <= b}`` -> (vertices, faces).

    The reference uses the ``polytope`` package for vertex enumeration; that package
    is not available here, so we enumerate vertices directly: every intersection of 3
    hyperplanes that satisfies all inequalities is a candidate vertex (fine for the
    small polytopes this is used for — tests and payload meshes).
    """
    assert A.ndim == 2 and A.shape[1] == 3
    m = A.shape[0]
    verts = []
    for i in range(m):
        for j in range(i + 1, m):
            for k in range(j + 1, m):
                M = A[[i, j, k]]
                if abs(np.linalg.det(M)) < 1e-10:
                    continue
                x = np.linalg.solve(M, b[[i, j, k]])
                if np.all(A @ x <= b + 1e-8):
                    verts.append(x)
    if not verts:
        raise ValueError("empty polytope")
    verts = np.unique(np.round(np.array(verts), 10), axis=0)
    return verts, faces_from_vertex_rep(verts)
