"""Backend-selection helper for entry-point scripts.

The axon site hook (PYTHONPATH=/root/.axon_site) rewrites ``jax_platforms``
to ``"axon,cpu"`` at interpreter startup, OVERRIDING the ``JAX_PLATFORMS``
env var — so when the TPU tunnel is down, a script that honors only the env
var hangs forever in backend init even under ``JAX_PLATFORMS=cpu``.
``bench.py`` and the test conftest counter this with a config-level
override; every example entry point calls :func:`honor_jax_platforms_env`
for the same guarantee. Must run before first device use (importing jax is
safe — backend init is lazy)."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-apply the ``JAX_PLATFORMS`` env var at the jax.config level in this
    process, so an explicit platform request always wins over site hooks."""
    envp = os.environ.get("JAX_PLATFORMS")
    if envp:
        import jax

        jax.config.update("jax_platforms", envp)
