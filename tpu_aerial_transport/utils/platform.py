"""Backend-selection helper for entry-point scripts.

The axon site hook (PYTHONPATH=/root/.axon_site) rewrites ``jax_platforms``
to ``"axon,cpu"`` at interpreter startup, OVERRIDING the ``JAX_PLATFORMS``
env var — so when the TPU tunnel is down, a script that honors only the env
var hangs forever in backend init even under ``JAX_PLATFORMS=cpu``.
``bench.py`` and the test conftest counter this with a config-level
override; every example entry point calls :func:`honor_jax_platforms_env`
for the same guarantee. Must run before first device use (importing jax is
safe — backend init is lazy)."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Re-apply the ``JAX_PLATFORMS`` env var at the jax.config level in this
    process, so an explicit platform request always wins over site hooks."""
    envp = os.environ.get("JAX_PLATFORMS")
    if envp:
        import jax

        jax.config.update("jax_platforms", envp)


# The persistent-cache knob shared by the test conftest, bench.py, the
# bench_retry child processes, and the AOT serve driver: override the
# location with TAT_XLA_CACHE_DIR, disable with TAT_XLA_CACHE_DIR="".
XLA_CACHE_ENV = "TAT_XLA_CACHE_DIR"

# The virtual-device knob (mirrors the TAT_XLA_CACHE_DIR pattern): ONE
# env var naming how many virtual CPU devices a process should fake via
# XLA's --xla_force_host_platform_device_count. The test conftest, the
# ci_check forced-mesh contract runs, and the pods localhost harness
# (tools/pods_local.py) all route through apply_virtual_devices() instead
# of hand-rolling XLA_FLAGS strings — hand-rolled copies drifted (4 here,
# 8 there) and a mismatch surfaces as silently-skipped min_devices tests.
VIRTUAL_DEVICES_ENV = "TAT_VIRTUAL_DEVICES"

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def virtual_device_count(default: int | None = None) -> int | None:
    """The requested virtual-device count: :data:`VIRTUAL_DEVICES_ENV` when
    set (must be a positive int), else ``default``."""
    raw = os.environ.get(VIRTUAL_DEVICES_ENV, "")
    if not raw:
        return default
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"{VIRTUAL_DEVICES_ENV}={raw!r} is not an integer"
        ) from None
    if n < 1:
        raise ValueError(f"{VIRTUAL_DEVICES_ENV}={raw!r} must be >= 1")
    return n


def apply_virtual_devices(default: int | None = None) -> int | None:
    """Request ``virtual_device_count(default)`` virtual CPU devices by
    appending :data:`_FORCE_FLAG` to ``XLA_FLAGS`` — unless XLA_FLAGS
    already pins a count (an ambient pin wins, same contract the test
    conftest always had: tests/conftest.py then SKIPS mesh tests with an
    actionable message instead of dying in ``make_mesh``). Must run
    BEFORE the first jax backend init to take effect. Returns the count
    requested here, or None when nothing was applied."""
    n = virtual_device_count(default)
    flags = os.environ.get("XLA_FLAGS", "")
    if n is None or _FORCE_FLAG in flags:
        return None
    os.environ["XLA_FLAGS"] = f"{flags} {_FORCE_FLAG}={n}".strip()
    return n


def default_cache_dir() -> str:
    """Repo-local default (gitignored): ``<repo>/.cache/xla``."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(repo, ".cache", "xla")


def enable_persistent_cache(cache_dir: str | None = None,
                            min_compile_secs: float = 1.0) -> str | None:
    """Point jax's persistent XLA compilation cache at ``cache_dir``
    (default: :data:`XLA_CACHE_ENV`, falling back to
    :func:`default_cache_dir`). The suite and the bench are COMPILE-bound
    and programs are identical run-to-run, so warm processes skip the XLA
    backend compile (keyed by program HLO + compile options + jax/XLA
    version — config changes miss cleanly). Returns the directory in use,
    or None when disabled (``TAT_XLA_CACHE_DIR=""``). Must run before the
    first compilation to cover it."""
    if cache_dir is None:
        cache_dir = os.environ.get(XLA_CACHE_ENV, default_cache_dir())
    if not cache_dir:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Only persist programs worth the disk round-trip.
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return cache_dir
