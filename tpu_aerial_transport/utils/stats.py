"""Aggregate statistics helpers (reference ``utils/math_utils.py:63-73``)."""

from __future__ import annotations

import jax.numpy as jnp


def compute_aggregate_statistics(a, axis: int = 0, valid=None):
    """Return ``(min, max, avg, std)`` of ``a`` along ``axis``.

    ``valid``: optional boolean mask of length ``a.shape[axis]`` selecting
    the slices that enter the statistics — the NaN-quarantine hook: pass
    ``~logs.quarantined[-1]`` (per-scenario) so a diverged Monte-Carlo lane
    is excluded instead of poisoning every aggregate with NaN. With no
    valid slice the min/max identities are ``+inf``/``-inf`` and avg/std
    are 0. ``valid=None`` is the historical unmasked path, bit-identical.
    """
    a = jnp.asarray(a)
    if valid is None:
        return (
            jnp.min(a, axis=axis),
            jnp.max(a, axis=axis),
            jnp.mean(a, axis=axis),
            jnp.std(a, axis=axis),
        )
    valid = jnp.asarray(valid, bool)
    shape = [1] * a.ndim
    shape[axis] = valid.shape[0]
    m = valid.reshape(shape)
    w = m.astype(a.dtype)
    cnt = jnp.maximum(jnp.sum(w, axis=axis), 1.0)
    avg = jnp.sum(jnp.where(m, a, 0.0), axis=axis) / cnt
    var = jnp.sum(
        jnp.where(m, (a - jnp.expand_dims(avg, axis)) ** 2, 0.0), axis=axis
    ) / cnt
    return (
        jnp.min(jnp.where(m, a, jnp.inf), axis=axis),
        jnp.max(jnp.where(m, a, -jnp.inf), axis=axis),
        avg,
        jnp.sqrt(var),
    )
