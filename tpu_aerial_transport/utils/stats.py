"""Aggregate statistics helpers (reference ``utils/math_utils.py:63-73``)."""

from __future__ import annotations

import jax.numpy as jnp


def compute_aggregate_statistics(a, axis: int = 0):
    """Return ``(min, max, avg, std)`` of ``a`` along ``axis``."""
    a = jnp.asarray(a)
    return (
        jnp.min(a, axis=axis),
        jnp.max(a, axis=axis),
        jnp.mean(a, axis=axis),
        jnp.std(a, axis=axis),
    )
