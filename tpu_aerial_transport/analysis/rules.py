"""Tier-A jaxlint rules: pure-AST jit-safety checks.

HARD CONSTRAINT: this module must import nothing beyond the stdlib — no
jax, no numpy, no package modules (tools/jaxlint.py loads it by file path
so the lint runs on machines with no accelerator stack at all). The no-jax
property is asserted by tests/test_jaxlint.py in a subprocess.

Every rule operates on one :class:`ModuleContext` (a parsed module plus
the traced-context inference described below) and yields
:class:`Finding` records. Rule functions are registered in :data:`RULES`;
``tools/jaxlint.py --list-rules`` prints :data:`RULE_DOCS`.

**Traced-context inference.** A purely syntactic over/under-approximation
of "this code runs under a jax trace":

- seeds: functions decorated with jit/vmap/grad/shard_map/etc. (including
  through ``partial``), functions passed as the callable argument to
  ``jax.jit`` / ``lax.scan`` / ``lax.while_loop`` / ``lax.fori_loop`` /
  ``lax.cond`` / ``lax.switch`` / ``jax.vmap`` / ``shard_map`` call sites,
  and functions named in the per-module entrypoint table
  (``analysis.entrypoints.TRACED_FUNCTIONS`` — the public controller /
  solver / rollout surface that callers jit);
- propagation: any module-level function called (by bare name, directly or
  as an attribute) from a traced function's body becomes traced, to a
  fixpoint. Cross-module propagation is intentionally NOT performed —
  instead each module's hot surface is named in the entrypoint table (the
  Tier-B registry-coverage test keeps that table honest).

**Host-region exemption.** Code inside an ``if`` whose test mentions
``Tracer`` (the ``isinstance(x, jax.core.Tracer)`` guard idiom) is treated
as host-only and exempt from every rule.

**Suppression.** ``# jaxlint: disable=JL003`` on a line suppresses those
rules for that line; ``# jaxlint: disable=all`` suppresses every rule;
``# jaxlint: skip-file`` anywhere in the first 10 lines skips the module.
Suppressions are the allowlist mechanism — each one should carry a short
justification comment (see README "Static analysis / jaxlint").
"""

from __future__ import annotations

import ast
import dataclasses
import re


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``severity`` is "error" (fails CI) or "warn"."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    context: str = ""  # enclosing function, dotted.
    severity: str = "error"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"({self.severity}){ctx} {self.message}"
        )


RULE_DOCS = {
    "JL001": (
        "host-float-cast: float()/int()/bool()/complex() applied to a "
        "jnp/lax expression in traced code forces a device->host sync "
        "(ConcretizationTypeError under jit)."
    ),
    "JL002": (
        "host-item-sync: .item()/.tolist()/.block_until_ready() in traced "
        "code is a host round-trip; return arrays and materialize outside "
        "the jitted region."
    ),
    "JL003": (
        "numpy-in-trace: calling numpy (np.*) inside traced code runs on "
        "the host per trace and concretizes tracers; use jnp, or guard "
        "with an isinstance(..., Tracer) host-region check."
    ),
    "JL004": (
        "f64-promotion: float64 dtype reachable from jnp code (np.float64 "
        "/ jnp.float64 / dtype='float64' / dtype=float) silently widens "
        "f32 graphs when x64 is enabled and adds convert_element_type "
        "churn when it is not."
    ),
    "JL005": (
        "traced-branch: Python if/while on a jnp/lax expression in traced "
        "code concretizes the value (crash under jit) or silently bakes "
        "one branch into the trace; use lax.cond/jnp.where."
    ),
    "JL006": (
        "asarray-in-loop-body: jnp.asarray/jnp.array inside a "
        "scan/while/fori body re-stages host data every iteration and "
        "defeats constant folding; hoist the conversion out of the loop."
    ),
    "JL007": (
        "assert-on-traced: bare assert on a jnp/lax expression is a "
        "no-op or a crash under jit; use checkify or move the check to "
        "host code."
    ),
    "JL008": (
        "static-argnames-unknown: static_argnames/static_argnums "
        "referencing parameters the jitted function does not have — the "
        "declaration silently does nothing (or raises at call time)."
    ),
    "JL009": (
        "static-argnames-missing: a jitted function has a str-defaulted "
        "parameter not declared static; strings are unhashable-as-tracers "
        "and will fail (or retrace) when passed."
    ),
    "JL010": (
        "callback-in-trace: pure_callback/io_callback/host_callback in "
        "traced code inserts a host round-trip into the hot path."
    ),
    "JL011": (
        "print-in-trace: print() in traced code fires at trace time only "
        "(silent after compilation); use jax.debug.print if the value is "
        "needed, or log outside the jitted region."
    ),
}

# Transform names whose callable argument(s) run under trace. Maps the
# callee's terminal name to the positional indices of callable args.
_TRANSFORM_CALLARGS = {
    "jit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "custom_vmap": (0,),
    "named_call": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2, 3),
    "switch": (1,),
    "associative_scan": (0,),
}

_TRACED_DECORATOR_NAMES = frozenset(
    {"jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint",
     "remat", "shard_map", "custom_vmap"}
)

_PRAGMA_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SKIP_FILE_RE = re.compile(r"#\s*jaxlint:\s*skip-file")

_HOST_CASTS = frozenset({"float", "int", "bool", "complex"})
_HOST_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})
_CALLBACK_NAMES = frozenset(
    {"pure_callback", "io_callback", "host_callback", "call_tf"}
)
_LOOP_TRANSFORMS = frozenset({"scan", "while_loop", "fori_loop"})
_STAGING_CALLS = frozenset({"asarray", "array"})
# Calls that inspect trace-time METADATA (dtypes, shapes, tree structure)
# — concrete under tracing, so branching/asserting on them is host-safe.
_TRACE_SAFE_CALLS = frozenset(
    {"issubdtype", "isdtype", "result_type", "promote_types", "dtype",
     "ndim", "shape", "size", "len", "isinstance", "hasattr",
     "tree_structure", "treedef_is_leaf"}
)


def _terminal_name(node: ast.expr) -> str | None:
    """``jax.lax.while_loop`` -> "while_loop"; ``scan`` -> "scan"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node: ast.expr) -> str | None:
    """``jnp.linalg.norm`` -> "jnp"; ``np.asarray`` -> "np"."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.expr) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _iter_names(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _tracer_guard_host_span(node: ast.If) -> tuple[int, int] | None:
    """Line span of the HOST branch of an isinstance-Tracer guard, if this
    `if` is one: `not isinstance(x, ..Tracer)` -> the body is host-only;
    `isinstance(x, ..Tracer)` -> the else branch is. Anything fancier
    (compound tests) gets no exemption — conservatively traced."""
    test = node.test
    negated = False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        test = test.operand
        negated = True
    if not (isinstance(test, ast.Call)
            and _terminal_name(test.func) == "isinstance"
            and any(n == "Tracer" for n in _iter_names(test))):
        return None
    stmts = node.body if negated else node.orelse
    if not stmts:
        return None
    return (stmts[0].lineno, stmts[-1].end_lineno or stmts[-1].lineno)


class ModuleContext:
    """Parsed module + alias tables + traced-context inference (class
    docstring of this module). One instance per linted file."""

    def __init__(self, path: str, source: str,
                 entry_names: frozenset[str] = frozenset()):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.skip_file = any(
            _SKIP_FILE_RE.search(ln) for ln in self.lines[:10]
        )
        self.entry_names = entry_names

        # Per-line suppressed rule ids ("all" suppresses everything).
        self.suppressed: dict[int, frozenset[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(ln)
            if m:
                ids = frozenset(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
                self.suppressed[i] = ids

        # Alias tables (module-wide, including function-local imports).
        self.np_aliases: set[str] = set()
        self.jnp_aliases: set[str] = set()
        self.lax_aliases: set[str] = set()
        self.jax_aliases: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_aliases.add(a.asname or "numpy")
                    elif a.name == "jax.numpy":
                        self.jnp_aliases.add(a.asname or "jax")
                    elif a.name == "jax.lax":
                        self.lax_aliases.add(a.asname or "jax")
                    elif a.name == "jax":
                        self.jax_aliases.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp_aliases.add(a.asname or "numpy")
                        elif a.name == "lax":
                            self.lax_aliases.add(a.asname or "lax")
                elif node.module == "numpy":
                    pass  # from numpy import x — rare; not tracked.

        # Parent / enclosing-function annotation.
        self.func_of: dict[ast.AST, ast.AST | None] = {}
        self.parent: dict[ast.AST, ast.AST] = {}
        self.functions: list[ast.AST] = []

        def annotate(node, func):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
                if isinstance(child, _FUNC_NODES):
                    self.functions.append(child)
                    self.func_of[child] = func
                    annotate(child, child)
                else:
                    self.func_of[child] = func
                    annotate(child, func)

        annotate(self.tree, None)

        # Module-level function table (top-level defs only — propagation
        # targets). Nested defs are reached through their parents.
        self.top_funcs: dict[str, ast.AST] = {
            n.name: n
            for n in self.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # ALL named defs (any nesting), for resolving callables passed to
        # transforms — e.g. a scan body defined inside its caller. Name
        # collisions resolve to every candidate (over-approximation).
        self.funcs_by_name: dict[str, list[ast.AST]] = {}
        for fn in self.functions:
            if not isinstance(fn, ast.Lambda):
                self.funcs_by_name.setdefault(fn.name, []).append(fn)

        # Host-only regions: the branch of a Tracer-isinstance guard whose
        # test PROVES host context — the body of
        # `if not isinstance(x, Tracer):` or the else of
        # `if isinstance(x, Tracer):`. Only these two canonical shapes
        # are exempt; the traced branch of either guard is NOT (a host
        # sync inside `if isinstance(x, Tracer): ...` is a real bug).
        self.host_ranges: list[tuple[int, int]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.If):
                span = _tracer_guard_host_span(node)
                if span is not None:
                    self.host_ranges.append(span)

        self._infer_traced()

    # --- traced-context inference -------------------------------------
    def _infer_traced(self) -> None:
        traced: set[ast.AST] = set()
        self.loop_bodies: set[ast.AST] = set()
        # Functions passed TO a callback primitive run on the host by
        # definition — they must not inherit the enclosing traced context.
        self.host_funcs: set[ast.AST] = set()
        # jit call sites for JL008/JL009: (call_node, fn_node_or_None,
        # decorated_def_or_None).
        self.jit_sites: list[tuple[ast.Call, ast.AST | None]] = []

        def resolve_all(arg: ast.expr) -> list[ast.AST]:
            """A callable argument -> candidate function nodes (any
            nesting level; name collisions yield every candidate)."""
            if isinstance(arg, ast.Lambda):
                return [arg]
            if isinstance(arg, ast.Name):
                return self.funcs_by_name.get(arg.id, [])
            if isinstance(arg, ast.Call):
                # partial(f, ...) / jax.jit(f) nested in another transform.
                tname = _terminal_name(arg.func)
                if tname == "partial" and arg.args:
                    return resolve_all(arg.args[0])
                if tname in _TRANSFORM_CALLARGS and arg.args:
                    return resolve_all(arg.args[0])
            return []

        def resolve(arg: ast.expr) -> ast.AST | None:
            cands = resolve_all(arg)
            return cands[0] if len(cands) == 1 else None

        # Seeds from decorators.
        for fn in self.functions:
            if isinstance(fn, ast.Lambda):
                continue
            for dec in fn.decorator_list:
                names = set(_iter_names(dec))
                if names & _TRACED_DECORATOR_NAMES:
                    traced.add(fn)
                if "jit" in names:
                    call = dec if isinstance(dec, ast.Call) else None
                    self.jit_sites.append((call, fn))

        # Seeds from entrypoint table.
        for name in self.entry_names:
            fn = self.top_funcs.get(name)
            if fn is not None:
                traced.add(fn)

        # Seeds from transform call sites.
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tname = _terminal_name(node.func)
            if tname in _CALLBACK_NAMES and node.args:
                target = resolve(node.args[0])
                if target is not None:
                    self.host_funcs.add(target)
            if tname not in _TRANSFORM_CALLARGS:
                continue
            for idx in _TRANSFORM_CALLARGS[tname]:
                if idx < len(node.args):
                    for target in resolve_all(node.args[idx]):
                        traced.add(target)
                        if tname in _LOOP_TRANSFORMS and (
                            tname != "while_loop" or idx == 1
                        ):
                            self.loop_bodies.add(target)
            if tname == "switch" and len(node.args) > 1 and isinstance(
                node.args[1], (ast.List, ast.Tuple)
            ):
                for el in node.args[1].elts:
                    for target in resolve_all(el):
                        traced.add(target)
            if tname == "jit":
                target = resolve(node.args[0]) if node.args else None
                self.jit_sites.append((node, target))

        # Propagation to a fixpoint: bare-name calls from traced bodies.
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = _terminal_name(node.func)
                    target = self.top_funcs.get(callee)
                    if target is not None and target not in traced:
                        traced.add(target)
                        changed = True
                # Nested defs inherit their parent's traced-ness (a
                # closure defined inside a traced function runs under
                # the same trace when called).
            for fn in self.functions:
                if fn in traced:
                    continue
                outer = self.func_of.get(fn)
                if outer is not None and outer in traced:
                    traced.add(fn)
                    changed = True
        self.traced = traced

    # --- helpers used by rules ----------------------------------------
    def in_traced(self, node: ast.AST) -> bool:
        fn = self.func_of.get(node)
        while fn is not None:
            if fn in self.host_funcs:
                return False  # callback body: host by definition.
            if fn in self.traced:
                return True
            fn = self.func_of.get(fn)
        return False

    def in_host_region(self, node: ast.AST) -> bool:
        line = getattr(node, "lineno", None)
        if line is None:
            return False
        return any(lo <= line <= hi for lo, hi in self.host_ranges)

    def enclosing_name(self, node: ast.AST) -> str:
        parts = []
        fn = self.func_of.get(node)
        while fn is not None:
            parts.append(getattr(fn, "name", "<lambda>"))
            fn = self.func_of.get(fn)
        return ".".join(reversed(parts))

    def is_suppressed(self, rule: str, line: int) -> bool:
        ids = self.suppressed.get(line)
        return ids is not None and (rule in ids or "all" in ids)

    def mentions_jnp_call(self, node: ast.AST) -> bool:
        """Does this expression CALL into jnp/lax (not merely read a
        constant attribute like jnp.pi)?"""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee = _terminal_name(sub.func)
                if callee in _TRACE_SAFE_CALLS:
                    continue  # dtype/shape metadata — concrete under trace.
                root = _root_name(sub.func)
                if root in self.jnp_aliases or root in self.lax_aliases:
                    return True
                if root in self.jax_aliases:
                    return True
                target = self.top_funcs.get(callee)
                if target is not None and target in self.traced:
                    return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str,
                severity: str = "error") -> Finding | None:
        line = getattr(node, "lineno", 0)
        if self.is_suppressed(rule, line) or self.in_host_region(node):
            return None
        return Finding(
            rule=rule, path=self.path, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            context=self.enclosing_name(node), severity=severity,
        )


# ----------------------------------------------------------------------
# Rules. Each takes a ModuleContext and yields Findings.
# ----------------------------------------------------------------------

def rule_jl001_host_cast(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in _HOST_CASTS and node.args):
            continue
        if not ctx.in_traced(node):
            continue
        if ctx.mentions_jnp_call(node.args[0]):
            f = ctx.finding(
                "JL001", node,
                f"`{node.func.id}()` on a jnp/lax expression forces a "
                "host sync (ConcretizationTypeError under jit)",
            )
            if f:
                yield f


def rule_jl002_host_item(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_ATTRS):
            continue
        if ctx.in_traced(node):
            f = ctx.finding(
                "JL002", node,
                f"`.{node.func.attr}()` in traced code is a device->host "
                "round-trip",
            )
            if f:
                yield f


def rule_jl003_numpy_in_trace(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        root = _root_name(node.func)
        if root not in ctx.np_aliases:
            continue
        if ctx.in_traced(node):
            f = ctx.finding(
                "JL003", node,
                f"numpy call `{_dotted(node.func)}(...)` in traced code "
                "runs on the host and concretizes tracers; use jnp or a "
                "Tracer-guarded host region",
            )
            if f:
                yield f


def rule_jl004_f64(ctx: ModuleContext):
    jnp_roots = ctx.jnp_aliases
    np_roots = ctx.np_aliases

    def call_root(node):
        p = ctx.parent.get(node)
        while p is not None and not isinstance(p, ast.Call):
            p = ctx.parent.get(p)
        if isinstance(p, ast.Call):
            return _root_name(p.func)
        return None

    for node in ast.walk(ctx.tree):
        # jnp.float64 anywhere; np.float64 when traced or inside a jnp call.
        if isinstance(node, ast.Attribute) and node.attr == "float64":
            root = _root_name(node)
            bad = root in jnp_roots or (
                root in np_roots
                and (ctx.in_traced(node) or call_root(node) in jnp_roots)
            )
            if bad:
                f = ctx.finding(
                    "JL004", node,
                    f"`{_dotted(node)}` feeds an f64 dtype into jnp code "
                    "(f32 graphs widen under x64; convert churn otherwise)",
                )
                if f:
                    yield f
        # dtype="float64" / astype("float64") / dtype=float builtin —
        # gated like the attribute branch above: only when traced or fed
        # into a jnp call (host-side numpy f64 is legitimate).
        elif isinstance(node, ast.keyword) and node.arg == "dtype":
            v = node.value
            is_str64 = isinstance(v, ast.Constant) and v.value == "float64"
            is_pyfloat = isinstance(v, ast.Name) and v.id == "float"
            if (is_str64 or is_pyfloat) and (
                ctx.in_traced(node.value) or call_root(v) in jnp_roots
            ):
                f = ctx.finding(
                    "JL004", v,
                    "dtype=%s promotes to float64 under x64"
                    % ("'float64'" if is_str64 else "float (Python)"),
                )
                if f:
                    yield f
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "astype" and node.args):
            a = node.args[0]
            if (isinstance(a, ast.Constant) and a.value == "float64"
                    and (ctx.in_traced(node)
                         or _root_name(node.func) in jnp_roots)):
                f = ctx.finding(
                    "JL004", node, "astype('float64') widens to f64"
                )
                if f:
                    yield f


def rule_jl005_traced_branch(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if not ctx.in_traced(node):
            continue
        if ctx.mentions_jnp_call(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            f = ctx.finding(
                "JL005", node,
                f"Python `{kind}` on a jnp/lax expression in traced code "
                "(concretization crash under jit, or one branch silently "
                "baked in); use lax.cond / jnp.where",
            )
            if f:
                yield f


def rule_jl006_asarray_in_loop(ctx: ModuleContext):
    for body in ctx.loop_bodies:
        for node in ast.walk(body):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STAGING_CALLS):
                continue
            root = _root_name(node.func)
            if root in ctx.jnp_aliases:
                f = ctx.finding(
                    "JL006", node,
                    f"`{_dotted(node.func)}(...)` inside a scan/while/fori "
                    "body re-stages data every iteration; hoist it out of "
                    "the loop",
                )
                if f:
                    yield f


def rule_jl007_assert_on_traced(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assert):
            continue
        if not ctx.in_traced(node):
            continue
        if ctx.mentions_jnp_call(node.test):
            f = ctx.finding(
                "JL007", node,
                "bare `assert` on a jnp/lax expression in traced code is "
                "a trace-time no-op or a concretization crash; use "
                "checkify or a host-side check",
            )
            if f:
                yield f


def _static_decls(call: ast.Call | None):
    """(static_argnames, static_argnums) constants from a jit call node."""
    names: list[str] = []
    nums: list[int] = []
    if call is None:
        return names, nums
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.extend(
                    el.value for el in v.elts
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, str)
                )
        elif kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums.extend(
                    el.value for el in v.elts
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, int)
                )
    return names, nums


def _params_of(fn: ast.AST):
    if isinstance(fn, ast.Lambda):
        a = fn.args
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
    else:
        return None
    pos = [p.arg for p in a.posonlyargs + a.args]
    kwonly = [p.arg for p in a.kwonlyargs]
    return pos, kwonly, a


def rule_jl008_static_unknown(ctx: ModuleContext):
    for call, fn in ctx.jit_sites:
        names, nums = _static_decls(call)
        if fn is None or (not names and not nums):
            continue
        params = _params_of(fn)
        if params is None:
            continue
        pos, kwonly, _ = params
        all_names = set(pos) | set(kwonly)
        node = call if call is not None else fn
        for nm in names:
            if nm not in all_names:
                f = ctx.finding(
                    "JL008", node,
                    f"static_argnames names `{nm}` which is not a "
                    f"parameter of the jitted function",
                )
                if f:
                    yield f
        for i in nums:
            if i >= len(pos):
                f = ctx.finding(
                    "JL008", node,
                    f"static_argnums index {i} out of range for the "
                    f"jitted function ({len(pos)} positional params)",
                )
                if f:
                    yield f


def rule_jl009_static_missing(ctx: ModuleContext):
    for call, fn in ctx.jit_sites:
        if fn is None:
            continue
        params = _params_of(fn)
        if params is None:
            continue
        pos, kwonly, a = params
        names, nums = _static_decls(call)
        static = set(names) | {pos[i] for i in nums if i < len(pos)}
        # str-defaulted params MUST be static: strings cannot be traced.
        defaults = list(a.defaults)
        defaulted = (a.posonlyargs + a.args)[-len(defaults):] if defaults \
            else []
        pairs = list(zip(defaulted, defaults)) + [
            (p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults)
            if d is not None
        ]
        for p, d in pairs:
            if (isinstance(d, ast.Constant) and isinstance(d.value, str)
                    and p.arg not in static):
                node = call if call is not None else fn
                f = ctx.finding(
                    "JL009", node,
                    f"jitted function parameter `{p.arg}` has a str "
                    f"default ({d.value!r}) but is not in static_argnames "
                    "— passing it will fail or mis-cache",
                )
                if f:
                    yield f


def rule_jl010_callback(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tname = _terminal_name(node.func)
        if tname in _CALLBACK_NAMES and ctx.in_traced(node):
            f = ctx.finding(
                "JL010", node,
                f"`{_dotted(node.func)}` inserts a host callback into a "
                "traced hot path",
            )
            if f:
                yield f


def rule_jl011_print(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "print" and ctx.in_traced(node)):
            f = ctx.finding(
                "JL011", node,
                "print() in traced code fires at trace time only; use "
                "jax.debug.print or log outside the jitted region",
                severity="warn",
            )
            if f:
                yield f


RULES = {
    "JL001": rule_jl001_host_cast,
    "JL002": rule_jl002_host_item,
    "JL003": rule_jl003_numpy_in_trace,
    "JL004": rule_jl004_f64,
    "JL005": rule_jl005_traced_branch,
    "JL006": rule_jl006_asarray_in_loop,
    "JL007": rule_jl007_assert_on_traced,
    "JL008": rule_jl008_static_unknown,
    "JL009": rule_jl009_static_missing,
    "JL010": rule_jl010_callback,
    "JL011": rule_jl011_print,
}


def run_rules(ctx: ModuleContext,
              disabled: frozenset[str] = frozenset()) -> list[Finding]:
    if ctx.skip_file:
        return []
    out: list[Finding] = []
    for rule_id, impl in RULES.items():
        if rule_id in disabled:
            continue
        out.extend(impl(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
