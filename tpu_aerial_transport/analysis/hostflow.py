"""Lightweight host-side dataflow helpers for Tier C (hostlint).

Everything here is pure-AST bookkeeping shared by the HL rules in
``hostrules.py``: parent links, module string-constant resolution,
clock-domain tagging (wall vs monotonic), lock-region iteration, the
per-class lock-acquisition graph (HL004's fixpoint), span begin/end
path analysis (HL002), and ``os.environ`` read detection (HL008).

Stdlib-only — the same never-imports-jax discipline as Tier A
(``rules.py``); loadable by file path from ``tools/jaxlint.py`` and
asserted by ``tests/test_hostlint.py`` in a subprocess.
"""

from __future__ import annotations

import ast
import re

# --------------------------------------------------------------- AST --


def attach_parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing_function(node: ast.AST, parents: dict) -> ast.AST | None:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def dotted(node: ast.expr) -> str:
    """``self.tracer.begin`` -> "self.tracer.begin"; best effort."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def terminal(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def module_str_consts(tree: ast.Module) -> dict[str, str]:
    """Module/class-level ``NAME = "literal"`` bindings — the idiom env
    knob names use (``FAULTS_ENV = "TAT_BACKEND_FAULTS"``)."""
    out: dict[str, str] = {}
    scopes = [tree.body] + [
        n.body for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    ]
    for body in scopes:
        for stmt in body:
            targets: list[ast.expr] = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if not (isinstance(value, ast.Constant)
                    and isinstance(value.value, str)):
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    out[t.id] = value.value
    return out


def literal_strings(node: ast.AST):
    """Every string constant anywhere inside an expression tree."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def module_dict_literal(tree: ast.Module, name: str) -> dict | None:
    """``NAME = {...literal...}`` evaluated via ``ast.literal_eval`` —
    how hostlint reads the event-kind vocabulary out of
    ``obs/export.py`` without importing it (export pulls in numpy)."""
    for stmt in tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            target, value = stmt.target, stmt.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == name):
            continue
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None
    return None


# ------------------------------------------------------ clock domains --

_WALL_CALLS = frozenset({"time.time", "time.time_ns"})
_MONO_CALLS = frozenset({
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
})
# Names that live in the monotonic domain BY CONTRACT: every deadline /
# timeout in the host tier is anchored on the queue/guard clock
# (time.monotonic) so restarts and NTP steps cannot fire or starve it.
_DEADLINE_NAME_RE = re.compile(r"deadline|timeout", re.IGNORECASE)


def call_domain(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d in _WALL_CALLS:
            return "wall"
        if d in _MONO_CALLS:
            return "mono"
    return None


def clock_domains(func: ast.AST) -> dict[str, str]:
    """``{var: "wall"|"mono"}`` for simple ``v = time.<clock>()`` (and
    ``v = <tagged> ± x``) assignments inside one function."""
    domains: dict[str, str] = {}
    for _ in range(2):  # one re-pass picks up derived anchors.
        for node in ast.walk(func):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            dom = expr_domain(node.value, domains)
            if dom is not None:
                domains[node.targets[0].id] = dom
    return domains


def expr_domain(node: ast.AST, domains: dict[str, str]) -> str | None:
    """The clock domain of an expression: a tagged call, a tagged
    variable, a deadline/timeout-named value (monotonic by contract),
    or arithmetic over one domain."""
    d = call_domain(node)
    if d is not None:
        return d
    if isinstance(node, ast.Name):
        if node.id in domains:
            return domains[node.id]
        if _DEADLINE_NAME_RE.search(node.id):
            return "mono"
        return None
    if isinstance(node, ast.Attribute):
        if _DEADLINE_NAME_RE.search(node.attr):
            return "mono"
        return None
    if isinstance(node, ast.BinOp):
        left = expr_domain(node.left, domains)
        right = expr_domain(node.right, domains)
        if left and right and left != right:
            return "mixed"
        return left or right
    return None


# ------------------------------------------------------- lock regions --

_LOCK_NAME_RE = re.compile(r"lock|mutex|(^|_)mu$|(^|_)cv$|cond",
                           re.IGNORECASE)


def lock_label(expr: ast.expr) -> str | None:
    """A with-item context expression's lock identity, or None when the
    expression does not look like a lock. ``with self._lock:`` ->
    "self._lock"; ``with lock_for(k):`` -> "lock_for(...)"."""
    target = expr.func if isinstance(expr, ast.Call) else expr
    name = terminal(target)
    if name is not None and _LOCK_NAME_RE.search(name):
        d = dotted(target)
        return d + "(...)" if isinstance(expr, ast.Call) else d
    return None


def iter_lock_withs(tree: ast.AST):
    """Yield ``(with_node, label)`` for every lock-acquiring with."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                label = lock_label(item.context_expr)
                if label is not None:
                    yield node, label


# -------------------------------------------- lock-order (HL004) -------


def _method_index(cls: ast.ClassDef) -> dict[str, ast.AST]:
    return {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _self_calls(func: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(func):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            out.add(node.func.attr)
    return out


def class_lock_graph(cls: ast.ClassDef) -> dict[str, set[str]]:
    """Edges ``held -> acquired-while-held`` over a class's methods,
    with self-method calls propagated to a fixpoint: if ``a()`` holds
    L1 while calling ``self.b()`` and ``b`` (transitively) acquires L2,
    the graph gains L1 -> L2. A cycle means two call paths can take the
    same locks in opposite orders — the classic supervisor/front
    deadlock shape."""
    methods = _method_index(cls)
    # locks each method may acquire, directly or via self calls.
    acquires: dict[str, set[str]] = {
        name: {label for _, label in iter_lock_withs(m)}
        for name, m in methods.items()
    }
    changed = True
    while changed:
        changed = False
        for name, m in methods.items():
            for callee in _self_calls(m):
                extra = acquires.get(callee, set()) - acquires[name]
                if extra:
                    acquires[name] |= extra
                    changed = True

    edges: dict[str, set[str]] = {}
    for name, m in methods.items():
        for with_node, label in iter_lock_withs(m):
            inner: set[str] = set()
            for stmt in with_node.body:
                for _, nested in iter_lock_withs(stmt):
                    inner.add(nested)
                for node in ast.walk(stmt):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"):
                        inner |= acquires.get(node.func.attr, set())
            inner.discard(label)
            if inner:
                edges.setdefault(label, set()).update(inner)
    return edges


def find_lock_cycle(edges: dict[str, set[str]]) -> list[str] | None:
    """One acquisition-order cycle (as a lock-name path), or None."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in edges}
    path: list[str] = []

    def visit(n: str) -> list[str] | None:
        color[n] = GREY
        path.append(n)
        for m in sorted(edges.get(n, ())):
            if color.get(m, WHITE) == GREY:
                return path[path.index(m):] + [m]
            if color.get(m, WHITE) == WHITE and m in edges:
                found = visit(m)
                if found:
                    return found
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(edges):
        if color[n] == WHITE:
            found = visit(n)
            if found:
                return found
    return None


# ------------------------------------------------- span paths (HL002) --

_TRACERISH_RE = re.compile(r"trace", re.IGNORECASE)


def span_begins(func: ast.AST):
    """Yield ``(assign_node, var)`` for ``v = <tracer-ish>.begin(...)``
    where the target is a plain local name. Attribute/subscript targets
    (``self._spans[rid] = ...``) are cross-method handoffs whose
    lifecycle HL002 cannot see — they are skipped, like escapes."""
    for node in ast.walk(func):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "begin"
                and _TRACERISH_RE.search(dotted(call.func.value))):
            continue
        yield node, node.targets[0].id


def _reads_var(node: ast.AST, var: str) -> bool:
    return any(isinstance(s, ast.Name) and s.id == var
               for s in ast.walk(node))


def var_escapes(func: ast.AST, var: str, begin_assign: ast.AST) -> bool:
    """Does ``var`` leave this function's span lifecycle — stored on an
    attribute/subscript, returned/yielded, aliased, or passed to a call
    that is not ``.end(...)``? Escaped spans are someone else's contract."""
    for node in ast.walk(func):
        if node is begin_assign:
            continue
        if (isinstance(node, ast.Assign)
                and not isinstance(node.value, ast.Call)
                and _reads_var(node.value, var)):
            return True  # alias or handoff store (call values are
            # judged by the Call branch below — a child begin reading
            # the span as parent= is a reference, not a handoff).
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if (node.value is not None
                    and not isinstance(node.value, ast.Call)
                    and _reads_var(node.value, var)):
                return True
        if isinstance(node, ast.Call):
            t = terminal(node.func)
            if t in ("end", "instant", "begin"):
                continue
            # parent/trace_parent keywords link a child's span to this
            # one without transferring its lifecycle.
            args = list(node.args) + [
                k.value for k in node.keywords
                if k.arg not in ("parent", "trace_parent")
            ]
            for arg in args:
                if _reads_var(arg, var):
                    return True
        if isinstance(node, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
            parent_ok = isinstance(node, ast.Tuple)  # unpack targets etc.
            if not parent_ok and _reads_var(node, var):
                return True
    return False


def _catches_baseexception(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Tuple):
        names = [terminal(e) for e in handler.type.elts]
    else:
        names = [terminal(handler.type)]
    return "BaseException" in names or "KeyboardInterrupt" in names


def span_protected(func: ast.AST, var: str, parents: dict) -> bool:
    """Is some ``.end(var...)`` on a path that survives BaseException —
    a ``finally`` block, or an except handler that catches
    BaseException (bare / explicit / KeyboardInterrupt)? This is the
    contract the serving/recovery span fixes converged on: success-path
    ends carry attributes, and ONE defensive end sits where a second
    Ctrl-C or SystemExit still passes through."""
    for node in ast.walk(func):
        if not (isinstance(node, ast.Call)
                and terminal(node.func) == "end" and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == var):
            continue
        cur = parents.get(node)
        prev: ast.AST = node
        while cur is not None and cur is not func:
            if isinstance(cur, ast.Try) and prev in cur.finalbody:
                return True
            if (isinstance(cur, ast.ExceptHandler)
                    and _catches_baseexception(cur)):
                return True
            prev, cur = cur, parents.get(cur)
    return False


# --------------------------------------------- environ reads (HL008) --


def environ_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to ``os.environ`` (directly or as the
    ``env or os.environ`` fallback idiom)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        exprs = [value]
        if isinstance(value, ast.BoolOp) and isinstance(value.op, ast.Or):
            exprs = value.values
        for e in exprs:
            if dotted(e) == "os.environ":
                out.add(node.targets[0].id)
    return out


def _is_environ(expr: ast.expr, aliases: set[str]) -> bool:
    if dotted(expr) == "os.environ":
        return True
    if isinstance(expr, ast.Name) and expr.id in aliases:
        return True
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
        return any(_is_environ(v, aliases) for v in expr.values)
    return False


def _loop_bindings(tree: ast.AST, consts: dict[str, str]) -> dict[str, set[str]]:
    """``for key in (A, B):`` over resolvable string constants — each
    binding resolves to the full candidate set (backend's expected-
    topology reader)."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and isinstance(node.iter, (ast.Tuple, ast.List))):
            continue
        values = set()
        for elt in node.iter.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                values.add(elt.value)
            elif isinstance(elt, ast.Name) and elt.id in consts:
                values.add(consts[elt.id])
        if values and len(values) == len(node.iter.elts):
            out.setdefault(node.target.id, set()).update(values)
    return out


def iter_env_reads(tree: ast.AST, consts: dict[str, str]):
    """Yield ``(node, key)`` for every resolvable ``os.environ`` /
    ``os.getenv`` read: ``.get(k)``, ``[k]``, including reads through a
    local ``env = os.environ``-style alias or the ``(env or
    os.environ).get(k)`` fallback form. Unresolvable keys (call
    results, cross-module attributes) are skipped — the knob drift TEST
    greps the raw text and closes that gap."""
    aliases = environ_aliases(tree)
    loops = _loop_bindings(tree, consts)

    def keys_of(expr: ast.expr) -> set[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return {expr.value}
        if isinstance(expr, ast.Name):
            if expr.id in consts:
                return {consts[expr.id]}
            if expr.id in loops:
                return loops[expr.id]
        return set()

    for node in ast.walk(tree):
        key_expr = None
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop", "setdefault")
                and node.args
                and _is_environ(node.func.value, aliases)):
            key_expr = node.args[0]
        elif (isinstance(node, ast.Call)
                and dotted(node.func) == "os.getenv" and node.args):
            key_expr = node.args[0]
        elif (isinstance(node, ast.Subscript)
                and _is_environ(node.value, aliases)):
            key_expr = node.slice
        if key_expr is None:
            continue
        for key in sorted(keys_of(key_expr)):
            yield node, key
