"""jaxlint: jit-safety / trace-contract static analysis for the package.

Two tiers (ISSUE 2):

- **Tier A** (:mod:`rules`, :mod:`linter`): a pure-AST lint with NO jax
  import — host-sync idioms, f64 literal promotion, Python branching on
  traced values, ``jnp.asarray`` in loop bodies, bare asserts on arrays,
  static_argnames mistakes, callbacks/prints under trace. Safe to run in
  any environment (CI boxes without an accelerator stack, pre-commit).
- **Tier B** (:mod:`contracts`): a trace-contract harness that lowers every
  registered public jitted entrypoint and asserts no retrace across
  same-shape calls, no f64 ``convert_element_type`` with x64 off, no
  ``pure_callback``/``io_callback`` in hot paths, and flags non-TPU-tile
  operand shapes (with an explicit allowlist). Imports jax.

Keep Tier A import-light: importing ``analysis.rules`` / ``analysis.linter``
/ ``analysis.entrypoints`` must never pull in jax (asserted by
tests/test_jaxlint.py via a subprocess). ``analysis.contracts`` is the only
module here allowed to import jax, and only lazily via this namespace.
"""

__all__ = ["rules", "linter", "entrypoints", "contracts"]


def __getattr__(name):
    if name in __all__:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(name)
