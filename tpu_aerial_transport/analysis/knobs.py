"""The environment-knob registry: every ``TAT_*`` / ``TPU_AERIAL_*``
env var the package, tools, and bench harness read, with its owning
resolver and documented default.

Pure data, stdlib-only, no jax import — the same discipline as
``entrypoints.py``. Tier C's HL008 flags any in-scope ``os.environ``
read of a ``TAT_*``/``TPU_AERIAL_*`` name that is not registered here,
and ``tests/test_hostlint.py`` greps the whole repo for knob names so
a knob cannot be added (or retired) without updating this table — the
perf-knob-resolver discipline from ROADMAP made machine-checkable.

``resolver`` is the file whose code OWNS parsing the variable (other
files should consume the resolver's output, not re-read the env);
``default`` is the behavior when unset, as a human-readable string.
The README "Configuration knobs" table is generated from this dict by
:func:`readme_table` — regenerate with
``python -c "import tpu_aerial_transport.analysis.knobs as k; print(k.readme_table())"``.
"""

from __future__ import annotations

KNOBS: dict[str, dict[str, str]] = {
    "TAT_MATMUL_PRECISION": {
        "resolver": "tpu_aerial_transport/__init__.py",
        "default": "highest (full-f32 matmuls; 'default' restores JAX's "
                   "platform default)",
        "doc": "jax_default_matmul_precision applied at import time.",
    },
    "TAT_EFFORT": {
        "resolver": "tpu_aerial_transport/ops/socp.py",
        "default": "auto (per-call heuristic)",
        "doc": "Adaptive solver-effort mode for the fused ADMM ladder "
               "(consumed via the resolver by control.cadmm too).",
    },
    "TPU_AERIAL_FUSED": {
        "resolver": "tpu_aerial_transport/ops/socp.py",
        "default": "auto (pallas off-CPU, scan on CPU)",
        "doc": "Fused whole-solve kernel selection: pallas|scan|kernel.",
    },
    "TPU_AERIAL_PRECISION": {
        "resolver": "tpu_aerial_transport/ops/socp.py",
        "default": "auto",
        "doc": "Solver precision mode for the fused kernel.",
    },
    "TPU_AERIAL_CONSENSUS": {
        "resolver": "tpu_aerial_transport/parallel/ring.py",
        "default": "auto",
        "doc": "Ring consensus-exchange implementation selection.",
    },
    "TAT_ENV_QUERY": {
        "resolver": "tpu_aerial_transport/envs/spatial.py",
        "default": "auto (bucketed when the world qualifies)",
        "doc": "Environment obstacle-query tier: bucketed|dense.",
    },
    "TAT_PODS_MESH": {
        "resolver": "tpu_aerial_transport/parallel/pods.py",
        "default": "auto (probe the device topology)",
        "doc": "Force an SxA scenario-by-agent pod mesh, e.g. 2x4.",
    },
    "TAT_PODS_COORDINATOR": {
        "resolver": "tpu_aerial_transport/parallel/pods.py",
        "default": "unset (single-process)",
        "doc": "Multi-process bootstrap: coordinator address.",
    },
    "TAT_PODS_NUM_PROCESSES": {
        "resolver": "tpu_aerial_transport/parallel/pods.py",
        "default": "unset (single-process)",
        "doc": "Multi-process bootstrap: world size.",
    },
    "TAT_PODS_PROCESS_ID": {
        "resolver": "tpu_aerial_transport/parallel/pods.py",
        "default": "unset (single-process)",
        "doc": "Multi-process bootstrap: this process's rank.",
    },
    "TAT_BACKEND_FAULTS": {
        "resolver": "tpu_aerial_transport/resilience/backend.py",
        "default": "empty (no injected faults)",
        "doc": "Fault-injection spec for the backend guard's chaos "
               "tests (resilience.FaultInjector.from_env).",
    },
    "TAT_BACKEND_DEADLINE_S": {
        "resolver": "tpu_aerial_transport/resilience/backend.py",
        "default": "backend.DEFAULT_DEADLINE_S",
        "doc": "Primary-dispatch watchdog deadline override.",
    },
    "TAT_EXPECTED_DEVICES": {
        "resolver": "tpu_aerial_transport/resilience/backend.py",
        "default": "unset (no topology expectation)",
        "doc": "Probe gate: required visible device count.",
    },
    "TAT_EXPECTED_PROCESSES": {
        "resolver": "tpu_aerial_transport/resilience/backend.py",
        "default": "unset (no topology expectation)",
        "doc": "Probe gate: required process count.",
    },
    "TAT_AOT_BUNDLE_DIR": {
        "resolver": "tpu_aerial_transport/resilience/backend.py",
        "default": "unset (probe compiles its own executable)",
        "doc": "AOT bundle whose precompiled probe executable "
               "probe()/tools/probe_chip.py replay.",
    },
    "TAT_FLEET_FAULTS": {
        "resolver": "tpu_aerial_transport/serving/fleet.py",
        "default": "empty (no chaos)",
        "doc": "Fleet chaos-storm plan (FleetFaultPlan.from_env).",
    },
    "TAT_XLA_CACHE_DIR": {
        "resolver": "tpu_aerial_transport/utils/platform.py",
        "default": ".cache/xla under the repo (empty string disables)",
        "doc": "Persistent XLA compilation cache location, shared by "
               "conftest, bench, bench_retry children, and AOT serving.",
    },
    "TAT_VIRTUAL_DEVICES": {
        "resolver": "tpu_aerial_transport/utils/platform.py",
        "default": "unset (caller's default; conftest pins 8)",
        "doc": "Virtual CPU device count via XLA's "
               "--xla_force_host_platform_device_count, applied through "
               "apply_virtual_devices() only.",
    },
    "TAT_SERVING_SURGERY": {
        "resolver": "tpu_aerial_transport/serving/lanes.py",
        "default": "host (numpy splice on the boundary host copy)",
        "doc": "Serving boundary lane-surgery implementation: "
               "host|device. Device keeps the batch carry device-"
               "resident and runs the donated select program "
               "(serving.lanes:lane_surgery); flip criterion in the "
               "resolver docstring.",
    },
    "TAT_SERVING_DISPATCH": {
        "resolver": "tpu_aerial_transport/serving/lanes.py",
        "default": "sync (block on chunk k before its boundary)",
        "doc": "Serving chunk-dispatch mode: sync|pipelined. Pipelined "
               "double-buffers — chunk k+1 dispatches before blocking "
               "on chunk k's harvest — and forces device surgery.",
    },
    "TAT_SESSION_LEASE_S": {
        "resolver": "tpu_aerial_transport/serving/sessions.py",
        "default": "30 (seconds)",
        "doc": "Closed-loop session lease TTL: a session whose client "
               "has not heartbeated (or stepped) for this long is "
               "evicted and its lease token fenced; tuning criterion "
               "in the resolver docstring.",
    },
    "TAT_SLO_BURN_RATES": {
        "resolver": "tpu_aerial_transport/obs/live.py",
        "default": "14.4:6 (fast:slow page/warn thresholds)",
        "doc": "Burn-rate alert thresholds for the live SLO engine, "
               "as FAST:SLOW multiples of steady budget spend; tuning "
               "criterion in the resolver docstring.",
    },
    "TAT_CONSOLE_REFRESH_S": {
        "resolver": "tpu_aerial_transport/obs/live.py",
        "default": "1.0 (seconds)",
        "doc": "Poll interval for the live followers "
               "(tools/fleet_console.py, run_health --follow).",
    },
    "TAT_SWEEP_CELLS": {
        "resolver": "bench.py",
        "default": "empty (run every sweep cell)",
        "doc": "Regex restricting which bench sweep cells run "
               "(test/debug hook).",
    },
    "TAT_SWEEP_SHARDED_N": {
        "resolver": "bench.py",
        "default": "64",
        "doc": "Agent count for the sharded bench cells (the "
               "fault-injection e2e sweeps a cheap n=4 twin).",
    },
}

# Literal PREFIX strings that legitimately appear in env-filtering code
# (``k.startswith("TAT_PODS_")`` passthrough into pod workers) — they
# name a family, not a knob, and the drift test skips them.
PREFIX_PASSTHROUGHS: frozenset[str] = frozenset({"TAT_PODS_"})


def readme_table() -> str:
    """The README "Configuration knobs" markdown table, generated so
    docs cannot drift from the registry."""
    rows = ["| Knob | Resolver | Default | What it does |",
            "| --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(
            f"| `{name}` | `{k['resolver']}` | {k['default']} "
            f"| {k['doc']} |"
        )
    return "\n".join(rows)
