"""Tier-B jaxlint: trace contracts over the package's public jitted
entrypoints. This module imports jax (unlike the Tier-A modules).

Every entry in :data:`REGISTRY` lowers a real entrypoint on tiny arguments
and checks four contracts:

- **TC101 no-retrace**: calling the jitted entrypoint twice with freshly
  built same-shape/same-dtype arguments must not grow the jit cache
  (cache-miss counting via the jit function's ``_cache_size``). A miss
  here means some argument leaks object identity / Python hashing into
  the trace key (e.g. an unhashable "static" config rebuilt per call).
- **TC102 no-f64**: with x64 disabled, the lowered StableHLO must contain
  no ``f64`` tensors — an f64 type here means a float64 literal/dtype
  sneaked into the graph and will either widen everything under x64 or
  pay convert_element_type churn without it.
- **TC103 no-callback**: the lowered text must contain no host callback
  custom-calls (``pure_callback``/``io_callback``); a callback in a hot
  path serializes every step through the host.
- **TC104 tile-alignment** (ENFORCED unless waived): flags ``dot_general``
  contractions that run over a misaligned long dim. The f32 TPU tile is
  (8 sublanes, 128 lanes); in this codebase the 128-lane axis is supplied
  by the FOLDED batch (agents x Monte-Carlo scenarios — the controllers'
  nested vmaps / the Pallas kernel's lane folding), so the static
  per-instance contract is SUBLANE alignment of every long contraction:
  a contracting dim of length >= :data:`MIN_ALIGNED_CONTRACT` must be a
  multiple of 8. Short contractions (3-vector physics, 6-row equality
  blocks) are exempt — their alignment cannot pay for itself and padding
  them would cascade through the rigid-body layer. The padded-operator
  tier (ops/socp.py ``pad_qp`` / ``padded_dims``, the C-ADMM Schur-plan
  V-padding) makes the consensus controllers pass this contract; entries
  whose operators are genuinely tiny or deliberately unpadded carry a
  waiver in ``entrypoints.TILE_WAIVERS`` with a reason. Promoted from
  warn-only to a failing contract when the padded tier landed (the
  ROADMAP "revisit when padding becomes a real perf item" item).
- **TC105 donation**: for entries listed in
  ``entrypoints.DONATION_CONTRACTS``, the lowered program must report at
  least the expected number of donated (input-output aliased) arguments
  — ``tf.aliasing_output`` attrs in the StableHLO. A drop here means a
  rollout/step carry silently went copy-in/copy-out again (e.g. an
  output's shape/dtype diverged from its donated input), re-paying HBM
  round-trips on every control step.
- **TC106 off-chip TPU lowering** (:func:`run_lowering_gate`; CLI
  ``tools/jaxlint.py --contracts --target tpu``): AOT-lower every
  registered entrypoint for the TPU *target* via ``jax.export`` — no
  device required — and require (a) the lowering to succeed and (b) the
  TPU-target StableHLO to contain no f64 tensor types. This is the
  r02-class gate: BENCH_r02 died at the first real dispatch on the chip
  (a ``convert_element_type`` surfacing a lazy backend-init failure),
  and the ordinary contracts only ever lowered for the host CPU — a
  TPU-only dtype/lowering bug could not fail tier-1 on a CPU box. Now it
  can: the whole registry TPU-lowers in ~35 s on this host.

Builders use deliberately tiny problem sizes: the contracts are about
program STRUCTURE (dtypes, callbacks, cache keys, alignment of the
static operator edges), which is size-independent, and tier-1 runs a
subset of these on every commit.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.analysis import entrypoints as entry_data
from tpu_aerial_transport.analysis.rules import Finding

_F64_RE = re.compile(r"f64>")
# Host-round-trip primitives at the JAXPR level. TC103 cannot work on the
# lowered StableHLO text: pure_callback, io_callback AND jax.debug.print
# all lower to the SAME `custom_call @xla_python_cpu_callback` target
# (verified on jax 0.4.37), and debug prints are exactly what JL011 tells
# people to use — only the jaxpr distinguishes them (`debug_callback` vs
# `pure_callback`/`io_callback`).
_CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback"})

# TC104: contracting dims at least this long must be SUBLANE_TILE-aligned.
# Below it a contraction is "short" (3-vector physics, 6-row equality
# blocks, 12-var reduced QPs): the reduction is latency-bound regardless of
# alignment and padding it would cascade through the rigid-body layer.
MIN_ALIGNED_CONTRACT = 16
SUBLANE = 8

# Donation marker jax emits into StableHLO for donated-and-aliasable args
# (jax 0.4.x; input-output aliasing attr on the main func).
_ALIAS_ATTR = "tf.aliasing_output"

# Fast subset exercised by tier-1 on every run (tests/test_jaxlint.py);
# the full registry runs under -m slow and via `tools/jaxlint.py
# --contracts`. Chosen to cover the solver core, one consensus
# controller, and one scan-of-solves rollout within a few seconds of
# CPU compile time each.
FAST_SUBSET = (
    "ops.socp:solve_socp",
    "ops.socp:solve_socp_padded",
    "control.cadmm:control",
    "harness.rollout:rollout",
)


@dataclasses.dataclass(frozen=True)
class Contract:
    """One registered entrypoint. ``build()`` returns ``(fn, make_args)``
    where ``fn`` is the UNjitted callable (statics closed over) and
    ``make_args()`` builds a fresh argument tuple (called twice by the
    retrace check — the two pytrees must be independent objects)."""

    name: str
    build: Callable[[], tuple[Callable, Callable[[], tuple]]]
    min_devices: int = 1
    # Entries whose lowering legitimately contains the string "callback"
    # (none today) would set this with a reason.
    allow_callbacks: str = ""
    # Non-empty reason => the entry is registered for the TC106 lowering
    # gate ONLY: check_entry skips the execution-based contracts
    # (TC101-TC105 all run or lower the program on the LOCAL backend,
    # which a chip-only kernel — e.g. the Pallas remote-DMA ring — cannot
    # do on a CPU lint host). The entry still counts toward registry
    # coverage and still runs through run_lowering_gate unless it also
    # carries an entrypoints.LOWERING_WAIVERS row.
    lowering_only: str = ""


REGISTRY: dict[str, Contract] = {}


def _register(name: str, **kw):
    def deco(build):
        REGISTRY[name] = Contract(name=name, build=build, **kw)
        return build

    return deco


# ----------------------------------------------------------------------
# Argument builders.
# ----------------------------------------------------------------------

def _acc():
    return (jnp.zeros(3), jnp.zeros(3))


def _rqp_bits(n=4):
    from tpu_aerial_transport.harness import setup

    params, col, state = setup.rqp_setup(n)
    return params, col, state


@_register("control.centralized:control")
def _build_centralized():
    from tpu_aerial_transport.control import centralized

    params, col, state = _rqp_bits(4)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=10
    )
    f_eq = centralized.equilibrium_forces(params)

    def fn(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    def make_args():
        return (centralized.init_ctrl_state(params, cfg),
                _rqp_bits(4)[2], _acc())

    return fn, make_args


def _cadmm_bits(forest=None):
    from tpu_aerial_transport.control import cadmm, centralized

    params, col, state = _rqp_bits(4)
    # pad_operators pinned True: TC104 checks the tile-target (padded)
    # program structure even when the lint host is CPU, where the
    # make_config "auto" default resolves to the raw layout.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(params)
    plan = cadmm.make_plan(params, cfg)

    def fn(cs, s, a):
        return cadmm.control(
            params, cfg, f_eq, cs, s, a, forest, plan=plan
        )

    def make_args():
        return (cadmm.init_cadmm_state(params, cfg), _rqp_bits(4)[2], _acc())

    return fn, make_args


@_register("control.cadmm:control")
def _build_cadmm():
    return _cadmm_bits()


@_register("control.cadmm:control_forest")
def _build_cadmm_forest():
    from tpu_aerial_transport.envs import forest as forest_mod

    return _cadmm_bits(forest=forest_mod.make_forest(0))


def _env_query_bits(env_query: str):
    """Env-query entrypoints (envs/spatial.py): the full query surface —
    dispatch, (for the bucketed tier) the grid-cell candidate-slab
    gather, the shared per-tree capsule sweep, and collision CBF row
    construction — on the reference 200-slot world. The bucketed twin's
    grid is rebuilt per make_args call: TC101 then also proves a fresh
    grid artifact of the same world re-uses the compiled query."""
    import jax.numpy as jnp

    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.envs import spatial

    vision_radius = 6.0

    def fn(forest, xl, vl):
        return forest_mod.collision_cbf_rows(
            forest, xl, vl, vision_radius - 5.0, 2.0, vision_radius,
            0.1, 1.5, 10, env_query=env_query,
        )

    def make_args():
        forest = forest_mod.make_forest(0)
        if env_query == "bucketed":
            forest = spatial.with_grid(
                forest, vision_radius + forest.bark_radius
            )
        return (
            forest,
            jnp.array([28.0, 1.0, 2.0], jnp.float32),
            jnp.array([0.5, 0.2, 0.0], jnp.float32),
        )

    return fn, make_args


@_register("envs.spatial:env_query_bucketed")
def _build_env_query_bucketed():
    return _env_query_bits("bucketed")


@_register("envs.spatial:env_query_dense")
def _build_env_query_dense():
    return _env_query_bits("dense")


@_register("control.dd:control")
def _build_dd():
    from tpu_aerial_transport.control import centralized, dd

    params, col, state = _rqp_bits(4)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(params)
    plan = dd.make_dd_plan(params, cfg)

    def fn(cs, s, a):
        return dd.control(params, cfg, f_eq, cs, s, a, plan=plan)

    def make_args():
        return (dd.init_dd_state(params, cfg), _rqp_bits(4)[2], _acc())

    return fn, make_args


@_register("control.rp_cadmm:control")
def _build_rp_cadmm():
    from tpu_aerial_transport.control import rp_cadmm, rp_centralized
    from tpu_aerial_transport.harness import setup

    params, col, state = setup.rp_setup(3)
    cfg = rp_cadmm.make_config(params, max_iter=2, inner_iters=4)
    f_eq = rp_centralized.equilibrium_forces(params)

    def fn(cs, s, a):
        return rp_cadmm.control(params, cfg, f_eq, cs, s, a)

    def make_args():
        return (rp_cadmm.init_state(params, cfg, f_eq),
                setup.rp_setup(3)[2], _acc())

    return fn, make_args


@_register("control.rp_centralized:control")
def _build_rp_centralized():
    from tpu_aerial_transport.control import rp_centralized
    from tpu_aerial_transport.harness import setup

    params, col, state = setup.rp_setup(3)
    cfg = rp_centralized.make_config(params, solver_iters=10)
    f_eq = rp_centralized.equilibrium_forces(params)

    def fn(cs, s, a):
        return rp_centralized.control(params, cfg, f_eq, cs, s, a)

    def make_args():
        return (rp_centralized.init_ctrl_state(params, cfg),
                setup.rp_setup(3)[2], _acc())

    return fn, make_args


@_register("control.pmrl_centralized:control")
def _build_pmrl():
    from tpu_aerial_transport.control import pmrl_centralized
    from tpu_aerial_transport.harness import setup

    params, col, state = setup.pmrl_setup(3)
    cfg = pmrl_centralized.make_config(params, solver_iters=10)

    def fn(cs, s, a):
        return pmrl_centralized.control(params, cfg, cs, s, a)

    def make_args():
        return (pmrl_centralized.init_ctrl_state(params, cfg, state),
                setup.pmrl_setup(3)[2], _acc())

    return fn, make_args


def _socp_problem(nv=8, n_box=6, soc=(4,)):
    rng = np.random.default_rng(0)
    L = rng.standard_normal((nv, nv))
    P = jnp.asarray(L @ L.T + np.eye(nv), jnp.float32)
    q = jnp.asarray(rng.standard_normal(nv), jnp.float32)
    m = n_box + sum(soc)
    A = jnp.asarray(rng.standard_normal((m, nv)) * 0.5, jnp.float32)
    lb = jnp.asarray(rng.uniform(-2.0, -0.5, n_box), jnp.float32)
    ub = jnp.asarray(rng.uniform(0.5, 2.0, n_box), jnp.float32)
    return P, q, A, lb, ub


@_register("ops.socp:solve_socp")
def _build_socp():
    from tpu_aerial_transport.ops import socp

    def fn(P, q, A, lb, ub):
        return socp.solve_socp(
            P, q, A, lb, ub, n_box=6, soc_dims=(4,), iters=20, fused="scan"
        )

    return fn, _socp_problem


@_register("ops.admm_kernel:solve_socp_interpret")
def _build_socp_interpret():
    from tpu_aerial_transport.ops import socp

    def fn(P, q, A, lb, ub):
        # The Pallas chunk kernel engages only under a batch axis (the
        # unbatched path is plain scan — see socp._fused_chunk_runner).
        return jax.vmap(
            lambda Pb, qb: socp.solve_socp(
                Pb, qb, A, lb, ub, n_box=6, soc_dims=(4,), iters=8,
                fused="interpret",
            )
        )(P, q)

    def make_args():
        P, q, A, lb, ub = _socp_problem()
        return (jnp.tile(P[None], (2, 1, 1)), jnp.tile(q[None], (2, 1)),
                A, lb, ub)

    return fn, make_args


@_register("ops.admm_kernel:fused_solve_interpret")
def _build_fused_solve_interpret():
    """The whole-solve mega-kernel through the padded tier (the hot
    callers' configuration): TC104 is ENFORCED here — no tile waiver —
    because solve_socp_padded rounds every operator edge to the sublane
    tile, so every long contraction the kernel stages (d, m_p) is
    8-aligned by construction."""
    from tpu_aerial_transport.ops import socp

    def fn(P, q, A, lb, ub):
        # The mega-kernel engages only under a batch axis (the unbatched
        # path is plain scan — see socp._fused_solve_runner).
        return jax.vmap(
            lambda Pb, qb: socp.solve_socp_padded(
                Pb, qb, A, lb, ub, n_box=6, soc_dims=(4,), iters=8,
                fused="kernel_interpret",
            )
        )(P, q)

    def make_args():
        P, q, A, lb, ub = _socp_problem()
        return (jnp.tile(P[None], (2, 1, 1)), jnp.tile(q[None], (2, 1)),
                A, lb, ub)

    return fn, make_args


@_register(
    "ops.admm_kernel:fused_solve_pallas",
    lowering_only="Mosaic whole-solve kernel: no CPU execution — the "
    "compiled broadcast-reduce body only runs on a TPU. Unlike the "
    "remote-DMA ring it carries NO entrypoints.LOWERING_WAIVERS row: "
    "jax.export AOT-lowers it cleanly for the tpu target on this image "
    "(measured — the earlier vmapped-dot body died in Mosaic at the "
    "batched dot_general, which is why the compiled form exists), so "
    "TC106 is enforced.",
)
def _build_fused_solve_pallas():
    """The REAL compiled kernel (interpret=False, exact_dot=False) on the
    C-ADMM-shaped padded dims: if its Mosaic lowering ever regresses —
    e.g. a jax upgrade rejecting the broadcast-reduce body — TC106 fails
    tier-1 on this CPU box instead of wedging the chip round."""
    import numpy as np

    from tpu_aerial_transport.ops import admm_kernel

    B, nv, m, n_box, soc_dims = 8, 16, 32, 24, (4, 4)
    d = nv + m

    def fn(K2, Minv, A, P, q, rho, lb, ub, shift, x, y, z):
        return admm_kernel.fused_solve_lanes(
            x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift,
            nv=nv, n_box=n_box, soc_dims=soc_dims, iters=4, alpha=1.6,
            interpret=False,
        )

    def make_args():
        rng = np.random.default_rng(0)
        f32 = jnp.float32
        return (
            jnp.asarray(rng.standard_normal((B, d, d)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, m, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv)), f32),
            jnp.ones((B, m), f32), -jnp.ones((B, n_box), f32),
            jnp.ones((B, n_box), f32), jnp.zeros((B, m), f32),
            jnp.zeros((B, nv), f32), jnp.zeros((B, m), f32),
            jnp.zeros((B, m), f32),
        )

    return fn, make_args


@_register("ops.admm_kernel:fused_solve_earlyexit_interpret")
def _build_fused_solve_earlyexit_interpret():
    """The in-kernel early-exit mega-kernel through the padded tier:
    check_every=3 over iters=8 exercises BOTH the whole-cell while loop
    (n_full=2) and the masked remainder chunk (rem=2); report_iters
    covers the effective-iteration output. TC104 enforced — no tile
    waiver (padded tier, like the fixed-iteration twin)."""
    from tpu_aerial_transport.ops import socp

    def fn(P, q, A, lb, ub):
        return jax.vmap(
            lambda Pb, qb: socp.solve_socp_padded(
                Pb, qb, A, lb, ub, n_box=6, soc_dims=(4,), iters=8,
                check_every=3, tol=1e-3, fused="kernel_interpret",
                report_iters=True,
            )
        )(P, q)

    def make_args():
        P, q, A, lb, ub = _socp_problem()
        return (jnp.tile(P[None], (2, 1, 1)), jnp.tile(q[None], (2, 1)),
                A, lb, ub)

    return fn, make_args


@_register(
    "ops.admm_kernel:fused_solve_earlyexit_pallas",
    lowering_only="Mosaic whole-solve early-exit kernel: no CPU "
    "execution — the compiled broadcast-reduce body with the scf.while "
    "chunk loop only runs on a TPU. NO entrypoints.LOWERING_WAIVERS "
    "row: jax.export AOT-lowers the while-loop form (per-lane masks, "
    "int32 iteration output, f32 gate input) cleanly for the tpu "
    "target on this image, so TC106 is enforced — a jax upgrade "
    "breaking Mosaic's scf.while support fails tier-1 on a CPU box "
    "instead of wedging the chip round.",
)
def _build_fused_solve_earlyexit_pallas():
    """The REAL compiled early-exit kernel (interpret=False,
    exact_dot=False) on the C-ADMM-shaped padded dims, with the
    consensus-effort gate input wired (has_active=True — the fullest
    signature the adaptive tier dispatches)."""
    import numpy as np

    from tpu_aerial_transport.ops import admm_kernel

    B, nv, m, n_box, soc_dims = 8, 16, 32, 24, (4, 4)
    d = nv + m

    def fn(K2, Minv, A, P, q, rho, lb, ub, shift, x, y, z, active):
        return admm_kernel.fused_solve_lanes(
            x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift, active,
            nv=nv, n_box=n_box, soc_dims=soc_dims, iters=8, alpha=1.6,
            check_every=3, tol=1e-3, interpret=False,
        )

    def make_args():
        rng = np.random.default_rng(0)
        f32 = jnp.float32
        return (
            jnp.asarray(rng.standard_normal((B, d, d)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, m, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv, nv)) * 0.1, f32),
            jnp.asarray(rng.standard_normal((B, nv)), f32),
            jnp.ones((B, m), f32), -jnp.ones((B, n_box), f32),
            jnp.ones((B, n_box), f32), jnp.zeros((B, m), f32),
            jnp.zeros((B, nv), f32), jnp.zeros((B, m), f32),
            jnp.zeros((B, m), f32),
            jnp.ones((B,), bool),
        )

    return fn, make_args


def _adaptive_cfg_kw():
    # inner_check_every=2 over inner_iters=4 exercises the gated chunk
    # loop + remainder inside a real consensus step at lint-host sizes.
    return dict(
        max_iter=2, inner_iters=4, pad_operators=True,
        effort="adaptive", inner_check_every=2,
    )


@_register("control.cadmm:control_adaptive")
def _build_cadmm_adaptive():
    """The adaptive-effort C-ADMM step (effort='adaptive' resolved at
    make_config): the consensus loop's per-lane converged gate threads
    into tolerance-chunked early-exit inner solves and the effort
    accounting lands on SolverStats.inner_iters. pad_operators pinned
    True (TC104 checks the tile-target program on the CPU lint host)."""
    from tpu_aerial_transport.control import cadmm, centralized

    params, col, state = _rqp_bits(4)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        **_adaptive_cfg_kw(),
    )
    f_eq = centralized.equilibrium_forces(params)
    plan = cadmm.make_plan(params, cfg)

    def fn(cs, s, a):
        return cadmm.control(params, cfg, f_eq, cs, s, a, plan=plan)

    def make_args():
        return (cadmm.init_cadmm_state(params, cfg), _rqp_bits(4)[2], _acc())

    return fn, make_args


@_register("control.dd:control_adaptive")
def _build_dd_adaptive():
    from tpu_aerial_transport.control import centralized, dd

    params, col, state = _rqp_bits(4)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        **_adaptive_cfg_kw(),
    )
    f_eq = centralized.equilibrium_forces(params)
    plan = dd.make_dd_plan(params, cfg)

    def fn(cs, s, a):
        return dd.control(params, cfg, f_eq, cs, s, a, plan=plan)

    def make_args():
        return (dd.init_dd_state(params, cfg), _rqp_bits(4)[2], _acc())

    return fn, make_args


@_register("ops.socp:solve_socp_padded")
def _build_socp_padded():
    from tpu_aerial_transport.ops import socp

    def fn(P, q, A, lb, ub):
        return socp.solve_socp_padded(
            P, q, A, lb, ub, n_box=6, soc_dims=(4,), iters=20, fused="scan"
        )

    return fn, _socp_problem


def _rollout_bits():
    from tpu_aerial_transport.control import centralized, lowlevel

    params, col, state = _rqp_bits(4)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=10
    )
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    return params, cfg, centralized, llc, hl


@_register("harness.rollout:rollout")
def _build_rollout():
    from tpu_aerial_transport.harness import rollout as h_rollout

    params, cfg, centralized, llc, hl = _rollout_bits()

    def fn(s0, cs0):
        return h_rollout.rollout(
            hl, llc.control, params, s0, cs0, n_hl_steps=2, hl_rel_freq=2
        )

    def make_args():
        return (_rqp_bits(4)[2], centralized.init_ctrl_state(params, cfg))

    return fn, make_args


@_register("harness.rollout:rollout_donated")
def _build_rollout_donated():
    from tpu_aerial_transport.harness import rollout as h_rollout

    params, cfg, centralized, llc, hl = _rollout_bits()
    # Already jitted WITH donation — check_entry uses the real compiled
    # object so the TC105 aliasing count sees the donated carries.
    fn = h_rollout.jit_rollout(
        hl, llc.control, params, n_hl_steps=2, hl_rel_freq=2
    )

    def make_args():
        # Decouple leaves that share a constant buffer (identical zeros
        # dedupe) — donating one buffer twice is a runtime error; see the
        # jit_rollout docstring.
        return jax.tree.map(
            jnp.copy,
            (_rqp_bits(4)[2], centralized.init_ctrl_state(params, cfg)),
        )

    return fn, make_args


@_register("harness.rollout:chunked_rollout")
def _build_chunked_rollout():
    import itertools

    from tpu_aerial_transport.harness import rollout as h_rollout

    params, cfg, centralized, llc, hl = _rollout_bits()
    x0 = _rqp_bits(4)[2].xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    # donate pinned True: the recovery drivers default it OFF for
    # bit-reproducibility under the persistent compilation cache, but the
    # donated configuration must STAY donation-clean (TC105 aliasing) and
    # single-compile (TC101) for serving callers that opt back in.
    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=4, n_chunks=2, hl_rel_freq=2,
        acc_des_fn=acc_des_fn, donate=True,
    )
    # The real jitted chunk (donated carry, traced step offset): TC105
    # sees the aliasing, TC101 sees the jit cache.
    fn = run.chunk_jit
    chunk_idx = itertools.count()

    def make_args():
        # Successive calls pass SUCCESSIVE chunk offsets, so the TC101
        # no-retrace check asserts the crash-recovery tier's core
        # property: all C chunks hit one compiled program (an offset
        # leaking into the trace key would retrace per chunk). Carries are
        # fresh + decoupled (donation; see _build_rollout_donated).
        c = next(chunk_idx) % run.n_chunks
        carry = jax.tree.map(
            jnp.copy,
            (_rqp_bits(4)[2], centralized.init_ctrl_state(params, cfg)),
        )
        return (carry, h_rollout.chunk_index_offset(c, run.chunk_len))

    return fn, make_args


@_register("harness.rollout:rollout_telemetry")
def _build_rollout_telemetry():
    from tpu_aerial_transport.harness import rollout as h_rollout
    from tpu_aerial_transport.obs import telemetry as telemetry_mod

    params, cfg, centralized, llc, hl = _rollout_bits()
    tcfg = telemetry_mod.TelemetryConfig()

    def fn(s0, cs0):
        return h_rollout.rollout(
            hl, llc.control, params, s0, cs0, n_hl_steps=2, hl_rel_freq=2,
            telemetry=tcfg,
        )

    def make_args():
        return (_rqp_bits(4)[2], centralized.init_ctrl_state(params, cfg))

    return fn, make_args


@_register("resilience.rollout:resilient_rollout")
def _build_resilient():
    from tpu_aerial_transport.control import cadmm, lowlevel
    from tpu_aerial_transport.resilience import faults as faults_mod
    from tpu_aerial_transport.resilience import rollout as r_rollout

    params, col, state = _rqp_bits(4)
    # pad_operators pinned True: TC104 checks the tile-target (padded)
    # program structure even when the lint host is CPU, where the
    # make_config "auto" default resolves to the raw layout.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    sched = faults_mod.make_schedule(4, t_fail={1: 1}, drop_rate=0.3)
    hl = r_rollout.make_cadmm_hl_step(params, cfg)
    llc = lowlevel.make_lowlevel_controller("pd", params)

    def fn(s0, cs0):
        return r_rollout.resilient_rollout(
            hl, llc.control, params, s0, cs0, n_hl_steps=2, hl_rel_freq=2,
            faults=sched,
        )

    def make_args():
        return (_rqp_bits(4)[2], cadmm.init_cadmm_state(params, cfg))

    return fn, make_args


@_register("resilience.rollout:resilient_rollout_donated")
def _build_resilient_donated():
    from tpu_aerial_transport.control import cadmm, lowlevel
    from tpu_aerial_transport.resilience import faults as faults_mod
    from tpu_aerial_transport.resilience import rollout as r_rollout

    params, col, state = _rqp_bits(4)
    # pad_operators pinned True: TC104 checks the tile-target (padded)
    # program structure even when the lint host is CPU, where the
    # make_config "auto" default resolves to the raw layout.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    sched = faults_mod.make_schedule(4, t_fail={1: 1}, drop_rate=0.3)
    hl = r_rollout.make_cadmm_hl_step(params, cfg)
    llc = lowlevel.make_lowlevel_controller("pd", params)
    fn = r_rollout.jit_resilient_rollout(
        hl, llc.control, params, n_hl_steps=2, hl_rel_freq=2, faults=sched,
    )

    def make_args():
        # Shared-constant decoupling; see _build_rollout_donated.
        return jax.tree.map(
            jnp.copy,
            (_rqp_bits(4)[2], cadmm.init_cadmm_state(params, cfg)),
        )

    return fn, make_args


@_register("resilience.rollout:resilient_rollout_telemetry")
def _build_resilient_telemetry():
    from tpu_aerial_transport.control import cadmm, lowlevel
    from tpu_aerial_transport.obs import telemetry as telemetry_mod
    from tpu_aerial_transport.resilience import faults as faults_mod
    from tpu_aerial_transport.resilience import rollout as r_rollout

    params, col, state = _rqp_bits(4)
    # pad_operators pinned True (TC104 checks the tile-target program on
    # the CPU lint host); track_agent_stats exercises the per-agent
    # solve-health stats path + telemetry's matching agent accumulators.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
        track_agent_stats=True,
    )
    sched = faults_mod.make_schedule(4, t_fail={1: 1}, drop_rate=0.3)
    hl = r_rollout.make_cadmm_hl_step(params, cfg)
    llc = lowlevel.make_lowlevel_controller("pd", params)
    tcfg = telemetry_mod.TelemetryConfig(track_agents=True)

    def fn(s0, cs0):
        return r_rollout.resilient_rollout(
            hl, llc.control, params, s0, cs0, n_hl_steps=2, hl_rel_freq=2,
            faults=sched, telemetry=tcfg,
        )

    def make_args():
        return (_rqp_bits(4)[2], cadmm.init_cadmm_state(params, cfg))

    return fn, make_args


@_register("parallel.mesh:cadmm_control_sharded", min_devices=4)
def _build_mesh_cadmm():
    from tpu_aerial_transport.control import cadmm, centralized
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    params, col, state = _rqp_bits(4)
    # pad_operators pinned True: TC104 checks the tile-target (padded)
    # program structure even when the lint host is CPU, where the
    # make_config "auto" default resolves to the raw layout.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(params)
    m = mesh_mod.make_mesh({"agent": 4})
    step = mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m)

    def make_args():
        return (cadmm.init_cadmm_state(params, cfg), _rqp_bits(4)[2], _acc())

    return step, make_args


@_register("parallel.mesh:cadmm_control_sharded_ring", min_devices=4)
def _build_mesh_cadmm_ring():
    from tpu_aerial_transport.control import cadmm, centralized
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    params, col, state = _rqp_bits(4)
    # The full agent-sharded consensus hot path on the ppermute ring tier
    # (consensus_impl pinned "ring" — the CPU lint host's make_config
    # "auto" resolves to allreduce); pad_operators pinned True so TC104
    # checks the tile-target program like the allreduce twin above.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
        consensus_impl="ring",
    )
    f_eq = centralized.equilibrium_forces(params)
    m = mesh_mod.make_mesh({"agent": 4})
    step = mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m)

    def make_args():
        return (cadmm.init_cadmm_state(params, cfg), _rqp_bits(4)[2], _acc())

    return step, make_args


def _ring_mesh_bits():
    from functools import partial

    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_aerial_transport.parallel import mesh as mesh_mod
    from tpu_aerial_transport.utils import compat

    d = 4
    m = mesh_mod.make_mesh({"agent": d})

    def shmap(fn, n_out):
        return partial(
            compat.shard_map, mesh=m, in_specs=P("agent"),
            out_specs=tuple(P("agent") for _ in range(n_out))
            if n_out > 1 else P("agent"),
            check_vma=False,
        )(fn)

    def make_args():
        rng = np.random.default_rng(0)
        return (jnp.asarray(rng.standard_normal((d, 6, 3)), jnp.float32),)

    return d, shmap, make_args


@_register("parallel.ring:consensus_exchange", min_devices=4)
def _build_ring_exchange():
    """The exchange's three faces (sum, max, gather) on the ppermute ring
    under shard_map, with a payload whose size does NOT divide the ring
    (18 elements over 4 shards — the chunk-pad path)."""
    from tpu_aerial_transport.parallel import ring as ring_mod

    d, shmap, make_args = _ring_mesh_bits()

    def fn(x):
        v = x[0]
        s = ring_mod.consensus_exchange(
            v, "agent", axis_size=d, op="sum", impl="ring"
        )
        mx = ring_mod.consensus_exchange(
            jnp.max(v), "agent", axis_size=d, op="max", impl="ring"
        )
        g = ring_mod.consensus_gather(v, "agent", axis_size=d, impl="ring")
        return s[None], mx[None, None], g[None]

    return shmap(fn, 3), make_args


@_register(
    "parallel.ring:consensus_exchange_pallas", min_devices=4,
    lowering_only="Mosaic remote-DMA kernel: no CPU execution or "
    "lowering; off-chip jax.export also fails (see the matching "
    "entrypoints.LOWERING_WAIVERS reason)",
)
def _build_ring_exchange_pallas():
    """The REAL remote-DMA kernel (not the off-TPU trace-time downgrade
    consensus_exchange would apply on this host): if the
    LOWERING_WAIVERS row is ever removed — e.g. after a jax upgrade —
    TC106 must attempt the genuine Mosaic program."""
    from tpu_aerial_transport.parallel import ring as ring_mod

    d, shmap, make_args = _ring_mesh_bits()

    def fn(x):
        return ring_mod._pallas_ring_allreduce(x[0], "agent", d)[None]

    return shmap(fn, 1), make_args


@_register("parallel.pods:pods_control_step", min_devices=8)
def _build_pods_step():
    """The 2-D (scenario, agent) pods-mesh C-ADMM step on the 2x4 virtual
    mesh (single-process here; the process boundary is exercised by
    tools/pods_local.py — the PROGRAM is identical, shard_map over the
    same mesh axes). pad_operators pinned True so TC104 checks the
    tile-target layout like the 1-D sharded twins."""
    from tpu_aerial_transport.control import cadmm, centralized
    from tpu_aerial_transport.parallel import pods

    params, col, state = _rqp_bits(4)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(params)
    mesh = pods.make_pods_mesh(pods.resolve_pods_spec(4, "2x4"))
    step = pods.pods_control_step(params, cfg, f_eq, mesh, None, "cadmm")

    def make_args():
        b = 4
        cs0 = cadmm.init_cadmm_state(params, cfg)
        css = jax.vmap(lambda _: cs0)(jnp.arange(b))
        states = jax.tree.map(
            lambda x: jnp.tile(x[None], (b,) + (1,) * x.ndim),
            _rqp_bits(4)[2],
        )
        return (css, states, _acc())

    return step, make_args


@_register("parallel.mesh:scenario_rollout", min_devices=2)
def _build_mesh_scenarios():
    from tpu_aerial_transport.harness import rollout as h_rollout
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    params, cfg, centralized, llc, hl = _rollout_bits()
    m = mesh_mod.make_mesh({"scenario": 2})

    def rollout_fn(s0, cs0):
        return h_rollout.rollout(
            hl, llc.control, params, s0, cs0, n_hl_steps=2, hl_rel_freq=2
        )

    run = mesh_mod.scenario_rollout(rollout_fn, m)
    # The contract drives the jit UNDER the wrapper (run.batched_jit) so
    # cache-miss counting sees the real compiled object.
    fn = run.batched_jit

    def make_args():
        state = _rqp_bits(4)[2]
        batch = jax.tree.map(
            lambda x: jnp.tile(x[None], (2,) + (1,) * x.ndim), state
        )
        cs = centralized.init_ctrl_state(params, cfg)
        cs_b = jax.tree.map(
            lambda x: jnp.tile(x[None], (2,) + (1,) * x.ndim), cs
        )
        return (batch, cs_b)

    return fn, make_args


def _serving_chunk_build(canonical: str):
    """Shared builder for the serving-tier batched chunk entries: the
    canonical family's vmapped chunk at the smallest shape bucket
    (serving/batcher.py — the SAME factory the server and the AOT bundle
    use, so bundle signatures match served batches by construction).
    make_args cycles chunk offsets like the chunked_rollout contract: all
    boundaries of a serving batch must hit ONE compiled program."""
    import itertools

    import numpy as np

    from tpu_aerial_transport.harness import rollout as h_rollout
    from tpu_aerial_transport.serving import batcher

    fam = batcher.make_family(canonical)
    bucket = batcher.DEFAULT_BUCKETS[0]
    chunk_idx = itertools.count()

    def make_args():
        c = next(chunk_idx) % 4
        carry = jax.tree.map(
            lambda x: np.stack([np.array(x, copy=True)] * bucket),
            fam.template_carry_host(),
        )
        return (carry, h_rollout.chunk_index_offset(c, fam.chunk_len))

    return fam.batched_fn, make_args


@_register("serving.batcher:serving_chunk")
def _build_serving_chunk():
    return _serving_chunk_build("cadmm4")


@_register("serving.batcher:serving_chunk_centralized")
def _build_serving_chunk_centralized():
    return _serving_chunk_build("centralized4")


def _lane_surgery_build(canonical: str):
    """Shared builder for the on-device boundary lane-surgery entries
    (serving/lanes.py): the family's batched carry at the smallest shape
    bucket, pre-jitted WITH carry donation — check_entry uses the real
    compiled object, so the TC105 aliasing count sees the donated
    boundary carry (the server's jit rung and the bundle build both
    start from this same registered callable). make_args exercises one
    late-join lane and one filler reset per call (runtime mask values —
    the compiled select program is identical for any mask)."""
    import numpy as np

    from tpu_aerial_transport.serving import batcher
    from tpu_aerial_transport.serving import lanes
    from tpu_aerial_transport.serving import queue as squeue

    fam = batcher.make_family(canonical)
    bucket = batcher.DEFAULT_BUCKETS[0]
    fn = jax.jit(lanes.lane_surgery, donate_argnums=(0,))

    def make_args():
        # Fresh numpy leaves per call: the donated carry is consumed by
        # each run, and the retrace check needs independent pytrees.
        carry = jax.tree.map(
            lambda x: np.stack([np.array(x, copy=True)] * bucket),
            fam.template_carry_host(),
        )
        req = squeue.ScenarioRequest(
            family=canonical, horizon=fam.chunk_len,
            x0=(0.1, -0.2, 0.3), v0=(0.01, 0.02, -0.03),
        )
        # Copy the cached per-bucket template too: make_args contracts
        # to return INDEPENDENT pytrees on every call.
        template_b = jax.tree.map(
            np.copy, fam.batched_template_host(bucket)
        )
        return (carry,) + lanes.make_surgery_args(
            template_b, [(0, req)], [1], bucket
        )

    return fn, make_args


@_register("serving.lanes:lane_surgery")
def _build_lane_surgery():
    return _lane_surgery_build("cadmm4")


@_register("serving.lanes:lane_surgery_centralized")
def _build_lane_surgery_centralized():
    return _lane_surgery_build("centralized4")


# ----------------------------------------------------------------------
# Checks.
# ----------------------------------------------------------------------

def scan_lowered_text(text: str, path: str) -> list[Finding]:
    """String-level TC102 over lowered StableHLO, factored out so the
    detection logic is unit-testable without having to synthesize an f64
    graph under x64-off canonicalization. (TC103 is jaxpr-level — see
    :data:`_CALLBACK_PRIMS` — because debug prints and real callbacks
    lower to the same custom_call target.)"""
    out: list[Finding] = []
    n = len(_F64_RE.findall(text))
    if n:
        out.append(Finding(
            rule="TC102", path=path, line=0, col=0,
            message=f"lowered StableHLO contains {n} f64 tensor "
            "type(s) with x64 disabled (f64 literal/dtype in the "
            "graph)",
        ))
    return out


def callback_primitives(jaxpr) -> list[str]:
    """Names of host-round-trip callback primitives anywhere in a (closed)
    jaxpr, recursing into scan/while/cond sub-jaxprs. ``debug_callback``
    (jax.debug.print) is deliberately NOT counted — it is the sanctioned
    replacement JL011 recommends."""
    return sorted(
        eqn.primitive.name
        for eqn in _iter_eqns(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr")
                              else jaxpr)
        if eqn.primitive.name in _CALLBACK_PRIMS
    )


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    ClosedJaxpr = jax.core.ClosedJaxpr
    Jaxpr = jax.core.Jaxpr
    if isinstance(v, ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def check_entry(contract: Contract,
                disabled: frozenset[str] = frozenset()) -> list[Finding]:
    """Run all trace contracts for one registry entry."""
    out: list[Finding] = []
    path = f"contracts:{contract.name}"
    if jax.device_count() < contract.min_devices:
        return out  # environment cannot host this entry; not a finding.
    if contract.lowering_only:
        return out  # chip-only program: TC106 territory (see the field).
    fn, make_args = contract.build()
    jitted = fn if hasattr(fn, "lower") and hasattr(fn, "_cache_size") \
        else jax.jit(fn)

    # TC101: no retrace across same-shape calls with fresh arguments.
    if "TC101" not in disabled:
        jax.block_until_ready(jitted(*make_args()))
        before = jitted._cache_size()
        jax.block_until_ready(jitted(*make_args()))
        after = jitted._cache_size()
        if after != before:
            out.append(Finding(
                rule="TC101", path=path, line=0, col=0,
                message=(
                    f"retrace on a second same-shape call (jit cache "
                    f"{before} -> {after}): an argument leaks identity "
                    "into the trace key"
                ),
            ))

    # TC102 (f64 scan) and TC105 (donation) both read the lowered text.
    expected_donated = entry_data.DONATION_CONTRACTS.get(contract.name, 0)
    check_donation = "TC105" not in disabled and expected_donated > 0
    need_text = check_donation or (
        "TC102" not in disabled and not jax.config.jax_enable_x64
    )
    if need_text:
        text = jitted.lower(*make_args()).as_text()
        if "TC102" not in disabled and not jax.config.jax_enable_x64:
            out.extend(scan_lowered_text(text, path))
        if check_donation:
            n_aliased = text.count(_ALIAS_ATTR)
            if n_aliased < expected_donated:
                out.append(Finding(
                    rule="TC105", path=path, line=0, col=0,
                    message=(
                        f"lowered program aliases {n_aliased} donated "
                        f"input(s), expected >= {expected_donated}: a "
                        "rollout/step carry went copy-in/copy-out (an "
                        "output's shape/dtype no longer matches its "
                        "donated input?)"
                    ),
                ))

    # TC103 needs the jaxpr (see _CALLBACK_PRIMS); TC104 walks it too.
    check_callbacks = ("TC103" not in disabled
                       and not contract.allow_callbacks)
    tile_waived = (
        "TC104" in disabled
        or entry_data.TILE_WAIVERS.get(contract.name) is not None
    )
    if check_callbacks or not tile_waived:
        jaxpr = jax.make_jaxpr(fn)(*make_args())

    if check_callbacks:
        cbs = callback_primitives(jaxpr)
        if cbs:
            out.append(Finding(
                rule="TC103", path=path, line=0, col=0,
                message=f"hot path contains host callback primitive(s) "
                f"{', '.join(sorted(set(cbs)))} "
                "(pure_callback/io_callback round-trip every step)",
            ))

    # TC104: sublane alignment of long dot_general contractions (ENFORCED;
    # waivable per entry). See misaligned_contractions for the rule.
    if not tile_waived:
        bad = misaligned_contractions(jaxpr.jaxpr)
        if bad:
            uniq = sorted(set(bad))[:6]
            out.append(Finding(
                rule="TC104", path=path, line=0, col=0,
                message=(
                    f"{len(bad)} dot_general contraction(s) over a long "
                    f"misaligned dim (>= {MIN_ALIGNED_CONTRACT}, not a "
                    f"multiple of {SUBLANE}), e.g. {', '.join(uniq)}; pad "
                    "the operator edge (ops/socp.py pad_qp tier) or add "
                    "an entrypoints.TILE_WAIVERS entry with a reason"
                ),
            ))
    return out


def misaligned_contractions(jaxpr) -> list[str]:
    """TC104 core, factored out for unit tests: every ``dot_general``
    contracting dim of length >= :data:`MIN_ALIGNED_CONTRACT` that is not a
    :data:`SUBLANE` multiple, rendered as ``"shape@dim"`` strings. Batch
    and free dims are NOT checked: leading batch dims are the folded
    lane axis (the 128-lane tile comes from batching at the operating
    point), and short free dims ride along for free in a lane-parallel
    contraction."""
    bad: list[str] = []
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        (lhs_c, rhs_c), _ = eqn.params["dimension_numbers"]
        for v, cdims in zip(eqn.invars, (lhs_c, rhs_c)):
            shape = getattr(v.aval, "shape", ())
            for cd in cdims:
                size = shape[cd]
                if size >= MIN_ALIGNED_CONTRACT and size % SUBLANE:
                    bad.append(f"{tuple(shape)}@{cd}")
    return bad


def run_contracts(names=None,
                  disabled: frozenset[str] = frozenset()) -> list[Finding]:
    """Run contracts for ``names`` (default: the whole registry)."""
    selected = names if names is not None else sorted(REGISTRY)
    out: list[Finding] = []
    for name in selected:
        out.extend(check_entry(REGISTRY[name], disabled))
    return out


# ----------------------------------------------------------------------
# TC106: off-chip target lowering gate (jax.export, no device needed).
# ----------------------------------------------------------------------

def lower_for_target(fn, make_args, target: str = "tpu") -> str:
    """AOT-lower an entrypoint for ``target`` and return the StableHLO
    text. ``jax.export`` lowers with a platform *specification*, so a
    CPU-only host can produce (and inspect) the TPU-target program;
    lowering failures propagate to the caller."""
    from jax import export as jax_export

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return jax_export.export(jitted, platforms=[target])(
        *make_args()
    ).mlir_module()


def check_entry_lowering(contract: Contract, target: str = "tpu",
                         disabled: frozenset[str] = frozenset(),
                         ) -> list[Finding]:
    """TC106 for one entry: the ``target`` lowering must succeed off-chip
    and must contain no f64 tensor types. A failure is classified through
    the backend-error taxonomy (``resilience.backend.classify``) so the
    finding names the failure class a chip would have hit at dispatch."""
    if "TC106" in disabled:
        return []
    path = f"contracts:{contract.name}"
    if jax.device_count() < contract.min_devices:
        return []  # environment cannot build this entry; not a finding.
    if entry_data.LOWERING_WAIVERS.get(contract.name) is not None:
        return []
    fn, make_args = contract.build()
    try:
        text = lower_for_target(fn, make_args, target)
    except Exception as e:  # noqa: BLE001 — ANY lowering failure is the
        # finding this gate exists for.
        from tpu_aerial_transport.resilience import backend as backend_mod

        kind = backend_mod.classify(e)
        return [Finding(
            rule="TC106", path=path, line=0, col=0,
            message=(
                f"AOT lowering for target '{target}' failed "
                f"[{kind}]: {type(e).__name__}: {str(e)[:200]} — an "
                "r02-class bug that would otherwise surface only at "
                "first dispatch on a chip"
            ),
        )]
    n = len(_F64_RE.findall(text))
    if n:
        return [Finding(
            rule="TC106", path=path, line=0, col=0,
            message=(
                f"{target}-target StableHLO contains {n} f64 tensor "
                "type(s): the program would pay convert_element_type "
                "churn (or die) on the accelerator — the BENCH_r02 "
                "failure class"
            ),
        )]
    return []


def run_lowering_gate(names=None, target: str = "tpu",
                      disabled: frozenset[str] = frozenset(),
                      ) -> list[Finding]:
    """TC106 over ``names`` (default: the whole registry)."""
    selected = names if names is not None else sorted(REGISTRY)
    out: list[Finding] = []
    for name in selected:
        out.extend(check_entry_lowering(REGISTRY[name], target, disabled))
    return out
