"""Tier-A lint driver: file discovery, entrypoint-table lookup, rule
execution, and output formatting. Stdlib-only (no jax import — see
rules.py module docstring); loadable by file path from tools/jaxlint.py.
"""

from __future__ import annotations

import json
import os
import sys

if __package__:
    from tpu_aerial_transport.analysis import entrypoints as _entry
    from tpu_aerial_transport.analysis import hostrules as _host
    from tpu_aerial_transport.analysis import rules as _rules
else:  # loaded by file path (tools/jaxlint.py) — sibling modules on sys.path.
    import entrypoints as _entry  # type: ignore
    import hostrules as _host  # type: ignore
    import rules as _rules  # type: ignore

Finding = _rules.Finding
RULES = _rules.RULES
RULE_DOCS = _rules.RULE_DOCS


def _posix(path: str) -> str:
    return path.replace(os.sep, "/")


def entry_names_for(path: str) -> frozenset[str]:
    """Traced-function seeds for a file, matched by path suffix. The path
    is made absolute first so relative invocations (e.g. linting
    ``control/cadmm.py`` from inside the package dir) still resolve their
    entrypoint seeds instead of silently analyzing without them."""
    p = _posix(os.path.abspath(path))
    for suffix, names in _entry.TRACED_FUNCTIONS.items():
        if p.endswith(suffix):
            return frozenset(names)
    return frozenset()


def iter_py_files(paths: list[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        else:
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in {"__pycache__", ".git", ".pytest_cache"}
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_file(path: str,
              disabled: frozenset[str] = frozenset()) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    try:
        ctx = _rules.ModuleContext(path, source, entry_names_for(path))
    except SyntaxError as e:
        return [Finding(
            rule="JL000", path=path, line=e.lineno or 0, col=e.offset or 0,
            message=f"syntax error: {e.msg}",
        )]
    return _rules.run_rules(ctx, disabled)


def lint_paths(paths: list[str],
               disabled: frozenset[str] = frozenset()) -> list[Finding]:
    out: list[Finding] = []
    for f in iter_py_files(paths):
        out.extend(lint_file(f, disabled))
    return out


def module_context(path: str) -> "_rules.ModuleContext":
    """Parse one file with the standard entrypoint seeding (test helper)."""
    with open(path, encoding="utf-8") as fh:
        return _rules.ModuleContext(path, fh.read(), entry_names_for(path))


def public_hot_functions(paths: list[str]) -> dict[str, str]:
    """``{"pkg/mod.py:func": "scan|while_loop|fori_loop"}`` for every
    PUBLIC module-level function lexically containing a hot loop — the
    live universe the Tier-B registry-coverage test checks against."""
    import ast

    out: dict[str, str] = {}
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        for node in tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name.startswith("_"):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _rules._terminal_name(sub.func)
                    if name in ("scan", "while_loop", "fori_loop"):
                        out[f"{_posix(path)}:{node.name}"] = name
                        break
            else:
                continue
    return out


def render_text(findings: list[Finding]) -> str:
    if not findings:
        return "jaxlint: clean (0 findings)"
    lines = [f.render() for f in findings]
    n_err = sum(f.severity == "error" for f in findings)
    n_warn = len(findings) - n_err
    lines.append(f"jaxlint: {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)


def render_json(findings: list[Finding],
                rules: list[str] | None = None) -> str:
    return json.dumps(
        {
            "findings": [f.to_dict() for f in findings],
            "errors": sum(f.severity == "error" for f in findings),
            "warnings": sum(f.severity == "warn" for f in findings),
            "rules": sorted(RULES) if rules is None else sorted(rules),
        },
        indent=2,
    )


def main(argv: list[str] | None = None) -> int:
    """CLI body shared by tools/jaxlint.py (which execs this by path)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="jit-safety / trace-contract analyzer (Tier A: pure "
        "AST, no jax import; Tier B via --contracts).",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule ids to skip (e.g. JL003,JL011)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--contracts", action="store_true",
                    help="also run Tier-B trace contracts (imports jax)")
    ap.add_argument("--host", action="store_true",
                    help="run Tier C (hostlint, HL rules) over the host "
                    "scan set — serving/, resilience/, obs/, "
                    "parallel/pods.py, tools/ — instead of Tier A "
                    "(pure AST, no jax import either)")
    ap.add_argument("--target", choices=("tpu", "cpu"), default=None,
                    help="ALSO AOT-lower every registered entrypoint for "
                    "this target (jax.export — no device needed) and run "
                    "the TC106 lowering contract; catches r02-class "
                    "dtype/lowering bugs on any host (implies Tier B)")
    ap.add_argument("--only", default="",
                    help="comma-separated entrypoint names restricting "
                    "--contracts/--target to a subset of the registry")
    ap.add_argument("--assert-no-jax", action="store_true",
                    help="exit 2 if jax was imported by the Tier-A run "
                    "(self-check used by the test suite)")
    ap.add_argument("--strict-warn", action="store_true",
                    help="exit nonzero on warnings too")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted({**RULE_DOCS, **_host.HOST_RULE_DOCS}):
            docs = RULE_DOCS if rid in RULE_DOCS else _host.HOST_RULE_DOCS
            print(f"{rid}  {docs[rid]}")
        return 0

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    disabled = frozenset(
        s.strip() for s in args.disable.split(",") if s.strip()
    )
    if args.host:
        paths = args.paths or _host.host_paths(os.path.dirname(pkg_root))
        findings = _host.lint_host_files(
            list(iter_py_files(paths)), disabled
        )
    else:
        paths = args.paths or [pkg_root]
        findings = lint_paths(paths, disabled)

    if args.contracts or args.target:
        sys.path.insert(0, os.path.dirname(pkg_root))
        from tpu_aerial_transport.analysis import contracts

        only = [s.strip() for s in args.only.split(",") if s.strip()] \
            or None
        if only:
            unknown = [n for n in only if n not in contracts.REGISTRY]
            if unknown:
                print(f"jaxlint: unknown --only entrypoint(s) {unknown}",
                      file=sys.stderr)
                return 1
        if args.contracts:
            findings.extend(
                contracts.run_contracts(names=only, disabled=disabled)
            )
        if args.target:
            findings.extend(contracts.run_lowering_gate(
                names=only, target=args.target, disabled=disabled
            ))

    json_rules = sorted(_host.HOST_RULES) if args.host else None
    print(render_json(findings, rules=json_rules)
          if args.format == "json" else render_text(findings))

    if args.assert_no_jax and "jax" in sys.modules:
        print("jaxlint: FAIL — Tier A imported jax", file=sys.stderr)
        return 2
    n_err = sum(f.severity == "error" for f in findings)
    n_warn = len(findings) - n_err
    if n_err or (args.strict_warn and n_warn):
        return 1
    return 0
