"""The package's jit surface, as pure data (NO jax import — Tier A and the
CLI load this by file path).

Three tables:

- :data:`TRACED_FUNCTIONS`: per-module names whose bodies run under a jax
  trace when the system is in use (callers jit them, or they are called
  from jitted rollouts). Tier A seeds its traced-context inference with
  these — cross-module call graphs are invisible to a per-file AST pass,
  so the hot surface is declared here instead.
- :data:`CONTRACT_ENTRYPOINTS`: the public jitted entrypoints that MUST
  have a Tier-B contract in ``analysis.contracts.REGISTRY``. The
  registry-coverage test (tests/test_jaxlint.py) fails when a new public
  hot function (one containing lax.scan/while_loop) appears in the package
  without either a registry entry or an entry in
  :data:`HOT_NON_ENTRYPOINTS`.
- :data:`HOT_NON_ENTRYPOINTS`: public functions that contain hot loops but
  are deliberately not contract entrypoints, each with a reason.

Keys are POSIX path suffixes relative to the repo root.
"""

from __future__ import annotations

TRACED_FUNCTIONS: dict[str, tuple[str, ...]] = {
    "tpu_aerial_transport/control/centralized.py": (
        "control", "equilibrium_forces", "smooth_block",
    ),
    "tpu_aerial_transport/control/cadmm.py": ("control",),
    "tpu_aerial_transport/control/dd.py": ("control",),
    "tpu_aerial_transport/control/rp_cadmm.py": ("control",),
    "tpu_aerial_transport/control/rp_centralized.py": (
        "control", "equilibrium_forces",
    ),
    "tpu_aerial_transport/control/pmrl_centralized.py": (
        "control", "equilibrium_forces",
    ),
    "tpu_aerial_transport/control/lowlevel.py": ("lowlevel_control",),
    "tpu_aerial_transport/control/so3_tracking.py": (
        "so3_pd_tracking_control", "so3_sm_tracking_control",
    ),
    "tpu_aerial_transport/ops/socp.py": (
        "solve_socp", "solve_socp_padded", "pad_qp", "pad_warm",
        "unpad_solution", "padded_kkt_operator",
    ),
    "tpu_aerial_transport/ops/lie.py": (
        "hat", "hat_square", "expm_so3", "log_so3", "polar_project",
        "polar_project_svd", "rotation_from_z", "rotation_a_to_b",
    ),
    "tpu_aerial_transport/ops/admm_kernel.py": (
        "admm_chunk_lanes", "fused_solve_lanes",
    ),
    "tpu_aerial_transport/models/rqp.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/models/rp.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/models/pmrl.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/envs/forest.py": (
        "ground_height", "braking_capsule", "capsule_forest_distance",
        "capsule_distance_data", "cbf_rows_from_distance",
        "vision_cone_mask", "cone_mask_at",
        "point_cylinder_distance", "segment_cylinder_distance",
        "collision_cbf_rows",
    ),
    "tpu_aerial_transport/envs/spatial.py": (
        "candidate_slab", "bucketed_distance", "env_query_bucketed",
        "env_query_dense",
    ),
    "tpu_aerial_transport/harness/rollout.py": ("rollout",),
    "tpu_aerial_transport/harness/diff.py": (
        "substep_rollout", "payload_pd_forces", "simulate_commands",
        "plan_share_forces",
    ),
    "tpu_aerial_transport/resilience/rollout.py": ("resilient_rollout",),
    "tpu_aerial_transport/resilience/faults.py": (
        "fault_step", "apply_sensor_noise",
    ),
    "tpu_aerial_transport/resilience/quarantine.py": (
        "tree_all_finite", "tree_where",
    ),
    "tpu_aerial_transport/obs/telemetry.py": ("update", "_p2_update"),
    "tpu_aerial_transport/parallel/ring.py": (
        "consensus_exchange", "consensus_gather", "_ring_allreduce_sum",
        "_rotate_allreduce", "_ring_gather", "_pallas_ring_allreduce",
        "_ring_sum_kernel",
    ),
    "tpu_aerial_transport/parallel/pods.py": (
        "pods_control_step", "_physics_substeps",
    ),
    "tpu_aerial_transport/serving/lanes.py": ("lane_surgery",),
}

# name -> short description; analysis.contracts.REGISTRY must carry
# exactly this key set (asserted by tests/test_jaxlint.py).
CONTRACT_ENTRYPOINTS: dict[str, str] = {
    "control.centralized:control": "centralized SOCP+CBF control step",
    "control.cadmm:control": "C-ADMM consensus control step (Schur path)",
    "control.cadmm:control_forest": "C-ADMM step with env CBF rows active",
    "control.dd:control": "dual-decomposition control step",
    "control.rp_cadmm:control": "RP consensus-ADMM control step",
    "control.rp_centralized:control": "RP centralized QP control step",
    "control.pmrl_centralized:control": "PMRL centralized control step",
    "ops.socp:solve_socp": "batched conic-QP solve (scan path)",
    "ops.socp:solve_socp_padded":
        "tile-aligned conic-QP solve (padded-operator tier)",
    "ops.admm_kernel:solve_socp_interpret":
        "fused ADMM chunk kernel (Pallas, interpret mode)",
    "ops.admm_kernel:fused_solve_interpret":
        "whole-solve ADMM mega-kernel through solve_socp_padded "
        "(fused='kernel_interpret': w2 build + iterations + residual "
        "reduction in one pallas_call, interpret mode — the bitwise-vs-"
        "scan twin; TC104-enforced on the padded tier)",
    "ops.admm_kernel:fused_solve_pallas":
        "whole-solve ADMM mega-kernel, compiled broadcast-reduce form "
        "(fused_solve_lanes interpret=False — TPU-only execution; TC106 "
        "off-chip jax.export lowering ENFORCED, no waiver: the compiled "
        "form AOT-lowers cleanly for the tpu target on this image)",
    "ops.admm_kernel:fused_solve_earlyexit_interpret":
        "in-kernel early-exit mega-kernel through solve_socp_padded "
        "(fused='kernel_interpret' + check_every/tol: per-lane converged "
        "freezing, whole-grid-cell loop exit, and the effective-"
        "iteration report in ONE pallas_call, interpret mode — the "
        "bitwise-vs-scan twin of the tolerance-chunked path; "
        "TC104-enforced on the padded tier)",
    "ops.admm_kernel:fused_solve_earlyexit_pallas":
        "in-kernel early-exit mega-kernel, compiled broadcast-reduce "
        "form with the scf.while chunk loop + consensus-effort gate "
        "input (fused_solve_lanes check_every/tol/active, "
        "interpret=False — TPU-only execution; TC106 off-chip jax.export "
        "lowering ENFORCED, no waiver: the while-loop form AOT-lowers "
        "cleanly for the tpu target on this image — the PR-12 "
        "precedent)",
    "control.cadmm:control_adaptive":
        "C-ADMM consensus control step with effort='adaptive' "
        "(socp.resolve_effort): tolerance-chunked early-exit inner "
        "solves gated by the consensus loop's own per-lane converged "
        "state, SolverStats.inner_iters effort accounting",
    "control.dd:control_adaptive":
        "dual-decomposition control step with effort='adaptive' (the "
        "cadmm twin: gated early-exit inner solves + effort accounting)",
    "harness.rollout:rollout": "nominal two-rate receding-horizon rollout",
    "harness.rollout:rollout_donated":
        "donation-clean jitted rollout (carries updated in place)",
    "harness.rollout:chunked_rollout":
        "chunk-resumable rollout: ONE compiled chunk reused for all C "
        "chunks (crash-recovery tier)",
    "resilience.rollout:resilient_rollout":
        "fault-injected rollout with fallback ladder + quarantine",
    "resilience.rollout:resilient_rollout_donated":
        "donation-clean jitted fault-injected rollout",
    "harness.rollout:rollout_telemetry":
        "rollout with the in-jit run-health telemetry accumulator on the "
        "scan carry (obs.telemetry)",
    "resilience.rollout:resilient_rollout_telemetry":
        "fault-injected rollout with telemetry + per-agent solve health "
        "(track_agent_stats)",
    "parallel.mesh:cadmm_control_sharded":
        "agent-sharded C-ADMM step (shard_map + psum/pmax)",
    "parallel.mesh:cadmm_control_sharded_ring":
        "agent-sharded C-ADMM step with the ppermute ring consensus "
        "exchange (parallel.ring, consensus_impl='ring')",
    "parallel.ring:consensus_exchange":
        "ring-collective consensus exchange under shard_map (sum/max + "
        "gather, impl='ring')",
    "parallel.ring:consensus_exchange_pallas":
        "async remote-DMA Pallas TPU ring exchange (impl='pallas_ring'; "
        "chip-only — see LOWERING_WAIVERS)",
    "parallel.mesh:scenario_rollout":
        "scenario-sharded Monte-Carlo batch rollout",
    "serving.batcher:serving_chunk":
        "continuous-batching serving chunk (canonical cadmm family): the "
        "PR-4 chunked rollout vmapped over a bucketed lane axis — the "
        "serving tier's compiled/bundled admission surface",
    "serving.batcher:serving_chunk_centralized":
        "serving chunk for the canonical centralized family (the mixed-"
        "stream twin of serving_chunk)",
    "serving.lanes:lane_surgery":
        "on-device boundary lane surgery (canonical cadmm family): "
        "harvest-read + filler-reset + late-join select program over the "
        "batched boundary carry, carry donated — the device-surgery "
        "serving knob's compiled/bundled boundary surface",
    "serving.lanes:lane_surgery_centralized":
        "boundary lane surgery for the canonical centralized family "
        "(same select program; per-family entry because the carry "
        "pytree/signature differs per controller)",
    "envs.spatial:env_query_bucketed":
        "spatial-hash bucketed environment query: grid-cell candidate-"
        "slab gather + the exact dense per-tree capsule sweep over "
        "candidates only, through collision CBF row construction — the "
        "city-scale (10^4-10^6 obstacle) world tier "
        "(envs/spatial.py; TC104 enforced on the 8-aligned slab edges, "
        "TC106 off-chip TPU lowering enforced — gather + the existing "
        "sweep math, no waiver)",
    "envs.spatial:env_query_dense":
        "the dense O(max_trees) environment query under the same "
        "entrypoint surface (envs.spatial.env_query_dense -> "
        "forest.capsule_forest_distance) — the bucketed tier's "
        "byte-identical-HLO baseline twin",
    "parallel.pods:pods_control_step":
        "2-D (scenario, agent) pods-mesh C-ADMM control step: scenarios "
        "vmapped per shard, consensus over the agent axis, batch stats "
        "over the scenario axis — the multi-process scale-out tier "
        "(parallel/pods.py; exercised single-process on the 2x4 virtual "
        "mesh, multi-process by tools/pods_local.py)",
}

# Public functions containing lax.scan / lax.while_loop / lax.fori_loop
# that are NOT contract entrypoints, with the reason they are exempt. The
# coverage test computes the live set of public hot functions from the AST
# and requires each to appear either here or (via its module) in a
# REGISTRY entry — a new hot entrypoint therefore cannot land unregistered.
HOT_NON_ENTRYPOINTS: dict[str, str] = {
    "tpu_aerial_transport/envs/forest.py:segment_cylinder_distance":
        "geometry kernel exercised inside every forest-coupled control "
        "contract (capsule sweep)",
    "tpu_aerial_transport/ops/lie.py:polar_project":
        "fixed-iteration Newton polar decomposition; exercised inside "
        "every integrate() call of the rollout contracts",
    "tpu_aerial_transport/harness/diff.py:substep_rollout":
        "differentiable-rollout research harness; tier-1 covers it via "
        "test_diff.py, not a hot serving path",
    "tpu_aerial_transport/harness/diff.py:make_rollout_loss":
        "loss factory over substep_rollout (see above)",
    "tpu_aerial_transport/harness/diff.py:simulate_commands":
        "sysid data generator, offline tooling",
    "tpu_aerial_transport/harness/diff.py:make_trajopt_loss":
        "trajectory-optimization research harness, offline tooling",
    "tpu_aerial_transport/harness/diff.py:tune_gains":
        "host-side Adam loop around a jitted loss, not itself traced",
    "tpu_aerial_transport/parallel/pods.py:make_pods_workload":
        "benchmark-workload factory over pods_control_step (the scan is "
        "the step rollout driver for tools/pods_local.py / bench pods_* "
        "cells); the 2-D sharded step inside carries the contract",
}

# Tier-B tile waivers: entrypoint name -> reason TC104 (sublane alignment
# of long dot contractions; analysis/contracts.py) is NOT enforced there.
# TC104 is a FAILING contract since the padded-operator tier landed
# (ops/socp.py pad_qp; the consensus controllers and the padded solve run
# tile-aligned and are enforced — they carry NO waiver). Waivers remain
# only for the genuinely tiny/deliberately-unpadded programs below; a new
# heavy entrypoint must either run on padded operators or add a row here
# with a reason.
TILE_WAIVERS: dict[str, str] = {
    "control.centralized:control":
        "single (9+3n)-var QP, one solve per step: padding the one-off "
        "operator buys nothing measurable; the consensus hot paths are "
        "the enforced ones",
    "control.rp_cadmm:control": "per-agent (6+3n)-var QPs; one consensus "
        "family, unpadded until it becomes a bench workload",
    "control.rp_centralized:control": "single (6+3n)-var QP; sub-tile",
    "control.pmrl_centralized:control": "single QP; sub-tile",
    "ops.socp:solve_socp": "the UNPADDED reference tier, kept for ad-hoc "
        "problems and the padded-vs-unpadded parity tests; hot callers go "
        "through pad_qp/solve_socp_padded (enforced)",
    "harness.rollout:rollout": "drives the centralized controller (waived "
        "above); 3-vector rigid-body physics otherwise",
    "harness.rollout:rollout_donated": "same program as harness.rollout",
    "harness.rollout:chunked_rollout":
        "same per-step program as harness.rollout, split into chunks",
    "harness.rollout:rollout_telemetry":
        "same program as harness.rollout plus the telemetry accumulator "
        "(elementwise P2/histogram updates; no long contractions)",
    "parallel.mesh:scenario_rollout":
        "scenario axis is data-parallel over the centralized-controller "
        "rollout; per-lane ops are 3-vectors",
    "serving.batcher:serving_chunk_centralized":
        "lanes are data-parallel over the centralized controller (waived "
        "above); the cadmm serving_chunk twin runs padded and is enforced",
}

# TC106 lowering waivers: entrypoint name -> reason the off-chip
# TPU-target lowering gate (analysis/contracts.py run_lowering_gate;
# ``tools/jaxlint.py --contracts --target tpu``) is NOT enforced there.
# Every OTHER registered entrypoint AOT-lowers cleanly for the TPU
# target on a CPU-only host (~35 s for the whole registry). A new
# entrypoint that genuinely cannot lower off-chip (e.g. a kernel needing
# a real device topology at trace time) must add a row here with a
# reason rather than silently shrinking the gate.
LOWERING_WAIVERS: dict[str, str] = {
    "parallel.ring:consensus_exchange_pallas":
        "jax.export cannot AOT-lower the Mosaic remote-DMA primitives "
        "off-chip on jax 0.4.37: export of the kernel dies in "
        "LoweringException at `semaphore_signal` (the neighbor barrier) "
        "and, with the barrier removed, at `dma_start` "
        "(make_async_remote_copy) — measured on this image with a "
        "4-virtual-device CPU mesh. The kernel is exercised on a real "
        "chip by the bench sweep's *_sharded_pallas_ring A/B cells; the "
        "XLA ring twin (parallel.ring:consensus_exchange) carries the "
        "off-chip TC106 coverage for the exchange program structure.",
}

# TC105 donation contracts: entrypoint -> MINIMUM number of donated
# (input-output aliased) arguments the lowered program must report. The
# counts are the physics-state leaf count (6: xl, vl, Rl, wl, R, w) — the
# floor every rollout carry must alias; controller-state leaves alias on
# top of it. analysis/contracts.py counts `tf.aliasing_output` attrs in
# the lowered StableHLO.
DONATION_CONTRACTS: dict[str, int] = {
    "harness.rollout:rollout_donated": 6,
    "harness.rollout:chunked_rollout": 6,
    "resilience.rollout:resilient_rollout_donated": 6,
    "parallel.mesh:scenario_rollout": 6,
    # The serving boundary carry: its scenario state holds the same six
    # physics leaves, batched over lanes.
    "serving.lanes:lane_surgery": 6,
    "serving.lanes:lane_surgery_centralized": 6,
}
