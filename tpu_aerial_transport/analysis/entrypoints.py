"""The package's jit surface, as pure data (NO jax import — Tier A and the
CLI load this by file path).

Three tables:

- :data:`TRACED_FUNCTIONS`: per-module names whose bodies run under a jax
  trace when the system is in use (callers jit them, or they are called
  from jitted rollouts). Tier A seeds its traced-context inference with
  these — cross-module call graphs are invisible to a per-file AST pass,
  so the hot surface is declared here instead.
- :data:`CONTRACT_ENTRYPOINTS`: the public jitted entrypoints that MUST
  have a Tier-B contract in ``analysis.contracts.REGISTRY``. The
  registry-coverage test (tests/test_jaxlint.py) fails when a new public
  hot function (one containing lax.scan/while_loop) appears in the package
  without either a registry entry or an entry in
  :data:`HOT_NON_ENTRYPOINTS`.
- :data:`HOT_NON_ENTRYPOINTS`: public functions that contain hot loops but
  are deliberately not contract entrypoints, each with a reason.

Keys are POSIX path suffixes relative to the repo root.
"""

from __future__ import annotations

TRACED_FUNCTIONS: dict[str, tuple[str, ...]] = {
    "tpu_aerial_transport/control/centralized.py": (
        "control", "equilibrium_forces", "smooth_block",
    ),
    "tpu_aerial_transport/control/cadmm.py": ("control",),
    "tpu_aerial_transport/control/dd.py": ("control",),
    "tpu_aerial_transport/control/rp_cadmm.py": ("control",),
    "tpu_aerial_transport/control/rp_centralized.py": (
        "control", "equilibrium_forces",
    ),
    "tpu_aerial_transport/control/pmrl_centralized.py": (
        "control", "equilibrium_forces",
    ),
    "tpu_aerial_transport/control/lowlevel.py": ("lowlevel_control",),
    "tpu_aerial_transport/control/so3_tracking.py": (
        "so3_pd_tracking_control", "so3_sm_tracking_control",
    ),
    "tpu_aerial_transport/ops/socp.py": ("solve_socp",),
    "tpu_aerial_transport/ops/lie.py": (
        "hat", "hat_square", "expm_so3", "log_so3", "polar_project",
        "polar_project_svd", "rotation_from_z", "rotation_a_to_b",
    ),
    "tpu_aerial_transport/ops/admm_kernel.py": ("admm_chunk_lanes",),
    "tpu_aerial_transport/models/rqp.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/models/rp.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/models/pmrl.py": (
        "forward_dynamics", "integrate_state", "integrate",
    ),
    "tpu_aerial_transport/envs/forest.py": (
        "ground_height", "braking_capsule", "capsule_forest_distance",
        "cbf_rows_from_distance", "vision_cone_mask",
        "point_cylinder_distance", "segment_cylinder_distance",
        "collision_cbf_rows",
    ),
    "tpu_aerial_transport/harness/rollout.py": ("rollout",),
    "tpu_aerial_transport/harness/diff.py": (
        "substep_rollout", "payload_pd_forces", "simulate_commands",
        "plan_share_forces",
    ),
    "tpu_aerial_transport/resilience/rollout.py": ("resilient_rollout",),
    "tpu_aerial_transport/resilience/faults.py": (
        "fault_step", "apply_sensor_noise",
    ),
    "tpu_aerial_transport/resilience/quarantine.py": (
        "tree_all_finite", "tree_where",
    ),
}

# name -> short description; analysis.contracts.REGISTRY must carry
# exactly this key set (asserted by tests/test_jaxlint.py).
CONTRACT_ENTRYPOINTS: dict[str, str] = {
    "control.centralized:control": "centralized SOCP+CBF control step",
    "control.cadmm:control": "C-ADMM consensus control step (Schur path)",
    "control.cadmm:control_forest": "C-ADMM step with env CBF rows active",
    "control.dd:control": "dual-decomposition control step",
    "control.rp_cadmm:control": "RP consensus-ADMM control step",
    "control.rp_centralized:control": "RP centralized QP control step",
    "control.pmrl_centralized:control": "PMRL centralized control step",
    "ops.socp:solve_socp": "batched conic-QP solve (scan path)",
    "ops.admm_kernel:solve_socp_interpret":
        "fused ADMM chunk kernel (Pallas, interpret mode)",
    "harness.rollout:rollout": "nominal two-rate receding-horizon rollout",
    "resilience.rollout:resilient_rollout":
        "fault-injected rollout with fallback ladder + quarantine",
    "parallel.mesh:cadmm_control_sharded":
        "agent-sharded C-ADMM step (shard_map + psum/pmax)",
    "parallel.mesh:scenario_rollout":
        "scenario-sharded Monte-Carlo batch rollout",
}

# Public functions containing lax.scan / lax.while_loop / lax.fori_loop
# that are NOT contract entrypoints, with the reason they are exempt. The
# coverage test computes the live set of public hot functions from the AST
# and requires each to appear either here or (via its module) in a
# REGISTRY entry — a new hot entrypoint therefore cannot land unregistered.
HOT_NON_ENTRYPOINTS: dict[str, str] = {
    "tpu_aerial_transport/envs/forest.py:segment_cylinder_distance":
        "geometry kernel exercised inside every forest-coupled control "
        "contract (capsule sweep)",
    "tpu_aerial_transport/ops/lie.py:polar_project":
        "fixed-iteration Newton polar decomposition; exercised inside "
        "every integrate() call of the rollout contracts",
    "tpu_aerial_transport/harness/diff.py:substep_rollout":
        "differentiable-rollout research harness; tier-1 covers it via "
        "test_diff.py, not a hot serving path",
    "tpu_aerial_transport/harness/diff.py:make_rollout_loss":
        "loss factory over substep_rollout (see above)",
    "tpu_aerial_transport/harness/diff.py:simulate_commands":
        "sysid data generator, offline tooling",
    "tpu_aerial_transport/harness/diff.py:make_trajopt_loss":
        "trajectory-optimization research harness, offline tooling",
    "tpu_aerial_transport/harness/diff.py:tune_gains":
        "host-side Adam loop around a jitted loss, not itself traced",
}

# Tier-B tile-shape waivers: entrypoint name -> reason the (8, 128) TPU
# tile-alignment warning is accepted. The physics is n-agent-by-3-vector
# shaped; the MXU-relevant operands are the solver's KKT operators, whose
# padding strategy is tracked in ROADMAP open items rather than forced
# onto every 3-vector op.
TILE_WAIVERS: dict[str, str] = {
    "control.centralized:control":
        "QP dims (9+3n, m) are problem-defined; padding tracked in ROADMAP",
    "control.cadmm:control": "per-agent 12-var Schur QPs; sub-tile by design",
    "control.cadmm:control_forest": "same operands as control.cadmm:control",
    "control.dd:control": "per-agent QPs + 6n dual system; sub-tile by design",
    "control.rp_cadmm:control": "per-agent (6+3n)-var QPs; sub-tile",
    "control.rp_centralized:control": "single (6+3n)-var QP; sub-tile",
    "control.pmrl_centralized:control": "single QP; sub-tile",
    "ops.socp:solve_socp": "KKT operator (nv+m)^2 < 128; fused via MXU matmul",
    "ops.admm_kernel:solve_socp_interpret":
        "kernel pads lanes to the sublane tile internally (_pad_lanes)",
    "harness.rollout:rollout": "3-vector rigid-body physics; no MXU operands",
    "resilience.rollout:resilient_rollout": "same as harness.rollout",
    "parallel.mesh:cadmm_control_sharded":
        "per-shard agent blocks; sub-tile by design",
    "parallel.mesh:scenario_rollout":
        "scenario axis is data-parallel; per-lane ops are 3-vectors",
}
