"""Tier C (hostlint): static analysis of the host-side concurrency,
durability, and observability contracts — rules HL001-HL010 over
``serving/``, ``resilience/``, ``obs/``, ``parallel/pods.py`` and
``tools/``.

Tier A guards the device side; this tier guards the concurrent host
Python around it, whose invariants were previously enforced only by
review. Each rule encodes one recurring post-review bug class:

- HL001 clock-domain mixing: deadlines/timeouts are anchored on
  ``time.monotonic`` by contract (wall clocks step under NTP and die
  across restarts); ``time.time()`` may only stamp record fields.
- HL002 span leak: every ``Tracer.begin`` needs an ``end`` that
  survives BaseException (try/finally or an ``except BaseException``
  re-raise) — the PR-15 harvest/snapshot-span bug class.
- HL003 blocking call under lock: fsync'd emits, file opens, sleeps,
  subprocess waits, and thread joins inside a ``with <lock>`` body
  serialize every other thread behind one slow syscall.
- HL004 lock-order cycle: two methods of a class acquiring the same
  locks in opposite orders (computed as a fixpoint over self-calls).
- HL005 jsonl durability bypass: ``obs.export.jsonl_append`` is THE
  fsync'd append primitive; a raw ``open(...).write`` to a ``*.jsonl``
  path silently drops the durability contract readers rely on.
- HL006 non-atomic artifact publish: published files are written
  temp + fsync + ``os.replace`` — a rename without fsync can publish
  an empty file after a crash; a direct write tears mid-crash.
- HL007 event-vocabulary drift: emitted ``kind=`` literals must exist
  in ``obs/export.py``'s kind tables and carry that kind's minimum
  keys — schema drift becomes lint-visible, not review-visible.
- HL008 unregistered knob: ``TAT_*``/``TPU_AERIAL_*`` env reads must
  be registered in ``analysis/knobs.py`` (name, owning resolver,
  documented default).
- HL009 subprocess hygiene: ``Popen`` without ``start_new_session``
  (group-kill) and an explicit ``stderr`` orphans children and wedges
  pipes — the pods_local/fleet_local discipline.
- HL010 truthiness gate on an observability/guard parameter: the
  zero-cost contract is ``is not None``; ``if tracer:`` or
  ``tracer is True`` lets a falsy-but-real (or truthy-but-wrong)
  sink slip through — the ``tracer=False`` pods-resume crash class.

Stdlib-only (never imports jax — asserted by tests/test_hostlint.py in
a subprocess) and loadable by file path from ``tools/jaxlint.py``.
Per-line ``# jaxlint: disable=HLxxx`` pragmas and ``# jaxlint:
skip-file`` work exactly as in Tier A. Intentional exceptions live in
:data:`HOST_WAIVERS` with a written reason; a waiver on a clean site
is itself an error (stale-waiver hygiene), as is a blank reason.
"""

from __future__ import annotations

import ast
import os

if __package__:
    from tpu_aerial_transport.analysis import hostflow as _flow
    from tpu_aerial_transport.analysis import knobs as _knobs
    from tpu_aerial_transport.analysis import rules as _rules
else:  # loaded by file path (tools/jaxlint.py) — siblings on sys.path.
    import hostflow as _flow  # type: ignore
    import knobs as _knobs  # type: ignore
    import rules as _rules  # type: ignore

Finding = _rules.Finding

HOST_RULE_DOCS = {
    "HL000": (
        "hostlint-meta: syntax error, stale waiver (a HOST_WAIVERS "
        "entry whose site no longer trips its rule), or a waiver with "
        "no written reason."
    ),
    "HL001": (
        "clock-domain-mixing: time.time() flowing into deadline/timeout "
        "arithmetic or compared against a time.monotonic() anchor. "
        "Deadlines are monotonic by contract; wall time only stamps "
        "record fields (trace rows carry BOTH)."
    ),
    "HL002": (
        "span-leak: a Tracer.begin(...) whose span is not end()-ed on "
        "every path including BaseException — use try/finally or an "
        "except BaseException re-raise (end() is idempotent, so a "
        "defensive close is free)."
    ),
    "HL003": (
        "blocking-under-lock: file I/O, subprocess work, sleeps, "
        "thread joins, or an fsync'd metrics emit inside a `with "
        "<lock>` body. Collect under the lock, emit after release."
    ),
    "HL004": (
        "lock-order-cycle: methods of one class acquire the same locks "
        "in opposite orders (self-call acquisition graph fixpoint) — "
        "two threads can deadlock."
    ),
    "HL005": (
        "jsonl-durability-bypass: writing a *.jsonl path with raw "
        "open()/json.dump instead of obs.export.jsonl_append, the ONE "
        "fsync'd append primitive (readers tolerate a torn tail only "
        "because every durable line was fsync'd)."
    ),
    "HL006": (
        "non-atomic-publish: artifact writes must be temp + fsync + "
        "os.replace. A rename without fsync can publish empty bytes "
        "after a crash; a direct artifacts/ write tears mid-crash."
    ),
    "HL007": (
        "event-vocabulary-drift: an emitted kind=\"...\" literal absent "
        "from obs/export.py's SERVING_EVENT_KINDS/FLEET_EVENT_KINDS, "
        "an unknown event type, or a call missing that kind's minimum "
        "keys at the current SCHEMA_VERSION."
    ),
    "HL008": (
        "unregistered-knob: an os.environ read of a TAT_*/TPU_AERIAL_* "
        "name not registered in analysis/knobs.py (name, owning "
        "resolver, documented default)."
    ),
    "HL009": (
        "subprocess-hygiene: Popen without start_new_session=True "
        "(group-kill discipline) or without an explicit stderr "
        "destination (an undrained pipe wedges chatty children; "
        "inherited stderr loses the post-mortem tail)."
    ),
    "HL010": (
        "truthiness-gated-observability: `if tracer:` / `tracer or "
        "...` / `tracer is True` on a tracer/telemetry/metrics/guard/"
        "emit/sink parameter. The zero-cost contract is `is not None` "
        "— truthiness lets tracer=False crash the first traced span."
    ),
}

# Per-site waivers: "<relpath>::<rule>::<enclosing-function>" -> reason.
# A key whose site no longer trips its rule is flagged HL000 (stale);
# a blank reason is flagged HL000 (un-reasoned). Keep reasons WRITTEN —
# they are the review record for why the contract bends here.
HOST_WAIVERS: dict[str, str] = {
    "tpu_aerial_transport/parallel/pods.py::HL010::pods_rollout_resumable": (
        "tracer is a tri-state convenience flag BY DESIGN here: True "
        "means 'wire a per-process tracer into the shared run dir', a "
        "Tracer instance passes through, and any falsy value is "
        "normalized to None at this boundary so the chunk driver's "
        "`is not None` zero-cost gate stays sound downstream. The "
        "`is True` / `not tracer` tests ARE the normalization."
    ),
    "tools/fleet_local.py::HL005::run_fleet": (
        "fleet.metrics.jsonl is a DERIVED merge written once at "
        "shutdown from the per-replica metrics files, each of which "
        "was already fsync'd line-by-line through jsonl_append. "
        "Re-fsyncing the merge per line buys nothing (it is fully "
        "reproducible from its durable inputs) and would add one "
        "fsync per event across the whole fleet to the drain path."
    ),
}

# The host-tier scan set (relative to the repo root). Directories are
# globbed recursively, so a NEW module under serving/resilience/obs is
# covered automatically — tests/test_hostlint.py fails if this tuple
# stops spanning those trees.
HOST_SCAN = (
    "tpu_aerial_transport/serving",
    "tpu_aerial_transport/resilience",
    "tpu_aerial_transport/obs",
    "tpu_aerial_transport/parallel/pods.py",
    "tools",
)

# File that owns the jsonl durability primitive (exempt from HL005) and
# the event vocabulary HL007 reads.
_EXPORT_RELPATH = "tpu_aerial_transport/obs/export.py"


def relpath_of(path: str) -> str:
    """Stable repo-relative posix path: slice at the last
    tpu_aerial_transport/tools/tests component so waiver keys do not
    depend on the invocation cwd."""
    p = os.path.abspath(path).replace(os.sep, "/")
    for anchor in ("/tpu_aerial_transport/", "/tools/", "/tests/"):
        idx = p.rfind(anchor)
        if idx >= 0:
            return p[idx + 1:]
    return os.path.basename(p)


def host_paths(repo_root: str) -> list[str]:
    """The default --host scan set (existing entries only)."""
    out = []
    for rel in HOST_SCAN:
        p = os.path.join(repo_root, rel)
        if os.path.exists(p):
            out.append(p)
    return out


class HostContext:
    """Parsed module + the bookkeeping every HL rule shares."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.relpath = relpath_of(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.skip_file = any(
            _rules._SKIP_FILE_RE.search(ln) for ln in self.lines[:10]
        )
        self.suppressed: dict[int, frozenset[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _rules._PRAGMA_RE.search(ln)
            if m:
                self.suppressed[i] = frozenset(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
        self.parents = _flow.attach_parents(self.tree)
        self.consts = _flow.module_str_consts(self.tree)
        self.waiver_hits: set[str] = set()

    def enclosing_name(self, node: ast.AST) -> str:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                return cur.name
            cur = self.parents.get(cur)
        return "<module>"

    def _function_name(self, node: ast.AST) -> str:
        cur = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.name
            cur = self.parents.get(cur)
        return "<module>"

    def is_suppressed(self, rule: str, line: int) -> bool:
        ids = self.suppressed.get(line)
        return ids is not None and (rule in ids or "all" in ids)

    def finding(self, rule: str, node: ast.AST, message: str,
                severity: str = "error") -> Finding | None:
        line = getattr(node, "lineno", 0)
        if self.is_suppressed(rule, line):
            return None
        key = f"{self.relpath}::{rule}::{self._function_name(node)}"
        if key in HOST_WAIVERS:
            self.waiver_hits.add(key)
            return None
        return Finding(
            rule=rule, path=self.path, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            context=self.enclosing_name(node), severity=severity,
        )


def _scopes(ctx: HostContext):
    """Every function plus the module body (as one pseudo-scope)."""
    yield ctx.tree
    yield from _flow.functions(ctx.tree)


def _own_nodes(scope: ast.AST):
    """Walk a scope WITHOUT descending into nested function scopes
    (module scope would otherwise re-report every function's nodes)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------- HL001 -----


def rule_hl001_clock_mixing(ctx: HostContext):
    out = []
    for scope in _scopes(ctx):
        domains = _flow.clock_domains(scope)
        for node in _own_nodes(scope):
            if isinstance(node, ast.Compare):
                doms = {
                    d for d in (
                        _flow.expr_domain(e, domains)
                        for e in [node.left] + node.comparators
                    ) if d
                }
                if "mixed" in doms or {"wall", "mono"} <= doms:
                    f = ctx.finding(
                        "HL001", node,
                        "wall-clock value compared against a monotonic "
                        "anchor — deadlines/timeouts are monotonic by "
                        "contract (NTP steps and restarts break wall "
                        "comparisons)",
                    )
                    if f:
                        out.append(f)
            elif isinstance(node, ast.BinOp):
                left = _flow.expr_domain(node.left, domains)
                right = _flow.expr_domain(node.right, domains)
                if left and right and left != right:
                    f = ctx.finding(
                        "HL001", node,
                        "arithmetic mixes the wall clock with the "
                        "monotonic domain — anchor deadline math on "
                        "time.monotonic(); wall time only stamps "
                        "record fields",
                    )
                    if f:
                        out.append(f)
            elif (isinstance(node, ast.Assign) and len(node.targets) == 1):
                tname = _flow.terminal(node.targets[0])
                if (tname is not None
                        and _flow._DEADLINE_NAME_RE.search(tname)
                        and _flow.expr_domain(node.value, domains)
                        == "wall"):
                    f = ctx.finding(
                        "HL001", node,
                        f"deadline/timeout '{tname}' anchored on the "
                        "wall clock (time.time()) — use the monotonic "
                        "clock so restarts/NTP cannot fire or starve it",
                    )
                    if f:
                        out.append(f)
    return out


# ---------------------------------------------------------- HL002 -----


def rule_hl002_span_leak(ctx: HostContext):
    out = []
    for func in _flow.functions(ctx.tree):
        for assign, var in _flow.span_begins(func):
            if _flow.var_escapes(func, var, assign):
                continue  # handed off — lifecycle owned elsewhere.
            if _flow.span_protected(func, var, ctx.parents):
                continue
            f = ctx.finding(
                "HL002", assign,
                f"span '{var}' from .begin(...) is not end()-ed on "
                "every path including BaseException — wrap in "
                "try/finally or add an `except BaseException` that "
                "ends it and re-raises (end() is idempotent)",
            )
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------- HL003 -----

_BLOCKING_NAME_CALLS = frozenset({"open", "sleep"})
_BLOCKING_TERMINALS = frozenset({
    "sleep", "fsync", "jsonl_append", "communicate", "Popen", "run",
    "check_call", "check_output", "block_until_ready", "device_put",
    "emit", "emit_fleet", "_emit", "_emit_serving",
})


def _is_blocking_call(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Name):
        if node.func.id in _BLOCKING_NAME_CALLS:
            return node.func.id
        return None
    term = _flow.terminal(node.func)
    d = _flow.dotted(node.func)
    if term in _BLOCKING_TERMINALS:
        # `run`/`check_*`/`Popen` only as subprocess attributes; the
        # emit family and sync primitives match on any receiver.
        if term in ("run", "check_call", "check_output", "Popen"):
            return d if d.startswith("subprocess.") else None
        return d
    if term == "join":
        # Thread/process join, not str.join: a constant-string receiver
        # is the separator idiom and never blocks.
        if not (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Constant)):
            return d
    return None


def rule_hl003_blocking_under_lock(ctx: HostContext):
    out = []
    for with_node, label in _flow.iter_lock_withs(ctx.tree):
        for stmt in with_node.body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                what = _is_blocking_call(node)
                if what is None:
                    continue
                f = ctx.finding(
                    "HL003", node,
                    f"blocking call {what}(...) while holding {label} "
                    "— every other thread serializes behind this "
                    "syscall; collect under the lock, emit/flush after "
                    "release",
                )
                if f:
                    out.append(f)
    return out


# ---------------------------------------------------------- HL004 -----


def rule_hl004_lock_order(ctx: HostContext):
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cycle = _flow.find_lock_cycle(_flow.class_lock_graph(node))
        if cycle is None:
            continue
        f = ctx.finding(
            "HL004", node,
            f"lock-order cycle across methods of {node.name}: "
            + " -> ".join(cycle)
            + " — two threads taking these paths concurrently can "
            "deadlock; impose one global acquisition order",
        )
        if f:
            out.append(f)
    return out


# ---------------------------------------------------------- HL005 -----


def _open_mode(node: ast.Call) -> str:
    if len(node.args) >= 2:
        m = node.args[1]
        if isinstance(m, ast.Constant) and isinstance(m.value, str):
            return m.value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _mentions_literal(node: ast.AST, needle: str,
                      consts: dict[str, str]) -> bool:
    for s in _flow.literal_strings(node):
        if needle in s:
            return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and needle in consts.get(sub.id, ""):
            return True
    return False


def _scope_str_consts(ctx: HostContext, scope: ast.AST) -> dict[str, str]:
    """Module-level string constants plus this scope's own simple
    ``name = <expr>`` bindings, each mapped to the concatenation of the
    string literals its value mentions — enough to see through the
    ``path = os.path.join(d, "x.jsonl")`` idiom."""
    consts = dict(ctx.consts)
    for node in ast.walk(scope):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        lits = " ".join(_flow.literal_strings(node.value))
        if lits:
            consts[node.targets[0].id] = (
                consts.get(node.targets[0].id, "") + " " + lits
            )
    return consts


def rule_hl005_jsonl_bypass(ctx: HostContext):
    if ctx.relpath == _EXPORT_RELPATH:
        return []  # the primitive itself.
    out = []
    for scope in _scopes(ctx):
        consts = _scope_str_consts(ctx, scope)
        for node in _own_nodes(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open" and node.args):
                continue
            mode = _open_mode(node)
            if not any(c in mode for c in "wa+"):
                continue
            if not _mentions_literal(node.args[0], ".jsonl", consts):
                continue
            f = ctx.finding(
                "HL005", node,
                "raw write-mode open() of a *.jsonl path — route the "
                "append through obs.export.jsonl_append (THE fsync'd "
                "primitive); a non-fsync'd line can vanish after the "
                "reader already acted on it",
            )
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------- HL006 -----


def rule_hl006_nonatomic_publish(ctx: HostContext):
    out = []
    for func in _flow.functions(ctx.tree):
        replaces = [
            n for n in ast.walk(func)
            if isinstance(n, ast.Call)
            and _flow.dotted(n.func) == "os.replace"
        ]
        opens_w = [
            n for n in ast.walk(func)
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            and n.func.id == "open" and n.args
            and any(c in _open_mode(n) for c in "wa+")
        ]
        has_fsync = any(
            isinstance(n, ast.Call)
            and _flow.terminal(n.func) == "fsync"
            for n in ast.walk(func)
        )
        if replaces and opens_w and not has_fsync:
            f = ctx.finding(
                "HL006", replaces[0],
                "os.replace publish without fsync of the temp file — "
                "after a crash the rename can land on disk before the "
                "data, publishing an empty/torn artifact; fsync before "
                "replacing",
            )
            if f:
                out.append(f)
        if not replaces:
            consts = _scope_str_consts(ctx, func)
            for n in opens_w:
                if _mentions_literal(n.args[0], "artifacts", consts):
                    f = ctx.finding(
                        "HL006", n,
                        "direct write into an artifacts/ path — publish "
                        "via temp file + fsync + os.replace so readers "
                        "never observe a torn file",
                    )
                    if f:
                        out.append(f)
    return out


# ---------------------------------------------------------- HL007 -----

_vocab_cache: dict[str, dict | None] = {}


def load_event_vocab(start_path: str) -> dict | None:
    """Kind tables parsed out of obs/export.py's AST (hostlint never
    imports the package — export pulls in numpy). Returns
    ``{"serving": {...}, "fleet": {...}, "session": {...},
    "events": {...}}`` or None when no export.py is reachable above
    ``start_path``."""
    d = os.path.dirname(os.path.abspath(start_path))
    root = d
    while True:
        if os.path.exists(os.path.join(root, _EXPORT_RELPATH)):
            break
        parent = os.path.dirname(root)
        if parent == root:
            return _vocab_cache.setdefault(d, None)
        root = parent
    export_path = os.path.join(root, _EXPORT_RELPATH)
    if export_path in _vocab_cache:
        return _vocab_cache[export_path]
    with open(export_path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=export_path)
    vocab = {
        "serving": _flow.module_dict_literal(tree, "SERVING_EVENT_KINDS"),
        "fleet": _flow.module_dict_literal(tree, "FLEET_EVENT_KINDS"),
        # Session (v8) and alert (v9) tables are newer vocabulary —
        # tolerated missing (None) so the linter still runs against
        # older export files.
        "session": _flow.module_dict_literal(tree, "SESSION_EVENT_KINDS"),
        "alert": _flow.module_dict_literal(tree, "ALERT_EVENT_KINDS"),
        "events": _flow.module_dict_literal(tree, "EVENT_FIELDS"),
    }
    if vocab["serving"] is None or vocab["fleet"] is None:
        vocab = None
    _vocab_cache[export_path] = vocab
    _vocab_cache[d] = vocab
    return vocab


_EMIT_TERMINALS = frozenset({"emit", "_emit", "emit_fleet",
                             "_emit_serving", "_emit_session"})


def rule_hl007_event_vocab(ctx: HostContext):
    if ctx.relpath == _EXPORT_RELPATH:
        return []  # the vocabulary's own definition site.
    vocab = load_event_vocab(ctx.path)
    if vocab is None:
        return []
    serving, fleet = vocab["serving"], vocab["fleet"]
    session = vocab.get("session") or {}
    alert = vocab.get("alert") or {}
    events = vocab["events"] or {}
    known = {**serving, **fleet, **session, **alert}
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        term = _flow.terminal(node.func)
        recv = (_flow.dotted(node.func.value).lower()
                if isinstance(node.func, ast.Attribute) else "")
        # Unknown event TYPE on a metrics-writer emit.
        if (term == "emit" and node.args
                and ("metrics" in recv or "writer" in recv)
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and events and node.args[0].value not in events):
            f = ctx.finding(
                "HL007", node,
                f"unknown metrics event type "
                f"{node.args[0].value!r} — not in obs.export."
                "EVENT_FIELDS (the writer raises at runtime; extend "
                "the vocabulary and bump SCHEMA_VERSION if readers "
                "must distinguish it)",
            )
            if f:
                out.append(f)
            continue
        if term not in _EMIT_TERMINALS:
            continue
        event_type = None
        if (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            event_type = node.args[0].value
        kws = {kw.arg: kw.value for kw in node.keywords}
        if None in kws:  # **kwargs — contents invisible to the AST.
            continue
        kind_node = kws.get("kind")
        if not (isinstance(kind_node, ast.Constant)
                and isinstance(kind_node.value, str)):
            continue
        kind = kind_node.value
        table = {"serving_event": serving, "fleet_event": fleet,
                 "session_event": session,
                 "alert": alert}.get(event_type, known)
        if not table:
            continue  # newer vocabulary absent from this export file.
        if kind not in table:
            f = ctx.finding(
                "HL007", node,
                f"event kind {kind!r} is not in obs/export.py's kind "
                f"vocabulary ({', '.join(sorted(table))}) — add it "
                "there (and bump SCHEMA_VERSION if readers must "
                "distinguish it) before emitting",
            )
            if f:
                out.append(f)
            continue
        missing = [k for k in table[kind] if k not in kws]
        if missing:
            f = ctx.finding(
                "HL007", node,
                f"event kind {kind!r} missing its minimum keys "
                f"{missing} — the per-kind reader contract "
                "(tools/run_health.py) requires them",
            )
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------- HL008 -----

_KNOB_PREFIXES = ("TAT_", "TPU_AERIAL_")


def rule_hl008_unregistered_knob(ctx: HostContext):
    out = []
    for node, key in _flow.iter_env_reads(ctx.tree, ctx.consts):
        if not key.startswith(_KNOB_PREFIXES):
            continue
        if key in _knobs.KNOBS:
            continue
        f = ctx.finding(
            "HL008", node,
            f"env knob {key!r} read here is not registered in "
            "analysis/knobs.py — register it (name, owning resolver, "
            "documented default) so the knob surface stays auditable",
        )
        if f:
            out.append(f)
    return out


# ---------------------------------------------------------- HL009 -----


def rule_hl009_subprocess_hygiene(ctx: HostContext):
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _flow.terminal(node.func) == "Popen"):
            continue
        kws = {kw.arg: kw.value for kw in node.keywords}
        if None in kws:
            continue  # **kwargs — invisible.
        problems = []
        sns = kws.get("start_new_session")
        if not (isinstance(sns, ast.Constant) and sns.value is True):
            problems.append("start_new_session=True (group-kill "
                            "discipline: one killpg reaps the tree)")
        if "stderr" not in kws:
            problems.append("an explicit stderr destination (a chatty "
                            "child wedges on a full inherited pipe; a "
                            "file keeps the post-mortem tail)")
        if problems:
            f = ctx.finding(
                "HL009", node,
                "Popen without " + " and ".join(problems),
            )
            if f:
                out.append(f)
    return out


# ---------------------------------------------------------- HL010 -----

_WATCHED_PARAMS = frozenset({
    "tracer", "telemetry", "metrics", "guard", "emit", "sink",
})


def _watched_params(func: ast.AST) -> set[str]:
    a = func.args
    names = {p.arg for p in a.args + a.kwonlyargs + a.posonlyargs}
    return names & _WATCHED_PARAMS


def rule_hl010_truthiness_gate(ctx: HostContext):
    out = []

    def hit(node, name, form):
        f = ctx.finding(
            "HL010", node,
            f"truthiness gate `{form}` on observability/guard "
            f"parameter '{name}' — the zero-cost contract is `is "
            "not None`; a falsy-but-real sink (or tracer=False) "
            "slips through truthiness and crashes downstream",
        )
        if f:
            out.append(f)

    for func in _flow.functions(ctx.tree):
        watched = _watched_params(func)
        if not watched:
            continue
        for node in _own_nodes(func):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                t = node.test
                if isinstance(t, ast.Name) and t.id in watched:
                    hit(t, t.id, f"if {t.id}:")
                elif (isinstance(t, ast.UnaryOp)
                        and isinstance(t.op, ast.Not)
                        and isinstance(t.operand, ast.Name)
                        and t.operand.id in watched):
                    hit(t, t.operand.id, f"if not {t.operand.id}:")
            elif isinstance(node, ast.BoolOp):
                for v in node.values:
                    if isinstance(v, ast.Name) and v.id in watched:
                        op = "or" if isinstance(node.op, ast.Or) else "and"
                        hit(v, v.id, f"{v.id} {op} ...")
            elif isinstance(node, ast.Compare):
                if (isinstance(node.left, ast.Name)
                        and node.left.id in watched
                        and len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.Is, ast.Eq))
                        and isinstance(node.comparators[0], ast.Constant)
                        and isinstance(node.comparators[0].value, bool)):
                    hit(node, node.left.id,
                        f"{node.left.id} is "
                        f"{node.comparators[0].value}")
    return out


# ------------------------------------------------------------ driver --

HOST_RULES = {
    "HL001": rule_hl001_clock_mixing,
    "HL002": rule_hl002_span_leak,
    "HL003": rule_hl003_blocking_under_lock,
    "HL004": rule_hl004_lock_order,
    "HL005": rule_hl005_jsonl_bypass,
    "HL006": rule_hl006_nonatomic_publish,
    "HL007": rule_hl007_event_vocab,
    "HL008": rule_hl008_unregistered_knob,
    "HL009": rule_hl009_subprocess_hygiene,
    "HL010": rule_hl010_truthiness_gate,
}


def run_host_rules(ctx: HostContext,
                   disabled: frozenset[str] = frozenset()
                   ) -> list[Finding]:
    if ctx.skip_file:
        return []
    out: list[Finding] = []
    for rule_id, impl in HOST_RULES.items():
        if rule_id in disabled:
            continue
        out.extend(impl(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_host_file(path: str,
                   disabled: frozenset[str] = frozenset()
                   ) -> tuple[list[Finding], set[str], str]:
    """(findings, waiver keys that matched, relpath) for one file."""
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    try:
        ctx = HostContext(path, source)
    except SyntaxError as e:
        return ([Finding(
            rule="HL000", path=path, line=e.lineno or 0,
            col=e.offset or 0, message=f"syntax error: {e.msg}",
        )], set(), relpath_of(path))
    return run_host_rules(ctx, disabled), ctx.waiver_hits, ctx.relpath


def waiver_hygiene(scanned_relpaths: set[str],
                   used_keys: set[str]) -> list[Finding]:
    """HL000 findings for stale waivers (site scanned, rule no longer
    trips) and waivers with no written reason."""
    out = []
    for key, reason in sorted(HOST_WAIVERS.items()):
        path = key.split("::", 1)[0]
        if not reason.strip():
            out.append(Finding(
                rule="HL000", path=path, line=0, col=0,
                message=f"waiver {key!r} has no written reason — every "
                "HOST_WAIVERS entry must say WHY the contract bends",
            ))
        if path in scanned_relpaths and key not in used_keys:
            out.append(Finding(
                rule="HL000", path=path, line=0, col=0,
                message=f"stale waiver {key!r}: the site no longer "
                "trips its rule — delete the entry (waivers must not "
                "outlive their reason)",
            ))
    return out


def lint_host_files(files: list[str],
                    disabled: frozenset[str] = frozenset()
                    ) -> list[Finding]:
    """Lint concrete files with the HL rules + waiver hygiene."""
    findings: list[Finding] = []
    used: set[str] = set()
    scanned: set[str] = set()
    for f in files:
        file_findings, hits, rel = lint_host_file(f, disabled)
        findings.extend(file_findings)
        used |= hits
        scanned.add(rel)
    findings.extend(waiver_hygiene(scanned, used))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
