"""Tier C (hostlint) tests: every HL rule fires on its seeded bad
fixture at the exact marked lines and stays silent on the clean twin
and on the package; pragma suppression and waiver hygiene work; the
CLI covers HL rules without importing jax; the scan set spans the host
tree; the knob registry cannot drift from the code; and the PR-15
HL002/HL010 bug classes are demonstrably caught on reconstructions of
the original buggy code. Plus behavior regressions for the host-side
fixes the sweep forced (falsy-but-callable sinks, emits outside the
admission lock, guard spans ended on BaseException).

tests/fixtures/hostlint/ holds one ``hlXXX_bad.py`` per rule with
``# expect: HLXXX`` markers on the violating lines, plus a
``hlXXX_ok.py`` clean twin that must produce zero findings.
"""

import json
import os
import re
import subprocess
import sys
import threading

import pytest

from tpu_aerial_transport.analysis import hostrules, knobs, linter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tpu_aerial_transport")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "hostlint")
JAXLINT = os.path.join(REPO, "tools", "jaxlint.py")

_EXPECT_RE = re.compile(r"#\s*expect:\s*(HL\d{3})")


def _expected(path):
    out = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            for rule in _EXPECT_RE.findall(line):
                out.append((rule, lineno))
    return out


def _fixture_files(kind):
    return sorted(
        os.path.join(FIXTURES, f)
        for f in os.listdir(FIXTURES)
        if f.endswith(f"_{kind}.py")
    )


def _lint_one(path, disabled=frozenset()):
    findings, _, _ = hostrules.lint_host_file(path, disabled)
    return findings


def _host_files():
    return list(linter.iter_py_files(hostrules.host_paths(REPO)))


# ----------------------------- fixtures --------------------------------

def test_every_hl_rule_has_a_seeded_fixture():
    covered = set()
    for path in _fixture_files("bad"):
        covered.update(r for r, _ in _expected(path))
    assert covered == set(hostrules.HOST_RULES), (
        "rules without a seeded-violation fixture: "
        f"{set(hostrules.HOST_RULES) - covered}"
    )


@pytest.mark.parametrize(
    "path", _fixture_files("bad"), ids=lambda p: os.path.basename(p)
)
def test_seeded_violations_fire_at_exact_lines(path):
    findings = {(f.rule, f.line) for f in _lint_one(path)}
    expected = set(_expected(path))
    assert expected, f"fixture {path} declares no expectations"
    missing = expected - findings
    assert not missing, (
        f"seeded violations not detected: {sorted(missing)}; "
        f"got {sorted(findings)}"
    )


@pytest.mark.parametrize(
    "path", _fixture_files("ok"), ids=lambda p: os.path.basename(p)
)
def test_clean_twins_produce_no_findings(path):
    findings = _lint_one(path)
    assert not findings, [f.render() for f in findings]


def test_package_hostlints_clean():
    findings = hostrules.lint_host_files(_host_files())
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------- PR-15 bug classes ---------------------------

def test_hl002_catches_the_pr15_span_leak_reconstruction(tmp_path):
    """The original harvest-span bug: begun, ended only on the success
    path — one device error between them leaked the span open."""
    src = (
        "def _advance(self, fam, batch):\n"
        "    hspan = self.tracer.begin('host_harvest',\n"
        "                              batch_id=batch.batch_id)\n"
        "    rows = batch.harvest()\n"
        "    self.tracer.end(hspan, rows=len(rows))\n"
        "    return rows\n"
    )
    p = tmp_path / "pr15_span.py"
    p.write_text(src)
    assert [(f.rule, f.line) for f in _lint_one(str(p))] == [("HL002", 2)]
    # The fixed shape (the one serving/server.py now uses) is clean.
    fixed = (
        "def _advance(self, fam, batch):\n"
        "    hspan = self.tracer.begin('host_harvest',\n"
        "                              batch_id=batch.batch_id)\n"
        "    try:\n"
        "        rows = batch.harvest()\n"
        "    except BaseException:\n"
        "        self.tracer.end(hspan, error=True)\n"
        "        raise\n"
        "    self.tracer.end(hspan, rows=len(rows))\n"
        "    return rows\n"
    )
    p.write_text(fixed)
    assert _lint_one(str(p)) == []


def test_hl010_catches_the_pr15_tracer_false_reconstruction(tmp_path):
    """The original pods-resume bug: ``if tracer:`` let tracer=False
    through every zero-cost gate until the first traced span crashed."""
    src = (
        "def pods_rollout_resumable(plan, tracer=None):\n"
        "    if tracer:\n"
        "        tracer.instant('resume', run_dir=plan)\n"
        "    return plan\n"
    )
    p = tmp_path / "pr15_tracer.py"
    p.write_text(src)
    assert [(f.rule, f.line) for f in _lint_one(str(p))] == [("HL010", 2)]
    p.write_text(src.replace("if tracer:", "if tracer is not None:"))
    assert _lint_one(str(p)) == []


# ------------------------- analyzer plumbing ---------------------------

def test_pragma_suppresses_hl_rule(tmp_path):
    src = (
        "import time\n\n"
        "def admit(deadline_s):\n"
        "    return time.time() + deadline_s"
        "  # jaxlint: disable=HL001\n"
    )
    p = tmp_path / "pragma_case.py"
    p.write_text(src)
    assert _lint_one(str(p)) == []
    p.write_text(src.replace("  # jaxlint: disable=HL001", ""))
    assert [f.rule for f in _lint_one(str(p))] == ["HL001"]


def test_skip_file_pragma(tmp_path):
    p = tmp_path / "skip_case.py"
    p.write_text(
        "# jaxlint: skip-file\nimport time\n\n"
        "def admit(d):\n    return time.time() + d\n"
    )
    assert _lint_one(str(p)) == []


def test_stale_waiver_on_a_clean_site_fails(tmp_path, monkeypatch):
    """A waiver whose site no longer trips its rule must itself become
    an error — waivers cannot outlive their reason."""
    p = tmp_path / "clean_mod.py"
    p.write_text("def f(tracer=None):\n    return tracer is not None\n")
    key = f"{os.path.basename(p)}::HL010::f"
    monkeypatch.setitem(hostrules.HOST_WAIVERS, key, "obsolete reason")
    findings = hostrules.lint_host_files([str(p)])
    assert [f.rule for f in findings] == ["HL000"]
    assert "stale waiver" in findings[0].message


def test_waiver_suppresses_and_counts_as_used(tmp_path, monkeypatch):
    p = tmp_path / "waived_mod.py"
    p.write_text("def f(tracer=None):\n    if tracer:\n        pass\n")
    key = f"{os.path.basename(p)}::HL010::f"
    monkeypatch.setitem(hostrules.HOST_WAIVERS, key,
                        "test: deliberate tri-state flag")
    assert hostrules.lint_host_files([str(p)]) == []


def test_unreasoned_waiver_fails(tmp_path, monkeypatch):
    p = tmp_path / "waived_mod.py"
    p.write_text("def f(tracer=None):\n    if tracer:\n        pass\n")
    key = f"{os.path.basename(p)}::HL010::f"
    monkeypatch.setitem(hostrules.HOST_WAIVERS, key, "   ")
    findings = hostrules.lint_host_files([str(p)])
    assert [f.rule for f in findings] == ["HL000"]
    assert "no written reason" in findings[0].message


def test_real_waivers_are_well_formed():
    for key, reason in hostrules.HOST_WAIVERS.items():
        path, rule, func = key.split("::")
        assert rule in hostrules.HOST_RULES, key
        assert os.path.exists(os.path.join(REPO, path)), key
        assert len(reason.strip()) >= 40, (
            f"waiver {key} needs a WRITTEN reason, not a stub"
        )


def test_every_hl_rule_has_a_doc():
    assert set(hostrules.HOST_RULE_DOCS) == (
        set(hostrules.HOST_RULES) | {"HL000"}
    )


def test_syntax_error_reports_hl000(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = _lint_one(str(p))
    assert [f.rule for f in findings] == ["HL000"]


def test_module_coverage_spans_the_host_tree():
    """A NEW module under serving/, resilience/, or obs/ must be visited
    by hostlint without anyone editing the scan set — and if the scan
    set ever stops spanning those trees, this fails."""
    scanned = {os.path.abspath(f) for f in _host_files()}
    for sub in ("serving", "resilience", "obs"):
        root = os.path.join(PKG, sub)
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in files:
                if f.endswith(".py"):
                    full = os.path.abspath(os.path.join(dirpath, f))
                    assert full in scanned, (
                        f"{full} is not visited by hostlint"
                    )
    assert os.path.abspath(
        os.path.join(PKG, "parallel", "pods.py")
    ) in scanned


# ------------------------------- CLI -----------------------------------

def test_cli_host_json_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--host", "--format", "json", FIXTURES],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["errors"] > 0
    assert payload["rules"] == sorted(hostrules.HOST_RULES)
    fired = {f["rule"] for f in payload["findings"]}
    assert fired == set(hostrules.HOST_RULES)
    clean = subprocess.run(
        [sys.executable, JAXLINT, "--host"], capture_output=True,
        text=True, cwd=REPO,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr


def test_cli_list_rules_covers_both_tiers():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    for rid in list(hostrules.HOST_RULES) + ["JL001"]:
        assert rid in proc.stdout, f"--list-rules missing {rid}"


def test_cli_host_never_imports_jax():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--host", "--assert-no-jax"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_host_disable_flag():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--host", "--disable",
         ",".join(hostrules.HOST_RULES), FIXTURES],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------- knob registry ----------------------------

_KNOB_TOKEN_RE = re.compile(r"\b(?:TAT_|TPU_AERIAL_)[A-Z0-9_]+")


def _knob_scan_files():
    yield os.path.join(REPO, "bench.py")
    for base in (PKG, os.path.join(REPO, "tools")):
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def test_knob_registry_has_no_drift():
    """Every TAT_*/TPU_AERIAL_* token in the package, tools, and bench
    harness is either a registered knob or a declared prefix
    passthrough; and every registered knob still exists in the code
    (no stale registry rows). The registry file itself is excluded —
    it IS the table being checked."""
    registry = os.path.join(PKG, "analysis", "knobs.py")
    seen: dict[str, set[str]] = {}
    for path in _knob_scan_files():
        if os.path.abspath(path) == os.path.abspath(registry):
            continue
        with open(path, encoding="utf-8") as fh:
            for tok in _KNOB_TOKEN_RE.findall(fh.read()):
                seen.setdefault(tok, set()).add(
                    os.path.relpath(path, REPO)
                )
    unregistered = {
        tok: sorted(paths) for tok, paths in seen.items()
        if tok not in knobs.KNOBS
        and tok not in knobs.PREFIX_PASSTHROUGHS
    }
    assert not unregistered, (
        f"env knobs read but not registered in analysis/knobs.py: "
        f"{unregistered}"
    )
    stale = set(knobs.KNOBS) - set(seen)
    assert not stale, f"registered knobs no longer in the code: {stale}"


def test_knob_registry_rows_are_complete():
    for name, row in knobs.KNOBS.items():
        assert set(row) == {"resolver", "default", "doc"}, name
        assert os.path.exists(os.path.join(REPO, row["resolver"])), (
            f"{name}: resolver file {row['resolver']} does not exist"
        )
        assert row["default"].strip() and row["doc"].strip(), name


def test_readme_carries_the_generated_knob_table():
    """The README table is generated from the registry — regen drift
    fails here."""
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    assert knobs.readme_table() in readme, (
        "README 'Configuration knobs' table is stale — regenerate with "
        "python -c \"import tpu_aerial_transport.analysis.knobs as k; "
        "print(k.readme_table())\""
    )


# ----------------- behavior regressions for the fixes ------------------

def test_falsy_but_callable_emit_still_receives_events():
    """The HL010 fix on AdmissionQueue: a sink whose __bool__ is False
    (a Mock configured falsy, a stats-counter that is 'empty') must
    still receive every serving event."""
    from tpu_aerial_transport.serving import queue as queue_mod

    class FalsySink:
        def __init__(self):
            self.events = []

        def __bool__(self):
            return False

        def __call__(self, **kw):
            self.events.append(kw["kind"])

    sink = FalsySink()
    q = queue_mod.AdmissionQueue(lambda fam: 4, emit=sink)
    q.submit(queue_mod.ScenarioRequest(family="f", horizon=8))
    assert sink.events == ["submitted"]


def test_submit_and_expire_emit_outside_the_admission_lock():
    """The HL003 fix: the emit sink runs with the queue lock RELEASED
    (it fsyncs per event in production) — asserted by re-acquiring the
    non-reentrant lock from inside the sink, which deadlocks or fails
    if emit still runs under it."""
    from tpu_aerial_transport.serving import queue as queue_mod

    kinds = []
    q = None

    def sink(**kw):
        assert q._lock.acquire(blocking=False), (
            f"emit({kw.get('kind')}) ran while holding the admission lock"
        )
        q._lock.release()
        kinds.append(kw["kind"])

    q = queue_mod.AdmissionQueue(lambda fam: 4, capacity=1, emit=sink,
                                 clock=lambda: 100.0)
    q.submit(queue_mod.ScenarioRequest(family="f", horizon=8,
                                       deadline_s=5.0))
    q.submit(queue_mod.ScenarioRequest(family="f", horizon=8))  # full.
    q.expire_deadlines()  # not yet due.
    # Push past the deadline via a fresh queue with a movable clock.
    now = [100.0]
    q2 = queue_mod.AdmissionQueue(lambda fam: 4, emit=sink,
                                  clock=lambda: now[0])
    q = q2  # the sink closes over q; point it at the live queue.
    q2.submit(queue_mod.ScenarioRequest(family="f", horizon=8,
                                        deadline_s=1.0))
    now[0] = 200.0
    missed = q2.expire_deadlines()
    assert [t.request.family for t in missed] == ["f"]
    assert kinds == ["submitted", "rejected", "submitted",
                     "deadline_missed"]


def test_guard_dispatch_span_ends_on_keyboard_interrupt():
    """The HL002 fix on BackendGuard.run: a KeyboardInterrupt inside
    the watchdogged primary must re-raise AND close the dispatch span
    (pre-fix it leaked open: only `except Exception` ended it)."""
    from tpu_aerial_transport.obs import trace as trace_mod
    from tpu_aerial_transport.resilience import backend as backend_mod

    tr = trace_mod.Tracer()
    guard = backend_mod.BackendGuard(tracer=tr, deadline_s=0)

    def primary():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        guard.run("interrupt_case", primary)
    rows = [r for r in tr.rows if r["name"] == "guard_dispatch"]
    assert len(rows) == 1
    assert rows[0]["attrs"]["kind"] == "interrupted"
    assert "t1_mono" in rows[0], "span leaked open on KeyboardInterrupt"


def test_validate_event_names_kind_and_missing_keys():
    """The satellite fix: schema errors name the offending kind and the
    exact missing keys, and unknown kinds list the vocabulary."""
    from tpu_aerial_transport.obs import export as export_mod

    base = {"schema": export_mod.SCHEMA_VERSION, "ts": 1.0}
    errs = export_mod.validate_event(
        {**base, "event": "fleet_event", "kind": "failover"}, lineno=7
    )
    assert errs == [
        "line 7: event 'fleet_event' kind 'failover' missing keys "
        "['request_id']"
    ]
    errs = export_mod.validate_event(
        {**base, "event": "serving_event", "kind": "teleported"}
    )
    assert len(errs) == 1 and "unknown kind 'teleported'" in errs[0]
    assert "batch_launch" in errs[0]  # the vocabulary is named.
    errs = export_mod.validate_event({**base, "event": "warp_event"})
    assert len(errs) == 1 and "unknown event type 'warp_event'" in errs[0]
    assert "serving_event" in errs[0]  # known types are named.
    ok = export_mod.validate_event(
        {**base, "event": "fleet_event", "kind": "failover",
         "request_id": "r1"}
    )
    assert ok == []


def test_lint_kind_tables_match_runtime_tables():
    """HL007 reads the kind tables out of obs/export.py's AST — assert
    the parse sees exactly what the runtime module exports, so the lint
    and the validator can never disagree."""
    from tpu_aerial_transport.obs import export as export_mod

    vocab = hostrules.load_event_vocab(
        os.path.join(PKG, "serving", "queue.py")
    )
    assert vocab is not None
    assert {k: tuple(v) for k, v in vocab["serving"].items()} == {
        k: tuple(v) for k, v in export_mod.SERVING_EVENT_KINDS.items()
    }
    assert {k: tuple(v) for k, v in vocab["fleet"].items()} == {
        k: tuple(v) for k, v in export_mod.FLEET_EVENT_KINDS.items()
    }


def test_concurrent_submitters_with_blocking_sink_make_progress():
    """End-to-end shape of the HL003 fix: many threads submitting
    through a deliberately slow sink still finish quickly because the
    sink runs outside the lock (pre-fix this serialized ~N*delay)."""
    import time as time_mod

    from tpu_aerial_transport.serving import queue as queue_mod

    def slow_sink(**kw):
        time_mod.sleep(0.02)

    q = queue_mod.AdmissionQueue(lambda fam: 4, capacity=64,
                                 emit=slow_sink)

    def submit_one(i):
        q.submit(queue_mod.ScenarioRequest(family="f", horizon=8,
                                           request_id=f"r{i}"))

    threads = [threading.Thread(target=submit_one, args=(i,))
               for i in range(8)]
    t0 = time_mod.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time_mod.monotonic() - t0
    assert q.depth("f") == 8
    # Serialized would be >= 8 * 0.02 = 0.16s; parallel sinks overlap.
    assert elapsed < 0.15, f"submits serialized behind the sink: {elapsed}"
