"""obs.trace: the distributed-tracing span layer — tracer mechanics,
request-path propagation through the serving tier, guard/recovery spans,
clock stitching, Chrome-trace conversion + validation, critical-path
accounting, and the zero-cost tracer=None contract (HLO identity)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import trace as trace_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_VIEW = os.path.join(REPO, "tools", "trace_view.py")
RUN_HEALTH = os.path.join(REPO, "tools", "run_health.py")


# ----------------------------- tracer core -----------------------------

def test_span_nesting_and_parentage():
    tr = trace_mod.Tracer(track="t")
    with tr.span("run", run_dir="/tmp/x") as run:
        with tr.span("chunk", chunk=0) as chunk:
            assert chunk.parent_id == run.span_id
            assert chunk.trace_id == run.trace_id
        # Sibling after the nested span closes: still under run.
        with tr.span("chunk", chunk=1) as c1:
            assert c1.parent_id == run.span_id
    names = [r["name"] for r in tr.rows]
    assert names == ["chunk", "chunk", "run"]  # children end first.
    run_row = tr.rows[-1]
    assert "parent_id" not in run_row  # the lexical root has no parent.
    assert run_row["attrs"]["run_dir"] == "/tmp/x"
    for r in tr.rows:
        assert r["t1_mono"] >= r["t0_mono"]
        assert r["track"] == "t"


def test_explicit_parent_and_cross_call_span():
    tr = trace_mod.Tracer()
    root = tr.begin("request", parent=None, request_id="r0")
    q = tr.begin("queue_wait", parent=root)
    assert q.trace_id == root.trace_id and q.parent_id == root.span_id
    tr.end(q, batch_id=3)
    tr.end(root, status="completed")
    assert tr.rows[0]["attrs"]["batch_id"] == 3
    # end() is idempotent: a defensive second end keeps the first stamps.
    t1 = tr.rows[1]["t1_mono"]
    tr.end(root)
    assert len(tr.rows) == 2 and tr.rows[1]["t1_mono"] == t1


def test_instant_and_sink_callable():
    seen = []
    tr = trace_mod.Tracer(sink=seen.append)
    tr.instant("preempted", parent=None, chunk=2)
    assert seen == tr.rows
    assert seen[0]["t1_mono"] == seen[0]["t0_mono"]
    assert seen[0]["attrs"]["chunk"] == 2


def test_rows_export_schema_v5_valid(tmp_path):
    path = str(tmp_path / "t.metrics.jsonl")
    tr = trace_mod.Tracer(export_mod.MetricsWriter(path), track="p0of1")
    with tr.span("run"):
        with tr.span("chunk", chunk=0):
            pass
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    trows = trace_mod.trace_rows(events)
    assert len(trows) == 2
    assert all(e["schema"] == export_mod.SCHEMA_VERSION for e in trows)


# ------------------------------ stitching ------------------------------

def _fake_row(track, name, t0_mono, t1_mono, wall_off, trace_id="tA",
              span_id=None, parent_id=None, attrs=None):
    return {
        "name": name, "trace_id": trace_id,
        "span_id": span_id or trace_mod.new_span_id(),
        "track": track, "t0_mono": t0_mono, "t1_mono": t1_mono,
        "t0_wall": t0_mono + wall_off, "t1_wall": t1_mono + wall_off,
        **({"parent_id": parent_id} if parent_id else {}),
        **({"attrs": attrs} if attrs else {}),
    }


def test_stitch_aligns_monotonic_domains():
    """Two processes whose monotonic clocks started at wildly different
    origins but whose wall clocks agree: stitched times are comparable
    across tracks, durations stay exactly the monotonic ones."""
    # p0's mono starts near 0, p1's near 1e6 (a long-lived process) —
    # the same physical instant (wall 1000.0) for both first spans.
    r0 = _fake_row("p0of2", "chunk", 5.0, 7.0, wall_off=995.0)
    r1 = _fake_row("p1of2", "chunk", 1e6 + 5.0, 1e6 + 6.0,
                   wall_off=995.0 - 1e6)
    stitched = trace_mod.stitch([r0, r1])
    s0, s1 = stitched
    assert s0["t0"] == pytest.approx(s1["t0"], abs=1e-6)  # same instant.
    assert s0["t1"] - s0["t0"] == pytest.approx(2.0)
    assert s1["t1"] - s1["t0"] == pytest.approx(1.0)


def test_stitch_run_dir_refuses_empty_fleet(tmp_path):
    """ZERO trace rows under a manifest naming N processes is the most
    complete partial-fleet lie (every worker killed before a span
    ended): refuse, don't publish an empty trace."""
    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "carry.shards.json"), "w") as fh:
        json.dump({"n_processes": 2}, fh)
    with pytest.raises(ValueError, match="only 0 track"):
        trace_mod.stitch_run_dir(run_dir)
    assert trace_mod.stitch_run_dir(run_dir, allow_partial=True) == []


def test_stitch_run_dir_refuses_partial_fleet(tmp_path):
    run_dir = str(tmp_path)
    export_mod.jsonl_append(
        os.path.join(run_dir, "trace.p0of2.metrics.jsonl"),
        {"schema": export_mod.SCHEMA_VERSION, "event": "trace_event",
         "ts": 0.0, **_fake_row("p0of2", "run", 0.0, 1.0, 100.0)},
    )
    with open(os.path.join(run_dir, "carry.shards.json"), "w") as fh:
        json.dump({"n_processes": 2}, fh)
    with pytest.raises(ValueError, match="2 processes"):
        trace_mod.stitch_run_dir(run_dir)
    assert len(trace_mod.stitch_run_dir(run_dir, allow_partial=True)) == 1
    # The second process's file completes the fleet.
    export_mod.jsonl_append(
        os.path.join(run_dir, "trace.p1of2.metrics.jsonl"),
        {"schema": export_mod.SCHEMA_VERSION, "event": "trace_event",
         "ts": 0.0, **_fake_row("p1of2", "run", 50.0, 51.0, 50.0)},
    )
    rows = trace_mod.stitch_run_dir(run_dir)
    assert {r["track"] for r in rows} == {"p0of2", "p1of2"}


# ------------------------- chrome trace + gate -------------------------

def test_chrome_trace_packs_overlapping_spans_and_validates():
    tr = trace_mod.Tracer(track="server")
    # Two concurrent requests: same-name spans overlapping in time must
    # land on separate packed lanes (Perfetto slice tracks cannot hold
    # overlapping X events).
    a = tr.begin("request", parent=None, request_id="a")
    b = tr.begin("request", parent=None, request_id="b")
    tr.end(a)
    tr.end(b)
    obj = trace_mod.chrome_trace(tr.rows)
    assert trace_mod.validate_chrome_trace(obj) == []
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 2
    assert xs[0]["tid"] != xs[1]["tid"]
    names = {e["args"]["name"] for e in obj["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"request", "request.1"} <= names


def test_validate_chrome_trace_catches_violations():
    ok = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 5.0, "args": {"trace_id": "t", "span_id": "s1"}},
    ]}
    assert trace_mod.validate_chrome_trace(ok) == []
    bad_parent = {"traceEvents": ok["traceEvents"] + [
        {"ph": "X", "name": "b", "pid": 1, "tid": 2, "ts": 1.0,
         "dur": 1.0,
         "args": {"trace_id": "t", "span_id": "s2",
                  "parent_id": "missing"}},
    ]}
    errs = trace_mod.validate_chrome_trace(bad_parent)
    assert errs and "parent_id" in errs[0]
    overlap = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 5.0, "args": {}},
        {"ph": "X", "name": "a", "pid": 1, "tid": 1, "ts": 2.0,
         "dur": 1.0, "args": {}},
    ]}
    assert any("overlap" in e for e in
               trace_mod.validate_chrome_trace(overlap))
    nonmono = {"traceEvents": [
        {"ph": "i", "s": "t", "name": "a", "pid": 1, "tid": 1, "ts": 5.0},
        {"ph": "i", "s": "t", "name": "b", "pid": 1, "tid": 1, "ts": 1.0},
    ]}
    assert any("non-monotone" in e for e in
               trace_mod.validate_chrome_trace(nonmono))
    assert trace_mod.validate_chrome_trace({"nope": 1})


def test_trace_view_cli_validate_gate(tmp_path):
    good = str(tmp_path / "good.trace.json")
    tr = trace_mod.Tracer()
    with tr.span("run"):
        pass
    trace_mod.write_chrome_trace(good, tr.rows)
    proc = subprocess.run(
        [sys.executable, TRACE_VIEW, "--validate", good],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bad = str(tmp_path / "bad.trace.json")
    with open(bad, "w") as fh:
        fh.write("{not json")
    proc = subprocess.run(
        [sys.executable, TRACE_VIEW, "--validate", bad],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    assert "unreadable" in proc.stderr


# ----------------------- serving-path propagation ----------------------

@pytest.fixture(scope="module")
def traced_serving_run(tmp_path_factory):
    """One small traced serving run (centralized family — cheapest
    compile), shared by the propagation / accounting / rendering tests."""
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    tmp = tmp_path_factory.mktemp("traced_serve")
    mpath = str(tmp / "serve.metrics.jsonl")
    writer = export_mod.MetricsWriter(mpath)
    tracer = trace_mod.Tracer(writer, track="server")
    server = server_mod.ScenarioServer(
        families=["centralized4"], buckets=(8,), metrics=writer,
        tracer=tracer,
    )
    tickets = [
        server.submit(ScenarioRequest(
            family="centralized4", horizon=2 * (1 + i % 2),
            request_id=f"req{i:03d}",
        ))
        for i in range(3)
    ]
    rejected = server.submit(ScenarioRequest(
        family="not_served", horizon=2, request_id="reqbad",
    ))
    server.run_until_drained()
    return server, tracer, tickets, rejected, mpath


def test_request_spans_propagate_through_pipeline(traced_serving_run):
    server, tracer, tickets, rejected, _ = traced_serving_run
    rows = tracer.rows
    by_name: dict = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    assert {trace_mod.REQUEST, trace_mod.QUEUE_WAIT,
            trace_mod.BATCH_FORM, trace_mod.CHUNK_DISPATCH,
            trace_mod.HARVEST, trace_mod.GUARD_DISPATCH} \
        <= set(by_name)
    # Every completed ticket: its own trace, queue_wait child of the
    # request root, and the request's trace id appearing in at least one
    # dispatch span's lane map.
    dispatch_members = set()
    for d in by_name[trace_mod.CHUNK_DISPATCH]:
        for lane in d["attrs"]["lanes"]:
            dispatch_members.add(lane[2])
        assert d["attrs"]["rung"]  # serve-ladder rung stamped.
    for t in tickets:
        assert t.status == "completed"
        assert t.trace is not None
        tid = t.trace.trace_id
        req = [r for r in by_name[trace_mod.REQUEST]
               if r["trace_id"] == tid]
        assert len(req) == 1
        assert req[0]["attrs"]["status"] == "completed"
        q = [r for r in by_name[trace_mod.QUEUE_WAIT]
             if r["trace_id"] == tid]
        assert len(q) == 1
        assert q[0]["parent_id"] == req[0]["span_id"]
        assert q[0]["attrs"]["batch_id"] == t.batch_id
        assert tid in dispatch_members
    # The minted trace context rides the (replaced) request object.
    assert all(t.request.trace_id == t.trace.trace_id for t in tickets)
    # Guard spans nest under the dispatch spans they guard.
    dspan_ids = {d["span_id"] for d in by_name[trace_mod.CHUNK_DISPATCH]}
    for g in by_name[trace_mod.GUARD_DISPATCH]:
        assert g["parent_id"] in dspan_ids


def test_rejection_is_terminal_span(traced_serving_run):
    _, tracer, _, rejected, _ = traced_serving_run
    assert rejected.status == "rejected"
    rej = [r for r in tracer.rows if r["name"] == trace_mod.REQUEST
           and r.get("attrs", {}).get("status") == "rejected"]
    assert len(rej) == 1
    assert rej[0]["attrs"]["reason"] == "no_bucket_coverage"
    # No queue_wait span for a rejected request.
    assert not any(r["name"] == trace_mod.QUEUE_WAIT
                   and r["trace_id"] == rej[0]["trace_id"]
                   for r in tracer.rows)


def test_critical_path_segments_sum_exactly(traced_serving_run):
    """The acceptance bar: every completed request's segments sum to its
    submit→complete interval within 1% (exact by construction here)."""
    _, tracer, tickets, _, _ = traced_serving_run
    cp = trace_mod.critical_path(tracer.rows)
    assert cp["completed"] == len(tickets)
    for q in cp["requests"]:
        if q["status"] != "completed":
            continue
        total = q["total_s"]
        s = sum(q["segments"].values())
        assert abs(s - total) <= max(1e-9, 0.01 * total), (q, s)
        assert set(q["segments"]) == set(trace_mod.SEGMENTS)
        assert q["segments"]["device"] > 0  # device time attributed.
    assert cp["worst"] is not None
    assert set(cp["per_segment"]) == set(trace_mod.SEGMENTS)


def test_critical_path_dedups_remeasured_requests():
    """Append-mode files re-measure requests under the same request_id:
    only the LAST request span per id counts (the run_health dedup
    rule)."""
    rows = []
    for run in range(2):
        off = 100.0 * run
        tid = f"t{run}"
        rows.append(_fake_row("s", "request", off, off + 2.0 + run, 0.0,
                              trace_id=tid,
                              attrs={"request_id": "reqX",
                                     "status": "completed"}))
        rows.append(_fake_row("s", "queue_wait", off, off + 1.0, 0.0,
                              trace_id=tid))
    cp = trace_mod.critical_path(rows)
    assert len(cp["requests"]) == 1
    assert cp["requests"][0]["total_s"] == pytest.approx(3.0)
    assert cp["requests"][0]["segments"]["queue_wait"] == pytest.approx(1.0)


def test_critical_path_clamps_window_to_restored_request_start():
    """Regression (review finding): a RESTORED request's post-resume
    span shares its trace_id with the dead run's queue_wait and batch
    spans; the in-batch window must start no earlier than the request
    span itself, or pre-resume device time counts into the restored
    request and the segments exceed the total."""
    rows = [
        # Dead run: queue span + a dispatch that served this trace.
        _fake_row("s", "queue_wait", 0.0, 50.0, 0.0, trace_id="tA"),
        _fake_row("s", "chunk_dispatch", 40.0, 60.0, 0.0,
                  trace_id="srv", attrs={"lanes": [[0, "rq", "tA"]]}),
        # Post-resume: the surviving request span (restored=True path),
        # plus the dispatch that actually finished it.
        _fake_row("s", "request", 100.0, 110.0, 0.0, trace_id="tA",
                  attrs={"request_id": "rq", "status": "completed"}),
        _fake_row("s", "chunk_dispatch", 102.0, 108.0, 0.0,
                  trace_id="srv", attrs={"lanes": [[0, "rq", "tA"]]}),
    ]
    cp = trace_mod.critical_path(rows)
    q = cp["requests"][0]
    assert q["total_s"] == pytest.approx(10.0)
    assert q["segments"]["device"] == pytest.approx(6.0)  # not 26.
    assert q["segments"]["queue_wait"] == 0.0  # dead-run span pre-t0.
    assert sum(q["segments"].values()) == pytest.approx(q["total_s"])


def test_snapshot_span_survives_failing_boundary_publish(
    chunked_bits, tmp_path, monkeypatch
):
    """Regression (review finding): a SnapshotError at the boundary
    publish must export the snapshot span (error-tagged), not drop the
    one record of the failing publish."""
    from tpu_aerial_transport.harness import checkpoint
    from tpu_aerial_transport.resilience import recovery

    run, state0, cs0 = chunked_bits
    tr = trace_mod.Tracer()

    def boom(*a, **k):
        raise checkpoint.SnapshotError("unreadable", "x", "disk gone")

    monkeypatch.setattr(recovery.checkpoint, "save_snapshot", boom)
    plan = recovery.RunPlan(run_dir=str(tmp_path / "run"),
                            n_hl_steps=4, n_chunks=2)
    with pytest.raises(checkpoint.SnapshotError):
        recovery.run_chunks(
            plan, run.chunk_jit, run.init_carry(state0, cs0), tracer=tr,
        )
    snap = [r for r in tr.rows if r["name"] == trace_mod.SNAPSHOT]
    assert len(snap) == 1 and snap[0]["attrs"]["error"] == "snapshot"
    chunk = [r for r in tr.rows if r["name"] == trace_mod.CHUNK]
    assert chunk[0]["attrs"]["error"] == "snapshot"
    run_row = [r for r in tr.rows if r["name"] == trace_mod.RUN]
    assert run_row[0]["attrs"]["status"] == "error"


def test_run_health_renders_critical_path_section(traced_serving_run):
    _, _, _, _, mpath = traced_serving_run
    proc = subprocess.run(
        [sys.executable, RUN_HEALTH, mpath],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "critical path (distributed tracing" in out
    assert "worst request: req" in out
    for seg in trace_mod.SEGMENTS:
        assert f"| {seg} |" in out
    # And the trace still validates as metrics jsonl (ci gate).
    gate = subprocess.run(
        [sys.executable, RUN_HEALTH, "--validate", mpath],
        capture_output=True, text=True, cwd=REPO,
    )
    assert gate.returncode == 0, gate.stdout + gate.stderr


def test_chrome_trace_of_serving_run_validates(traced_serving_run, tmp_path):
    _, tracer, _, _, _ = traced_serving_run
    out = str(tmp_path / "serve.trace.json")
    obj = trace_mod.write_chrome_trace(out, tracer.rows)
    assert trace_mod.validate_chrome_trace(obj) == []
    # Perfetto-loadable basics: process metadata + X slices present.
    phs = {e["ph"] for e in obj["traceEvents"]}
    assert "X" in phs and "M" in phs


# --------------------------- guard + recovery --------------------------

def test_guard_spans_carry_rung_and_error_kind():
    from tpu_aerial_transport.resilience import backend as backend_mod

    tr = trace_mod.Tracer(track="guard")
    faults = backend_mod.FaultInjector.from_env("crash@boom")
    guard = backend_mod.BackendGuard(
        faults=faults, tracer=tr, primary_rung="on-chip",
    )
    parent = tr.begin("chunk_dispatch", parent=None, lanes=[[0, "r", "t"]])
    value, rung = guard.run("boom", lambda: 42, fallback_fn=lambda: 7,
                            trace_parent=parent)
    tr.end(parent)
    assert (value, rung) == (7, backend_mod.RUNG_CPU)
    g = [r for r in tr.rows if r["name"] == trace_mod.GUARD_DISPATCH]
    f = [r for r in tr.rows if r["name"] == trace_mod.GUARD_FALLBACK]
    assert len(g) == 1 and len(f) == 1
    assert g[0]["attrs"]["kind"] == "device_crash"
    assert g[0]["parent_id"] == parent.span_id
    assert f[0]["attrs"]["rung"] == backend_mod.RUNG_CPU
    assert f[0]["attrs"]["after"] == "device_crash"
    # The fallback span inherits the dispatch's lane map through the
    # parent chain (the accountant's "retry" segment linkage).
    by_id = {r["span_id"]: r for r in tr.rows}
    assert trace_mod._members(f[0], by_id) == ["t"]


def test_guard_success_span_records_rung():
    from tpu_aerial_transport.resilience import backend as backend_mod

    tr = trace_mod.Tracer()
    guard = backend_mod.BackendGuard(tracer=tr, primary_rung="cpu-tagged")
    value, rung = guard.run("ok", lambda: 1)
    assert value == 1
    g = [r for r in tr.rows if r["name"] == trace_mod.GUARD_DISPATCH]
    assert len(g) == 1 and g[0]["attrs"]["rung"] == rung


@pytest.fixture(scope="module")
def chunked_bits():
    import jax.numpy as jnp

    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.harness import rollout as h_rollout
    from tpu_aerial_transport.harness import setup

    params, col, state0 = setup.rqp_setup(4)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=8
    )
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)
    x0 = state0.xl

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=4, n_chunks=2,
        acc_des_fn=acc_des_fn,
    )
    cs0 = centralized.init_ctrl_state(params, cfg)
    return run, state0, cs0


def test_run_chunks_emits_chunk_spans(chunked_bits, tmp_path):
    from tpu_aerial_transport.resilience import recovery

    run, state0, cs0 = chunked_bits
    tr = trace_mod.Tracer(track="p0of1")
    plan = recovery.RunPlan(run_dir=str(tmp_path / "run"),
                            n_hl_steps=4, n_chunks=2)
    result = recovery.run_chunks(
        plan, run.chunk_jit, run.init_carry(state0, cs0), tracer=tr,
    )
    assert result.status == "done"
    names = [r["name"] for r in tr.rows]
    assert names.count(trace_mod.CHUNK) == 2
    assert names.count(trace_mod.SNAPSHOT) == 2
    assert names.count(trace_mod.RUN) == 1
    run_row = next(r for r in tr.rows if r["name"] == trace_mod.RUN)
    assert run_row["attrs"]["status"] == "done"
    for r in tr.rows:
        if r["name"] == trace_mod.CHUNK:
            assert r["parent_id"] == run_row["span_id"]
        if r["name"] == trace_mod.SNAPSHOT:
            assert r["parent_id"] in {
                c["span_id"] for c in tr.rows
                if c["name"] == trace_mod.CHUNK
            }


def test_resume_trace_shows_boundary_with_parented_spans(
    chunked_bits, tmp_path
):
    """The resume acceptance shape: a preempted run's trace (pre spans)
    plus the resumed run's trace (resume span + post chunk spans
    parented under it), both in the run dir's metrics files, stitch into
    one validating trace."""
    from tpu_aerial_transport.resilience import recovery

    run, state0, cs0 = chunked_bits
    run_dir = str(tmp_path / "run")
    m1 = os.path.join(run_dir, "trace.pre.metrics.jsonl")
    tr1 = trace_mod.Tracer(export_mod.MetricsWriter(m1), track="p0of1")
    plan = recovery.RunPlan(run_dir=run_dir, n_hl_steps=4, n_chunks=2)

    class _Trip:  # trigger after chunk 0 completes.
        @property
        def triggered(self):
            journal = recovery.RunJournal(run_dir)
            return ("SIM" if 0 in journal.completed_chunks() else None)

    r1 = recovery.run_chunks(
        plan, run.chunk_jit, run.init_carry(state0, cs0),
        interrupt=_Trip(), tracer=tr1,
    )
    assert r1.status == "preempted" and r1.chunks_done == 1

    m2 = os.path.join(run_dir, "trace.post.metrics.jsonl")
    tr2 = trace_mod.Tracer(export_mod.MetricsWriter(m2), track="p0of1")
    r2 = recovery.resume_run(
        run_dir, run.chunk_jit, run.init_carry(state0, cs0), tracer=tr2,
    )
    assert r2.status == "done" and r2.resumed_from_chunk == 1

    resume_row = next(r for r in tr2.rows
                      if r["name"] == trace_mod.RESUME)
    run_row = next(r for r in tr2.rows if r["name"] == trace_mod.RUN)
    assert resume_row["attrs"]["start_chunk"] == 1
    assert run_row["parent_id"] == resume_row["span_id"]
    assert run_row["trace_id"] == resume_row["trace_id"]
    post_chunks = [r for r in tr2.rows if r["name"] == trace_mod.CHUNK]
    assert len(post_chunks) == 1 and post_chunks[0]["attrs"]["chunk"] == 1
    assert post_chunks[0]["parent_id"] == run_row["span_id"]
    # Pre spans: chunk 0 + the preemption instant.
    assert any(r["name"] == "preempted" for r in tr1.rows)

    # The whole run dir stitches into one validating Perfetto trace.
    rows = trace_mod.stitch_run_dir(run_dir)
    assert len(rows) == len(tr1.rows) + len(tr2.rows)
    obj = trace_mod.chrome_trace(rows)
    assert trace_mod.validate_chrome_trace(obj) == []


def test_retry_instant_on_host_level_requeue(chunked_bits, tmp_path):
    from tpu_aerial_transport.resilience import recovery

    run, state0, cs0 = chunked_bits
    tr = trace_mod.Tracer()
    calls = {"n": 0}
    real = run.chunk_jit

    def flaky(carry, i0):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("synthetic device error")
        return real(carry, i0)

    plan = recovery.RunPlan(run_dir=str(tmp_path / "run"),
                            n_hl_steps=4, n_chunks=2)
    result = recovery.run_chunks(
        plan, flaky, run.init_carry(state0, cs0), max_retries=1,
        tracer=tr,
    )
    assert result.status == "done" and result.retries == 1
    retries = [r for r in tr.rows if r["name"] == trace_mod.RETRY]
    assert len(retries) == 1 and retries[0]["attrs"]["attempt"] == 1
    # The failed chunk span closed with the error, then chunk 0 ran again.
    errored = [r for r in tr.rows if r["name"] == trace_mod.CHUNK
               and "error" in r.get("attrs", {})]
    assert len(errored) == 1


# ------------------------------ zero cost ------------------------------

def test_tracer_none_is_zero_cost_and_hlo_identical():
    """tracer=None: no trace handles on tickets, no rows anywhere — and
    since tracing is host-only, the served program's lowered HLO is
    byte-identical with a tracer active vs absent (the no_faults() /
    telemetry=None contract)."""
    import jax

    from tpu_aerial_transport.serving import batcher

    def lowered(with_tracer: bool):
        jax.clear_caches()  # identical trace-cache footing (PR 12 rule).
        fam = batcher.make_family("centralized4")
        carry = fam.template_carry_host()
        batch = jax.tree.map(lambda x: np.stack([x, x]), carry)
        if with_tracer:
            tr = trace_mod.Tracer()
            with tr.span(trace_mod.CHUNK_DISPATCH):
                return jax.jit(fam.batched_fn).lower(
                    batch, np.int32(0)
                ).as_text()
        return jax.jit(fam.batched_fn).lower(batch, np.int32(0)).as_text()

    assert lowered(False) == lowered(True)


def test_minted_trace_id_reaches_the_serving_journal(tmp_path):
    """Regression (review finding): admission mints the trace_id onto a
    REPLACED request object; the server must journal that one, or
    resume re-mints and pre/post-resume spans land on different traces
    (the acceptance criterion's trace-identity contract)."""
    from tpu_aerial_transport.resilience.recovery import RunJournal
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    run_dir = str(tmp_path / "run")
    tracer = trace_mod.Tracer()
    server = server_mod.ScenarioServer(
        families=["centralized4"], buckets=(8,), run_dir=run_dir,
        tracer=tracer,
    )
    t = server.submit(ScenarioRequest(family="centralized4", horizon=2,
                                      request_id="rj0"))
    assert t.trace is not None and t.request.trace_id == t.trace.trace_id
    rows = [e for e in RunJournal(run_dir, server_mod.SERVING_JOURNAL)
            .read() if e.get("event") == "serving_request"]
    assert len(rows) == 1
    assert rows[0]["request"]["trace_id"] == t.trace.trace_id
    # And the round-trip the resume path performs keeps it.
    back = ScenarioRequest.from_json(rows[0]["request"])
    assert back.trace_id == t.trace.trace_id


def test_pods_runner_normalizes_falsy_tracer(tmp_path):
    """Regression (review finding): a caller passing tracer=False (the
    bool(flag) idiom) must get the untraced zero-cost path, not False
    leaking through the `tracer is not None` gates into `.begin` calls.
    """
    import jax

    from tpu_aerial_transport.parallel import pods

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual devices")
    spec = pods.resolve_pods_spec(4, "1x2", n_devices=2, n_processes=1)
    mesh = pods.make_pods_mesh(spec)

    def chunk_fn(carry, i0):
        return carry + i0.astype(carry.dtype), carry[None]

    run = pods.pods_rollout_resumable(
        chunk_fn, mesh, n_hl_steps=2, n_chunks=2,
        run_dir=str(tmp_path / "run"), tracer=False,
    )
    import numpy as np

    result = run(np.zeros((2, 4), np.float32))
    assert result.status == "done"
    assert not os.path.exists(
        str(tmp_path / "run" / "trace.p0of1.metrics.jsonl")
    )


def test_untraced_server_allocates_no_trace_state():
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    server = server_mod.ScenarioServer(families=["centralized4"],
                                       buckets=(8,))
    t = server.submit(ScenarioRequest(family="centralized4", horizon=2))
    assert server.tracer is None and t.trace is None
    assert t.request.trace_id is None  # no ids minted untraced.
    server.run_until_drained()
    assert t.status == "completed" and t.trace is None
