"""tools/op_profile.py: plane/line selection and op aggregation over a
synthesized xplane proto (the checked-in-fixture substitute — the proto is
built in-test so it tracks the installed schema), the --by-phase rollup's
three attribution sources (per-event tf_op stats, HLO op_name metadata,
consumer-chain inheritance for compiler-split ops), and an end-to-end
capture of a real scoped program asserting the >= 80% attribution bar."""

import importlib.util
import os
import re
import sys

import pytest

xplane_pb2 = pytest.importorskip(
    "tensorflow.tsl.profiler.protobuf.xplane_pb2"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_op_profile():
    spec = importlib.util.spec_from_file_location(
        "op_profile", os.path.join(REPO, "tools", "op_profile.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


op_profile = _load_op_profile()


def _plane(xs, name):
    plane = xs.planes.add()
    plane.name = name
    return plane


def _line(plane, name):
    line = plane.lines.add()
    line.name = name
    return line


def _event(plane, line, op_name, dur_us, tf_op=None):
    meta_id = len(plane.event_metadata) + 1
    plane.event_metadata[meta_id].name = op_name
    ev = line.events.add()
    ev.metadata_id = meta_id
    ev.duration_ps = int(dur_us * 1e6)
    if tf_op is not None:
        stat_id = len(plane.stat_metadata) + 1
        plane.stat_metadata[stat_id].name = "tf_op"
        stat = ev.stats.add()
        stat.metadata_id = stat_id
        stat.str_value = tf_op
    return ev


def _synth_space():
    """A TPU-shaped capture: one device plane with an 'XLA Ops' line
    (events carry tf_op scope stats), an 'XLA Modules' line whose single
    whole-executable event must NOT be double-counted against the ops, a
    framework line that must be ignored, and a metadata plane that must
    be skipped entirely."""
    xs = xplane_pb2.XSpace()
    dev = _plane(xs, "/device:TPU:0")
    modules = _line(dev, "XLA Modules")
    _event(dev, modules, "jit_step(1)", 1400.0)  # spans all op events.
    ops = _line(dev, "XLA Ops")
    _event(dev, ops, "fusion.1", 600.0,
           tf_op="jit(step)/tat.local_solve/dot_general")
    _event(dev, ops, "fusion.1", 400.0,
           tf_op="jit(step)/tat.local_solve/dot_general")
    _event(dev, ops, "fusion.7", 300.0,
           tf_op="jit(step)/tat.consensus/reduce_sum")
    # The cross-device exchange itself (parallel/ring.py) — scoped
    # SEPARATELY from the local consensus arithmetic so the ring-vs-
    # allreduce A/B can read the wire share off the phase table.
    _event(dev, ops, "all-reduce.2", 200.0,
           tf_op="jit(step)/tat.consensus/tat.consensus_exchange/psum")
    _event(dev, ops, "copy.3", 100.0)  # no scope: unattributed.
    host_frames = _line(dev, "python")
    _event(dev, host_frames, "should_not_count", 1e6)
    meta = _plane(xs, "/host:metadata")
    _event(meta, _line(meta, "whatever"), "also_not_counted", 1e6)
    return xs


def test_plane_and_line_selection_and_aggregation():
    agg = op_profile.op_aggregate([_synth_space()])
    assert "should_not_count" not in agg
    assert "also_not_counted" not in agg
    # The module-level event spans the whole executable — counting it
    # would double op_total and tank the attribution fraction.
    assert "jit_step(1)" not in agg
    assert agg["fusion.1"]["count"] == 2
    assert agg["fusion.1"]["total_us"] == pytest.approx(1000.0)
    assert agg["fusion.1"]["scope"].endswith("dot_general")
    # Back-compat per-op table shim.
    times = op_profile.device_op_times([_synth_space()])
    assert times["fusion.7"] == {"total_us": pytest.approx(300.0),
                                 "count": 1}


def test_phase_rollup_from_tf_op_stats():
    rows, op_total, attributed = op_profile.rollup_phases(
        op_profile.op_aggregate([_synth_space()]), hlo_map=None
    )
    assert op_total == pytest.approx(1600.0)
    assert attributed == pytest.approx(1500.0)
    assert rows["local_solve"]["total_us"] == pytest.approx(1000.0)
    assert rows["consensus"]["total_us"] == pytest.approx(300.0)
    # The exchange is its own row (innermost scope wins over the enclosing
    # tat.consensus): a regression that drops the scope from
    # parallel/ring.py would move this time to (unattributed).
    assert rows["consensus_exchange"]["total_us"] == pytest.approx(200.0)
    assert rows["(unattributed)"]["total_us"] == pytest.approx(100.0)


def test_phase_rollup_from_hlo_map_cpu_shape():
    """CPU-shaped capture: thunk lines named tf_XLAEigen/..., no per-event
    stats — attribution resolves through the HLO op_name map, including
    the .clone/renumber fallback and consumer-chain inheritance for a
    metadata-less compiler-split op."""
    xs = xplane_pb2.XSpace()
    host = _plane(xs, "/host:CPU")
    thunks = _line(host, "tf_XLAEigen/-123")
    _event(host, thunks, "dot.5", 500.0)          # exact HLO name.
    _event(host, thunks, "sine.4.clone", 200.0)   # renumbered clone.
    _event(host, thunks, "reduce-window", 300.0)  # no metadata: consumer.
    _event(host, thunks, "while.36", 50.0)        # genuinely unattributed.
    client = _line(host, "tf_XLATfrtCpuClient/9")
    _event(host, client, "TfrtCpuExecutable::Execute", 5000.0)

    hlo = """
  %sine.0.clone = f32[8]{0} sine(f32[8]{0} %p), metadata={op_name="jit(f)/tat.local_solve/sin"}
  %dot.5 = f32[8]{0} dot(f32[8]{0} %sine.0.clone, f32[8]{0} %q), metadata={op_name="jit(f)/tat.local_solve/dot_general"}
  %reduce-window = f32[2]{0} reduce-window(f32[8]{0} %dot.5, f32[] %c)
  %reduce.0 = f32[]{} reduce(f32[2]{0} %reduce-window, f32[] %c), metadata={op_name="jit(f)/tat.consensus/reduce_sum"}
"""
    hlo_path = None
    import tempfile
    with tempfile.NamedTemporaryFile(
        "w", suffix=".hlo.txt", delete=False
    ) as fh:
        fh.write(hlo)
        hlo_path = fh.name
    try:
        hlo_map = op_profile.load_hlo_map(hlo_path)
    finally:
        os.unlink(hlo_path)
    # Consumer inheritance: the split reduce-window inherits reduce.0's
    # consensus scope.
    assert op_profile.phase_of(hlo_map["reduce-window"]) == "consensus"

    agg = op_profile.op_aggregate([xs])
    rows, op_total, attributed = op_profile.rollup_phases(agg, hlo_map)
    # The client-line framework event never enters the aggregation; the
    # '::' guard is belt-and-suspenders for broad-filter fallbacks.
    assert "TfrtCpuExecutable::Execute" not in agg
    assert op_total == pytest.approx(1050.0)
    assert rows["local_solve"]["total_us"] == pytest.approx(700.0)
    assert rows["consensus"]["total_us"] == pytest.approx(300.0)
    assert rows["(unattributed)"]["total_us"] == pytest.approx(50.0)
    assert attributed / op_total >= 0.8


def test_phase_of_uses_innermost_scope():
    assert op_profile.phase_of(
        "jit(f)/tat.sharded_step/while/tat.local_solve/dot"
    ) == "local_solve"
    assert op_profile.phase_of(
        "jit(f)/tat.consensus/tat.consensus_exchange/ppermute"
    ) == "consensus_exchange"
    assert op_profile.phase_of("jit(f)/while/dot") is None
    assert op_profile.phase_of(None) is None


def test_phase_vocabulary_covers_consensus_exchange():
    """The obs.phases vocabulary (the rollup's row names) must carry the
    exchange phase: every impl of parallel.ring.consensus_exchange runs
    inside this scope, and bench A/Bs read the wire share off it."""
    from tpu_aerial_transport.obs import phases

    assert phases.CONSENSUS_EXCHANGE == "consensus_exchange"
    assert phases.CONSENSUS_EXCHANGE in phases.PHASES


def test_real_trace_ring_exchange_attribution(tmp_path):
    """End-to-end on a real capture of the ppermute ring exchange under
    shard_map (the sharded consensus hot path's communication shape): the
    exchange ops attribute under tat.consensus_exchange — NOT
    (unattributed) — via the compiled-HLO op_name source, so a dropped
    scope in parallel/ring.py fails tier-1 on CPU instead of silently
    degrading the on-chip attribution bar."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_aerial_transport.parallel import mesh as mesh_mod
    from tpu_aerial_transport.parallel import ring
    from tpu_aerial_transport.utils import compat

    d = 4
    m = mesh_mod.make_mesh({"agent": d})

    @jax.jit
    @functools.partial(
        compat.shard_map, mesh=m, in_specs=P("agent"),
        out_specs=P("agent"), check_vma=False,
    )
    def step(v):
        x = v[0]
        for _ in range(8):  # enough exchange work to show up in the trace.
            x = ring.consensus_exchange(
                x, "agent", axis_size=d, op="sum", impl="ring"
            ) / d
        return x[None]

    x = jnp.ones((d, 256, 128), jnp.float32)
    step(x).block_until_ready()
    trace_dir = str(tmp_path / "trace")
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            step(x).block_until_ready()
    with open(os.path.join(trace_dir, "headline.hlo.txt"), "w") as fh:
        fh.write(jax.jit(step).lower(x).compile().as_text())

    agg = op_profile.op_aggregate(op_profile.load_xplanes(trace_dir))
    assert agg, "no op events captured"
    hlo_map = op_profile.load_hlo_map(op_profile.find_hlo_dump(trace_dir))
    rows, op_total, _ = op_profile.rollup_phases(agg, hlo_map)
    assert op_total > 0
    assert "consensus_exchange" in rows, rows.keys()
    assert rows["consensus_exchange"]["total_us"] > 0


def test_real_trace_attribution_meets_bar(tmp_path):
    """End-to-end on a real capture of a scoped scan program (the shape of
    the rollout hot loop): >= 80% of XLA op self-time attributes to tat.*
    phases — the ISSUE 5 acceptance bar, runnable on CPU."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tpu_aerial_transport.obs import phases

    @jax.jit
    def step(x):
        def body(c, _):
            with phases.scope(phases.LOCAL_SOLVE):
                c = jnp.tanh(c @ c)
            with phases.scope(phases.CONSENSUS):
                c = c - jnp.mean(c, axis=0, keepdims=True)
            return c, None

        return lax.scan(body, x, None, length=24)[0]

    # Compute-dominant sizing (the real control step's shape: the scoped
    # solve/consensus ops dwarf loop bookkeeping); on a toy-sized carry
    # the pre-loop input copies and while-thunk overhead — genuinely
    # phase-less — would swamp the ratio.
    x = jnp.eye(256) * 0.5
    step(x).block_until_ready()
    trace_dir = str(tmp_path / "trace")
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            step(x).block_until_ready()
    with open(os.path.join(trace_dir, "headline.hlo.txt"), "w") as fh:
        fh.write(jax.jit(step).lower(x).compile().as_text())

    agg = op_profile.op_aggregate(op_profile.load_xplanes(trace_dir))
    assert agg, "no op events captured"
    hlo_map = op_profile.load_hlo_map(
        op_profile.find_hlo_dump(trace_dir)
    )
    rows, op_total, attributed = op_profile.rollup_phases(agg, hlo_map)
    assert op_total > 0
    frac = attributed / op_total
    assert frac >= 0.8, (frac, rows)
    assert "local_solve" in rows and "consensus" in rows


def test_phase_vocabulary_covers_env_query():
    """The obs.phases vocabulary must carry the environment-query phase:
    both query impls (the dense forest sweep and the bucketed slab
    gather, envs/forest.py / envs/spatial.py) run inside this scope, and
    the bench env_* A/B cells read the query share off it."""
    from tpu_aerial_transport.obs import phases

    assert phases.ENV_QUERY == "env_query"
    assert phases.ENV_QUERY in phases.PHASES


@pytest.mark.parametrize("env_query", ["dense", "bucketed"])
def test_real_trace_env_query_attribution(env_query, tmp_path):
    """End-to-end on a real capture of the batched environment query
    (both impls): the sweep/gather ops attribute under tat.env_query —
    NOT (unattributed) — via the compiled-HLO op_name source, so a
    dropped scope in envs/forest.py or envs/spatial.py fails tier-1 on
    CPU instead of silently degrading the on-chip attribution bar."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.envs import spatial as spatial_mod

    op_profile = _load_op_profile()
    forest = forest_mod.make_forest(seed=0)
    if env_query == "bucketed":
        forest = spatial_mod.with_grid(forest, 6.3)

    @jax.jit
    def step(xs, vs):
        def one(x, v):
            return forest_mod.collision_cbf_rows(
                forest, x, v, 1.0, 2.0, 6.0, 0.1, 1.5, 10,
                env_query=env_query,
            )

        cbf = jax.vmap(one)(xs, vs)
        return cbf.min_dist

    rng = np.random.default_rng(0)
    xs = jnp.asarray(
        np.concatenate([rng.uniform(5, 55, (64, 2)),
                        np.full((64, 1), 2.0)], axis=1), jnp.float32)
    vs = jnp.asarray(rng.normal(size=(64, 3)), jnp.float32)
    step(xs, vs).block_until_ready()
    trace_dir = str(tmp_path / "trace")
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            step(xs, vs).block_until_ready()
    with open(os.path.join(trace_dir, "headline.hlo.txt"), "w") as fh:
        fh.write(jax.jit(step).lower(xs, vs).compile().as_text())

    agg = op_profile.op_aggregate(op_profile.load_xplanes(trace_dir))
    assert agg, "no op events captured"
    hlo_map = op_profile.load_hlo_map(op_profile.find_hlo_dump(trace_dir))
    rows, op_total, _ = op_profile.rollup_phases(agg, hlo_map)
    assert op_total > 0
    assert "env_query" in rows, rows.keys()
    assert rows["env_query"]["total_us"] > 0
