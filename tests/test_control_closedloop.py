"""Closed-loop integration tests: SO(3) tracking convergence and the full
centralized-MPC rollout (reference test/utils/test_so3tracking.py and
test/control/test_rqpcontrollers.py, with asserted bounds)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.control import centralized, lowlevel, so3_tracking
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.harness import rollout as ro
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _so3_convergence(law, params):
    """Integrate rigid-body attitude dynamics under the tracking law toward a
    fixed random target (the reference's self-contained rotational integrator,
    test_so3tracking.py:36-47)."""
    J = jnp.diag(jnp.array([2.32e-3, 2.32e-3, 4e-3]))
    J_inv = jnp.linalg.inv(J)
    Rd = lie.expm_so3(jnp.array([0.5, -0.7, 0.3]))
    wd = jnp.zeros(3)
    dwd = jnp.zeros(3)
    dt = 1e-3

    def body(carry, _):
        R, w = carry
        M = law(R, Rd, w, wd, dwd, J, params)
        dw = J_inv @ (M - jnp.cross(w, J @ w))
        R = R @ lie.expm_so3((w + dw * dt / 2) * dt)
        w = w + dw * dt
        R = lie.polar_project(R)
        e_R = 0.5 * lie.vee(Rd.T @ R - R.T @ Rd)
        return (R, w), jnp.linalg.norm(e_R)

    R0 = jnp.eye(3)
    w0 = jnp.zeros(3)
    (_, _), errs = jax.lax.scan(body, (R0, w0), None, length=4000)
    return errs


def test_so3_pd_convergence():
    errs = _so3_convergence(
        so3_tracking.so3_pd_tracking_control, so3_tracking.So3PDParams()
    )
    assert float(errs[-1]) < 1e-2
    assert float(errs[-1]) < float(errs[0])


def test_so3_sm_convergence():
    errs = _so3_convergence(
        so3_tracking.so3_sm_tracking_control, so3_tracking.So3SMParams()
    )
    assert float(errs[-1]) < 1e-2


def test_lowlevel_thrust_projection():
    params, _, state = setup.rqp_setup(3)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    f_des = jnp.tile(jnp.array([0.0, 0.0, 5.0]), (3, 1))
    f, M = ll.control(state, f_des)
    # Identity attitude: thrust = f_des_z, zero attitude error -> zero moment.
    assert jnp.abs(f - 5.0).max() < 1e-5
    assert jnp.abs(M).max() < 1e-5


def test_centralized_closedloop_hover_to_point():
    """Centralized MPC + low-level PD must fly the payload from rest to a nearby
    setpoint with bounded velocity and tilt (the safety CBFs) and settle."""
    params, col, state0 = setup.rqp_setup(3)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=120
    )
    f_eq = centralized.equilibrium_forces(params)
    cs0 = centralized.init_ctrl_state(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)

    target = jnp.array([1.0, 0.5, 0.3])

    def acc_des_fn(state, t):
        dvl_des = -1.5 * state.vl - 1.0 * (state.xl - target)
        nrm = jnp.linalg.norm(dvl_des)
        dvl_des = jnp.where(nrm > 1.0, dvl_des / jnp.where(nrm > 0, nrm, 1), dvl_des)
        return (dvl_des, jnp.zeros(3)), target, jnp.zeros(3)

    hl = lambda cs, s, acc: centralized.control(params, cfg, f_eq, cs, s, acc)
    final, _, logs = jax.jit(
        lambda s0, c0: ro.rollout(
            hl, ll.control, params, s0, c0, n_hl_steps=600,
            acc_des_fn=acc_des_fn,
        )
    )(state0, cs0)

    assert bool(jnp.all(jnp.isfinite(final.xl)))
    # Settles near the target (within 15 cm after 6 s).
    assert float(jnp.linalg.norm(final.xl - target)) < 0.15
    # Safety invariants held throughout: |vl| <= 1 (+5% slack), tilt <= 15 deg.
    assert float(jnp.max(jnp.linalg.norm(logs.vl, axis=-1))) < 1.05
    cos_tilt = logs.Rl[:, 2, 2]
    assert float(jnp.min(cos_tilt)) > float(jnp.cos(jnp.pi / 12)) - 0.02
    # Solver converged throughout.
    assert float(jnp.max(logs.solve_res)) < 5e-3


def test_centralized_forest_rollout_avoids_trees():
    """Short forest traversal: the collision CBF rows must keep min distance
    above dist_eps (the reference's safety invariant, SURVEY.md §6)."""
    params, col, state0 = setup.rqp_setup(3)
    forest = forest_mod.make_forest(seed=0)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=120
    )
    f_eq = centralized.equilibrium_forces(params)
    cs0 = centralized.init_ctrl_state(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    acc_des_fn = ro.make_forest_acc_des(forest)

    # Start near the forest edge at cruise height, flying in.
    state0 = state0.replace(
        xl=jnp.array([2.0, 0.5, 1.5], jnp.float32),
        vl=jnp.array([0.5, 0.0, 0.0], jnp.float32),
    )

    def hl(cs, s, acc):
        env_cbf = forest_mod.collision_cbf_rows(
            forest, s.xl, s.vl, col.collision_radius, col.max_deceleration,
            cfg.vision_radius, cfg.dist_eps, cfg.alpha_env_cbf, cfg.n_env_cbfs,
        )
        return centralized.control(params, cfg, f_eq, cs, s, acc, env_cbf)

    final, _, logs = jax.jit(
        lambda s0, c0: ro.rollout(
            hl, ll.control, params, s0, c0, n_hl_steps=800,
            acc_des_fn=acc_des_fn,
        )
    )(state0, cs0)

    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert not bool(jnp.any(logs.collision))
    # Safety margin: distance stays above dist_eps.
    assert float(jnp.min(logs.min_env_dist)) > cfg.dist_eps
    # It actually makes forward progress.
    assert float(final.xl[0]) > float(state0.xl[0]) + 1.0
