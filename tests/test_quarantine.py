"""NaN-quarantine tests: a diverging Monte-Carlo lane is frozen and flagged
while every other lane's logs and the masked aggregate statistics stay
bit-identical to a batch without the diverging lane."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport import resilience
from tpu_aerial_transport.control import cadmm, lowlevel
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.harness import bucketing, setup
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.quarantine import (
    tree_all_finite,
    tree_where,
)
from tpu_aerial_transport.resilience.rollout import resilient_rollout
from tpu_aerial_transport.utils import stats as stats_mod


def test_tree_all_finite_and_where():
    good = {"a": jnp.ones(3), "b": jnp.zeros((), jnp.int32)}
    bad = {"a": jnp.array([1.0, jnp.nan, 0.0]), "b": jnp.ones((), jnp.int32)}
    assert bool(tree_all_finite(good))
    assert not bool(tree_all_finite(bad))  # int leaves ignored, NaN caught.
    sel = tree_where(jnp.zeros((), bool), bad, good)
    assert bool(tree_all_finite(sel))


def test_masked_aggregate_statistics():
    a = jnp.array([[1.0, 2.0], [jnp.nan, jnp.inf], [3.0, 4.0]])
    valid = jnp.array([True, False, True])
    mn, mx, avg, std = stats_mod.compute_aggregate_statistics(a, 0, valid)
    np.testing.assert_allclose(np.asarray(mn), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(mx), [3.0, 4.0])
    np.testing.assert_allclose(np.asarray(avg), [2.0, 3.0])
    np.testing.assert_allclose(np.asarray(std), [1.0, 1.0])
    # Unmasked path unchanged (and poisoned, as expected).
    _, _, avg_all, _ = stats_mod.compute_aggregate_statistics(a, 0)
    assert not bool(jnp.isfinite(avg_all[0]))


def test_bucketing_metric_quarantine_guard():
    forest = forest_mod.make_forest(seed=0)
    metric = bucketing.quarantine_guarded_metric(
        bucketing.env_congestion_metric(forest, vision_radius=8.0)
    )
    _, _, state = setup.rqp_setup(3)
    good = state.replace(xl=jnp.array([5.0, 0.0, 1.5]))
    bad = state.replace(xl=jnp.array([jnp.nan, 0.0, 1.5]))
    assert int(metric(good)) >= 0
    assert int(metric(bad)) == -1


def _batched_rollout(n=4, batch=3, n_steps=12):
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=15,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)

    def run(scheds):
        return jax.jit(jax.vmap(
            lambda f: resilient_rollout(
                hl, ll.control, params, state0, cs0, n_hl_steps=n_steps,
                faults=f,
            )
        ))(scheds)

    return params, run


def _stack_schedules(scheds):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *scheds)


def test_nan_lane_is_quarantined_and_others_bit_identical():
    """Lane 1's actuator gain blows up to +inf mid-rollout (direct physics
    NaN injection, past the fallback ladder); the lane must freeze with its
    sticky flag raised while lanes 0 and 2 produce BIT-IDENTICAL logs to a
    batch whose lane 1 is benign — the quarantine keeps the divergence from
    leaking across the vmap."""
    n, B = 4, 3
    params, run = _batched_rollout(n=n, batch=B)
    benign = [faults_mod.make_schedule(n, key=jax.random.PRNGKey(k))
              for k in range(B)]
    killer = faults_mod.make_schedule(
        n, t_degrade={0: 5}, thrust_scale=jnp.inf,
        key=jax.random.PRNGKey(1),
    )
    batch_bad = _stack_schedules([benign[0], killer, benign[2]])
    batch_good = _stack_schedules(benign)

    _, _, logs_bad = run(batch_bad)
    _, _, logs_good = run(batch_good)

    # The poisoned lane froze and flagged instead of emitting NaN physics.
    assert bool(jnp.any(logs_bad.quarantined[1]))
    q_from = int(jnp.argmax(logs_bad.quarantined[1]))
    frozen = logs_bad.xl[1, q_from:]
    assert bool(jnp.all(frozen == frozen[0:1]))
    # Other lanes: every logged leaf bit-identical to the all-benign batch.
    for name in ("xl", "vl", "Rl", "wl", "R", "w", "f_des", "x_err",
                 "v_err", "iters", "solve_res", "fallback_rung"):
        a = np.asarray(getattr(logs_bad, name))[[0, 2]]
        b = np.asarray(getattr(logs_good, name))[[0, 2]]
        assert np.array_equal(a, b), f"lane leakage in {name}"
    assert not bool(jnp.any(logs_bad.quarantined[jnp.array([0, 2])]))

    # Masked aggregates over the final tracking error exclude the NaN lane.
    x_err_final = logs_bad.x_err[:, -1]
    valid = ~logs_bad.quarantined[:, -1]
    mn, mx, avg, std = stats_mod.compute_aggregate_statistics(
        x_err_final, 0, valid
    )
    assert all(bool(jnp.isfinite(v)) for v in (mn, mx, avg, std))
