"""Cross-implementation tests: the native C++ ADMM solver must agree with the
JAX solver (independent f64 oracle for the conic-QP core)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.ops import socp

native = pytest.importorskip("tpu_aerial_transport.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain on this host"
)


def _random_qp(seed, nv=8, n_eq=3, n_ineq=6):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    L = jax.random.normal(ks[0], (nv, nv)) * 0.5
    P = L @ L.T + 0.5 * jnp.eye(nv)
    q = jax.random.normal(ks[1], (nv,))
    A_eq = jax.random.normal(ks[2], (n_eq, nv))
    b_eq = jax.random.normal(ks[3], (n_eq,)) * 0.3
    A_in = jax.random.normal(ks[4], (n_ineq, nv))
    A = jnp.concatenate([A_eq, A_in], axis=0)
    lb = jnp.concatenate([b_eq, jnp.full((n_ineq,), -socp.INF)])
    ub = jnp.concatenate([b_eq, jnp.ones((n_ineq,))])
    return P, q, A, lb, ub


@pytest.mark.parametrize("seed", range(4))
def test_native_matches_jax_qp(seed):
    P, q, A, lb, ub = _random_qp(seed)
    jx = socp.solve_socp(P, q, A, lb, ub, n_box=9, iters=800)
    x, _, _, prim, _ = native.solve_socp_native(
        P, q, A, lb, ub, n_box=9, iters=800
    )
    assert prim < 1e-6  # f64 converges tighter than the f32 JAX path.
    assert np.abs(x - np.asarray(jx.x)).max() < 5e-3


def test_native_soc_projection_problem():
    p = np.array([0.5, 3.0, -4.0, 1.0])
    P = 2 * np.eye(4)
    q = -2.0 * p
    A = np.eye(4)
    x, _, _, prim, _ = native.solve_socp_native(
        P, q, A, np.zeros(0), np.zeros(0), n_box=0, soc_dims=(4,), iters=800
    )
    expected = np.asarray(socp.project_soc(jnp.asarray(p)))
    assert np.abs(x - expected).max() < 1e-4


def test_native_shifted_cone():
    """Norm cap via shifted SOC: min ||x - p||^2 s.t. ||x|| <= 1."""
    p = np.array([2.0, 1.0, -2.0])
    P = 2 * np.eye(3)
    q = -2 * p
    A = np.concatenate([np.zeros((1, 3)), np.eye(3)], axis=0)
    shift = np.array([1.0, 0.0, 0.0, 0.0])
    x, _, _, prim, _ = native.solve_socp_native(
        P, q, A, np.zeros(0), np.zeros(0), n_box=0, soc_dims=(4,),
        iters=800, shift=shift,
    )
    assert abs(np.linalg.norm(x) - 1.0) < 1e-4
    assert np.abs(x - p / np.linalg.norm(p)).max() < 1e-4


def test_native_batch():
    Ps, qs, As, lbs, ubs = [], [], [], [], []
    for seed in range(6):
        P, q, A, lb, ub = _random_qp(seed + 50)
        Ps.append(P), qs.append(q), As.append(A), lbs.append(lb), ubs.append(ub)
    x, res = native.solve_socp_native_batch(
        np.stack(Ps), np.stack(qs), np.stack(As), np.stack(lbs), np.stack(ubs),
        n_box=9, iters=600,
    )
    assert x.shape == (6, 8)
    assert res[:, 0].max() < 1e-5
    # Spot-check one instance against the JAX path.
    jx = socp.solve_socp(Ps[2], qs[2], As[2], lbs[2], ubs[2], n_box=9, iters=800)
    assert np.abs(x[2] - np.asarray(jx.x)).max() < 5e-3


def test_native_warm_start_fixed_point():
    P, q, A, lb, ub = _random_qp(11)
    x, y, z, _, _ = native.solve_socp_native(P, q, A, lb, ub, n_box=9, iters=800)
    x2, _, _, prim, _ = native.solve_socp_native(
        P, q, A, lb, ub, n_box=9, iters=5, warm=(x, y, z)
    )
    assert np.abs(x2 - x).max() < 1e-6
