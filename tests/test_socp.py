"""Tests for the batched conic-QP solver (ops/socp.py) — KKT residuals and
agreement with independent oracles (equality-KKT closed form, scipy SLSQP),
the gate from SURVEY.md §7 stage 3."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.ops import socp


def test_project_soc_cases():
    # Inside: unchanged.
    z = jnp.array([2.0, 1.0, 0.5])
    assert jnp.allclose(socp.project_soc(z), z)
    # Polar cone: zero.
    z = jnp.array([-2.0, 1.0, 0.5])
    assert jnp.allclose(socp.project_soc(z), 0.0)
    # Outside: projection satisfies ||v|| == t and is idempotent.
    z = jnp.array([0.5, 3.0, -4.0])
    p = socp.project_soc(z)
    assert jnp.abs(jnp.linalg.norm(p[1:]) - p[0]) < 1e-6
    assert jnp.allclose(socp.project_soc(p), p, atol=1e-6)
    # Batched.
    zb = jnp.stack([z, z, z])
    assert socp.project_soc(zb).shape == (3, 3)


def _random_qp(key, nv=8, n_eq=3, n_ineq=6):
    ks = jax.random.split(key, 5)
    L = jax.random.normal(ks[0], (nv, nv)) * 0.5
    P = L @ L.T + 0.5 * jnp.eye(nv)
    q = jax.random.normal(ks[1], (nv,))
    A_eq = jax.random.normal(ks[2], (n_eq, nv))
    b_eq = jax.random.normal(ks[3], (n_eq,)) * 0.3
    A_in = jax.random.normal(ks[4], (n_ineq, nv))
    # A_in x <= 1 (feasible near origin).
    A = jnp.concatenate([A_eq, A_in], axis=0)
    lb = jnp.concatenate([b_eq, jnp.full((n_ineq,), -socp.INF)])
    ub = jnp.concatenate([b_eq, jnp.ones((n_ineq,))])
    return P, q, A, lb, ub


def test_equality_qp_matches_kkt_closed_form():
    """Pure equality QP has a closed-form KKT solution to compare against."""
    key = jax.random.PRNGKey(0)
    P, q, A, lb, ub = _random_qp(key, nv=8, n_eq=4, n_ineq=0)
    A_eq, b_eq = A, lb
    sol = socp.solve_socp(P, q, A, lb, ub, n_box=4, iters=400)
    # KKT: [P A^T; A 0] [x; nu] = [-q; b].
    nv, ne = 8, 4
    K = jnp.block([[P, A_eq.T], [A_eq, jnp.zeros((ne, ne))]])
    rhs = jnp.concatenate([-q, b_eq])
    xnu = jnp.linalg.solve(K, rhs)
    assert jnp.abs(sol.x - xnu[:nv]).max() < 1e-3
    assert float(sol.prim_res) < 1e-4


@pytest.mark.parametrize("seed", range(4))
def test_random_qp_matches_scipy(seed):
    from scipy.optimize import minimize

    P, q, A, lb, ub = _random_qp(jax.random.PRNGKey(seed), nv=8, n_eq=3, n_ineq=6)
    sol = socp.solve_socp(P, q, A, lb, ub, n_box=9, iters=800)
    Pn, qn, An = np.asarray(P, np.float64), np.asarray(q, np.float64), np.asarray(A, np.float64)
    cons = [
        {"type": "eq", "fun": lambda x: An[:3] @ x - np.asarray(lb[:3])},
        {"type": "ineq", "fun": lambda x: np.asarray(ub[3:]) - An[3:] @ x},
    ]
    ref = minimize(
        lambda x: 0.5 * x @ Pn @ x + qn @ x,
        np.zeros(8),
        jac=lambda x: Pn @ x + qn,
        constraints=cons,
        method="SLSQP",
        options={"maxiter": 200, "ftol": 1e-12},
    )
    assert ref.success
    obj_admm = 0.5 * np.asarray(sol.x) @ Pn @ np.asarray(sol.x) + qn @ np.asarray(sol.x)
    # Objective agreement (solutions may differ along near-degenerate directions).
    assert abs(obj_admm - ref.fun) < 2e-3 * max(1.0, abs(ref.fun))
    assert float(sol.prim_res) < 2e-3


def test_socp_projection_problem():
    """min ||x - p||^2 s.t. x in SOC == closed-form cone projection."""
    p = jnp.array([0.5, 3.0, -4.0, 1.0])
    nv = 4
    P = 2 * jnp.eye(nv)
    q = -2.0 * p
    A = jnp.eye(nv)  # A x = x must lie in SOC(4).
    lb = ub = jnp.zeros((0,))
    sol = socp.solve_socp(P, q, A, lb, ub, n_box=0, soc_dims=(4,), iters=400)
    assert jnp.abs(sol.x - socp.project_soc(p)).max() < 1e-3


def test_mixed_box_soc_kkt():
    """Thrust-cone-shaped instance: min ||f - f0||^2, f_z >= fz_min,
    ||f|| <= sec(30 deg) f_z  (the per-agent actuation set from
    control/rqp_centralized.py:185-190)."""
    f0 = jnp.array([3.0, 0.5, 2.0])
    sec30 = 1.0 / jnp.cos(jnp.pi / 6)
    P = 2 * jnp.eye(3)
    q = -2 * f0
    # Rows: [e3 (box, f_z >= 0.3)] + SOC block [sec30 * f_z; f].
    A = jnp.concatenate(
        [
            jnp.array([[0.0, 0.0, 1.0]]),
            jnp.array([[0.0, 0.0, float(sec30)]]),
            jnp.eye(3),
        ],
        axis=0,
    )
    lb = jnp.array([0.3])
    ub = jnp.array([socp.INF])
    sol = socp.solve_socp(P, q, A, lb, ub, n_box=1, soc_dims=(4,), iters=600)
    f = sol.x
    # Feasible.
    assert f[2] >= 0.3 - 1e-4
    assert jnp.linalg.norm(f) <= sec30 * f[2] + 1e-3
    # KKT residuals small.
    stat, prim, comp = socp.kkt_residuals(P, q, A, lb, ub, 1, (4,), sol)
    assert float(prim) < 1e-3
    assert float(stat) < 1e-2
    # Oracle: scipy on the smooth reformulation.
    from scipy.optimize import minimize

    f0n = np.asarray(f0, np.float64)
    ref = minimize(
        lambda x: np.sum((x - f0n) ** 2),
        np.array([0.0, 0.0, 1.0]),
        constraints=[
            {"type": "ineq", "fun": lambda x: x[2] - 0.3},
            {
                "type": "ineq",
                "fun": lambda x: (float(sec30) * x[2]) ** 2 - x @ x,
            },
        ],
        method="SLSQP",
        options={"maxiter": 200, "ftol": 1e-12},
    )
    assert np.abs(np.asarray(f) - ref.x).max() < 5e-3


def test_warm_start_accelerates():
    P, q, A, lb, ub = _random_qp(jax.random.PRNGKey(7))
    sol = socp.solve_socp(P, q, A, lb, ub, n_box=9, iters=800)
    # Re-solving the SAME problem warm-started from its solution must stay at
    # the solution after very few iterations (ADMM fixed point). Residual
    # trajectories are not monotone, so comparing warm-vs-cold at an arbitrary
    # cutoff would be flaky; the fixed-point property is the real contract.
    warm = socp.solve_socp(P, q, A, lb, ub, n_box=9, iters=10, warm=sol)
    assert jnp.abs(warm.x - sol.x).max() < 1e-3
    # Slightly perturbed problem, warm-started: converges to the perturbed
    # optimum in far fewer iterations than the cold solve needed.
    q2 = q + 0.01
    ref = socp.solve_socp(P, q2, A, lb, ub, n_box=9, iters=800)
    warm2 = socp.solve_socp(P, q2, A, lb, ub, n_box=9, iters=100, warm=sol)
    assert jnp.abs(warm2.x - ref.x).max() < 5e-3


def test_vmap_batch_of_qps():
    keys = jax.random.split(jax.random.PRNGKey(3), 16)
    Ps, qs, As, lbs, ubs = jax.vmap(_random_qp)(keys)

    batched = jax.vmap(
        lambda P, q, A, lb, ub: socp.solve_socp(
            P, q, A, lb, ub, n_box=9, iters=300
        )
    )
    sols = batched(Ps, qs, As, lbs, ubs)
    assert sols.x.shape == (16, 8)
    assert float(jnp.max(sols.prim_res)) < 5e-3


def test_early_exit_matches_fixed():
    P, q, A, lb, ub = _random_qp(jax.random.PRNGKey(11))
    fixed = socp.solve_socp(P, q, A, lb, ub, n_box=9, iters=1000)
    early = socp.solve_socp(
        P, q, A, lb, ub, n_box=9, iters=1000, check_every=50, tol=1e-4
    )
    assert jnp.abs(fixed.x - early.x).max() < 5e-3


def test_explicit_inverse_matches_f64_cholesky_on_production_kkt():
    """Accuracy regression for the explicit f32 KKT inverse (see the design
    note in ops/socp.py): on the PRODUCTION per-agent KKT matrices (whose
    conditioning depends on EQ_RHO_SCALE and the problem scaling), the f32
    ``Minv @ rhs`` must track a float64 Cholesky solve. If a config change
    worsens conditioning, this fails loudly instead of agents silently
    tripping the equilibrium-fallback path."""
    import numpy as np
    import scipy.linalg

    from tpu_aerial_transport.control import cadmm, centralized
    from tpu_aerial_transport.control.types import inactive_env_cbf
    from tpu_aerial_transport.harness import setup

    rng = np.random.default_rng(0)
    for n in (3, 8):  # full (n=3) and Schur-reduced (n=8) formulations.
        params, col, state = setup.rqp_setup(n)
        acfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration
        )
        f_eq = centralized.equilibrium_forces(params)
        cbf = inactive_env_cbf(
            acfg.n_env_cbfs, acfg.vision_radius, acfg.dist_eps,
            acfg.alpha_env_cbf, dtype=jnp.float32,
        )
        rho = jnp.float32(acfg.rho0)
        if cadmm._use_reduced(acfg, n):
            plan = cadmm.make_schur_plan(params, acfg)
            pk = jax.tree.map(lambda x: x[0, 0], plan)
            Ecc, e0s, xq = cadmm._schur_state_pieces(
                params, acfg, state, plan.scale[0, 0]
            )
            P, _, A, lb, ub, _ = cadmm._schur_step_qp(
                params, acfg, pk, f_eq, state, (jnp.zeros(3), jnp.zeros(3)),
                cbf, jnp.int32(0), jnp.float32(1.0), rho, Ecc, e0s, xq,
            )
            n_box = 7 + acfg.n_env_cbfs
        else:
            onehot = jax.nn.one_hot(0, n, dtype=jnp.float32)
            P, _, A, lb, ub, _ = cadmm._build_agent_qp(
                params, acfg, f_eq, state, (jnp.zeros(3), jnp.zeros(3)), cbf,
                onehot, jnp.float32(1.0), rho,
            )
            n_box = 13 + acfg.n_env_cbfs
        m = A.shape[0]
        rho_vec = socp.make_rho_vec(m, n_box, lb, ub, 0.4, jnp.float32)
        op = socp.kkt_operator(P, A, rho_vec)

        M64 = (np.asarray(P, np.float64)
               + float(op.sigma) * np.eye(P.shape[0])
               + np.asarray(A, np.float64).T
               @ np.diag(np.asarray(rho_vec, np.float64))
               @ np.asarray(A, np.float64))
        cho = scipy.linalg.cho_factor(M64)
        for _ in range(5):
            rhs = rng.normal(size=P.shape[0])
            x32 = np.asarray(op.Minv, np.float64) @ rhs
            x64 = scipy.linalg.cho_solve(cho, rhs)
            rel = np.abs(x32 - x64).max() / max(np.abs(x64).max(), 1e-12)
            assert rel < 1e-3, f"n={n}: f32 inverse rel err {rel:.2e}"
