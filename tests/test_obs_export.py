"""obs.export + tools/run_health.py: schema-versioned jsonl writer,
validation (the ci_check gate), chunk-boundary emission from
recovery.run_chunks with a telemetry-threaded carry, and the operator
summary tables from a real chunked run."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import centralized, lowlevel
from tpu_aerial_transport.harness import rollout as h_rollout
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import telemetry as tmod
from tpu_aerial_transport.resilience import recovery

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUN_HEALTH = os.path.join(REPO, "tools", "run_health.py")


def _chunked_run_bits(n=4):
    params, col, state0 = setup.rqp_setup(n)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=10
    )
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    cs0 = centralized.init_ctrl_state(params, cfg)
    return params, state0, cs0, hl, llc, acc_des_fn


# --------------------------- writer + schema ---------------------------

def test_writer_roundtrip_and_validation(tmp_path):
    path = str(tmp_path / "run.metrics.jsonl")
    w = export_mod.MetricsWriter(path, meta={"seed": 7})
    w.emit("chunk", chunk=0, wall_s=0.5)
    w.emit("done", chunks=1)
    events = export_mod.read_events(path)
    assert [e["event"] for e in events] == ["run_start", "chunk", "done"]
    assert all(e["schema"] == export_mod.SCHEMA_VERSION for e in events)
    assert export_mod.validate_file(path) == []


def test_writer_rejects_unknown_event(tmp_path):
    w = export_mod.MetricsWriter(str(tmp_path / "m.jsonl"))
    with pytest.raises(ValueError, match="unknown metrics event"):
        w.emit("mystery", foo=1)


def test_validate_flags_schema_violations(tmp_path):
    path = str(tmp_path / "bad.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"schema": 99, "event": "chunk", "ts": 0}) + "\n")
        fh.write('{"torn interior\n')
        fh.write(json.dumps({
            "schema": export_mod.SCHEMA_VERSION, "event": "chunk", "ts": 0,
        }) + "\n")
        fh.write('{"torn final tail')  # crash artifact: tolerated.
    errs = export_mod.validate_file(path)
    text = "\n".join(errs)
    assert "schema 99" in text
    assert "unparseable" in text
    assert "missing fields" in text  # chunk without chunk/wall_s.
    assert "torn final" not in text and "line 4" not in text


def test_logs_summary_exact_digest():
    params, state0, cs0, hl, llc, acc_des_fn = _chunked_run_bits()
    _, _, logs = jax.jit(
        lambda s, c: h_rollout.rollout(
            hl, llc.control, params, s, c, 5, acc_des_fn=acc_des_fn
        )
    )(state0, cs0)
    d = export_mod.logs_summary(logs)
    assert d["steps"] == 5
    assert sum(d["rung_hist"]) == 5
    assert d["residual"]["count"] == 5
    assert d["min_env_dist"] == pytest.approx(
        float(np.min(np.asarray(logs.min_env_dist)))
    )
    assert d["quarantined_final"] == 0


def test_rollout_metrics_on_demand(tmp_path):
    params, state0, cs0, hl, llc, acc_des_fn = _chunked_run_bits()
    tcfg = tmod.TelemetryConfig()
    _, _, logs, tel = jax.jit(
        lambda s, c: h_rollout.rollout(
            hl, llc.control, params, s, c, 4, acc_des_fn=acc_des_fn,
            telemetry=tcfg,
        )
    )(state0, cs0)
    path = str(tmp_path / "rollout.metrics.jsonl")
    rec = export_mod.rollout_metrics(path, logs, tel, tcfg, meta={"n": 4})
    assert rec["logs"]["steps"] == 4
    assert rec["telemetry"]["steps"] == 4
    assert export_mod.validate_file(path) == []


# ------------------- chunk-boundary emission + CLI ---------------------

@pytest.fixture(scope="module")
def chunked_metrics_run(tmp_path_factory):
    """One real chunked run with telemetry + metrics export, shared by the
    emission and CLI tests."""
    tmp = tmp_path_factory.mktemp("obsrun")
    params, state0, cs0, hl, llc, acc_des_fn = _chunked_run_bits()
    tcfg = tmod.TelemetryConfig()
    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=6, n_chunks=3,
        acc_des_fn=acc_des_fn, telemetry=tcfg,
    )
    plan = recovery.RunPlan(
        run_dir=str(tmp / "run"), n_hl_steps=6, n_chunks=3, seed=0
    )
    metrics_path = str(tmp / "run.metrics.jsonl")
    result = recovery.run_chunks(
        plan, run.chunk_jit, run.init_carry(state0, cs0),
        metrics=metrics_path,
    )
    return metrics_path, result


def test_batched_carry_metrics_export(tmp_path):
    """A VMAPPED chunk carry threading telemetry (the
    scenario_rollout_resumable shape: every telemetry leaf grows a leading
    lane axis) must export a cross-lane roll-up at each boundary instead
    of crashing summary() on non-scalar leaves."""
    params, state0, cs0, hl, llc, acc_des_fn = _chunked_run_bits()
    tcfg = tmod.TelemetryConfig()
    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=4, n_chunks=2,
        acc_des_fn=acc_des_fn, telemetry=tcfg,
    )
    batched_jit = jax.jit(jax.vmap(run.chunk_fn, in_axes=(0, None)))
    n_lanes = 3
    batch = jax.tree.map(
        lambda x: jnp.tile(x[None], (n_lanes,) + (1,) * x.ndim),
        run.init_carry(state0, cs0),
    )
    plan = recovery.RunPlan(
        run_dir=str(tmp_path / "run"), n_hl_steps=4, n_chunks=2,
        logs_time_axis=1,
    )
    path = str(tmp_path / "batched.metrics.jsonl")
    res = recovery.run_chunks(plan, batched_jit, batch, metrics=path)
    assert res.status == "done"
    assert export_mod.validate_file(path) == []
    chunks = [e for e in export_mod.read_events(path)
              if e["event"] == "chunk"]
    tel = chunks[-1]["telemetry"]
    assert tel["lanes"] == n_lanes
    assert tel["steps"] == 4
    assert sum(tel["rung_hist"]) == 4 * n_lanes
    assert tel["residual"]["count"] == 4 * n_lanes
    assert tel["residual"]["p50"] is not None


def test_run_chunks_emits_boundary_events(chunked_metrics_run):
    metrics_path, result = chunked_metrics_run
    assert result.status == "done"
    assert export_mod.validate_file(metrics_path) == []
    events = export_mod.read_events(metrics_path)
    kinds = [e["event"] for e in events]
    assert kinds == ["run_start", "chunk", "chunk", "chunk", "done"]
    chunks = [e for e in events if e["event"] == "chunk"]
    for i, e in enumerate(chunks):
        assert e["chunk"] == i
        assert e["wall_s"] > 0
        assert e["logs"]["steps"] == 2  # chunk_len.
    # Telemetry is cumulative across boundaries: 2 -> 4 -> 6 steps.
    assert [e["telemetry"]["steps"] for e in chunks] == [2, 4, 6]
    assert chunks[-1]["telemetry"]["residual"]["count"] == 6


def test_run_health_renders_summary(chunked_metrics_run):
    metrics_path, _ = chunked_metrics_run
    proc = subprocess.run(
        [sys.executable, RUN_HEALTH, metrics_path],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "fallback-rung distribution" in out
    assert "consensus residual" in out
    assert "safety margins" in out
    assert "chunk wall-times" in out
    assert "chunks: 3" in out


def test_run_health_renders_nondefault_quantiles(tmp_path):
    """The residual table's percentile columns come from the event keys,
    so a run recorded with non-default quantiles shows its actual
    percentiles instead of empty p50/p90/p99 columns."""
    params, state0, cs0, hl, llc, acc_des_fn = _chunked_run_bits()
    tcfg = tmod.TelemetryConfig(quantiles=(0.25, 0.75))
    _, _, logs, tel = jax.jit(
        lambda s, c: h_rollout.rollout(
            hl, llc.control, params, s, c, 6, acc_des_fn=acc_des_fn,
            telemetry=tcfg,
        )
    )(state0, cs0)
    path = str(tmp_path / "q.metrics.jsonl")
    export_mod.rollout_metrics(path, logs, tel, tcfg)
    proc = subprocess.run(
        [sys.executable, RUN_HEALTH, path],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    header = next(l for l in proc.stdout.splitlines()
                  if l.startswith("| count"))
    assert "p25" in header and "p75" in header and "p50" not in header
    row = proc.stdout.splitlines()[
        proc.stdout.splitlines().index(header) + 2
    ]
    assert "—" not in row.split("|")[2]  # p25 cell holds a number.


def test_run_health_json_mode(chunked_metrics_run):
    metrics_path, _ = chunked_metrics_run
    proc = subprocess.run(
        [sys.executable, RUN_HEALTH, metrics_path, "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["telemetry"]["steps"] == 6
    assert payload["chunks"]["count"] == 3


def test_run_health_validate_gate(chunked_metrics_run, tmp_path):
    metrics_path, _ = chunked_metrics_run
    ok = subprocess.run(
        [sys.executable, RUN_HEALTH, "--validate", metrics_path],
        capture_output=True, text=True, cwd=REPO,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad_path = str(tmp_path / "bad.metrics.jsonl")
    with open(bad_path, "w") as fh:
        fh.write(json.dumps({"schema": 0, "event": "nope", "ts": 0}) + "\n")
        fh.write("x\n")  # make the torn line non-final.
        fh.write(json.dumps({
            "schema": export_mod.SCHEMA_VERSION, "event": "done",
            "chunks": 1, "ts": 0,
        }) + "\n")
    bad = subprocess.run(
        [sys.executable, RUN_HEALTH, "--validate", bad_path],
        capture_output=True, text=True, cwd=REPO,
    )
    assert bad.returncode == 1
    assert "schema violation" in bad.stderr


# ------------------- schema v2: backend_event vocabulary ---------------

def test_backend_event_validates_at_schema_v2(tmp_path):
    path = str(tmp_path / "be.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("backend_event", kind="wedge_timeout", label="cadmm_n64_single",
           rung="cpu-tagged", detail="deadline exceeded")
    assert export_mod.validate_file(path) == []
    ev = export_mod.read_events(path)[-1]
    assert ev["schema"] == export_mod.SCHEMA_VERSION >= 2


def test_backend_event_requires_kind_and_label(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("backend_event", kind="oom")  # no label.
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "missing fields ['label']" in errs[0]


def test_v1_files_remain_valid_but_not_for_backend_events(tmp_path):
    """The bump is ADDITIVE: a v1 file written before this PR still
    validates; a backend_event STAMPED v1 does not (the v1 reader
    contract never defined it)."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 1, "event": "chunk", "ts": 0.0,
            "chunk": 0, "wall_s": 0.1,
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 1, "event": "backend_event", "ts": 0.0,
            "kind": "oom", "label": "x",
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 2" in errs[0]


# ------------------- schema v3: aot_serve vocabulary -------------------

def test_aot_serve_validates_at_schema_v3(tmp_path):
    path = str(tmp_path / "aot.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("aot_serve", entry="control.cadmm:control", rung="bundle_exec",
           label="coldstart_bundled", wall_s=1.5)
    assert export_mod.validate_file(path) == []
    ev = export_mod.read_events(path)[-1]
    assert ev["schema"] == export_mod.SCHEMA_VERSION >= 3


def test_aot_serve_requires_entry_and_rung(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("aot_serve", entry="control.cadmm:control")  # no rung.
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "missing fields ['rung']" in errs[0]


def test_v2_files_remain_valid_but_not_for_aot_serve(tmp_path):
    """Same additive contract as the v2 bump: a v2 file still validates;
    an aot_serve event STAMPED v2 does not (the v2 reader contract never
    defined it)."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 2, "event": "backend_event", "ts": 0.0,
            "kind": "oom", "label": "x",
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 2, "event": "aot_serve", "ts": 0.0,
            "entry": "control.cadmm:control", "rung": "bundle_exec",
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 3" in errs[0]


# ------------------- schema v5: trace_event vocabulary -----------------

def test_trace_event_validates_at_schema_v5(tmp_path):
    from tpu_aerial_transport.obs import trace as trace_mod

    path = str(tmp_path / "tr.metrics.jsonl")
    tr = trace_mod.Tracer(export_mod.MetricsWriter(path), track="p0of1")
    with tr.span(trace_mod.CHUNK, chunk=0):
        pass
    tr.instant("preempted", parent=None, chunk=1)
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    assert [e["event"] for e in events] == ["trace_event", "trace_event"]
    assert all(e["schema"] == export_mod.SCHEMA_VERSION >= 5
               for e in events)
    # Both clock domains present — the stitcher's alignment anchor.
    assert all("t0_mono" in e and "t0_wall" in e for e in events)


def test_trace_event_requires_ids_and_clocks(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("trace_event", name="chunk", trace_id="t", span_id="s",
           track="p0of1", t0_mono=0.0)  # no t0_wall.
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "missing fields ['t0_wall']" in errs[0]


def test_v4_files_remain_valid_but_not_for_trace_event(tmp_path):
    """Additive bump contract, v5 edition: a v4 file still validates; a
    trace_event STAMPED v4 does not."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 4, "event": "serving_event", "ts": 0.0,
            "kind": "submitted", "request_id": "r0",
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 4, "event": "trace_event", "ts": 0.0,
            "name": "chunk", "trace_id": "t", "span_id": "s",
            "track": "p0of1", "t0_mono": 0.0, "t0_wall": 0.0,
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 5" in errs[0]


# ------------- concurrent writers: the pods durability pin -------------

def test_concurrent_writers_interleave_without_torn_lines(tmp_path):
    """Two PROCESSES appending to one jsonl through
    obs.export.jsonl_append (the pods tier's implicit reliance: N
    workers share one run dir, the guard/journal/metrics writers all
    ride this primitive): every line lands whole — no torn or
    interleaved lines — and validate_file stays green. O_APPEND +
    single-write-per-line is the mechanism; this pins it."""
    path = str(tmp_path / "shared.metrics.jsonl")
    n_events = 200
    # Payload long enough that a non-atomic append WOULD interleave.
    code = (
        "import sys\n"
        "sys.path.insert(0, {repo!r})\n"
        "import json, os\n"
        "def append(path, obj):\n"
        "    with open(path, 'a', encoding='utf-8') as fh:\n"
        "        fh.write(json.dumps(obj) + '\\n')\n"
        "        fh.flush()\n"
        "        os.fsync(fh.fileno())\n"
        "wid = int(sys.argv[1])\n"
        "for i in range({n}):\n"
        "    append({path!r}, {{'schema': {schema}, 'event': 'chunk',\n"
        "            'ts': 0.0, 'chunk': i, 'wall_s': 0.1,\n"
        "            'writer': wid, 'pad': 'x' * 512}})\n"
    ).format(repo=REPO, n=n_events, path=path,
             schema=export_mod.SCHEMA_VERSION)
    procs = [
        subprocess.Popen([sys.executable, "-c", code, str(w)],
                         cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
        for w in range(2)
    ]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()
    with open(path, encoding="utf-8") as fh:
        lines = fh.readlines()
    assert len(lines) == 2 * n_events
    seen = {0: [], 1: []}
    for line in lines:
        obj = json.loads(line)  # raises on any torn/interleaved line.
        seen[obj["writer"]].append(obj["chunk"])
    # Per-writer order preserved (appends are sequential per process).
    assert seen[0] == list(range(n_events))
    assert seen[1] == list(range(n_events))
    assert export_mod.validate_file(path) == []


def test_jsonl_append_itself_matches_the_subprocess_recipe(tmp_path):
    """The subprocess above re-implements the 5-line append so it can't
    silently diverge from the real one: pin jsonl_append's observable
    behavior (whole line + newline, appended, fsync'd) here."""
    path = str(tmp_path / "a.jsonl")
    export_mod.jsonl_append(path, {"a": 1})
    export_mod.jsonl_append(path, {"b": 2})
    with open(path) as fh:
        assert [json.loads(l) for l in fh] == [{"a": 1}, {"b": 2}]


# -------------- run_health serving-SLO dedup (append mode) -------------

def _serving_events(writer, latency, occupancy, reason="queue_full"):
    """One synthetic request lifecycle + boundary + rejection, the
    fields run_health's serving section reads."""
    writer.emit("serving_event", kind="submitted", request_id="rq0",
                family="f")
    writer.emit("serving_event", kind="completed", request_id="rq0",
                family="f", batch_id=0,
                slo={"latency_s": latency,
                     "admit_to_complete_s": latency / 2})
    writer.emit("serving_event", kind="rejected", request_id="rq1",
                family="f", reason=reason)
    writer.emit("serving_event", kind="deadline_missed",
                request_id="rq2", family="f", missed="in_queue")
    writer.emit("serving_event", kind="batch_launch", family="f",
                batch_id=0, bucket=8, lanes=1)
    writer.emit("serving_event", kind="batch_boundary", family="f",
                batch_id=0, chunk=1, occupancy=occupancy, rung="jit")


def test_run_health_serving_section_dedups_appended_rerun(tmp_path):
    """Regression (ISSUE 15 satellite): a metrics file APPENDED by a
    re-measured run (bench --resume / a re-run example) must not skew
    the serving percentile/occupancy rows — aggregate per request_id /
    (batch_id, chunk), LAST event wins (the PR-10 topology-table rule).
    """
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "serve.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    _serving_events(w, latency=1.0, occupancy=0.5)
    # The re-measured run appends the SAME identities, new numbers.
    _serving_events(w, latency=3.0, occupancy=0.9,
                    reason="no_bucket_coverage")
    sv = run_health.summarize(export_mod.read_events(path))["serving"]
    # One completed request, not two: percentiles from the last run.
    assert sv["latency_s"]["count"] == 1
    assert sv["latency_s"]["p50"] == 3.0
    assert sv["admit_to_complete_s"]["count"] == 1
    # One boundary per (batch, chunk): occupancy from the last event.
    assert sv["mean_occupancy"] == 0.9
    # Rejection reason deduped per request: last reason only.
    assert sv["rejections"] == {"no_bucket_coverage": 1}
    assert sv["deadline_misses"] == {"in_queue": 1}
    # Raw event counts stay honest counts (the dedup is aggregation-
    # side).
    assert sv["kinds"]["completed"] == 2


# --------------- schema v6: fleet_event (serving fleet) ----------------

def test_fleet_event_validates_at_schema_v6(tmp_path):
    """The fleet vocabulary (ISSUE 16): heartbeat / transition /
    failover / tenant_rejected rows write and validate at v6."""
    path = str(tmp_path / "fleet.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("fleet_event", kind="heartbeat", replica=0, seq=1, pid=123)
    w.emit("fleet_event", kind="transition", replica=0,
           from_state="up", to_state="suspect",
           reason="2 missed heartbeat leases", seq=1)
    w.emit("fleet_event", kind="failover", request_id="req00001",
           from_replica="1", to_replica="0", trace_id="t1",
           latency_s=0.004)
    w.emit("fleet_event", kind="tenant_rejected", tenant="burst",
           request_id="req00002", reason="tenant_rate_limited")
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    assert [e["event"] for e in events] == ["fleet_event"] * 4
    assert all(e["schema"] == export_mod.SCHEMA_VERSION >= 6
               for e in events)


def test_fleet_event_requires_kind(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("fleet_event", replica=0)  # no kind.
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "missing fields ['kind']" in errs[0]


def test_v5_files_remain_valid_but_not_for_fleet_event(tmp_path):
    """Additive bump contract, v6 edition: a v5 file still validates; a
    fleet_event STAMPED v5 does not (the v5 reader contract never
    defined it)."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 5, "event": "trace_event", "ts": 0.0,
            "name": "chunk", "trace_id": "t", "span_id": "s",
            "track": "p0of1", "t0_mono": 0.0, "t0_wall": 0.0,
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 5, "event": "fleet_event", "ts": 0.0,
            "kind": "heartbeat", "replica": 0,
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 6" in errs[0]


def test_run_health_fleet_section_dedups_appended_rerun(tmp_path):
    """The fleet section follows the append-mode dedup rule: transitions
    per (replica, seq), failovers and tenant admissions per request_id,
    LAST event wins."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "fleet.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    for _ in range(2):  # the re-run appends the SAME identities.
        w.emit("fleet_event", kind="transition", replica=1,
               from_state="up", to_state="down", reason="exited", seq=3)
        w.emit("fleet_event", kind="failover", request_id="req1",
               from_replica="1", to_replica="0", trace_id="t1",
               latency_s=0.5)
        w.emit("serving_event", kind="submitted", request_id="req1",
               family="cadmm4", tenant="pro")
        w.emit("serving_event", kind="completed", request_id="req1",
               family="cadmm4", tenant="pro",
               slo={"latency_s": 2.0})
        w.emit("fleet_event", kind="tenant_rejected", tenant="free",
               request_id="req2", reason="tenant_rate_limited")
    fl = run_health.summarize(export_mod.read_events(path))["fleet"]
    assert len(fl["transitions"]) == 1
    assert fl["transitions"][0]["to_state"] == "down"
    assert fl["failovers"] == 1
    assert fl["failover_latency_s"]["count"] == 1
    pro = fl["tenants"]["pro"]
    assert pro["submitted"] == 1 and pro["completed"] == 1
    assert pro["latency_s"]["count"] == 1
    assert fl["tenants"]["free"]["throttled"] == 2
    # Raw counts stay honest (dedup is aggregation-side).
    assert fl["kinds"]["failover"] == 2


# ------------- schema v8: session_event (closed-loop sessions) ---------

def test_session_event_validates_at_schema_v8(tmp_path):
    """The session vocabulary (closed-loop serving): lease lifecycle +
    step admission + per-step SLO rows write and validate at v8."""
    path = str(tmp_path / "sess.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("session_event", kind="opened", session_id="c0",
           lease="c0:l0", family="cadmm4", epoch=0, reconnect=False)
    w.emit("session_event", kind="renewed", session_id="c0", gap_s=0.2)
    w.emit("session_event", kind="step_submitted", session_id="c0",
           step_seq=1, request_id="c0.s000001")
    w.emit("session_event", kind="step_done", session_id="c0",
           step_seq=1, rung="served", request_id="c0.s000001",
           slo={"latency_s": 0.01})
    w.emit("session_event", kind="step_degraded", session_id="c0",
           step_seq=2, rung="hold_last", missed="in_flight",
           request_id="c0.s000002")
    w.emit("session_event", kind="stale_step", session_id="c0",
           step_seq=2, expected=3)
    w.emit("session_event", kind="evicted", session_id="c0",
           lease="c0:l0", gap_s=31.0, step_seq=2)
    w.emit("session_event", kind="fenced", session_id="c0", op="step",
           lease="c0:l0")
    w.emit("session_event", kind="rehomed", session_id="c0",
           to_replica="1", from_replica="0")
    w.emit("fleet_event", kind="autoscale", hint="scale_up",
           queue_depth=20, sessions=4)
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    assert all(e["schema"] == export_mod.SCHEMA_VERSION >= 8
               for e in events)


def test_session_event_requires_kind_and_kind_keys(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("session_event", session_id="c0")  # no kind.
    w.emit("session_event", kind="opened", session_id="c0")  # no lease.
    errs = export_mod.validate_file(path)
    assert len(errs) == 2
    assert "missing fields ['kind']" in errs[0]
    assert "missing keys" in errs[1] and "lease" in errs[1]


def test_v7_files_remain_valid_but_not_for_session_event(tmp_path):
    """Additive bump contract, v8 edition: a v7 file still validates; a
    session_event STAMPED v7 does not (the v7 reader contract never
    defined it)."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 7, "event": "fleet_event", "ts": 0.0,
            "kind": "heartbeat", "replica": 0,
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 7, "event": "session_event", "ts": 0.0,
            "kind": "fenced", "session_id": "c0",
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 8" in errs[0]


def test_run_health_sessions_section_dedups_appended_rerun(tmp_path):
    """The sessions section follows the append-mode dedup rule:
    lifecycle per session_id, step terminals per (session_id, step_seq),
    LAST event wins; raw kind counts stay honest."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "sess.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    for latency in (1.0, 3.0):  # the re-run appends SAME identities.
        w.emit("session_event", kind="opened", session_id="c0",
               lease="c0:l0")
        w.emit("session_event", kind="renewed", session_id="c0",
               gap_s=0.3)
        w.emit("session_event", kind="step_done", session_id="c0",
               step_seq=1, rung="served", request_id="c0.s000001",
               slo={"latency_s": latency})
        w.emit("session_event", kind="step_degraded", session_id="c0",
               step_seq=2, rung="hold_last", missed="in_queue",
               request_id="c0.s000002")
    w.emit("session_event", kind="evicted", session_id="c0",
           lease="c0:l0", gap_s=31.0)
    sx = run_health.summarize(export_mod.read_events(path))["sessions"]
    # One session, final state evicted — not two opens.
    assert (sx["live"], sx["evicted"], sx["closed"]) == (0, 1, 0)
    # One terminal per step: percentiles from the LAST run's numbers.
    assert sx["steps"] == 2
    assert sx["step_latency_s"]["count"] == 1
    assert sx["step_latency_s"]["p50"] == 3.0
    assert sx["degraded_steps"] == 1 and sx["served_steps"] == 1
    assert sx["degraded_rate"] == 0.5
    # Heartbeat-gap histogram spans renewals and the eviction gap.
    assert sx["heartbeat_gap_hist"]["0.1-0.5"] == 2
    assert sx["heartbeat_gap_hist"][">=30.0"] == 1
    # Raw counts stay honest (dedup is aggregation-side).
    assert sx["kinds"]["opened"] == 2 and sx["kinds"]["step_done"] == 2


# ------------------ schema v9: alert (live SLO engine) -----------------

def test_alert_event_validates_at_schema_v9(tmp_path):
    """The alert vocabulary (obs/live.py burn-rate engine): fire carries
    the burn diagnosis, resolve points back at its fire."""
    path = str(tmp_path / "alerts.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("alert", kind="fire", slo="miss_rate", tenant="pro",
           severity="fast", burn_rate=28.7, window_s=300,
           objective=0.99, metric="deadline_miss")
    w.emit("alert", kind="resolve", slo="miss_rate", tenant="pro",
           fired_ts=123.0)
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    assert all(e["schema"] == export_mod.SCHEMA_VERSION >= 9
               for e in events)


def test_alert_event_requires_kind_and_kind_keys(tmp_path):
    path = str(tmp_path / "m.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("alert", slo="miss_rate")  # no kind.
    w.emit("alert", kind="fire", slo="miss_rate")  # no severity/burn.
    w.emit("alert", kind="resolve", slo="miss_rate")  # no fired_ts.
    errs = export_mod.validate_file(path)
    assert len(errs) == 3
    assert "missing fields ['kind']" in errs[0]
    assert "missing keys" in errs[1] and "severity" in errs[1]
    assert "missing keys" in errs[2] and "fired_ts" in errs[2]


def test_v8_files_remain_valid_but_not_for_alert(tmp_path):
    """Additive bump contract, v9 edition: a v8 file still validates; an
    alert STAMPED v8 does not (the v8 reader contract never defined
    it)."""
    path = str(tmp_path / "old.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({
            "schema": 8, "event": "session_event", "ts": 0.0,
            "kind": "renewed", "session_id": "c0", "gap_s": 0.1,
        }) + "\n")
    assert export_mod.validate_file(path) == []
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "schema": 8, "event": "alert", "ts": 0.0,
            "kind": "fire", "slo": "miss_rate", "severity": "fast",
            "burn_rate": 20.0, "window_s": 300,
        }) + "\n")
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 9" in errs[0]


def test_run_health_alerts_section_pairs_fire_resolve(tmp_path):
    """The alerts section: fire/resolve pair per (slo, tenant) in journal
    order; a fire with no later resolve is UNRESOLVED."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "alerts.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("alert", kind="fire", slo="miss_rate", tenant="pro",
           severity="fast", burn_rate=30.0, window_s=300)
    w.emit("alert", kind="resolve", slo="miss_rate", tenant="pro",
           fired_ts=1.0)
    w.emit("alert", kind="fire", slo="rejection", tenant="free",
           severity="slow", burn_rate=7.0, window_s=300)
    al = run_health.summarize(export_mod.read_events(path))["alerts"]
    assert al["fired"] == 2 and al["resolved"] == 1
    assert al["unresolved"] == ["rejection/free"]
    assert [e["kind"] for e in al["trail"]] == [
        "fire", "resolve", "fire"]
