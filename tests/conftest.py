"""Device-count guard for the mesh/sharding tests.

The root conftest.py requests 8 virtual CPU devices via XLA_FLAGS before JAX
initializes; if the ambient environment already pinned
``--xla_force_host_platform_device_count`` to fewer (the root conftest
respects an existing setting), the mesh tests would die inside
``make_mesh``'s bare assert instead of reporting why. Skip them with an
actionable message instead.
"""

import jax
import pytest

_REQUIRED_DEVICES = 8


def pytest_collection_modifyitems(config, items):
    n = jax.device_count()
    if n >= _REQUIRED_DEVICES:
        return
    skip = pytest.mark.skip(
        reason=(
            f"needs {_REQUIRED_DEVICES} virtual devices, have {n}: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 (the root "
            "conftest.py does this unless XLA_FLAGS already pins a count)"
        )
    )
    for item in items:
        if "test_parallel" in item.nodeid or "device" in item.name:
            item.add_marker(skip)
