"""bench.py backend handling: the XLA-CPU fallback must produce a TAGGED
valid record path instead of a null-valued error row, and hard failures
must carry the machine-readable ``backend_unavailable`` status. Also the
env-gated fused-mode resolution (ops/socp.py TPU_AERIAL_FUSED)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from tpu_aerial_transport.ops import socp  # noqa: E402


def test_ensure_backend_cpu_fallback(monkeypatch):
    monkeypatch.setattr(
        bench, "_probe_backend",
        lambda: (False, "attempt 1: backend probe timed out after 60s"),
    )
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert "unavailable" in note


def test_ensure_backend_silent_cpu_fallback_is_tagged(monkeypatch):
    """Plugin absent -> probe 'succeeds' on cpu without an explicit CPU
    request: with fallback enabled this becomes a tagged cpu record, not a
    refusal."""
    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, "cpu"))
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")  # TPU request.
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert "silently fell back" in note


def test_ensure_backend_hard_failure_is_structured(monkeypatch, capsys):
    monkeypatch.setattr(
        bench, "_probe_backend", lambda: (False, "chip wedged")
    )
    with pytest.raises(SystemExit):
        bench.ensure_backend(metric="bench_sweep", cpu_fallback=False)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["status"] == "backend_unavailable"
    assert rec["value"] is None
    assert rec["metric"] == "bench_sweep"


def test_explicit_cpu_request_is_not_a_fallback(monkeypatch):
    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, "cpu"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert note is None  # an explicit CPU run is not tagged as degraded.


def test_resolve_fused_env_gate(monkeypatch):
    """TPU_AERIAL_FUSED overrides the non-CPU 'auto' default; CPU always
    resolves to scan; junk values raise."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("TPU_AERIAL_FUSED", raising=False)
    assert socp.resolve_fused("auto") == socp._AUTO_FUSED_NONCPU
    monkeypatch.setenv("TPU_AERIAL_FUSED", "pallas")
    assert socp.resolve_fused("auto") == "pallas"
    monkeypatch.setenv("TPU_AERIAL_FUSED", "scan")
    assert socp.resolve_fused("auto") == "scan"
    monkeypatch.setenv("TPU_AERIAL_FUSED", "auto")
    assert socp.resolve_fused("auto") == socp._AUTO_FUSED_NONCPU
    monkeypatch.setenv("TPU_AERIAL_FUSED", "vector")
    with pytest.raises(ValueError):
        socp.resolve_fused("auto")
    # Explicit modes pass through untouched, env ignored.
    assert socp.resolve_fused("pallas") == "pallas"
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    monkeypatch.setenv("TPU_AERIAL_FUSED", "pallas")
    assert socp.resolve_fused("auto") == "scan"
