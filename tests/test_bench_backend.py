"""bench.py backend handling: the XLA-CPU fallback must produce a TAGGED
valid record path instead of a null-valued error row, and hard failures
must carry the machine-readable ``backend_unavailable`` status. Also the
env-gated fused-mode resolution (ops/socp.py TPU_AERIAL_FUSED)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from tpu_aerial_transport.ops import socp  # noqa: E402


def test_ensure_backend_cpu_fallback(monkeypatch):
    monkeypatch.setattr(
        bench, "_probe_backend",
        lambda: (False, "attempt 1: backend probe timed out after 60s"),
    )
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert "unavailable" in note


def test_ensure_backend_silent_cpu_fallback_is_tagged(monkeypatch):
    """Plugin absent -> probe 'succeeds' on cpu without an explicit CPU
    request: with fallback enabled this becomes a tagged cpu record, not a
    refusal."""
    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, "cpu"))
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")  # TPU request.
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert "silently fell back" in note


def test_ensure_backend_hard_failure_is_structured(monkeypatch, capsys):
    monkeypatch.setattr(
        bench, "_probe_backend", lambda: (False, "chip wedged")
    )
    with pytest.raises(SystemExit):
        bench.ensure_backend(metric="bench_sweep", cpu_fallback=False)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["status"] == "backend_unavailable"
    assert rec["value"] is None
    assert rec["metric"] == "bench_sweep"


def test_explicit_cpu_request_is_not_a_fallback(monkeypatch):
    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, "cpu"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    platform, note = bench.ensure_backend(cpu_fallback=True)
    assert platform == "cpu"
    assert note is None  # an explicit CPU run is not tagged as degraded.


def test_sweep_cells_record_topology(monkeypatch):
    """Every sweep cell value additionally records n_processes /
    n_devices / mesh (plain additive fields, no schema bump) — a
    chip-round record can never be ambiguous about what topology
    measured it (the MULTICHIP_r01 ambiguity)."""
    monkeypatch.setitem(bench._PROBE_INFO, "n_devices", 8)
    monkeypatch.setitem(bench._PROBE_INFO, "n_processes", 2)
    v = bench._annotate_topology({"mpc_steps_per_sec": 1.0})
    assert v["n_devices"] == 8 and v["n_processes"] == 2
    assert v["mesh"] is None
    # Sharded A/B cells imply an agent mesh from their devices field.
    v = bench._annotate_topology({"mpc_steps_per_sec": 1.0, "devices": 4})
    assert v["mesh"] == {"agent": 4}
    # Pods cells carry their own mesh — never overwritten.
    v = bench._annotate_topology({
        "mesh": {"scenario": 2, "agent": 4},
        "n_processes": 2, "n_devices": 8,
    })
    assert v["mesh"] == {"scenario": 2, "agent": 4}
    assert v["n_processes"] == 2
    # Non-dict values (nothing today) pass through untouched.
    assert bench._annotate_topology(None) is None
    # Error cells measured nothing: left unstamped.
    assert bench._annotate_topology({"error": "boom"}) == {"error": "boom"}


def test_guard_degraded_cells_get_cpu_topology(monkeypatch):
    """Probe green on the chip, but the guard degraded THIS cell to the
    CPU rung: it must record the CPU fallback's topology, not the probed
    accelerator mesh (stamping the chip's shape on a cpu-tagged cell is
    the ambiguity the field exists to kill)."""
    import jax

    monkeypatch.setitem(bench._PROBE_INFO, "platform", "tpu")
    monkeypatch.setitem(bench._PROBE_INFO, "n_devices", 999)
    monkeypatch.setitem(bench._PROBE_INFO, "n_processes", 1)
    v = bench._annotate_topology({"x": 1.0, "rung": "cpu-tagged"})
    assert v["n_devices"] == len(jax.devices("cpu"))  # not 999.
    # A healthy on-chip cell keeps the probed topology.
    v = bench._annotate_topology({"x": 1.0, "rung": "on-chip"})
    assert v["n_devices"] == 999


def test_run_health_topology_section():
    """tools/run_health.py renders the topology trail: per-cell shapes,
    pods-cell rung table, topology_mismatch events."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_health",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "run_health.py"),
    )
    rh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rh)
    events = [
        {"event": "bench_cell", "cell": "pods_swarm_128x8_2proc",
         "value": {"scenario_mpc_steps_per_sec": 10.0, "rung": "cpu-tagged",
                   "n_processes": 2, "n_devices": 8,
                   "mesh": {"scenario": 2, "agent": 4}}},
        {"event": "bench_cell", "cell": "cadmm_n64_single",
         "value": {"mpc_steps_per_sec": 90.0, "n_processes": 1,
                   "n_devices": 8, "mesh": None}},
        {"event": "backend_event", "kind": "topology_mismatch",
         "label": "probe", "rung": "unresolved",
         "detail": "visible 1 of 8 devices"},
    ]
    summary = rh.summarize(events)
    topo = summary["topology"]
    assert topo["shapes"] == {"2proc x 8dev": 1, "1proc x 8dev": 1}
    assert topo["pods_cells"][0]["cell"] == "pods_swarm_128x8_2proc"
    assert topo["pods_cells"][0]["rung"] == "cpu-tagged"
    assert topo["mismatch_events"][0]["detail"] == "visible 1 of 8 devices"
    rh.render(summary)  # the table renders without crashing.


def test_resolve_fused_env_gate(monkeypatch):
    """TPU_AERIAL_FUSED overrides the non-CPU 'auto' default; CPU always
    resolves to scan; junk values raise."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("TPU_AERIAL_FUSED", raising=False)
    assert socp.resolve_fused("auto") == socp._AUTO_FUSED_NONCPU
    monkeypatch.setenv("TPU_AERIAL_FUSED", "pallas")
    assert socp.resolve_fused("auto") == "pallas"
    monkeypatch.setenv("TPU_AERIAL_FUSED", "scan")
    assert socp.resolve_fused("auto") == "scan"
    monkeypatch.setenv("TPU_AERIAL_FUSED", "auto")
    assert socp.resolve_fused("auto") == socp._AUTO_FUSED_NONCPU
    monkeypatch.setenv("TPU_AERIAL_FUSED", "vector")
    with pytest.raises(ValueError):
        socp.resolve_fused("auto")
    # Explicit modes pass through untouched, env ignored.
    assert socp.resolve_fused("pallas") == "pallas"
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    monkeypatch.setenv("TPU_AERIAL_FUSED", "pallas")
    assert socp.resolve_fused("auto") == "scan"
