"""In-jit telemetry (obs.telemetry): the zero-cost-when-disabled HLO
identity (the acceptance bar, mirroring resilience's no_faults contract),
P² percentile accuracy against np.percentile, accumulator correctness
against the exact per-step logs, chunked-vs-unchunked identity, and the
per-agent solve-health path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from tpu_aerial_transport.control import cadmm, centralized, lowlevel
from tpu_aerial_transport.harness import rollout as h_rollout
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.obs import telemetry as tmod
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience import rollout as r_rollout


def _centralized_bits(n=4):
    params, col, state0 = setup.rqp_setup(n)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=10
    )
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    return params, state0, centralized.init_ctrl_state(params, cfg), hl, llc


def _cadmm_bits(n=4, **cfg_kw):
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=4, inner_iters=10, **cfg_kw,
    )
    llc = lowlevel.make_lowlevel_controller("pd", params)
    hl = r_rollout.make_cadmm_hl_step(params, cfg)
    return params, state0, cadmm.init_cadmm_state(params, cfg), hl, llc


def test_disabled_telemetry_compiles_to_identical_hlo():
    """telemetry=None and telemetry=no_telemetry() lower to the SAME HLO
    (``active`` is static, every telemetry branch is Python-level) — the
    same zero-cost contract as resilience.no_faults()."""
    params, state0, cs0, hl, llc = _centralized_bits()

    def run(tel):
        return jax.jit(
            lambda s, c: h_rollout.rollout(
                hl, llc.control, params, s, c, 3, telemetry=tel
            )
        ).lower(state0, cs0).as_text()

    assert run(None) == run(tmod.no_telemetry())


def test_disabled_telemetry_identical_hlo_resilient():
    params, state0, cs0, hl, llc = _cadmm_bits()
    sched = faults_mod.make_schedule(4, t_fail={1: 1}, drop_rate=0.3)

    def run(tel):
        return jax.jit(
            lambda s, c: r_rollout.resilient_rollout(
                hl, llc.control, params, s, c, 3, faults=sched,
                telemetry=tel,
            )
        ).lower(state0, cs0).as_text()

    assert run(None) == run(tmod.no_telemetry())


def test_p2_percentiles_track_np_percentile():
    """The vectorized P² estimator tracks exact percentiles of a skewed
    stream to a few percent after a few thousand observations."""
    tcfg = tmod.TelemetryConfig()
    tel0 = tmod.init_telemetry(tcfg)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(
        rng.lognormal(mean=-3.0, sigma=1.0, size=4000), jnp.float32
    )

    def step(tel, x):
        q, n = tmod._p2_update(tcfg, tel.p2_q, tel.p2_n, tel.res_count, x)
        return tel.replace(p2_q=q, p2_n=n, res_count=tel.res_count + 1), None

    tel, _ = jax.jit(lambda t, v: lax.scan(step, t, v))(tel0, xs)
    est = tmod.residual_percentiles(tel, tcfg.quantiles)
    for p in tcfg.quantiles:
        key = "p%g" % (p * 100)
        ref = float(np.percentile(np.asarray(xs), p * 100))
        assert abs(est[key] - ref) / ref < 0.08, (key, est[key], ref)


def test_p2_small_sample_is_exact():
    """Below 5 observations the bootstrap markers ARE the sample — the
    host-side percentile falls back to the exact small-sample estimate."""
    tcfg = tmod.TelemetryConfig(quantiles=(0.5,))
    tel = tmod.init_telemetry(tcfg)
    for x in (3.0, 1.0, 2.0):
        q, n = tmod._p2_update(
            tcfg, tel.p2_q, tel.p2_n, tel.res_count, jnp.float32(x)
        )
        tel = tel.replace(p2_q=q, p2_n=n, res_count=tel.res_count + 1)
    assert tmod.residual_percentiles(tel, (0.5,))["p50"] == pytest.approx(2.0)


def test_rollout_telemetry_matches_logs():
    """The on-device accumulator agrees with exact reductions over the
    per-step logs for every metric both can see."""
    params, state0, cs0, hl, llc = _centralized_bits()
    tcfg = tmod.TelemetryConfig()
    state, cs, logs, tel = jax.jit(
        lambda s, c: h_rollout.rollout(
            hl, llc.control, params, s, c, 8, telemetry=tcfg
        )
    )(state0, cs0)
    assert int(tel.steps) == 8
    assert int(tel.res_count) == 8
    np.testing.assert_array_equal(
        np.asarray(tel.rung_hist),
        np.bincount(np.asarray(logs.fallback_rung), minlength=4),
    )
    assert float(tel.min_env_dist) == pytest.approx(
        float(np.min(np.asarray(logs.min_env_dist)))
    )
    assert float(tel.res_max) == pytest.approx(
        float(np.max(np.asarray(logs.solve_res))), rel=1e-6
    )
    assert float(tel.res_sum) == pytest.approx(
        float(np.sum(np.asarray(logs.solve_res), dtype=np.float64)),
        rel=1e-5,
    )
    s = tmod.summary(tel, tcfg)
    assert s["steps"] == 8 and s["residual"]["count"] == 8


def test_resilient_telemetry_counts_rungs_and_quarantine():
    """Under an agent kill + dropout the rung histogram matches the logged
    ladder rungs and the quarantine counter matches the sticky flag."""
    params, state0, cs0, hl, llc = _cadmm_bits()
    sched = faults_mod.make_schedule(4, t_fail={1: 2}, drop_rate=0.4)
    tcfg = tmod.TelemetryConfig()
    state, cs, logs, tel = jax.jit(
        lambda s, c: r_rollout.resilient_rollout(
            hl, llc.control, params, s, c, 6, faults=sched, telemetry=tcfg
        )
    )(state0, cs0)
    np.testing.assert_array_equal(
        np.asarray(tel.rung_hist),
        np.bincount(np.asarray(logs.fallback_rung), minlength=4),
    )
    assert int(tel.quarantine_steps) == int(
        np.sum(np.asarray(logs.quarantined))
    )
    assert int(tel.steps) == 6


def test_chunked_telemetry_matches_unchunked():
    """The accumulator through C chunks (ONE compiled chunk, carry
    threaded) equals the fused-scan accumulator bitwise."""
    params, state0, cs0, hl, llc = _centralized_bits()
    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    tcfg = tmod.TelemetryConfig()
    _, _, _, tel_fused = jax.jit(
        lambda s, c: h_rollout.rollout(
            hl, llc.control, params, s, c, 6, acc_des_fn=acc_des_fn,
            telemetry=tcfg,
        )
    )(state0, cs0)

    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=6, n_chunks=3,
        acc_des_fn=acc_des_fn, telemetry=tcfg,
    )
    seen = {}
    run(state0, cs0,
        on_boundary=lambda c, carry, logs: seen.update(carry=carry))
    tel_chunked = tmod.find_state(seen["carry"])
    assert tel_chunked is not None
    for a, b in zip(jax.tree.leaves(tel_fused), jax.tree.leaves(tel_chunked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_track_agent_stats_surfaces_per_agent_residuals():
    n = 4
    params, state0, cs0, hl, llc = _cadmm_bits(track_agent_stats=True)
    tcfg = tmod.TelemetryConfig(track_agents=True, solver_tol=5e-3)
    state, cs, logs, tel = jax.jit(
        lambda s, c: r_rollout.resilient_rollout(
            hl, llc.control, params, s, c, 4, telemetry=tcfg
        )
    )(state0, cs0)
    assert tel.agent_fail_steps.shape == (n,)
    assert tel.agent_res_max.shape == (n,)
    # Warm-started steady-state solves meet tolerance: no agent should be
    # failing every step, and the per-agent max residual is finite.
    assert np.all(np.asarray(tel.agent_fail_steps) <= 4)
    assert np.all(np.isfinite(np.asarray(tel.agent_res_max)))
    s = tmod.summary(tel, tcfg)
    assert len(s["agent_fail_steps"]) == n


def test_track_agents_mismatch_raises():
    """telemetry.track_agents without the controller's track_agent_stats
    is a configuration error, caught at trace time — not a silent zero."""
    params, state0, cs0, hl, llc = _cadmm_bits()  # no track_agent_stats.
    tcfg = tmod.TelemetryConfig(track_agents=True)
    with pytest.raises(ValueError, match="track_agent_stats"):
        jax.eval_shape(
            lambda s, c: r_rollout.resilient_rollout(
                hl, llc.control, params, s, c, 2, telemetry=tcfg
            ),
            state0, cs0,
        )


def test_nondefault_quantiles_label_from_state():
    """The quantile labels ride the STATE (static field), so a reader
    holding only a snapshot — recovery.run_chunks' boundary export calls
    summary() with no config — labels non-default configs correctly
    instead of crashing on the (Q,5) marker shape."""
    tcfg = tmod.TelemetryConfig(quantiles=(0.25, 0.75))
    tel = tmod.init_telemetry(tcfg)
    from tpu_aerial_transport.control.types import SolverStats

    for i in range(8):
        tel = tmod.update(tcfg, tel, SolverStats(
            iters=jnp.asarray(1, jnp.int32),
            solve_res=jnp.asarray(float(i + 1), jnp.float32),
            collision=jnp.zeros((), bool),
            min_env_dist=jnp.asarray(1.0, jnp.float32),
        ))
    s = tmod.summary(tel)  # no config — the run_chunks reader's view.
    assert set(s["residual"]) >= {"p25", "p75"}
    assert "p50" not in s["residual"]
    assert s["residual"]["p25"] <= s["residual"]["p75"]
    # A host/numpy snapshot copy keeps the labels (treedef, not leaves).
    host = jax.tree.map(lambda x: np.array(x), tel)
    assert tmod.summary(host)["residual"]["p75"] == s["residual"]["p75"]


def test_update_ignores_nonfinite_residuals():
    """A poisoned step's inf/nan residual must not wedge the P² markers or
    the min/max; the rung histogram still counts the step."""
    tcfg = tmod.TelemetryConfig()
    tel = tmod.init_telemetry(tcfg)
    from tpu_aerial_transport.control.types import SolverStats

    def stats(res):
        return SolverStats(
            iters=jnp.asarray(3, jnp.int32),
            solve_res=jnp.asarray(res, jnp.float32),
            collision=jnp.zeros((), bool),
            min_env_dist=jnp.asarray(2.0, jnp.float32),
        )

    tel = tmod.update(tcfg, tel, stats(0.5))
    tel = tmod.update(tcfg, tel, stats(jnp.nan))
    tel = tmod.update(tcfg, tel, stats(jnp.inf))
    tel = tmod.update(tcfg, tel, stats(0.25))
    assert int(tel.steps) == 4
    assert int(tel.res_count) == 2
    assert float(tel.res_max) == pytest.approx(0.5)
    assert float(tel.res_min) == pytest.approx(0.25)
    assert np.all(np.isfinite(np.asarray(tel.p2_q)[:, :2]))
    assert int(tel.iters_sum) == 12
