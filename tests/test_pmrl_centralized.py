"""PMRL centralized controller tests (a capability BEYOND the reference,
which ships PMRL as dynamics+viz only — see control/pmrl_centralized.py).

Oracles: (1) the jacfwd-extracted affine dynamics must reproduce the true
forward dynamics exactly at the solved thrusts (the map IS affine);
(2) closed-loop setpoint tracking stays finite, respects the tilt CBF, and
converges toward the target; (3) equilibrium thrusts hover."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import pmrl_centralized as ctrl
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import pmrl


def _setup(n=3):
    params, col, state = setup.pmrl_setup(n)
    cfg = ctrl.make_config(params, solver_iters=250)
    return params, col, state, cfg


def test_affine_dynamics_is_exact():
    """B f + c must equal forward_dynamics' payload accelerations at random
    thrusts — jacfwd of an affine map is exact, machine precision."""
    n = 4
    params, col, state, cfg = _setup(n)
    state = state.replace(
        q=state.q + 0.1 * jax.random.normal(jax.random.PRNGKey(0), (n, 3)),
        dq=0.2 * jax.random.normal(jax.random.PRNGKey(1), (n, 3)),
        wl=jnp.array([0.1, -0.05, 0.2]),
    )
    state = pmrl.pmrl_state(state.q, state.dq, state.xl, state.vl,
                            state.Rl, state.wl)
    B, c, B_rob, c_rob = ctrl._affine_dynamics(params, state)
    for seed in range(3):
        f = 2.0 * jax.random.normal(jax.random.PRNGKey(10 + seed), (n, 3))
        (ddq, dvl, dwl), _ = pmrl.forward_dynamics(params, state, f)
        pred = B @ f.reshape(-1) + c
        err = float(jnp.abs(pred - jnp.concatenate([dvl, dwl])).max())
        assert err < 1e-3, f"affine payload map mismatch: {err}"
        # Robot-acceleration map: ddx = dvl + L ddq + Rl(hat^2(wl)+hat(dwl)) r.
        from tpu_aerial_transport.ops import lie
        kin = (lie.hat_square(state.wl, state.wl) + lie.hat(dwl)) @ params.r.T
        ddx = dvl[None] + ddq * params.L[:, None] + (state.Rl @ kin).T
        pred_r = (B_rob @ f.reshape(-1) + c_rob).reshape(n, 3)
        err_r = float(jnp.abs(pred_r - ddx).max())
        assert err_r < 1e-3, f"affine robot map mismatch: {err_r}"


def test_equilibrium_forces_hover():
    """At the setup state (vertical links), the equilibrium thrusts must
    produce ~zero payload acceleration and taut links (positive tension)."""
    params, col, state, cfg = _setup(3)
    f_eq = ctrl.equilibrium_forces(params, state)
    (ddq, dvl, dwl), T = pmrl.forward_dynamics(params, state, f_eq)
    assert float(jnp.abs(dvl).max()) < 1e-4
    assert float(jnp.abs(dwl).max()) < 1e-4
    assert bool(jnp.all(T > 0)), "links must be taut at equilibrium"


def test_closed_loop_setpoint():
    """Track a position setpoint with a PD outer loop: the payload must move
    toward the target, stay finite, and keep the tilt CBF satisfied."""
    n = 3
    params, col, state0, cfg = _setup(n)
    cs0 = ctrl.init_ctrl_state(params, cfg, state0)
    target = jnp.array([0.4, -0.2, 0.3])
    dt, n_steps = 1e-2, 800

    def body(carry, _):
        cs, s = carry
        # Damping-heavy PD: the payload hangs below swinging links, so the
        # lateral pendulum mode needs velocity damping to settle.
        dvl_des = -3.0 * s.vl - 1.5 * (s.xl - target)
        # Reference-style norm clamp (rqp_example.py:33-59 clamps at 1.0).
        nrm = jnp.linalg.norm(dvl_des)
        dvl_des = dvl_des * jnp.minimum(1.0, 1.0 / jnp.maximum(nrm, 1e-9))
        f, cs, stats = ctrl.control(
            params, cfg, cs, s, (dvl_des, jnp.zeros(3))
        )
        s = pmrl.integrate(params, s, f, dt)
        return (cs, s), (s.xl, s.Rl[2, 2], stats.ok_frac)

    (cs, s_fin), (xs, tilt, okf) = jax.jit(
        lambda c: jax.lax.scan(body, c, None, length=n_steps)
    )((cs0, state0))

    assert bool(jnp.all(jnp.isfinite(xs)))
    final_err = float(jnp.linalg.norm(s_fin.xl - target))
    initial_err = float(jnp.linalg.norm(target))
    # swing_damp = 3.5 (calibrated, see make_config) settles to ~0.036 m
    # here; 0.15x initial keeps >2x margin while still catching a return of
    # the under-damped limit cycle (which plateaued at ~0.4x).
    assert final_err < 0.15 * initial_err, \
        f"did not approach target: {final_err} vs {initial_err}"
    # Tilt CBF: cos(payload tilt) stays above the 30-deg bound.
    assert float(tilt.min()) > cfg.cos_max_p_ang - 1e-3
    # Solver healthy throughout: no equilibrium/prev-force fallbacks.
    assert float(okf.min()) == 1.0


def test_jits_under_scan_any_n():
    for n in (3, 5):
        params, col, state0, cfg = (_setup(n) + (None,))[:4]
        params, col, state0 = setup.pmrl_setup(n)
        cfg = ctrl.make_config(params)
        cs0 = ctrl.init_ctrl_state(params, cfg, state0)

        def body(carry, _):
            cs, s = carry
            f, cs, _ = ctrl.control(params, cfg, cs, s, (jnp.zeros(3), jnp.zeros(3)))
            return (cs, pmrl.integrate(params, s, f, 1e-2)), f

        (_, s_fin), fs = jax.jit(
            lambda c: jax.lax.scan(body, c, None, length=5)
        )((cs0, state0))
        assert bool(jnp.all(jnp.isfinite(fs))), n
