"""Whole-solve fused ADMM mega-kernel (ops/admm_kernel.fused_solve_lanes,
solve_socp fused="kernel"/"kernel_interpret") vs the scan path.

Oracles, strongest first:

1. **Bitwise** (interpret mode, padded operators): the kernel's
   ``exact_dot`` body is ``jax.vmap`` of the scan path's OWN per-instance
   functions, so per-iteration AND end-to-end solutions — including the
   in-kernel w2 build and residual reduction — equal the scan path's
   bit-for-bit (np.array_equal, not allclose).
2. **f32 rounding**: the compiled broadcast-reduce body (the form Mosaic
   can actually lower — run here under the interpreter with
   ``exact_dot=False``) vs the exact body; and full cadmm/dd control
   steps (nominal + alive-masked, single-program + agent-sharded).
3. **Zero-cost gates**: fused="scan" lowers IDENTICAL HLO regardless of
   the precision knob (the no_faults()/telemetry=None contract);
   fused="kernel" downgrades to the scan program off-TPU at trace time.
4. **VMEM bounds**: MAX_FUSED_DIM stays the derived 112 and
   fused_solve_fits flips exactly at the documented budget.
5. **bf16 gate** (bench.py _fused_ab_cell): the bf16 arm refuses — falls
   back to a f32 measurement — when the consensus-residual parity bar
   fails, and the decision lands in precision/precision_resolved.
"""

import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.ops import admm_kernel, socp
from tpu_aerial_transport.resilience import faults as faults_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------- problem builders --------------------------


def _problems(B=5, nv=8, n_box=6, soc=(4,), seed=0):
    rng = np.random.default_rng(seed)

    def one():
        L = rng.standard_normal((nv, nv))
        P = jnp.asarray(L @ L.T + np.eye(nv), jnp.float32)
        q = jnp.asarray(rng.standard_normal(nv), jnp.float32)
        m = n_box + sum(soc)
        A = jnp.asarray(rng.standard_normal((m, nv)) * 0.5, jnp.float32)
        lb = jnp.asarray(rng.uniform(-2.0, -0.5, n_box), jnp.float32)
        ub = jnp.asarray(rng.uniform(0.5, 2.0, n_box), jnp.float32)
        shift = jnp.zeros((m,), jnp.float32).at[n_box].set(3.0)
        return P, q, A, lb, ub, shift

    return [jnp.stack(x) for x in zip(*[one() for _ in range(B)])]


def _solve_batch(mode, args, iters, with_shift=True, precision="f32"):
    Ps, qs, As, lbs, ubs, shifts = args
    if with_shift:
        return jax.vmap(
            lambda P_, q_, A_, lb_, ub_, s_: socp.solve_socp_padded(
                P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=iters,
                shift=s_, fused=mode, precision=precision,
            )
        )(Ps, qs, As, lbs, ubs, shifts)
    return jax.vmap(
        lambda P_, q_, A_, lb_, ub_: socp.solve_socp(
            P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=iters,
            fused=mode, precision=precision,
        )
    )(Ps, qs, As, lbs, ubs)


def _assert_bitwise(out, ref):
    for name in ("x", "y", "z", "prim_res", "dual_res"):
        a, b = np.asarray(getattr(out, name)), np.asarray(getattr(ref, name))
        assert np.array_equal(a, b), (
            f"{name} differs (max abs {np.abs(a - b).max()})"
        )


# --------------------------- bitwise parity ----------------------------


@pytest.mark.parametrize("iters", [1, 2, 30])
def test_kernel_interpret_bitwise_vs_scan(iters):
    """The acceptance bar: interpret-mode mega-kernel ≡ scan path BITWISE
    per iteration (iters=1, 2) and end-to-end (30) on the padded
    operator — solution iterates AND the in-kernel residual reduction."""
    args = _problems()
    ref = _solve_batch("scan", args, iters)
    out = _solve_batch("kernel_interpret", args, iters)
    _assert_bitwise(out, ref)


def test_kernel_interpret_bitwise_double_fold():
    """Nested vmaps (scenarios x instances — the controllers' fold) still
    land bitwise: the custom_vmap recursion folds both axes into one
    kernel batch axis without changing any per-lane op."""
    args = _problems()
    stacked = [jnp.stack([a, a]) for a in args]

    def run(mode):
        one = lambda P_, q_, A_, lb_, ub_, s_: socp.solve_socp_padded(
            P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=10,
            shift=s_, fused=mode,
        )
        return jax.vmap(jax.vmap(one))(*stacked)

    _assert_bitwise(run("kernel_interpret"), run("scan"))


def test_kernel_interpret_bitwise_no_shift():
    """shift=None takes the static shiftless branch in BOTH realizations
    (no z + 0 signed-zero drift from a zeros placeholder)."""
    args = _problems()
    ref = _solve_batch("scan", args, 10, with_shift=False)
    out = _solve_batch("kernel_interpret", args, 10, with_shift=False)
    _assert_bitwise(out, ref)


def test_kernel_interpret_unbatched_matches_scan():
    """A lone (unbatched) solve takes the runner's scan twin — bitwise."""
    Ps, qs, As, lbs, ubs, shifts = _problems(B=1)
    kw = dict(n_box=6, soc_dims=(4,), iters=12)
    ref = socp.solve_socp_padded(
        Ps[0], qs[0], As[0], lbs[0], ubs[0], shift=shifts[0], fused="scan",
        **kw,
    )
    out = socp.solve_socp_padded(
        Ps[0], qs[0], As[0], lbs[0], ubs[0], shift=shifts[0],
        fused="kernel_interpret", **kw,
    )
    _assert_bitwise(out, ref)


def test_compiled_form_matches_exact_form_f32():
    """The Mosaic-lowerable broadcast-reduce body (exact_dot=False — what
    a real chip runs), executed under the interpreter, agrees with the
    bitwise exact_dot body to f32 rounding — the chunk kernel's numerics
    contract, asserted for the mega-kernel's compiled form."""
    Ps, qs, As, lbs, ubs, shifts = _problems()
    B = Ps.shape[0]
    nv_p, n_box_p = socp.padded_dims(8, 6, (4,))
    m_p = n_box_p + 4
    pqps = jax.vmap(
        lambda P_, A_, lb_, ub_, s_: socp.padded_kkt_operator(
            P_, A_, lb_, ub_, s_, n_box=6, soc_dims=(4,)
        )
    )(Ps, As, lbs, ubs, shifts)
    qs_p = jnp.pad(qs, ((0, 0), (0, nv_p - 8)))
    z0 = jax.vmap(
        lambda lb_, ub_, s_: socp._project_cone(
            jnp.zeros((m_p,)), lb_, ub_, n_box_p, (4,), s_
        )
    )(pqps.lb, pqps.ub, pqps.shift)
    rho_v = jax.vmap(
        lambda lb_, ub_: socp.make_rho_vec(m_p, n_box_p, lb_, ub_, 0.4)
    )(pqps.lb, pqps.ub)

    def run(exact_dot):
        return admm_kernel.fused_solve_lanes(
            jnp.zeros((B, nv_p)), jnp.zeros((B, m_p)), z0,
            pqps.op.K2, pqps.op.Minv, pqps.A, pqps.P, qs_p, rho_v,
            pqps.lb, pqps.ub, pqps.shift,
            nv=nv_p, n_box=n_box_p, soc_dims=(4,), iters=30, alpha=1.6,
            interpret=True, exact_dot=exact_dot,
        )

    exact, compiled = run(True), run(False)
    for a, b in zip(exact, compiled):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-4
        )


# ----------------------- controller-level parity -----------------------


_HEALTH = faults_mod.FaultStep(
    alive=jnp.array([False, True, True, True]),
    thrust_scale=jnp.array([0.0, 1.0, 1.0, 1.0], jnp.float32),
    msg_ok=jnp.array([False, True, False, True]),
)


def _cadmm_step_batch(mode, health):
    n = 4
    params, col, state = setup.rqp_setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=10, res_tol=1e-3, socp_fused=mode,
        pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(
        params, alive=None if health is None else health.alive
    )
    astate = cadmm.init_cadmm_state(params, cfg)
    if health is not None:
        astate = astate.replace(held=astate.f)
    vls = jnp.stack([
        jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
        jnp.array([0.0, 0.0, -0.2]),
    ])
    states = jax.vmap(lambda v: state.replace(vl=v))(vls)
    astates = jax.vmap(lambda _: astate)(vls)

    def one(ast, st):
        return cadmm.control(
            params, cfg, f_eq, ast, st, acc_des, health=health
        )

    f, _, stats = jax.jit(jax.vmap(one))(astates, states)
    return np.asarray(f), np.asarray(stats.iters)


@pytest.mark.parametrize("masked", [False, True],
                         ids=["nominal", "alive-masked"])
def test_cadmm_control_step_kernel_matches_scan(masked):
    """Full C-ADMM control step (vmapped scenario batch, padded tier),
    kernel vs scan, nominal AND alive-masked/fault-injected: the
    acceptance bar is f32 rounding; on this image it is in fact bitwise
    (every per-lane op identical), asserted at 1e-5 to stay robust to
    XLA re-fusion across versions."""
    health = _HEALTH if masked else None
    f_ref, it_ref = _cadmm_step_batch("scan", health)
    f_out, it_out = _cadmm_step_batch("kernel_interpret", health)
    np.testing.assert_allclose(f_out, f_ref, rtol=0, atol=1e-5)
    assert np.array_equal(it_out, it_ref)


def _dd_step_batch(mode, health):
    n = 4
    params, col, state = setup.rqp_setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=10, socp_fused=mode, pad_operators=True,
    )
    f_eq = centralized.equilibrium_forces(
        params, alive=None if health is None else health.alive
    )
    dstate = dd.init_dd_state(params, cfg)
    if health is not None:
        dstate = dstate.replace(
            held_f=dstate.f, held_lam_F=dstate.lam_F,
            held_lam_M=dstate.lam_M,
        )
    vls = jnp.stack([
        jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
    ])
    states = jax.vmap(lambda v: state.replace(vl=v))(vls)
    dstates = jax.vmap(lambda _: dstate)(vls)

    def one(dst, st):
        return dd.control(params, cfg, f_eq, dst, st, acc_des, health=health)

    f, _, stats = jax.jit(jax.vmap(one))(dstates, states)
    return np.asarray(f), np.asarray(stats.iters)


@pytest.mark.parametrize("masked", [False, True],
                         ids=["nominal", "alive-masked"])
def test_dd_control_step_kernel_matches_scan(masked):
    """Full DD control step parity, nominal + alive-masked (see the cadmm
    twin for the tolerance rationale)."""
    health = _HEALTH if masked else None
    f_ref, it_ref = _dd_step_batch("scan", health)
    f_out, it_out = _dd_step_batch("kernel_interpret", health)
    np.testing.assert_allclose(f_out, f_ref, rtol=0, atol=1e-5)
    assert np.array_equal(it_out, it_ref)


def test_sharded_cadmm_kernel_matches_single_program():
    """Agent-sharded consensus (shard_map, ring exchange seam outside the
    kernel) with the mega-kernel == the single-program scan path — the
    composition a real mesh runs, where the per-iteration consensus hop
    rides parallel.ring.consensus_exchange around the fused solve."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    n = 4
    params, col, state = setup.rqp_setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)

    cfg_ref = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=10, res_tol=1e-3, socp_fused="scan",
        pad_operators=True,
    )
    astate = cadmm.init_cadmm_state(params, cfg_ref)
    f_ref, _, _ = jax.jit(
        lambda a, s: cadmm.control(params, cfg_ref, f_eq, a, s, acc_des)
    )(astate, state)

    cfg = cfg_ref.replace(socp_fused="kernel_interpret")
    m = mesh_mod.make_mesh({"agent": 4})
    step = jax.jit(mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m))
    f_sh, _, _ = step(astate, state, acc_des)
    assert np.abs(np.asarray(f_sh) - np.asarray(f_ref)).max() < 5e-3


# ------------------------- gates and fallbacks -------------------------


def test_kernel_downgrades_to_scan_offchip():
    """fused="kernel" on a non-TPU host is a TRACE-TIME downgrade (the
    pallas_ring precedent): the compiled program IS the scan program —
    same HLO, bitwise results — so a backend-guard CPU re-run of a
    kernel-configured cell measures a working solve."""
    args = _problems()
    ref = _solve_batch("scan", args, 10)
    out = _solve_batch("kernel", args, 10)
    _assert_bitwise(out, ref)

    Ps, qs, As, lbs, ubs, shifts = args

    def lowered(mode):
        return jax.jit(
            lambda P_, q_, A_, lb_, ub_, s_: socp.solve_socp_padded(
                P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=10,
                shift=s_, fused=mode,
            )
        ).lower(Ps[0], qs[0], As[0], lbs[0], ubs[0], shifts[0]).as_text()

    assert lowered("kernel") == lowered("scan")


def test_oversized_solve_falls_back_to_scan():
    """Solves over the whole-solve VMEM bound must not build a kernel:
    fused="kernel_interpret" silently takes the scan path and still
    solves."""
    nv = 4
    while admm_kernel.fused_solve_fits(nv, 4):
        nv += 64
    P = jnp.eye(nv)
    q = -jnp.ones((nv,))
    A = jnp.eye(nv)[:4]
    lb, ub = jnp.zeros(4), jnp.full((4,), 0.5)
    sol = socp.solve_socp(
        P, q, A, lb, ub, n_box=4, soc_dims=(), iters=30,
        fused="kernel_interpret",
    )
    assert float(sol.prim_res) < 1e-3
    np.testing.assert_allclose(np.asarray(sol.x[:4]), 0.5, atol=1e-2)


def test_vmem_bounds_derived():
    """The VMEM-residency guards are DERIVED from the documented budget,
    not hand-maintained constants: MAX_FUSED_DIM reproduces the padded-
    tier recomputation (112) and sits exactly at the double-buffered
    boundary; fused_solve_fits admits both consensus controllers' padded
    dims and flips at its own budget line."""
    assert admm_kernel.MAX_FUSED_DIM == 112
    budget = admm_kernel.VMEM_BUDGET_BYTES
    lanes = admm_kernel.LANE_TILE
    d = admm_kernel.MAX_FUSED_DIM
    assert 2 * admm_kernel.chunk_kernel_bytes_per_lane(d) * lanes <= budget
    nxt = d + admm_kernel.SUBLANE_TILE
    assert 2 * admm_kernel.chunk_kernel_bytes_per_lane(nxt) * lanes > budget

    # The hot padded dims: C-ADMM reduced (nv_p=16, m_p=32) and DD
    # (nv_p=24, m_p=32) both fit the whole-solve kernel.
    assert admm_kernel.fused_solve_fits(16, 32, 24)
    assert admm_kernel.fused_solve_fits(24, 32, 24)
    # The boundary is exactly the budget inequality.
    nv = 8
    while admm_kernel.fused_solve_fits(nv + 8, nv + 8):
        nv += 8
    bytes_next = admm_kernel.fused_solve_bytes_per_lane(
        nv + 8, nv + 8, nv + 8
    )
    assert 2 * bytes_next * admm_kernel.SOLVE_BATCH_TILE > budget


def _normalize_symbols(hlo: str) -> str:
    """Strip jax's private-helper dedup suffixes (@_where vs @_where_2):
    WHICH suffix a helper symbol gets depends on process-global trace
    caches (what was traced earlier in the pytest process), not on the
    program — the helper bodies themselves stay in the text and are still
    compared."""
    return re.sub(r"(@[A-Za-z_][\w.]*?)_\d+\b", r"\1", hlo)


def test_precision_inert_off_kernel_identical_hlo():
    """The zero-cost contract (the no_faults()/telemetry=None pattern):
    with the gate off (fused="scan" — today's default path), the
    precision knob changes NOTHING — identical lowered programs at both
    the solver and the full-control-step level, so shipping the knob
    cannot perturb any existing deployment."""
    Ps, qs, As, lbs, ubs, shifts = _problems(B=1)

    def solve_fn(precision):
        return lambda P_, q_, A_, lb_, ub_, s_: socp.solve_socp_padded(
            P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=10,
            shift=s_, fused="scan", precision=precision,
        )

    solve_args = (Ps[0], qs[0], As[0], lbs[0], ubs[0], shifts[0])

    def fresh_trace(fn, *args):
        # Trace from an EMPTY process-global cache state: which shared
        # sub-jaxprs (clip, _pad, _where) get hoisted/named in the
        # printed program depends on what earlier tests left in jax's
        # trace caches — a text artifact, not an op difference. Clearing
        # puts both variants on identical footing.
        jax.clear_caches()
        jxp = str(jax.make_jaxpr(fn)(*args))
        jax.clear_caches()
        hlo = _normalize_symbols(jax.jit(fn).lower(*args).as_text())
        return jxp, hlo

    assert fresh_trace(solve_fn("f32"), *solve_args) \
        == fresh_trace(solve_fn("bf16"), *solve_args)

    n = 4
    params, col, state = setup.rqp_setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)

    def step_fn(precision):
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=2, inner_iters=4, socp_fused="scan",
            socp_precision=precision, pad_operators=True,
        )
        astate = cadmm.init_cadmm_state(params, cfg)
        return (
            lambda a, s: cadmm.control(params, cfg, f_eq, a, s, acc_des),
            astate,
        )

    fn32, cs32 = step_fn("f32")
    fn16, cs16 = step_fn("bf16")

    def fresh_step_hlo(fn, cs):
        jax.clear_caches()  # see fresh_trace above.
        return _normalize_symbols(jax.jit(fn).lower(cs, state).as_text())

    assert fresh_step_hlo(fn32, cs32) == fresh_step_hlo(fn16, cs16)


def test_bf16_storage_close_to_f32():
    """bf16-storage / f32-accumulation stays within bf16 mantissa
    distance of the f32 solve (the operators carry ~8 mantissa bits; the
    iterates and accumulation are full f32)."""
    args = _problems()
    ref = _solve_batch("scan", args, 30)
    out = _solve_batch("kernel_interpret", args, 30, precision="bf16")
    np.testing.assert_allclose(
        np.asarray(out.x), np.asarray(ref.x), rtol=0, atol=3e-2
    )
    # And it is genuinely different from the f32 kernel (the cast is
    # real, not dropped on the floor).
    f32 = _solve_batch("kernel_interpret", args, 30)
    assert float(jnp.max(jnp.abs(out.x - f32.x))) > 0.0


def test_fused_solve_scope_in_lowered_program():
    """The kernel dispatch is attributed under tat.fused_solve
    (obs/phases.py vocabulary; op_profile --by-phase picks tat.* scopes
    up generically, innermost wins inside tat.local_solve), and the
    scope exists ONLY on the kernel path — scan stays scope-free there
    (pure-metadata zero-cost rule)."""
    from tpu_aerial_transport.obs import phases

    assert phases.FUSED_SOLVE in phases.PHASES
    Ps, qs, As, lbs, ubs, shifts = _problems(B=2)

    def compiled(mode):
        # Scopes live in op_name METADATA — present in the compiled
        # HloModule text (what bench --profile dumps for op_profile's
        # hlo_map), not in the metadata-stripped StableHLO dump.
        return jax.jit(jax.vmap(
            lambda P_, q_, A_, lb_, ub_, s_: socp.solve_socp_padded(
                P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=4,
                shift=s_, fused=mode,
            )
        )).lower(Ps, qs, As, lbs, ubs, shifts).compile().as_text()

    assert "tat.fused_solve" in compiled("kernel_interpret")
    assert "tat.fused_solve" not in compiled("scan")


def test_resolve_fused_env_gains_kernel(monkeypatch):
    """TPU_AERIAL_FUSED gains the "kernel" value (non-CPU 'auto' only —
    CPU still always resolves to scan)."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("TPU_AERIAL_FUSED", "kernel")
    assert socp.resolve_fused("auto") == "kernel"
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert socp.resolve_fused("auto") == "scan"
    # Explicit modes pass through untouched.
    assert socp.resolve_fused("kernel") == "kernel"
    assert socp.resolve_fused("kernel_interpret") == "kernel_interpret"


def test_runtime_fused_mode_shared_resolver(monkeypatch):
    """socp.runtime_fused_mode — the ONE resolver solve_socp's dispatch
    and bench's fused_resolved labels share: junk modes are a clear
    ValueError (not an opaque Mosaic failure), oversize dims label as the
    scan fallback they actually run, and the off-TPU downgrade applies."""
    with pytest.raises(ValueError):
        socp.runtime_fused_mode("kernal", 16, 32)  # typo'd mode.
    # Oversize: the VMEM-fits fallback is reflected in the label.
    big = admm_kernel.MAX_FUSED_DIM * 4
    assert socp.runtime_fused_mode("kernel_interpret", big, big) == "scan"
    assert socp.runtime_fused_mode("pallas", big, big) == "scan"
    # In-budget dims keep the kernel; "kernel" additionally downgrades
    # off-TPU (this host) while the interpret twin runs anywhere.
    assert socp.runtime_fused_mode("kernel_interpret", 16, 32, 24) \
        == "kernel_interpret"
    assert socp.runtime_fused_mode("kernel", 16, 32, 24) == "scan"
    monkeypatch.setattr(socp, "_kernel_runs_offchip", lambda: False)
    assert socp.runtime_fused_mode("kernel", 16, 32, 24) == "kernel"


def test_resolve_precision_gate(monkeypatch):
    """socp.resolve_precision: auto -> f32 (until the chip-round bf16
    parity bars pass), TPU_AERIAL_PRECISION env force, junk raises."""
    monkeypatch.delenv("TPU_AERIAL_PRECISION", raising=False)
    assert socp.resolve_precision("auto") == "f32"
    assert socp.resolve_precision(None) == "f32"
    monkeypatch.setenv("TPU_AERIAL_PRECISION", "bf16")
    assert socp.resolve_precision("auto") == "bf16"
    assert socp.resolve_precision("f32") == "f32"  # explicit wins.
    monkeypatch.setenv("TPU_AERIAL_PRECISION", "fp8")
    with pytest.raises(ValueError):
        socp.resolve_precision("auto")
    with pytest.raises(ValueError):
        socp.resolve_precision("int8")
    # Config-build plumbing: the resolved value lands on the static field
    # of BOTH controller configs (dd shares the base).
    params, col, _ = setup.rqp_setup(4)
    monkeypatch.setenv("TPU_AERIAL_PRECISION", "bf16")
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
    )
    assert cfg.socp_precision == "bf16"
    dcfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        socp_precision="f32",
    )
    assert dcfg.base.socp_precision == "f32"


# ------------------------- bench bf16 A/B gate -------------------------


def _patch_onchip(monkeypatch):
    """Pretend the kernel path is live (no off-TPU downgrade) so the
    gate logic is reachable on this CPU host."""
    monkeypatch.setattr(socp, "_kernel_runs_offchip", lambda: False)


def test_bench_bf16_gate_refuses_on_residual_bar(monkeypatch):
    """bench._fused_ab_cell: a bf16 arm whose final consensus residual
    fails the parity bar (>= 1e-2 N) REFUSES — the cell re-measures at
    f32 and records the refusal on precision_resolved."""
    sys.path.insert(0, REPO)
    import bench

    _patch_onchip(monkeypatch)
    calls = []

    def fake_measure(controller, n, ns, fused, precision, n_steps=10):
        calls.append(precision)
        if precision == "bf16":
            return 1000.0, 1.0, 0.5, 1e-2  # residual fails the bar.
        return 800.0, 1.0, 2e-3, 1e-2

    monkeypatch.setattr(bench, "_fused_measure", fake_measure)
    v = bench._fused_ab_cell("cadmm", 16, 8, "kernel", precision="bf16")
    assert calls == ["bf16", "f32"]
    assert v["precision"] == "bf16"
    assert v["precision_resolved"] == "f32"
    assert v["bf16_refused"] is True
    assert v["scenario_mpc_steps_per_sec"] == 800.0  # the usable rate.
    assert v["bf16_rate_unusable"] == 1000.0
    assert v["fused_resolved"] == "kernel"


def test_bench_bf16_gate_inconclusive_when_f32_also_fails(monkeypatch):
    """A cap-railed operating point (f32's own residual above the bar)
    cannot indict bf16: the cell keeps the bf16 measurement and flags
    the bar inconclusive instead of faking a refusal."""
    sys.path.insert(0, REPO)
    import bench

    _patch_onchip(monkeypatch)

    def fake_measure(controller, n, ns, fused, precision, n_steps=10):
        return (1000.0, 1.0, 0.5, 1e-2) if precision == "bf16" \
            else (800.0, 1.0, 0.4, 1e-2)  # f32 fails the bar too.

    monkeypatch.setattr(bench, "_fused_measure", fake_measure)
    v = bench._fused_ab_cell("cadmm", 16, 8, "kernel", precision="bf16")
    assert v["precision_resolved"] == "bf16"
    assert v["res_bar_inconclusive"] is True
    assert v["f32_final_consensus_res"] == 0.4
    assert "bf16_refused" not in v
    assert v["scenario_mpc_steps_per_sec"] == 1000.0


def test_bench_bf16_gate_passes_under_bar(monkeypatch):
    """The passing arm keeps bf16: one measurement, precision_resolved
    stays bf16."""
    sys.path.insert(0, REPO)
    import bench

    _patch_onchip(monkeypatch)
    monkeypatch.setattr(
        bench, "_fused_measure",
        lambda c, n, ns, f, p, n_steps=10: (1000.0, 1.0, 2e-3, 1e-2),
    )
    v = bench._fused_ab_cell("dd", 16, 8, "kernel", precision="bf16")
    assert v["precision_resolved"] == "bf16"
    assert "bf16_refused" not in v
    assert v["final_consensus_res"] == 2e-3


def test_bench_bf16_inert_on_cpu_rung(monkeypatch):
    """Off-TPU (the real state of this host) the kernel downgrades to
    scan, where the precision knob is inert: the cell must LABEL the
    measurement f32/scan instead of claiming a bf16 rate."""
    sys.path.insert(0, REPO)
    import bench

    monkeypatch.setattr(
        bench, "_fused_measure",
        lambda c, n, ns, f, p, n_steps=10: (700.0, 1.0, 2e-3, 1e-2),
    )
    v = bench._fused_ab_cell("cadmm", 16, 8, "kernel", precision="bf16")
    assert v["fused_resolved"] == "scan"
    assert v["precision_resolved"] == "f32"


# --------------------------- run_health column -------------------------


def test_run_health_solve_impl_column(tmp_path):
    """The bench-health table renders a `solve impl` column from the
    fused A/B cells' plain value fields — downgrades as kernel(scan),
    bf16 refusals as /bf16(f32). Plain v4 fields, no schema bump."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    from tpu_aerial_transport.obs import export as export_mod

    path = str(tmp_path / "rh.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("bench_cell", cell="cadmm_n16_fused_kernel",
           value={"rung": "on-chip", "fused": "kernel",
                  "fused_resolved": "kernel", "precision": "f32",
                  "precision_resolved": "f32"})
    w.emit("bench_cell", cell="cadmm_n16_fused_kernel_bf16",
           value={"rung": "cpu-tagged", "fused": "kernel",
                  "fused_resolved": "scan", "precision": "bf16",
                  "precision_resolved": "f32"})
    s = run_health.summarize(export_mod.read_events(path))
    rows = {r[0]: r for r in s["backend"]["rungs"]}
    assert rows["cadmm_n16_fused_kernel"][2] == "kernel"
    assert rows["cadmm_n16_fused_kernel_bf16"][2] == "kernel(scan)/bf16(f32)"
    # Ring cells keep their exchange-impl column untouched.
    w.emit("bench_cell", cell="cadmm_n4_sharded_pallas_ring",
           value={"rung": "cpu-tagged", "impl": "pallas_ring",
                  "impl_resolved": "ring"})
    s = run_health.summarize(export_mod.read_events(path))
    rows = {r[0]: r for r in s["backend"]["rungs"]}
    assert rows["cadmm_n4_sharded_pallas_ring"][1] == "pallas_ring(ring)"
