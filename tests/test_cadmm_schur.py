"""Schur-reduced C-ADMM per-agent QP tests (n >= 4 path).

The reduction eliminates the other agents' unconstrained force columns from
each agent's per-iteration solve by exact partial minimization (see
cadmm.SchurQP); these tests pin the exactness claim: the reduced QP +
reconstruction must reproduce the full (9+3n)-var QP's solution, and the
reduced consensus loop must agree with the centralized controller."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import cadmm, centralized
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie, socp


def _setup(n):
    params, col, state = setup.rqp_setup(n)
    acfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    return params, col, state, acfg, f_eq


def _random_state(key, n):
    ks = jax.random.split(key, 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.1 * jax.random.normal(ks[0], (n, 3))),
        w=0.1 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.3 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=0.05 * jax.random.normal(jax.random.fold_in(key, 9), (3,)),
    )


def test_reduced_qp_matches_full_qp():
    """Direct exactness check: for random states and consensus linear terms,
    the 12-var reduced QP + closed-form reconstruction of the eliminated
    columns reproduces the full (9+3n)-var QP solution."""
    n = 5
    params, col, _, acfg, f_eq = _setup(n)
    from tpu_aerial_transport.control.types import inactive_env_cbf

    for seed in range(3):
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        state = _random_state(ks[0], n)
        acc_des = (0.4 * jax.random.normal(ks[1], (3,)), jnp.zeros(3))
        lam = 0.1 * jax.random.normal(ks[2], (n, 3))
        f_mean = f_eq + 0.05 * jax.random.normal(ks[3], (n, 3))
        rho = jnp.float32(acfg.rho0)
        cbf = inactive_env_cbf(
            acfg.n_env_cbfs, acfg.vision_radius, acfg.dist_eps,
            acfg.alpha_env_cbf, dtype=jnp.float32,
        )
        agent_id = jnp.int32(1)
        is_leader = jnp.float32(0.0)
        delta = lam - rho * f_mean  # (n, 3)

        # Full QP for agent 1.
        onehot = jax.nn.one_hot(agent_id, n, dtype=jnp.float32)
        P, q0, A, lb, ub, shift = cadmm._build_agent_qp(
            params, acfg, f_eq, state, acc_des, cbf, onehot, is_leader, rho
        )
        q = q0.at[9:].add(delta.reshape(-1))
        sol_full = socp.solve_socp(
            P, q, A, lb, ub, n_box=13 + acfg.n_env_cbfs, soc_dims=(4, 4),
            iters=4000, shift=shift,
        )
        f_full = sol_full.x[9:].reshape(n, 3)
        c_full = sol_full.x[:9]

        # Reduced QP (payload-frame plan) + reconstruction. The plan is
        # built UNPADDED here (pad_operators=False): this test pins the
        # raw Schur algebra at V = 3(n-1); the padded-plan path is covered
        # by tests/test_socp_padded.py's controller parity test.
        plan = cadmm.make_schur_plan(
            params, acfg.replace(pad_operators=False)
        )
        pk = jax.tree.map(lambda x: x[0, int(agent_id)], plan)
        Rl = state.Rl
        Ecc, e0s, xq = cadmm._schur_state_pieces(
            params, acfg, state, plan.scale[0, 0]
        )
        Pr, q0r, Ar, lbr, ubr, shiftr = cadmm._schur_step_qp(
            params, acfg, pk, f_eq, state, acc_des, cbf, agent_id,
            is_leader, rho, Ecc, e0s, xq,
        )
        dperm = delta[pk.perm]
        d_u = dperm[0]
        d_v = jnp.einsum("ij,nj->ni", Rl.T, dperm[1:]).reshape(-1)
        q_red = q0r + jnp.concatenate(
            [-Ecc.T @ (pk.J.T @ d_v), d_u - Rl @ (pk.Mu @ d_v)]
        )
        sol_red = socp.solve_socp(
            Pr, q_red, Ar, lbr, ubr,
            n_box=7 + acfg.n_env_cbfs, soc_dims=(4, 4), iters=4000,
            shift=shiftr,
        )
        c_red, u = sol_red.x[:9], sol_red.x[9:12]
        ut = Rl.T @ u
        d6 = e0s - Ecc @ c_red - pk.Eu @ ut
        vt = -pk.Nsum @ xq - pk.N @ d_v - pk.NCt @ ut + pk.J @ d6
        v = jnp.einsum("ij,nj->ni", Rl, vt.reshape(n - 1, 3))
        f_red = jnp.zeros((n, 3)).at[pk.perm].set(
            jnp.concatenate([u[None], v])
        )

        err_f = float(jnp.abs(f_full - f_red).max())
        err_c = float(jnp.abs(c_full - c_red).max())
        assert err_f < 5e-3, f"seed {seed}: force mismatch {err_f}"
        # Accel vars are only pinned through (scaled) equality rows, so the
        # f32 ADMM fixed point leaves them ~2x looser than the forces.
        assert err_c < 2e-2, f"seed {seed}: accel mismatch {err_c}"


def test_reduced_control_agrees_with_centralized():
    """n = 5 uses the reduced path by default; consensus forces must match the
    centralized QP solution (the reference's own implicit invariant)."""
    n = 5
    params, col, _, acfg, f_eq = _setup(n)
    assert cadmm._use_reduced(acfg, n)
    ccfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=250
    )
    for seed in range(2):
        ks = jax.random.split(jax.random.PRNGKey(seed + 10), 2)
        state = _random_state(ks[0], n)
        acc_des = (0.5 * jax.random.normal(ks[1], (3,)), jnp.zeros(3))
        cs = centralized.init_ctrl_state(params, ccfg)
        f_cent, _, _ = centralized.control(params, ccfg, f_eq, cs, state, acc_des)
        astate = cadmm.init_cadmm_state(params, acfg)
        f_admm, astate, stats = cadmm.control(
            params, acfg, f_eq, astate, state, acc_des
        )
        assert int(stats.iters) < 61, "consensus did not converge"
        err = float(jnp.abs(f_admm - f_cent).max())
        assert err < 5e-2, f"seed {seed}: |f_admm - f_cent| = {err}"


def test_reduced_matches_full_control():
    """Forcing reduced_qp True/False at the same n must give the same
    consensus forces (both formulations solve identical per-agent problems)."""
    n = 5
    params, col, _, acfg, f_eq = _setup(n)
    state = _random_state(jax.random.PRNGKey(42), n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    cfg_red = acfg.replace(reduced_qp=True)
    cfg_full = acfg.replace(reduced_qp=False)
    a_red = cadmm.init_cadmm_state(params, cfg_red)
    a_full = cadmm.init_cadmm_state(params, cfg_full)
    f_red, _, st_red = cadmm.control(params, cfg_red, f_eq, a_red, state, acc_des)
    f_full, _, st_full = cadmm.control(
        params, cfg_full, f_eq, a_full, state, acc_des
    )
    assert int(st_red.iters) < acfg.max_iter
    assert int(st_full.iters) < acfg.max_iter
    err = float(jnp.abs(f_red - f_full).max())
    assert err < 1e-2, f"|f_reduced - f_full| = {err}"


def test_reduced_warm_start_shapes_and_rollout():
    """init_cadmm_state sizes the warm start for the reduced QP; a short jitted
    closed-loop rollout at n = 6 stays finite and converges."""
    n = 6
    params, col, state0, acfg, f_eq = _setup(n)
    astate = cadmm.init_cadmm_state(params, acfg)
    # Warm starts live in the tile-padded solve layout (ops/socp.py
    # padded tier): 12 vars -> 16, m = 25 rows -> 32.
    _, _, nv_p, _, m_p = cadmm._qp_dims(acfg, n)
    assert astate.warm.x.shape == (n, nv_p)
    assert astate.warm.y.shape == (n, m_p)
    acc_des = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))

    def body(carry, _):
        astate, state = carry
        f, astate, stats = cadmm.control(params, acfg, f_eq, astate, state, acc_des)
        fz = jnp.sum(f * state.R[..., :, 2], axis=-1)
        state = rqp.integrate(params, state, (fz, jnp.zeros((n, 3))), 1e-3)
        return (astate, state), (f, stats.iters)

    (a_fin, s_fin), (fs, iters) = jax.jit(
        lambda c: jax.lax.scan(body, c, None, length=4)
    )((astate, state0))
    assert bool(jnp.all(jnp.isfinite(fs)))
    assert bool(jnp.all(jnp.isfinite(s_fin.xl)))
    assert int(iters.max()) < acfg.max_iter
