"""Force-smoothing cost parity (reference rqp_centralized.py:215-225,
rqp_cadmm.py:455-462, rqp_dd.py:451-457, all defaulting k_smooth = 0 with the
note "Controller is more stable without smoothing"). The knob must exist in all
three controllers, perturb forces when enabled, and be a no-op at 0."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _state(n, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.3 * jax.random.normal(ks[0], (n, 3))),
        w=0.3 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.2 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=jnp.zeros(3),
    )


ACC = (jnp.array([0.4, 0.1, 0.0]), jnp.zeros(3))
# The reference writes its (disabled) default as "0 / dt^2"; a mildly stiff
# value exercises the knob without driving the fixed-rho first-order inner
# solver outside its comfort zone (the reference leans on Clarabel's
# interior-point robustness for extreme cost anisotropy).
K_SMOOTH = 10.0


def test_centralized_k_smooth():
    n = 3
    params, col, _ = setup.rqp_setup(n)
    state = _state(n)
    f_eq = centralized.equilibrium_forces(params)
    base = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=250
    )
    f0, _, _ = centralized.control(
        params, base, f_eq, centralized.init_ctrl_state(params, base), state, ACC
    )
    smooth = base.replace(k_smooth=K_SMOOTH)
    f1, _, _ = centralized.control(
        params, smooth, f_eq, centralized.init_ctrl_state(params, smooth), state, ACC
    )
    assert bool(jnp.all(jnp.isfinite(f1)))
    assert float(jnp.abs(f1 - f0).max()) > 1e-4, \
        "enabling k_smooth did not perturb the solution"
    # k_smooth is a dynamic leaf: explicit 0 reproduces the default bitwise.
    zero = base.replace(k_smooth=0.0)
    f2, _, _ = centralized.control(
        params, zero, f_eq, centralized.init_ctrl_state(params, zero), state, ACC
    )
    assert float(jnp.abs(f2 - f0).max()) == 0.0


def test_cadmm_k_smooth_full_and_reduced():
    for n, label in ((3, "full"), (5, "reduced")):
        params, col, _ = setup.rqp_setup(n)
        state = _state(n, seed=n)
        f_eq = centralized.equilibrium_forces(params)
        # inner budget sized for the K_SMOOTH=10 anisotropy UNDER row
        # equilibration: the unequilibrated builders' large equality-row
        # norms acted as an accidental preconditioner for exactly this
        # corner (A^T rho A dominated the smoothing cost's 100:1 P
        # anisotropy); with unit rows the same QP needs ~300 inner
        # iterations instead of ~80 — while every production-path QP got
        # cheaper (see socp.equilibrate_rows).
        base = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=60, inner_iters=300, res_tol=1e-3,
        )
        a0 = cadmm.init_cadmm_state(params, base)
        f0, _, _ = cadmm.control(params, base, f_eq, a0, state, ACC)
        smooth = base.replace(k_smooth=K_SMOOTH)
        f1, _, st = cadmm.control(params, smooth, f_eq, a0, state, ACC)
        # No iteration-count assert: smoothing makes the agents' preferred
        # force orientations conflict, so consensus may legitimately rail
        # against the cap and return the capped iterate (exactly what the
        # reference's `iter > max_iter` break does, rqp_cadmm.py:661-665).
        assert bool(jnp.all(jnp.isfinite(f1))), label
        assert float(st.solve_res) < 1.0, label
        assert float(jnp.abs(f1 - f0).max()) > 1e-4, \
            f"{label}: enabling k_smooth did not perturb the solution"


def test_dd_k_smooth():
    n = 3
    params, col, _ = setup.rqp_setup(n)
    state = _state(n, seed=2)
    f_eq = centralized.equilibrium_forces(params)
    base = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80,
    )
    d0 = dd.init_dd_state(params, base)
    f0, _, _ = dd.control(params, base, f_eq, d0, state, ACC)
    smooth = base.replace(base=base.base.replace(k_smooth=K_SMOOTH))
    f1, _, st = dd.control(params, smooth, f_eq, d0, state, ACC)
    # No iteration-count assert: the QN preconditioner deliberately omits the
    # state-dependent k_smooth curvature (dd.DDPlan docstring), so enabled
    # smoothing takes conservative dual steps and may rail the iteration cap
    # (the reference's `iter > max_iter` break returns the capped iterate the
    # same way, rqp_dd.py:742-748).
    assert bool(jnp.all(jnp.isfinite(f1)))
    assert float(st.solve_res) < 1.0
    assert float(jnp.abs(f1 - f0).max()) > 1e-4, \
        "enabling k_smooth did not perturb the solution"
