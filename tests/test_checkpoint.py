"""Checkpoint/resume tests: log-artifact round-trip, mid-run scan-carry
resume producing the identical trajectory, and the crash-recovery snapshot
tier — atomic versioned writes, per-leaf digests, treedef/config
verification, structured rejection of corrupt/truncated/mismatched
snapshots with fallback to the previous valid one, keep-last-K retention,
and the orbax/npz backend shim."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.harness import checkpoint, setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.utils import compat


def test_run_dict_roundtrip(tmp_path):
    logs = {
        "n": 3,
        "dt": 1e-3,
        "state_seq": {"xl": np.random.default_rng(0).normal(size=(5, 3))},
        "x_err_seq": np.arange(5.0),
    }
    p = str(tmp_path / "run.npz")
    checkpoint.save_run(p, logs)
    back = checkpoint.load_run(p)
    assert back["n"] == 3
    assert np.allclose(back["state_seq"]["xl"], logs["state_seq"]["xl"])
    assert np.allclose(back["x_err_seq"], logs["x_err_seq"])


def test_load_run_preserves_scalar_dtype(tmp_path):
    """Regression: 0-d restore used ``v.item()``, silently widening a
    saved np.float32 scalar to a Python float (and np.int32 to int) — a
    save/load/save cycle changed dtypes. ``v[()]`` keeps them."""
    p = str(tmp_path / "run.npz")
    checkpoint.save_run(p, {
        "f32_scalar": np.float32(1.5),
        "i32_scalar": np.int32(7),
        "nested": {"b": np.bool_(True)},
    })
    back = checkpoint.load_run(p)
    assert np.asarray(back["f32_scalar"]).dtype == np.float32
    assert np.asarray(back["i32_scalar"]).dtype == np.int32
    assert np.asarray(back["nested"]["b"]).dtype == np.bool_
    # Round-trip again: dtypes must be stable under re-save.
    checkpoint.save_run(p, back)
    again = checkpoint.load_run(p)
    assert np.asarray(again["f32_scalar"]).dtype == np.float32


def test_midrun_resume_bitwise(tmp_path):
    """Integrating 100 steps straight == 50 steps, checkpoint, restore, 50 more."""
    n = 3
    params, _, state0 = setup.rqp_setup(n)
    f = jnp.full((n,), float(params.mT) * rqp.GRAVITY / n * 0.9)
    M = jnp.zeros((n, 3))

    def run(state, k):
        def body(s, _):
            return rqp.integrate(params, s, (f, M), 1e-3), None
        return jax.lax.scan(body, state, None, length=k)[0]

    full = run(state0, 100)

    half = run(state0, 50)
    p = str(tmp_path / "ckpt")
    checkpoint.save_state(p, half)
    restored = checkpoint.load_state(p, half)
    resumed = run(restored, 50)

    for leaf_a, leaf_b in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
        assert jnp.array_equal(leaf_a, leaf_b), "resume diverged from straight run"


def test_save_state_npz_fallback_roundtrip(tmp_path, monkeypatch):
    """With orbax absent the shim must fall back to npz (save_state used to
    hard-ImportError), and the round-trip must stay exact."""
    monkeypatch.setattr(compat, "_import_orbax", lambda: None)
    assert compat.pytree_io()[2] == "npz"
    state = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
             "b": jnp.zeros((), jnp.int32)}
    p = str(tmp_path / "st")
    checkpoint.save_state(p, state)
    back = checkpoint.load_state(p, state)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        assert jnp.array_equal(a, b)
        assert a.dtype == b.dtype


# ----------------------------------------------------------------------
# Crash-recovery snapshot tier.
# ----------------------------------------------------------------------

def _state():
    return {
        "a": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "quar": jnp.ones((), bool),
        "step": jnp.int32(41),
    }


def _tamper_leaf(path):
    """Rewrite a snapshot with one leaf's payload modified but the stale
    manifest kept — the per-leaf digest check must catch what the zip
    container cannot."""
    raw = dict(np.load(path, allow_pickle=False))
    raw["leaf_000000"] = raw["leaf_000000"] + 1
    with open(path, "wb") as fh:
        np.savez(fh, **raw)


def test_snapshot_roundtrip_bit_exact(tmp_path):
    d = str(tmp_path)
    state = _state()
    checkpoint.save_snapshot(d, 0, state, config_hash="h", meta={"chunk": 0})
    back, manifest, skipped = checkpoint.load_latest_valid(
        d, jax.eval_shape(lambda: state), config_hash="h"
    )
    assert skipped == []
    assert manifest["step"] == 0 and manifest["meta"]["chunk"] == 0
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        assert jnp.array_equal(a, b)
        assert a.dtype == b.dtype  # bool/int/float all restored exactly.


def test_snapshot_keep_last_k_retention(tmp_path):
    d = str(tmp_path)
    for step in range(6):
        checkpoint.save_snapshot(d, step, _state(), keep_last=3)
    assert [s for s, _ in checkpoint.list_snapshots(d)] == [3, 4, 5]
    # keep_last=0 disables pruning (the per-chunk log snapshots need all).
    for step in range(6, 9):
        checkpoint.save_snapshot(d, step, _state(), prefix="logs",
                                 keep_last=0)
    assert len(checkpoint.list_snapshots(d, "logs")) == 3


def test_corrupt_snapshot_rejected_with_fallback(tmp_path):
    d = str(tmp_path)
    state = _state()
    checkpoint.save_snapshot(d, 0, state, keep_last=0)
    checkpoint.save_snapshot(d, 1, state, keep_last=0)
    newest = checkpoint.snapshot_path(d, 1)
    _tamper_leaf(newest)
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_snapshot(newest, state)
    assert ei.value.kind == "corrupt"
    # load_latest_valid falls back to the previous valid snapshot and
    # reports the structured error of the one it skipped.
    back, manifest, skipped = checkpoint.load_latest_valid(d, state)
    assert manifest["step"] == 0
    assert [e.kind for e in skipped] == ["corrupt"]
    assert jnp.array_equal(back["a"], state["a"])


def test_truncated_snapshot_rejected(tmp_path):
    d = str(tmp_path)
    checkpoint.save_snapshot(d, 0, _state())
    p = checkpoint.snapshot_path(d, 0)
    with open(p, "r+b") as fh:
        fh.truncate(os.path.getsize(p) // 2)
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_snapshot(p, _state())
    assert ei.value.kind == "unreadable"
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_latest_valid(d, _state())
    assert ei.value.kind == "no_valid_snapshot"
    assert ei.value.errors  # carries the per-file reasons.


def test_config_mismatch_refused(tmp_path):
    d = str(tmp_path)
    checkpoint.save_snapshot(d, 0, _state(), config_hash="cfg-A")
    p = checkpoint.snapshot_path(d, 0)
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_snapshot(p, _state(), config_hash="cfg-B")
    assert ei.value.kind == "config_mismatch"
    # Hash-less loads (either side) skip the check by design.
    checkpoint.load_snapshot(p, _state())
    checkpoint.save_snapshot(d, 1, _state())
    checkpoint.load_snapshot(
        checkpoint.snapshot_path(d, 1), _state(), config_hash="cfg-B"
    )


def test_structure_mismatch_refused(tmp_path):
    d = str(tmp_path)
    state = _state()
    checkpoint.save_snapshot(d, 0, state)
    p = checkpoint.snapshot_path(d, 0)
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_snapshot(p, {"a": state["a"]})
    assert ei.value.kind == "structure_mismatch"
    # Same structure, different leaf dtype: also a mismatch.
    drifted = dict(state, step=jnp.float32(41))
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_snapshot(p, drifted)
    assert ei.value.kind == "structure_mismatch"


def test_atomic_write_leaves_no_temp_debris(tmp_path):
    d = str(tmp_path)
    checkpoint.save_snapshot(d, 0, _state())
    names = os.listdir(d)
    assert names == ["snap-00000000.ckpt"]
    # Published files are complete by construction: loading right after a
    # save must never hit a partial write.
    checkpoint.load_snapshot(checkpoint.snapshot_path(d, 0), _state())


def test_config_fingerprint_sensitivity():
    a = checkpoint.config_fingerprint(n=4, cfg="config-repr")
    assert a == checkpoint.config_fingerprint(n=4, cfg="config-repr")
    assert a != checkpoint.config_fingerprint(n=5, cfg="config-repr")
    assert a != checkpoint.config_fingerprint(n=4, cfg="other")


def test_config_fingerprint_sees_interior_of_big_arrays():
    """Array leaves hash from their full bytes: numpy's repr elides
    interiors past ~1000 elements with '...', which used to make two
    different big-fleet params tables fingerprint identical — the resume
    config_mismatch check would then silently accept a stale snapshot."""
    a = np.zeros(2000, np.float32)
    b = a.copy()
    b[1000] = 1.0  # repr(a) == repr(b): both elide the changed interior.
    assert repr(a) == repr(b)
    assert checkpoint.config_fingerprint(params=a) \
        != checkpoint.config_fingerprint(params=b)
    assert checkpoint.config_fingerprint(params=a) \
        == checkpoint.config_fingerprint(params=a.copy())
    # dtype/shape changes flip it even when the bytes match.
    assert checkpoint.config_fingerprint(params=np.zeros(4, np.float32)) \
        != checkpoint.config_fingerprint(params=np.zeros(2, np.float64))
