"""Checkpoint/resume tests: log-artifact round-trip and mid-run scan-carry
resume producing the identical trajectory."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.harness import checkpoint, setup
from tpu_aerial_transport.models import rqp


def test_run_dict_roundtrip(tmp_path):
    logs = {
        "n": 3,
        "dt": 1e-3,
        "state_seq": {"xl": np.random.default_rng(0).normal(size=(5, 3))},
        "x_err_seq": np.arange(5.0),
    }
    p = str(tmp_path / "run.npz")
    checkpoint.save_run(p, logs)
    back = checkpoint.load_run(p)
    assert back["n"] == 3
    assert np.allclose(back["state_seq"]["xl"], logs["state_seq"]["xl"])
    assert np.allclose(back["x_err_seq"], logs["x_err_seq"])


def test_midrun_resume_bitwise(tmp_path):
    """Integrating 100 steps straight == 50 steps, checkpoint, restore, 50 more."""
    n = 3
    params, _, state0 = setup.rqp_setup(n)
    f = jnp.full((n,), float(params.mT) * rqp.GRAVITY / n * 0.9)
    M = jnp.zeros((n, 3))

    def run(state, k):
        def body(s, _):
            return rqp.integrate(params, s, (f, M), 1e-3), None
        return jax.lax.scan(body, state, None, length=k)[0]

    full = run(state0, 100)

    half = run(state0, 50)
    p = str(tmp_path / "ckpt")
    checkpoint.save_state(p, half)
    restored = checkpoint.load_state(p, half)
    resumed = run(restored, 50)

    for leaf_a, leaf_b in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
        assert jnp.array_equal(leaf_a, leaf_b), "resume diverged from straight run"
