"""Property-based tests for the batched conic-QP solver (ops/socp.py) —
the port's replacement for cvxpy+Clarabel (SURVEY §2.9) and therefore the
component whose corners matter most. test_socp.py pins fixed-seed cases;
here hypothesis searches problem scale and conditioning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip(
    "hypothesis",
    reason="optional dev dependency (pip install -e .[test]); property "
    "tests skip without it instead of failing collection",
)
from hypothesis import assume, given, settings
from hypothesis import strategies as st

from tpu_aerial_transport.ops import socp

COMMON = dict(max_examples=20, deadline=None)


def _problem(seed: int, scale: float, nv=8, n_box=6, soc=(4,)):
    """Random strongly-convex QP with box + SOC rows; ``scale`` sweeps the
    cost conditioning over orders of magnitude."""
    rng = np.random.default_rng(seed)
    L = rng.standard_normal((nv, nv))
    P = (L @ L.T + 0.5 * np.eye(nv)) * scale
    q = rng.standard_normal(nv) * scale
    m = n_box + sum(soc)
    A = rng.standard_normal((m, nv)) * 0.5
    lb = rng.uniform(-2.0, -0.5, n_box)
    ub = rng.uniform(0.5, 2.0, n_box)
    return tuple(
        jnp.asarray(a, jnp.float32) for a in (P, q, A, lb, ub)
    ) + (n_box, soc)


@given(seed=st.integers(0, 2**31))
@settings(**COMMON)
def test_kkt_residuals_at_native_scale(seed):
    """Converged solutions satisfy the KKT system (stationarity, cone
    feasibility, complementarity) at the controllers' operating scale."""
    P, q, A, lb, ub, n_box, soc = _problem(seed, 1.0)
    sol = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=400
    )
    stat, prim, comp = socp.kkt_residuals(P, q, A, lb, ub, n_box, soc, sol)
    x_scale = max(1.0, float(jnp.abs(sol.x).max()))
    assert float(prim) < 5e-3 * x_scale, float(prim)
    assert float(stat) < 2e-2 * x_scale, float(stat)
    assert float(comp) < 2e-2 * x_scale, float(comp)


@given(seed=st.integers(0, 2**31), log_scale=st.floats(-2.0, 2.0))
@settings(**COMMON)
def test_rho_scale_covariance(seed, log_scale):
    """Scaling the COST by s and the penalty rho by s leaves the solution
    invariant (the ADMM iterates are identical up to the cost scale). This
    is the real scale property of the fixed-rho solver: rho must track the
    problem scale (the controllers build both together, make_rho_vec) —
    fixed rho at a 100x-different cost scale legitimately converges slowly,
    which hypothesis confirmed when this test asserted raw KKT residuals
    at mismatched scale."""
    scale = float(10.0**log_scale)
    P, q, A, lb, ub, n_box, soc = _problem(seed, 1.0)
    base = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=300, rho=0.4
    )
    scaled = socp.solve_socp(
        P * scale, q * scale, A, lb, ub, n_box=n_box, soc_dims=soc,
        iters=300, rho=0.4 * scale, sigma=1e-6 * scale,
    )
    np.testing.assert_allclose(
        np.asarray(scaled.x), np.asarray(base.x), rtol=2e-3, atol=2e-4
    )


@given(seed=st.integers(0, 2**31))
@settings(**COMMON)
def test_warm_start_is_a_fixed_point(seed):
    """Re-solving from a CONVERGED solution must stay at that solution
    (ADMM fixed point) — the property the controllers' cross-step warm
    starts rely on. Problems the fixed budget fails to converge (hypothesis
    found conditioning where 400 iterations still drift ~3e-3/30-iter) are
    assumed away: an unconverged iterate is not a fixed point and says
    nothing about warm-start correctness."""
    P, q, A, lb, ub, n_box, soc = _problem(seed, 1.0)
    sol = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=600
    )
    assume(float(sol.prim_res) < 1e-4 and float(sol.dual_res) < 1e-4)
    again = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=30, warm=sol
    )
    np.testing.assert_allclose(
        np.asarray(again.x), np.asarray(sol.x), atol=5e-4
    )


@given(seed=st.integers(0, 2**31), k=st.integers(1, 3))
@settings(**COMMON)
def test_equality_rows_are_enforced(seed, k):
    """Box rows with lb == ub are equalities; make_rho_vec's EQ_RHO_SCALE
    boost must drive them tight regardless of which rows they are."""
    P, q, A, lb, ub, n_box, soc = _problem(seed, 1.0)
    rng = np.random.default_rng(seed + 1)
    idx = rng.choice(n_box, size=k, replace=False)
    lbn = np.asarray(lb).copy()
    ubn = np.asarray(ub).copy()
    vals = rng.uniform(-0.5, 0.5, k)
    lbn[idx] = vals
    ubn[idx] = vals
    lb, ub = jnp.asarray(lbn), jnp.asarray(ubn)
    m = A.shape[0]
    rho_vec = socp.make_rho_vec(m, n_box, lb, ub, 0.4, jnp.float32)
    op = socp.kkt_operator(P, A, rho_vec)
    sol = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=500, op=op
    )
    Ax = np.asarray(A @ sol.x)
    np.testing.assert_allclose(Ax[idx], vals, atol=5e-3)


@given(seed=st.integers(0, 2**31))
@settings(**COMMON)
def test_solution_invariant_to_lane_position(seed):
    """vmapped solves are lane-independent: the same problem solved solo and
    embedded at a random lane of a batch of different problems must agree
    exactly (no cross-lane leakage through the batched operators)."""
    P, q, A, lb, ub, n_box, soc = _problem(seed, 1.0)
    solo = socp.solve_socp(
        P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=200
    )
    probs = [_problem(seed + 10 + i, 1.0) for i in range(4)]
    lane = seed % 5
    stacked = []
    for j in range(5):
        stacked.append((P, q, A, lb, ub) if j == lane
                       else probs[j if j < lane else j - 1][:5])
    Ps, qs, As, lbs, ubs = (jnp.stack(z) for z in zip(*stacked))
    batch = jax.vmap(
        lambda P_, q_, A_, lb_, ub_: socp.solve_socp(
            P_, q_, A_, lb_, ub_, n_box=n_box, soc_dims=soc, iters=200
        )
    )(Ps, qs, As, lbs, ubs)
    # Tolerance-level, not bitwise: batched jnp.linalg.inv takes a
    # different LAPACK path than the single-instance call, so the KKT
    # operators differ by f32 roundoff before the first iteration. The
    # property under test is no cross-lane LEAKAGE, not kernel identity.
    np.testing.assert_allclose(
        np.asarray(batch.x[lane]), np.asarray(solo.x), atol=2e-4
    )
