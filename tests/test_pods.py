"""Pods tier (tpu_aerial_transport/parallel/pods.py): 2-D (scenario,
agent) mesh resolution, the topology gate, multi-process placement /
extraction, the 2-D sharded control step's parity against the unsharded
program (nominal AND alive-masked), per-process shard snapshots with the
global manifest, the resumable pods runner, and the subprocess e2e
through tools/pods_local.py — 2 REAL processes, gloo collectives, parity
to f32 rounding against the single-process run of the same mesh.

Heavy multi-process e2es (the acceptance-config 2x4 parity, the
1024-agent swarm, the 2-process preempt+resume) are marked slow; the
bounded 2-process smoke stays in tier-1.
"""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_aerial_transport.control import cadmm, centralized, dd  # noqa: E402
from tpu_aerial_transport.harness import checkpoint, setup  # noqa: E402
from tpu_aerial_transport.parallel import mesh as mesh_mod  # noqa: E402
from tpu_aerial_transport.parallel import pods  # noqa: E402
from tpu_aerial_transport.resilience import backend as backend_mod  # noqa: E402
from tpu_aerial_transport.resilience import faults as faults_mod  # noqa: E402

pytestmark = pytest.mark.pods

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="needs 8 virtual devices (root conftest requests them unless "
           "XLA_FLAGS pins a smaller count)",
)
needs_cores = pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="multi-process pods harness needs >= 2 CPU cores",
)

PODS_LOCAL = os.path.join(REPO, "tools", "pods_local.py")


def _load_pods_local():
    spec = importlib.util.spec_from_file_location("pods_local", PODS_LOCAL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------- resolution gate -------------------------


def test_resolve_spec_auto_prefers_intra_process_agent_shards():
    spec = pods.resolve_pods_spec(8, n_devices=8, n_processes=2)
    assert (spec.scenario_shards, spec.agent_shards) == (2, 4)
    assert spec.local_devices == 4
    # Agent shards never straddle a process: 4 devices/process, agent=4.
    spec = pods.resolve_pods_spec(6, n_devices=8, n_processes=4)
    assert spec.agent_shards == 2  # max d | 6 and | 2.
    assert spec.scenario_shards == 4


def test_resolve_spec_env_force_and_validation(monkeypatch):
    monkeypatch.setenv(pods.ENV_VAR, "4x2")
    spec = pods.resolve_pods_spec(8, n_devices=8, n_processes=1)
    assert (spec.scenario_shards, spec.agent_shards) == (4, 2)
    # An explicit spec wins over the env force.
    spec = pods.resolve_pods_spec(8, "2x4", n_devices=8, n_processes=1)
    assert (spec.scenario_shards, spec.agent_shards) == (2, 4)
    monkeypatch.setenv(pods.ENV_VAR, "bogus")
    with pytest.raises(ValueError, match="TAT_PODS_MESH"):
        pods.resolve_pods_spec(8, n_devices=8, n_processes=1)
    monkeypatch.delenv(pods.ENV_VAR)
    # Agent shards must divide n.
    with pytest.raises(ValueError, match="not divisible"):
        pods.resolve_pods_spec(6, "2x4", n_devices=8, n_processes=1)
    # Process boundary must lie along the scenario axis.
    with pytest.raises(ValueError, match="process boundary"):
        pods.PodsSpec(3, 2, n_processes=2).validate(8)


def test_check_topology_mismatch_is_classified():
    """A mesh bigger than the visible topology raises the classified
    breaker-eligible topology_mismatch (the MULTICHIP_r01 gap)."""
    spec = pods.PodsSpec(scenario_shards=8, agent_shards=8,
                         n_processes=1)
    with pytest.raises(backend_mod.BackendError) as ei:
        pods.check_topology(spec)
    assert ei.value.kind == "topology_mismatch"
    assert backend_mod.classify(ei.value) == "topology_mismatch"
    # Classification from the TEXT alone (a subprocess tail) too.
    assert backend_mod.classify(str(ei.value)) == "topology_mismatch"
    assert "topology_mismatch" in backend_mod.BREAKER_KINDS


def test_probe_reports_topology_and_expected_gate():
    """The subprocess probe reports visible device/process counts and a
    shortfall against the expected topology FAILS it with a classified
    detail (probe-level belt to check_topology's suspender)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop(backend_mod.FAULTS_ENV, None)
    info: dict = {}
    ok, detail = backend_mod.probe_subprocess(
        timeout_s=120.0, env=env, info=info
    )
    assert ok, detail
    assert info["platform"] == "cpu"
    assert info["n_devices"] >= 1 and info["n_processes"] == 1
    info2: dict = {}
    ok, detail = backend_mod.probe_subprocess(
        timeout_s=120.0, env=env, expect_devices=10_000, info=info2
    )
    assert not ok
    assert backend_mod.classify(detail) == "topology_mismatch"
    assert info2["n_devices"] < 10_000  # topology still reported.


# --------------------------- placement plane ---------------------------


@needs_devices
def test_place_global_and_extract_roundtrip():
    m = pods.make_pods_mesh(pods.resolve_pods_spec(8, "2x4"))
    batch = {"a": np.arange(24, dtype=np.float32).reshape(6, 4),
             "s": np.float32(3.0)}
    placed = mesh_mod.shard_scenarios(m, batch)
    # Single-process: device_put path; values roundtrip exactly.
    back = pods.local_host_shard(placed)
    assert np.array_equal(back["a"], batch["a"])
    # place_local_batch with one process: local block IS the global.
    placed2 = pods.place_local_batch(m, {"a": batch["a"]})
    assert placed2["a"].shape == (6, 4)
    assert np.array_equal(pods.host_global(placed2)["a"], batch["a"])


@needs_devices
def test_shard_scenarios_single_process_never_routes_to_pods(monkeypatch):
    """Single-process paths pay zero cost: the multi-process branch is
    never taken on a single-process mesh (1-D or 2-D)."""
    def boom(*a, **k):
        raise AssertionError("pods placement taken on single-process mesh")

    monkeypatch.setattr(pods, "place_global_batch", boom)
    m1 = mesh_mod.make_mesh({"agent": 4})
    m2 = pods.make_pods_mesh(pods.resolve_pods_spec(8, "2x4"))
    batch = {"a": np.ones((4, 3), np.float32)}
    mesh_mod.shard_scenarios(m1, batch, axis="agent")
    mesh_mod.shard_scenarios(m2, batch)


# ------------------------ 2-D control-step parity ----------------------

_TOL = 2e-3  # the test_ring full-control-step bar (f32 summation order).


def _pods_vs_unsharded(controller, n=4, b=4, mesh_str="2x2",
                       max_iter=2, inner_iters=4):
    params, col, state0 = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    m = pods.make_pods_mesh(pods.resolve_pods_spec(n, mesh_str))
    if controller == "cadmm":
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=inner_iters,
        )
        cs0 = cadmm.init_cadmm_state(params, cfg)
        ctrl = cadmm.control
    else:
        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=inner_iters,
        )
        cs0 = dd.init_dd_state(params, cfg)
        ctrl = dd.control
    step = pods.pods_control_step(params, cfg, f_eq, m, None, controller)
    states = pods.scenario_batch(state0, b)
    css = jax.vmap(lambda _: cs0)(jnp.arange(b))
    acc = (jnp.array([0.3, 0.0, 0.1], jnp.float32),
           jnp.zeros(3, jnp.float32))
    f, _, stats, batch_res = jax.jit(step)(
        mesh_mod.shard_scenarios(m, css),
        mesh_mod.shard_scenarios(m, states), acc,
    )
    ref_f, _, ref_stats = jax.vmap(
        lambda cs, s: ctrl(params, cfg, f_eq, cs, s, acc, None)
    )(css, states)
    return (np.asarray(f), float(batch_res), np.asarray(ref_f),
            float(jnp.max(ref_stats.solve_res)))


@needs_devices
def test_pods_step_matches_unsharded_cadmm():
    """The 2-D (scenario, agent) sharded step == the unsharded vmapped
    controller to f32 rounding, and the scenario-axis batch statistic ==
    the host-side max (exact: max is order-free)."""
    f, batch_res, ref_f, ref_res = _pods_vs_unsharded("cadmm")
    assert np.abs(f - ref_f).max() < _TOL
    assert abs(batch_res - ref_res) < _TOL


@needs_devices
@pytest.mark.slow  # tier-1 keeps the cadmm twin; same seam, same specs.
def test_pods_step_matches_unsharded_dd():
    f, batch_res, ref_f, ref_res = _pods_vs_unsharded(
        "dd", n=8, mesh_str="2x4", max_iter=4, inner_iters=8
    )
    assert np.abs(f - ref_f).max() < _TOL
    assert abs(batch_res - ref_res) < _TOL


@needs_devices
@pytest.mark.slow  # tier-1 covers masked parity via the 2-process
#                    smoke's --check-parity (f_masked is in its digest).
def test_pods_step_masked_matches_unsharded():
    """Alive-masked/fault-injected parity over the 2-D mesh: dead agent
    applies zero force, masked sums/denominators/gathers all ride the
    axis-aware exchange."""
    n, b = 8, 4
    params, col, state0 = setup.rqp_setup(n)
    m = pods.make_pods_mesh(pods.resolve_pods_spec(n, "2x4"))
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=4, inner_iters=8,
    )
    alive = np.ones(n, dtype=bool)
    alive[0] = False
    msg_ok = np.ones(n, dtype=bool)
    msg_ok[2] = False
    health = faults_mod.FaultStep(
        alive=jnp.asarray(alive),
        thrust_scale=jnp.asarray(alive, jnp.float32),
        msg_ok=jnp.asarray(msg_ok),
    )
    f_eq = centralized.equilibrium_forces(params, alive=health.alive)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    cs0 = cs0.replace(held=cs0.f)
    states = pods.scenario_batch(state0, b)
    css = jax.vmap(lambda _: cs0)(jnp.arange(b))
    healths = jax.tree.map(
        lambda x: jnp.tile(x[None], (b,) + (1,) * x.ndim), health
    )
    acc = (jnp.array([0.3, 0.0, 0.1], jnp.float32),
           jnp.zeros(3, jnp.float32))
    step = pods.pods_control_step(
        params, cfg, f_eq, m, None, "cadmm", with_health=True
    )
    f, _, _, _ = jax.jit(step)(
        mesh_mod.shard_scenarios(m, css),
        mesh_mod.shard_scenarios(m, states), acc,
        mesh_mod.shard_scenarios(m, healths),
    )
    plan = cadmm.make_plan(params, cfg)
    ref_f, _, _ = jax.vmap(
        lambda cs, s, h: cadmm.control(
            params, cfg, f_eq, cs, s, acc, None, plan=plan, health=h
        )
    )(css, states, healths)
    f = np.asarray(f)
    assert np.isfinite(f).all()
    assert np.abs(f[:, 0]).max() == 0.0  # dead agent: zero force.
    assert np.abs(f - np.asarray(ref_f)).max() < _TOL


# ---------------------- shard snapshots + manifest ---------------------


def test_shard_prefix_and_manifest(tmp_path):
    d = str(tmp_path)
    p0 = checkpoint.shard_prefix("carry", 0, 2)
    assert p0 == "carry.p0of2"
    with pytest.raises(ValueError):
        checkpoint.shard_prefix("carry", 2, 2)
    # Shard snapshots live in the normal grammar: retention/listing see
    # them per prefix, other prefixes invisible.
    checkpoint.save_snapshot(d, 0, {"x": np.ones(3)}, prefix=p0)
    checkpoint.save_snapshot(
        d, 0, {"x": np.ones(3)}, prefix=checkpoint.shard_prefix("carry", 1, 2)
    )
    assert len(checkpoint.list_snapshots(d, p0)) == 1

    checkpoint.save_shard_manifest(
        d, prefix="carry", n_processes=2,
        topology={"scenario_shards": 2, "agent_shards": 4},
        config_hash="abc",
    )
    man = checkpoint.load_shard_manifest(
        d, prefix="carry", n_processes=2, config_hash="abc"
    )
    assert man["shard_prefixes"] == ["carry.p0of2", "carry.p1of2"]
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_shard_manifest(d, prefix="carry", n_processes=4)
    assert ei.value.kind == "config_mismatch"
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_shard_manifest(
            d, prefix="carry", n_processes=2, config_hash="OTHER"
        )
    assert ei.value.kind == "config_mismatch"
    with pytest.raises(checkpoint.SnapshotError) as ei:
        checkpoint.load_shard_manifest(str(tmp_path / "absent"),
                                       prefix="carry")
    assert ei.value.kind == "unreadable"


@needs_devices
def test_pods_rollout_resumable_single_process(tmp_path):
    """The pods chunk driver on a single-process 2-D mesh: per-process
    (p0of1) shard prefixes + manifest, simulated preemption at a
    boundary, agreement (trivial with one process), and bit-identical
    resume — the multi-process twin is the slow subprocess e2e."""
    pl = _load_pods_local()
    m = pods.make_pods_mesh(pods.resolve_pods_spec(4, "2x2"))
    params, cfg, llc, hl, acc_des_fn = pl._centralized_bits(4)
    from tpu_aerial_transport.harness import rollout as h_rollout

    runner = h_rollout.make_chunked_rollout(
        hl, llc.control, params, n_hl_steps=4, n_chunks=2,
        hl_rel_freq=2, acc_des_fn=acc_des_fn,
    )
    _p, _c, state0 = setup.rqp_setup(4)
    states = pods.scenario_batch(state0, 4)
    cs0 = centralized.init_ctrl_state(params, cfg)
    css = jax.vmap(lambda _: cs0)(jnp.arange(4))
    carry0 = pods.local_host_shard(jax.vmap(runner.init_carry)(states, css))

    def make_run(d):
        return pods.pods_rollout_resumable(
            runner.chunk_fn, m, n_hl_steps=4, n_chunks=2,
            run_dir=str(d), seed=0,
        )

    full = make_run(tmp_path / "full")(carry0)
    assert full.status == "done" and full.chunks_done == 2

    run = make_run(tmp_path / "pre")
    pre = run(carry0, interrupt=pl._simulated_preemption(run.plan, 1))
    assert pre.status == "preempted" and pre.chunks_done == 1
    assert os.path.exists(
        checkpoint.shard_manifest_path(str(tmp_path / "pre"), "carry")
    )
    res = make_run(tmp_path / "pre")(carry0, resume=True)
    assert res.status == "done"
    assert res.resumed_from_chunk == 1
    a = pods.local_host_shard(res.carry)
    b = pods.local_host_shard(full.carry)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(la, lb)  # bitwise: same program, same mesh.

    # Topology drift refusal: a run dir written under 1 process refuses
    # a 2-process manifest check (the rebuilt-mesh safety net).
    with pytest.raises(checkpoint.SnapshotError):
        checkpoint.load_shard_manifest(
            str(tmp_path / "pre"), prefix="carry", n_processes=2
        )


# --------------------------- subprocess e2e ----------------------------


def _run_pods_local(args, timeout=900):
    proc = subprocess.run(
        [sys.executable, PODS_LOCAL] + args,
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
    )
    rows = []
    for line in (proc.stdout or "").strip().splitlines():
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return proc, (rows[-1] if rows else None)


@needs_cores
@pytest.mark.slow  # tier-1 already runs the bounded 2-process parity
#                    smoke through tools/ci_check.sh (test_jaxlint
#                    exercises it); this twin ADDS the masked arm.
def test_pods_two_process_parity_smoke():
    """2 REAL processes x 2 virtual devices each (gloo cross-process
    collectives) vs the single-process run of the same 2x2 mesh —
    nominal rollout AND the alive-masked step, compared to f32 rounding
    by the harness itself (--check-parity). The acceptance-config twin
    (2 x 4 devices, n=8) is test_pods_acceptance_parity_2x4."""
    proc, row = _run_pods_local([
        "--mode", "parity", "--check-parity", "--processes", "2",
        "--local-devices", "2", "--n", "4", "--scenarios", "4",
        "--steps", "1", "--max-iter", "2",
        "--out-dir", os.path.join("artifacts", "pods-smoke-test"),
        "--timeout", "600",
    ])
    assert row is not None, proc.stderr[-2000:]
    if "skipped" in row:
        pytest.skip(row["skipped"])
    assert proc.returncode == 0, (row, proc.stderr[-2000:])
    assert row["parity_ok"], row
    assert "f_masked" in row["max_diffs"], row  # masked arm compared too.


@needs_cores
@pytest.mark.slow
def test_pods_acceptance_parity_2x4():
    """The acceptance bar verbatim: 2-process x 4-virtual-device localhost
    pods run of the sharded C-ADMM control step matches the
    single-process 8-device run to f32 rounding, nominal AND masked."""
    proc, row = _run_pods_local([
        "--mode", "parity", "--check-parity", "--processes", "2",
        "--local-devices", "4", "--mesh", "2x4", "--n", "8",
        "--scenarios", "8", "--steps", "2", "--max-iter", "4",
        "--out-dir", os.path.join("artifacts", "pods-parity-2x4"),
        "--timeout", "840",
    ], timeout=1800)
    assert row is not None, proc.stderr[-2000:]
    if "skipped" in row:
        pytest.skip(row["skipped"])
    assert proc.returncode == 0, (row, proc.stderr[-2000:])
    assert row["parity_ok"], row


@needs_cores
@pytest.mark.slow
def test_pods_1024_agent_swarm_e2e():
    """The 1024-agent BASELINE config (128 scenarios x 8 agents) runs
    END-TO-END through the multi-process pods tier on localhost."""
    proc, row = _run_pods_local([
        "--mode", "bench", "--processes", "2", "--local-devices", "4",
        "--mesh", "2x4", "--n", "8", "--scenarios", "128",
        "--steps", "2", "--max-iter", "4", "--reps", "1",
        "--timeout", "1200",
    ], timeout=1500)
    assert row is not None, proc.stderr[-2000:]
    if "skipped" in row:
        pytest.skip(row["skipped"])
    assert proc.returncode == 0, (row, proc.stderr[-2000:])
    assert row["ok"] and row["agents_total"] == 1024, row
    assert row["scenario_mpc_steps_per_sec"] > 0


@needs_cores
@pytest.mark.slow
def test_pods_two_process_preempt_resume_e2e(tmp_path):
    """2-process preempt + resume: per-process shard snapshots, the
    cross-process boundary agreement, bit-identical completion."""
    d = str(tmp_path / "run")
    base = ["--mode", "resume", "--processes", "2", "--local-devices",
            "2", "--n", "4", "--scenarios", "4", "--steps", "4",
            "--chunks", "2", "--out-dir", d, "--timeout", "600"]
    proc, row = _run_pods_local(base + ["--stop-after-chunk", "1"])
    if row and "skipped" in row:
        pytest.skip(row["skipped"])
    assert row and row["status"] == "preempted", (row, proc.stderr[-1500:])
    proc, row = _run_pods_local(base + ["--resume"])
    assert row and row["status"] == "done", (row, proc.stderr[-1500:])
    assert row["resumed_from_chunk"] == 1, row
    ref_dir = str(tmp_path / "ref")
    proc, ref = _run_pods_local(
        ["--mode", "resume", "--processes", "2", "--local-devices", "2",
         "--n", "4", "--scenarios", "4", "--steps", "4", "--chunks", "2",
         "--out-dir", ref_dir, "--timeout", "600"]
    )
    assert ref and ref["status"] == "done", (ref, proc.stderr[-1500:])
    assert row["xl0"] == ref["xl0"]  # bitwise across invocations.


# ------------------------- serving mesh= plumbing ----------------------


@needs_devices
def test_serving_accepts_pods_mesh():
    """serving ``mesh=`` takes the 2-D pods mesh: batch placement rides
    shard_scenarios' multi-process-aware path (single-process here — the
    placement contract, not the wire) and per-request results match the
    meshless server to f32 rounding. (Bitwise is deliberately NOT the
    bar ACROSS placements: sharding the lane axis re-partitions the
    compiled program — the serving tier's bitwise
    composition-independence contract holds within one placement.)"""
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    m = pods.make_pods_mesh(pods.resolve_pods_spec(4, "2x2"))

    def run(mesh):
        srv = server_mod.ScenarioServer(
            families=("cadmm4",), buckets=(2,), capacity=8, mesh=mesh,
        )
        fam = srv.families["cadmm4"]
        tickets = [
            srv.submit(ScenarioRequest(
                family="cadmm4", horizon=fam.chunk_len,
                x0=(1.0 + i, 0.5, 2.0), request_id=f"r{i}",
            ))
            for i in range(2)
        ]
        srv.run_until_drained(max_rounds=16)
        return tickets

    ref = run(None)
    out = run(m)
    for t_ref, t_out in zip(ref, out):
        assert t_out.status == t_ref.status == "completed"
        a = jax.tree.leaves(t_ref.result)
        b = jax.tree.leaves(t_out.result)
        for la, lb in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-4
            )


@needs_devices
def test_serving_boundary_extraction_is_pods_aware(monkeypatch):
    """The boundary carry extraction routes through pods.host_global on
    a MULTI-process mesh (plain host_copy's np.array raises on an array
    spanning non-addressable devices) and stays the plain host copy on
    single-process meshes."""
    from tpu_aerial_transport.serving import server as server_mod

    m = pods.make_pods_mesh(pods.resolve_pods_spec(4, "2x2"))
    srv = server_mod.ScenarioServer(
        families=("cadmm4",), buckets=(2,), capacity=4, mesh=m,
    )
    marker = {"a": np.zeros(1)}

    def fake_global(tree):
        return marker

    monkeypatch.setattr(pods, "host_global", fake_global)
    monkeypatch.setattr(
        mesh_mod, "is_multiprocess_mesh", lambda mesh: True
    )
    assert srv._boundary_host({"a": np.ones(2)}) is marker
    monkeypatch.setattr(
        mesh_mod, "is_multiprocess_mesh", lambda mesh: False
    )
    out = srv._boundary_host({"a": np.ones(2)})
    assert isinstance(out["a"], np.ndarray)
    assert np.array_equal(out["a"], np.ones(2))


# --------------------------- registry coverage -------------------------


def test_pods_entrypoint_registered():
    """Dropping the pods entry from the contract registry (or the traced
    table) must fail tier-1 — pods.py's only scan lives in the waived
    workload factory, so the generic hot-function test cannot see the
    step itself."""
    from tpu_aerial_transport.analysis import contracts, entrypoints

    name = "parallel.pods:pods_control_step"
    assert name in entrypoints.CONTRACT_ENTRYPOINTS
    assert name in contracts.REGISTRY
    assert contracts.REGISTRY[name].min_devices == 8
    traced = entrypoints.TRACED_FUNCTIONS[
        "tpu_aerial_transport/parallel/pods.py"
    ]
    assert "pods_control_step" in traced
    waiver = entrypoints.HOT_NON_ENTRYPOINTS.get(
        "tpu_aerial_transport/parallel/pods.py:make_pods_workload"
    )
    assert waiver and len(waiver) > 40
