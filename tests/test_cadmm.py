"""C-ADMM distributed controller tests. The key oracle (SURVEY.md §4): the
distributed solvers optimize the same convex problem as the centralized
controller, so at consensus their solutions must agree to tolerance."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import cadmm, centralized
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _setup(n=3):
    params, col, state = setup.rqp_setup(n)
    ccfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=250
    )
    acfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    return params, col, state, ccfg, acfg, f_eq


def _random_state(key, n):
    ks = jax.random.split(key, 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.1 * jax.random.normal(ks[0], (n, 3))),
        w=0.1 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.3 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=jnp.zeros(3),
    )


def test_cadmm_agrees_with_centralized_no_env():
    """Random feasible states + targets: C-ADMM consensus forces must match the
    centralized QP solution (both solve the same problem; the reference's own
    implicit invariant)."""
    n = 3
    params, col, _, ccfg, acfg, f_eq = _setup(n)
    for seed in range(3):
        ks = jax.random.split(jax.random.PRNGKey(seed), 2)
        state = _random_state(ks[0], n)
        acc_des = (
            0.5 * jax.random.normal(ks[1], (3,)),
            jnp.zeros(3),
        )
        cs = centralized.init_ctrl_state(params, ccfg)
        f_cent, _, _ = centralized.control(params, ccfg, f_eq, cs, state, acc_des)
        astate = cadmm.init_cadmm_state(params, acfg)
        f_admm, astate, stats = cadmm.control(
            params, acfg, f_eq, astate, state, acc_des
        )
        assert int(stats.iters) < 61, "consensus did not converge"
        err = float(jnp.abs(f_admm - f_cent).max())
        assert err < 5e-2, f"seed {seed}: |f_admm - f_cent| = {err}"


def test_cadmm_converges_and_warm_start_helps():
    n = 3
    params, col, state0, _, acfg, f_eq = _setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.0]), jnp.zeros(3))
    astate = cadmm.init_cadmm_state(params, acfg)
    f1, astate, stats1 = cadmm.control(params, acfg, f_eq, astate, state0, acc_des)
    # Re-solving the same problem warm: should converge in very few iterations.
    f2, astate, stats2 = cadmm.control(params, acfg, f_eq, astate, state0, acc_des)
    assert int(stats2.iters) <= int(stats1.iters)
    assert jnp.abs(f1 - f2).max() < 1e-2
    # err_seq is recorded and decreasing overall.
    errs = stats1.err_seq[~jnp.isnan(stats1.err_seq)]
    assert errs.shape[0] == int(stats1.iters)


def test_cadmm_with_forest_runs_and_is_safe():
    n = 3
    params, col, state0, _, acfg, f_eq = _setup(n)
    forest = forest_mod.make_forest(seed=0)
    state0 = state0.replace(
        xl=jnp.array([5.0, 0.0, 2.0], jnp.float32),
        vl=jnp.array([0.5, 0.0, 0.0], jnp.float32),
    )
    astate = cadmm.init_cadmm_state(params, acfg)
    acc_des = (jnp.array([0.3, 0.0, 0.0]), jnp.zeros(3))
    f, astate, stats = jax.jit(
        lambda a, s: cadmm.control(params, acfg, f_eq, a, s, acc_des, forest)
    )(astate, state0)
    assert bool(jnp.all(jnp.isfinite(f)))
    assert float(stats.min_env_dist) > 0
    # Per-agent vision cones: the masked env data still yields valid rows.
    env = cadmm.agent_env_cbfs(params, acfg, forest, state0)
    assert env.lhs.shape == (n, acfg.n_env_cbfs, 3)


def test_cadmm_jit_compiles_under_scan():
    """The whole distributed control step must compose with lax.scan (rollouts)."""
    n = 3
    params, col, state0, _, acfg, f_eq = _setup(n)
    astate = cadmm.init_cadmm_state(params, acfg)
    acc_des = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))

    def body(carry, _):
        astate, state = carry
        f, astate, _ = cadmm.control(params, acfg, f_eq, astate, state, acc_des)
        M = jnp.zeros((n, 3))
        fz = jnp.sum(f * state.R[..., :, 2], axis=-1)
        state = rqp.integrate(params, state, (fz, M), 1e-2)
        return (astate, state), f

    (_, final), fs = jax.jit(
        lambda c: jax.lax.scan(body, c, None, length=5)
    )((astate, state0))
    assert bool(jnp.all(jnp.isfinite(fs)))


def test_leader_hooks_and_setters():
    """Runtime set_leader/unset_leader/set_tolerance (reference
    rqp_cadmm.py:503-507, 677-688): leader changes re-use the compiled step
    (dynamic pytree leaf), unset_leader drops the tracking cost everywhere."""
    n = 3
    params, col, _, ccfg, acfg, f_eq = _setup(n)
    state = _random_state(jax.random.PRNGKey(3), n)
    acc_des = (jnp.array([0.5, 0.0, 0.0]), jnp.zeros(3))

    step = jax.jit(
        lambda cfg, a, s: cadmm.control(params, cfg, f_eq, a, s, acc_des)
    )
    a0 = cadmm.init_cadmm_state(params, acfg)
    f0, _, _ = step(acfg, a0, state)

    # Same compiled step, different leader — no retrace (leader_idx is a leaf).
    # _cache_size is a private jax API: skip the retrace assertion (not the
    # test) if a jax upgrade removes it, rather than failing the suite.
    has_cache_api = hasattr(step, "_cache_size")
    n_traces = step._cache_size() if has_cache_api else None
    f1, _, _ = step(cadmm.set_leader(acfg, 1), a0, state)
    if has_cache_api:
        assert step._cache_size() == n_traces, "leader change retraced the step"
    assert not bool(jnp.allclose(f0, f1, atol=1e-4)), \
        "leader change did not alter the solution"

    # unset_leader: no tracking cost -> forces stay near equilibrium.
    f_un, _, _ = step(cadmm.unset_leader(acfg), a0, state)
    assert float(jnp.abs(f_un - f_eq).max()) < float(jnp.abs(f0 - f_eq).max())

    # set_tolerance loosens the stop -> no more iterations than the tight run.
    _, _, st_tight = step(acfg, a0, state)
    _, _, st_loose = step(cadmm.set_tolerance(acfg, 1e-1), a0, state)
    assert int(st_loose.iters) <= int(st_tight.iters)

    # set_max_iter caps the consensus loop (static: fresh compile is expected).
    _, _, st_cap = step(cadmm.set_max_iter(acfg, 2), a0, state)
    assert int(st_cap.iters) <= 3


def test_leader_change_mid_rollout():
    """Leader handoff inside a jitted scan: switch the tracking-cost carrier at
    the halfway step; the rollout stays finite and the consensus keeps
    converging (VERDICT round-2 item 7)."""
    n = 3
    params, col, state0, ccfg, acfg, f_eq = _setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.0]), jnp.zeros(3))
    n_steps = 6

    def body(carry, i):
        astate, state = carry
        cfg_i = cadmm.set_leader(
            acfg, jnp.where(i < n_steps // 2, 0, 2)
        )
        f, astate, stats = cadmm.control(
            params, cfg_i, f_eq, astate, state, acc_des
        )
        fz = jnp.sum(f * state.R[..., :, 2], axis=-1)
        state = rqp.integrate(params, state, (fz, jnp.zeros((n, 3))), 1e-3)
        return (astate, state), (stats.iters, stats.solve_res)

    a0 = cadmm.init_cadmm_state(params, acfg)
    (a_fin, s_fin), (iters, res) = jax.jit(
        lambda c, i: jax.lax.scan(body, c, i)
    )((a0, state0), jnp.arange(n_steps))
    assert bool(jnp.all(jnp.isfinite(s_fin.xl)))
    # Consensus converged on both sides of the handoff.
    assert int(iters.max()) <= acfg.max_iter
    assert float(res[-1]) < 1e-2


def test_two_phase_inner_budget_agrees():
    """inner_iters_warm (cheaper solves for consensus iterations >= 2, whose
    warm start is the same step's previous iterate) must converge to the same
    forces as the single-budget path within the consensus tolerance."""
    n = 3
    params, col, _, ccfg, acfg, f_eq = _setup(n)
    state = _random_state(jax.random.PRNGKey(7), n)
    acc_des = (jnp.array([0.4, 0.0, 0.1]), jnp.zeros(3))

    a0 = cadmm.init_cadmm_state(params, acfg)
    f_one, _, st_one = cadmm.control(params, acfg, f_eq, a0, state, acc_des)

    two = acfg.replace(inner_iters_warm=30)
    a0b = cadmm.init_cadmm_state(params, two)
    f_two, _, st_two = cadmm.control(params, two, f_eq, a0b, state, acc_des)

    assert int(st_two.iters) <= two.max_iter
    assert float(st_two.solve_res) < two.res_tol
    assert float(jnp.abs(f_two - f_one).max()) < 5e-3


def test_inner_tol_early_exit_agrees():
    """Tolerance-chunked inner solves (inner_tol > 0: each agent QP stops its
    ADMM chunks once primal+dual residuals clear the tolerance instead of
    always burning the full fixed budget) must reproduce the fixed-budget
    forces and iteration counts for BOTH distributed controllers."""
    from tpu_aerial_transport.control import dd

    n = 4
    params, col, _, _, _, f_eq = _setup(n)
    state = _random_state(jax.random.PRNGKey(11), n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    for mod, make, init in (
        (cadmm, cadmm.make_config, cadmm.init_cadmm_state),
        (dd, dd.make_config, dd.init_dd_state),
    ):
        def run(**kw):
            cfg = make(params, col.collision_radius, col.max_deceleration,
                       max_iter=10, inner_iters=40, **kw)
            st = init(params, cfg)
            return mod.control(params, cfg, f_eq, st, state, acc_des)

        f0, _, st0 = run()
        f1, _, st1 = run(inner_tol=2e-3, inner_check_every=10)
        assert int(st1.iters) == int(st0.iters)
        assert float(jnp.abs(f1 - f0).max()) < 1e-3
