"""RP consensus-ADMM controller (control/rp_cadmm.py) — BEYOND-REFERENCE
(the reference's RP controller is centralized-only): the distributed
machinery generalizes across model families with the same guarantees the
RQP tests assert — centralized agreement, convergence, warm-start reuse,
batched == solo."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.control import rp_cadmm, rp_centralized
from tpu_aerial_transport.harness import setup


def _setup():
    params, col, state0 = setup.rp_setup(3)
    f_eq = rp_centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.array([0.0, 0.0, 0.05]))
    state = state0.replace(
        vl=jnp.array([0.2, 0.1, 0.0]), wl=jnp.array([0.05, 0.0, 0.0])
    )
    return params, f_eq, acc_des, state


def test_agrees_with_centralized():
    """Both decompositions solve the same convex problem: the consensus
    solution must match the centralized one within the consensus
    tolerance."""
    params, f_eq, acc_des, state = _setup()
    ccfg = rp_centralized.make_config(params)
    cs0 = rp_centralized.init_ctrl_state(params, ccfg)
    f_c, _, _ = jax.jit(
        lambda c, s: rp_centralized.control(params, ccfg, f_eq, c, s, acc_des)
    )(cs0, state)

    # carry_duals=True for the warm-restart clause below: the carried duals
    # are the memory that lets a repeat solve at the SAME state close in ~1
    # iteration (the default resets them per step — the closed-loop test
    # covers why).
    dcfg = rp_cadmm.make_config(params, max_iter=60, inner_iters=40,
                                res_tol=1e-3, carry_duals=True)
    ds0 = rp_cadmm.init_state(params, dcfg, f_eq)
    f_d, ds, st = jax.jit(
        lambda c, s: rp_cadmm.control(params, dcfg, f_eq, c, s, acc_des)
    )(ds0, state)
    assert float(st.solve_res) < dcfg.res_tol
    assert float(st.ok_frac) == 1.0  # no equilibrium fallbacks.
    assert float(jnp.abs(f_d - f_c).max()) < 5e-3

    # Warm restart at the same state: consensus must close in ~1 iteration.
    _, _, st2 = jax.jit(
        lambda c, s: rp_cadmm.control(params, dcfg, f_eq, c, s, acc_des)
    )(ds, state)
    assert int(st2.iters) <= 2, int(st2.iters)


def test_respects_actuation_limits():
    """Every agent's own force satisfies its min-thrust and cone/norm-cap
    constraints (the rows kept in its local QP)."""
    params, f_eq, acc_des, state = _setup()
    cfg = rp_cadmm.make_config(params, max_iter=60, inner_iters=40,
                               res_tol=1e-3)
    ds0 = rp_cadmm.init_state(params, cfg, f_eq)
    f, _, _ = jax.jit(
        lambda c, s: rp_cadmm.control(params, cfg, f_eq, c, s, acc_des)
    )(ds0, state)
    f = np.asarray(f)
    base = cfg.base
    tol = 1e-3
    assert np.all(f[:, 2] >= base.min_fz - tol)
    norms = np.linalg.norm(f, axis=-1)
    assert np.all(norms <= base.sec_max_f_ang * f[:, 2] + tol)
    assert np.all(norms <= base.max_f + tol)


def test_batched_matches_solo():
    """vmapped scenarios reproduce per-scenario solo runs (while_loop
    batching keeps converged lanes frozen — the same contract the RQP
    controllers assert)."""
    params, f_eq, acc_des, state = _setup()
    cfg = rp_cadmm.make_config(params, max_iter=30, inner_iters=30,
                               res_tol=1e-3)
    ds0 = rp_cadmm.init_state(params, cfg, f_eq)
    vls = jnp.stack([
        jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.0, 0.2]),
        jnp.array([0.0, -0.2, 0.1]),
    ])
    states = jax.vmap(lambda v: state.replace(vl=v))(vls)
    dss = jax.vmap(lambda _: ds0)(vls)

    f_b, _, st_b = jax.jit(jax.vmap(
        lambda c, s: rp_cadmm.control(params, cfg, f_eq, c, s, acc_des)
    ))(dss, states)
    for k in range(3):
        f_s, _, st_s = jax.jit(
            lambda c, s: rp_cadmm.control(params, cfg, f_eq, c, s, acc_des)
        )(ds0, states_k := jax.tree.map(lambda x: x[k], states))
        np.testing.assert_allclose(
            np.asarray(f_b[k]), np.asarray(f_s), atol=2e-4
        )


def test_sharded_matches_single_program():
    """Agent-sharded RP consensus (shard_map + pmean/pmax over the virtual
    CPU mesh) must reproduce the single-program result — the same contract
    the RQP sharded controllers assert."""
    import pytest

    if len(jax.devices()) < 3:
        pytest.skip("needs >=3 virtual devices")
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    params, f_eq, acc_des, state = _setup()
    cfg = rp_cadmm.make_config(params, max_iter=30, inner_iters=30,
                               res_tol=1e-3)
    ds0 = rp_cadmm.init_state(params, cfg, f_eq)

    f_ref, _, st_ref = jax.jit(
        lambda c, s: rp_cadmm.control(params, cfg, f_eq, c, s, acc_des)
    )(ds0, state)

    m = mesh_mod.make_mesh({"agent": 3})
    step = mesh_mod.rp_cadmm_control_sharded(params, cfg, f_eq, m)
    f_sh, _, st_sh = jax.jit(step)(ds0, state, acc_des)

    np.testing.assert_allclose(
        np.asarray(f_sh), np.asarray(f_ref), atol=2e-4
    )
    # +-1 tolerance: the two paths reduce the consensus mean in different
    # f32 orders (one-kernel sum vs per-shard sums + psum), so a residual
    # landing within epsilon of res_tol can close one iteration apart.
    assert abs(int(st_sh.iters) - int(st_ref.iters)) <= 1


def test_closedloop_circle():
    """Distributed RP consensus tracking the same circular reference the
    centralized closed-loop test flies (reference test_rpcentralized.py:
    14-38 pattern): bounded post-transient tracking error and the tilt CBF
    held — the distributed decomposition is a drop-in for the centralized
    controller in closed loop, not just at a single solve."""
    from tpu_aerial_transport.models import rp as rp_mod

    params, col, state0 = setup.rp_setup(3)
    # With row equilibration in the RP QP builder (socp.equilibrate_rows —
    # before it, the leader-cost QP needed ~600 ADMM iterations and every
    # step ran on the solve-failure edge) this closed loop runs at ~1.2
    # consensus iterations/step with zero fallbacks and ~0.05 m error.
    cfg = rp_cadmm.make_config(params, max_iter=20, inner_iters=40,
                               res_tol=5e-3)
    f_eq = rp_centralized.equilibrium_forces(params)
    ds0 = rp_cadmm.init_state(params, cfg, f_eq)

    radius, omega, dt = 0.5, 0.4, 1e-3

    def ref(t):
        x = jnp.stack([
            radius * jnp.cos(omega * t) - radius,
            radius * jnp.sin(omega * t),
            0.1 * t,
        ])
        v = jnp.stack([
            -radius * omega * jnp.sin(omega * t),
            radius * omega * jnp.cos(omega * t),
            jnp.asarray(0.1),
        ])
        a = jnp.stack([
            -radius * omega**2 * jnp.cos(omega * t),
            -radius * omega**2 * jnp.sin(omega * t),
            jnp.asarray(0.0),
        ])
        return x, v, a

    def body(carry, i):
        state, cs = carry
        t = i * dt * 10
        x_ref, v_ref, a_ref = ref(t)
        dvl_des = a_ref - 1.5 * (state.vl - v_ref) - 2.0 * (state.xl - x_ref)
        acc_des = (dvl_des, jnp.zeros(3))
        f, cs, _ = rp_cadmm.control(params, cfg, f_eq, cs, state, acc_des)

        def ll(s, _):
            return rp_mod.integrate(params, s, f, dt), None

        state, _ = jax.lax.scan(ll, state, None, length=10)
        return (state, cs), jnp.linalg.norm(state.xl - x_ref)

    (final, _), errs = jax.jit(
        lambda c: jax.lax.scan(body, c, jnp.arange(500))
    )((state0, ds0))
    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert float(jnp.max(errs[300:])) < 0.15
    assert float(final.Rl[2, 2]) > float(jnp.cos(jnp.pi / 6)) - 0.02
