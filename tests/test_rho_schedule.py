"""C-ADMM rho schedule parity (reference rqp_cadmm.py:565-567, :657):
``rho_{k+1} = min(rho_k tau_incr, rho_max)``. tau_incr = 1 (the reference
default) must reproduce the constant-rho path exactly; tau_incr > 1 must still
reach consensus agreeing with the centralized solution."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import cadmm, centralized
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _setup(n):
    params, col, state = setup.rqp_setup(n)
    acfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    return params, col, state, acfg, f_eq


def _random_state(key, n):
    ks = jax.random.split(key, 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.1 * jax.random.normal(ks[0], (n, 3))),
        w=0.1 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.3 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=jnp.zeros(3),
    )


def test_schedule_values():
    params, col, _, acfg, _ = _setup(3)
    assert cadmm._rho_schedule(acfg) == [1.0]
    sched = cadmm._rho_schedule(acfg.replace(tau_incr=1.5))
    # 1.0 -> 1.5 -> capped at 2.0, then saturates.
    assert sched == [1.0, 1.5, 2.0]
    assert cadmm._rho_schedule(acfg.replace(tau_incr=1.5, rho0=2.0)) == [2.0]


def test_tau_one_reproduces_constant_rho_path():
    """tau_incr = 1 must be bit-identical to the (previous) constant-rho
    build — the schedule machinery collapses to a single precomputed QP."""
    for n in (3, 5):  # full and reduced formulations.
        params, col, _, acfg, f_eq = _setup(n)
        state = _random_state(jax.random.PRNGKey(n), n)
        acc_des = (jnp.array([0.4, 0.0, 0.1]), jnp.zeros(3))
        a0 = cadmm.init_cadmm_state(params, acfg)
        f_a, _, st_a = cadmm.control(params, acfg, f_eq, a0, state, acc_des)
        explicit = acfg.replace(tau_incr=1.0, rho_max=2.0)
        f_b, _, st_b = cadmm.control(params, explicit, f_eq, a0, state, acc_des)
        assert float(jnp.abs(f_a - f_b).max()) == 0.0, n
        assert int(st_a.iters) == int(st_b.iters), n


def test_tau_incr_agrees_with_centralized():
    """An increasing rho schedule changes the ADMM trajectory but must still
    converge to the same (centralized) solution."""
    for n in (3, 5):
        params, col, _, acfg, f_eq = _setup(n)
        ccfg = centralized.make_config(
            params, col.collision_radius, col.max_deceleration,
            solver_iters=250,
        )
        state = _random_state(jax.random.PRNGKey(n + 20), n)
        acc_des = (0.5 * jax.random.normal(jax.random.PRNGKey(n + 30), (3,)),
                   jnp.zeros(3))
        cs = centralized.init_ctrl_state(params, ccfg)
        f_cent, _, _ = centralized.control(params, ccfg, f_eq, cs, state, acc_des)
        sched = acfg.replace(tau_incr=1.2, rho_max=2.0)
        a0 = cadmm.init_cadmm_state(params, sched)
        f_admm, _, stats = cadmm.control(params, sched, f_eq, a0, state, acc_des)
        assert int(stats.iters) <= sched.max_iter, n
        err = float(jnp.abs(f_admm - f_cent).max())
        assert err < 5e-2, f"n={n}: |f_admm - f_cent| = {err}"
        # The schedule actually visited multiple rho values.
        assert len(cadmm._rho_schedule(sched)) > 1


def test_config_guards():
    import pytest

    params, col, _, acfg, _ = _setup(3)
    # Decaying schedules are rejected loudly (the reference only increases).
    with pytest.raises(ValueError, match="tau_incr"):
        cadmm._rho_schedule(acfg.replace(tau_incr=0.5))
    # The Schur plan refuses n = 3 (singular E_v) instead of returning NaNs.
    with pytest.raises(ValueError, match="n >= 4"):
        cadmm.make_schur_plan(params, acfg)
    # Public factory: None selects the full path at n = 3.
    assert cadmm.make_plan(params, acfg) is None
    params5, _, _, acfg5, _ = _setup(5)[:5]
    assert cadmm.make_plan(params5, acfg5) is not None
