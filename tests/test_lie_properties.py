"""Property-based tests for the SO(3) math core (ops/lie.py) via hypothesis.

The reference checks these identities at a handful of random samples with
printed average errors a human reads (test/utils/test_mathutils.py,
SURVEY.md §4); here each algebraic identity is asserted over a searched
input space, including the adversarial corners hypothesis shrinks toward
(near-zero axes, near-pi rotations, antipodal pairs, ill-conditioned
near-rotations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip(
    "hypothesis",
    reason="optional dev dependency (pip install -e .[test]); property "
    "tests skip without it instead of failing collection",
)
from hypothesis import given, settings
from hypothesis import strategies as st

from tpu_aerial_transport.ops import lie

# Moderate example counts: every example pays a jitted-call dispatch; the
# functions under test are deterministic algebra, so width beats depth.
COMMON = dict(max_examples=60, deadline=None)

finite3 = st.lists(
    st.floats(-10.0, 10.0, allow_nan=False), min_size=3, max_size=3
).map(lambda v: np.asarray(v, np.float32))

unit3 = finite3.filter(lambda v: np.linalg.norm(v) > 1e-3).map(
    lambda v: (v / np.linalg.norm(v)).astype(np.float32)
)


def _is_rotation(R, atol=1e-5):
    R = np.asarray(R, np.float64)
    return (
        np.allclose(R @ R.T, np.eye(3), atol=atol)
        and abs(np.linalg.det(R) - 1.0) < atol
    )


@given(w=finite3)
@settings(**COMMON)
def test_expm_in_so3(w):
    R = np.asarray(lie.expm_so3(jnp.asarray(w)))
    assert _is_rotation(R)


@given(w=finite3)
@settings(**COMMON)
def test_expm_inverse_is_transpose(w):
    Rp = np.asarray(lie.expm_so3(jnp.asarray(w)))
    Rm = np.asarray(lie.expm_so3(jnp.asarray(-w)))
    np.testing.assert_allclose(Rm, Rp.T, atol=1e-5)


@given(w=finite3.filter(lambda v: 1e-4 < np.linalg.norm(v) < np.pi - 0.05))
@settings(**COMMON)
def test_log_expm_roundtrip(w):
    """log(exp(w)) = w on the injectivity ball |w| < pi. The filter backs
    off the pi boundary: f32 log/exp conditioning degrades as the rotation
    angle approaches pi (sin(theta) -> 0 in the denominator), and
    hypothesis reliably finds >2e-3 relative error within 1e-2 of pi."""
    back = np.asarray(lie.log_so3(lie.expm_so3(jnp.asarray(w))))
    np.testing.assert_allclose(back, w, rtol=5e-3, atol=2e-5)


@given(a=finite3, b=finite3)
@settings(**COMMON)
def test_hat_is_cross_product(a, b):
    np.testing.assert_allclose(
        np.asarray(lie.hat(jnp.asarray(a)) @ b), np.cross(a, b),
        rtol=1e-4, atol=1e-4,
    )


@given(v=finite3)
@settings(**COMMON)
def test_vee_hat_roundtrip(v):
    np.testing.assert_allclose(
        np.asarray(lie.vee(lie.hat(jnp.asarray(v)))), v, atol=0
    )


@given(w=finite3, noise=st.floats(0.0, 0.3))
@settings(**COMMON)
def test_polar_project_recovers_rotation(w, noise):
    """Newton-Schulz polar projection: maps a noise-perturbed rotation back
    to SO(3), and is (near-)identity on exact rotations."""
    R = np.asarray(lie.expm_so3(jnp.asarray(w)), np.float32)
    rng = np.random.default_rng(0)
    M = R + noise * 0.1 * rng.standard_normal((3, 3)).astype(np.float32)
    P = np.asarray(lie.polar_project(jnp.asarray(M)))
    assert _is_rotation(P, atol=5e-4)
    if noise == 0.0:
        np.testing.assert_allclose(P, R, atol=1e-5)


@given(a=unit3, b=unit3)
@settings(**COMMON)
def test_rotation_a_to_b_maps_a_to_b(a, b):
    R = np.asarray(lie.rotation_a_to_b(jnp.asarray(a), jnp.asarray(b)))
    assert _is_rotation(R, atol=2e-4)
    np.testing.assert_allclose(R @ a, b, atol=5e-3)


@given(a=unit3)
@settings(**COMMON)
def test_rotation_a_to_b_antipodal(a):
    """The b = -a corner has no unique minimal rotation; the construction
    must still return a proper rotation with R a = -a (reference
    test_mathutils.py:30-39 checks exactly this edge)."""
    R = np.asarray(lie.rotation_a_to_b(jnp.asarray(a), jnp.asarray(-a)))
    assert _is_rotation(R, atol=2e-4)
    np.testing.assert_allclose(R @ a, -a, atol=5e-3)


@given(q=unit3.filter(lambda v: np.hypot(v[0], v[2]) > 1e-2))
@settings(**COMMON)
def test_rotation_from_z_alignment(q):
    """rotation_from_z(q): proper rotation whose third column is q (body z
    aligned with the commanded direction, reference rotation_matrix_from_z_
    vector). Domain excludes q = +-e2, the zero-yaw (ZYX) construction's
    gimbal singularity (hypothesis found it immediately) — unreachable in
    use: the low-level controller feeds thrust directions with q_z > 0
    (min_fz box constraint)."""
    R = np.asarray(lie.rotation_from_z(jnp.asarray(q)))
    assert _is_rotation(R, atol=2e-4)
    np.testing.assert_allclose(R[:, 2], q, atol=5e-3)


@given(theta=st.floats(0.05, np.pi / 2 - 0.05), seed=st.integers(0, 2**31))
@settings(**COMMON)
def test_random_cone_vector_membership(theta, seed):
    """Samples lie inside the half-angle-theta cone about +z and are unit
    (reference test_mathutils.py cone membership, N=10000 -> searched)."""
    v = np.asarray(
        lie.random_cone_vector(jax.random.PRNGKey(seed), theta, shape=(32,))
    )
    norms = np.linalg.norm(v, axis=-1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)
    assert np.all(v[..., 2] >= np.cos(theta) - 1e-5)


# ---- Dynamics-level properties (searched amplitudes, all three models) ----

amp = st.floats(0.01, 5.0)


@given(seed=st.integers(0, 2**31), w_amp=amp, f_amp=st.floats(0.1, 50.0))
@settings(max_examples=25, deadline=None)
def test_rqp_residual_zero_under_searched_amplitudes(seed, w_amp, f_amp):
    """forward_dynamics must zero the Newton-Euler residual at ANY state and
    input amplitude, not just the unit-scale seeds of test_rqp_model.py —
    hypothesis drives angular rates and thrusts orders of magnitude apart
    to expose conditioning-sensitive terms. Tolerance scales with the
    forcing (f32 residual is ~eps * ||terms||)."""
    from tpu_aerial_transport.models import rqp

    n = 4
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 10)
    params = rqp.rqp_params(
        m=0.5 + jax.random.uniform(ks[0], (n,)),
        J=jnp.tile(jnp.eye(3) * 0.01, (n, 1, 1)),
        ml=1.0 + jax.random.uniform(ks[1], ()),
        Jl=jnp.eye(3) * (0.1 + 0.1 * jax.random.uniform(ks[2], ())),
        r=jax.random.normal(ks[3], (n, 3)),
    )
    state = rqp.rqp_state(
        R=jax.vmap(lie.expm_so3)(jax.random.normal(ks[4], (n, 3))),
        w=w_amp * jax.random.normal(ks[5], (n, 3)),
        xl=jnp.zeros(3),
        vl=jnp.zeros(3),
        Rl=lie.expm_so3(jax.random.normal(ks[6], (3,))),
        wl=w_amp * jax.random.normal(ks[7], (3,)),
    )
    # Fresh keys: inputs must be decorrelated from the sampled plant.
    f = f_amp * (1.0 + jax.random.uniform(ks[8], (n,)))
    M = 0.1 * f_amp * jax.random.normal(ks[9], (n, 3))
    acc = rqp.forward_dynamics(params, state, (f, M))
    err = float(rqp.inverse_dynamics_error(state, params, (f, M), acc))
    scale = max(1.0, f_amp * (1.0 + w_amp))
    assert err < 1e-4 * scale, (err, w_amp, f_amp)


@given(seed=st.integers(0, 2**31), w_amp=st.floats(0.1, 30.0),
       dt=st.floats(1e-4, 5e-3))
@settings(max_examples=25, deadline=None)
def test_rqp_integrator_stays_on_manifold(seed, w_amp, dt):
    """20 integrator steps at searched (extreme) angular rates and step
    sizes: every rotation stays orthonormal to f32 roundoff — the manifold
    integrator's whole point (SURVEY §2.2 orthonormality test)."""
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.models import rqp

    params, _, state = setup.rqp_setup(3)
    key = jax.random.PRNGKey(seed)
    state = state.replace(
        w=w_amp * jax.random.normal(key, (3, 3)),
        wl=w_amp * jax.random.normal(jax.random.fold_in(key, 1), (3,)),
    )
    f = params.mT * 9.81 / 3 * jnp.ones((3,))
    M = jnp.zeros((3, 3))

    def body(s, _):
        return rqp.integrate(params, s, (f, M), dt), None

    state, _ = jax.lax.scan(body, state, None, length=20)
    for R in list(np.asarray(state.R)) + [np.asarray(state.Rl)]:
        err = np.abs(R.astype(np.float64) @ R.T - np.eye(3)).max()
        assert err < 5e-5, (err, w_amp, dt)
