"""obs/live.py: the live SLO engine — mergeable log-histogram laws,
in-process metrics hub (zero-cost when off), torn-tail/rotation/resume
jsonl tailing, rolling windows, deterministic burn-rate alerting with a
schema-valid journaled trail, the fleet console's exact consistency with
a post-hoc recompute, and the autoscale burn-rate gate."""

import gc
import json
import math
import os
import random
import subprocess
import sys

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import live as live_mod
from tpu_aerial_transport.serving import fleet as fleet_mod
from tpu_aerial_transport.serving import queue as queue_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONSOLE = os.path.join(REPO, "tools", "fleet_console.py")
RUN_HEALTH = os.path.join(REPO, "tools", "run_health.py")

BASE = 1_700_000_000.0  # deterministic wall-epoch base for journals.


# ------------------------------------------------------ log histogram --

def _hist(values):
    h = live_mod.LogHistogram()
    for v in values:
        h.add(v)
    return h


def test_histogram_merge_is_associative_and_order_independent():
    """Merging is per-bucket integer addition, so any merge tree over
    any partition order yields the SAME buckets — and therefore the
    same quantiles/count_above (the cross-replica consistency law)."""
    rng = random.Random(7)
    values = [rng.lognormvariate(0.0, 2.0) for _ in range(300)]
    values += [0.0, -1.0, 1e-9, 1e9]
    a, b, c = values[:100], values[100:180], values[180:]
    whole = _hist(values)

    left = _hist(a).merge(_hist(b)).merge(_hist(c))       # (a+b)+c
    right = _hist(a).merge(_hist(b).merge(_hist(c)))      # a+(b+c)
    shuffled = _hist(c).merge(_hist(a)).merge(_hist(b))   # c+a+b

    want = whole.to_dict()
    for m in (left, right, shuffled):
        got = m.to_dict()
        # Buckets/counts are integer math: EXACTLY merge-invariant.
        assert {k: got[k] for k in ("counts", "n", "zero")} \
            == {k: want[k] for k in ("counts", "n", "zero")}
        # The float running total is the one order-sensitive field
        # (summation order); everything derived for SLOs is bucketed.
        assert math.isclose(got["total"], want["total"], rel_tol=1e-12)
        for q in (0.5, 0.9, 0.99):
            assert m.quantile(q) == whole.quantile(q)
        assert m.count_above(1.0) == whole.count_above(1.0)


def test_histogram_quantiles_and_zero_bucket():
    h = _hist([0.0, -3.0])
    assert h.quantile(0.5) == 0.0     # zero bucket sorts first.
    assert h.count_above(0.5) == 0    # zeros are never "slow".
    h.add(100.0)
    assert h.quantile(0.99) >= 100.0  # upper bucket edge covers it.
    assert h.count_above(0.5) == 1
    assert live_mod.LogHistogram().quantile(0.5) is None  # empty.
    # Round-trip through the snapshot form.
    assert live_mod.LogHistogram.from_dict(h.to_dict()).to_dict() \
        == h.to_dict()


# --------------------------------------------------------- metrics hub --

def test_hub_primitives_and_ingest_mappers():
    hub = live_mod.MetricsHub()
    hub.inc("x")
    hub.inc("x", n=2)
    hub.gauge("g", 0.5, key="f")
    hub.ingest_serving({"kind": "completed", "tenant": "pro",
                        "request_id": "r1", "slo": {"latency_s": 0.25}})
    hub.ingest_serving({"kind": "rejected", "request_id": "r2",
                        "reason": "queue_full", "depth": 3})
    hub.ingest_session({"kind": "step_done", "session_id": "c0",
                        "step_seq": 1, "rung": "served",
                        "slo": {"latency_s": 0.1}})
    hub.ingest_backend({"kind": "circuit_open"})
    hub.ingest_aot({"rung": "bundle_exec", "wall_s": 0.02})
    snap = hub.snapshot()
    assert snap["counters"]["x"] == 3
    assert snap["counters"]["serving.events{completed}"] == 1
    assert snap["counters"]["serving.rejected{queue_full}"] == 1
    assert snap["counters"]["backend.events{circuit_open}"] == 1
    assert snap["counters"]["aot.serves{bundle_exec}"] == 1
    assert snap["gauges"]["g{f}"] == 0.5
    assert snap["gauges"]["queue.depth"] == 3
    assert snap["histograms"]["serving.latency_s{pro}"]["count"] == 1
    assert snap["histograms"]["session.step_latency_s{served}"][
        "count"] == 1


def test_admission_queue_hub_counters():
    """The queue's hub instrumentation counts submits/rejections/
    dequeues/deadline misses without touching the emit contract."""
    hub = live_mod.MetricsHub()
    q = queue_mod.AdmissionQueue(lambda fam: 4, capacity=1, hub=hub)
    t1 = q.submit(queue_mod.ScenarioRequest(family="f", horizon=4))
    t2 = q.submit(queue_mod.ScenarioRequest(family="f", horizon=4))
    assert t1.status == queue_mod.PENDING
    assert t2.status == queue_mod.REJECTED
    taken = q.take("f", 4)
    assert len(taken) == 1
    snap = hub.snapshot()
    assert snap["counters"]["queue.submitted{default}"] == 1
    assert snap["counters"][
        f"queue.rejected{{{queue_mod.REASON_QUEUE_FULL}}}"] == 1
    assert snap["counters"]["queue.dequeued{f}"] == 1


def test_hub_none_is_zero_cost():
    """The zero-cost contract: with ``hub=None`` the instrumented queue
    path allocates NO obs.live objects at all (checked against the gc
    heap), and the hub attribute stays None end to end."""
    q = queue_mod.AdmissionQueue(lambda fam: 4, capacity=8, hub=None)
    gc.collect()
    live_types = (live_mod.MetricsHub, live_mod.LogHistogram)
    before = sum(isinstance(o, live_types) for o in gc.get_objects())
    for i in range(16):
        q.submit(queue_mod.ScenarioRequest(family="f", horizon=4))
    q.take("f", 16)
    q.expire_deadlines()
    gc.collect()
    after = sum(isinstance(o, live_types) for o in gc.get_objects())
    assert q.hub is None
    assert after == before


# -------------------------------------------------------- jsonl tailer --

def test_tailer_holds_back_torn_tail(tmp_path):
    """A concurrent writer mid-line never yields a phantom event: the
    unterminated tail stays buffered until its newline lands."""
    path = str(tmp_path / "r0.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "a"}) + "\n")
        fh.write('{"event": "b", "x"')  # writer caught mid-line.
    t = live_mod.JsonlTailer(path)
    assert [e["event"] for e in t.poll()] == ["a"]
    assert t.poll() == []  # still torn: nothing new, no phantom.
    with open(path, "a") as fh:
        fh.write(': 1}\n')
    assert [e["event"] for e in t.poll()] == ["b"]


def test_tailer_rotation_and_truncation_reopen_from_top(tmp_path):
    path = str(tmp_path / "r0.metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "old1"}) + "\n")
        fh.write(json.dumps({"event": "old2"}) + "\n")
    t = live_mod.JsonlTailer(path)
    assert len(t.poll()) == 2
    # Rotation: a NEW file (new inode) appears at the path.
    side = str(tmp_path / "new.jsonl")
    with open(side, "w") as fh:
        fh.write(json.dumps({"event": "fresh"}) + "\n")
    os.replace(side, path)
    assert [e["event"] for e in t.poll()] == ["fresh"]
    # Truncation below the offset also restarts from byte 0.
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "tiny"}) + "\n")
    assert [e["event"] for e in t.poll()] == ["tiny"]


def test_tailer_resume_from_offset_equals_posthoc_read(tmp_path):
    """Stop a console mid-stream, resume a NEW one from the saved byte
    offsets: the union of both consoles' events equals the post-hoc
    ``jsonl_read`` of the finished file (no loss, no duplication)."""
    path = str(tmp_path / "r0.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    for i in range(5):
        w.emit("serving_event", kind="submitted", request_id=f"a{i}",
               ts=BASE + i)
    first = live_mod.FleetTailer([str(tmp_path)])
    got1 = [e for _r, e in first.poll()]
    offsets = first.offsets()
    for i in range(5):
        w.emit("serving_event", kind="completed", request_id=f"a{i}",
               ts=BASE + 10 + i)
    resumed = live_mod.FleetTailer([str(tmp_path)], offsets=offsets)
    got2 = [e for _r, e in resumed.poll()]
    assert len(got1) == 5 and len(got2) == 5
    assert got1 + got2 == export_mod.read_events(path)
    # Replica label comes from the file stem.
    assert live_mod.FleetTailer.replica_of(path) == "r0"


# ------------------------------------------------------ rolling windows --

def _sev(kind, rid, ts, tenant="pro", family="f", **extra):
    return {"event": "serving_event", "schema": 9, "ts": ts,
            "kind": kind, "request_id": rid, "tenant": tenant,
            "family": family, **extra}


def test_rolling_windows_rates_and_trailing_sum():
    w = live_mod.RollingWindows()
    w.ingest("r0", _sev("submitted", "r1", BASE))
    w.ingest("r0", _sev("completed", "r1", BASE + 1,
                        slo={"latency_s": 0.5}))
    w.ingest("r1", _sev("submitted", "r2", BASE + 2))
    w.ingest("r1", _sev("rejected", "r3", BASE + 2,
                        reason="queue_full"))
    w.ingest("r1", _sev("deadline_missed", "r2", BASE + 30))
    w.ingest("r0", _sev("submitted", "c1", BASE + 30, tenant="free"))
    w.ingest("r0", _sev("completed", "c1", BASE + 31, tenant="free",
                        slo={"latency_s": 0.1}, cached=True))
    w.ingest("r0", _sev("cache_hit", "c1", BASE + 30, tenant="free"))
    rates = w.rates(60)
    pro = rates["pro"]
    # rejected submits count as attempts: 2 clean + 1 rejected.
    assert pro["submitted"] == 3 and pro["rejected"] == 1
    assert pro["completed"] == 1 and pro["missed"] == 1
    assert pro["miss_rate"] == 0.5          # missed / (completed+missed)
    assert pro["rejection_rate"] == 1 / 3
    free = rates["free"]
    assert free["cache_hit_rate"] == 1.0
    # A 1s window ending at the newest ts sees only that second.
    counts, _ = w.window(1)
    assert counts == {"completed": 1}
    counts10, _ = w.window(10)  # trailing 10 s spans BASE+22..BASE+31.
    assert counts10 == {"submitted": 1, "completed": 1,
                        "cache_hit": 1, "missed": 1}
    # Groups carry (tenant, family, replica) identity.
    assert ("pro", "f", "r1") in w.groups()


# ------------------------------------------- burn-rate alerting (SLOs) --

def _write_storm(w, base, n_good, n_miss, tenant="pro"):
    """One deterministic traffic minute at ``base``: latencies tiny,
    timestamps spread over 60 s so every window sees the same totals."""
    for i in range(n_good):
        w.emit("serving_event", kind="completed", request_id=f"g{i}",
               tenant=tenant, family="f", slo={"latency_s": 0.01},
               ts=base + (i % 60))
    for i in range(n_miss):
        w.emit("serving_event", kind="deadline_missed",
               request_id=f"m{i}", tenant=tenant, family="f",
               ts=base + (i % 60))


def test_miss_storm_fires_fast_burn_then_resolves(tmp_path):
    """The alerting proof: a seeded deadline-miss storm deterministically
    fires the fast-burn page for exactly (miss_rate, pro), journals a
    schema-valid ``alert`` trail into the metrics file, and a clean
    fast window later resolves it."""
    path = str(tmp_path / "storm.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    _write_storm(w, BASE, n_good=30, n_miss=30)  # 50% misses.

    engine = live_mod.SLOEngine(metrics=export_mod.MetricsWriter(path))
    for e in export_mod.read_events(path):
        engine.ingest("r0", e)
    fired = engine.evaluate()
    assert [(a["kind"], a["slo"], a["tenant"]) for a in fired] == [
        ("fire", "miss_rate", "pro")]
    # Deterministic diagnosis: bad/total = 30/60, budget 0.01 → burn 50.
    assert fired[0]["burn_rate"] == 50.0
    assert fired[0]["severity"] == "fast"
    assert math.isclose(engine.max_burn(), 50.0, rel_tol=1e-9)
    assert sorted(engine.firing) == [("miss_rate", "pro")]

    # Recovery: a clean trailing fast-window (300 s) of good traffic.
    _write_storm(w, BASE + 400, n_good=60, n_miss=0)
    for e in export_mod.read_events(path)[60:]:
        if e.get("event") == "serving_event":
            engine.ingest("r0", e)
    resolved = engine.evaluate()
    assert [(a["kind"], a["slo"]) for a in resolved] == [
        ("resolve", "miss_rate")]
    assert resolved[0]["fired_ts"] == fired[0]["ts"]
    assert engine.firing == {}

    # The journaled trail is schema-valid v9 alongside the traffic.
    assert export_mod.validate_file(path) == []
    alerts = [e for e in export_mod.read_events(path)
              if e["event"] == "alert"]
    assert [a["kind"] for a in alerts] == ["fire", "resolve"]

    # run_health renders the trail: fired 1, resolved 1, none open.
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health
    al = run_health.summarize(export_mod.read_events(path))["alerts"]
    assert al["fired"] == 1 and al["resolved"] == 1
    assert al["unresolved"] == []


def test_nominal_traffic_fires_nothing(tmp_path):
    path = str(tmp_path / "calm.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    _write_storm(w, BASE, n_good=200, n_miss=0)
    engine = live_mod.SLOEngine(metrics=export_mod.MetricsWriter(path))
    for e in export_mod.read_events(path):
        engine.ingest("r0", e)
    assert engine.evaluate() == []
    assert engine.firing == {} and engine.alerts == []
    # And the console's CI mode agrees: exit 0, no firing alerts.
    out = subprocess.run(
        [sys.executable, CONSOLE, path, "--once", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["slo"]["firing"] == []


def test_slo_spec_grammar_and_validation(tmp_path):
    spec = live_mod.parse_slo_spec(
        "p99:step_latency:0.99:threshold_s=0.5:tenant=pro:fast_burn=10")
    assert spec.name == "p99" and spec.threshold_s == 0.5
    assert spec.tenant == "pro" and spec.fast_burn == 10.0
    for bad in ("p99:step_latency",            # too few parts.
                "x:unknown_metric:0.9",        # unknown metric.
                "x:rejection:1.5",             # objective out of range.
                "x:step_latency:0.99",         # missing threshold_s.
                "x:rejection:0.9:bogus=1"):    # unknown key.
        try:
            live_mod.parse_slo_spec(bad)
        except ValueError:
            continue
        raise AssertionError(f"spec {bad!r} should have been rejected")


def test_burn_rate_knob_resolvers(monkeypatch):
    monkeypatch.delenv("TAT_SLO_BURN_RATES", raising=False)
    monkeypatch.delenv("TAT_CONSOLE_REFRESH_S", raising=False)
    assert live_mod.resolve_burn_rates() == live_mod.DEFAULT_BURN_RATES
    assert live_mod.resolve_burn_rates((10, 5)) == (10.0, 5.0)
    monkeypatch.setenv("TAT_SLO_BURN_RATES", "8:2")
    assert live_mod.resolve_burn_rates((10, 5)) == (8.0, 2.0)  # env wins.
    monkeypatch.setenv("TAT_SLO_BURN_RATES", "bogus")
    try:
        live_mod.resolve_burn_rates()
        raise AssertionError("bad TAT_SLO_BURN_RATES should raise")
    except ValueError:
        pass
    monkeypatch.setenv("TAT_CONSOLE_REFRESH_S", "0.25")
    assert live_mod.resolve_refresh_s(5.0) == 0.25  # env wins.


# ----------------------------------------------------- fleet console --

def test_fleet_console_once_matches_posthoc_recompute(tmp_path):
    """The consistency proof: ``fleet_console --once --json`` numbers
    equal an independent post-hoc recompute from ``jsonl_read`` exactly
    — same windows, same rates, same burn rates, float-for-float."""
    path = str(tmp_path / "fleet.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    rng = random.Random(3)
    for i in range(40):
        tenant = ("pro", "free", "batch")[i % 3]
        ts = BASE + rng.uniform(0, 45)
        w.emit("serving_event", kind="submitted", request_id=f"r{i}",
               tenant=tenant, family="f", ts=ts)
        w.emit("serving_event", kind="completed", request_id=f"r{i}",
               tenant=tenant, family="f", ts=ts + rng.uniform(0, 5),
               slo={"latency_s": rng.lognormvariate(-2, 1)})
    out = subprocess.run(
        [sys.executable, CONSOLE, path, "--once", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    frame = json.loads(out.stdout)

    windows = live_mod.RollingWindows()
    replica = live_mod.FleetTailer.replica_of(path)
    for e in export_mod.read_events(path):
        windows.ingest(replica, e)
    engine = live_mod.SLOEngine(windows=windows)
    engine.evaluate()
    expect = json.loads(json.dumps({
        "now": windows.latest_ts,
        "groups": [list(g) for g in windows.groups()],
        "windows": {str(win): windows.rates(win)
                    for win in live_mod.CONSOLE_WINDOWS},
        "slo": engine.snapshot(),
    }))
    assert frame == expect


def test_run_health_follow_renders_live_rates(tmp_path):
    """The --follow satellite: one bounded round over a directory of
    replica journals prints the trailing-window vitals as JSON."""
    w0 = export_mod.MetricsWriter(str(tmp_path / "r0.metrics.jsonl"))
    w1 = export_mod.MetricsWriter(str(tmp_path / "r1.metrics.jsonl"))
    for i in range(4):
        w0.emit("serving_event", kind="submitted", request_id=f"a{i}",
                tenant="pro", family="f", ts=BASE + i)
        w1.emit("serving_event", kind="completed", request_id=f"a{i}",
                tenant="pro", family="f", ts=BASE + i + 1,
                slo={"latency_s": 0.2})
    out = subprocess.run(
        [sys.executable, RUN_HEALTH, str(tmp_path), "--follow",
         "--window", "60", "--rounds", "1", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["window_s"] == 60
    assert row["tenants"]["pro"]["submitted"] == 4
    assert row["tenants"]["pro"]["completed"] == 4
    assert row["tenants"]["pro"]["latency"]["count"] == 4


# -------------------------------------------- autoscale burn-rate gate --

def test_autoscale_burn_rate_gates_up_and_down():
    events = []
    sig = fleet_mod.AutoscaleSignal(
        policy=fleet_mod.AutoscalePolicy(confirm=1),
        emit=lambda **kw: events.append(kw))
    # Budget burning at the paging rate scales up even on an idle queue.
    assert sig.observe(queue_depth=0, sessions=0,
                       burn_rate=20.0) == "scale_up"
    assert events[-1]["burn_rate"] == 20.0
    # An elevated (but sub-page) burn BLOCKS scale_down: not up, and
    # the down gate needs burn <= sustainable.
    sig2 = fleet_mod.AutoscaleSignal(
        policy=fleet_mod.AutoscalePolicy(confirm=1))
    assert sig2.observe(queue_depth=0, sessions=0,
                        burn_rate=5.0) == "steady"
    assert sig2.last["raw"] == "steady"
    # Sustainable burn allows the idle scale_down again.
    assert sig2.observe(queue_depth=0, sessions=0,
                        burn_rate=0.5) == "scale_down"
    # burn_rate=None (no engine / no traffic) leaves behavior unchanged.
    sig3 = fleet_mod.AutoscaleSignal(
        policy=fleet_mod.AutoscalePolicy(confirm=1))
    assert sig3.observe(queue_depth=0, sessions=0) == "scale_down"


def test_fleet_front_feeds_slo_burn_into_autoscale():
    """FleetFront.pump() threads the engine's worst fast-window burn
    into the autoscale observation (None before any traffic)."""

    class FakeEngine:
        def __init__(self):
            self.burn = None

        def max_burn(self):
            return self.burn

    engine = FakeEngine()
    front = fleet_mod.FleetFront(
        [0], lambda fam: 4, send=lambda r, op: None, slo=engine,
        autoscale_policy=fleet_mod.AutoscalePolicy(confirm=1))
    front.pump()
    assert front.autoscale.last["burn_rate"] is None
    engine.burn = 30.0
    front.pump()
    assert front.autoscale.last["burn_rate"] == 30.0
    assert front.autoscale.hint == "scale_up"


def test_fleet_front_hub_sees_admissions():
    hub = live_mod.MetricsHub()
    front = fleet_mod.FleetFront(
        [0], lambda fam: 4, send=lambda r, op: None, hub=hub)
    front.submit(queue_mod.ScenarioRequest(family="f", horizon=4))
    snap = hub.snapshot()
    assert snap["counters"]["queue.submitted{default}"] == 1
    assert snap["counters"]["serving.events{submitted}"] == 1
