"""Fused ADMM chunk kernel (ops/admm_kernel.py) vs the scan path.

Oracles: (1) solver-level — identical (P, q, A) batches solved with
``fused="interpret"`` (the Pallas kernel under the interpreter) must match
``fused="scan"`` iterate-for-iterate to f32 roundoff, including warm starts,
shifts, and SOC blocks; (2) controller-level — a C-ADMM control step with the
fused chunks must reproduce the scan step's forces through the full
vmap-folding path (agents, then scenarios: the custom_vmap recursion that
collapses nested vmaps into kernel lanes); (3) the >MAX_FUSED_DIM guard
falls back to scan instead of building an oversized kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.ops import admm_kernel, socp


def _random_qp(key, nv=10, n_eq=3, n_ineq=4, n_soc=2, soc_dim=4):
    """A feasible conic QP with equalities, inequalities, and SOC blocks."""
    ks = jax.random.split(key, 6)
    G = jax.random.normal(ks[0], (nv, nv))
    P = G @ G.T / nv + 0.5 * jnp.eye(nv)
    q = jax.random.normal(ks[1], (nv,))
    n_box = n_eq + n_ineq
    A_box = jax.random.normal(ks[2], (n_box, nv))
    x_feas = 0.1 * jax.random.normal(ks[3], (nv,))
    b = A_box @ x_feas
    lb = jnp.concatenate([b[:n_eq], b[n_eq:] - 1.0])
    ub = jnp.concatenate([b[:n_eq], jnp.full((n_ineq,), socp.INF)])
    A_soc = jax.random.normal(ks[4], (n_soc * soc_dim, nv)) * 0.3
    # Make the cone rows loose at x_feas via a constant top-entry shift.
    shift = jnp.zeros((n_box + n_soc * soc_dim,))
    for i in range(n_soc):
        shift = shift.at[n_box + i * soc_dim].add(3.0)
    A = jnp.concatenate([A_box, A_soc], axis=0)
    return P, q, A, lb, ub, shift, n_box, (soc_dim,) * n_soc


@pytest.mark.parametrize("warm_start", [False, True])
def test_fused_matches_scan_solver_level(warm_start):
    B = 5
    keys = jax.random.split(jax.random.PRNGKey(0), B)
    P, q, A, lb, ub, shift, n_box, soc_dims = jax.vmap(_random_qp)(keys)
    n_box, soc_dims = 7, (4, 4)

    warm = None
    if warm_start:
        m = A.shape[1]
        nv = P.shape[-1]
        warm = socp.SOCPSolution(
            x=0.1 * jnp.ones((B, nv)), y=0.05 * jnp.ones((B, m)),
            z=jnp.zeros((B, m)), prim_res=jnp.zeros((B,)),
            dual_res=jnp.zeros((B,)),
        )

    def solve(mode, w):
        return jax.vmap(
            lambda P_, q_, A_, lb_, ub_, s_, w_: socp.solve_socp(
                P_, q_, A_, lb_, ub_, n_box=n_box, soc_dims=soc_dims,
                iters=50, shift=s_, warm=w_, fused=mode,
            )
        )(P, q, A, lb, ub, shift, w)

    ref = solve("scan", warm)
    out = solve("interpret", warm)
    # 1e-4 abs: 50 f32 iterations with a different matvec reduction order
    # (kernel: broadcast-multiply + sublane sum; scan: dot) accumulate ~5e-5.
    np.testing.assert_allclose(
        np.asarray(out.x), np.asarray(ref.x), rtol=0, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out.y), np.asarray(ref.y), rtol=0, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out.prim_res), np.asarray(ref.prim_res), rtol=0, atol=1e-4
    )


def test_fused_chunk_lanes_direct_padding():
    """admm_chunk_lanes pads B to LANE_TILE and slices back: B = 3 (heavy
    padding) must equal per-instance scans exactly."""
    B, nv, m = 3, 6, 9
    n_box, soc_dims = 5, (4,)
    d = nv + m
    ks = jax.random.split(jax.random.PRNGKey(1), 9)
    K2 = 0.1 * jax.random.normal(ks[0], (B, d, d))
    w2 = jax.random.normal(ks[1], (B, d))
    rho = jnp.abs(jax.random.normal(ks[2], (B, m))) + 0.1
    lb = -jnp.abs(jax.random.normal(ks[3], (B, n_box)))
    ub = jnp.abs(jax.random.normal(ks[4], (B, n_box)))
    shift = 0.1 * jax.random.normal(ks[5], (B, m))
    x = jax.random.normal(ks[6], (B, nv))
    y = jax.random.normal(ks[7], (B, m))
    z = jax.random.normal(ks[8], (B, m))

    xo, yo, zo = admm_kernel.admm_chunk_lanes(
        x, y, z, K2, w2, rho, lb, ub, shift,
        nv=nv, n_box=n_box, soc_dims=soc_dims, iters=7, alpha=1.6,
        interpret=True,
    )

    def ref_one(x_, y_, z_, K2_, w2_, rho_, lb_, ub_, s_):
        c = (x_, y_, z_)
        for _ in range(7):
            c = socp._admm_step(
                c, K2_, w2_, rho_, lb_, ub_, s_,
                nv=nv, n_box=n_box, soc_dims=soc_dims, alpha=1.6,
            )
        return c

    xr, yr, zr = jax.vmap(ref_one)(x, y, z, K2, w2, rho, lb, ub, shift)
    np.testing.assert_allclose(np.asarray(xo), np.asarray(xr), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(yo), np.asarray(yr), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(zo), np.asarray(zr), rtol=1e-5, atol=1e-5)


def test_cadmm_step_fused_matches_scan():
    """Full C-ADMM control step (agents vmapped inside, scenarios vmapped
    outside — the double fold) with fused chunks == scan chunks."""
    n = 4
    params, col, state = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    def run(mode):
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=6, inner_iters=10, res_tol=1e-3, socp_fused=mode,
        )
        astate = cadmm.init_cadmm_state(params, cfg)
        # Scenario batch: vary the payload velocity.
        vls = jnp.stack([
            jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
            jnp.array([0.0, 0.0, -0.2]),
        ])
        states = jax.vmap(lambda v: state.replace(vl=v))(vls)
        astates = jax.vmap(lambda _: astate)(vls)

        def one(ast, st):
            return cadmm.control(params, cfg, f_eq, ast, st, acc_des)

        f, new_state, stats = jax.jit(jax.vmap(one))(astates, states)
        return f, stats

    f_ref, st_ref = run("scan")
    f_out, st_out = run("interpret")
    np.testing.assert_allclose(
        np.asarray(f_out), np.asarray(f_ref), rtol=0, atol=5e-4
    )
    assert np.array_equal(np.asarray(st_out.iters), np.asarray(st_ref.iters))


def test_sharded_cadmm_fused_matches_single_program():
    """Agent-sharded consensus (shard_map + psum) with the fused kernel must
    match the single-program scan path — the combination a real TPU mesh
    runs (each shard's local-agent vmap folds into kernel lanes; the
    consensus collectives stay outside the kernel)."""
    if len(jax.devices()) < 4:
        import pytest as _pytest

        _pytest.skip("needs 4 virtual devices")
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    n = 4
    params, col, state = setup.rqp_setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)

    cfg_ref = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=10, res_tol=1e-3, socp_fused="scan",
    )
    astate = cadmm.init_cadmm_state(params, cfg_ref)
    # jit both paths: eager consensus dispatch costs ~2k one-op compiles
    # per step (see tests/test_parallel.py sharded tests).
    f_ref, _, _ = jax.jit(
        lambda a, s: cadmm.control(params, cfg_ref, f_eq, a, s, acc_des)
    )(astate, state)

    cfg = cfg_ref.replace(socp_fused="interpret")
    m = mesh_mod.make_mesh({"agent": 4})
    step = jax.jit(mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m))
    f_sh, _, _ = step(astate, state, acc_des)
    assert np.abs(np.asarray(f_sh) - np.asarray(f_ref)).max() < 5e-3


def test_oversized_solve_falls_back_to_scan():
    """nv + m > MAX_FUSED_DIM must not build a kernel (would blow VMEM):
    fused="pallas" silently uses the scan path and still solves."""
    nv = admm_kernel.MAX_FUSED_DIM + 10
    P = jnp.eye(nv)
    q = -jnp.ones((nv,))
    A = jnp.eye(nv)[:4]
    lb, ub = jnp.zeros(4), jnp.full((4,), 0.5)
    sol = socp.solve_socp(
        P, q, A, lb, ub, n_box=4, soc_dims=(), iters=30, fused="pallas"
    )
    assert float(sol.prim_res) < 1e-3
    np.testing.assert_allclose(np.asarray(sol.x[:4]), 0.5, atol=1e-2)


def test_dd_step_fused_matches_scan():
    """Full DD control step (18-var agent QPs, d = nv + m = 49 kernel dim)
    with fused chunks == scan chunks — the coverage that lets the on-chip
    fused A/B flip DD's default too, not just C-ADMM's."""
    from tpu_aerial_transport.control import dd

    n = 4
    params, col, state = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    def run(mode):
        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=6, inner_iters=10, socp_fused=mode,
        )
        dstate = dd.init_dd_state(params, cfg)
        vls = jnp.stack([
            jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
        ])
        states = jax.vmap(lambda v: state.replace(vl=v))(vls)
        dstates = jax.vmap(lambda _: dstate)(vls)

        def one(dst, st):
            return dd.control(params, cfg, f_eq, dst, st, acc_des)

        f, _, stats = jax.jit(jax.vmap(one))(dstates, states)
        return f, stats

    f_ref, st_ref = run("scan")
    f_out, st_out = run("interpret")
    np.testing.assert_allclose(
        np.asarray(f_out), np.asarray(f_ref), rtol=0, atol=5e-4
    )
    assert np.array_equal(np.asarray(st_out.iters), np.asarray(st_ref.iters))


def test_dd_step_fused_inner_tol_matches_scan():
    """Tolerance-chunked inner solves UNDER the fused kernel (the while_loop
    of fused chunk runners, batched by vmap) — the exact composition the
    on-chip sweep cell dd_n64_batch64_innertol_pallas runs — must trace,
    execute, and match the scan path on CPU (interpret mode) first."""
    from tpu_aerial_transport.control import dd

    n = 4
    params, col, state = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    def run(mode):
        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=6, inner_iters=20, socp_fused=mode,
            inner_tol=2e-3, inner_check_every=5,
        )
        dstate = dd.init_dd_state(params, cfg)
        vls = jnp.stack([
            jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
        ])
        states = jax.vmap(lambda v: state.replace(vl=v))(vls)
        dstates = jax.vmap(lambda _: dstate)(vls)

        def one(dst, st):
            return dd.control(params, cfg, f_eq, dst, st, acc_des)

        f, _, stats = jax.jit(jax.vmap(one))(dstates, states)
        return f, stats

    f_ref, st_ref = run("scan")
    f_out, st_out = run("interpret")
    np.testing.assert_allclose(
        np.asarray(f_out), np.asarray(f_ref), rtol=0, atol=5e-4
    )
    assert np.array_equal(np.asarray(st_out.iters), np.asarray(st_ref.iters))
