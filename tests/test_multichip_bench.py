"""Shape/compile correctness of bench.py --multichip on the virtual 8-device
CPU mesh — the driver can run the same command unchanged on a real slice
(VERDICT r3 item 6). Tiny budgets: the property under test is that every
multi-device config builds, shards, compiles, and executes, not throughput."""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_multichip_configs_compile_and_run(capsys):
    results = bench.multichip(
        n_steps=2, n_swarm=16, reps=1, max_iter=3, inner_cadmm=5, inner_dd=5
    )
    assert set(results) == {
        "dd_n16_sharded", "cadmm_n8_sharded", "swarm_scenario_sharded"
    }
    for key, rate in results.items():
        assert np.isfinite(rate) and rate > 0, (key, rate)
    # One JSON line per config on stdout (driver-facing contract).
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert len(lines) == 3
