"""Mesh-sharding tests on the virtual 8-device CPU mesh (conftest.py): the
agent-sharded C-ADMM step must produce the same forces as the single-program
path, and scenario sharding must partition Monte-Carlo batches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.parallel import mesh as mesh_mod
from tpu_aerial_transport.utils import compat


def test_eight_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def _setup(n):
    params, col, state = setup.rqp_setup(n)
    # Small iteration budget: the property under test is sharded ==
    # single-program, which holds at ANY fixed iteration count — running the
    # consensus to tight convergence here only burns CI minutes (these six
    # tests dominated the round-1 suite wall time). Convergence itself is
    # asserted in tests/test_cadmm.py / test_dd_rp.py.
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=8, inner_iters=20, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    return params, col, state, cfg, f_eq


# (4,4) covers one-agent-per-shard, (8,2) covers multi-agent blocks; an
# (8,8) case adds only compile time (~2.5 min per test on the 8-process
# CPU mesh) without new sharding structure.
@pytest.mark.parametrize("n,n_shards", [(4, 4), (8, 2)])
def test_sharded_cadmm_matches_single_program(n, n_shards):
    """Agent-sharded consensus (psum/pmax over the mesh) == vmap-only path."""
    params, col, state, cfg, f_eq = _setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    astate = cadmm.init_cadmm_state(params, cfg)
    # jit both paths: eagerly each consensus step dispatches ~2k one-op
    # programs (measured: ~90 s/test, none persistently cacheable) vs a
    # handful of cached compiles jitted — same numerics, same oracle.
    f_ref, _, stats_ref = jax.jit(
        lambda a, s: cadmm.control(params, cfg, f_eq, a, s, acc_des)
    )(astate, state)

    m = mesh_mod.make_mesh({"agent": n_shards})
    step = jax.jit(mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m))
    f_sh, astate_sh, stats_sh = step(astate, state, acc_des)

    assert f_sh.shape == (n, 3)
    # psum reduction order differs from jnp.mean's; f32 noise compounds over the
    # consensus iterations, so agreement is to ~1e-3 N (forces are ~5 N).
    assert np.abs(np.asarray(f_sh) - np.asarray(f_ref)).max() < 5e-3
    assert abs(int(stats_sh.iters) - int(stats_ref.iters)) <= 1
    # The sharded state keeps the right leading dims for the next step.
    assert astate_sh.f.shape == (n, n, 3)
    # Second step consumes the sharded state (round-trip).
    f2, _, _ = step(astate_sh, state, acc_des)
    assert np.all(np.isfinite(np.asarray(f2)))


# (4,4) covers one-agent-per-shard, (8,2) covers multi-agent blocks; an
# (8,8) case adds only compile time (~2.5 min per test on the 8-process
# CPU mesh) without new sharding structure.
@pytest.mark.parametrize("n,n_shards", [(4, 4), (8, 2)])
def test_sharded_dd_matches_single_program(n, n_shards):
    """Agent-sharded DD (psum price sums + all_gather'd replicated QN dual
    step) == vmap-only path (mirror of the C-ADMM test above)."""
    params, col, state, _, f_eq = _setup(n)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=8, inner_iters=20, prim_inf_tol=1e-3,
    )
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))

    ds = dd.init_dd_state(params, cfg)
    # jit both paths (see the C-ADMM twin above for the why + measurement).
    f_ref, _, stats_ref = jax.jit(
        lambda d, s: dd.control(params, cfg, f_eq, d, s, acc_des)
    )(ds, state)

    m = mesh_mod.make_mesh({"agent": n_shards})
    step = jax.jit(mesh_mod.dd_control_sharded(params, cfg, f_eq, m))
    f_sh, ds_sh, stats_sh = step(ds, state, acc_des)

    assert f_sh.shape == (n, 3)
    assert np.abs(np.asarray(f_sh) - np.asarray(f_ref)).max() < 5e-3
    assert abs(int(stats_sh.iters) - int(stats_ref.iters)) <= 1
    assert ds_sh.f.shape == (n, 3) and ds_sh.lam_M.shape == (n, 3)
    # Second step consumes the sharded state (round-trip).
    f2, _, _ = step(ds_sh, state, acc_des)
    assert np.all(np.isfinite(np.asarray(f2)))


def test_scenario_sharding_placement():
    m = mesh_mod.make_mesh({"scenario": 8})
    batch = jnp.ones((16, 5))
    out = mesh_mod.shard_scenarios(m, batch)
    assert len(out.sharding.device_set) == 8


def test_swarm_payloads_sharded_cadmm():
    """Swarm config (BASELINE config 5 at test scale): independent payload teams
    sharded over the mesh, each running a full C-ADMM consensus step (vmap of
    the distributed controller over the payload axis)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n, n_payloads = 4, 8
    params, col, state0, cfg, f_eq = _setup(n)
    m = mesh_mod.make_mesh({"scenario": 8})
    sharding = NamedSharding(m, P("scenario"))

    xs = jnp.asarray(
        np.random.default_rng(1).normal(size=(n_payloads, 3)), jnp.float32
    )
    states = jax.vmap(lambda x: state0.replace(xl=x))(xs)
    astates = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(n_payloads)
    )
    states = jax.device_put(states, sharding)
    acc = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))
    f, astates2, stats = jax.jit(
        jax.vmap(lambda a, s: cadmm.control(params, cfg, f_eq, a, s, acc))
    )(astates, states)
    assert f.shape == (n_payloads, n, 3)
    assert bool(jnp.all(jnp.isfinite(f)))


def test_scenario_parallel_rollout_smoke():
    """Batch of scenarios through a tiny jitted physics rollout, sharded."""
    from tpu_aerial_transport.models import rqp

    params, col, state0, cfg, f_eq = _setup(4)
    m = mesh_mod.make_mesh({"scenario": 8})

    def one(xl0):
        s = state0.replace(xl=xl0)
        hover = jnp.full((4,), float(params.mT) * rqp.GRAVITY / 4)

        def body(s, _):
            return rqp.integrate(params, s, (hover, jnp.zeros((4, 3))), 1e-3), None

        s, _ = jax.lax.scan(body, s, None, length=50)
        return s.xl

    xs = jnp.asarray(np.random.default_rng(0).normal(size=(16, 3)), jnp.float32)
    xs = mesh_mod.shard_scenarios(m, xs)
    out = jax.jit(jax.vmap(one))(xs)
    assert out.shape == (16, 3)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_swarm_1024_agents_sharded():
    """BASELINE config 5 at full agent count: 128 payloads x 8 quadrotors =
    1024 agents, scenario-sharded over the 8-device mesh, one C-ADMM MPC step
    + physics each (small iteration budget — correctness, not perf; the
    throughput row lives in BASELINE.md via bench.py --sweep)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_aerial_transport.models import rqp

    n, n_payloads = 8, 128
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=3, inner_iters=10,
    )
    f_eq = centralized.equilibrium_forces(params)
    m = mesh_mod.make_mesh({"scenario": 8})

    xs = jnp.asarray(
        np.random.default_rng(2).normal(size=(n_payloads, 3)) * 2.0
        + np.array([0.0, 0.0, 3.0]),
        jnp.float32,
    )
    states = jax.vmap(lambda x: state0.replace(xl=x))(xs)
    astates = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(n_payloads)
    )
    states = jax.device_put(states, NamedSharding(m, P("scenario")))
    acc = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))

    def step(a, s):
        f, a, stats = cadmm.control(params, cfg, f_eq, a, s, acc)
        fz = jnp.sum(f * s.R[..., :, 2], axis=-1)
        s = rqp.integrate(params, s, (fz, jnp.zeros((n, 3))), 1e-3)
        return a, s, stats

    astates2, states2, stats = jax.jit(jax.vmap(step))(astates, states)
    assert states2.xl.shape == (n_payloads, 3)
    assert bool(jnp.all(jnp.isfinite(states2.xl)))
    assert astates2.f.shape == (n_payloads, n, n, 3)  # 1024-agent solver state.
    # Outputs stay sharded over the mesh (no silent gather to one device).
    assert len(states2.xl.sharding.device_set) == 8


def test_2d_mesh_scenario_by_agent_cadmm():
    """2-D mesh {scenario: 2, agent: 4}: Monte-Carlo scenarios data-parallel
    on one axis while every scenario's C-ADMM consensus runs psum/pmax
    collectives over the other — the full SURVEY §2.10 composition in one
    program. Must match the unsharded vmap result."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from tpu_aerial_transport.control.types import SolverStats

    n, n_batch = 8, 4
    params, col, state0, cfg, f_eq = _setup(n)
    m = mesh_mod.make_mesh({"scenario": 2, "agent": 4})
    acc = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))
    plan = cadmm.make_plan(params, cfg)

    xs = jnp.asarray(
        np.random.default_rng(2).normal(size=(n_batch, 3)), jnp.float32
    )
    states = jax.vmap(lambda x: state0.replace(xl=x))(xs)
    astates = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(n_batch)
    )

    f_ref, _, _ = jax.jit(jax.vmap(
        lambda a, s: cadmm.control(params, cfg, f_eq, a, s, acc, plan=plan)
    ))(astates, states)

    admm_spec = cadmm.CADMMState(
        f=P("scenario", "agent"), lam=P("scenario", "agent"),
        f_mean=P("scenario"),
        warm=jax.tree.map(
            lambda _: P("scenario", "agent"), mesh_mod._warm_structure()
        ),
    )
    state_spec = jax.tree.map(lambda _: P("scenario"), states)
    # Spec built by tree.map over a throwaway instance (the
    # __graft_entry__.dryrun_multichip pattern) so EVERY SolverStats leaf —
    # including defaulted fields like the PR-1 fallback_rung, which the
    # inner vmap broadcasts to the local scenario batch like the rest —
    # gets the scenario spec; spelling leaves out by hand silently leaves
    # new defaults as array leaves that shard_map rejects (or, worse, as
    # P() on a batched output, which assembles a wrong-shaped global).
    stats_spec = jax.tree.map(
        lambda _: P("scenario"),
        SolverStats(iters=0, solve_res=0, collision=0, min_env_dist=0,
                    err_seq=0, ok_frac=0),
    )

    @partial(
        compat.shard_map, mesh=m,
        in_specs=(admm_spec, state_spec, (P(), P())),
        out_specs=(P("scenario", "agent"), admm_spec, stats_spec),
        check_vma=False,
    )
    def step(astate, state, acc_des):
        return jax.vmap(
            lambda a, s: cadmm.control(
                params, cfg, f_eq, a, s, acc_des,
                axis_name="agent", plan=plan,
            )
        )(astate, state)

    f_2d, astates_2d, stats = jax.jit(step)(astates, states, acc)
    assert f_2d.shape == (n_batch, n, 3)
    err = float(jnp.abs(f_2d - f_ref).max())
    assert err < 1e-4, f"2-D-mesh forces deviate from vmap path: {err}"
    assert bool(jnp.all(jnp.isfinite(astates_2d.f)))
