"""Property tests for the SO(3) math core (reference: test/utils/test_mathutils.py,
but with asserted tolerances instead of printed averages — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.ops import lie

KEY = jax.random.PRNGKey(0)


def _random_rotations(key, batch):
    w = jax.random.normal(key, (batch, 3))
    return lie.expm_so3(w)


def test_hat_vee_roundtrip():
    v = jax.random.normal(KEY, (17, 3))
    assert jnp.allclose(lie.vee(lie.hat(v)), v)
    # hat(v) x = v cross x
    x = jax.random.normal(jax.random.PRNGKey(1), (17, 3))
    lhs = jnp.einsum("bij,bj->bi", lie.hat(v), x)
    assert jnp.allclose(lhs, jnp.cross(v, x), atol=1e-6)


def test_hat_square_matches_product():
    u = jax.random.normal(KEY, (11, 3))
    v = jax.random.normal(jax.random.PRNGKey(2), (11, 3))
    assert jnp.allclose(lie.hat_square(u, v), lie.hat(u) @ lie.hat(v), atol=1e-5)


def test_expm_orthonormal():
    R = _random_rotations(KEY, 64)
    eye = jnp.broadcast_to(jnp.eye(3), R.shape)
    err = jnp.abs(jnp.swapaxes(R, -1, -2) @ R - eye).max()
    assert err < 1e-5
    det = jnp.linalg.det(R)
    assert jnp.abs(det - 1.0).max() < 1e-5


def test_expm_small_angle_smooth():
    w = jnp.array([[0.0, 0.0, 0.0], [1e-9, 0.0, 0.0], [1e-7, 1e-8, 0.0]])
    R = lie.expm_so3(w)
    assert jnp.all(jnp.isfinite(R))
    assert jnp.allclose(R[0], jnp.eye(3))
    # Gradient must be finite through zero.
    g = jax.grad(lambda w_: lie.expm_so3(w_).sum())(jnp.zeros(3))
    assert jnp.all(jnp.isfinite(g))


def test_expm_matches_scipy():
    from scipy.spatial.transform import Rotation

    w = np.asarray(jax.random.normal(KEY, (32, 3)))
    R_jax = np.asarray(lie.expm_so3(jnp.asarray(w)))
    R_ref = Rotation.from_rotvec(w).as_matrix()
    assert np.abs(R_jax - R_ref).max() < 1e-5


def test_log_exp_roundtrip():
    w = jax.random.normal(KEY, (32, 3)) * 0.9
    w2 = lie.log_so3(lie.expm_so3(w))
    assert jnp.abs(w - w2).max() < 1e-4


def test_polar_project_newton_schulz():
    R = _random_rotations(KEY, 16)
    # Perturb off the manifold (the integrator-drift regime).
    noise = 1e-3 * jax.random.normal(jax.random.PRNGKey(3), R.shape)
    P = lie.polar_project(R + noise)
    eye = jnp.broadcast_to(jnp.eye(3), P.shape)
    assert jnp.abs(jnp.swapaxes(P, -1, -2) @ P - eye).max() < 1e-5
    # Matches the SVD polar factor (the reference's scipy.linalg.polar).
    P_svd = lie.polar_project_svd(R + noise)
    assert jnp.abs(P - P_svd).max() < 1e-4


def test_polar_project_idempotent():
    R = _random_rotations(KEY, 8)
    assert jnp.abs(lie.polar_project(R) - R).max() < 1e-5


def test_rotation_a_to_b():
    key1, key2 = jax.random.split(KEY)
    a = jax.random.normal(key1, (32, 3))
    a = a / jnp.linalg.norm(a, axis=-1, keepdims=True)
    b = jax.random.normal(key2, (32, 3))
    b = b / jnp.linalg.norm(b, axis=-1, keepdims=True)
    R = lie.rotation_a_to_b(a, b)
    assert jnp.abs(jnp.einsum("bij,bj->bi", R, a) - b).max() < 1e-5
    assert jnp.abs(jnp.linalg.det(R) - 1.0).max() < 1e-5
    eye = jnp.broadcast_to(jnp.eye(3), R.shape)
    assert jnp.abs(jnp.swapaxes(R, -1, -2) @ R - eye).max() < 1e-5


def test_rotation_a_to_b_antipodal():
    a = jnp.array([0.0, 0.0, 1.0])
    R = lie.rotation_a_to_b(a, -a)
    assert jnp.abs(R @ a + a).max() < 1e-6
    assert jnp.abs(jnp.linalg.det(R) - 1.0) < 1e-5
    # Antipodal along e1 exercises the second fallback.
    a = jnp.array([1.0, 0.0, 0.0])
    R = lie.rotation_a_to_b(a, -a)
    assert jnp.abs(R @ a + a).max() < 1e-6


def test_rotation_from_z():
    q = lie.random_cone_vector(KEY, jnp.pi / 3, (64,))
    R = lie.rotation_from_z(q)
    assert jnp.abs(R[..., :, 2] - q).max() < 1e-6
    eye = jnp.broadcast_to(jnp.eye(3), R.shape)
    assert jnp.abs(jnp.swapaxes(R, -1, -2) @ R - eye).max() < 2e-5
    # Zero yaw in ZYX convention: R[1, 0] == 0.
    assert jnp.abs(R[..., 1, 0]).max() < 1e-6


def test_random_cone_vector_membership():
    theta = 0.4
    v = lie.random_cone_vector(KEY, theta, (5000,))
    assert jnp.abs(jnp.linalg.norm(v, axis=-1) - 1.0).max() < 1e-5
    angles = jnp.arccos(jnp.clip(v[..., 2], -1, 1))
    assert angles.max() <= theta + 1e-5


@pytest.mark.parametrize("fn", [lie.hat, lie.expm_so3])
def test_jit_and_vmap_compose(fn):
    v = jax.random.normal(KEY, (4, 5, 3))
    out = jax.jit(jax.vmap(jax.vmap(fn)))(v)
    assert out.shape[:2] == (4, 5)
