"""Backend guard (resilience/backend.py): error-taxonomy classification,
backoff policy, circuit-breaker state machine, deadline watchdogs,
process-group kill, the TAT_BACKEND_FAULTS fake backend, and the
end-to-end contract the whole PR exists for — a fault-injected
``bench.py --sweep`` completes with exit 0, every cell tagged with the
rung it actually ran at, a journaled ``backend_event`` trail that
validates against the bumped metrics schema, and bounded wall time."""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.resilience import backend as b

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ----------------------------- taxonomy --------------------------------


def test_classify_r02_tail_is_init_not_dtype():
    """The BENCH_r02 tail contains BOTH convert_element_type and the
    backend-init UNAVAILABLE; the root cause is init failure surfacing
    lazily at first dispatch, so init patterns must win over dtype."""
    tail = (
        "jaxlib.xla_extension.XlaRuntimeError: UNAVAILABLE: "
        "Unable to initialize backend 'tpu': ... (raised while executing "
        "convert_element_type)"
    )
    assert b.classify(tail) == "init_unavailable"


def test_classify_each_kind():
    assert b.classify("watchdog: timed out waiting") == "wedge_timeout"
    assert b.classify("RESOURCE_EXHAUSTED: failed to allocate 8G") == "oom"
    assert b.classify("unsupported element type f64 in op") \
        == "dtype_lowering"
    assert b.classify("Mosaic lowering failed for fusion.3") \
        == "compile_error"
    assert b.classify("INTERNAL: device halt detected") == "device_crash"
    assert b.classify(ValueError("plain code bug")) == "unknown"


def test_classify_lowercase_status_words_are_code_bugs():
    """Regression: device_crash anchors to the XLA/gRPC STATUS forms
    (INTERNAL/ABORTED/DATA_LOSS, case-sensitive) — an ordinary exception
    whose message happens to contain lowercase 'aborted'/'internal' is a
    code bug and must classify unknown (re-raised, never degraded)."""
    assert b.classify(
        ValueError("aborted: plan has internal inconsistency")
    ) == "unknown"


def test_classify_backend_error_keeps_kind():
    e = b.BackendError("oom", "whatever text says timed out")
    assert b.classify(e) == "oom"


def test_classify_unmatched_xla_runtime_error_is_device_crash():
    """The runtime itself raising is a device problem whatever the
    message text says."""
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert b.classify(XlaRuntimeError("gibberish nobody patterned")) \
        == "device_crash"


def test_backend_error_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown BackendError kind"):
        b.BackendError("typo_kind", "detail")


# ------------------------------ backoff --------------------------------


def test_backoff_growth_and_cap():
    p = b.BackoffPolicy(initial_s=10.0, factor=2.0, max_s=35.0, jitter=0.0)
    assert [p.delay(k) for k in range(4)] == [10.0, 20.0, 35.0, 35.0]


def test_backoff_jitter_bounded_and_seeded():
    p = b.BackoffPolicy(initial_s=10.0, factor=2.0, max_s=600.0, jitter=0.2)
    rng = random.Random(0)
    ds = [p.delay(0, rng) for _ in range(100)]
    assert all(8.0 <= d <= 12.0 for d in ds)
    # Seeded rng => deterministic draws (tests can pin the cadence).
    rng2 = random.Random(0)
    assert ds == [p.delay(0, rng2) for _ in range(100)]


# --------------------------- circuit breaker ---------------------------


def _breaker(threshold=3, initial_s=10.0):
    clock = [0.0]
    cb = b.CircuitBreaker(
        failure_threshold=threshold,
        policy=b.BackoffPolicy(initial_s=initial_s, factor=2.0,
                               max_s=600.0, jitter=0.0),
        clock=lambda: clock[0],
    )
    return cb, clock


def test_circuit_opens_after_k_consecutive_failures():
    cb, _ = _breaker(threshold=3)
    cb.record_failure("wedge_timeout")
    cb.record_failure("wedge_timeout")
    assert cb.state == b.CLOSED and cb.allow()
    cb.record_failure("device_crash")
    assert cb.state == b.OPEN and not cb.allow()
    assert cb.cooldown_s == 10.0


def test_circuit_success_resets_consecutive_count():
    cb, _ = _breaker(threshold=2)
    cb.record_failure("oom")
    cb.record_success()
    cb.record_failure("oom")
    assert cb.state == b.CLOSED  # never 2 CONSECUTIVE failures.


def test_circuit_half_open_probe_closes_on_success():
    cb, clock = _breaker(threshold=1, initial_s=10.0)
    cb.record_failure("wedge_timeout")
    assert not cb.allow()
    clock[0] = 10.0
    assert cb.allow() and cb.state == b.HALF_OPEN
    cb.record_success()
    assert cb.state == b.CLOSED and cb.consecutive_failures == 0
    assert [t["to"] for t in cb.transitions] \
        == [b.OPEN, b.HALF_OPEN, b.CLOSED]


def test_circuit_half_open_failure_reopens_with_longer_cooldown():
    cb, clock = _breaker(threshold=1, initial_s=10.0)
    cb.record_failure("wedge_timeout")
    first_cooldown = cb.cooldown_s
    clock[0] = 10.0
    assert cb.allow() and cb.state == b.HALF_OPEN
    cb.record_failure("wedge_timeout")
    assert cb.state == b.OPEN
    assert cb.cooldown_s == 2.0 * first_cooldown  # exponential backoff.
    assert cb.seconds_until_half_open() == pytest.approx(20.0)


def test_circuit_rejects_nonpositive_threshold():
    with pytest.raises(ValueError):
        b.CircuitBreaker(failure_threshold=0)


# --------------------------- deadline watchdog -------------------------


def test_deadline_passes_value_and_forwards_errors():
    assert b.call_with_deadline(lambda: 41 + 1, 5.0) == 42
    with pytest.raises(KeyError):
        b.call_with_deadline(lambda: {}["missing"], 5.0)
    # None / <=0 disables the watchdog entirely (plain call).
    assert b.call_with_deadline(lambda: "plain", None) == "plain"
    assert b.call_with_deadline(lambda: "plain", 0) == "plain"


def test_deadline_converts_wedge_into_structured_timeout():
    t0 = time.monotonic()
    with pytest.raises(b.BackendError) as ei:
        b.call_with_deadline(lambda: time.sleep(5.0), 0.2, label="wedged")
    assert ei.value.kind == "wedge_timeout"
    assert "wedged" in str(ei.value)
    assert time.monotonic() - t0 < 3.0  # the deadline, not the sleep.


# --------------------------- fault injector ----------------------------


def test_fault_injector_parses_directives():
    inj = b.FaultInjector.from_env("init_unavailable, wedge=1.5, crash@3")
    assert inj.init_unavailable and inj.wedge_s == 1.5 and inj.crash_at == 3
    assert inj.active
    assert b.FaultInjector.from_env("crash@mycell").crash_label == "mycell"
    assert not b.FaultInjector.from_env("").active


def test_fault_injector_rejects_unknown_directive():
    """A typo silently disabling fault injection would fake a green
    test — parsing is strict."""
    with pytest.raises(ValueError, match="unknown TAT_BACKEND_FAULTS"):
        b.FaultInjector.from_env("wedg=5")


def test_fault_injector_crash_at_nth_call():
    inj = b.FaultInjector(crash_at=2)
    inj.maybe_fault("a")  # call 1: clean.
    with pytest.raises(RuntimeError, match="INTERNAL: device crashed"):
        inj.maybe_fault("b")
    inj.maybe_fault("c")  # call 3: clean again (one-shot crash).


def test_fault_injector_crash_on_label():
    inj = b.FaultInjector(crash_label="n64")
    inj.maybe_fault("cadmm_n4_single")
    with pytest.raises(RuntimeError, match="device crashed"):
        inj.maybe_fault("cadmm_n64_single")


def test_fault_injector_wedge_raises_structured_timeout():
    inj = b.FaultInjector(wedge_s=0.01)
    with pytest.raises(b.BackendError) as ei:
        inj.maybe_fault("cell")
    assert ei.value.kind == "wedge_timeout"


# ------------------------------- guard ---------------------------------


def _guard(**kw):
    kw.setdefault("deadline_s", 5.0)
    kw.setdefault("primary_rung", b.RUNG_ONCHIP)
    kw.setdefault("faults", b.FaultInjector())
    return b.BackendGuard(**kw)


def test_guard_success_returns_primary_rung():
    g = _guard()
    value, rung = g.run("cell", lambda: 7, fallback_fn=lambda: -1)
    assert (value, rung) == (7, b.RUNG_ONCHIP)
    assert not g.last_fell_back and g.events == []


def test_guard_classified_failure_falls_back_and_records():
    g = _guard()

    def dying():
        raise RuntimeError("INTERNAL: device crashed mid-execution")

    value, rung = g.run("cell", dying, fallback_fn=lambda: 42)
    assert (value, rung) == (42, b.RUNG_CPU)
    assert g.last_fell_back
    kinds = [e["kind"] for e in g.events]
    assert "device_crash" in kinds
    assert g.breaker.consecutive_failures == 1


def test_guard_program_bug_kinds_do_not_trip_the_breaker():
    """compile_error / dtype_lowering indict the PROGRAM, not the chip:
    the cell degrades but the circuit must not open (three Pallas compile
    failures on a healthy chip must not route the sweep to CPU)."""
    g = _guard(breaker=b.CircuitBreaker(failure_threshold=1))

    def bad_program():
        raise RuntimeError("Mosaic lowering failed for fused op")

    value, rung = g.run("cell", bad_program, fallback_fn=lambda: 1)
    assert rung == b.RUNG_CPU
    assert g.breaker.state == b.CLOSED
    assert g.breaker.consecutive_failures == 0


def test_guard_unknown_error_reraises():
    """An unclassified failure is a CODE bug — degrading to CPU would
    only reproduce it more slowly."""
    g = _guard()
    with pytest.raises(ValueError, match="plain code bug"):
        g.run("cell", lambda: (_ for _ in ()).throw(
            ValueError("plain code bug")), fallback_fn=lambda: 0)
    assert g.events == []


def test_guard_open_circuit_routes_to_cpu_without_touching_primary():
    clock = [0.0]
    g = _guard(
        breaker=b.CircuitBreaker(
            failure_threshold=1,
            policy=b.BackoffPolicy(initial_s=100.0, jitter=0.0),
            clock=lambda: clock[0],
        ),
    )

    def dying():
        raise RuntimeError("INTERNAL: aborted")

    g.run("c0", dying, fallback_fn=lambda: 0)
    assert g.breaker.state == b.OPEN

    touched = []

    def primary():
        touched.append(1)
        return 1

    value, rung = g.run("c1", primary, fallback_fn=lambda: 2)
    assert (value, rung) == (2, b.RUNG_CPU) and not touched
    assert any(e["kind"] == "circuit_routed_cpu" for e in g.events)
    assert any(e["kind"] == "circuit_open" for e in g.events)
    # Cooldown elapsed: the next run() is the half-open probe and a
    # success closes the circuit again — journaled as transitions.
    clock[0] = 100.0
    value, rung = g.run("c2", primary, fallback_fn=lambda: 2)
    assert (value, rung) == (1, b.RUNG_ONCHIP) and touched
    assert g.breaker.state == b.CLOSED
    kinds = [e["kind"] for e in g.events]
    assert "circuit_half_open" in kinds and "circuit_closed" in kinds


def test_guard_wedge_hits_deadline_then_falls_back_bounded():
    g = _guard(deadline_s=0.2, faults=b.FaultInjector(wedge_s=30.0))
    t0 = time.monotonic()
    value, rung = g.run("cell", lambda: "never", fallback_fn=lambda: "cpu")
    assert (value, rung) == ("cpu", b.RUNG_CPU)
    assert time.monotonic() - t0 < 5.0  # deadline-bounded, not wedge-bound.
    assert [e["kind"] for e in g.events
            if not e["kind"].startswith("circuit_")] == ["wedge_timeout"]


def test_guard_rung_resolution_is_deadline_bounded():
    """Regression: resolving the primary rung touches
    jax.default_backend() — the first in-process backend init, which can
    wedge exactly like the work. It must happen INSIDE run()'s watchdog:
    with no explicit primary_rung and a wedging primary, the guard still
    returns within the deadline and tags the error rung 'unresolved'."""
    g = b.BackendGuard(deadline_s=0.2,
                       faults=b.FaultInjector(wedge_s=30.0))
    assert g._primary_rung is None
    t0 = time.monotonic()
    value, rung = g.run("cell", lambda: "never", fallback_fn=lambda: "cpu")
    assert (value, rung) == ("cpu", b.RUNG_CPU)
    assert time.monotonic() - t0 < 5.0
    assert g.events[0]["rung"] == "unresolved"
    # On a healthy backend the success path resolves the real rung
    # (inside the watchdog) — cpu-tagged on this CPU-only host.
    g2 = b.BackendGuard(deadline_s=30.0, faults=b.FaultInjector())
    value, rung = g2.run("cell", lambda: 1)
    assert (value, rung) == (1, b.RUNG_CPU)


def test_guard_no_fallback_raises_structured_backend_error():
    g = _guard()
    with pytest.raises(b.BackendError) as ei:
        g.run("cell", lambda: (_ for _ in ()).throw(
            RuntimeError("INTERNAL: aborted")))
    assert ei.value.kind == "device_crash"


def test_guard_emits_to_metrics_writer(tmp_path):
    path = str(tmp_path / "g.metrics.jsonl")
    g = _guard(metrics=export_mod.MetricsWriter(path))
    g.run("cell", lambda: (_ for _ in ()).throw(
        RuntimeError("INTERNAL: aborted")), fallback_fn=lambda: 0)
    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    be = [e for e in events if e["event"] == "backend_event"]
    assert be and be[0]["kind"] == "device_crash" \
        and be[0]["label"] == "cell"


def test_default_deadline_env_parsing():
    assert b.default_deadline_s({}) == b.DEFAULT_DEADLINE_S
    assert b.default_deadline_s({b.DEADLINE_ENV: "12.5"}) == 12.5
    with pytest.raises(ValueError, match="not a number"):
        b.default_deadline_s({b.DEADLINE_ENV: "fast"})


# --------------------------- process-group kill ------------------------


def test_run_group_kills_whole_process_group_on_timeout(tmp_path):
    """The r03-r05 orphan bug: a wedged child's OWN subprocess (the probe
    it spawned, a runtime helper holding the chip) must die with it —
    ``subprocess.run(timeout=)`` only kills the direct child."""
    pid_file = str(tmp_path / "grandchild.pid")
    child_code = (
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c',"
        " 'import time; time.sleep(60)'])\n"
        f"open({pid_file!r}, 'w').write(str(p.pid))\n"
        "time.sleep(60)\n"
    )
    with pytest.raises(subprocess.TimeoutExpired):
        b.run_group([sys.executable, "-c", child_code], timeout_s=10.0)
    gpid = int(open(pid_file).read())
    # SIGKILL is asynchronous; give the reaper a moment.
    for _ in range(50):
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        os.kill(gpid, signal.SIGKILL)  # don't leak it from the test.
        pytest.fail("grandchild survived the group kill (orphaned)")


def test_probe_fault_injected_init_unavailable_fails_fast():
    t0 = time.monotonic()
    ok, detail = b.probe_subprocess(
        timeout_s=60.0,
        env={**os.environ, b.FAULTS_ENV: "init_unavailable"},
    )
    assert not ok and "Unable to initialize backend" in detail
    assert time.monotonic() - t0 < 2.0  # in-process, no subprocess spawned.


def test_probe_real_cpu_backend_warms_first_dispatch():
    """The probe must run a REAL device computation (matmul + an explicit
    convert_element_type round-trip — the r02 op class), not just
    enumerate devices: on this host it passes and reports the cpu
    platform."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop(b.FAULTS_ENV, None)
    ok, detail = b.probe_subprocess(timeout_s=120.0, env=env)
    assert ok, detail
    assert detail == "cpu"


# ----------------------- end-to-end: fault-injected sweep --------------


def test_sweep_survives_crash_and_wedge_with_tagged_cells(tmp_path):
    """The acceptance contract: with the fake crashing+wedging backend
    injected, ``bench.py --sweep`` exits 0, the sweep CONTINUES past the
    faulted cells, every cell records the rung it actually ran at, the
    ``backend_event`` trail validates against the bumped schema, and wall
    time is bounded by the watchdog (the wedge costs one deadline, not a
    hung round)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Two cheap cells: the crash fires on the first guarded call, the
        # wedge applies to the second (crash wins before the sleep on
        # call 1), so BOTH failure modes degrade in one sweep.
        "TAT_SWEEP_CELLS": r"^centralized_n4_single$|^cadmm_n4_single$",
        "TAT_BACKEND_FAULTS": "crash@1,wedge=30",
        "TAT_BACKEND_DEADLINE_S": "0.5",
    })
    # A prior full record: the cell-filtered run must CARRY its
    # non-matching cells forward (stamped in _meta), not replace hours of
    # measurements with a two-cell file.
    (tmp_path / "BENCH_SWEEP.json").write_text(json.dumps({
        "_meta": {"git_head": "feedf00d"},
        "legacy_cell": {"mpc_steps_per_sec": 123.0},
    }))
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--sweep"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=540,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    # Bounded wall time: the wedge costs ONE 0.5 s deadline (not 30 s of
    # sleep, not a hung round); the rest is probe + two CPU measures.
    assert wall < 300, f"sweep took {wall:.0f}s — watchdog not bounding"

    results = json.loads((tmp_path / "BENCH_SWEEP.json").read_text())
    cells = {k: v for k, v in results.items() if not k.startswith("_")}
    assert set(cells) == {"centralized_n4_single", "cadmm_n4_single",
                          "legacy_cell"}
    assert cells.pop("legacy_cell") == {"mpc_steps_per_sec": 123.0}
    assert results["_meta"]["carried_cells"] == ["legacy_cell"]
    assert results["_meta"]["carried_from_head"] == "feedf00d"
    for key, value in cells.items():
        assert value.get("rung") == b.RUNG_CPU, (key, value)
        assert "error" not in value

    metrics_path = tmp_path / "artifacts" / "bench_sweep.metrics.jsonl"
    assert export_mod.validate_file(str(metrics_path)) == []
    events = export_mod.read_events(str(metrics_path))
    be = [e for e in events if e["event"] == "backend_event"]
    assert sorted(e["kind"] for e in be) \
        == ["device_crash", "wedge_timeout"]
    # Stamped at the writer's CURRENT schema (>= 2, the version that
    # introduced backend_event; later additive bumps re-stamp).
    assert all(e["schema"] == export_mod.SCHEMA_VERSION for e in be)
    # The resumable sweep journal (which carried the same backend_event
    # trail mid-run) is cleaned up on success — the metrics file is the
    # durable record.
    assert not (tmp_path / "BENCH_SWEEP_JOURNAL.jsonl").exists()
    assert not (tmp_path / "BENCH_SWEEP_PARTIAL.json").exists()

    # run_health renders the backend-health table from the trail.
    health = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_health.py"),
         str(metrics_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert health.returncode == 0, health.stderr
    assert "backend health" in health.stdout
    assert "cpu-tagged" in health.stdout


@pytest.mark.slow
def test_ring_ab_and_donate_cells_survive_injected_fault(tmp_path):
    """ISSUE 7 acceptance: the consensus-exchange A/B cells and the
    donated-resume A/B cell ride the same guard contract — with a crash
    injected on the first guarded call, ``bench.py --sweep`` still exits
    0, the faulted sharded-ring cell re-runs on the tagged CPU rung, the
    donate cell completes, and the backend_event trail validates.
    (TAT_SWEEP_SHARDED_N=4 shrinks the sharded cells to a CI-sized twin;
    cell keys carry the actual n.)"""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TAT_SWEEP_SHARDED_N": "4",
        "TAT_SWEEP_CELLS": r"^cadmm_n4_sharded_ring$|^chunked_resume_donate_ab$",
        "TAT_BACKEND_FAULTS": "crash@1",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--sweep"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]

    results = json.loads((tmp_path / "BENCH_SWEEP.json").read_text())
    cells = {k: v for k, v in results.items() if not k.startswith("_")}
    assert set(cells) == {"cadmm_n4_sharded_ring", "chunked_resume_donate_ab"}
    ring_cell = cells["cadmm_n4_sharded_ring"]
    assert "error" not in ring_cell
    assert ring_cell["rung"] == b.RUNG_CPU
    assert ring_cell["impl"] == "ring"
    assert ring_cell["mpc_steps_per_sec"] > 0
    donate = cells["chunked_resume_donate_ab"]
    assert "error" not in donate
    assert {"donated_ms_per_step", "undonated_ms_per_step",
            "donated_bitexact_vs_undonated",
            "donated_replay_bitexact"} <= set(donate)

    metrics_path = tmp_path / "artifacts" / "bench_sweep.metrics.jsonl"
    assert export_mod.validate_file(str(metrics_path)) == []
    events = export_mod.read_events(str(metrics_path))
    be = [e for e in events if e["event"] == "backend_event"]
    assert [e["kind"] for e in be] == ["device_crash"]

    # run_health renders the per-unit rungs table; the faulted ring
    # cell must land on the tagged CPU rung. Match head and tail of the
    # row rather than the full column list so added middle columns
    # (solve impl, effort, iters, env query, ...) don't re-break this.
    health = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_health.py"),
         str(metrics_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert health.returncode == 0, health.stderr
    assert "exchange impl" in health.stdout
    ring_row = next(
        (ln for ln in health.stdout.splitlines()
         if ln.startswith("| cadmm_n4_sharded_ring | ring | ")),
        None)
    assert ring_row is not None, health.stdout
    assert ring_row.endswith("| cpu-tagged |"), ring_row
