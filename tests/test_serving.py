"""Scenario-serving tier (tpu_aerial_transport/serving/): admission
control rejects with structured reasons (never an exception in the
server loop), SLO accounting classifies deadline misses, continuous
batching is composition-independent (a request's result is bitwise
identical whether it runs alone, in a busy mixed batch, or joins late at
a chunk boundary), preemption + resume reproduces the uninterrupted
stream bit-exactly, and the bundled path serves with zero in-process
compiles (slow e2e — the whole-process counter proof of
tests/test_aot.py at serving scale)."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.serving import batcher, queue as queue_mod
from tpu_aerial_transport.serving import server as server_mod
from tpu_aerial_transport.serving.queue import ScenarioRequest

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeInterrupt:
    triggered = None


@pytest.fixture(scope="session")
def cadmm_family():
    """ONE family instance per session so its batched chunk compiles once
    across every jit-path test."""
    return batcher.make_family("cadmm4")


def _mk_server(fam, tmp_path=None, **kw):
    kw.setdefault("families", [fam])
    kw.setdefault("buckets", (4, 8))
    if tmp_path is not None:
        kw.setdefault("metrics", str(tmp_path / "serving.metrics.jsonl"))
    return server_mod.ScenarioServer(**kw)


def _drain(srv):
    while srv.pump():
        pass


def _req(i, horizon=4, family="cadmm4", **kw):
    return ScenarioRequest(family=family, horizon=horizon,
                           x0=(0.3 * i, 0.1, 1.0),
                           request_id=f"t{i:03d}", **kw)


# ----------------------------------------------------------------------
# Admission control (no device work — queue only).
# ----------------------------------------------------------------------

def _stub_queue(tmp_path, capacity=2):
    path = str(tmp_path / "adm.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path)
    q = queue_mod.AdmissionQueue(
        lambda fam: 2 if fam == "known" else None,
        capacity=capacity,
        emit=lambda **kw: metrics.emit("serving_event", **kw),
    )
    return q, path


def test_admission_rejections_structured(tmp_path):
    """Every rejection path resolves the ticket with a structured reason
    and a schema-valid serving_event — no exception escapes."""
    q, path = _stub_queue(tmp_path, capacity=2)

    t = q.submit(ScenarioRequest(family="nope", horizon=4))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_NO_COVERAGE)
    t = q.submit(ScenarioRequest(family="known", horizon=3))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_BAD_HORIZON)
    t = q.submit(ScenarioRequest(family="known", horizon=4,
                                 deadline_s=-1.0))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_DEADLINE_SPENT)
    assert q.submit(ScenarioRequest(family="known", horizon=4)).status \
        == queue_mod.PENDING
    assert q.submit(ScenarioRequest(family="known", horizon=4)).status \
        == queue_mod.PENDING
    t = q.submit(ScenarioRequest(family="known", horizon=4))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_QUEUE_FULL)

    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    rejected = [e for e in events if e.get("kind") == "rejected"]
    assert sorted(e["reason"] for e in rejected) == sorted([
        queue_mod.REASON_NO_COVERAGE, queue_mod.REASON_BAD_HORIZON,
        queue_mod.REASON_DEADLINE_SPENT, queue_mod.REASON_QUEUE_FULL,
    ])


def test_deadline_expires_in_queue(tmp_path):
    """A queued request whose deadline passes before admission resolves
    deadline_missed, classified in_queue."""
    clock = [0.0]
    path = str(tmp_path / "dl.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path)
    q = queue_mod.AdmissionQueue(
        lambda fam: 2, capacity=8, clock=lambda: clock[0],
        emit=lambda **kw: metrics.emit("serving_event", **kw),
    )
    t = q.submit(ScenarioRequest(family="f", horizon=4, deadline_s=5.0))
    assert t.status == queue_mod.PENDING
    clock[0] = 4.0
    assert q.expire_deadlines() == []
    clock[0] = 6.0
    missed = q.expire_deadlines()
    assert missed == [t]
    assert t.status == queue_mod.DEADLINE_MISSED
    assert t.slo.missed == queue_mod.MISSED_IN_QUEUE
    assert q.depth() == 0
    assert export_mod.validate_file(path) == []


def test_server_submit_never_raises(cadmm_family, tmp_path):
    """Rejections through the full server (unknown family / bad horizon)
    come back as resolved tickets, not exceptions."""
    srv = _mk_server(cadmm_family, tmp_path)
    bad = srv.submit(ScenarioRequest(family="martian", horizon=4))
    assert (bad.status, bad.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_NO_COVERAGE)
    odd = srv.submit(ScenarioRequest(family="cadmm4", horizon=3))
    assert (odd.status, odd.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_BAD_HORIZON)
    assert not srv.has_work()


# ----------------------------------------------------------------------
# Continuous batching (device work — shared compiled family).
# ----------------------------------------------------------------------

def test_composition_independent_results_and_late_join(
        cadmm_family, tmp_path):
    """THE serving-tier correctness claim: a request's result does not
    depend on its batch composition. The same request served (a) alone
    (filler-padded small bucket), (b) in a busy batch, and (c) as a LATE
    arrival joining a running batch at a chunk boundary produces bitwise
    identical final states."""
    probe = ScenarioRequest(family="cadmm4", horizon=4, x0=(1.2, -0.4, 0.8),
                            request_id="probe_a")

    srv_alone = _mk_server(cadmm_family)
    t_alone = srv_alone.submit(probe)
    _drain(srv_alone)
    assert t_alone.status == queue_mod.COMPLETED

    srv_busy = _mk_server(
        cadmm_family, tmp_path,
        metrics=str(tmp_path / "busy.metrics.jsonl"),
    )
    tickets = [srv_busy.submit(_req(i, horizon=(4 if i % 2 else 8)))
               for i in range(6)]
    t_busy = srv_busy.submit(ScenarioRequest(
        family="cadmm4", horizon=4, x0=(1.2, -0.4, 0.8),
        request_id="probe_b",
    ))
    srv_busy.pump()  # chunk 1 in flight batch.
    late = srv_busy.submit(ScenarioRequest(
        family="cadmm4", horizon=4, x0=(1.2, -0.4, 0.8),
        request_id="probe_late",
    ))
    launched_batch = t_busy.batch_id
    _drain(srv_busy)

    for t in tickets + [t_busy, late]:
        assert t.status == queue_mod.COMPLETED, t
    # The late arrival JOINED the running batch at a boundary — same
    # batch, admitted after the first chunk launched.
    assert late.batch_id == launched_batch
    assert late.slo.t_admit > t_busy.slo.t_launch

    leaves_a = jax.tree.leaves(t_alone.result)
    for other in (t_busy, late):
        leaves_o = jax.tree.leaves(other.result)
        assert len(leaves_a) == len(leaves_o)
        for x, y in zip(leaves_a, leaves_o):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    assert export_mod.validate_file(
        str(tmp_path / "busy.metrics.jsonl")) == []
    stats = srv_busy.stats()
    assert stats["completed"] == 8
    assert stats["mean_occupancy"] is not None
    assert stats["scenario_steps"] == sum(
        t.request.horizon for t in tickets + [t_busy, late]
    )


def test_deadline_missed_in_flight(cadmm_family, tmp_path):
    """A request admitted in time but finishing after its deadline
    resolves deadline_missed classified in_flight (result attached — it
    finished, just late)."""
    now = [0.0]
    srv = _mk_server(cadmm_family, tmp_path, clock=lambda: now[0])
    t = srv.submit(ScenarioRequest(family="cadmm4", horizon=4,
                                   deadline_s=5.0))
    now[0] = 1.0
    srv.pump()  # admitted + chunk 1 of 2 — still inside the deadline.
    assert t.status == queue_mod.PENDING
    now[0] = 10.0  # deadline passes while the request is IN FLIGHT.
    _drain(srv)
    assert t.status == queue_mod.DEADLINE_MISSED
    assert t.slo.missed == queue_mod.MISSED_IN_FLIGHT
    assert t.result is not None
    events = export_mod.read_events(
        str(tmp_path / "serving.metrics.jsonl"))
    miss = [e for e in events if e.get("kind") == "deadline_missed"]
    assert len(miss) == 1 and miss[0]["missed"] == queue_mod.MISSED_IN_FLIGHT


def test_preempt_resume_bit_identity(cadmm_family, tmp_path):
    """SIGTERM semantics in-process: preemption stops at the chunk
    boundary, the journal + snapshots restore the remainder, and the
    merged results are bitwise identical to an uninterrupted run."""
    def stream():
        return [_req(i, horizon=6) for i in range(6)]

    ref_srv = _mk_server(cadmm_family)
    ref_tickets = [ref_srv.submit(r) for r in stream()]
    _drain(ref_srv)
    ref = {t.request.request_id: t.result for t in ref_tickets}
    assert all(t.status == queue_mod.COMPLETED for t in ref_tickets)

    run_dir = str(tmp_path / "run")
    fi = FakeInterrupt()
    srv1 = _mk_server(cadmm_family, run_dir=run_dir, interrupt=fi)
    t1 = [srv1.submit(r) for r in stream()]
    srv1.pump()
    fi.triggered = "SIGTERM"
    assert srv1.pump() is False
    assert srv1.preempted
    done1 = {t.request.request_id: t.result for t in t1
             if t.status == queue_mod.COMPLETED}

    srv2 = server_mod.ScenarioServer.resume(
        run_dir, families=[cadmm_family], buckets=(4, 8))
    _drain(srv2)
    done2 = {rid: t.result for rid, t in srv2.tickets.items()
             if t.status == queue_mod.COMPLETED}

    merged = {**done1, **done2}
    assert set(merged) == set(ref)
    for rid in ref:
        for x, y in zip(jax.tree.leaves(ref[rid]),
                        jax.tree.leaves(merged[rid])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_resume_snapshot_corruption_falls_back_to_replay(
        cadmm_family, tmp_path):
    """A bitrotted boundary snapshot must not kill resume: the affected
    requests replay from their specs (bit-identical by determinism)."""
    run_dir = str(tmp_path / "run")
    fi = FakeInterrupt()
    srv1 = _mk_server(cadmm_family, run_dir=run_dir, interrupt=fi)
    t1 = [srv1.submit(_req(i, horizon=6)) for i in range(3)]
    srv1.pump()
    fi.triggered = "SIGTERM"
    srv1.pump()
    del t1
    for name in os.listdir(run_dir):
        if name.endswith(".ckpt"):
            path = os.path.join(run_dir, name)
            with open(path, "r+b") as fh:
                first = fh.read(1)
                fh.seek(0)
                fh.write(bytes([first[0] ^ 0xFF]))
    srv2 = server_mod.ScenarioServer.resume(
        run_dir, families=[cadmm_family], buckets=(4, 8))
    _drain(srv2)
    assert len(srv2.tickets) == 3
    assert all(t.status == queue_mod.COMPLETED
               for t in srv2.tickets.values())


def test_batch_id_reservation_monotonic():
    """resume() reserves journaled batch ids so post-resume launches
    cannot collide snapshot prefixes/journal identities; the allocator
    never moves backward (in-process resumes must not reuse ids
    either)."""
    a = batcher._alloc_batch_id()
    batcher.reserve_batch_ids(a + 10)
    assert batcher._alloc_batch_id() == a + 10
    batcher.reserve_batch_ids(0)  # never backward.
    assert batcher._alloc_batch_id() == a + 11


# ----------------------------------------------------------------------
# The serve ladder integration.
# ----------------------------------------------------------------------

def test_serve_entry_prejitted_fallback_no_retrace(cadmm_family):
    """serve_entry with a PRE-JITTED fallback reuses its jit cache across
    serves — a serving replica must not retrace per request (the PR-8
    serve_entry wrapped plain callables in a fresh jax.jit per call)."""
    from tpu_aerial_transport.aot import loader as loader_mod

    fam = cadmm_family
    jitted = fam.batched_jit

    def args():
        carry = jax.tree.map(
            lambda x: np.stack([np.asarray(x)] * 4),
            fam.template_carry_host(),
        )
        return (carry, np.int32(0))

    loader_mod.serve_entry(None, "warm", args(), jit_fallback=jitted)
    before = jitted._cache_size()
    for _ in range(3):
        _, rung = loader_mod.serve_entry(
            None, "again", args(), jit_fallback=jitted)
    assert jitted._cache_size() == before
    assert rung in (loader_mod.RUNG_JIT_CACHED, loader_mod.RUNG_JIT_COLD)


@pytest.fixture(scope="session")
def serving_bundle_dir(tmp_path_factory):
    """A real CPU bundle of the canonical cadmm serving chunk (default
    bucket only — the slow e2e builds the multi-bucket one)."""
    from tpu_aerial_transport.aot import bundle as bundle_mod

    out = str(tmp_path_factory.mktemp("serving_aot") / "cpu")
    bundle_mod.build_bundle(
        out, platform="cpu", names=["serving.batcher:serving_chunk"],
    )
    return out


def test_bundle_sample_template_matches_family(
        serving_bundle_dir, cadmm_family):
    """DRIFT GUARD for the zero-compile path: the template carry a
    bundled server reconstructs from args_sample must be bitwise the
    jnp-built family template — otherwise bundled and jit replicas would
    serve different trajectories for the same request."""
    from tpu_aerial_transport.aot import loader as loader_mod

    b = loader_mod.load_bundle(serving_bundle_dir)
    sample = b.sample_args("serving.batcher:serving_chunk")
    lane0 = jax.tree.map(lambda x: np.asarray(x)[0], sample[0])
    built = cadmm_family.template_carry_host()
    la, lb = jax.tree.leaves(lane0), jax.tree.leaves(built)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.asarray(x).dtype == np.asarray(y).dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_bundled_server_exec_rung_parity(
        serving_bundle_dir, cadmm_family, tmp_path):
    """A require_bundle server serves the whole stream on the exec rung
    with results bitwise equal to the jit-path server."""
    reqs = [_req(i, horizon=4) for i in range(3)]

    srv_jit = _mk_server(cadmm_family)
    jit_tix = [srv_jit.submit(r) for r in reqs]
    _drain(srv_jit)

    metrics = str(tmp_path / "bundled.metrics.jsonl")
    srv_b = server_mod.ScenarioServer(
        families=["cadmm4"], bundle=serving_bundle_dir,
        require_bundle=True, metrics=metrics,
    )
    # Coverage comes from the bundle: the default variant's bucket.
    b_tix = [srv_b.submit(ScenarioRequest(
        family="cadmm4", horizon=r.horizon, x0=r.x0,
        request_id=r.request_id + "_b")) for r in reqs]
    _drain(srv_b)

    events = export_mod.read_events(metrics)
    serves = [e for e in events if e.get("event") == "aot_serve"]
    assert serves and all(e["rung"] == "bundle_exec" for e in serves)
    for tj, tb in zip(jit_tix, b_tix):
        assert tj.status == tb.status == queue_mod.COMPLETED
        for x, y in zip(jax.tree.leaves(tj.result),
                        jax.tree.leaves(tb.result)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_bundle_batch_buckets_listing(serving_bundle_dir):
    from tpu_aerial_transport.aot import loader as loader_mod

    b = loader_mod.load_bundle(serving_bundle_dir)
    assert b.batch_buckets("serving.batcher:serving_chunk") == [
        batcher.DEFAULT_BUCKETS[0]
    ]


def test_require_bundle_rejects_uncovered_family(serving_bundle_dir):
    """Strict bundled admission: a family the bundle does not cover
    rejects with no_bucket_coverage instead of silently compiling."""
    srv = server_mod.ScenarioServer(
        families=["cadmm4", "centralized4"], bundle=serving_bundle_dir,
        require_bundle=True,
    )
    t = srv.submit(ScenarioRequest(family="centralized4", horizon=4))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_NO_COVERAGE)


# ----------------------------------------------------------------------
# Schema + run_health.
# ----------------------------------------------------------------------

def test_serving_event_schema_v4(tmp_path):
    path = str(tmp_path / "v4.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    ev = w.emit("serving_event", kind="completed", request_id="r0",
                slo={"latency_s": 0.5})
    assert ev["schema"] == export_mod.SCHEMA_VERSION >= 4
    assert export_mod.validate_file(path) == []
    # Stamped v3 it is invalid: the v3 reader contract never defined it.
    export_mod.jsonl_append(path, {
        "schema": 3, "event": "serving_event", "ts": 0.0, "kind": "x",
    })
    errs = export_mod.validate_file(path)
    assert len(errs) == 1 and "requires schema >= 4" in errs[0]
    # Missing the kind field is invalid.
    export_mod.jsonl_append(path, {
        "schema": 4, "event": "serving_event", "ts": 0.0,
    })
    assert any("missing fields" in e for e in export_mod.validate_file(path))


def test_run_health_serving_section(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "rh.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("serving_event", kind="batch_launch", batch_id=0,
           family="cadmm4", bucket=8, lanes=5)
    for i in range(4):
        w.emit("serving_event", kind="completed", request_id=f"r{i}",
               slo={"latency_s": 0.1 * (i + 1),
                    "admit_to_complete_s": 0.05 * (i + 1)})
    w.emit("serving_event", kind="rejected", request_id="r9",
           reason=queue_mod.REASON_QUEUE_FULL)
    w.emit("serving_event", kind="deadline_missed", request_id="r8",
           missed=queue_mod.MISSED_IN_QUEUE)
    w.emit("serving_event", kind="batch_boundary", batch_id=0,
           family="cadmm4", chunk=1, occupancy=0.75, rung="bundle_exec")
    w.emit("serving_event", kind="batch_boundary", batch_id=0,
           family="cadmm4", chunk=2, occupancy=0.25, rung="bundle_exec")

    s = run_health.summarize(export_mod.read_events(path))
    sv = s["serving"]
    assert sv["completed"] == 4
    assert sv["rejections"] == {queue_mod.REASON_QUEUE_FULL: 1}
    assert sv["deadline_misses"] == {queue_mod.MISSED_IN_QUEUE: 1}
    assert sv["mean_occupancy"] == pytest.approx(0.5)
    assert sv["latency_s"]["p50"] == pytest.approx(0.3)  # nearest-rank.
    assert sv["batches"][0]["bucket"] == 8
    assert sv["batches"][0]["rungs"] == {"bundle_exec": 2}


# ----------------------------------------------------------------------
# ISSUE 18: device-resident lane surgery, double-buffered dispatch,
# content-addressed result cache.
# ----------------------------------------------------------------------

def _assert_same_leaves(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_device_surgery_composition_independent(cadmm_family):
    """The composition-independence claim through the DEVICE surgery
    path: alone / busy / late-join all produce states bitwise equal to
    the host-surgery reference (jnp.where selects copy bits — the knob
    may change wall clock, never values)."""
    probe = dict(family="cadmm4", horizon=4, x0=(1.2, -0.4, 0.8))

    ref_srv = _mk_server(cadmm_family)  # host default.
    t_ref = ref_srv.submit(ScenarioRequest(request_id="ref", **probe))
    _drain(ref_srv)
    assert ref_srv.stats()["surgery"] == "host"

    srv = _mk_server(cadmm_family, surgery="device")
    assert srv.stats()["surgery"] == "device"
    t_alone = srv.submit(ScenarioRequest(request_id="d_alone", **probe))
    _drain(srv)

    busy = _mk_server(cadmm_family, surgery="device")
    tickets = [busy.submit(_req(i, horizon=(4 if i % 2 else 8)))
               for i in range(6)]
    t_busy = busy.submit(ScenarioRequest(request_id="d_busy", **probe))
    busy.pump()  # chunk 1 in flight.
    t_late = busy.submit(ScenarioRequest(request_id="d_late", **probe))
    launched_batch = t_busy.batch_id
    _drain(busy)

    for t in tickets + [t_alone, t_busy, t_late]:
        assert t.status == queue_mod.COMPLETED, t
    assert t_late.batch_id == launched_batch  # joined at a boundary.
    for t in (t_alone, t_busy, t_late):
        _assert_same_leaves(t_ref.result, t.result)


def test_pipelined_dispatch_bit_identity(cadmm_family):
    """sync vs pipelined dispatch (and host vs device surgery) over a
    mixed-horizon stream with a mid-stream late join: identical results.
    Pipelined speculatively launches chunk k+1 before harvesting chunk k
    — legal because the boundary plan is admission-counter arithmetic,
    data-independent of chunk k's values."""
    def serve(**kw):
        srv = _mk_server(cadmm_family, **kw)
        tickets = [srv.submit(_req(i, horizon=(8 if i % 3 else 4)))
                   for i in range(5)]
        srv.pump()
        tickets.append(srv.submit(_req(99, horizon=4)))  # late join.
        _drain(srv)
        assert all(t.status == queue_mod.COMPLETED for t in tickets)
        return srv, {t.request.request_id: t.result for t in tickets}

    _, ref = serve()  # host + sync (the pre-knob path).
    srv_p, got_p = serve(dispatch="pipelined")
    assert (srv_p.stats()["surgery"], srv_p.stats()["dispatch"]) == \
        ("device", "pipelined")
    _, got_s = serve(surgery="device", dispatch="sync")
    for got in (got_p, got_s):
        assert set(got) == set(ref)
        for rid in ref:
            _assert_same_leaves(ref[rid], got[rid])


@pytest.mark.parametrize("mode", [
    dict(surgery="device"), dict(dispatch="pipelined"),
])
def test_device_preempt_resume_bit_identity(cadmm_family, tmp_path, mode):
    """SIGTERM + resume through the device-surgery (and pipelined) path:
    preemption lands at the chunk boundary with the journaled lane map
    matching the published carry, and the merged results are bitwise the
    uninterrupted host run's."""
    def stream():
        return [_req(i, horizon=6) for i in range(6)]

    ref_srv = _mk_server(cadmm_family)
    ref = {t.request.request_id: t for t in
           [ref_srv.submit(r) for r in stream()]}
    _drain(ref_srv)

    run_dir = str(tmp_path / "run")
    fi = FakeInterrupt()
    srv1 = _mk_server(cadmm_family, run_dir=run_dir, interrupt=fi, **mode)
    t1 = [srv1.submit(r) for r in stream()]
    srv1.pump()
    fi.triggered = "SIGTERM"
    assert srv1.pump() is False
    assert srv1.preempted
    done1 = {t.request.request_id: t.result for t in t1
             if t.status == queue_mod.COMPLETED}

    srv2 = server_mod.ScenarioServer.resume(
        run_dir, families=[cadmm_family], buckets=(4, 8), **mode)
    _drain(srv2)
    done2 = {rid: t.result for rid, t in srv2.tickets.items()
             if t.status == queue_mod.COMPLETED}
    merged = {**done1, **done2}
    assert set(merged) == set(ref)
    for rid in ref:
        _assert_same_leaves(ref[rid].result, merged[rid])


def test_host_default_zero_cost(monkeypatch):
    """With the knobs off the server is the pre-ISSUE-18 one: host
    surgery + sync dispatch, the surgery program is never built (no
    hidden compile), the chunk program's lowered HLO is byte-identical
    to what a device-knobbed process lowers (the knobs touch only
    boundary code), and the server grew no threading primitives (the
    pipeline is dispatch-async, not thread-based)."""
    import inspect

    from tpu_aerial_transport.serving import lanes

    monkeypatch.delenv("TAT_SERVING_SURGERY", raising=False)
    monkeypatch.delenv("TAT_SERVING_DISPATCH", raising=False)
    assert lanes.resolve_surgery(None) == "host"
    assert lanes.resolve_dispatch(None) == "sync"

    fam = batcher.make_family("cadmm4")  # fresh: no shared jit state.
    srv = server_mod.ScenarioServer(families=[fam], buckets=(4,))
    t = srv.submit(_req(0, horizon=4))
    _drain(srv)
    assert t.status == queue_mod.COMPLETED
    assert fam._surgery_jit is None  # host path never builds it.

    carry = jax.tree.map(
        lambda x: np.stack([np.asarray(x)] * 4),
        fam.template_carry_host(),
    )
    text_default = fam.batched_jit.lower(carry, np.int32(0)).as_text()
    monkeypatch.setenv("TAT_SERVING_SURGERY", "device")
    fam2 = batcher.make_family("cadmm4")
    text_device = fam2.batched_jit.lower(carry, np.int32(0)).as_text()
    assert text_default == text_device

    src = inspect.getsource(server_mod)
    assert "import threading" not in src and "Lock(" not in src


def test_serving_knob_resolvers(monkeypatch):
    """Env force > config > default; bad values raise; pipelined implies
    device surgery; device surgery rejects a mesh (the mesh boundary IS
    host surgery via pods.host_global)."""
    from tpu_aerial_transport.serving import lanes

    monkeypatch.delenv("TAT_SERVING_SURGERY", raising=False)
    monkeypatch.delenv("TAT_SERVING_DISPATCH", raising=False)
    assert lanes.resolve_surgery("auto") == "host"
    assert lanes.resolve_surgery("device") == "device"
    with pytest.raises(ValueError):
        lanes.resolve_surgery("gpu")
    with pytest.raises(ValueError):
        lanes.resolve_dispatch("async")

    monkeypatch.setenv("TAT_SERVING_SURGERY", "device")
    monkeypatch.setenv("TAT_SERVING_DISPATCH", "pipelined")
    assert lanes.resolve_surgery(None) == "device"
    assert lanes.resolve_surgery("host") == "device"  # force wins.
    assert lanes.resolve_dispatch("sync") == "pipelined"
    monkeypatch.setenv("TAT_SERVING_SURGERY", "lanes")
    with pytest.raises(ValueError):
        lanes.resolve_surgery(None)
    monkeypatch.delenv("TAT_SERVING_SURGERY")
    monkeypatch.delenv("TAT_SERVING_DISPATCH")

    srv = server_mod.ScenarioServer(
        families=["cadmm4"], buckets=(4,), dispatch="pipelined")
    assert (srv.surgery, srv.dispatch) == ("device", "pipelined")
    with pytest.raises(ValueError, match="single-device"):
        server_mod.ScenarioServer(
            families=["cadmm4"], buckets=(4,), surgery="device",
            mesh=object())


def test_result_cache_hit_skips_dispatch(cadmm_family, tmp_path):
    """A repeat submit of a content-identical request (different id)
    resolves at SUBMIT time from the cache — no admission, no batch
    launch — bitwise equal to the computed result, with a schema-valid
    cache_hit event and the hit surfacing in stats() and run_health."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    path = str(tmp_path / "cache.metrics.jsonl")
    srv = _mk_server(cadmm_family, metrics=path, cache=4)
    t1 = srv.submit(ScenarioRequest(family="cadmm4", horizon=4,
                                    x0=(0.5, -0.2, 0.9),
                                    request_id="orig"))
    _drain(srv)
    assert t1.status == queue_mod.COMPLETED

    events = export_mod.read_events(path)
    launches_before = sum(1 for e in events
                          if e.get("kind") == "batch_launch")
    t2 = srv.submit(ScenarioRequest(family="cadmm4", horizon=4,
                                    x0=(0.5, -0.2, 0.9),
                                    request_id="replay"))
    # Resolved at submit: COMPLETED before any pump, nothing in flight.
    assert t2.status == queue_mod.COMPLETED
    assert not srv.has_work()
    assert t2.steps_served == t1.steps_served
    _assert_same_leaves(t1.result, t2.result)

    assert export_mod.validate_file(path) == []
    events = export_mod.read_events(path)
    assert sum(1 for e in events
               if e.get("kind") == "batch_launch") == launches_before
    hits = [e for e in events if e.get("kind") == "cache_hit"]
    assert len(hits) == 1 and hits[0]["request_id"] == "replay"
    assert srv.stats()["cache"]["hits"] == 1

    sv = run_health.summarize(events)["serving"]
    assert sv["cache_hits"] == 1
    assert sv["cache_hit_rate"] == pytest.approx(0.5)


def test_result_cache_lru_and_keying():
    """Unit contract of serving/cache.py: content addressing ignores the
    request id, distinguishes payloads, and the LRU bound evicts the
    least-recently-used entry."""
    from tpu_aerial_transport.serving import cache as cache_mod

    r = ScenarioRequest(family="cadmm4", horizon=4, x0=(0.1, 0.2, 0.3),
                        request_id="a")
    same = ScenarioRequest(family="cadmm4", horizon=4, x0=(0.1, 0.2, 0.3),
                           request_id="b")
    other = ScenarioRequest(family="cadmm4", horizon=4,
                            x0=(0.1, 0.2, 0.30000001), request_id="c")
    assert cache_mod.request_key("h", r) == cache_mod.request_key("h", same)
    assert cache_mod.request_key("h", r) != cache_mod.request_key("h", other)
    assert cache_mod.request_key("h", r) != cache_mod.request_key("g", r)

    c = cache_mod.ResultCache(max_entries=2)
    c.put("k1", {"x": np.ones(3)}, 4)
    c.put("k2", {"x": np.zeros(3)}, 4)
    assert c.get("k1") is not None  # touch: k1 now most-recent.
    c.put("k3", {"x": np.full(3, 2.0)}, 8)
    assert c.get("k2") is None  # LRU evicted.
    hit = c.get("k1")
    assert hit is not None and hit[1] == 4
    # Deep-copied both ways: mutating the hit never corrupts the cache.
    hit[0]["x"][0] = 123.0
    assert c.get("k1")[0]["x"][0] == 1.0
    assert c.stats()["entries"] == 2


# ----------------------------------------------------------------------
# The acceptance e2e (slow): zero-compile mixed-shape soak + SIGTERM.
# ----------------------------------------------------------------------

@pytest.fixture(scope="session")
def serving_soak_bundle(tmp_path_factory):
    """Multi-bucket bundle for both canonical families (the slow soak's
    zero-compile admission surface)."""
    from tpu_aerial_transport.aot import bundle as bundle_mod

    out = str(tmp_path_factory.mktemp("serving_soak") / "cpu")
    bundle_mod.build_bundle(
        out, platform="cpu",
        names=["serving.batcher:serving_chunk",
               "serving.batcher:serving_chunk_centralized"],
        batch_buckets=(16, 32),
    )
    return out


def _serve_cli(bundle, extra, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu", TAT_XLA_CACHE_DIR="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "serve_scenarios.py"),
         "--requests", "96", "--waves-spec", "64,24,8",
         "--bundle", bundle, "--require-bundle", *extra],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO,
    )
    return proc


@pytest.mark.slow
def test_zero_compile_mixed_stream_soak(serving_soak_bundle, tmp_path):
    """ACCEPTANCE: a fresh process serves >= 64 requests over >= 3 shape
    buckets with late arrivals joining at chunk boundaries and 0 traces /
    0 lowerings / 0 backend compiles, every request resolving with a
    schema-v4 serving_event trail."""
    metrics = str(tmp_path / "soak.metrics.jsonl")
    proc = _serve_cli(serving_soak_bundle,
                      ["--expect-zero-compile", "--metrics", metrics])
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert (row["traces"], row["lowerings"], row["backend_compiles"]) \
        == (0, 0, 0)
    assert row["requests"] >= 64 and row["completed"] == row["requests"]

    assert export_mod.validate_file(metrics) == []
    events = export_mod.read_events(metrics)
    launches = [e for e in events if e.get("kind") == "batch_launch"]
    assert len({e["bucket"] for e in launches}) >= 3
    lanes_at_launch = sum(e["lanes"] for e in launches)
    admits = sum(1 for e in events if e.get("kind") == "admitted")
    assert admits - lanes_at_launch >= 1  # late joins at boundaries.
    serves = [e for e in events if e.get("event") == "aot_serve"]
    assert serves and all(e["rung"] == "bundle_exec" for e in serves)


@pytest.mark.slow
def test_sigterm_resume_bit_identity_subprocess(
        serving_soak_bundle, tmp_path):
    """ACCEPTANCE: SIGTERM mid-stream completes at the chunk boundary;
    a --resume invocation finishes the remainder; merged per-request
    digests equal the uninterrupted run's."""
    ref = str(tmp_path / "ref.json")
    proc = _serve_cli(serving_soak_bundle, ["--results", ref])
    assert proc.returncode == 0, proc.stderr[-2000:]

    run_dir = str(tmp_path / "rundir")
    r1 = str(tmp_path / "r1.json")
    proc = _serve_cli(serving_soak_bundle, [
        "--run-dir", run_dir, "--sigterm-after", "2", "--results", r1])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert json.loads(proc.stdout.strip().splitlines()[-1])["preempted"]

    r2 = str(tmp_path / "r2.json")
    proc = _serve_cli(serving_soak_bundle, [
        "--run-dir", run_dir, "--resume", "--results", r2])
    assert proc.returncode == 0, proc.stderr[-2000:]

    with open(ref) as fh:
        want = {k: v["digest"] for k, v in json.load(fh).items()
                if "digest" in v}
    got = {}
    for p in (r1, r2):
        with open(p) as fh:
            for k, v in json.load(fh).items():
                if "digest" in v:
                    got[k] = v["digest"]
    assert set(got) == set(want)
    assert all(got[k] == want[k] for k in want)
