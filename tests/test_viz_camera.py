"""viz.scene.smooth_camera_track: a user-supplied EVEN window on a long
trajectory must be coerced odd instead of raising inside savgol_filter at
render time (ISSUE 1 satellite)."""

import numpy as np

from tpu_aerial_transport.viz.scene import smooth_camera_track


def _traj(T):
    t = np.linspace(0.0, 1.0, T)
    return np.stack([t, np.sin(4 * t), 0.1 * t], axis=-1)


def test_even_window_on_long_trajectory():
    xl = _traj(400)
    out = smooth_camera_track(xl, window=50)  # even, < T: used to raise.
    assert out.shape == xl.shape
    assert np.all(np.isfinite(out))
    # Still an actual smoothing (not a passthrough).
    assert not np.allclose(out, xl)


def test_window_clamped_to_short_trajectory():
    xl = _traj(20)
    out = smooth_camera_track(xl, window=51)  # window > T: clamp path.
    assert out.shape == xl.shape
    assert np.all(np.isfinite(out))


def test_tiny_trajectory_passthrough():
    xl = _traj(4)
    out = smooth_camera_track(xl, window=6)
    assert np.array_equal(out, xl)
