"""AOT artifact bundles (tpu_aerial_transport/aot/): round-trip parity
(serve-from-bundle ≡ jit output bitwise for the cadmm/dd control steps and
chunked_rollout on the CPU target), manifest refusals (stale exec
fingerprint, treedef/signature mismatch, corrupt object), registry
coverage drift, the serve fallback ladder + aot_serve metrics events, the
bundle-warmed backend probe, and the acceptance proof: a FRESH subprocess
serving a registered control step from the bundle with zero traces /
lowerings / backend compiles (tools/aot_bundle.py serve
--expect-zero-compile — the whole-process flavor of the TC101 cache-miss
counting)."""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import jax
import numpy as np
import pytest

from tpu_aerial_transport.analysis import contracts
from tpu_aerial_transport.aot import bundle as bundle_mod
from tpu_aerial_transport.aot import loader as loader_mod
from tpu_aerial_transport.aot.bundle import BundleError
from tpu_aerial_transport.resilience import backend as backend_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The parity surface the issue names: both distributed control steps plus
# the chunked rollout (the recovery tier's one compiled chunk).
PARITY_ENTRIES = (
    "control.cadmm:control",
    "control.dd:control",
    "harness.rollout:chunked_rollout",
)


def _load_aot_cli():
    spec = importlib.util.spec_from_file_location(
        "aot_bundle_cli", os.path.join(REPO, "tools", "aot_bundle.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="session")
def cpu_bundle_dir(tmp_path_factory):
    """One real CPU bundle for the session: the three parity entries plus
    the probe entry every bundle carries."""
    out = str(tmp_path_factory.mktemp("aot") / "cpu")
    bundle_mod.build_bundle(out, platform="cpu", names=list(PARITY_ENTRIES))
    return out


@pytest.fixture(scope="session")
def cpu_bundle(cpu_bundle_dir):
    return loader_mod.load_bundle(cpu_bundle_dir)


def _leaves_bitwise_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    return all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# ----------------------------------------------------------------------
# Round-trip parity.
# ----------------------------------------------------------------------

@pytest.mark.parametrize("entry", PARITY_ENTRIES)
def test_roundtrip_parity_exec(cpu_bundle, entry):
    """Serving from the bundle's serialized executable is BITWISE the jit
    output — same program, same backend, no re-lowering drift."""
    fn, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    want = jax.jit(fn)(*args)
    got, rung = cpu_bundle.call(entry, args)
    assert rung == loader_mod.RUNG_EXEC
    assert jax.tree.structure(got) == jax.tree.structure(want)
    assert _leaves_bitwise_equal(got, want)


def test_roundtrip_parity_export_rung(cpu_bundle):
    """The export (StableHLO replay) rung serves the same bits too — the
    ladder's downgrade path must not change results."""
    entry = "control.cadmm:control"
    fn, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    want = jax.jit(fn)(*args)
    got, rung = cpu_bundle.call(entry, args, rung=loader_mod.RUNG_EXPORT)
    assert rung == loader_mod.RUNG_EXPORT
    assert _leaves_bitwise_equal(got, want)


def test_probe_entry_runs(cpu_bundle):
    out = loader_mod.call_probe(cpu_bundle)
    assert np.isfinite(float(out))


def test_exec_artifact_survives_warm_compilation_cache(tmp_path):
    """REGRESSION: an executable the persistent compilation cache hands
    back re-serializes WITHOUT its compiled object code ("Symbols not
    found" at deserialize) — a bundle built on a warm cache (any test or
    bench host) used to publish corrupt exec artifacts. The builder now
    forces a real compile; the SECOND build below, whose backend compile
    would otherwise be a cache hit, must still serve on the exec rung."""
    cache_before = jax.config.jax_compilation_cache_dir
    min_before = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        # Everything persists (min compile time 0), so even the small
        # probe program reproduces the cache-hit build.
        jax.config.update("jax_compilation_cache_dir",
                          str(tmp_path / "xla-cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        bundle_mod.build_bundle(str(tmp_path / "b1"), platform="cpu",
                                names=[])  # populates the cache.
        bundle_mod.build_bundle(str(tmp_path / "b2"), platform="cpu",
                                names=[])  # cache-hit build.
        b2 = loader_mod.load_bundle(str(tmp_path / "b2"))
        out, rung = b2.call(bundle_mod.PROBE_ENTRY, b2.probe_args())
        assert rung == loader_mod.RUNG_EXEC
        assert np.isfinite(float(jax.tree.leaves(out)[0]))
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_before)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_before)


# ----------------------------------------------------------------------
# Refusals.
# ----------------------------------------------------------------------

def _tampered_copy(cpu_bundle_dir, tmp_path, mutate):
    dst = str(tmp_path / "tampered")
    shutil.copytree(cpu_bundle_dir, dst)
    mpath = os.path.join(dst, bundle_mod.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    mutate(manifest, dst)
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    return loader_mod.load_bundle(dst)


def test_stale_fingerprint_refusal(cpu_bundle_dir, tmp_path):
    """An exec artifact built under a different jaxlib refuses with
    ``bundle_stale`` — and the default ladder falls through to the export
    rung instead of serving a possibly-ABI-incompatible executable."""
    entry = "control.cadmm:control"

    def mutate(manifest, _dst):
        art = manifest["entries"][entry]["variants"][0]["artifacts"]["exec"]
        art["fingerprint"]["jaxlib"] = "0.0.0-stale"

    b = _tampered_copy(cpu_bundle_dir, tmp_path, mutate)
    _, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    with pytest.raises(BundleError) as ei:
        b.call(entry, args, rung=loader_mod.RUNG_EXEC)
    assert ei.value.kind == "bundle_stale"
    assert "rebuild" in str(ei.value)
    # Default ladder: stale exec downgrades to the export rung, still
    # serving without a retrace.
    out, rung = b.call(entry, args)
    assert rung == loader_mod.RUNG_EXPORT
    fn, _ = contracts.REGISTRY[entry].build()
    assert _leaves_bitwise_equal(out, jax.jit(fn)(*args))


def test_bundle_stale_classified_not_breaker(tmp_path):
    """The taxonomy files a stale bundle as a BUILD artifact problem: its
    kind never indicts the chip (circuit breaker ignores it)."""
    err = BundleError("bundle_stale", str(tmp_path), "fingerprint differs")
    assert backend_mod.classify(str(err)) == "bundle_stale"
    assert "bundle_stale" not in backend_mod.BREAKER_KINDS
    assert "bundle_stale" in backend_mod.ERROR_KINDS


def test_treedef_mismatch_refusal(cpu_bundle):
    entry = "control.cadmm:control"
    _, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    with pytest.raises(BundleError) as ei:
        cpu_bundle.call(entry, list(args))  # tuple -> list: new structure.
    assert ei.value.kind == "treedef_mismatch"


def test_signature_mismatch_refusal(cpu_bundle):
    """Same pytree structure, different leaf shape: no precompiled
    variant — refuse rather than silently recompile."""
    import jax.numpy as jnp

    with pytest.raises(BundleError) as ei:
        cpu_bundle.call(
            bundle_mod.PROBE_ENTRY, (jnp.ones((64, 64), jnp.float32),)
        )
    assert ei.value.kind == "signature_mismatch"


def test_corrupt_object_refusal(cpu_bundle_dir, tmp_path):
    dst = str(tmp_path / "corrupt")
    shutil.copytree(cpu_bundle_dir, dst)
    objdir = os.path.join(dst, bundle_mod.OBJECTS_DIR)
    for name in sorted(os.listdir(objdir)):
        path = os.path.join(objdir, name)
        with open(path, "r+b") as fh:
            first = fh.read(1)
            fh.seek(0)
            fh.write(bytes([first[0] ^ 0xFF]))
    b = loader_mod.load_bundle(dst)
    _, make_args = contracts.REGISTRY["control.cadmm:control"].build()
    with pytest.raises(BundleError) as ei:
        b.call("control.cadmm:control", make_args())
    assert ei.value.kind == "corrupt"


def test_unreadable_and_newer_schema_refusal(tmp_path):
    with pytest.raises(BundleError) as ei:
        loader_mod.load_bundle(str(tmp_path / "nope"))
    assert ei.value.kind == "unreadable"
    d = tmp_path / "future"
    d.mkdir()
    (d / bundle_mod.MANIFEST_NAME).write_text(
        json.dumps({"schema": bundle_mod.SCHEMA_VERSION + 1})
    )
    with pytest.raises(BundleError) as ei:
        loader_mod.load_bundle(str(d))
    assert ei.value.kind == "schema"


# ----------------------------------------------------------------------
# Coverage drift (the CI gate's core).
# ----------------------------------------------------------------------

def test_coverage_diff_missing_and_ok(tmp_path):
    """A manifest-only bundle restricted to one entry reports every other
    registered entrypoint as missing; the full record diffs clean."""
    out = str(tmp_path / "subset")
    manifest = bundle_mod.build_bundle(
        out, platform="cpu", names=["control.cadmm:control"],
        manifest_only=True,
    )
    diff = bundle_mod.coverage_diff(manifest)
    assert not diff["ok"]
    assert "control.dd:control" in diff["missing"]

    full = str(tmp_path / "full")
    manifest = bundle_mod.build_bundle(full, platform="cpu",
                                       manifest_only=True)
    diff = bundle_mod.coverage_diff(manifest)
    assert diff["ok"], diff


def test_coverage_diff_unregistered_entry_fails(tmp_path):
    """A NEW registry entrypoint the bundle predates (simulated by
    dropping it from the manifest) is drift — exactly what lands when an
    entrypoint is registered without a bundle rebuild. The CLI check
    exits 1 on it."""
    out = str(tmp_path / "drift")
    bundle_mod.build_bundle(out, platform="cpu", manifest_only=True)
    mpath = os.path.join(out, bundle_mod.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    del manifest["entries"]["control.dd:control"]
    manifest["entries"]["ops.retired:gone"] = {"variants": [{"sig": "x"}]}
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)

    diff = bundle_mod.coverage_diff(manifest)
    assert not diff["ok"]
    assert "control.dd:control" in diff["missing"]
    assert "ops.retired:gone" in diff["stale"]

    cli = _load_aot_cli()
    ns = type("NS", (), {"bundle": out, "manifest_hint": True})
    assert cli.cmd_check(ns) == 1


def test_coverage_diff_changed_signature(tmp_path):
    out = str(tmp_path / "changed")
    bundle_mod.build_bundle(out, platform="cpu", manifest_only=True)
    mpath = os.path.join(out, bundle_mod.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    manifest["entries"]["control.cadmm:control"]["variants"][0]["sig"] = \
        "0" * 16
    diff = bundle_mod.coverage_diff(manifest)
    assert not diff["ok"]
    assert any("control.cadmm:control" in c for c in diff["changed"])


# ----------------------------------------------------------------------
# Shape buckets.
# ----------------------------------------------------------------------

def test_bucketed_batch_rounds_to_tile():
    import jax.numpy as jnp

    args = (jnp.arange(3 * 5, dtype=jnp.float32).reshape(3, 5),)
    bargs, b = bundle_mod.bucketed_batch(args, 0, 5)
    assert b == 8 and bargs[0].shape == (8, 5)
    # Tiled cyclically from the originals (values only seed compilation).
    np.testing.assert_array_equal(
        np.asarray(bargs[0][:3]), np.asarray(args[0])
    )
    np.testing.assert_array_equal(
        np.asarray(bargs[0][3:6]), np.asarray(args[0])
    )


def test_variant_for_batch_selection(tmp_path):
    manifest = {
        "schema": bundle_mod.SCHEMA_VERSION,
        "platform": "cpu",
        "entries": {"e": {"variants": [
            {"sig": "a", "artifacts": {}},
            {"sig": "b", "artifacts": {}, "batch": 16},
            {"sig": "c", "artifacts": {}, "batch": 8},
        ]}},
        "skipped": {},
    }
    b = loader_mod.Bundle(str(tmp_path), manifest)
    assert b.variant_for_batch("e", 5)["batch"] == 8
    assert b.variant_for_batch("e", 12)["batch"] == 16
    assert b.variant_for_batch("e", 99)["batch"] == 16  # largest wins.
    with pytest.raises(BundleError):
        loader_mod.Bundle(str(tmp_path), {
            "schema": 1, "platform": "cpu", "skipped": {},
            "entries": {"e": {"variants": [{"sig": "a", "artifacts": {}}]}},
        }).variant_for_batch("e", 5)


def test_abstract_signature_shape_only():
    """The signature keys on treedef + avals, not values — computable
    from ShapeDtypeStructs without tracing."""
    import jax.numpy as jnp

    concrete = (jnp.ones((4, 3), jnp.float32), jnp.zeros((2,), jnp.int32))
    structs = (jax.ShapeDtypeStruct((4, 3), jnp.float32),
               jax.ShapeDtypeStruct((2,), jnp.int32))
    assert (bundle_mod.abstract_signature(concrete)
            == bundle_mod.abstract_signature(structs))
    other = (jnp.ones((4, 4), jnp.float32), jnp.zeros((2,), jnp.int32))
    assert (bundle_mod.abstract_signature(concrete)
            != bundle_mod.abstract_signature(other))


# ----------------------------------------------------------------------
# The serve ladder + metrics events.
# ----------------------------------------------------------------------

def test_serve_ladder_rungs_and_metrics(cpu_bundle, tmp_path):
    from tpu_aerial_transport.obs import export as export_mod

    entry = "control.cadmm:control"
    fn, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    path = str(tmp_path / "serve.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path, meta={"mode": "test"})

    out_b, rung_b = loader_mod.serve_entry(
        cpu_bundle, entry, args, metrics=metrics
    )
    assert rung_b == loader_mod.RUNG_EXEC
    out_j, rung_j = loader_mod.serve_entry(
        None, entry, args, jit_fallback=fn, metrics=metrics
    )
    # The suite's conftest configures the persistent cache, so the jit
    # fallback lands on the cached rung here.
    assert rung_j == (loader_mod.RUNG_JIT_CACHED
                      if jax.config.jax_compilation_cache_dir
                      else loader_mod.RUNG_JIT_COLD)
    assert _leaves_bitwise_equal(out_b, out_j)

    assert export_mod.validate_file(path) == []
    events = [json.loads(ln) for ln in open(path)]
    serves = [e for e in events if e.get("event") == "aot_serve"]
    assert [e["rung"] for e in serves] == [rung_b, rung_j]
    assert all(e["entry"] == entry and "wall_s" in e for e in serves)


def test_serve_coverage_miss_falls_through_to_jit(cpu_bundle, tmp_path):
    """A COVERAGE miss (signature_mismatch: no precompiled variant for
    this shape) degrades to the jit fallback — the ladder's job."""
    import jax.numpy as jnp

    from tpu_aerial_transport.obs import export as export_mod

    path = str(tmp_path / "miss.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path, meta={"mode": "test"})
    args = (jnp.ones((64, 64), jnp.float32),)
    out, rung = loader_mod.serve_entry(
        cpu_bundle, bundle_mod.PROBE_ENTRY, args,
        jit_fallback=lambda x: (x @ x).sum(), metrics=metrics,
    )
    assert rung in (loader_mod.RUNG_JIT_CACHED, loader_mod.RUNG_JIT_COLD)
    ev = [json.loads(ln) for ln in open(path)][-1]
    assert ev["tried"] == ["bundle[signature_mismatch]"]


def test_serve_integrity_failure_raises_despite_fallback(
        cpu_bundle_dir, tmp_path):
    """An INTEGRITY failure (bitrotted object) re-raises even when a jit
    fallback exists — a corrupt artifact must not silently become a cold
    compile; the operator-visible error event is the contract."""
    from tpu_aerial_transport.obs import export as export_mod

    dst = str(tmp_path / "rot")
    shutil.copytree(cpu_bundle_dir, dst)
    objdir = os.path.join(dst, bundle_mod.OBJECTS_DIR)
    for fname in sorted(os.listdir(objdir)):
        with open(os.path.join(objdir, fname), "r+b") as fh:
            first = fh.read(1)
            fh.seek(0)
            fh.write(bytes([first[0] ^ 0xFF]))
    b = loader_mod.load_bundle(dst)
    entry = "control.cadmm:control"
    fn, make_args = contracts.REGISTRY[entry].build()
    path = str(tmp_path / "rot.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path, meta={"mode": "test"})
    with pytest.raises(BundleError) as ei:
        loader_mod.serve_entry(b, entry, make_args(), jit_fallback=fn,
                               metrics=metrics)
    assert ei.value.kind == "corrupt"
    assert ei.value.kind in loader_mod.INTEGRITY_KINDS
    ev = [json.loads(ln) for ln in open(path)][-1]
    assert ev["rung"] == "error" and "corrupt" in ev["error"]


def test_cpu_kernel_binding_failure_downgrades_to_export(
        cpu_bundle, monkeypatch):
    """If the LAPACK custom-call binding is unavailable (jaxlib
    reshuffled the private module), the exec rung REFUSES with
    exec_unavailable — dispatching unbound kernels segfaults, it does not
    raise — and the default ladder serves the export rung instead."""
    monkeypatch.setattr(loader_mod, "_cpu_kernels_state",
                        "ImportError: no jaxlib.cpu._lapack")
    entry = "control.cadmm:control"
    _, make_args = contracts.REGISTRY[entry].build()
    args = make_args()
    with pytest.raises(BundleError) as ei:
        cpu_bundle.call(entry, args, rung=loader_mod.RUNG_EXEC)
    assert ei.value.kind == "exec_unavailable"
    out, rung = cpu_bundle.call(entry, args)
    assert rung == loader_mod.RUNG_EXPORT
    fn, _ = contracts.REGISTRY[entry].build()
    assert _leaves_bitwise_equal(out, jax.jit(fn)(*args))


def test_serve_error_journaled_then_raised(cpu_bundle, tmp_path):
    """A bundle failure with NO fallback re-raises AFTER journaling — a
    corrupt artifact must not become an invisible cold compile."""
    from tpu_aerial_transport.obs import export as export_mod

    path = str(tmp_path / "err.metrics.jsonl")
    metrics = export_mod.MetricsWriter(path, meta={"mode": "test"})
    import jax.numpy as jnp

    with pytest.raises(BundleError):
        loader_mod.serve_entry(
            cpu_bundle, bundle_mod.PROBE_ENTRY,
            (jnp.ones((64, 64), jnp.float32),), metrics=metrics,
        )
    events = [json.loads(ln) for ln in open(path)]
    errs = [e for e in events if e.get("event") == "aot_serve"]
    assert len(errs) == 1 and errs[0]["rung"] == "error"
    assert "signature_mismatch" in errs[0]["error"]


# ----------------------------------------------------------------------
# Bundle-warmed backend probe.
# ----------------------------------------------------------------------

def test_probe_subprocess_prefers_bundle(cpu_bundle_dir):
    notes: list = []
    ok, detail = backend_mod.probe_subprocess(
        timeout_s=120.0, bundle_dir=cpu_bundle_dir, notes=notes
    )
    assert ok and detail == "cpu"
    assert notes == ["bundle"]


def test_probe_subprocess_stale_bundle_surfaces_note(
        cpu_bundle_dir, tmp_path):
    """A STALE exec fingerprint surfaces in the probe notes (the rebuild
    hint), instead of the ladder silently absorbing it into the export
    rung's backend compile: call_probe pins the exec rung, so the stale
    refusal falls back to the compile probe inside the subprocess."""
    dst = str(tmp_path / "stale")
    shutil.copytree(cpu_bundle_dir, dst)
    mpath = os.path.join(dst, bundle_mod.MANIFEST_NAME)
    with open(mpath) as fh:
        manifest = json.load(fh)
    art = manifest["entries"][bundle_mod.PROBE_ENTRY]["variants"][0][
        "artifacts"]["exec"]
    art["fingerprint"]["jaxlib"] = "0.0.0-stale"
    with open(mpath, "w") as fh:
        json.dump(manifest, fh)
    notes: list = []
    ok, detail = backend_mod.probe_subprocess(
        timeout_s=120.0, bundle_dir=dst, notes=notes
    )
    assert ok and detail == "cpu"
    assert len(notes) == 1 and notes[0].startswith("bundle_fallback:")
    assert "bundle_stale" in notes[0]


def test_probe_subprocess_bundle_fallback_note(tmp_path):
    """A missing/stale bundle downgrades to the compile probe INSIDE the
    subprocess: the chip still validates, the note carries the classified
    bundle problem (a rebuild hint, never a probe failure)."""
    notes: list = []
    ok, detail = backend_mod.probe_subprocess(
        timeout_s=120.0, bundle_dir=str(tmp_path / "absent"), notes=notes
    )
    assert ok and detail == "cpu"
    assert len(notes) == 1 and notes[0].startswith("bundle_fallback:")


# ----------------------------------------------------------------------
# The acceptance proof: zero-compile cold start in a fresh process.
# ----------------------------------------------------------------------

def test_zero_compile_fresh_subprocess(cpu_bundle_dir):
    """A FRESH subprocess loading the CPU bundle executes the registered
    C-ADMM control step with 0 traces, 0 MLIR lowerings, and 0 XLA
    backend compiles — counted by jax's monitoring events over the WHOLE
    process (the process-level twin of TC101's per-function cache-miss
    counting). ``--expect-zero-compile`` makes the child itself exit 3 on
    any compile, so the proof cannot rot into a warning."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", TAT_XLA_CACHE_DIR="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "aot_bundle.py"),
         "serve", "--entry", "control.cadmm:control", "--mode", "bundled",
         "--bundle", cpu_bundle_dir, "--expect-zero-compile"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["rung"] == loader_mod.RUNG_EXEC
    assert (row["traces"], row["lowerings"], row["backend_compiles"]) \
        == (0, 0, 0)
    assert row["ttfs_s"] > 0
