"""Padded-operator tier (ops/socp.py) + donation contracts.

Parity: the tile-padded solve must agree with the unpadded reference path
to f32 reduction-order rounding — including warm starts, SOC blocks that
land directly adjacent to the padded box rows, batched (vmapped) solves,
and full consensus-controller steps. Donation: the donated rollout
entrypoints must actually alias their carries in the lowered program and
delete the donated buffers at runtime.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import rollout as h_rollout
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.harness.bucketing import bucket_dim
from tpu_aerial_transport.ops import socp


def _problem(seed=0, nv=12, n_box=17, soc_dims=(4, 4), soc_shift=True):
    rng = np.random.default_rng(seed)
    m = n_box + sum(soc_dims)
    L = rng.standard_normal((nv, nv))
    P = jnp.asarray(L @ L.T + np.eye(nv), jnp.float32)
    q = jnp.asarray(rng.standard_normal(nv), jnp.float32)
    A = jnp.asarray(rng.standard_normal((m, nv)) * 0.5, jnp.float32)
    lb = jnp.asarray(rng.uniform(-2.0, -0.5, n_box), jnp.float32)
    ub = jnp.asarray(rng.uniform(0.5, 2.0, n_box), jnp.float32)
    shift = None
    if soc_shift:
        shift = jnp.asarray(
            np.r_[np.zeros(n_box), rng.standard_normal(sum(soc_dims)) * 0.1],
            jnp.float32,
        )
    return P, q, A, lb, ub, shift, n_box, soc_dims


def test_padded_dims_bucket():
    assert socp.padded_dims(12, 17, (4, 4)) == (16, 24)  # m 25 -> 32.
    assert socp.padded_dims(18, 23, (4, 4)) == (24, 24)  # m 31 -> 32.
    assert socp.padded_dims(8, 8, ()) == (8, 8)  # already aligned: no-op.
    assert bucket_dim(37, 8) == 40 and bucket_dim(48, 8) == 48


def test_padded_solve_matches_unpadded():
    """Cold solve: padded == unpadded to f32 rounding; residuals too. The
    SOC blocks sit directly after the padded (free) box rows — the
    adjacency the projection layout must keep exact."""
    P, q, A, lb, ub, shift, n_box, soc = _problem()
    ref = socp.solve_socp(P, q, A, lb, ub, n_box=n_box, soc_dims=soc,
                          iters=200, shift=shift)
    pad = socp.solve_socp_padded(P, q, A, lb, ub, n_box=n_box, soc_dims=soc,
                                 iters=200, shift=shift)
    np.testing.assert_allclose(np.asarray(pad.x), np.asarray(ref.x),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(pad.y), np.asarray(ref.y),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(pad.z), np.asarray(ref.z),
                               rtol=0, atol=1e-5)
    assert abs(float(pad.prim_res) - float(ref.prim_res)) < 1e-5
    assert abs(float(pad.dual_res) - float(ref.dual_res)) < 1e-5
    # Layout shape: solution comes back UNPADDED.
    assert pad.x.shape == ref.x.shape and pad.y.shape == ref.y.shape


def test_padded_solve_warm_start_parity():
    """Warm-started re-solve (the consensus controllers' steady state):
    an unpadded warm start lifts into the padded layout exactly."""
    P, q, A, lb, ub, shift, n_box, soc = _problem(seed=3)
    ref0 = socp.solve_socp(P, q, A, lb, ub, n_box=n_box, soc_dims=soc,
                           iters=150, shift=shift)
    pad0 = socp.solve_socp_padded(P, q, A, lb, ub, n_box=n_box,
                                  soc_dims=soc, iters=150, shift=shift)
    q2 = q + 0.02
    ref = socp.solve_socp(P, q2, A, lb, ub, n_box=n_box, soc_dims=soc,
                          iters=40, shift=shift, warm=ref0)
    pad = socp.solve_socp_padded(P, q2, A, lb, ub, n_box=n_box,
                                 soc_dims=soc, iters=40, shift=shift,
                                 warm=pad0)
    np.testing.assert_allclose(np.asarray(pad.x), np.asarray(ref.x),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(pad.y), np.asarray(ref.y),
                               rtol=0, atol=1e-5)


def test_padded_operator_reuse_and_vmap():
    """PaddedKKTOp built once, reused across a vmapped batch of q's —
    the controllers' per-step pattern (operator per step, q per iteration)."""
    P, q, A, lb, ub, shift, n_box, soc = _problem(seed=5)
    pqp = socp.padded_kkt_operator(P, A, lb, ub, shift, n_box=n_box,
                                   soc_dims=soc)
    # The padded operator's real block matches the unpadded operator.
    rho_vec = socp.make_rho_vec(A.shape[0], n_box, lb, ub, 0.4, jnp.float32)
    op_ref = socp.kkt_operator(P, A, rho_vec)
    nv = P.shape[-1]
    np.testing.assert_allclose(np.asarray(pqp.op.Minv[:nv, :nv]),
                               np.asarray(op_ref.Minv), rtol=0, atol=2e-5)
    qs = jnp.stack([q, q + 0.1, q - 0.1])
    sols = jax.vmap(
        lambda q_: socp.solve_socp_padded(
            P, q_, A, lb, ub, n_box=n_box, soc_dims=soc, iters=120,
            shift=shift, pqp=pqp,
        )
    )(qs)
    refs = jax.vmap(
        lambda q_: socp.solve_socp(
            P, q_, A, lb, ub, n_box=n_box, soc_dims=soc, iters=120,
            shift=shift,
        )
    )(qs)
    np.testing.assert_allclose(np.asarray(sols.x), np.asarray(refs.x),
                               rtol=0, atol=2e-5)


def test_pad_qp_exactness_invariants():
    """Structural invariants the exactness argument rests on: zero pad
    rows/cols, free pad bounds, unit pad diagonal, zero pad shift."""
    P, q, A, lb, ub, shift, n_box, soc = _problem()
    nv, m = P.shape[-1], A.shape[0]
    P_p, q_p, A_p, lb_p, ub_p, shift_p = socp.pad_qp(
        P, q, A, lb, ub, shift, n_box=n_box, soc_dims=soc
    )
    nv_p, n_box_p = socp.padded_dims(nv, n_box, soc)
    pad_b = n_box_p - n_box
    assert P_p.shape == (nv_p, nv_p) and A_p.shape == (m + pad_b, nv_p)
    assert np.all(np.asarray(A_p[n_box:n_box_p]) == 0)  # pad rows zero.
    assert np.all(np.asarray(A_p[:, nv:]) == 0)  # pad cols zero.
    assert np.all(np.asarray(lb_p[n_box:]) == -socp.INF)
    assert np.all(np.asarray(ub_p[n_box:]) == socp.INF)
    np.testing.assert_array_equal(np.asarray(P_p[nv:, nv:]),
                                  np.eye(nv_p - nv, dtype=np.float32))
    assert np.all(np.asarray(shift_p[n_box:n_box_p]) == 0)
    # SOC rows land directly after the pad rows, unchanged.
    np.testing.assert_array_equal(np.asarray(A_p[n_box_p:, :nv]),
                                  np.asarray(A[n_box:]))


@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_controller_padded_matches_unpadded(ctrl):
    """Full consensus control steps, padded vs unpadded operators: same
    forces to f32 rounding, same iteration counts (n = 4: the Schur path
    for C-ADMM incl. the V-padded plan cores)."""
    n = 4
    params, col, state = setup.rqp_setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    f_eq = centralized.equilibrium_forces(params)
    acc = (jnp.array([0.3, 0.0, 0.1], jnp.float32),
           jnp.zeros(3, jnp.float32))
    mod = cadmm if ctrl == "cadmm" else dd
    outs = {}
    for padded in (True, False):
        cfg = mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=6, inner_iters=12, pad_operators=padded,
        )
        if ctrl == "cadmm":
            cs = cadmm.init_cadmm_state(params, cfg)
            plan = cadmm.make_plan(params, cfg)
        else:
            cs = dd.init_dd_state(params, cfg)
            plan = dd.make_dd_plan(params, cfg)
        step = jax.jit(
            lambda c, s, cfg=cfg, plan=plan: mod.control(
                params, cfg, f_eq, c, s, acc, None, plan=plan
            )
        )
        # Two chained steps: the second exercises warm starts carried in
        # the padded layout.
        f1, cs, st1 = step(cs, state)
        f2, cs, st2 = step(cs, state)
        outs[padded] = (np.asarray(f1), np.asarray(f2),
                        int(st1.iters), int(st2.iters))
    assert np.abs(outs[True][0] - outs[False][0]).max() < 5e-4
    assert np.abs(outs[True][1] - outs[False][1]).max() < 5e-4
    assert outs[True][2:] == outs[False][2:]


# ----------------------------- donation --------------------------------

def test_jit_rollout_donates_and_deletes():
    """The donated rollout must (a) report input-output aliasing in its
    lowered program (the TC105 contract) and (b) actually delete the
    donated buffers at runtime, with chained calls working."""
    params, col, state0 = setup.rqp_setup(4)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=8
    )
    f_eq = centralized.equilibrium_forces(params)
    from tpu_aerial_transport.control import lowlevel

    llc = lowlevel.make_lowlevel_controller("pd", params)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    run = h_rollout.jit_rollout(
        hl, llc.control, params, n_hl_steps=2, hl_rel_freq=2
    )
    args = jax.tree.map(
        jnp.copy, (state0, centralized.init_ctrl_state(params, cfg))
    )
    n_leaves = len(jax.tree.leaves(args))
    text = run.lower(*args).as_text()
    n_aliased = text.count("tf.aliasing_output")
    assert n_aliased >= 6, (
        f"expected >= 6 aliased (donated) inputs, lowered program has "
        f"{n_aliased} of {n_leaves} donated leaves"
    )
    state, cs, logs = run(*args)
    assert args[0].xl.is_deleted(), "donated physics state not deleted"
    # Chaining the returned carries works (the serving pattern).
    state, cs, logs = run(state, cs)
    assert np.isfinite(np.asarray(state.xl)).all()


def test_jit_control_step_donates_ctrl_state():
    params, col, state0 = setup.rqp_setup(4)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=3, inner_iters=6,
    )
    f_eq = centralized.equilibrium_forces(params)
    step = cadmm.jit_control_step(params, cfg, f_eq)
    acc = (jnp.zeros(3, jnp.float32), jnp.zeros(3, jnp.float32))
    cs = jax.tree.map(jnp.copy, cadmm.init_cadmm_state(params, cfg))
    f, cs2, _ = step(cs, state0, acc)
    assert cs.f.is_deleted()
    f, cs3, _ = step(cs2, state0, acc)  # chained.
    assert not cs3.f.is_deleted()


def test_tc105_contract_detects_missing_donation():
    """The TC105 check must fire when a registered donated entrypoint stops
    aliasing (here: an undonated twin of the rollout entry)."""
    from tpu_aerial_transport.analysis import contracts, entrypoints

    name = "harness.rollout:rollout_donated"
    assert entrypoints.DONATION_CONTRACTS[name] >= 6
    base = contracts.REGISTRY[name]

    def build_undonated():
        fn, make_args = base.build()
        # Re-wrap WITHOUT donation: same program, no aliasing.
        return (lambda *a: fn(*a)), make_args

    c = contracts.Contract(name=name, build=build_undonated)
    findings = [
        f for f in contracts.check_entry(
            c, disabled=frozenset({"TC101", "TC103", "TC104"})
        ) if f.rule == "TC105"
    ]
    assert findings, "TC105 did not fire on an undonated rollout"


def test_misaligned_contraction_detector():
    from tpu_aerial_transport.analysis.contracts import (
        misaligned_contractions,
    )

    def f(a, b):
        return a @ b

    # Long misaligned contraction (37): flagged on both operands' dims.
    jx = jax.make_jaxpr(f)(jnp.ones((8, 37)), jnp.ones((37, 8)))
    assert misaligned_contractions(jx.jaxpr)
    # Padded twin (40): clean.
    jx = jax.make_jaxpr(f)(jnp.ones((8, 40)), jnp.ones((40, 8)))
    assert not misaligned_contractions(jx.jaxpr)
    # Short misaligned contraction (12 < MIN_ALIGNED_CONTRACT): exempt.
    jx = jax.make_jaxpr(f)(jnp.ones((8, 12)), jnp.ones((12, 8)))
    assert not misaligned_contractions(jx.jaxpr)
