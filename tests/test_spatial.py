"""Spatial-hash bucketed environment queries (envs/spatial.py): grid
build invariants + the structured overflow refusal, bitwise
bucketed-vs-dense EnvCBF parity (single, batched, vmapped, nominal and
vision-cone-masked), the lax.top_k tie-order discipline, the dense-mode
byte-identical-HLO zero-cost contract, the resolver gates, and the
city-scale world parameterization of make_forest."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.envs import forest as fo
from tpu_aerial_transport.envs import spatial as sp
from tpu_aerial_transport.harness import setup

VISION = 6.0
QUERY_R = VISION + fo.BARK_RADIUS


def _rows(forest, xl, vl, mode, n_rows=10):
    return fo.collision_cbf_rows(
        forest, xl, vl, VISION - 5.0, 2.0, VISION, 0.1, 1.5, n_rows,
        env_query=mode,
    )


def _cbf_equal(a, b):
    return all(
        bool(jnp.array_equal(getattr(a, k), getattr(b, k)))
        for k in ("lhs", "rhs", "collision", "min_dist")
    )


def _city(n_trees=4096, seed=1, max_trees=None):
    import math

    n_side = math.isqrt(n_trees)
    pitch = 1.0 / np.sqrt(0.085)
    return fo.make_forest(
        seed=seed, max_trees=max_trees or n_trees,
        world_size=(n_side + 0.5) * pitch, density=0.085,
    )


# ----------------------------- build ----------------------------------


def test_auto_threshold_matches_max_trees():
    # DENSE_AUTO_MAX_TREES is a literal (forest is mid-import when
    # spatial loads); this pin keeps it equal to the real constant.
    assert sp.DENSE_AUTO_MAX_TREES == fo.MAX_TREES


def test_build_invariants_and_coverage():
    """Every valid tree within query_radius (XY) of any probe point must
    sit in the probe cell's slab — the completeness guarantee bitwise
    parity rests on — and slabs are ascending (the tie-order
    discipline), K tile-rounded."""
    forest = fo.make_forest(seed=3)
    grid = sp.build_grid(forest, QUERY_R)
    assert grid.k % sp.SLAB_TILE == 0 and grid.k >= sp.MIN_SLAB
    idxs = np.asarray(grid.cell_idx)
    valids = np.asarray(grid.cell_valid)
    for c in range(idxs.shape[0]):
        s = idxs[c][valids[c]]
        assert (np.diff(s) > 0).all(), f"slab {c} not ascending"

    pos = np.asarray(forest.tree_pos)
    num = int(forest.num_trees)
    rng = np.random.default_rng(0)
    probes = rng.uniform(-30, 30, size=(64, 2)) + np.asarray(
        fo.MOUNTAIN_CENTER
    )
    for p in probes:
        mid = jnp.asarray([p[0], p[1], 2.0], jnp.float32)
        idx, valid = jax.jit(sp.candidate_slab)(forest.replace(grid=grid),
                                                mid)
        slab = set(np.asarray(idx)[np.asarray(valid)].tolist())
        d = np.linalg.norm(pos[:num, :2] - p[None], axis=1)
        required = set(np.nonzero(d <= QUERY_R)[0].tolist())
        assert required <= slab, (p, required - slab)


def test_overflow_refusal_measures_k_needed():
    forest = fo.make_forest(seed=0)
    with pytest.raises(sp.GridOverflowError) as ei:
        sp.build_grid(forest, QUERY_R, k=2)
    err = ei.value
    assert err.k == 2 and err.k_needed > 2
    assert str(err.k_needed) in str(err)
    # The measured number IS the fix.
    grid = sp.build_grid(forest, QUERY_R, k=err.k_needed)
    assert grid.k == err.k_needed
    # And auto-sizing admits it with the tile rounding.
    auto = sp.build_grid(forest, QUERY_R)
    assert auto.k >= err.k_needed


def test_empty_world_grid():
    forest = fo.forest_from_tree_pos(np.zeros((0, 3)), 0)
    grid = sp.build_grid(forest, QUERY_R)
    stats = sp.grid_stats(grid)
    assert stats["max_occupancy"] == 0 and stats["n_cells"] == 1


# ----------------------------- parity ---------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_bitwise_parity_single_and_batched(seed):
    """Bucketed EnvCBF rows == dense bitwise: the candidate set is
    complete by the build-time coverage guarantee and the per-tree sweep
    math is elementwise along the tree axis, so gathering candidates
    cannot change a selected tree's row values."""
    forest = sp.with_grid(fo.make_forest(seed=seed), QUERY_R)
    rng = np.random.default_rng(seed)
    xl = jnp.asarray(
        np.append(rng.uniform(5, 55, 2), 2.0), jnp.float32
    )
    vl = jnp.asarray(rng.normal(size=3), jnp.float32)
    dense = jax.jit(lambda f, x, v: _rows(f, x, v, "dense"))(forest, xl, vl)
    buck = jax.jit(lambda f, x, v: _rows(f, x, v, "bucketed"))(
        forest, xl, vl
    )
    assert _cbf_equal(dense, buck)

    xs = jnp.asarray(
        np.concatenate([rng.uniform(0, 60, (32, 2)),
                        np.full((32, 1), 2.0)], axis=1), jnp.float32
    )
    vs = jnp.asarray(rng.normal(size=(32, 3)), jnp.float32)
    bd = jax.jit(jax.vmap(lambda x, v: _rows(forest, x, v, "dense")))(xs, vs)
    bb = jax.jit(jax.vmap(lambda x, v: _rows(forest, x, v, "bucketed")))(
        xs, vs
    )
    assert _cbf_equal(bd, bb)


@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_bitwise_parity_vision_cone_masked(ctrl):
    """The controllers' per-agent vision-cone path (sweep once, cone mask
    per agent over the candidate centers) keeps bitwise parity too — for
    both consensus controllers."""
    params, col, state = setup.rqp_setup(4)
    mod = cadmm if ctrl == "cadmm" else dd
    kw = dict(max_iter=2, inner_iters=4)
    cfg_d = mod.make_config(params, col.collision_radius,
                            col.max_deceleration, env_query="dense", **kw)
    cfg_b = mod.make_config(params, col.collision_radius,
                            col.max_deceleration, env_query="bucketed",
                            **kw)
    base_d = cfg_d if ctrl == "cadmm" else cfg_d.base
    base_b = cfg_b if ctrl == "cadmm" else cfg_b.base
    forest = sp.with_grid(
        fo.make_forest(seed=0), base_d.vision_radius + fo.BARK_RADIUS
    )
    state = state.replace(
        xl=jnp.array([28.0, 1.0, 2.0], jnp.float32),
        vl=jnp.array([0.5, 0.2, 0.0], jnp.float32),
    )
    ed = jax.jit(
        lambda s: cadmm.agent_env_cbfs(params, base_d, forest, s)
    )(state)
    eb = jax.jit(
        lambda s: cadmm.agent_env_cbfs(params, base_b, forest, s)
    )(state)
    assert _cbf_equal(ed, eb)
    # Vmapped over perturbed states (the batched-scenario shape).
    xs = jnp.asarray(
        np.random.default_rng(1).normal(size=(8, 3)) * 3
        + np.array([30.0, 0.0, 2.0]), jnp.float32)
    sd = jax.jit(jax.vmap(lambda x: cadmm.agent_env_cbfs(
        params, base_d, forest, state.replace(xl=x))))(xs)
    sb = jax.jit(jax.vmap(lambda x: cadmm.agent_env_cbfs(
        params, base_b, forest, state.replace(xl=x))))(xs)
    assert _cbf_equal(sd, sb)


def test_topk_tie_order_pinned():
    """The deliberate tie-order discipline: lax.top_k breaks equal
    distances toward the SMALLER index, so slabs are stored ascending by
    tree index and a bucketed selection resolves ties exactly like the
    dense sweep's tree-index order. Two mirrored trees produce bitwise-
    equal distances; both impls must pick tree 0's row first."""
    trees = np.array([[33.0, 3.0, 2.0], [33.0, -3.0, 2.0]])
    forest = sp.with_grid(fo.forest_from_tree_pos(trees, 2), QUERY_R)
    xl = jnp.array([33.0, 0.0, 2.0], jnp.float32)
    vl = jnp.array([1.0, 0.0, 0.0], jnp.float32)
    data = fo.capsule_forest_distance(forest, xl, xl, 0.5, VISION)
    assert np.float32(data.dists[0]) == np.float32(data.dists[1])
    # The dense pin: smaller index first on the tie.
    from jax import lax

    _, idx = lax.top_k(jnp.where(data.mask, -data.dists, -jnp.inf), 2)
    assert idx[0] == 0 and idx[1] == 1
    # The bucketed slab stores ascending indices, so its selection ties
    # the same way — rows bitwise equal end to end.
    assert _cbf_equal(_rows(forest, xl, vl, "dense"),
                      _rows(forest, xl, vl, "bucketed"))


# --------------------------- edge cases --------------------------------


def test_zero_range_cone_keep_through_bucketed():
    """vision_cone_mask keeps trees at zero camera range; the bucketed
    per-candidate cone mask (cone_mask_at over gathered centers) must
    preserve that — and the full masked query stays bitwise dense."""
    trees = np.array([[30.0, 0.0, 2.0], [35.0, 1.0, 2.0]])
    forest = sp.with_grid(fo.forest_from_tree_pos(trees, 2), QUERY_R)
    camera = jnp.array([30.0, 0.0], jnp.float32)
    direction = jnp.array([1.0, 0.0], jnp.float32)
    dense_mask = fo.vision_cone_mask(forest, camera, direction, 0.1)
    assert bool(dense_mask[0])  # zero-range keep.
    idx, valid = sp.candidate_slab(
        forest, jnp.array([30.0, 0.0, 2.0], jnp.float32)
    )
    cand_mask = fo.cone_mask_at(
        jnp.take(forest.tree_pos, idx, axis=0), camera, direction, 0.1
    )
    # Per-candidate mask == gathered dense mask (elementwise math).
    assert jnp.array_equal(cand_mask, jnp.take(dense_mask, idx))


def test_exact_axis_contact_normal_through_bucketed():
    """The exact axis-surface-contact radial-fallback normal (the PR-1
    near-contact hardening) must survive the bucketed path: same active
    protective row as dense, bitwise."""
    tree = np.array([[1.0, 0.0, 2.0]])
    forest = sp.with_grid(fo.forest_from_tree_pos(tree, 1), 6.0 + 0.3)
    xl = jnp.array([1.0 - fo.BARK_RADIUS, 0.0, 2.0], jnp.float32)
    cbf = fo.collision_cbf_rows(
        forest, xl, jnp.zeros(3), collision_radius=0.9,
        max_deceleration=2.0, vision_radius=6.0, dist_eps=0.1,
        alpha_env_cbf=1.5, n_rows=4, env_query="bucketed",
    )
    lhs, rhs = np.asarray(cbf.lhs), np.asarray(cbf.rhs)
    act = np.abs(lhs).max(axis=1) > 0
    assert act.any(), "exact contact must keep its protecting row"
    r = int(np.argmax(act))
    assert lhs[r, 0] < 0 and rhs[r] > 0
    dense = fo.collision_cbf_rows(
        forest, xl, jnp.zeros(3), collision_radius=0.9,
        max_deceleration=2.0, vision_radius=6.0, dist_eps=0.1,
        alpha_env_cbf=1.5, n_rows=4, env_query="dense",
    )
    assert _cbf_equal(dense, cbf)


def test_empty_cell_matches_forest_none_semantics():
    """A query landing in an empty/far cell returns the inactive-row
    EnvCBF — exactly the ``forest=None`` contract."""
    forest = sp.with_grid(_city(4096), QUERY_R)
    far = jnp.array([-4000.0, -4000.0, 2.0], jnp.float32)
    v = jnp.array([0.5, 0.0, 0.0], jnp.float32)
    buck = jax.jit(lambda f, u: _rows(f, far, u, "bucketed"))(forest, v)
    none = fo.collision_cbf_rows(
        None, far, v, VISION - 5.0, 2.0, VISION, 0.1, 1.5, 10
    )
    assert _cbf_equal(buck, none)


# ---------------------- zero-cost dense contract -----------------------


def test_dense_hlo_byte_identical():
    """The zero-cost contract (the no_faults()/effort="fixed" pattern):
    a grid-attached forest under env_query="dense" lowers the cadmm
    control step to byte-identical HLO vs a plain forest under the
    pre-knob default config — shipping the bucketed tier cannot perturb
    a dense deployment — while "bucketed" genuinely changes the program
    (sanity that the knob is live)."""
    params, col, state = setup.rqp_setup(4)
    acc = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)
    kw = dict(max_iter=2, inner_iters=4, pad_operators=True)
    cfg0 = cadmm.make_config(params, col.collision_radius,
                             col.max_deceleration, **kw)
    cfg_d = cadmm.make_config(params, col.collision_radius,
                              col.max_deceleration, env_query="dense",
                              **kw)
    cfg_b = cadmm.make_config(params, col.collision_radius,
                              col.max_deceleration, env_query="bucketed",
                              **kw)
    plain = fo.make_forest(seed=0)
    gridded = sp.with_grid(plain, cfg0.vision_radius + fo.BARK_RADIUS)
    cs = cadmm.init_cadmm_state(params, cfg0)
    plan = cadmm.make_plan(params, cfg0)

    def hlo(cfg, forest):
        return jax.jit(
            lambda a, s: cadmm.control(
                params, cfg, f_eq, a, s, acc, forest, plan=plan
            )
        ).lower(cs, state).as_text()

    base = hlo(cfg0, plain)
    assert base == hlo(cfg_d, gridded)
    assert base != hlo(cfg_b, gridded)


# ----------------------------- resolvers -------------------------------


def test_resolve_env_query_gates(monkeypatch):
    monkeypatch.delenv("TAT_ENV_QUERY", raising=False)
    assert sp.resolve_env_query("auto") == "auto"
    assert sp.resolve_env_query(None) == "auto"
    assert sp.resolve_env_query("dense") == "dense"
    assert sp.resolve_env_query("bucketed") == "bucketed"
    with pytest.raises(ValueError, match="env_query"):
        sp.resolve_env_query("grid")
    monkeypatch.setenv("TAT_ENV_QUERY", "bucketed")
    assert sp.resolve_env_query("auto") == "bucketed"
    assert sp.resolve_env_query("dense") == "dense"  # explicit wins.
    monkeypatch.setenv("TAT_ENV_QUERY", "quadtree")
    with pytest.raises(ValueError, match="TAT_ENV_QUERY"):
        sp.resolve_env_query("auto")


def test_runtime_env_query_resolution():
    small = fo.make_forest(seed=0)
    assert sp.runtime_env_query("auto", small) == "dense"
    big = _city(4096)
    with pytest.raises(ValueError, match="no spatial grid"):
        sp.runtime_env_query("auto", big)  # big world needs its grid.
    assert sp.runtime_env_query("auto", sp.with_grid(big, QUERY_R)) \
        == "bucketed"
    with pytest.raises(ValueError, match="no spatial grid"):
        sp.runtime_env_query("bucketed", small)
    assert sp.runtime_env_query("dense", big) == "dense"


def test_coverage_and_rowcount_refusals():
    forest = sp.with_grid(fo.make_forest(seed=0), 3.0)  # short grid.
    xl = jnp.array([30.0, 0.0, 2.0], jnp.float32)
    with pytest.raises(ValueError, match="query_radius"):
        sp.bucketed_distance(forest, xl, xl, 1.0, VISION)
    ok = sp.with_grid(fo.make_forest(seed=0), QUERY_R)
    with pytest.raises(ValueError, match="n_rows"):
        sp.bucketed_distance(ok, xl, xl, 1.0, VISION,
                             n_rows=ok.grid.k + 1)


def test_make_config_resolution(monkeypatch):
    params, col, _ = setup.rqp_setup(4)
    monkeypatch.delenv("TAT_ENV_QUERY", raising=False)
    cfg = cadmm.make_config(params, col.collision_radius,
                            col.max_deceleration)
    assert cfg.env_query == "auto"
    monkeypatch.setenv("TAT_ENV_QUERY", "bucketed")
    cfg = cadmm.make_config(params, col.collision_radius,
                            col.max_deceleration)
    assert cfg.env_query == "bucketed"
    dcfg = dd.make_config(params, col.collision_radius,
                          col.max_deceleration, env_query="dense")
    assert dcfg.base.env_query == "dense"


# -------------------- world parameterization ---------------------------


def test_make_forest_world_size():
    forest = _city(1024, seed=2)
    assert int(forest.num_trees) == 1024
    pos = np.asarray(forest.tree_pos[:1024])
    assert np.isfinite(pos).all()
    assert (pos[:, 2] > 0).all()  # z = (ground + bark_height)/2 > 0.
    # Min spacing holds on the jittered grid.
    from scipy.spatial import cKDTree

    d, _ = cKDTree(pos[:, :2]).query(pos[:, :2], k=2)
    assert d[:, 1].min() >= fo.MIN_DIST_BETWEEN_TREES - 1e-9
    # Determinism.
    assert jnp.array_equal(forest.tree_pos, _city(1024, seed=2).tree_pos)


def test_make_forest_refusals():
    with pytest.raises(ValueError, match="density"):
        fo.make_forest(seed=0, world_size=100.0, density=0.2)
    with pytest.raises(ValueError, match="max_trees"):
        fo.make_forest(seed=0, max_trees=100, world_size=100.0,
                       density=0.085)
    with pytest.raises(ValueError, match="world_size"):
        fo.make_forest(seed=0, density=0.05)
    with pytest.raises(ValueError, match="max_trees"):
        fo.forest_from_tree_pos(np.zeros((5, 3)), 5, max_trees=4)


def test_grid_survives_rollout_pytree():
    """The grid rides the Forest pytree: tree-mapping the forest (the
    rollout/serving plumbing shape) preserves the bucketed query."""
    forest = sp.with_grid(fo.make_forest(seed=0), QUERY_R)
    moved = jax.tree.map(lambda x: x + 0 if x.dtype != bool else x, forest)
    xl = jnp.array([30.0, 0.0, 2.0], jnp.float32)
    vl = jnp.array([0.5, 0.0, 0.0], jnp.float32)
    assert _cbf_equal(_rows(forest, xl, vl, "bucketed"),
                      _rows(moved, xl, vl, "bucketed"))
