"""Smoke tests for the host-side plotting / scene-rendering layer."""

import os

import numpy as np
import pytest


def _fake_logs(T=20, n=3):
    rng = np.random.default_rng(0)
    eye = np.tile(np.eye(3), (T, 1, 1))
    return {
        "n": n,
        "dt": 1e-3,
        "T": 2.0,
        "hl_rel_freq": 10,
        "log_freq": 10,
        "state_seq": {
            "xl": np.cumsum(rng.normal(size=(T, 3)) * 0.05, axis=0),
            "vl": rng.normal(size=(T, 3)) * 0.1,
            "Rl": eye,
            "wl": np.zeros((T, 3)),
            "R": np.tile(np.eye(3), (T, n, 1, 1)),
            "w": np.zeros((T, n, 3)),
        },
        "x_err_seq": np.abs(rng.normal(size=T)),
        "v_err_seq": np.abs(rng.normal(size=T)),
        "iter_seq": rng.integers(1, 20, T),
        "min_env_dist_seq": np.abs(rng.normal(size=T)) + 0.2,
        "tree_pos": rng.normal(size=(5, 3)) * 3,
    }


def test_plots_render(tmp_path):
    from tpu_aerial_transport.viz import plots

    logs = _fake_logs()
    plots.plot_tracking_errors(logs, str(tmp_path / "t.png"))
    plots.plot_solver_stats(logs, str(tmp_path / "s.png"))
    plots.plot_xy_trajectory(logs, str(tmp_path / "xy.png"))
    errs = np.abs(np.random.default_rng(1).normal(size=(10, 25)))
    errs[:, 15:] = np.nan
    plots.plot_convergence_rates({"C-ADMM": errs, "DD": errs * 0.5},
                                 str(tmp_path / "c.png"))
    for f in ("t.png", "s.png", "xy.png", "c.png"):
        assert (tmp_path / f).stat().st_size > 0


def test_plots_all_nan_convergence_column_no_warning(tmp_path):
    """All-NaN iteration columns (no sample reached that iteration) must not
    emit RuntimeWarnings (VERDICT round-1 weak #8)."""
    import warnings

    from tpu_aerial_transport.viz import plots

    errs = np.full((4, 10), np.nan)
    errs[:, :3] = 1.0
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        plots.plot_convergence_rates({"C-ADMM": errs}, str(tmp_path / "c.png"))
    assert (tmp_path / "c.png").stat().st_size > 0


@pytest.mark.parametrize(
    "ctype", ["centralized", "consensus-admm", "dual-decomposition"]
)
def test_paper_figures_render(tmp_path, ctype):
    """Full paper-figure parity path: key-frame overlays (payload polygon,
    quad footprints, braking capsule, vision cones) + 600-dpi min-dist figure
    (reference rqp_plots.py:173-390, 393-467)."""
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import plots

    params, col, _ = setup.rqp_setup(3)
    logs = _fake_logs()
    xy = tmp_path / f"xy_{ctype}.png"
    plots.plot_xy_trajectory(
        logs, str(xy), params=params, collision=col, controller_type=ctype,
        dpi=600,
    )
    md = tmp_path / f"min_dist_{ctype}.png"
    plots.plot_min_dist(logs, str(md), dist_eps=0.1, dpi=600)
    assert xy.stat().st_size > 0 and md.stat().st_size > 0


def test_scene_frames(tmp_path):
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import scene

    params, col, _ = setup.rqp_setup(3)
    logs = _fake_logs()
    frames = scene.render_frames(
        logs, params, col.payload_vertices, str(tmp_path / "frames"), stride=10
    )
    assert len(frames) == 2
    assert all(os.path.getsize(f) > 0 for f in frames)
    scene.render_ghost_snapshot(
        logs, params, col.payload_vertices, str(tmp_path / "ghost.png"),
        times=[0, 10, 19],
    )
    assert (tmp_path / "ghost.png").stat().st_size > 0


def test_meshcat_backend_optional():
    pytest.importorskip("meshcat")
    from tpu_aerial_transport.viz.scene import MeshcatBackend  # noqa: F401


def test_quadrotor_mesh_and_forest_scene(tmp_path):
    """Procedural quadrotor mesh (replaces the reference's objs/quadrotor.obj)
    is a valid triangle mesh; full 3-D scene (mesh quads + forest with cones,
    ground, mountain) renders."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import scene

    mv, mf = scene.quadrotor_mesh()
    assert mv.ndim == 2 and mv.shape[1] == 3 and len(mv) > 50
    assert mf.ndim == 2 and mf.shape[1] == 3
    assert mf.min() >= 0 and mf.max() < len(mv)

    params, col, _ = setup.rqp_setup(3)
    forest = forest_mod.make_forest(seed=0, max_trees=12)

    fig = plt.figure(figsize=(4, 3))
    ax = fig.add_subplot(projection="3d")

    class _S:
        xl = np.array([30.0, 0.0, 2.0])
        Rl = np.eye(3)
        R = np.tile(np.eye(3), (3, 1, 1))

    # Force arrows: the reference's optional _DRAW_FORCE_ARROWS overlay —
    # include a near-zero force to exercise the min-length floor.
    forces = np.array([[0.0, 0.0, 5.0], [0.5, 0.0, 4.0], [0.0, 1e-12, 0.0]])
    scene.draw_snapshot(ax, params, col.payload_vertices, _S(), forest=forest,
                        quad_mesh=True, forces=forces)
    out = tmp_path / "scene3d.png"
    fig.savefig(str(out))
    plt.close(fig)
    assert out.stat().st_size > 0


def test_rotation_y_to():
    """Minimal rotation taking +y onto an arbitrary unit direction: proper
    orthogonal, maps y exactly, antipodal -y handled."""
    from tpu_aerial_transport.viz.scene import _rotation_y_to

    rng = np.random.default_rng(3)
    dirs = rng.normal(size=(20, 3))
    dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
    dirs = np.concatenate([dirs, [[0, 1, 0.0]], [[0, -1, 0.0]],
                           [[0, 0, 1.0]]])
    for d in dirs:
        R = _rotation_y_to(d)
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-12)
        assert np.linalg.det(R) > 0.99
        np.testing.assert_allclose(R @ np.array([0, 1, 0.0]), d, atol=1e-9)


def test_meshcat_force_arrow_geometry(monkeypatch):
    """Solid cylinder+cone force arrows (reference rigid_payload.py:249-274
    update path) against a stub meshcat: shaft height = max(|f|*scaling,
    min-length), shaft centered at root + L/2 d, head at root + (L + h/2) d,
    zero force points +z at min length."""
    import sys
    import types

    calls = {}

    class _Rec:
        def __init__(self, path):
            self.path = path

        def set_object(self, geom, *a):
            calls.setdefault(self.path, {})["geom"] = geom

        def set_transform(self, T):
            calls.setdefault(self.path, {})["T"] = np.array(T)

    class _Vis:
        def __getitem__(self, path):
            return _Rec(path)

    class _Cyl:
        def __init__(self, height, radius=None, radiusBottom=None,
                     radiusTop=None):
            self.height = height
            self.radius = radius

    gm = types.ModuleType("meshcat.geometry")
    gm.Cylinder = _Cyl
    tfm = types.ModuleType("meshcat.transformations")

    def _tl(v):
        T = np.eye(4)
        T[:3, 3] = np.asarray(v, float)
        return T

    tfm.translation_matrix = _tl
    mc = types.ModuleType("meshcat")
    mc.geometry = gm
    mc.transformations = tfm
    monkeypatch.setitem(sys.modules, "meshcat", mc)
    monkeypatch.setitem(sys.modules, "meshcat.geometry", gm)
    monkeypatch.setitem(sys.modules, "meshcat.transformations", tfm)

    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.viz import scene

    params, _, _ = setup.rqp_setup(3)
    backend = scene.MeshcatBackend.__new__(scene.MeshcatBackend)
    backend.vis = _Vis()
    backend._objs = set()

    xl = np.array([1.0, 2.0, 3.0])
    Rl = np.eye(3)
    forces = np.array([[0.0, 0.0, 4.0], [3.0, 0.0, 0.0], [0.0, 0.0, 0.0]])
    backend._update_force_arrows(params, xl, Rl, forces)

    r = np.asarray(params.r)
    for i, (f, d_exp) in enumerate(zip(
        forces, [[0, 0, 1.0], [1.0, 0, 0], [0, 0, 1.0]]
    )):
        L = max(np.linalg.norm(f) * scene.FORCE_SCALING,
                scene.FORCE_MIN_LENGTH)
        root = xl + Rl @ r[i]
        tail = calls[f"force_tail_{i}"]
        head = calls[f"force_head_{i}"]
        # Unit-height shaft, re-posed per frame: the length rides in the
        # transform as a y-axis scale (no per-frame geometry re-uploads).
        assert abs(tail["geom"].height - 1.0) < 1e-12
        np.testing.assert_allclose(
            tail["T"][:3, 3], root + 0.5 * L * np.array(d_exp), atol=1e-9
        )
        np.testing.assert_allclose(
            head["T"][:3, 3],
            root + (L + 0.5 * scene.FORCE_HEAD_LENGTH) * np.array(d_exp),
            atol=1e-9,
        )
        # Cylinder axis (+y) maps onto the force direction, scaled to the
        # arrow length; the cross axes stay unit (radius unscaled).
        np.testing.assert_allclose(
            tail["T"][:3, :3] @ np.array([0, 1, 0.0]),
            L * np.array(d_exp), atol=1e-9,
        )
        for axis in ([1.0, 0, 0], [0, 0, 1.0]):
            assert abs(np.linalg.norm(tail["T"][:3, :3] @ np.array(axis))
                       - 1.0) < 1e-9
