"""jaxlint tests: Tier-A rules fire on their seeded fixtures (exact rule
id + line) and stay silent on the clean twins and on the package; the CLI
runs without importing jax; the Tier-B registry covers every public hot
entrypoint; and the contract checks detect seeded violations.

tests/fixtures/jaxlint/ holds one ``jlXXX_bad.py`` per rule with
``# expect: JLXXX`` markers on the violating lines, plus a ``jlXXX_ok.py``
clean twin that must produce zero findings.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from tpu_aerial_transport.analysis import contracts, entrypoints, linter
from tpu_aerial_transport.analysis.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "tpu_aerial_transport")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jaxlint")
JAXLINT = os.path.join(REPO, "tools", "jaxlint.py")

_EXPECT_RE = re.compile(r"#\s*expect:\s*(JL\d{3})")


def _expected(path):
    out = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            for rule in _EXPECT_RE.findall(line):
                out.append((rule, lineno))
    return out


def _fixture_files(kind):
    return sorted(
        os.path.join(FIXTURES, f)
        for f in os.listdir(FIXTURES)
        if f.endswith(f"_{kind}.py")
    )


# ----------------------------- Tier A ---------------------------------

def test_every_rule_has_a_seeded_fixture():
    covered = set()
    for path in _fixture_files("bad"):
        covered.update(r for r, _ in _expected(path))
    assert covered == set(RULES), (
        f"rules without a seeded-violation fixture: {set(RULES) - covered}"
    )
    assert len(RULES) >= 8  # ISSUE 2 acceptance: >= 8 distinct rules.


@pytest.mark.parametrize(
    "path", _fixture_files("bad"), ids=lambda p: os.path.basename(p)
)
def test_seeded_violations_fire_at_exact_lines(path):
    findings = {(f.rule, f.line) for f in linter.lint_file(path)}
    expected = set(_expected(path))
    assert expected, f"fixture {path} declares no expectations"
    missing = expected - findings
    assert not missing, (
        f"seeded violations not detected: {sorted(missing)}; "
        f"got {sorted(findings)}"
    )


@pytest.mark.parametrize(
    "path", _fixture_files("ok"), ids=lambda p: os.path.basename(p)
)
def test_clean_twins_produce_no_findings(path):
    findings = linter.lint_file(path)
    assert not findings, [f.render() for f in findings]


def test_package_lints_clean():
    findings = linter.lint_paths([PKG])
    assert not findings, "\n".join(f.render() for f in findings)


def test_pragma_suppresses_rule(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))  # jaxlint: disable=JL001\n"
    )
    p = tmp_path / "pragma_case.py"
    p.write_text(src)
    assert linter.lint_file(str(p)) == []
    # Without the pragma the same line fires.
    p.write_text(src.replace("  # jaxlint: disable=JL001", ""))
    assert [f.rule for f in linter.lint_file(str(p))] == ["JL001"]


def test_entry_seeds_resolve_from_relative_paths():
    """Linting `control/cadmm.py` from inside the package dir must still
    seed the entrypoint table (suffix matching happens on the ABSOLUTE
    path) — otherwise a relative invocation silently analyzes without
    traced context and passes on anything."""
    cwd = os.getcwd()
    os.chdir(PKG)
    try:
        assert "control" in linter.entry_names_for("control/cadmm.py")
    finally:
        os.chdir(cwd)


def test_tracer_guard_exempts_only_the_host_branch(tmp_path):
    src = (
        "import jax\nimport jax.numpy as jnp\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if isinstance(x, jax.core.Tracer):\n"
        "        y = float(jnp.sum(x))  # traced branch: REAL bug\n"
        "    else:\n"
        "        y = float(np.sum(np.asarray(x)))  # host branch: fine\n"
        "    return y\n"
    )
    p = tmp_path / "guard_case.py"
    p.write_text(src)
    findings = linter.lint_file(str(p))
    assert [(f.rule, f.line) for f in findings] == [("JL001", 8)], [
        f.render() for f in findings
    ]


def test_cli_json_format_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--format", "json", FIXTURES],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["errors"] > 0
    assert sorted(RULES) == payload["rules"]
    clean = subprocess.run(
        [sys.executable, JAXLINT, PKG], capture_output=True, text=True,
        cwd=REPO,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr


def test_cli_tier_a_never_imports_jax():
    """The lint must run on boxes with no accelerator stack: --assert-no-jax
    makes the CLI itself fail (exit 2) if jax ended up in sys.modules."""
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--assert-no-jax", PKG],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_ci_check_script_passes():
    """tier-1 exercises the same entry CI and humans run."""
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "ci_check.sh")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ci_check: OK" in proc.stdout


# ----------------------------- Tier B ---------------------------------

def test_registry_matches_entrypoint_table():
    assert set(contracts.REGISTRY) == set(entrypoints.CONTRACT_ENTRYPOINTS)


def test_registry_covers_every_public_hot_function():
    """A new public function containing lax.scan/while_loop/fori_loop must
    either get a Tier-B contract or an explicit HOT_NON_ENTRYPOINTS entry
    with a reason — it cannot land unregistered."""
    hot = linter.public_hot_functions([PKG])
    assert hot, "hot-function scan found nothing — scanner broken?"
    covered_modules = set()
    for name in contracts.REGISTRY:
        mod, _, fn = name.partition(":")
        covered_modules.add(
            ("tpu_aerial_transport/" + mod.replace(".", "/") + ".py", fn)
        )
    uncovered = []
    for key in hot:
        path, _, fn = key.partition(":")
        suffix = path.split("tpu_aerial_transport/", 1)[-1]
        rel = "tpu_aerial_transport/" + suffix
        if (rel, fn) in covered_modules:
            continue
        if f"{rel}:{fn}" in entrypoints.HOT_NON_ENTRYPOINTS:
            continue
        uncovered.append(f"{rel}:{fn}")
    assert not uncovered, (
        "public hot functions with neither a Tier-B contract nor a "
        f"HOT_NON_ENTRYPOINTS waiver: {uncovered}"
    )


def test_tile_waivers_reference_registered_entrypoints():
    unknown = set(entrypoints.TILE_WAIVERS) - set(contracts.REGISTRY)
    assert not unknown, f"TILE_WAIVERS for unknown entrypoints: {unknown}"


def test_contracts_fast_subset():
    """The solver core + one consensus controller + one rollout, on every
    tier-1 run (the full registry runs under -m slow and via
    `tools/jaxlint.py --contracts`)."""
    findings = contracts.run_contracts(names=contracts.FAST_SUBSET)
    assert not findings, "\n".join(f.render() for f in findings)


@pytest.mark.slow
def test_contracts_full_registry():
    findings = contracts.run_contracts()
    assert not findings, "\n".join(f.render() for f in findings)


def test_tc101_detects_identity_leaking_static():
    """A static argument hashed by object identity must trip the
    no-retrace contract (the exact bug class TC101 exists for)."""
    import jax
    import jax.numpy as jnp

    class LeakyCfg:  # default __hash__/__eq__: object identity.
        pass

    def build():
        fn = jax.jit(lambda cfg, x: x * 2.0, static_argnums=0)

        def make_args():
            return (LeakyCfg(), jnp.ones(3))

        return fn, make_args

    c = contracts.Contract(name="test:leaky", build=build)
    # The other checks trace through make_jaxpr/lower, which cannot
    # abstractify the deliberately-unhashable-by-value static — TC101 is
    # the check under test here.
    rules_fired = {
        f.rule for f in contracts.check_entry(
            c, disabled=frozenset({"TC102", "TC103", "TC104"})
        )
    }
    assert rules_fired == {"TC101"}


def test_tc102_detects_seeded_f64_text():
    bad = "func.func @main(%arg0: tensor<3xf64>) { stablehlo.add }"
    assert [f.rule for f in contracts.scan_lowered_text(bad, "syn")] \
        == ["TC102"]
    clean = "func.func @main(%arg0: tensor<3xf32>) { stablehlo.dot_general }"
    assert contracts.scan_lowered_text(clean, "syn") == []


def test_tc103_flags_callbacks_but_not_debug_print():
    """pure_callback/io_callback and jax.debug.print all lower to the SAME
    custom_call target, so TC103 works at the jaxpr-primitive level —
    following JL011's advice (debug.print) must NOT trip the contract."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def with_cb(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((3,), jnp.float32), x,
        )

    def with_debug(x):
        jax.debug.print("v={v}", v=x[0])
        return x * 2

    x = jnp.ones(3)
    assert contracts.callback_primitives(
        jax.make_jaxpr(with_cb)(x)) == ["pure_callback"]
    assert contracts.callback_primitives(
        jax.make_jaxpr(with_debug)(x)) == []


def test_tc104_flags_long_misaligned_contraction():
    """TC104 v2 (enforced): a LONG contraction (>= MIN_ALIGNED_CONTRACT)
    over a non-sublane-multiple dim is an ERROR finding; short contractions
    and misaligned FREE dims (the folded batch supplies the lane axis) are
    exempt."""
    import jax.numpy as jnp
    import numpy as np

    def build(k):
        A = jnp.asarray(np.ones((9, k), np.float32))

        def fn(x):
            return A @ x  # contraction over k.

        def make_args():
            return (jnp.ones((k,), jnp.float32),)

        return fn, make_args

    # k = 130: long misaligned contraction -> error-severity finding.
    c = contracts.Contract(name="test:unaligned", build=lambda: build(130))
    findings = [f for f in contracts.check_entry(c) if f.rule == "TC104"]
    assert findings and findings[0].severity == "error"
    # k = 128: aligned contraction -> clean, even though the free dim is 9
    # (free-dim alignment comes from the folded batch, not the instance).
    c = contracts.Contract(name="test:aligned", build=lambda: build(128))
    assert not [f for f in contracts.check_entry(c) if f.rule == "TC104"]
    # k = 12: short misaligned contraction (3-vector/equality-block class)
    # -> exempt below MIN_ALIGNED_CONTRACT.
    c = contracts.Contract(name="test:short", build=lambda: build(12))
    assert not [f for f in contracts.check_entry(c) if f.rule == "TC104"]


# ------------------- TC106: off-chip TPU lowering gate -----------------

def test_tc106_seeded_f64_fixture_fails_offchip():
    """The r02 acceptance contract: a seeded f64/convert_element_type
    entrypoint must FAIL the TPU-target lowering gate on this CPU-only
    host — the bug class that previously surfaced only at first dispatch
    on a chip now fails tier-1 anywhere. The clean f32 twin passes."""
    import jax

    sys.path.insert(0, os.path.join(REPO, "tests", "fixtures"))
    try:
        import contracts_f64 as fx
    finally:
        sys.path.pop(0)

    with jax.experimental.enable_x64():
        seeded = contracts.Contract(name="fixture:f64_convert",
                                    build=fx.build)
        findings = contracts.check_entry_lowering(seeded, target="tpu")
        assert [f.rule for f in findings] == ["TC106"]
        assert "f64" in findings[0].message
        clean = contracts.Contract(name="fixture:f32_clean",
                                   build=fx.build_ok)
        assert contracts.check_entry_lowering(clean, target="tpu") == []


def test_tc106_lowering_failure_is_classified():
    """A lowering EXCEPTION (not just an f64 type) is the other face of
    the gate; the finding names the backend-error class a chip would
    have hit at dispatch."""

    def build():
        def fn(x):
            raise RuntimeError(
                "Mosaic lowering failed: unsupported op"
            )

        def make_args():
            import jax.numpy as jnp

            return (jnp.ones((4,), jnp.float32),)

        return fn, make_args

    c = contracts.Contract(name="fixture:lowering_boom", build=build)
    findings = contracts.check_entry_lowering(c, target="tpu")
    assert [f.rule for f in findings] == ["TC106"]
    assert "compile_error" in findings[0].message


def test_tc106_fast_subset_lowers_clean_for_tpu():
    """Solver core + consensus controller + rollout TPU-lower cleanly on
    every tier-1 run (the full registry runs under -m slow and via
    `tools/jaxlint.py --contracts --target tpu`)."""
    findings = contracts.run_lowering_gate(
        names=list(contracts.FAST_SUBSET), target="tpu"
    )
    assert not findings, "\n".join(f.render() for f in findings)


@pytest.mark.slow
def test_tc106_full_registry_lowers_for_tpu():
    findings = contracts.run_lowering_gate(target="tpu")
    assert not findings, "\n".join(f.render() for f in findings)


def test_tc106_disabled_and_waived_entries_skipped(monkeypatch):
    boom = contracts.Contract(
        name="fixture:waived",
        build=lambda: (_ for _ in ()).throw(AssertionError("not built")),
    )
    assert contracts.check_entry_lowering(
        boom, disabled=frozenset({"TC106"})) == []
    monkeypatch.setitem(entrypoints.LOWERING_WAIVERS, "fixture:waived",
                        "test waiver")
    assert contracts.check_entry_lowering(boom) == []


def test_lowering_waivers_reference_registered_entrypoints():
    unknown = set(entrypoints.LOWERING_WAIVERS) - set(contracts.REGISTRY)
    assert not unknown, f"LOWERING_WAIVERS for unknown entrypoints: {unknown}"


def test_cli_target_tpu_mode(tmp_path):
    """`jaxlint --target tpu --only <entry>` runs the lowering gate from
    the CLI (tier B implied); an unknown --only name is a usage error."""
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = linter.main(
        ["--target", "tpu", "--only", "ops.socp:solve_socp", str(clean)]
    )
    assert rc == 0
    rc = linter.main(["--target", "tpu", "--only", "no.such:entry",
                      str(clean)])
    assert rc == 1
