"""Fault-schedule semantics, per-step controller graceful degradation, and
the zero-cost-when-disabled guarantee (identical HLO with
``FaultSchedule.none``-style ``no_faults``)."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport import resilience
from tpu_aerial_transport.control import cadmm, centralized, dd, lowlevel
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.rollout import resilient_rollout

GRAVITY = rqp.GRAVITY


def test_schedule_evaluation_semantics():
    n = 4
    sched = faults_mod.make_schedule(
        n,
        t_fail={1: 10},
        t_degrade={2: 5},
        thrust_scale=0.6,
        drop_rate=0.5,
        drop_hold=3,
        key=jax.random.PRNGKey(0),
    )
    h0 = faults_mod.fault_step(sched, 0)
    assert bool(jnp.all(h0.alive))
    assert float(h0.thrust_scale[2]) == 1.0  # not yet degraded.
    h7 = faults_mod.fault_step(sched, 7)
    assert abs(float(h7.thrust_scale[2]) - 0.6) < 1e-6  # degraded from 5.
    assert bool(h7.alive[1])
    h12 = faults_mod.fault_step(sched, 12)
    assert not bool(h12.alive[1])  # dead from step 10.
    assert float(h12.thrust_scale[1]) == 0.0
    assert not bool(h12.msg_ok[1])  # the dead never transmit.
    # Dropout draws are constant within each drop_hold block (staleness
    # window) and deterministic under replay.
    for t0 in (0, 3, 6):  # block starts before any agent dies at 10.
        block = [faults_mod.fault_step(sched, t0 + k).msg_ok for k in range(3)]
        for b in block[1:]:
            assert bool(jnp.all(b == block[0]))
    again = faults_mod.fault_step(sched, 7)
    assert bool(jnp.all(again.msg_ok == h7.msg_ok))


def test_masked_equilibrium_redistributes():
    params, _, _ = setup.rqp_setup(4)
    alive = jnp.array([False, True, True, True])
    f_eq = centralized.equilibrium_forces(params, alive)
    mTg = float(params.mT) * GRAVITY
    assert float(jnp.abs(f_eq[0]).max()) == 0.0  # dead agent carries nothing.
    assert abs(float(jnp.sum(f_eq[:, 2])) - mTg) < 1e-3 * mTg
    # Healthy mask reproduces the nominal distribution.
    f_all = centralized.equilibrium_forces(params, jnp.ones(4, bool))
    f_nom = centralized.equilibrium_forces(params)
    assert float(jnp.abs(f_all - f_nom).max()) < 1e-4


def test_lowlevel_thrust_scale_and_zero_fdes_guard():
    params, _, state = setup.rqp_setup(3)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    f_des = jnp.tile(jnp.array([0.0, 0.0, 5.0]), (3, 1))
    scale = jnp.array([0.0, 0.5, 1.0])
    f, M = ll.control(state, f_des, scale)
    assert float(jnp.abs(f[0])) == 0.0 and float(jnp.abs(M[0]).max()) == 0.0
    assert abs(float(f[1]) - 2.5) < 1e-5
    assert abs(float(f[2]) - 5.0) < 1e-5
    # Zero desired force (a dead agent's masked command) must not emit NaNs.
    f2, M2 = ll.control(state, f_des.at[0].set(0.0))
    assert bool(jnp.all(jnp.isfinite(f2))) and bool(jnp.all(jnp.isfinite(M2)))


def _one_step(mod, make_cfg, init_state, n=4, health=None):
    params, col, state = setup.rqp_setup(n)
    cfg = make_cfg(
        params, col.collision_radius, col.max_deceleration,
        max_iter=10, inner_iters=20,
    )
    alive = None if health is None else health.alive
    f_eq = centralized.equilibrium_forces(params, alive)
    cs = init_state(params, cfg)
    acc_des = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))
    f, cs, stats = mod.control(
        params, cfg, f_eq, cs, state, acc_des, health=health
    )
    return params, f, stats


def test_cadmm_health_step_dead_agent():
    n = 4
    sched = faults_mod.make_schedule(n, t_fail={0: 0})
    health = faults_mod.fault_step(sched, 0)
    params, f, stats = _one_step(
        cadmm, cadmm.make_config, cadmm.init_cadmm_state, n, health
    )
    assert bool(jnp.all(jnp.isfinite(f)))
    assert float(jnp.abs(f[0]).max()) == 0.0  # the corpse applies nothing.
    mTg = float(params.mT) * GRAVITY
    tot = float(jnp.sum(f[1:, 2]))
    assert 0.7 * mTg < tot < 1.3 * mTg  # survivors carry the payload.


def test_dd_health_step_dead_agent():
    n = 4
    sched = faults_mod.make_schedule(n, t_fail={0: 0})
    health = faults_mod.fault_step(sched, 0)
    params, f, stats = _one_step(
        dd, dd.make_config, dd.init_dd_state, n, health
    )
    assert bool(jnp.all(jnp.isfinite(f)))
    assert float(jnp.abs(f[0]).max()) == 0.0
    mTg = float(params.mT) * GRAVITY
    tot = float(jnp.sum(f[1:, 2]))
    assert 0.7 * mTg < tot < 1.3 * mTg


def test_disabled_faults_compile_to_identical_hlo():
    """The acceptance bar for zero-cost fault support: the nominal rollout
    and a ``no_faults`` rollout lower to the SAME HLO (``active`` is static
    and every fault branch is Python-level)."""
    n = 4
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=4, inner_iters=10,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    sched = faults_mod.no_faults(n)

    def run(faults):
        return jax.jit(
            lambda s, c: resilient_rollout(
                hl, ll.control, params, s, c, n_hl_steps=3, faults=faults
            )
        ).lower(state0, cs0).as_text()

    assert run(None) == run(sched)


def test_dropout_holds_last_delivered_snapshot_across_steps():
    """Staleness is LAST-DELIVERED, not one-step-delayed: across a multi-
    step dropout window, the peers' view of the dropped agent (the ``held``
    snapshot) stays frozen at its last delivered copy even though the agent
    keeps iterating locally."""
    n = 4
    params, col, state = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=8, inner_iters=15,
    )
    f_eq = centralized.equilibrium_forces(params)
    cs = cadmm.init_cadmm_state(params, cfg).replace(
        held=jnp.tile(f_eq, (n, 1, 1))
    )
    acc = (jnp.array([0.3, 0.0, 0.0]), jnp.zeros(3))
    alive = jnp.ones(n, bool)
    ok_all = faults_mod.FaultStep(
        alive=alive, thrust_scale=jnp.ones(n), msg_ok=alive
    )
    drop0 = ok_all.replace(msg_ok=alive.at[0].set(False))

    # Step A: everything delivered -> held == the published copies.
    _, csA, _ = cadmm.control(params, cfg, f_eq, cs, state, acc, health=ok_all)
    assert bool(jnp.all(csA.held == csA.f))
    snapshot = csA.held[0]

    # Steps B, C: agent 0 dropped while the problem moves (new acc target).
    acc2 = (jnp.array([0.0, 0.4, 0.1]), jnp.zeros(3))
    _, csB, _ = cadmm.control(params, cfg, f_eq, csA, state, acc2, health=drop0)
    _, csC, _ = cadmm.control(params, cfg, f_eq, csB, state, acc2, health=drop0)
    # Agent 0 kept iterating locally...
    assert float(jnp.abs(csC.f[0] - snapshot).max()) > 1e-5
    # ...but its held snapshot (what the peers consume) never moved.
    assert bool(jnp.all(csB.held[0] == snapshot))
    assert bool(jnp.all(csC.held[0] == snapshot))
    # Delivered agents' snapshots track their fresh copies.
    assert bool(jnp.all(csC.held[1:] == csC.f[1:]))
