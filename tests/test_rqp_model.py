"""Property tests for the RQP system model.

Mirrors the reference's checkable properties (SURVEY.md §4) with asserted tolerances:
- inverse-dynamics residual of forward dynamics ~ 0 (test/system/test_rqpdynamics.py),
- manifold integrator tracks an analytic trajectory (test/system/test_rqpstate.py),
- rotations stay on SO(3) through long rollouts.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _random_params(key, n=3, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    m = 0.4 + 0.2 * jax.random.uniform(k1, (n,))
    J = jnp.broadcast_to(jnp.diag(jnp.array([2.32e-3, 2.32e-3, 4e-3])), (n, 3, 3))
    ml = jnp.asarray(0.225)
    Jl = jnp.diag(jnp.array([2.1e-2, 1.87e-2, 3.97e-2]))
    ang = 2 * jnp.pi * jnp.arange(n) / n
    r = jnp.stack([jnp.cos(ang), jnp.sin(ang), jnp.zeros(n)], axis=-1) * 0.5
    r = r + 0.01 * jax.random.normal(k2, (n, 3))
    return rqp.rqp_params(m, J, ml, Jl, r, dtype=dtype)


def _random_state(key, n=3):
    ks = jax.random.split(key, 6)
    return rqp.rqp_state(
        R=lie.expm_so3(jax.random.normal(ks[0], (n, 3)) * 0.5),
        w=jax.random.normal(ks[1], (n, 3)),
        xl=jax.random.normal(ks[2], (3,)),
        vl=jax.random.normal(ks[3], (3,)),
        Rl=lie.expm_so3(jax.random.normal(ks[4], (3,)) * 0.5),
        wl=jax.random.normal(ks[5], (3,)),
    )


@pytest.mark.parametrize("n", [3, 4, 8])
def test_inverse_dynamics_residual(n):
    """forward_dynamics output must zero the Newton-Euler residual (the reference's
    self-consistency oracle, test_rqpdynamics.py:57-61)."""
    key = jax.random.PRNGKey(0)
    params = _random_params(key, n)
    for seed in range(5):
        ks = jax.random.split(jax.random.PRNGKey(seed + 1), 3)
        state = _random_state(ks[0], n)
        f = 2.0 + jax.random.uniform(ks[1], (n,))
        M = 0.1 * jax.random.normal(ks[2], (n, 3))
        acc = rqp.forward_dynamics(params, state, (f, M))
        err = rqp.inverse_dynamics_error(state, params, (f, M), acc)
        # Scale-relative tolerance: f32 path, residual is ~eps * ||terms||.
        assert float(err) < 1e-4, f"residual {err} at seed {seed}"


def _analytic_trajectory(t, n):
    """Closed-form (state, acc) trajectory (reference test_rqpstate.py:9-44 pattern):
    sinusoidal translation + spinning attitude, all agents sharing the payload
    rotation."""
    k1, k2 = jnp.pi / 2, 2 / 3 * jnp.pi
    a, b = k1 * t, k2 * t
    xl = jnp.stack([jnp.cos(a), jnp.sin(a), jnp.sin(b)])
    vl = jnp.stack([-jnp.sin(a) * k1, jnp.cos(a) * k1, jnp.cos(b) * k2])
    dvl = jnp.stack(
        [-jnp.cos(a) * k1**2, -jnp.sin(a) * k1**2, -jnp.sin(b) * k2**2]
    )
    ang = (2 * jnp.pi) * jnp.sin(jnp.pi / 2 * t)
    dang = jnp.pi**2 * jnp.cos(jnp.pi / 2 * t)
    ddang = -(jnp.pi**3) / 2 * jnp.sin(jnp.pi / 2 * t)
    e3 = jnp.array([0.0, 0.0, 1.0])
    Rl = lie.expm_so3(ang * e3)
    wl = dang * e3
    dwl = ddang * e3
    R = jnp.broadcast_to(Rl, (n, 3, 3))
    w = jnp.broadcast_to(wl, (n, 3))
    dw = jnp.broadcast_to(dwl, (n, 3))
    state = rqp.RQPState(
        R=R, w=w, xl=xl, vl=vl, Rl=Rl, wl=wl, step=jnp.zeros((), jnp.int32)
    )
    return state, (dw, dvl, dwl)


def test_integrator_tracks_analytic_trajectory():
    n, dt, T = 4, 1e-3, 2.0
    steps = int(T / dt)
    state0, _ = _analytic_trajectory(0.0, n)

    def body(state, t):
        _, acc = _analytic_trajectory(t, n)
        return rqp.integrate_state(state, acc, dt), None

    ts = jnp.arange(steps) * dt
    final, _ = jax.lax.scan(body, state0, ts)
    ref, _ = _analytic_trajectory(T, n)
    assert jnp.abs(final.xl - ref.xl).max() < 5e-3
    assert jnp.abs(final.vl - ref.vl).max() < 5e-3
    assert jnp.abs(final.Rl - ref.Rl).max() < 2e-2
    assert jnp.abs(final.R - ref.R).max() < 2e-2


def test_rotations_stay_orthonormal_long_rollout():
    """2000 hover-ish steps: periodic Newton-Schulz projection must keep R in SO(3)."""
    n = 3
    key = jax.random.PRNGKey(7)
    params = _random_params(key, n)
    state = _random_state(jax.random.PRNGKey(8), n)
    hover_f = jnp.full((n,), float(params.mT) * rqp.GRAVITY / n)
    M = jnp.zeros((n, 3))

    def body(s, _):
        return rqp.integrate(params, s, (hover_f, M), 1e-3), None

    final, _ = jax.lax.scan(body, state, None, length=2000)
    eye = jnp.eye(3)
    err_R = jnp.abs(jnp.swapaxes(final.R, -1, -2) @ final.R - eye).max()
    err_Rl = jnp.abs(final.Rl.T @ final.Rl - eye).max()
    assert err_R < 1e-4 and err_Rl < 1e-4


def test_com_free_fall_invariant():
    """With zero thrust the CoM must free-fall: dv_com = g exactly, independent of
    attitude/spin (checks the composite-inertia bookkeeping)."""
    n = 3
    params = _random_params(jax.random.PRNGKey(0), n)
    state = _random_state(jax.random.PRNGKey(5), n)
    f = jnp.zeros((n,))
    M = jnp.zeros((n, 3))
    dw, dvl, dwl = rqp.forward_dynamics(params, state, (f, M))
    # Reconstruct dv_com from dvl by undoing the kinematic correction.
    corr = (lie.hat_square(state.wl, state.wl) + lie.hat(dwl)) @ params.x_com
    dv_com = dvl + state.Rl @ corr
    assert jnp.abs(dv_com - jnp.array([0, 0, -rqp.GRAVITY])).max() < 1e-4


def test_integrate_jits_and_vmaps():
    n = 3
    params = _random_params(jax.random.PRNGKey(0), n)
    states = jax.vmap(lambda k: _random_state(k, n))(jax.random.split(jax.random.PRNGKey(1), 5))
    f = jnp.ones((5, n)) * 2.0
    M = jnp.zeros((5, n, 3))
    out = jax.jit(jax.vmap(lambda s, f_, M_: rqp.integrate(params, s, (f_, M_), 1e-3)))(
        states, f, M
    )
    assert out.R.shape == (5, n, 3, 3)
