"""Ring-collective consensus exchange (parallel/ring.py) on the virtual
multi-device CPU mesh (conftest.py): the ppermute ring tier must reproduce
the psum/allreduce tier to f32 rounding for the raw exchange (payload sizes
that do NOT divide the ring included — the chunk-pad path) and for the full
C-ADMM / DD sharded control steps, nominal AND alive-masked (fault-
injected); gathers are bitwise. Plus the auto-resolution gate: "auto" is
allreduce on CPU (the existing headline keeps its program) and the
chip-only pallas_ring downgrades to the XLA ring off-TPU at trace time."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.parallel import mesh as mesh_mod
from tpu_aerial_transport.parallel import ring
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.utils import compat

D = 4  # ring size for the raw-exchange tests (mesh uses 4 of the 8 devices).


# ----------------------------- resolution gate -------------------------


def test_resolve_auto_is_allreduce_on_cpu(monkeypatch):
    monkeypatch.delenv(ring.ENV_VAR, raising=False)
    assert ring.resolve_consensus("auto") == "allreduce"
    assert ring.resolve_consensus(None) == "allreduce"


def test_resolve_env_force_and_validation(monkeypatch):
    monkeypatch.setenv(ring.ENV_VAR, "ring")
    assert ring.resolve_consensus("auto") == "ring"
    # An explicit impl wins over the env var (the env only resolves "auto").
    assert ring.resolve_consensus("allreduce") == "allreduce"
    monkeypatch.setenv(ring.ENV_VAR, "bogus")
    with pytest.raises(ValueError, match="TPU_AERIAL_CONSENSUS"):
        ring.resolve_consensus("auto")
    monkeypatch.delenv(ring.ENV_VAR)
    with pytest.raises(ValueError, match="consensus_impl"):
        ring.resolve_consensus("bogus")


def test_pallas_ring_downgrades_off_tpu():
    """Trace-time downgrade (the socp._resolve_fused idiom): a config
    forced to pallas_ring still compiles — as the XLA ring — when the
    program lands on a non-TPU backend (e.g. the backend guard's CPU
    fallback rung)."""
    assert ring._resolve_impl("pallas_ring") == "ring"
    assert ring._resolve_impl("ring") == "ring"
    assert ring._resolve_impl("allreduce") == "allreduce"


def test_make_config_resolves_auto_at_build_time(monkeypatch):
    monkeypatch.delenv(ring.ENV_VAR, raising=False)
    params, col, _ = setup.rqp_setup(4)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration
    )
    assert cfg.consensus_impl == "allreduce"  # CPU default: no wire to hide.
    monkeypatch.setenv(ring.ENV_VAR, "ring")
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration
    )
    assert cfg.consensus_impl == "ring"
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration
    )
    assert cfg.base.consensus_impl == "ring"


# ----------------------------- raw exchange ----------------------------


def _shmap(fn, mesh):
    return functools.partial(
        compat.shard_map, mesh=mesh, in_specs=P("agent"),
        out_specs=P("agent"), check_vma=False,
    )(fn)


def _exchange(x, op, impl, d=D):
    m = mesh_mod.make_mesh({"agent": d})

    @functools.partial(_shmap, mesh=m)
    def step(v):
        return ring.consensus_exchange(
            v[0], "agent", axis_size=d, op=op, impl=impl
        )[None]

    return np.asarray(jax.jit(step)(x))


@pytest.mark.parametrize("op", ["sum", "max", "min"])
def test_exchange_parity_payload_not_divisible_by_ring(op):
    """18 elements over a 4-ring: the reduce-scatter chunk-pad path. Sum
    agrees to f32 rounding (summation order differs); max/min are exact
    under any schedule — bitwise."""
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((D, 18)), jnp.float32
    )
    ref = _exchange(x, op, "allreduce")
    out = _exchange(x, op, "ring")
    if op == "sum":
        assert np.abs(out - ref).max() <= 1e-5
    else:
        assert (out == ref).all()
    # The result must be identical on every shard (reduce-scatter computes
    # each chunk once, then broadcasts).
    assert (out == out[0][None]).all()


def test_exchange_parity_scalar_payload():
    """1 element over a 4-ring (the residual-max shape): pad-dominated."""
    x = jnp.asarray([[1.5], [-2.25], [0.5], [3.0]], jnp.float32)
    for op in ("sum", "max", "min"):
        ref = _exchange(x, op, "allreduce")
        out = _exchange(x, op, "ring")
        assert (out == ref).all(), op  # exact: 4 f32 values, tiny sums.


def test_gather_bitwise_matches_all_gather():
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((D, 18)), jnp.float32
    )
    m = mesh_mod.make_mesh({"agent": D})

    def g(impl):
        @functools.partial(_shmap, mesh=m)
        def step(v):
            return ring.consensus_gather(
                v[0], "agent", axis_size=D, impl=impl
            )[None]

        return np.asarray(jax.jit(step)(x))

    ref, out = g("allreduce"), g("ring")
    assert out.shape == (D, D, 18)
    assert (out == ref).all()


def test_exchange_axis_size_one_is_identity():
    x = jnp.asarray([[1.0, 2.0, 3.0]], jnp.float32)
    m = mesh_mod.make_mesh({"agent": 1})

    @functools.partial(_shmap, mesh=m)
    def step(v):
        s = ring.consensus_exchange(
            v[0], "agent", axis_size=1, op="sum", impl="ring"
        )
        g = ring.consensus_gather(v[0], "agent", axis_size=1, impl="ring")
        return (s + g[0])[None]

    assert np.asarray(jax.jit(step)(x)) == pytest.approx(
        2.0 * np.asarray(x)
    )


# ------------------------ full sharded controllers ---------------------

# Small iteration budget: the property under test is ring == allreduce,
# which holds at ANY fixed iteration count (convergence is asserted in
# test_cadmm.py / test_dd_rp.py; sharded == single-program in
# test_parallel.py). Forces are ~5 N; the two impls differ only in f32
# summation order, compounding over 4 consensus iterations.
_TOL = 2e-3


def _cadmm_cfg(params, col, impl):
    return cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=4, inner_iters=8, consensus_impl=impl,
    )


def _dd_cfg(params, col, impl):
    return dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=4, inner_iters=8, consensus_impl=impl,
    )


def _run_sharded(ctrl, impl, n=8, n_shards=4):
    """One sharded control step through parallel.mesh with the given
    consensus impl; returns (f, consensus residual)."""
    params, col, state = setup.rqp_setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)
    m = mesh_mod.make_mesh({"agent": n_shards})
    if ctrl == "cadmm":
        cfg = _cadmm_cfg(params, col, impl)
        cs0 = cadmm.init_cadmm_state(params, cfg)
        step = mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m)
    else:
        cfg = _dd_cfg(params, col, impl)
        cs0 = dd.init_dd_state(params, cfg)
        step = mesh_mod.dd_control_sharded(params, cfg, f_eq, m)
    f, _, stats = jax.jit(step)(cs0, state, acc_des)
    return np.asarray(f), float(stats.solve_res)


@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_sharded_ring_matches_allreduce(ctrl):
    """impl="ring" == impl="allreduce" to f32 rounding for the full
    agent-sharded control step (2 agents/shard: the block case)."""
    f_ref, res_ref = _run_sharded(ctrl, "allreduce")
    f_ring, res_ring = _run_sharded(ctrl, "ring")
    assert np.abs(f_ring - f_ref).max() < _TOL, (ctrl, f_ring - f_ref)
    assert abs(res_ring - res_ref) < _TOL


def _run_masked(ctrl, impl, n=4):
    """Alive-masked (fault-injected) sharded step: agent 0 dead, agent 2's
    consensus message dropped — exercises the masked sums, the
    alive-count denominator exchange, and (DD) the masked gather."""
    params, col, state = setup.rqp_setup(n)
    state = state.replace(vl=jnp.array([0.2, 0.1, 0.0], jnp.float32))
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    health = faults_mod.FaultStep(
        alive=jnp.array([False, True, True, True]),
        thrust_scale=jnp.array([0.0, 1.0, 1.0, 1.0], jnp.float32),
        msg_ok=jnp.array([False, True, False, True]),
    )
    m = mesh_mod.make_mesh({"agent": n})
    warm_spec = jax.tree.map(lambda _: P("agent"), mesh_mod._warm_structure())
    if ctrl == "cadmm":
        cfg = _cadmm_cfg(params, col, impl)
        f_eq = centralized.equilibrium_forces(params, alive=health.alive)
        # Seed the held (last-delivered) snapshots like the resilience
        # rollout adapters do, so the in/out state pytrees match.
        cs0 = cadmm.init_cadmm_state(params, cfg)
        cs0 = cs0.replace(held=cs0.f)
        plan = cadmm.make_plan(params, cfg)
        state_spec = cadmm.CADMMState(
            f=P("agent"), lam=P("agent"), f_mean=P(), warm=warm_spec,
            held=P("agent"),
        )

        def fn(cs, s, a, h):
            return cadmm.control(
                params, cfg, f_eq, cs, s, a, None, axis_name="agent",
                plan=plan, health=h,
            )
    else:
        cfg = _dd_cfg(params, col, impl)
        f_eq = centralized.equilibrium_forces(params, alive=health.alive)
        cs0 = dd.init_dd_state(params, cfg)
        cs0 = cs0.replace(
            held_f=cs0.f, held_lam_F=cs0.lam_F, held_lam_M=cs0.lam_M
        )
        plan = dd.make_dd_plan(params, cfg)
        state_spec = dd.DDState(
            f=P("agent"), F=P("agent"), M=P("agent"), lam_F=P("agent"),
            lam_M=P("agent"), warm=warm_spec, held_f=P("agent"),
            held_lam_F=P("agent"), held_lam_M=P("agent"),
        )

        def fn(cs, s, a, h):
            return dd.control(
                params, cfg, f_eq, cs, s, a, None, axis_name="agent",
                plan=plan, health=h,
            )

    step = functools.partial(
        compat.shard_map, mesh=m,
        in_specs=(state_spec, P(), (P(), P()), P()),
        out_specs=(P("agent"), state_spec, P()),
        check_vma=False,
    )(fn)
    f, _, stats = jax.jit(step)(cs0, state, acc_des, health)
    return np.asarray(f), float(stats.solve_res)


# --------------------------- registry coverage -------------------------


def test_ring_entrypoints_registered():
    """ring.py has no scan/while/fori (the ring is unrolled over the
    static axis size), so the generic hot-function coverage test in
    test_jaxlint.py cannot see it — this test is what makes dropping the
    ring entrypoints from the contract registry fail tier-1. The pallas
    entry must also keep its WRITTEN TC106 lowering waiver (jax.export
    cannot AOT-lower the Mosaic remote-DMA kernel off-chip)."""
    from tpu_aerial_transport.analysis import contracts, entrypoints

    required = (
        "parallel.ring:consensus_exchange",
        "parallel.ring:consensus_exchange_pallas",
        "parallel.mesh:cadmm_control_sharded_ring",
    )
    for name in required:
        assert name in entrypoints.CONTRACT_ENTRYPOINTS, name
        assert name in contracts.REGISTRY, name
    waiver = entrypoints.LOWERING_WAIVERS.get(
        "parallel.ring:consensus_exchange_pallas"
    )
    assert waiver and len(waiver) > 40, (
        "the chip-only pallas ring needs a written TC106 waiver reason"
    )
    # Tier-A traced-context inference must know ring.py's traced surface
    # (consensus_exchange & co run under shard_map/jit).
    traced = entrypoints.TRACED_FUNCTIONS[
        "tpu_aerial_transport/parallel/ring.py"
    ]
    assert "consensus_exchange" in traced and "consensus_gather" in traced


@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_sharded_ring_matches_allreduce_masked(ctrl):
    """Ring parity holds for the alive-masked consensus too: dead-agent
    zeroing, the psum'd n_alive denominator, and message-dropout masking
    all ride the exchange seam."""
    f_ref, res_ref = _run_masked(ctrl, "allreduce")
    f_ring, res_ring = _run_masked(ctrl, "ring")
    assert np.isfinite(f_ring).all()
    assert np.abs(f_ring[0]).max() == 0.0  # dead agent applies zero force.
    assert np.abs(f_ring - f_ref).max() < _TOL, (ctrl, f_ring - f_ref)
    assert abs(res_ring - res_ref) < _TOL
