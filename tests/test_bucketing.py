"""Bucketed batching must be a pure regrouping: per-scenario outputs equal
the plain vmapped step's exactly (same solves, same order restored)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.control import cadmm, centralized
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.harness import bucketing, setup


def test_bucketed_equals_vmapped():
    n = 4
    params, col, state0 = setup.rqp_setup(n)
    forest = forest_mod.make_forest(seed=0)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=10, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    cs0 = cadmm.init_cadmm_state(params, cfg)

    # 8 scenarios at varying distances from the forest -> varying congestion.
    rng = np.random.default_rng(1)
    xs = jnp.asarray(
        rng.normal(size=(8, 3)) * 3.0 + np.array([5.0, 0.0, 2.0]),
        jnp.float32,
    )
    states = jax.vmap(
        lambda x: state0.replace(
            xl=x, vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    )(xs)
    css = jax.vmap(lambda _: cs0)(jnp.arange(8))

    def step(cs, state):
        return cadmm.control(params, cfg, f_eq, cs, state, acc_des, forest)

    f_ref, cs_ref, st_ref = jax.jit(jax.vmap(step))(css, states)

    metric = bucketing.env_congestion_metric(forest, cfg.vision_radius)
    bstep = bucketing.bucketed_step(step, metric, n_buckets=2)
    f_b, cs_b, st_b = jax.jit(bstep)(css, states)

    np.testing.assert_array_equal(np.asarray(f_b), np.asarray(f_ref))
    np.testing.assert_array_equal(
        np.asarray(st_b.iters), np.asarray(st_ref.iters)
    )
    np.testing.assert_array_equal(
        np.asarray(cs_b.f_mean), np.asarray(cs_ref.f_mean)
    )


def test_pick_bucket_exact_fit():
    """An exactly-fitting bucket is chosen (no padding to the next one)."""
    assert bucketing.pick_bucket(8, (8, 16, 32)) == 8
    assert bucketing.pick_bucket(16, (8, 16, 32)) == 16


def test_pick_bucket_smallest_admitting():
    assert bucketing.pick_bucket(5, (8, 16, 32)) == 8
    assert bucketing.pick_bucket(9, (32, 16, 8)) == 16  # order-free.
    assert bucketing.pick_bucket(0, (8, 16)) == 8


def test_pick_bucket_no_admitting_bucket():
    """A size above every bucket returns None — admission control
    rejects, the AOT loader falls back to its largest variant."""
    assert bucketing.pick_bucket(33, (8, 16, 32)) is None


def test_pick_bucket_tie_on_padded_size():
    """Duplicate bucket values (two variants padding to the same size)
    resolve to that value deterministically."""
    assert bucketing.pick_bucket(7, (8, 8, 16)) == 8


def test_pick_bucket_invalid_args():
    import pytest

    with pytest.raises(ValueError):
        bucketing.pick_bucket(-1, (8,))
    with pytest.raises(ValueError):
        bucketing.pick_bucket(4, ())


def test_loader_variant_for_batch_uses_pick_bucket():
    """The AOT loader's smallest-admitting-bucket selection is the shared
    rule (regression for the PR-8 private copy)."""
    from tpu_aerial_transport.aot import bundle as bundle_mod
    from tpu_aerial_transport.aot import loader as loader_mod

    manifest = {
        "schema": bundle_mod.SCHEMA_VERSION, "platform": "cpu",
        "skipped": {},
        "entries": {"e": {"variants": [
            {"sig": "a", "artifacts": {}, "batch": 32},
            {"sig": "b", "artifacts": {}, "batch": 8},
            {"sig": "c", "artifacts": {}, "batch": 16},
        ]}},
    }
    b = loader_mod.Bundle("/nonexistent", manifest)
    assert b.variant_for_batch("e", 8)["batch"] == 8    # exact fit.
    assert b.variant_for_batch("e", 9)["batch"] == 16   # smallest admitting.
    assert b.variant_for_batch("e", 99)["batch"] == 32  # largest fallback.


def test_metric_counts_nearby_trees():
    forest = forest_mod.make_forest(seed=0)
    metric = bucketing.env_congestion_metric(forest, vision_radius=8.0)

    class _S:
        pass

    s_near = _S()
    s_near.xl = jnp.asarray(forest.tree_pos[0, :3]).astype(jnp.float32)
    s_far = _S()
    s_far.xl = jnp.array([-500.0, -500.0, 2.0], jnp.float32)
    assert int(metric(s_near)) > 0
    assert int(metric(s_far)) == 0
