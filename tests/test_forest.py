"""Tests for the JAX forest environment: distance oracle comparisons (numpy f64
brute force stands in for hppfcl, which is not available — SURVEY.md §7 stage 5),
generation invariants, vision-cone masking, and CBF row construction."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.envs import forest as fo


def _np_point_cyl(p, c, R, H):
    d_rad = np.linalg.norm(p[:2] - c[:2]) - R
    d_ax = abs(p[2] - c[2]) - H
    if d_rad <= 0 and d_ax <= 0:
        return max(d_rad, d_ax)
    return np.hypot(max(d_rad, 0.0), max(d_ax, 0.0))


def _np_seg_cyl(a, b, c, R, H, n=20001):
    ts = np.linspace(0.0, 1.0, n)
    pts = a[None] + ts[:, None] * (b - a)[None]
    return min(_np_point_cyl(p, c, R, H) for p in pts)


def test_forest_generation_invariants():
    f = fo.make_forest(seed=0)
    num = int(f.num_trees)
    assert 1 <= num <= fo.MAX_TREES
    pos = np.asarray(f.tree_pos[:num])
    # Min spacing respected.
    d = np.linalg.norm(pos[None, :, :2] - pos[:, None, :2], axis=-1)
    d[np.diag_indices(num)] = np.inf
    assert d.min() >= fo.MIN_DIST_BETWEEN_TREES - 1e-9
    # All inside the mountain disc.
    assert (
        np.linalg.norm(pos[:, :2] - fo.MOUNTAIN_CENTER, axis=1)
        <= fo.MOUNTAIN_RADIUS + 1e-9
    ).all()
    # Determinism.
    f2 = fo.make_forest(seed=0)
    assert jnp.array_equal(f.tree_pos, f2.tree_pos)
    # Different seed -> different forest.
    f3 = fo.make_forest(seed=1)
    assert not jnp.array_equal(f.tree_pos, f3.tree_pos)


@pytest.mark.parametrize("seed", range(8))
def test_segment_cylinder_distance_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=3) * 3
    b = a + rng.normal(size=3) * 4
    c = rng.normal(size=3) * 2
    R, H = 0.3, 2.0
    d_jax, p_seg, p_cyl = fo.segment_cylinder_distance(
        jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32),
        jnp.asarray(c, jnp.float32), R, H,
    )
    d_ref = _np_seg_cyl(a, b, c, R, H)
    assert abs(float(d_jax) - d_ref) < 2e-4, (float(d_jax), d_ref)
    if d_ref > 1e-3:
        # Witness points consistent with the distance.
        gap = np.linalg.norm(np.asarray(p_seg) - np.asarray(p_cyl))
        assert abs(gap - d_ref) < 2e-3


def test_point_cylinder_inside_sign():
    d, cp = fo.point_cylinder_distance(
        jnp.array([0.1, 0.0, 0.5]), jnp.zeros(3), 0.3, 2.0
    )
    assert float(d) < 0  # inside -> negative


def test_capsule_forest_distance_and_collision_flag():
    f = fo.make_forest(seed=0)
    tree0 = f.tree_pos[0]
    # Capsule axis passing right next to tree 0, 1.0 m away in y.
    a = jnp.array([tree0[0] - 3.0, tree0[1] + 1.0, tree0[2]])
    b = jnp.array([tree0[0] + 3.0, tree0[1] + 1.0, tree0[2]])
    data = fo.capsule_forest_distance(f, a, b, 0.2, 10.0)
    # Expected distance to tree 0: 1.0 - bark_radius - cap_radius = 0.5.
    # (Other trees may be closer to this capsule, so check slot 0 specifically.)
    assert abs(float(data.dists[0]) - 0.5) < 1e-3
    # Touching capsule -> collision.
    a2 = jnp.array([tree0[0] - 3.0, tree0[1], tree0[2]])
    b2 = jnp.array([tree0[0] + 3.0, tree0[1], tree0[2]])
    data2 = fo.capsule_forest_distance(f, a2, b2, 0.4, 10.0)
    assert bool(data2.collision)


def test_vision_cone_mask():
    f = fo.make_forest(seed=0)
    cam = jnp.asarray(f.tree_pos[0, :2]) - jnp.array([5.0, 0.0])
    # Looking +x: tree 0 visible; looking -x: not.
    m_fwd = fo.vision_cone_mask(f, cam, jnp.array([1.0, 0.0]), jnp.pi / 4)
    m_bwd = fo.vision_cone_mask(f, cam, jnp.array([-1.0, 0.0]), jnp.pi / 4)
    assert bool(m_fwd[0])
    assert not bool(m_bwd[0])


def test_collision_cbf_rows_active_and_inactive():
    f = fo.make_forest(seed=0)
    tree0 = np.asarray(f.tree_pos[0])
    vision_radius = 6.0
    # Moving toward tree 0 at 1 m/s from 4 m away -> active rows.
    xl = jnp.asarray(tree0 - np.array([4.0, 0.0, 0.0]), jnp.float32)
    vl = jnp.array([1.0, 0.0, 0.0])
    cbf = fo.collision_cbf_rows(
        f, xl, vl, collision_radius=0.5, max_deceleration=1.96,
        vision_radius=vision_radius, dist_eps=0.1, alpha_env_cbf=2.0, n_rows=10,
    )
    assert cbf.lhs.shape == (10, 3)
    assert float(cbf.min_dist) < vision_radius
    active = jnp.any(jnp.abs(cbf.lhs) > 0, axis=1)
    assert bool(jnp.any(active))
    # Active row normal points from tree toward the system (negative x here).
    i = int(jnp.argmax(active))
    assert float(cbf.lhs[i, 0]) < 0
    # Far away -> all rows vacuous (lhs 0, rhs < 0).
    xl_far = jnp.array([-100.0, -100.0, 1.0])
    cbf_far = fo.collision_cbf_rows(
        f, xl_far, vl, 0.5, 1.96, vision_radius, 0.1, 2.0, 10,
    )
    assert float(jnp.abs(cbf_far.lhs).max()) == 0.0
    assert bool(jnp.all(cbf_far.rhs < 0))
    # No-env path.
    cbf_none = fo.collision_cbf_rows(None, xl, vl, 0.5, 1.96,
                                     vision_radius, 0.1, 2.0, 10)
    assert float(jnp.abs(cbf_none.lhs).max()) == 0.0


def test_ground_height():
    f = fo.make_forest(seed=0)
    center = jnp.asarray(fo.MOUNTAIN_CENTER, jnp.float32)
    h_center = fo.ground_height(f, center)
    # The cap apex height implied by the reference's sphere construction
    # (env_forest.py:74-77) — note it is NOT _MOUNTAIN_HEIGHT itself.
    expected = float(f.mountain_sphere_radius - f.mountain_center_depth)
    assert abs(float(h_center) - expected) < 1e-3
    assert 0.0 < expected < fo.MOUNTAIN_HEIGHT
    h_far = fo.ground_height(f, center + 100.0)
    assert float(h_far) == 0.0


def test_distance_query_jits_and_vmaps():
    f = fo.make_forest(seed=0)
    xs = jnp.stack([jnp.array([20.0, 0.0, 2.0]), jnp.array([30.0, 5.0, 2.0])])
    vs = jnp.tile(jnp.array([1.0, 0.0, 0.0]), (2, 1))
    fn = jax.jit(jax.vmap(
        lambda x, v: fo.collision_cbf_rows(f, x, v, 0.5, 1.96, 6.0, 0.1, 2.0, 10)
    ))
    out = fn(xs, vs)
    assert out.lhs.shape == (2, 10, 3)


def test_cbf_rows_stay_protective_under_penetration():
    """Near-contact hardening (deliberate deviation from the reference,
    which drops rows at dist < 1e-4 and whose braking-time coefficient
    degenerates to zero at contact — measured closed-loop consequence: the
    payload punches straight through trees once it grazes into contact):
    with the capsule PENETRATING a tree, the nearest-obstacle row must stay
    active, point AWAY from the tree (sign-corrected outward normal), and
    carry a positive rhs demanding outward acceleration."""
    tree = jnp.array([[1.0, 0.0, 2.0]])
    forest = fo.forest_from_tree_pos(np.asarray(tree), 1)
    xl = jnp.array([0.0, 0.0, 2.0])
    vl = jnp.array([0.5, 0.0, 0.0])  # flying straight at the tree.
    collision_radius = 0.9  # 0.9 + bark 0.3 = 1.2 > 1.0 separation: contact.
    cbf = fo.collision_cbf_rows(
        forest, xl, vl, collision_radius, max_deceleration=2.0,
        vision_radius=6.0, dist_eps=0.1, alpha_env_cbf=1.5, n_rows=4,
    )
    assert float(cbf.min_dist) < 0  # penetrating, by construction.
    lhs = np.asarray(cbf.lhs)
    rhs = np.asarray(cbf.rhs)
    act = np.abs(lhs).max(axis=1) > 0
    assert act.any(), "penetrating obstacle must still produce a row"
    r = int(np.argmax(act))
    # Outward = -x (tree is at +x): coefficient strictly negative in x,
    # with the NEAR_BRAKE_TIME floor magnitude.
    assert lhs[r, 0] < -0.9 * fo.NEAR_BRAKE_TIME, lhs[r]
    # rhs = -alpha (d - eps) - n . vl with d < 0 and n = -x: both terms
    # positive — the row demands deceleration/outward acceleration.
    assert rhs[r] > 0, rhs[r]
    # The demanded acceleration is feasible (well inside thrust envelopes).
    assert rhs[r] / -lhs[r, 0] < 10.0


def test_cbf_rows_protective_deep_penetration_and_at_rest():
    """The two corners the first hardening pass missed (found by review,
    reproduced, now fixed at the source): (a) DEEP penetration — the
    capsule axis inside the bark — needs interior points to witness the
    nearest SURFACE point (a self-witness zeroes the outward normal);
    (b) a system AT REST in contact keeps its near row (the speed gate
    applies only to far rows whose braking-capsule construction needs
    motion)."""
    tree = jnp.array([[1.0, 0.0, 2.0]])
    forest = fo.forest_from_tree_pos(np.asarray(tree), 1)

    # (a) axis inside the bark: payload 0.1 m from the tree axis.
    cbf = fo.collision_cbf_rows(
        forest, jnp.array([0.9, 0.0, 2.0]), jnp.array([0.3, 0.0, 0.0]),
        collision_radius=0.9, max_deceleration=2.0,
        vision_radius=6.0, dist_eps=0.1, alpha_env_cbf=1.5, n_rows=4,
    )
    lhs, rhs = np.asarray(cbf.lhs), np.asarray(cbf.rhs)
    act = np.abs(lhs).max(axis=1) > 0
    assert act.any(), "deep penetration must still produce a row"
    r = int(np.argmax(act))
    assert lhs[r, 0] < 0, lhs[r]  # outward = -x.
    assert rhs[r] > 0, rhs[r]

    # (b) at rest in shallow contact.
    cbf = fo.collision_cbf_rows(
        forest, jnp.array([0.0, 0.0, 2.0]), jnp.zeros(3),
        collision_radius=0.9, max_deceleration=2.0,
        vision_radius=6.0, dist_eps=0.1, alpha_env_cbf=1.5, n_rows=4,
    )
    lhs, rhs = np.asarray(cbf.lhs), np.asarray(cbf.rhs)
    act = np.abs(lhs).max(axis=1) > 0
    assert act.any(), "at-rest contact must keep its near row"
    r = int(np.argmax(act))
    assert lhs[r, 0] < 0 and rhs[r] > 0, (lhs[r], rhs[r])


def test_cbf_row_survives_exact_axis_surface_contact():
    """Exact axis-surface contact (dist_axis == 0.0, surface witnesses
    coincident): the outward normal must fall back to the radial direction
    from the tree axis instead of vanishing — ``-sign(dist_axis)`` used to
    zero the protecting row at the worst possible moment (ISSUE 1
    satellite)."""
    tree = jnp.array([[1.0, 0.0, 2.0]])
    forest = fo.forest_from_tree_pos(np.asarray(tree), 1)
    # Point capsule exactly bark_radius from the tree axis: dist_axis == 0.
    xl = jnp.array([1.0 - fo.BARK_RADIUS, 0.0, 2.0], jnp.float32)
    data = fo.capsule_forest_distance(forest, xl, xl, 0.9, 6.0)
    # Exact contact by construction (f32: dist_axis - cap_radius == -0.9).
    assert np.float32(data.dists[0]) + np.float32(0.9) == np.float32(0.0)
    n0 = np.asarray(data.normal_out[0])
    assert abs(np.linalg.norm(n0) - 1.0) < 1e-5, n0  # unit, not zeroed.
    assert n0[0] < -0.99, n0  # outward = -x (tree is at +x).

    # End-to-end: the CBF row stays active and protective.
    cbf = fo.collision_cbf_rows(
        forest, xl, jnp.zeros(3), collision_radius=0.9,
        max_deceleration=2.0, vision_radius=6.0, dist_eps=0.1,
        alpha_env_cbf=1.5, n_rows=4,
    )
    lhs, rhs = np.asarray(cbf.lhs), np.asarray(cbf.rhs)
    act = np.abs(lhs).max(axis=1) > 0
    assert act.any(), "exact contact must keep its protecting row"
    r = int(np.argmax(act))
    assert lhs[r, 0] < 0 and rhs[r] > 0, (lhs[r], rhs[r])


def test_cbf_normal_vertical_at_exact_cap_contact():
    """Exact contact on a tree's flat TOP CAP: the fallback normal must be
    the signed vertical (+z above the cap), not the horizontal radial — a
    sideways row would constrain motion in a direction that does not clear
    the cap."""
    tree = jnp.array([[1.0, 0.0, 2.0]])  # cylinder z in [0, 4].
    forest = fo.forest_from_tree_pos(np.asarray(tree), 1)
    # Point capsule exactly on the top cap (z = 4), inside the radius.
    xl = jnp.array([1.1, 0.0, 4.0], jnp.float32)
    data = fo.capsule_forest_distance(forest, xl, xl, 0.9, 6.0)
    assert np.float32(data.dists[0]) + np.float32(0.9) == np.float32(0.0)
    n0 = np.asarray(data.normal_out[0])
    assert abs(np.linalg.norm(n0) - 1.0) < 1e-5, n0
    assert n0[2] > 0.99, n0  # outward = +z off the cap.
