"""Dual-decomposition controller tests + RP centralized closed-loop test
(reference test/control/test_rqpcontrollers.py and test_rpcentralized.py)."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import centralized, dd, rp_centralized
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rp as rp_mod
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie


def _setup(n=3):
    params, col, state = setup.rqp_setup(n)
    ccfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=250
    )
    # Reference stop tolerance is 1e-2 N (rqp_dd.py:609); 5e-3 is reachable with
    # f32 inner solves, 1e-3 is below their accuracy floor.
    dcfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=80, inner_iters=80, prim_inf_tol=5e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    return params, col, state, ccfg, dcfg, f_eq


def _random_state(key, n):
    ks = jax.random.split(key, 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.1 * jax.random.normal(ks[0], (n, 3))),
        w=0.1 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.3 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=jnp.zeros(3),
    )


def test_dd_agrees_with_centralized():
    """DD consensus forces must match the centralized solution (same convex
    problem — the reference's implicit cross-solver invariant)."""
    n = 3
    params, col, _, ccfg, dcfg, f_eq = _setup(n)
    for seed in range(3):
        ks = jax.random.split(jax.random.PRNGKey(seed + 20), 2)
        state = _random_state(ks[0], n)
        acc_des = (0.5 * jax.random.normal(ks[1], (3,)), jnp.zeros(3))
        cs = centralized.init_ctrl_state(params, ccfg)
        f_cent, _, _ = centralized.control(params, ccfg, f_eq, cs, state, acc_des)
        ds = dd.init_dd_state(params, dcfg)
        f_dd, ds, stats = dd.control(params, dcfg, f_eq, ds, state, acc_des)
        assert int(stats.iters) < 81, "DD did not converge"
        err = float(jnp.abs(f_dd - f_cent).max())
        assert err < 5e-2, f"seed {seed}: |f_dd - f_cent| = {err}"


def test_dd_warm_start_and_errseq():
    n = 3
    params, col, state0, _, dcfg, f_eq = _setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.0]), jnp.zeros(3))
    ds = dd.init_dd_state(params, dcfg)
    f1, ds, s1 = dd.control(params, dcfg, f_eq, ds, state0, acc_des)
    f2, ds, s2 = dd.control(params, dcfg, f_eq, ds, state0, acc_des)
    assert int(s2.iters) <= int(s1.iters)
    assert jnp.abs(f1 - f2).max() < 1e-2
    errs = s1.err_seq[~jnp.isnan(s1.err_seq)]
    assert errs.shape[0] == int(s1.iters)


def test_dd_jits():
    n = 3
    params, col, state0, _, dcfg, f_eq = _setup(n)
    ds = dd.init_dd_state(params, dcfg)
    acc_des = (jnp.array([0.2, 0.0, 0.0]), jnp.zeros(3))
    f, ds, stats = jax.jit(
        lambda d, s: dd.control(params, dcfg, f_eq, d, s, acc_des)
    )(ds, state0)
    assert bool(jnp.all(jnp.isfinite(f)))


def test_rp_centralized_closedloop_circle():
    """RP centralized QP tracking a circular reference (reference
    test_rpcentralized.py:14-38): bounded tracking error, safety invariants."""
    params, col, state0 = setup.rp_setup(3)
    cfg = rp_centralized.make_config(params, solver_iters=120)
    f_eq = rp_centralized.equilibrium_forces(params)
    cs0 = rp_centralized.init_ctrl_state(params, cfg)

    radius, omega = 0.5, 0.4

    def ref(t):
        x = jnp.stack([
            radius * jnp.cos(omega * t) - radius,
            radius * jnp.sin(omega * t),
            0.1 * t,
        ])
        v = jnp.stack([
            -radius * omega * jnp.sin(omega * t),
            radius * omega * jnp.cos(omega * t),
            jnp.asarray(0.1),
        ])
        a = jnp.stack([
            -radius * omega**2 * jnp.cos(omega * t),
            -radius * omega**2 * jnp.sin(omega * t),
            jnp.asarray(0.0),
        ])
        return x, v, a

    dt = 1e-3

    def body(carry, i):
        state, cs = carry
        t = i * dt * 10
        x_ref, v_ref, a_ref = ref(t)
        dvl_des = a_ref - 1.5 * (state.vl - v_ref) - 2.0 * (state.xl - x_ref)
        acc_des = (dvl_des, jnp.zeros(3))
        f, cs, _ = rp_centralized.control(params, cfg, f_eq, cs, state, acc_des)

        def ll(s, _):
            return rp_mod.integrate(params, s, f, dt), None

        state, _ = jax.lax.scan(ll, state, None, length=10)
        x_err = jnp.linalg.norm(state.xl - x_ref)
        return (state, cs), x_err

    (final, _), errs = jax.jit(
        lambda c: jax.lax.scan(body, c, jnp.arange(800))
    )((state0, cs0))
    assert bool(jnp.all(jnp.isfinite(final.xl)))
    # After the transient, tracking error stays bounded.
    assert float(jnp.max(errs[300:])) < 0.3
    # Tilt stays within the 30 deg CBF bound.
    assert float(final.Rl[2, 2]) > float(jnp.cos(jnp.pi / 6)) - 0.02


def test_dd_runtime_hooks():
    """The leader/tolerance/iteration runtime hooks work on the DD config
    wrapper too (reference rqp_dd.py:507-511, 754-764): setters descend into
    cfg.base, and unset_leader removes the tracking cost."""
    from tpu_aerial_transport.control import cadmm as hooks

    params, col, state = setup.rqp_setup(3)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=20, inner_iters=40,
    )
    assert hooks.set_leader(cfg, 1).base.leader_idx == 1
    assert hooks.unset_leader(cfg).base.leader_idx == -1
    t = hooks.set_tolerance(cfg, 5e-2)
    assert t.base.res_tol == 5e-2 and t.prim_inf_tol == 5e-2
    assert hooks.set_max_iter(cfg, 7).base.max_iter == 7

    # Behavior: with no leader, no agent carries the tracking cost, so the
    # solution stays closer to equilibrium than the led solve.
    f_eq = centralized.equilibrium_forces(params)
    acc = (jnp.array([0.6, 0.0, 0.0]), jnp.zeros(3))
    ds = dd.init_dd_state(params, cfg)
    step = jax.jit(
        lambda c, d, s: dd.control(params, c, f_eq, d, s, acc)
    )
    f_led, _, _ = step(cfg, ds, state)
    f_unled, _, _ = step(hooks.unset_leader(cfg), ds, state)
    assert float(jnp.abs(f_unled - f_eq).max()) \
        < float(jnp.abs(f_led - f_eq).max())
