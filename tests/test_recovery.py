"""Crash-recovery tests: chunked rollouts bitwise-identical to the fused
scan through ONE compiled chunk; kill-at-any-chunk-boundary + resume
reproducing the uninterrupted trajectory exactly (corrupted snapshots
falling back to the previous valid one); SIGTERM-graceful preemption;
host-level retry requeuing after a device error; and the sharded
Monte-Carlo batch path resuming with a quarantined lane bit-exactly."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, lowlevel
from tpu_aerial_transport.harness import checkpoint, setup
from tpu_aerial_transport.harness import rollout as ro
from tpu_aerial_transport.parallel import mesh as mesh_mod
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience import recovery
from tpu_aerial_transport.resilience.rollout import (
    init_resilient_carry,
    make_cadmm_hl_step,
    make_chunked_resilient_rollout,
    resilient_rollout,
)

N_HL = 6
CHUNKS = 3
HL_REL = 2


def _problem(n=3):
    params, col, state0 = setup.rqp_setup(n)
    cfg = centralized.make_config(
        params, col.collision_radius, col.max_deceleration, solver_iters=10
    )
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = centralized.init_ctrl_state(params, cfg)
    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    def hl(cs, s, a):
        return centralized.control(params, cfg, f_eq, cs, s, a)

    return params, cfg, state0, cs0, ll, hl, acc_des_fn


def _reference(params, state0, cs0, ll, hl, acc_des_fn):
    full = ro.jit_rollout(
        hl, ll.control, params, n_hl_steps=N_HL, hl_rel_freq=HL_REL,
        acc_des_fn=acc_des_fn, donate=False,
    )
    return full(state0, cs0)


def _assert_trees_equal(a, b, what=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), \
            f"bitwise mismatch {what}"


def _runner(params, ll, hl, acc_des_fn, n_chunks=CHUNKS):
    return ro.make_chunked_rollout(
        hl, ll.control, params, n_hl_steps=N_HL, n_chunks=n_chunks,
        hl_rel_freq=HL_REL, acc_des_fn=acc_des_fn,
    )


def _fresh_carry(runner, state0, cs0):
    # Decoupled copies: the chunk donates its carry and a freshly built
    # rest state shares constant zero buffers.
    return runner.init_carry(*jax.tree.map(jnp.copy, (state0, cs0)))


def test_chunked_rollout_bitwise_identical_single_compile():
    """The acceptance gate: chunked == fused scan, all chunks through ONE
    jit-cache entry, boundaries surfaced to the hook in order."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)

    runner = _runner(params, ll, hl, acc_des_fn)
    boundaries = []
    s2, c2, log2 = runner(
        *jax.tree.map(jnp.copy, (state0, cs0)),
        on_boundary=lambda c, carry, logs: boundaries.append(c),
    )
    assert boundaries == list(range(CHUNKS))
    assert runner.chunk_jit._cache_size() == 1, \
        "C chunks must compile exactly once"
    _assert_trees_equal((fs, fc, flog), (s2, c2, log2), "chunked vs fused")


def test_chunked_rollout_validates_args():
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    with pytest.raises(ValueError, match="divisible"):
        ro.make_chunked_rollout(
            hl, ll.control, params, n_hl_steps=7, n_chunks=3,
            acc_des_fn=acc_des_fn,
        )
    with pytest.raises(ValueError, match="acc_des_fn"):
        ro.make_chunked_rollout(
            hl, ll.control, params, n_hl_steps=6, n_chunks=3,
            acc_des_fn=None,
        )


@pytest.mark.parametrize("kill_after", [1, 2])
def test_kill_at_chunk_boundary_then_resume_bit_identical(
        tmp_path, kill_after):
    """A run killed at an arbitrary chunk boundary resumes (fresh process:
    deterministic setup regen + journal + snapshots only) to the
    bitwise-identical final state and log of the uninterrupted run."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    ch = checkpoint.config_fingerprint(cfg=cfg, n=3)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS,
                            seed=0, config_hash=ch)

    interrupt = recovery.GracefulInterrupt()
    calls = {"n": 0}

    def killing_chunk(carry, i0):
        out = runner.chunk_jit(carry, i0)
        calls["n"] += 1
        if calls["n"] == kill_after:
            interrupt.triggered = "SIGTERM"  # "process killed here".
        return out

    res = recovery.run_chunks(
        plan, killing_chunk, _fresh_carry(runner, state0, cs0),
        interrupt=interrupt,
    )
    assert res.status == "preempted"
    assert res.chunks_done == kill_after
    events = [e["event"] for e in recovery.RunJournal(d).read()]
    assert events == ["run_start"] + ["chunk"] * kill_after + ["preempted"]

    # "New process": only the run dir + deterministic regen survive.
    res2 = recovery.resume_run(
        d, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
        config_hash=ch,
    )
    assert res2.status == "done"
    assert res2.resumed_from_chunk == kill_after
    s2, c2 = res2.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res2.logs),
                        f"resume after kill@{kill_after}")


def test_resume_falls_back_past_corrupt_snapshot(tmp_path):
    """Corrupting the newest carry snapshot must not poison the resume:
    the walk falls back to the previous valid boundary, recomputes the
    tail, and still reproduces the uninterrupted run bit-exactly."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    ch = checkpoint.config_fingerprint(cfg=cfg, n=3)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS,
                            config_hash=ch)
    res = recovery.run_chunks(
        plan, runner.chunk_jit, _fresh_carry(runner, state0, cs0)
    )
    assert res.status == "done"

    newest = checkpoint.snapshot_path(d, CHUNKS - 1, recovery.CARRY_PREFIX)
    raw = dict(np.load(newest, allow_pickle=False))
    raw["leaf_000000"] = raw["leaf_000000"] + 1  # stale manifest digests.
    with open(newest, "wb") as fh:
        np.savez(fh, **raw)

    res2 = recovery.resume_run(
        d, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
        config_hash=ch,
    )
    assert res2.status == "done"
    assert res2.resumed_from_chunk == CHUNKS - 1  # fell back one boundary.
    s2, c2 = res2.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res2.logs),
                        "resume past corrupt snapshot")
    resume_events = [e for e in recovery.RunJournal(d).read()
                     if e.get("event") == "resume"]
    assert resume_events[-1]["skipped"], "skipped snapshot must be journaled"


def test_resume_refuses_config_mismatch(tmp_path):
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    runner = _runner(params, ll, hl, acc_des_fn)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS,
                            config_hash="cfg-A")
    recovery.run_chunks(plan, runner.chunk_jit,
                        _fresh_carry(runner, state0, cs0))
    with pytest.raises(checkpoint.SnapshotError) as ei:
        recovery.resume_run(
            d, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
            config_hash="cfg-B",
        )
    assert ei.value.kind == "config_mismatch"


def test_sigterm_graceful_interrupt_real_signal(tmp_path):
    """A real SIGTERM mid-run stops at the next chunk boundary with the
    snapshot flushed and the preemption journaled; a later resume finishes
    the run bit-identically."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    ch = checkpoint.config_fingerprint(cfg=cfg, n=3)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS,
                            config_hash=ch)
    calls = {"n": 0}

    def chunk_sending_sigterm(carry, i0):
        out = runner.chunk_jit(carry, i0)
        calls["n"] += 1
        if calls["n"] == 1:
            os.kill(os.getpid(), signal.SIGTERM)
        return out

    with recovery.GracefulInterrupt() as interrupt:
        res = recovery.run_chunks(
            plan, chunk_sending_sigterm,
            _fresh_carry(runner, state0, cs0), interrupt=interrupt,
        )
    assert res.status == "preempted"
    assert interrupt.triggered == "SIGTERM"
    assert [e for e in recovery.RunJournal(d).read()
            if e.get("event") == "preempted"][0]["signal"] == "SIGTERM"
    # The flushed boundary snapshot is loadable.
    checkpoint.load_latest_valid(
        d, _fresh_carry(runner, state0, cs0),
        prefix=recovery.CARRY_PREFIX, config_hash=ch,
    )
    res2 = recovery.resume_run(
        d, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
        config_hash=ch,
    )
    s2, c2 = res2.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res2.logs),
                        "resume after real SIGTERM")


def test_host_level_retry_requeues_after_device_error(tmp_path):
    """A chunk raising mid-run (device error) is requeued from the last
    boundary's host carry copy; the completed run is bit-identical and the
    retry is journaled."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS)
    calls = {"n": 0}

    def dying_chunk(carry, i0):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated device error")
        return runner.chunk_jit(carry, i0)

    res = recovery.run_chunks(
        plan, dying_chunk, _fresh_carry(runner, state0, cs0), max_retries=1
    )
    assert res.status == "done" and res.retries == 1
    s2, c2 = res.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res.logs), "after retry")
    assert [e for e in recovery.RunJournal(d).read()
            if e.get("event") == "retry"]
    # Retry budget exhausted -> the error propagates (no silent loop).
    plan2 = recovery.RunPlan(run_dir=str(tmp_path / "b"), n_hl_steps=N_HL,
                             n_chunks=CHUNKS)

    def always_dying(carry, i0):
        raise RuntimeError("dead device")

    with pytest.raises(RuntimeError, match="dead device"):
        recovery.run_chunks(
            plan2, always_dying, _fresh_carry(runner, state0, cs0),
            max_retries=2,
        )


def test_snapshot_io_failure_retry_does_not_double_apply(
        tmp_path, monkeypatch):
    """Regression: a transient snapshot-write failure (plain OSError, e.g.
    ENOSPC) after the chunk computed must retry from the LAST boundary,
    not from the failed chunk's own output — the retry anchor advances
    only once the boundary is fully published."""
    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS)
    real_save = checkpoint.save_snapshot
    fails = {"n": 0}

    def flaky_save(directory, step, state, **kw):
        if (kw.get("prefix") == recovery.LOGS_PREFIX and step == 1
                and fails["n"] == 0):
            fails["n"] += 1
            raise OSError("simulated disk hiccup")
        return real_save(directory, step, state, **kw)

    monkeypatch.setattr(checkpoint, "save_snapshot", flaky_save)
    res = recovery.run_chunks(
        plan, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
        max_retries=1,
    )
    assert res.status == "done" and res.retries == 1
    s2, c2 = res.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res.logs),
                        "after snapshot IO retry")


def test_journal_tolerates_torn_tail(tmp_path):
    j = recovery.RunJournal(str(tmp_path))
    j.append({"event": "run_start", "n_hl_steps": 4, "n_chunks": 2})
    j.append({"event": "chunk", "chunk": 0})
    with open(j.path, "a") as fh:
        fh.write('{"event": "chunk", "chu')  # power cut mid-append.
    events = j.read()
    assert [e["event"] for e in events] == ["run_start", "chunk"]
    assert j.completed_chunks() == {0}
    assert recovery.read_plan(str(tmp_path)).n_chunks == 2


def test_resilient_vmapped_batch_resume_with_quarantined_lane(tmp_path):
    """The sharded serving path end to end: a vmapped batch (one lane
    driven to NaN and quarantined) runs through
    ``mesh.scenario_rollout_resumable`` — checkpoint at every chunk
    boundary — is preempted mid-run, and resumes to logs bit-identical to
    the uninterrupted vmapped run, sticky quarantine flag included."""
    n, B, n_steps, n_chunks = 4, 4, 8, 2
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=6, inner_iters=15,
    )
    hl = make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    scheds = [faults_mod.make_schedule(n, key=jax.random.PRNGKey(k))
              for k in range(B)]
    # Lane 1 blows up mid-run (inf actuator gain) and must quarantine.
    scheds[1] = faults_mod.make_schedule(
        n, t_degrade={0: 3}, thrust_scale=jnp.inf,
        key=jax.random.PRNGKey(1),
    )
    batch_scheds = jax.tree.map(lambda *xs: jnp.stack(xs), *scheds)

    m = mesh_mod.make_mesh({"scenario": 2})
    batch_states = jax.vmap(lambda _: state0)(jnp.arange(B))
    batch_cs = jax.vmap(lambda _: cs0)(jnp.arange(B))

    # Uninterrupted reference (the test_quarantine pattern), with the
    # initial carries as ARGUMENTS (not baked constants) and the batch
    # sharded over the same mesh, so the reference and the resumable path
    # run the identical program shape on identical placements.
    ref_fn = jax.jit(jax.vmap(
        lambda f, s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=n_steps,
            hl_rel_freq=HL_REL, acc_des_fn=acc_des_fn, faults=f,
        )
    ))
    _, _, ref_logs = ref_fn(*mesh_mod.shard_scenarios(
        m, (batch_scheds, batch_states, batch_cs)
    ))
    assert bool(jnp.any(ref_logs.quarantined[1])), "lane 1 must quarantine"

    # Chunked: the per-lane fault schedule rides INSIDE the carry so one
    # chunk function serves heterogeneous lanes under vmap.
    chunk_len = n_steps // n_chunks

    def chunk_fn(carry, i0):
        rc, sched = carry
        rc, logs = resilient_rollout(
            hl, ll.control, params, None, None, chunk_len,
            hl_rel_freq=HL_REL, acc_des_fn=acc_des_fn, faults=sched,
            carry0=rc, step_offset=i0, return_carry=True,
        )
        return (rc, sched), logs

    def batch_carry0():
        return jax.vmap(
            lambda f, s, c: (init_resilient_carry(hl, params, s, c, f), f)
        )(jax.tree.map(jnp.copy, batch_scheds),
          jax.tree.map(jnp.copy, batch_states),
          jax.tree.map(jnp.copy, batch_cs))

    ch = checkpoint.config_fingerprint(cfg=cfg, n=n, B=B)
    run = mesh_mod.scenario_rollout_resumable(
        chunk_fn, m, n_hl_steps=n_steps, n_chunks=n_chunks,
        run_dir=str(tmp_path), config_hash=ch,
    )
    interrupt = recovery.GracefulInterrupt()
    interrupt.triggered = None
    orig_jit = run.batched_jit

    def preempting(carry, i0):
        out = orig_jit(carry, i0)
        interrupt.triggered = "SIGTERM"  # killed after the first chunk.
        return out

    plan = run.plan
    res = recovery.run_chunks(
        plan, preempting, batch_carry0(), interrupt=interrupt,
        place=lambda c: mesh_mod.shard_scenarios(m, c),
    )
    assert res.status == "preempted" and res.chunks_done == 1

    res2 = run(batch_carry0(), resume=True)
    assert res2.status == "done" and res2.resumed_from_chunk == 1
    _assert_trees_equal(ref_logs, res2.logs, "vmapped resume")
    final_rc, _ = res2.carry
    quar = np.asarray(final_rc[3])
    assert quar[1] and not quar[[0, 2, 3]].any(), \
        "sticky quarantine flag must survive the resume bit-exactly"


def test_run_chunks_guard_degrades_to_cpu_and_continues(tmp_path):
    """Backend guard wired into the chunk loop: a classified device error
    on one chunk's primary execution re-runs THAT chunk on the CPU rung
    from the last boundary's host carry — the run CONTINUES (no host-level
    retry consumed), every boundary records the rung it ran at, a
    ``backend_event`` lands in both the journal and the metrics file, and
    the completed trajectory is bit-identical to the unguarded one."""
    from tpu_aerial_transport.resilience import backend as backend_mod

    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    d = str(tmp_path)
    plan = recovery.RunPlan(run_dir=d, n_hl_steps=N_HL, n_chunks=CHUNKS)
    metrics_path = str(tmp_path / "run.metrics.jsonl")
    guard = backend_mod.BackendGuard(
        deadline_s=300.0,
        faults=backend_mod.FaultInjector(crash_at=2),  # 2nd chunk crashes.
    )
    res = recovery.run_chunks(
        plan, runner.chunk_jit, _fresh_carry(runner, state0, cs0),
        metrics=metrics_path, guard=guard,
    )
    assert res.status == "done" and res.retries == 0
    s2, c2 = res.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res.logs),
                        "after guard degradation")

    events = recovery.RunJournal(d).read()
    be = [e for e in events if e.get("event") == "backend_event"]
    assert [e["kind"] for e in be] == ["device_crash"]
    assert be[0]["label"] == "chunk1"
    chunk_rungs = [e.get("rung") for e in events
                   if e.get("event") == "chunk"]
    # Every boundary records its rung; chunk 1 (and everything after the
    # one-way degradation) ran on the CPU rung.
    assert len(chunk_rungs) == CHUNKS
    assert all(r is not None for r in chunk_rungs)
    assert chunk_rungs[1:] == [backend_mod.RUNG_CPU] * (CHUNKS - 1)

    from tpu_aerial_transport.obs import export as export_mod

    assert export_mod.validate_file(metrics_path) == []
    mev = export_mod.read_events(metrics_path)
    assert [e["kind"] for e in mev if e["event"] == "backend_event"] \
        == ["device_crash"]
    assert [e.get("rung") for e in mev if e["event"] == "chunk"] \
        == chunk_rungs


def test_run_chunks_guard_unknown_error_still_host_retries(tmp_path):
    """An UNCLASSIFIED chunk failure is a code bug: the guard re-raises
    it and the pre-existing host-level retry machinery (max_retries)
    handles it exactly as before — guard and retry tiers compose."""
    from tpu_aerial_transport.resilience import backend as backend_mod

    params, cfg, state0, cs0, ll, hl, acc_des_fn = _problem()
    fs, fc, flog = _reference(params, state0, cs0, ll, hl, acc_des_fn)
    runner = _runner(params, ll, hl, acc_des_fn)
    plan = recovery.RunPlan(run_dir=str(tmp_path), n_hl_steps=N_HL,
                            n_chunks=CHUNKS)
    calls = {"n": 0}

    def flaky_chunk(carry, i0):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated transient code-path error")
        return runner.chunk_jit(carry, i0)

    guard = backend_mod.BackendGuard(
        deadline_s=300.0, faults=backend_mod.FaultInjector()
    )
    res = recovery.run_chunks(
        plan, flaky_chunk, _fresh_carry(runner, state0, cs0),
        max_retries=1, guard=guard,
    )
    assert res.status == "done" and res.retries == 1
    s2, c2 = res.carry
    _assert_trees_equal((fs, fc, flog), (s2, c2, res.logs),
                        "retry under guard")
