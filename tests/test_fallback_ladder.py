"""Fallback-ladder property tests: a forced-divergent high-level solve must
walk the rungs in order (clean -> retry -> hold-previous -> equilibrium) and
never feed non-finite forces to the physics."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import centralized, lowlevel
from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.resilience.rollout import (
    RUNG_CLEAN,
    RUNG_EQUILIBRIUM,
    RUNG_HOLD,
    RUNG_RETRY,
    resilient_rollout,
)


def _stats(ok_frac):
    return SolverStats(
        iters=jnp.zeros((), jnp.int32),
        solve_res=jnp.zeros(()),
        collision=jnp.zeros((), bool),
        min_env_dist=jnp.zeros(()),
        ok_frac=jnp.asarray(ok_frac, jnp.float32),
    )


def _run_scripted(script_fdes, script_okfrac, n_steps):
    """Roll out with a scripted stub controller: at step i it returns
    ``script_fdes(i, f_eq)`` and reports ``script_okfrac(i)``."""
    params, _, state0 = setup.rqp_setup(3)
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)

    def hl_step(cs, state, acc_des, health=None):
        i = cs
        return script_fdes(i, f_eq), i + 1, _stats(script_okfrac(i))

    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl_step, ll.control, params, s, c, n_hl_steps=n_steps
        )
    )(state0, jnp.zeros((), jnp.int32))
    return params, f_eq, final, logs


def test_ladder_walks_rungs_in_order():
    """Scripted failure sequence: clean, internal-retry, NaN (hold), clean
    again — the logged rungs must be exactly [0, 1, 2, 0] and the held step
    must reuse the previous step's applied force."""
    nan = jnp.nan

    def fdes(i, f_eq):
        good = f_eq * (1.0 + 0.01 * i.astype(f_eq.dtype))
        return jnp.where(i == 2, jnp.full_like(f_eq, nan), good)

    def okf(i):
        return jnp.where(i == 1, 0.5, 1.0)

    params, f_eq, final, logs = _run_scripted(fdes, okf, 4)
    assert [int(r) for r in logs.fallback_rung] == [
        RUNG_CLEAN, RUNG_RETRY, RUNG_HOLD, RUNG_CLEAN
    ]
    # The held step re-applied step 1's force, not the NaNs.
    assert bool(jnp.all(jnp.isfinite(logs.f_des)))
    assert float(jnp.abs(logs.f_des[2] - logs.f_des[1]).max()) == 0.0
    # Physics never saw a non-finite wrench.
    assert bool(jnp.all(jnp.isfinite(logs.xl)))
    assert bool(jnp.all(jnp.isfinite(final.xl)))


def test_ladder_bottom_rung_equilibrium_on_first_step():
    """A solver that diverges from the very first step (no previous force to
    hold) must land on the equilibrium rung, then hold it afterwards."""

    def fdes(i, f_eq):
        return jnp.full_like(f_eq, jnp.nan)

    def okf(i):
        return jnp.ones(())

    params, f_eq, final, logs = _run_scripted(fdes, okf, 3)
    rungs = [int(r) for r in logs.fallback_rung]
    assert rungs[0] == RUNG_EQUILIBRIUM
    assert rungs[1:] == [RUNG_HOLD, RUNG_HOLD]
    # Step 0 applied exactly the equilibrium forces; later steps held them.
    assert float(jnp.abs(logs.f_des[0] - f_eq).max()) == 0.0
    assert float(jnp.abs(logs.f_des[1] - f_eq).max()) == 0.0
    assert bool(jnp.all(jnp.isfinite(final.xl)))


def test_ladder_counts_internal_retries():
    """ok_frac < 1 with finite forces is the retry rung — forces pass
    through unchanged (the controller already substituted its own internal
    fallback)."""

    def fdes(i, f_eq):
        return f_eq * 1.01

    def okf(i):
        return jnp.full((), 0.75)

    params, f_eq, final, logs = _run_scripted(fdes, okf, 2)
    assert [int(r) for r in logs.fallback_rung] == [RUNG_RETRY, RUNG_RETRY]
    assert float(jnp.abs(logs.f_des[0] - f_eq * 1.01).max()) == 0.0
