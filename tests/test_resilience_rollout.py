"""Closed-loop resilience acceptance tests (ISSUE 1): an n=4 RQP rollout
survives a mid-flight agent loss (and, separately, 30% consensus-message
dropout) without NaNs, with the survivors redistributing the payload load
and the payload tracking error bounded."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport import resilience
from tpu_aerial_transport.control import cadmm, dd, lowlevel
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.resilience import faults as faults_mod
from tpu_aerial_transport.resilience.rollout import resilient_rollout

GRAVITY = rqp.GRAVITY


def _cadmm_setup(n=4):
    params, col, state0 = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=15, inner_iters=20,
    )
    hl = resilience.make_cadmm_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = cadmm.init_cadmm_state(params, cfg)
    return params, state0, hl, ll, cs0


def test_agent_loss_at_1s_redistributes_and_tracks():
    """One agent killed at t = 1 s (HL step 100 at 100 Hz): the rollout
    completes without NaNs, the dead agent applies nothing, the survivors
    pick up its share of the payload weight, and the hover tracking error
    stays bounded through the transient."""
    n = 4
    params, state0, hl, ll, cs0 = _cadmm_setup(n)
    sched = faults_mod.make_schedule(n, t_fail={0: 100})
    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=170, faults=sched
        )
    )(state0, cs0)

    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert bool(jnp.all(jnp.isfinite(logs.xl)))
    assert not bool(jnp.any(logs.quarantined))
    # Dead agent applies nothing from the failure step on.
    assert float(jnp.abs(logs.f_des[105:, 0]).max()) == 0.0
    # ... and was actually flying before it.
    assert float(jnp.abs(logs.f_des[:95, 0, 2]).min()) > 0.0
    # Survivors redistribute: total commanded vertical force returns to the
    # payload weight (mT g) once the transient settles.
    mTg = float(params.mT) * GRAVITY
    tot = float(jnp.mean(jnp.sum(logs.f_des[150:, 1:, 2], axis=-1)))
    assert 0.8 * mTg < tot < 1.2 * mTg, tot
    # Payload tracking error bounded through the loss transient (hover at
    # the origin; losing 1 of 4 agents keeps hover feasible: 3 x max_f =
    # 1.5 mT g).
    assert float(jnp.max(logs.x_err)) < 0.5
    assert float(jnp.max(logs.x_err[-10:])) < 0.25


def test_consensus_dropout_30pct_stays_bounded():
    """30% consensus-message dropout (held in 5-step blocks): the masked
    consensus means/residuals keep every step finite and the payload
    tracking error bounded."""
    n = 4
    params, state0, hl, ll, cs0 = _cadmm_setup(n)
    sched = faults_mod.make_schedule(
        n, drop_rate=0.3, drop_hold=5, key=jax.random.PRNGKey(7)
    )
    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=120, faults=sched
        )
    )(state0, cs0)

    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert bool(jnp.all(jnp.isfinite(logs.f_des)))
    assert not bool(jnp.any(logs.quarantined))
    assert float(jnp.max(logs.x_err)) < 0.3
    # All four agents keep flying.
    assert float(jnp.min(logs.f_des[:, :, 2])) > 0.0


def test_dd_agent_loss_short_rollout():
    """The DD controller's masked price/violation aggregations survive an
    agent loss too (shorter horizon: DD's inner solves are deeper)."""
    n = 4
    params, col, state0 = setup.rqp_setup(n)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=10, inner_iters=40,
    )
    hl = resilience.make_dd_hl_step(params, cfg)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    cs0 = dd.init_dd_state(params, cfg)
    sched = faults_mod.make_schedule(n, t_fail={2: 20})
    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=60, faults=sched
        )
    )(state0, cs0)

    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert not bool(jnp.any(logs.quarantined))
    assert float(jnp.abs(logs.f_des[25:, 2]).max()) == 0.0
    mTg = float(params.mT) * GRAVITY
    tot = float(jnp.mean(jnp.sum(
        logs.f_des[50:, [0, 1, 3], 2], axis=-1)))
    assert 0.7 * mTg < tot < 1.3 * mTg, tot
    assert float(jnp.max(logs.x_err)) < 0.5


def test_sensor_noise_and_degradation_stay_finite():
    """Actuator degradation (40% thrust-cap loss on two agents) plus sensor
    noise on the controller's state view: the true physics stays finite and
    tracking degrades gracefully rather than diverging."""
    n = 4
    params, state0, hl, ll, cs0 = _cadmm_setup(n)
    sched = faults_mod.make_schedule(
        n,
        t_degrade={1: 30, 3: 30},
        thrust_scale=0.6,
        noise_std=5e-3,
        key=jax.random.PRNGKey(3),
    )
    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=80, faults=sched
        )
    )(state0, cs0)
    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert not bool(jnp.any(logs.quarantined))
    assert float(jnp.max(logs.x_err)) < 0.5


def test_total_consensus_blackout_flags_degraded_rung():
    """drop_rate = 1: every step is a consensus blackout (masked residual
    vacuously 0). Such steps must surface on the retry rung instead of
    logging as the cleanest in the run, while the team holds formation on
    held values."""
    n = 4
    params, state0, hl, ll, cs0 = _cadmm_setup(n)
    sched = faults_mod.make_schedule(
        n, drop_rate=1.0, drop_hold=2, key=jax.random.PRNGKey(0)
    )
    final, _, logs = jax.jit(
        lambda s, c: resilient_rollout(
            hl, ll.control, params, s, c, n_hl_steps=12, faults=sched
        )
    )(state0, cs0)
    assert bool(jnp.all(jnp.isfinite(final.xl)))
    assert bool(jnp.all(logs.fallback_rung >= 1))
    assert float(jnp.max(logs.x_err)) < 0.3
