"""Closed-loop session serving (tpu_aerial_transport/serving/
sessions.py): lease lifecycle with fenced eviction (a zombie's stale
token can NEVER write into a reclaimed lane), step-sequenced admission
(replay/out-of-order -> structured ``stale_step``), per-step deadline
SLOs that degrade to an explicit ``hold_last`` rung instead of raising,
crash-safe session tables (bitwise acceptance across a mid-stream
SIGTERM+resume), fleet re-homing on the SAME trace_id, the autoscale
hint's no-flap hysteresis, and the result-cache refusal for delta-state
steps."""

import json
import os

import jax
import numpy as np
import pytest

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.serving import batcher, cache as cache_mod
from tpu_aerial_transport.serving import fleet as fleet_mod
from tpu_aerial_transport.serving import queue as queue_mod
from tpu_aerial_transport.serving import server as server_mod
from tpu_aerial_transport.serving import sessions as sessions_mod
from tpu_aerial_transport.serving.queue import ScenarioRequest

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeInterrupt:
    triggered = None


@pytest.fixture(scope="session")
def cadmm_family():
    """ONE family instance per session so its batched chunk compiles
    once across every jit-path test in this module."""
    return batcher.make_family("cadmm4")


def _mk_server(fam, tmp_path=None, **kw):
    kw.setdefault("families", [fam])
    kw.setdefault("buckets", (4, 8))
    if tmp_path is not None:
        kw.setdefault("metrics", str(tmp_path / "sess.metrics.jsonl"))
    return server_mod.ScenarioServer(**kw)


def _drain(host):
    while host.pump():
        pass


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb) and la
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------------------
# Lease lifecycle (no device work — fake clock only).
# ----------------------------------------------------------------------

def test_lease_lifecycle_renew_evict_fence_reconnect(
        cadmm_family, tmp_path):
    """The state machine end to end on a fake clock: heartbeat renews,
    TTL expiry evicts and fences, the zombie's token is rejected
    structurally, reconnect mints the next epoch and resets the
    watermark."""
    now = [0.0]
    srv = _mk_server(cadmm_family, tmp_path, clock=lambda: now[0])
    host = sessions_mod.SessionHost(srv, lease_s=5.0)

    grant = host.open("alice", "cadmm4", (0.2, 0.1, 1.0))
    assert grant["ok"] and grant["lease"] == "alice:l0"
    assert grant["step_seq"] == 0

    now[0] = 4.0  # inside the TTL: renew works, gap recorded.
    hb = host.heartbeat("alice", "alice:l0")
    assert hb["ok"] and hb["expires_in_s"] == 5.0

    now[0] = 8.0  # 4s gap < TTL: still live.
    assert host.sweep() == []
    now[0] = 9.5  # 5.5s of silence: the sweep evicts and fences.
    assert host.sweep() == ["alice"]
    assert host.sessions["alice"].status == sessions_mod.EVICTED

    # The zombie: heartbeat AND step with the fenced token both get the
    # structured rejection — never an exception, never a server write.
    hb = host.heartbeat("alice", "alice:l0")
    assert (hb["ok"], hb["reason"]) == (
        False, queue_mod.REASON_LEASE_FENCED)
    zs = host.step("alice", "alice:l0", 1)
    assert (zs.status, zs.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_LEASE_FENCED)
    assert not srv.has_work()

    # Reconnect: NEW lease (next epoch), watermark reset.
    grant2 = host.open("alice", "cadmm4", (0.3, 0.1, 1.0))
    assert grant2["ok"] and grant2["lease"] == "alice:l1"
    assert host.sessions["alice"].step_seq == 0
    # ... and the OLD token stays fenced even while the session lives.
    zs = host.step("alice", "alice:l0", 1)
    assert zs.reason == queue_mod.REASON_LEASE_FENCED
    assert host.stats()["fenced_rejections"] == 3

    assert export_mod.validate_file(
        str(tmp_path / "sess.metrics.jsonl")) == []


def test_open_unknown_family_structured(cadmm_family):
    host = sessions_mod.SessionHost(_mk_server(cadmm_family))
    grant = host.open("a", "martian")
    assert (grant["ok"], grant["reason"]) == (
        False, queue_mod.REASON_NO_COVERAGE)


def test_duplicate_open_fences_the_first_writer(cadmm_family):
    """Two clients claiming one session_id: the second open supersedes —
    exactly one lease can ever write."""
    host = sessions_mod.SessionHost(_mk_server(cadmm_family))
    first = host.open("s", "cadmm4")["lease"]
    second = host.open("s", "cadmm4")["lease"]
    assert first != second
    assert host.heartbeat("s", first)["reason"] == \
        queue_mod.REASON_LEASE_FENCED
    assert host.heartbeat("s", second)["ok"]


def test_resolve_lease_s_env_force(monkeypatch):
    assert sessions_mod.resolve_lease_s(None) == \
        sessions_mod.DEFAULT_LEASE_S
    assert sessions_mod.resolve_lease_s(2.5) == 2.5
    monkeypatch.setenv("TAT_SESSION_LEASE_S", "0.25")
    assert sessions_mod.resolve_lease_s(60.0) == 0.25  # env force wins.
    monkeypatch.setenv("TAT_SESSION_LEASE_S", "nope")
    with pytest.raises(ValueError):
        sessions_mod.resolve_lease_s(None)
    monkeypatch.setenv("TAT_SESSION_LEASE_S", "-1")
    with pytest.raises(ValueError):
        sessions_mod.resolve_lease_s(None)


# ----------------------------------------------------------------------
# Step-sequenced admission.
# ----------------------------------------------------------------------

def test_stale_step_replay_and_out_of_order(cadmm_family, tmp_path):
    """A replayed or skipped-ahead step_seq rejects ``stale_step`` and
    the watermark does not move; the in-order step then serves."""
    srv = _mk_server(cadmm_family, tmp_path)
    host = sessions_mod.SessionHost(srv, lease_s=1e6)
    lease = host.open("s", "cadmm4", (0.4, 0.1, 1.0))["lease"]

    s1 = host.step("s", lease, 1, (0.01, 0.0, 0.0))
    replay = host.step("s", lease, 1, (9.9, 9.9, 9.9))
    assert (replay.status, replay.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_STALE_STEP)
    skip = host.step("s", lease, 3, (9.9, 9.9, 9.9))
    assert skip.reason == queue_mod.REASON_STALE_STEP
    assert host.sessions["s"].step_seq == 1  # watermark unmoved.
    # The rejected deltas did NOT touch the state stream.
    np.testing.assert_array_equal(
        host.sessions["s"].x,
        np.asarray((0.4, 0.1, 1.0), np.float64)
        + np.asarray((0.01, 0.0, 0.0), np.float64))

    s2 = host.step("s", lease, 2, (0.01, 0.0, 0.0))
    _drain(host)
    assert s1.rung == s2.rung == sessions_mod.RUNG_SERVED
    assert host.stats()["stale_rejections"] == 2

    events = export_mod.read_events(str(tmp_path / "sess.metrics.jsonl"))
    stale = [e for e in events if e.get("kind") == "stale_step"]
    assert [(e["step_seq"], e["expected"]) for e in stale] == \
        [(1, 2), (3, 2)]


def test_zombie_fence_never_writes_into_reclaimed_lane(
        cadmm_family, tmp_path):
    """THE fencing acceptance: after eviction the zombie's step leaves
    NO trace server-side — no ticket, no journaled serving_request, no
    journaled session_step — and the surviving session's served stream
    is bitwise identical to a zombie-free run."""
    now = [0.0]
    run_dir = str(tmp_path / "run")
    srv = _mk_server(cadmm_family, tmp_path, clock=lambda: now[0],
                     run_dir=run_dir)
    host = sessions_mod.SessionHost(srv, lease_s=5.0)
    alice = host.open("alice", "cadmm4", (0.2, 0.1, 1.0))["lease"]
    bob = host.open("bob", "cadmm4", (0.5, 0.1, 1.0))["lease"]
    a1 = host.step("alice", alice, 1, (0.01, 0.0, 0.0))
    b1 = host.step("bob", bob, 1, (0.02, 0.0, 0.0))
    _drain(host)
    assert a1.rung == b1.rung == sessions_mod.RUNG_SERVED

    now[0] = 4.0
    host.heartbeat("bob", bob)  # bob keeps renewing...
    now[0] = 8.0  # ...alice is now 8s silent past the 5s TTL.
    host.heartbeat("bob", bob)
    assert host.sessions["alice"].status == sessions_mod.EVICTED

    zs = host.step("alice", alice, 2, (7.7, 7.7, 7.7))
    assert zs.reason == queue_mod.REASON_LEASE_FENCED
    assert zs.request_id not in srv.tickets
    journal = [json.loads(line) for line in
               open(os.path.join(run_dir, "serving_journal.jsonl"))]
    assert not any(
        e.get("event") == "serving_request"
        and e["request"]["request_id"] == zs.request_id
        for e in journal)
    assert not any(
        e.get("event") == "session_step" and e.get("step_seq") == 2
        and e.get("session_id") == "alice"
        for e in journal)

    # Bob's NEXT step is bitwise what a zombie-free server serves for
    # the same state (the lane the zombie aimed at is provably clean).
    b2 = host.step("bob", bob, 2, (0.02, 0.0, 0.0))
    _drain(host)
    ref_srv = _mk_server(cadmm_family)
    ref = ref_srv.submit(ScenarioRequest(
        family="cadmm4", horizon=cadmm_family.chunk_len,
        x0=tuple(float(t) for t in host.sessions["bob"].x),
        v0=tuple(float(t) for t in host.sessions["bob"].v),
        request_id="ref"))
    while ref_srv.pump():
        pass
    _assert_tree_equal(b2.result, ref.result)


def test_step_rid_roundtrip_and_lookalike_rejection():
    """The canonical step rid is epoch-qualified and strictly parseable;
    caller-chosen one-shot rids that merely contain '.s' do not parse
    (the guard behind the fleet's rid->session fallback)."""
    rid = sessions_mod._step_rid("c0", 3, 41)
    assert rid == "c0.e3.s000041"
    assert sessions_mod.parse_step_rid(rid) == ("c0", 3, 41)
    # Session ids containing dots still round-trip (longest prefix).
    assert sessions_mod.parse_step_rid("a.b.e0.s000001") == ("a.b", 0, 1)
    for bad in ("req.solver1", "c0.s000001", "c0.e1.s1", "c0.e.s000001",
                "c0.e1.s0000010x", "warmup"):
        assert sessions_mod.parse_step_rid(bad) is None


def test_admission_reject_consumes_nothing(cadmm_family, tmp_path):
    """Regression (REVIEW): a step rejected at ADMISSION (queue full)
    must not consume the seq or bake its delta into the state stream —
    nothing is journaled, and the client retries the SAME seq and gets
    the control the offline rollout serves for that state."""
    run_dir = str(tmp_path / "run")
    srv = _mk_server(cadmm_family, tmp_path, capacity=2,
                     run_dir=run_dir)
    host = sessions_mod.SessionHost(srv, lease_s=1e9)
    lease = host.open("s", "cadmm4", (0.4, 0.1, 1.0))["lease"]

    # Fill the admission queue to capacity with one-shots.
    for i in range(2):
        srv.submit(ScenarioRequest(
            family="cadmm4", horizon=cadmm_family.chunk_len,
            x0=(0.1 * (i + 1), 0.0, 1.0), request_id=f"fill{i}"))
    t = host.step("s", lease, 1, (0.05, 0.0, 0.0))
    assert (t.status, t.reason) == (
        queue_mod.REJECTED, queue_mod.REASON_QUEUE_FULL)
    # Rolled back: watermark unmoved, delta NOT applied, no journal row.
    assert host.sessions["s"].step_seq == 0
    np.testing.assert_array_equal(
        host.sessions["s"].x, np.asarray((0.4, 0.1, 1.0), np.float64))
    assert host.stats()["steps_accepted"] == 0
    journal = [json.loads(line) for line in
               open(os.path.join(run_dir, "serving_journal.jsonl"))]
    assert not any(e.get("event") == "session_step" for e in journal)

    _drain(host)  # the queue drains; the SAME seq now serves.
    retry = host.step("s", lease, 1, (0.05, 0.0, 0.0))
    _drain(host)
    assert retry.rung == sessions_mod.RUNG_SERVED
    ref = _offline_digileaves(
        cadmm_family, (0.4, 0.1, 1.0), (0.0, 0.0, 0.0),
        [((0.05, 0.0, 0.0), (0.0, 0.0, 0.0))])
    _assert_tree_equal(retry.result, ref[1])


def test_fenced_inflight_result_never_writes_new_incarnation(
        cadmm_family, tmp_path):
    """Regression (REVIEW): a step submitted by a superseded incarnation
    that resolves AFTER the reconnect resolves its own ticket but never
    writes hold-last/lane state onto the new incarnation — and a
    deadline miss before the new incarnation was ever served resolves
    at the honest ``no_control`` rung (None is not a control)."""
    now = [0.0]
    srv = _mk_server(cadmm_family, tmp_path, clock=lambda: now[0])
    host = sessions_mod.SessionHost(srv, lease_s=1e9)
    l0 = host.open("s", "cadmm4", (0.3, 0.1, 1.0))["lease"]
    old = host.step("s", l0, 1, (0.01, 0.0, 0.0))  # in flight...
    assert not old.done

    l1 = host.open("s", "cadmm4", (0.6, 0.2, 1.0))["lease"]  # reconnect
    assert l1 != l0
    _drain(host)  # the fenced incarnation's step resolves as an orphan.
    assert old.rung == sessions_mod.RUNG_SERVED
    assert old.result is not None
    sess = host.sessions["s"]
    assert sess.epoch == 1
    assert sess.last_result is None  # the new incarnation saw NOTHING.
    assert sess.lane is None and sess.batch_id is None

    # First step of the new incarnation misses in queue: there is no
    # last control to hold — the rung says so instead of dressing None
    # up as a served control.
    t1 = host.step("s", l1, 1, (0.01, 0.0, 0.0), deadline_s=5.0)
    now[0] = 20.0
    _drain(host)
    assert (t1.status, t1.rung, t1.missed) == (
        queue_mod.COMPLETED, sessions_mod.RUNG_NO_CONTROL,
        queue_mod.MISSED_IN_QUEUE)
    assert t1.result is None


# ----------------------------------------------------------------------
# Per-step deadline SLOs: degrade, never raise.
# ----------------------------------------------------------------------

def test_deadline_miss_storm_degrades_every_step(cadmm_family, tmp_path):
    """A deadline-miss storm resolves EVERY step with an explicit rung —
    hold_last carrying the last served control, misses classified
    in_queue vs in_flight, no exception in the server loop — and the
    traced requests' critical-path segments sum exactly."""
    now = [0.0]
    rows = []

    class Sink:
        # A single-chunk step launches AND harvests inside one pump, so
        # an in-flight miss needs the clock to move MID-pump: jump it
        # when the batch_launch event lands (after admission passed the
        # deadline gate, before the harvest reads the clock).
        jump = None  # (kind, t)

        def emit(self, event, **kw):
            rows.append({"event": event, **kw})
            if self.jump is not None and kw.get("kind") == self.jump[0]:
                now[0] = self.jump[1]
                self.jump = None

    sink = Sink()
    tracer = trace_mod.Tracer(sink, track="server",
                              clock_mono=lambda: now[0])
    srv = _mk_server(cadmm_family, clock=lambda: now[0], metrics=sink,
                     tracer=tracer)
    host = sessions_mod.SessionHost(srv, lease_s=1e9)
    lease = host.open("s", "cadmm4", (0.3, 0.1, 1.0))["lease"]

    s1 = host.step("s", lease, 1, (0.01, 0.0, 0.0))
    _drain(host)
    assert s1.rung == sessions_mod.RUNG_SERVED

    # MISS IN QUEUE: the deadline passes before the step is launched.
    s2 = host.step("s", lease, 2, (0.01, 0.0, 0.0), deadline_s=5.0)
    now[0] = 20.0
    _drain(host)
    assert (s2.status, s2.rung, s2.missed) == (
        queue_mod.COMPLETED, sessions_mod.RUNG_HOLD_LAST,
        queue_mod.MISSED_IN_QUEUE)
    _assert_tree_equal(s2.result, s1.result)  # held control.

    # MISS IN FLIGHT: launched in time, finishes late — the step still
    # degrades to hold_last, and the LATE fresh result refreshes the
    # hold-last state for the next degradation.
    s3 = host.step("s", lease, 3, (0.01, 0.0, 0.0), deadline_s=5.0)
    sink.jump = ("batch_launch", 40.0)  # launched in time, harvested late.
    _drain(host)
    assert (s3.rung, s3.missed) == (
        sessions_mod.RUNG_HOLD_LAST, queue_mod.MISSED_IN_FLIGHT)
    _assert_tree_equal(s3.result, s1.result)  # held (served stream).
    assert s3.ticket.result is not None  # the late result DID land...
    assert host.sessions["s"].last_result is s3.ticket.result  # ...here.

    s4 = host.step("s", lease, 4, (0.01, 0.0, 0.0))
    _drain(host)
    assert s4.rung == sessions_mod.RUNG_SERVED
    assert host.stats()["steps_degraded"] == 2

    # Every step resolved; the degradations are first-class events.
    degraded = [r for r in rows if r.get("kind") == "step_degraded"]
    assert [(e["step_seq"], e["missed"]) for e in degraded] == [
        (2, queue_mod.MISSED_IN_QUEUE), (3, queue_mod.MISSED_IN_FLIGHT)]

    # Spans / critical path: each completed traced request's segments
    # sum exactly to its submit->complete window.
    cp = trace_mod.critical_path(trace_mod.stitch(tracer.rows))
    done = [q for q in cp["requests"] if q["status"] == "completed"]
    assert done
    for q in done:
        assert sum(q["segments"].values()) == pytest.approx(
            q["total_s"], abs=1e-9)


# ----------------------------------------------------------------------
# Bitwise acceptance: sessions == offline rollout, across SIGTERM+resume.
# ----------------------------------------------------------------------

def _offline_digileaves(fam, x0, v0, deltas):
    """The offline rollout: cumulative post-delta states served as
    one-shot requests on a FRESH server."""
    srv = server_mod.ScenarioServer(families=[fam], buckets=(4, 8))
    tickets = {}
    x = np.asarray(x0, dtype=np.float64)
    v = np.asarray(v0, dtype=np.float64)
    for s, (dx, dv) in enumerate(deltas, start=1):
        x = x + np.asarray(dx, dtype=np.float64)
        v = v + np.asarray(dv, dtype=np.float64)
        tickets[s] = srv.submit(ScenarioRequest(
            family="cadmm4", horizon=fam.chunk_len,
            x0=tuple(float(t) for t in x), v0=tuple(float(t) for t in v),
            request_id=f"off{s:03d}"))
    while srv.pump():
        pass
    return {s: t.result for s, t in tickets.items()}


def test_session_stream_bitwise_equals_offline_rollout(
        cadmm_family, tmp_path):
    """The tentpole claim, single-process edition: a session's served
    control stream (steps interleaved with ANOTHER session in the same
    batches) is bitwise the offline rollout of its state stream."""
    deltas = {
        "p": [((0.01, 0.0, 0.0), (0.0, 0.001, 0.0)) for _ in range(3)],
        "q": [((-0.02, 0.01, 0.0), (0.0, 0.0, 0.0)) for _ in range(3)],
    }
    x0 = {"p": (0.3, 0.1, 1.0), "q": (0.7, 0.2, 1.1)}
    v0 = {"p": (0.1, 0.0, 0.0), "q": (0.0, 0.1, 0.0)}

    srv = _mk_server(cadmm_family, tmp_path)
    host = sessions_mod.SessionHost(srv, lease_s=1e9)
    leases = {sid: host.open(sid, "cadmm4", x0[sid], v0[sid])["lease"]
              for sid in deltas}
    served = {}
    for s in range(1, 4):
        batch = [host.step(sid, leases[sid], s, *deltas[sid][s - 1])
                 for sid in sorted(deltas)]
        _drain(host)
        for t in batch:
            assert t.rung == sessions_mod.RUNG_SERVED
            served[(t.session_id, t.step_seq)] = t.result

    for sid in deltas:
        ref = _offline_digileaves(cadmm_family, x0[sid], v0[sid],
                                  deltas[sid])
        for s in range(1, 4):
            _assert_tree_equal(served[(sid, s)], ref[s])


@pytest.mark.slow
def test_session_sigterm_resume_bitwise_acceptance(
        cadmm_family, tmp_path):
    """THE acceptance e2e: mid-stream SIGTERM with a step in flight,
    then resume — the session table restores bit-identically (lease,
    watermark, float64 state), the in-flight step completes, post-resume
    steps serve, and the WHOLE served stream is bitwise the offline
    rollout."""
    deltas = [((0.01, -0.005, 0.0), (0.001, 0.0, 0.0))
              for _ in range(4)]
    x0, v0 = (0.25, 0.1, 1.0), (0.1, 0.0, 0.0)
    run_dir = str(tmp_path / "run")

    fi = FakeInterrupt()
    srv1 = _mk_server(cadmm_family, run_dir=run_dir, interrupt=fi)
    host1 = sessions_mod.SessionHost(srv1, lease_s=1e9)
    lease1 = host1.open("s", "cadmm4", x0, v0)["lease"]
    served = {}
    for s in (1, 2):
        t = host1.step("s", lease1, s, *deltas[s - 1])
        _drain(host1)
        assert t.rung == sessions_mod.RUNG_SERVED
        served[s] = t.result
    t3 = host1.step("s", lease1, 3, *deltas[2])  # journaled, queued...
    fi.triggered = "SIGTERM"
    host1.pump()  # the preemption lands at pump start: t3 stays queued.
    assert srv1.preempted and not t3.done

    srv2 = server_mod.ScenarioServer.resume(
        run_dir, families=[cadmm_family], buckets=(4, 8))
    host2 = sessions_mod.SessionHost.resume(srv2, lease_s=1e9)
    sess = host2.sessions["s"]
    # Bit-identical restore: lease token, epoch, watermark, f64 state.
    assert (sess.lease, sess.epoch, sess.step_seq) == (lease1, 0, 3)
    want = np.asarray(x0, np.float64)
    for d in deltas[:3]:  # sequential, the order the host applied them.
        want = want + np.asarray(d[0], np.float64)
    np.testing.assert_array_equal(sess.x, want)
    assert sess.status == sessions_mod.LIVE  # lease re-armed.

    r3 = host2._steps[t3.request_id]  # reattached in-flight step.
    _drain(host2)
    assert r3.rung == sessions_mod.RUNG_SERVED
    served[3] = r3.result
    t4 = host2.step("s", sess.lease, 4, *deltas[3])
    _drain(host2)
    assert t4.rung == sessions_mod.RUNG_SERVED
    served[4] = t4.result

    ref = _offline_digileaves(cadmm_family, x0, v0, deltas)
    for s in range(1, 5):
        _assert_tree_equal(served[s], ref[s])


@pytest.mark.slow
def test_reconnect_crash_resume_epochs_never_alias(
        cadmm_family, tmp_path):
    """Regression (REVIEW): step identities carry the lease epoch, so a
    reconnect incarnation's in-flight step whose seq matches a COMPLETED
    old-epoch step is not swallowed by resume's done-request dedup — it
    reattaches and serves, bitwise the offline rollout of the new
    incarnation's state stream."""
    run_dir = str(tmp_path / "run")
    fi = FakeInterrupt()
    srv1 = _mk_server(cadmm_family, run_dir=run_dir, interrupt=fi)
    host1 = sessions_mod.SessionHost(srv1, lease_s=1e9)

    l0 = host1.open("s", "cadmm4", (0.25, 0.1, 1.0))["lease"]
    t_old = host1.step("s", l0, 1, (0.01, 0.0, 0.0))
    _drain(host1)
    assert t_old.rung == sessions_mod.RUNG_SERVED  # epoch-0 step 1 DONE.

    x0b, db = (0.55, 0.2, 1.0), ((0.02, -0.01, 0.0), (0.0, 0.001, 0.0))
    l1 = host1.open("s", "cadmm4", x0b)["lease"]  # reconnect: epoch 1.
    t_new = host1.step("s", l1, 1, *db)           # same SEQ, in flight.
    assert t_new.request_id != t_old.request_id   # epoch-qualified rid.
    fi.triggered = "SIGTERM"
    host1.pump()
    assert srv1.preempted and not t_new.done

    srv2 = server_mod.ScenarioServer.resume(
        run_dir, families=[cadmm_family], buckets=(4, 8))
    assert t_old.request_id in srv2.done_requests  # the alias hazard...
    host2 = sessions_mod.SessionHost.resume(srv2, lease_s=1e9)
    sess = host2.sessions["s"]
    assert (sess.lease, sess.epoch, sess.step_seq) == (l1, 1, 1)
    # ...and the new incarnation's step was NOT treated as done: it is
    # reattached and completes.
    r1 = host2._steps[t_new.request_id]
    _drain(host2)
    assert r1.rung == sessions_mod.RUNG_SERVED
    t2 = host2.step("s", l1, 2, *db)  # the stream continues post-resume.
    _drain(host2)
    assert t2.rung == sessions_mod.RUNG_SERVED
    ref = _offline_digileaves(cadmm_family, x0b, (0.0, 0.0, 0.0),
                              [db, db])
    _assert_tree_equal(r1.result, ref[1])
    _assert_tree_equal(t2.result, ref[2])


# ----------------------------------------------------------------------
# Result cache x sessions: delta-state steps are NEVER cache-served.
# ----------------------------------------------------------------------

def test_session_steps_never_served_from_result_cache(
        cadmm_family, tmp_path):
    """Regression: a session step whose post-delta state content-matches
    a cached one-shot result must NOT resolve from the cache (closed-
    loop state is not idempotent request content) and must not populate
    it either."""
    srv = _mk_server(cadmm_family, tmp_path, cache=8)
    host = sessions_mod.SessionHost(srv, lease_s=1e9)

    # Warm the cache with a one-shot whose content equals the session's
    # post-delta step-1 state.
    warm = srv.submit(ScenarioRequest(
        family="cadmm4", horizon=cadmm_family.chunk_len,
        x0=(0.35, 0.1, 1.0), v0=(0.1, 0.0, 0.0), request_id="warm"))
    _drain(host)
    assert warm.status == queue_mod.COMPLETED
    key = cache_mod.request_key(
        cadmm_family.config_hash(), warm.request)
    assert srv.cache.get(key) is not None

    lease = host.open("s", "cadmm4", (0.3, 0.1, 1.0),
                      (0.1, 0.0, 0.0))["lease"]
    s1 = host.step("s", lease, 1, (0.05, 0.0, 0.0))
    assert not s1.done  # NOT cache-resolved at submit.
    hits_before = srv.cache.stats()["hits"]
    _drain(host)
    assert s1.rung == sessions_mod.RUNG_SERVED
    assert srv.cache.stats()["hits"] == hits_before  # no hit charged.
    # ... bitwise the same answer, computed not replayed.
    _assert_tree_equal(s1.result, warm.result)

    # And the boundary did not cache-populate from the session step: a
    # fresh one-shot of DIFFERENT content than anything warmed misses.
    s2 = host.step("s", lease, 2, (0.05, 0.0, 0.0))
    _drain(host)
    assert s2.rung == sessions_mod.RUNG_SERVED
    probe = ScenarioRequest(
        family="cadmm4", horizon=cadmm_family.chunk_len,
        x0=tuple(float(t) for t in host.sessions["s"].x),
        v0=tuple(float(t) for t in host.sessions["s"].v),
        request_id="probe")
    assert srv.cache.get(cache_mod.request_key(
        cadmm_family.config_hash(), probe)) is None

    events = export_mod.read_events(str(tmp_path / "sess.metrics.jsonl"))
    assert not any(
        e.get("kind") == "cache_hit"
        and str(e.get("request_id", "")).startswith("s.")
        for e in events)


# ----------------------------------------------------------------------
# Fleet: session re-homing + autoscale hysteresis + chaos grammar.
# ----------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _front(clock, sent, tracer=None, sink=None, replica_ids=(0, 1)):
    sup = fleet_mod.ReplicaSupervisor(
        list(replica_ids), lease_s=1.0, boot_grace_s=100.0,
        clock=clock, emit=sink)
    for r in replica_ids:
        sup.heartbeat(r)
    front = fleet_mod.FleetFront(
        list(replica_ids), lambda fam: 2 if fam == "f" else None,
        send=lambda rid, op: sent.append((rid, op)),
        buckets=(4, 8), supervisor=sup, clock=clock,
        metrics=sink, tracer=tracer)
    return front, sup


def test_fleet_rehomes_sessions_on_same_trace_id():
    """Replica death re-homes its sessions to a live replica on the
    SAME trace_id, the failover span held open until the first
    post-rehome session result."""
    rows = []

    class Sink:
        def emit(self, event, **kw):
            rows.append({"event": event, **kw})

    clock, sent = FakeClock(), []
    sink = Sink()
    tracer = trace_mod.Tracer(sink, track="front",
                              clock_mono=lambda: clock.t)
    front, sup = _front(clock, sent, tracer=tracer, sink=sink)
    owner = front.open_session("s1", "f", trace_id="T1")
    assert owner in (0, 1)
    assert sent[-1][1]["op"] == "session_open"
    assert front.stats()["sessions"] == 1

    other = 1 - owner
    sup.notify_exit(owner, returncode=-9)
    front.failover(owner)
    rehome = [(rid, op) for rid, op in sent
              if op["op"] == "session_rehome"]
    assert rehome == [(other, {"op": "session_rehome",
                               "session_id": "s1", "family": "f",
                               "trace_id": "T1"})]
    assert front.session_replica("s1") == other
    ev = [r for r in rows if r.get("kind") == "rehomed"]
    assert len(ev) == 1 and ev[0]["to_replica"] == str(other)
    assert "s1" in front._rehome_spans  # held open...

    clock.t = 2.0
    front.deliver_result({"request_id": "s1.s000004",
                          "status": "completed", "replica": str(other)})
    assert "s1" not in front._rehome_spans  # ...until the next result.
    spans = [r for r in rows if r.get("event") == "trace_event"
             and r.get("name") == trace_mod.GUARD_FALLBACK
             and r.get("t1_mono") is not None]
    assert len(spans) == 1 and spans[0]["trace_id"] == "T1"
    assert spans[0]["t1_mono"] - spans[0]["t0_mono"] == \
        pytest.approx(2.0)


def test_fleet_rid_fallback_requires_exact_session_step_shape():
    """Regression (REVIEW): the request_id -> session fallback in
    deliver_result fires ONLY on the session-step rid shape for a
    session this front routes — a caller-chosen one-shot rid containing
    '.s' (or an unknown session prefix) must never end another
    session's held-open re-home span."""
    rows = []

    class Sink:
        def emit(self, event, **kw):
            rows.append({"event": event, **kw})

    clock, sent = FakeClock(), []
    sink = Sink()
    tracer = trace_mod.Tracer(sink, track="front",
                              clock_mono=lambda: clock.t)
    front, sup = _front(clock, sent, tracer=tracer, sink=sink)
    owner = front.open_session("s1", "f", trace_id="T1")
    sup.notify_exit(owner, returncode=-9)
    front.failover(owner)
    assert "s1" in front._rehome_spans

    other = str(1 - owner)
    # A one-shot whose caller-chosen rid contains '.s': NO match.
    front.deliver_result({"request_id": "s1.speed",
                          "status": "completed", "replica": other})
    assert "s1" in front._rehome_spans
    # Valid step suffix but an unknown session prefix: NO match.
    front.deliver_result({"request_id": "s9.e0.s000001",
                          "status": "completed", "replica": other})
    assert "s1" in front._rehome_spans
    # The exact epoch-qualified session-step shape closes the span.
    front.deliver_result({"request_id": "s1.e0.s000001",
                          "status": "completed", "replica": other})
    assert "s1" not in front._rehome_spans


def test_fleet_session_orphaned_then_rehomed_when_fleet_heals():
    """A full-fleet outage orphans the session at the front (replica
    None); the next pump with a routable replica re-homes it."""
    clock, sent = FakeClock(), []
    front, sup = _front(clock, sent)
    owner = front.open_session("s1", "f")
    for r in (0, 1):
        sup.notify_exit(r, returncode=-9)
    front.failover(owner)
    assert front.session_replica("s1") is None  # orphaned, not lost.
    sup.heartbeat(0)  # one replica heals.
    front.pump()
    assert front.session_replica("s1") == 0
    assert [op["op"] for _, op in sent].count("session_rehome") == 1


def test_autoscale_hysteresis_never_flaps():
    """An input oscillating across the up threshold every observation
    can never move the confirmed hint; N consecutive agreeing raws
    switch it exactly once (one event per transition)."""
    events = []
    sig = fleet_mod.AutoscaleSignal(
        policy=fleet_mod.AutoscalePolicy(confirm=3),
        emit=lambda **kw: events.append(kw))

    for i in range(12):  # flap storm: up, steady, up, steady, ...
        hint = sig.observe(
            queue_depth=(20 if i % 2 == 0 else 4), sessions=2)
        assert hint == "steady"
    assert events == []

    for _ in range(2):
        assert sig.observe(queue_depth=20, sessions=2) == "steady"
    assert sig.observe(queue_depth=20, sessions=2) == "scale_up"
    assert len(events) == 1 and events[0]["hint"] == "scale_up"
    # Staying up emits nothing more.
    assert sig.observe(queue_depth=30, sessions=2) == "scale_up"
    assert len(events) == 1

    # Down requires idle depth AND no sessions AND cold occupancy —
    # a live session blocks scale_down (standing capacity demand).
    for _ in range(6):
        sig.observe(queue_depth=0, occupancy=0.1, sessions=1)
    assert sig.hint == "steady"
    for _ in range(3):
        sig.observe(queue_depth=0, occupancy=0.1, sessions=0)
    assert sig.hint == "scale_down"
    assert [e["hint"] for e in events] == [
        "scale_up", "steady", "scale_down"]


def test_front_stats_exposes_autoscale_and_sessions():
    clock, sent = FakeClock(), []
    front, _ = _front(clock, sent)
    front.open_session("s1", "f")
    front.pump()
    st = front.stats()
    assert st["sessions"] == 1
    assert st["autoscale"]["hint"] == "steady"
    assert st["autoscale"]["sessions"] == 1
    assert st["autoscale"]["raw"] in fleet_mod.AutoscaleSignal.HINTS


def test_fault_plan_client_actions_roundtrip():
    """The chaos grammar's client-side faults parse, round-trip, and
    seed deterministically (rR indexes the CLIENT for them)."""
    spec = "silent@1:r0,slow@2:r1=2.5,duplicate@3:r0,zombie@4:r1"
    plan = fleet_mod.FleetFaultPlan.parse(spec)
    assert plan.to_spec() == spec
    assert fleet_mod.FleetFaultPlan.parse(plan.to_spec()) == plan
    acts = {a.action for a in plan.actions}
    assert acts == fleet_mod.CLIENT_FAULT_ACTIONS
    with pytest.raises(ValueError):
        fleet_mod.FleetFaultPlan.parse("zombie@1:q0")
    # Seeded plans may draw client faults with a slow-factor arg.
    a = fleet_mod.FleetFaultPlan.seeded(7, 3)
    assert a == fleet_mod.FleetFaultPlan.seeded(7, 3)
    for act in a.actions:
        assert act.action in fleet_mod.FAULT_ACTIONS
        if act.action in ("wedge", "slow"):
            assert float(act.arg) > 0
