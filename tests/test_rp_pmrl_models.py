"""Property tests for the RP and PMRL system models (reference test/system/*)."""

import jax
import jax.numpy as jnp
import pytest

from tpu_aerial_transport.models import pmrl, rp
from tpu_aerial_transport.ops import lie


def _rp_params(n=3):
    ang = 2 * jnp.pi * jnp.arange(n) / n
    r = jnp.stack([jnp.cos(ang), jnp.sin(ang), jnp.zeros(n)], axis=-1) * 0.4
    Jl = jnp.diag(jnp.array([2.1e-2, 1.87e-2, 3.97e-2]))
    return rp.rp_params(0.225, Jl, r)


def _rp_random_state(key):
    ks = jax.random.split(key, 4)
    return rp.rp_state(
        xl=jax.random.normal(ks[0], (3,)),
        vl=jax.random.normal(ks[1], (3,)),
        Rl=lie.expm_so3(jax.random.normal(ks[2], (3,)) * 0.5),
        wl=jax.random.normal(ks[3], (3,)),
    )


@pytest.mark.parametrize("n", [3, 5])
def test_rp_inverse_dynamics_residual(n):
    params = _rp_params(n)
    for seed in range(5):
        ks = jax.random.split(jax.random.PRNGKey(seed), 2)
        state = _rp_random_state(ks[0])
        f = jax.random.normal(ks[1], (n, 3))
        acc = rp.forward_dynamics(params, state, f)
        err = rp.inverse_dynamics_error(state, params, f, acc)
        assert float(err) < 1e-4


def test_rp_hover_equilibrium():
    """Equal vertical forces summing to ml*g with symmetric attachments -> zero acc."""
    n = 3
    params = _rp_params(n)
    state = rp.rp_identity_state()
    f = jnp.tile(jnp.array([0.0, 0.0, float(params.ml) * rp.GRAVITY / n]), (n, 1))
    dvl, dwl = rp.forward_dynamics(params, state, f)
    assert jnp.abs(dvl).max() < 1e-5
    assert jnp.abs(dwl).max() < 1e-5


def test_rp_integrator_orthonormality():
    params = _rp_params(3)
    state = _rp_random_state(jax.random.PRNGKey(3))
    f = jnp.zeros((3, 3))

    def body(s, _):
        return rp.integrate(params, s, f, 1e-3), None

    final, _ = jax.lax.scan(body, state, None, length=500)
    assert jnp.abs(final.Rl.T @ final.Rl - jnp.eye(3)).max() < 1e-4


# ---------------------------------------------------------------------------- PMRL


def _pmrl_params(n=3):
    ang = 2 * jnp.pi * jnp.arange(n) / n
    r = jnp.stack([jnp.cos(ang), jnp.sin(ang), jnp.zeros(n)], axis=-1) * 0.4
    Jl = jnp.diag(jnp.array([2.1e-2, 1.87e-2, 3.97e-2]))
    m = jnp.full((n,), 0.5)
    L = jnp.full((n,), 1.0)
    return pmrl.pmrl_params(m, 0.225, Jl, r, L)


def _pmrl_random_state(key, n=3):
    ks = jax.random.split(key, 6)
    q = lie.random_cone_vector(ks[0], 0.6, (n,))  # links pointing upward-ish
    dq = 0.3 * jax.random.normal(ks[1], (n, 3))
    return pmrl.pmrl_state(
        q=q,
        dq=dq,
        xl=jax.random.normal(ks[2], (3,)),
        vl=jax.random.normal(ks[3], (3,)),
        Rl=lie.expm_so3(jax.random.normal(ks[4], (3,)) * 0.3),
        wl=jax.random.normal(ks[5], (3,)),
    )


@pytest.mark.parametrize("n", [3, 6])
def test_pmrl_inverse_dynamics_residual(n):
    """Validates the implicit SPD tension solve (reference test_pmrldynamics.py)."""
    params = _pmrl_params(n)
    for seed in range(5):
        ks = jax.random.split(jax.random.PRNGKey(seed + 10), 2)
        state = _pmrl_random_state(ks[0], n)
        f = jax.random.normal(ks[1], (n, 3)) * 2.0
        acc, T = pmrl.forward_dynamics(params, state, f)
        err = pmrl.inverse_dynamics_error(state, params, f, T, acc)
        assert float(err) < 5e-4, f"residual {err} at seed {seed}"


def test_pmrl_state_projection_invariants():
    state = _pmrl_random_state(jax.random.PRNGKey(0))
    assert jnp.abs(jnp.linalg.norm(state.q, axis=-1) - 1.0).max() < 1e-6
    assert jnp.abs(jnp.sum(state.q * state.dq, axis=-1)).max() < 1e-6


def test_pmrl_integrator_keeps_manifolds():
    n = 3
    params = _pmrl_params(n)
    state = _pmrl_random_state(jax.random.PRNGKey(2), n)
    # Roughly supporting thrusts along the links.
    f = state.q * 2.0

    def body(s, _):
        return pmrl.integrate(params, s, f, 1e-3), None

    final, _ = jax.lax.scan(body, state, None, length=1000)
    assert jnp.abs(jnp.linalg.norm(final.q, axis=-1) - 1.0).max() < 1e-5
    assert jnp.abs(jnp.sum(final.q * final.dq, axis=-1)).max() < 1e-4
    assert jnp.abs(final.Rl.T @ final.Rl - jnp.eye(3)).max() < 1e-4
    assert jnp.all(jnp.isfinite(final.xl))


def _pmrl_analytic_trajectory(t):
    """Analytic (state, acc) at time t for 3 robots — the S^2 + SE(3) test
    trajectory from reference test/system/test_pmrlstate.py:9-69: link
    directions spiral on the sphere (azimuth k1*t, polar k3*sin(k2*t)), the
    payload follows a circle in xy with sinusoidal z, Rl spins about z."""
    import numpy as np

    k1, k2, k3 = np.pi / 2, 2 / 3 * np.pi, np.pi / 5
    a, b = k1 * t, k3 * np.sin(k2 * t)
    ca, sa, cb, sb = np.cos(a), np.sin(a), np.cos(b), np.sin(b)
    da, dda = k1, 0.0
    db, ddb = k3 * k2 * np.cos(k2 * t), -k3 * k2**2 * np.sin(k2 * t)
    q_ = np.array([ca * sb, sa * sb, cb])
    dq_ = np.array(
        [-sa * sb * da + ca * cb * db, ca * sb * da + sa * cb * db, -sb * db]
    )
    ddq_ = np.array([
        -ca * sb * da**2 - 2 * sa * cb * da * db - sa * sb * dda
        - ca * sb * db**2 + ca * cb * ddb,
        -sa * sb * da**2 + 2 * ca * cb * da * db + ca * sb * dda
        - sa * sb * db**2 + sa * cb * ddb,
        -cb * db**2 - sb * ddb,
    ])
    q = np.tile(q_, (3, 1))
    dq = np.tile(dq_, (3, 1))
    ddq = np.tile(ddq_, (3, 1))

    kx1, kx2 = np.pi / 2, 2 / 3 * np.pi
    ax_, bx = kx1 * t, kx2 * t
    cax, sax, cbx, sbx = np.cos(ax_), np.sin(ax_), np.cos(bx), np.sin(bx)
    xl = np.array([cax, sax, sbx])
    vl = np.array([-sax * kx1, cax * kx1, cbx * kx2])
    dvl = np.array([-cax * kx1**2, -sax * kx1**2, -sbx * kx2**2])

    ang = (2 * np.pi) * np.sin(np.pi / 2 * t)
    c, s = np.cos(ang), np.sin(ang)
    Rl = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])
    wl = np.array([0.0, 0.0, np.pi**2 * np.cos(np.pi / 2 * t)])
    dwl = np.array([0.0, 0.0, -np.pi**3 / 2 * np.sin(np.pi / 2 * t)])
    return (q, dq, xl, vl, Rl, wl), (ddq, dvl, dwl)


def test_pmrl_integrator_tracks_analytic_s2_trajectory():
    """Integrate the analytic accelerations from t=0 and compare against the
    closed-form state (reference test_pmrlstate.py plots these drifts and a
    human checks they stay small; here they are asserted). The trajectory
    exercises the S^2 manifold integrator (q spirals pole-to-equator), the
    trapezoidal SE(3) update, and the periodic SO(3) projection."""
    import numpy as np

    dt = 1e-3
    n_steps = 2000  # 2 s of the reference's 10 s horizon (CI budget).
    (q, dq, xl, vl, Rl, wl), acc = _pmrl_analytic_trajectory(0.0)
    state = pmrl.pmrl_state(q=q, dq=dq, xl=xl, vl=vl, Rl=Rl, wl=wl)

    step = jax.jit(
        lambda s, a: pmrl.integrate_state(s, jax.tree.map(jnp.asarray, a), dt)
    )
    for i in range(1, n_steps + 1):
        state = step(state, acc)
        _, acc = _pmrl_analytic_trajectory(i * dt)

    ref_state, _ = _pmrl_analytic_trajectory(n_steps * dt)
    q_r, dq_r, xl_r, vl_r, Rl_r, wl_r = ref_state
    # First-order-in-dt drift bounds over 2000 steps (f32 + trapezoid).
    assert float(np.linalg.norm(np.asarray(state.q) - q_r)) < 2e-2
    assert float(np.linalg.norm(np.asarray(state.xl) - xl_r)) < 1e-2
    assert float(np.linalg.norm(np.asarray(state.vl) - vl_r)) < 1e-2
    assert float(np.linalg.norm(np.asarray(state.Rl) - Rl_r)) < 5e-2
    # Manifold invariants survive the whole run.
    assert float(np.abs(np.linalg.norm(np.asarray(state.q), axis=-1) - 1).max()) < 1e-5
    RtR = np.asarray(state.Rl).T @ np.asarray(state.Rl)
    assert float(np.abs(RtR - np.eye(3)).max()) < 1e-4


def test_pmrl_collision_metadata():
    """PMRLCollision mirrors the reference class (point_mass_rigid_link.py:
    257-278) plus a conservative bounding radius covering extended links."""
    from tpu_aerial_transport.harness import setup

    params, col, state = setup.pmrl_setup(3)
    assert isinstance(col, pmrl.PMRLCollision)
    assert col.payload_vertices.shape[1] == 3
    assert col.payload_mesh_vertices.shape[1] == 3
    # Radius >= payload mesh radius + longest link.
    import numpy as np

    mesh_r = np.max(np.linalg.norm(col.payload_mesh_vertices, axis=1))
    assert col.collision_radius >= mesh_r + float(np.max(np.asarray(params.L)))
