"""Property tests for the RP and PMRL system models (reference test/system/*)."""

import jax
import jax.numpy as jnp
import pytest

from tpu_aerial_transport.models import pmrl, rp
from tpu_aerial_transport.ops import lie


def _rp_params(n=3):
    ang = 2 * jnp.pi * jnp.arange(n) / n
    r = jnp.stack([jnp.cos(ang), jnp.sin(ang), jnp.zeros(n)], axis=-1) * 0.4
    Jl = jnp.diag(jnp.array([2.1e-2, 1.87e-2, 3.97e-2]))
    return rp.rp_params(0.225, Jl, r)


def _rp_random_state(key):
    ks = jax.random.split(key, 4)
    return rp.rp_state(
        xl=jax.random.normal(ks[0], (3,)),
        vl=jax.random.normal(ks[1], (3,)),
        Rl=lie.expm_so3(jax.random.normal(ks[2], (3,)) * 0.5),
        wl=jax.random.normal(ks[3], (3,)),
    )


@pytest.mark.parametrize("n", [3, 5])
def test_rp_inverse_dynamics_residual(n):
    params = _rp_params(n)
    for seed in range(5):
        ks = jax.random.split(jax.random.PRNGKey(seed), 2)
        state = _rp_random_state(ks[0])
        f = jax.random.normal(ks[1], (n, 3))
        acc = rp.forward_dynamics(params, state, f)
        err = rp.inverse_dynamics_error(state, params, f, acc)
        assert float(err) < 1e-4


def test_rp_hover_equilibrium():
    """Equal vertical forces summing to ml*g with symmetric attachments -> zero acc."""
    n = 3
    params = _rp_params(n)
    state = rp.rp_identity_state()
    f = jnp.tile(jnp.array([0.0, 0.0, float(params.ml) * rp.GRAVITY / n]), (n, 1))
    dvl, dwl = rp.forward_dynamics(params, state, f)
    assert jnp.abs(dvl).max() < 1e-5
    assert jnp.abs(dwl).max() < 1e-5


def test_rp_integrator_orthonormality():
    params = _rp_params(3)
    state = _rp_random_state(jax.random.PRNGKey(3))
    f = jnp.zeros((3, 3))

    def body(s, _):
        return rp.integrate(params, s, f, 1e-3), None

    final, _ = jax.lax.scan(body, state, None, length=500)
    assert jnp.abs(final.Rl.T @ final.Rl - jnp.eye(3)).max() < 1e-4


# ---------------------------------------------------------------------------- PMRL


def _pmrl_params(n=3):
    ang = 2 * jnp.pi * jnp.arange(n) / n
    r = jnp.stack([jnp.cos(ang), jnp.sin(ang), jnp.zeros(n)], axis=-1) * 0.4
    Jl = jnp.diag(jnp.array([2.1e-2, 1.87e-2, 3.97e-2]))
    m = jnp.full((n,), 0.5)
    L = jnp.full((n,), 1.0)
    return pmrl.pmrl_params(m, 0.225, Jl, r, L)


def _pmrl_random_state(key, n=3):
    ks = jax.random.split(key, 6)
    q = lie.random_cone_vector(ks[0], 0.6, (n,))  # links pointing upward-ish
    dq = 0.3 * jax.random.normal(ks[1], (n, 3))
    return pmrl.pmrl_state(
        q=q,
        dq=dq,
        xl=jax.random.normal(ks[2], (3,)),
        vl=jax.random.normal(ks[3], (3,)),
        Rl=lie.expm_so3(jax.random.normal(ks[4], (3,)) * 0.3),
        wl=jax.random.normal(ks[5], (3,)),
    )


@pytest.mark.parametrize("n", [3, 6])
def test_pmrl_inverse_dynamics_residual(n):
    """Validates the implicit SPD tension solve (reference test_pmrldynamics.py)."""
    params = _pmrl_params(n)
    for seed in range(5):
        ks = jax.random.split(jax.random.PRNGKey(seed + 10), 2)
        state = _pmrl_random_state(ks[0], n)
        f = jax.random.normal(ks[1], (n, 3)) * 2.0
        acc, T = pmrl.forward_dynamics(params, state, f)
        err = pmrl.inverse_dynamics_error(state, params, f, T, acc)
        assert float(err) < 5e-4, f"residual {err} at seed {seed}"


def test_pmrl_state_projection_invariants():
    state = _pmrl_random_state(jax.random.PRNGKey(0))
    assert jnp.abs(jnp.linalg.norm(state.q, axis=-1) - 1.0).max() < 1e-6
    assert jnp.abs(jnp.sum(state.q * state.dq, axis=-1)).max() < 1e-6


def test_pmrl_integrator_keeps_manifolds():
    n = 3
    params = _pmrl_params(n)
    state = _pmrl_random_state(jax.random.PRNGKey(2), n)
    # Roughly supporting thrusts along the links.
    f = state.q * 2.0

    def body(s, _):
        return pmrl.integrate(params, s, f, 1e-3), None

    final, _ = jax.lax.scan(body, state, None, length=1000)
    assert jnp.abs(jnp.linalg.norm(final.q, axis=-1) - 1.0).max() < 1e-5
    assert jnp.abs(jnp.sum(final.q * final.dq, axis=-1)).max() < 1e-4
    assert jnp.abs(final.Rl.T @ final.Rl - jnp.eye(3)).max() < 1e-4
    assert jnp.all(jnp.isfinite(final.xl))
