"""Seeded violation: print() under trace (JL011, warn)."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    print("residual:", jnp.max(x))  # expect: JL011
    return x * 0.5
