"""Clean twin of jl009_bad: every str-defaulted parameter is static."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("iters", "mode"))
def solve(x, iters: int = 10, mode: str = "auto"):
    return x * iters
