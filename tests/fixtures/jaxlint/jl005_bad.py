"""Seeded violation: Python branch on a traced value (JL005)."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp(x):
    if jnp.max(x) > 1.0:  # expect: JL005
        x = x / jnp.max(x)
    while jnp.any(x > 2.0):  # expect: JL005
        x = x * 0.5
    return x
