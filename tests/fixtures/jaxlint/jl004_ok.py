"""Clean twin of jl004_bad: stay in f32; host-side np f64 is fine."""
import jax.numpy as jnp
import numpy as np


def widen(x):
    return jnp.asarray(x, jnp.float32)


def host_geometry(vertices):
    # Host-side double-precision geometry (never traced) is legitimate.
    return np.asarray(vertices, np.float64)


def stringly(x):
    return jnp.zeros_like(x, dtype="float32")
