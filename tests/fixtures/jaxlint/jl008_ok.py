"""Clean twin of jl008_bad: static declarations match the signature."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("iters",))
def solve(x, iters: int = 10):
    return x * iters


def outer(y):
    return jax.jit(scale, static_argnums=(1,))(y, 2.0)


def scale(x, s):
    return x * s
