"""Seeded violation: jnp.asarray inside a scan body (JL006)."""
import jax.numpy as jnp
from jax import lax

OFFSETS = [1.0, 2.0, 3.0]


def body(carry, _):
    ofs = jnp.asarray(OFFSETS)  # expect: JL006
    bias = jnp.array([0.5, 0.5, 0.5])  # expect: JL006
    return carry + ofs + bias, None


def run(c0):
    return lax.scan(body, c0, None, length=8)
