"""Clean twin of jl010_bad: solve on-device instead of calling back."""
import jax
import jax.numpy as jnp


@jax.jit
def solve(x):
    return jnp.linalg.solve(jnp.eye(x.shape[0], dtype=x.dtype), x)
