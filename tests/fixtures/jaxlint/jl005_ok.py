"""Clean twin of jl005_bad: data branches via where; static branches ok."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp(x, n_steps: int = 3):
    mx = jnp.max(x)
    x = jnp.where(mx > 1.0, x / mx, x)
    if n_steps > 2:  # static Python value — fine.
        x = x * 0.5
    if jnp.issubdtype(x.dtype, jnp.inexact):  # dtype metadata — fine.
        x = x + 0.0
    return x
