"""Seeded violation: f64 dtype reaching jnp code (JL004)."""
import jax
import jax.numpy as jnp
import numpy as np


def widen(x):
    hi = jnp.asarray(x, np.float64)  # expect: JL004
    return hi


@jax.jit
def accumulate(x):
    acc = np.float64(0.0)  # expect: JL004
    return x + acc


def stringly(x):
    return jnp.zeros_like(x, dtype="float64")  # expect: JL004
