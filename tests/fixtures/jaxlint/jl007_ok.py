"""Clean twin of jl007_bad: shape asserts are static; value checks via
where/checkify or host code."""
import jax
import jax.numpy as jnp


@jax.jit
def project(x):
    assert x.ndim == 1, x.shape  # static shape metadata — fine.
    nrm = jnp.linalg.norm(x)
    return x / jnp.where(nrm > 0, nrm, 1.0)
