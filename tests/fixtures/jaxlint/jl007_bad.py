"""Seeded violation: bare assert on a traced expression (JL007)."""
import jax
import jax.numpy as jnp


@jax.jit
def project(x):
    assert jnp.all(jnp.isfinite(x)), "non-finite input"  # expect: JL007
    return x / jnp.linalg.norm(x)
