"""Seeded violation: static_argnames not matching the signature (JL008)."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("iters", "mode"))  # expect: JL008
def solve(x, iters: int = 10):
    # "mode" is not a parameter: the static declaration is dead.
    return x * iters


def outer(y):
    return jax.jit(scale, static_argnums=(2,))(y, 2.0)  # expect: JL008


def scale(x, s):
    return x * s
