"""Clean twin of jl011_bad: jax.debug.print survives compilation."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    jax.debug.print("residual: {r}", r=jnp.max(x))
    return x * 0.5
