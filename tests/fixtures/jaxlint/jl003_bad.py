"""Seeded violation: numpy call inside traced code (JL003)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def normalize(x):
    nrm = np.linalg.norm(x)  # expect: JL003
    return x / nrm


def body(carry, _):
    return carry + np.asarray([1.0, 2.0]), None  # expect: JL003


def run(c0):
    return lax.scan(body, c0, None, length=3)
