"""Clean twin of jl006_bad: conversions hoisted out of the loop."""
import jax.numpy as jnp
from jax import lax

OFFSETS = jnp.asarray([1.0, 2.0, 3.0])
BIAS = jnp.array([0.5, 0.5, 0.5])


def body(carry, _):
    return carry + OFFSETS + BIAS, None


def run(c0):
    return lax.scan(body, c0, None, length=8)
