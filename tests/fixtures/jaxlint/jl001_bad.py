"""Seeded violation: host cast of a traced expression (JL001)."""
import jax
import jax.numpy as jnp


@jax.jit
def energy(x):
    scale = float(jnp.sum(x * x))  # expect: JL001
    return scale * x


def loop(x):
    n = int(jnp.max(x))  # expect: JL001
    return n


jax.vmap(loop)
