"""Seeded violation: jitted str-defaulted parameter not static (JL009)."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("iters",))  # expect: JL009
def solve(x, iters: int = 10, mode: str = "auto"):
    # "mode" is a string — it can never be traced; passing it will raise.
    return x * iters
