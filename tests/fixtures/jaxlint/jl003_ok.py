"""Clean twin of jl003_bad: jnp under trace; np behind a Tracer guard."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def normalize(x):
    return x / jnp.linalg.norm(x)


def checked_plan(scale):
    if not isinstance(scale, jax.core.Tracer):
        # Host-only region (Tracer-guard idiom): numpy is fine here.
        assert np.all(np.isfinite(np.asarray(scale)))
    return jnp.sqrt(scale)


jax.jit(checked_plan)
