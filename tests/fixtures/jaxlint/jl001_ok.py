"""Clean twin of jl001_bad: casts on static config values are host-safe."""
import jax
import jax.numpy as jnp

SCALE = float(jnp.pi / 4)  # module level — not traced context.


@jax.jit
def energy(x, cfg_gain=2.0):
    gain = cfg_gain * SCALE  # no host cast of a traced value.
    return gain * jnp.sum(x * x)


def make_config(theta):
    # Host-side factory (never traced): eager casts are fine.
    return {"sec": float(jnp.cos(theta)), "n": int(theta // 1)}
