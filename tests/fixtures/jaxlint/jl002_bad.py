"""Seeded violation: .item()/.tolist() host sync under trace (JL002)."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    worst = jnp.max(x).item()  # expect: JL002
    rows = x.tolist()  # expect: JL002
    return worst, rows
