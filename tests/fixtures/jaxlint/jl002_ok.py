"""Clean twin of jl002_bad: materialize on the host, outside the jit."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    return jnp.max(x), x


def report(x):
    worst, rows = step(x)
    return worst.item(), rows.tolist()  # host context — fine.
