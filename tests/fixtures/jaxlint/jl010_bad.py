"""Seeded violation: host callback in a traced hot path (JL010)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def solve(x):
    y = jax.pure_callback(  # expect: JL010
        lambda a: np.linalg.solve(np.eye(a.shape[0]), a),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        x,
    )
    return y
