"""HL008 seeded violation: TAT_*/TPU_AERIAL_* env reads not registered
in analysis/knobs.py."""

import os

SECRET_ENV = "TAT_SECRET_MODE"


def secret_mode():
    return os.environ.get(SECRET_ENV, "")  # expect: HL008


def turbo(env=None):
    src = env or os.environ
    return src.get("TPU_AERIAL_TURBO")  # expect: HL008


def legacy():
    return os.getenv("TAT_LEGACY_FLAG")  # expect: HL008
