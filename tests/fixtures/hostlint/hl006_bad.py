"""HL006 seeded violation: non-atomic artifact publishes — a rename
without fsync, and a direct write into artifacts/."""

import json
import os


def publish_manifest(path, manifest):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
    os.replace(tmp, path)  # expect: HL006


def publish_report(report):
    with open("artifacts/report.json", "w") as fh:  # expect: HL006
        json.dump(report, fh)
