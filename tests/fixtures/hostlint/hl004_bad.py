"""HL004 seeded violation: two methods of one class acquire the same
pair of locks in opposite orders (one directly nested, one through a
self-call) — two threads can deadlock."""


class Front:
    def deliver(self, result):
        with self._state_lock:
            self._results.append(result)
            with self._route_lock:
                self._routes.pop(result, None)


class Supervisor:  # expect: HL004
    def heartbeat(self, rid):
        with self._health_lock:
            self._seen[rid] = True
            self._route(rid)

    def _route(self, rid):
        with self._route_lock:
            self._targets[rid] = rid

    def failover(self, rid):
        with self._route_lock:
            target = self._targets.get(rid)
            with self._health_lock:
                self._seen[target] = False
