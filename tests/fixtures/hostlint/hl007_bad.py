"""HL007 seeded violation: event-vocabulary drift — a kind literal
absent from obs/export.py's kind tables, a kind missing its minimum
keys, and an unknown event type on a metrics writer."""


class Replica:
    def report(self, rid):
        self.emit(kind="teleported", replica=rid)  # expect: HL007

    def fail_over(self, rid):
        self.emit_fleet(kind="failover", latency_s=0.5)  # expect: HL007

    def boundary(self):
        self.metrics.emit("serving_checkpoint", step=1)  # expect: HL007
