"""HL002 seeded violation: the PR-15 span-leak bug class, reconstructed
— a harvest span begun and ended only on the success path, so any
exception (or Ctrl-C) between begin and end leaks it open."""


def harvest(self, batch):
    hspan = self.tracer.begin("host_harvest", batch_id=batch.batch_id)  # expect: HL002
    rows = batch.collect()
    self.tracer.end(hspan, rows=len(rows))
    return rows


def snapshot(tracer, run_dir, carry):
    sspan = tracer.begin("snapshot", run_dir=run_dir)  # expect: HL002
    try:
        save(run_dir, carry)
        tracer.end(sspan)
    except ValueError:
        # Ends on ValueError only — KeyboardInterrupt still leaks it.
        tracer.end(sspan, error="save")
        raise


def save(run_dir, carry):
    return run_dir, carry
