"""HL005 seeded violation: raw write-mode opens of *.jsonl paths —
the durability contract (fsync per line) lives in
obs.export.jsonl_append, not here."""

import json
import os

EVENTS = "events.jsonl"


def journal(run_dir, record):
    path = os.path.join(run_dir, "journal.jsonl")
    with open(path, "a") as fh:  # expect: HL005
        fh.write(json.dumps(record) + "\n")


def rewrite(run_dir, records):
    with open(EVENTS, mode="w") as fh:  # expect: HL005
        for r in records:
            fh.write(json.dumps(r) + "\n")
