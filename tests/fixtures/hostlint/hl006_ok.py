"""HL006 clean twin: temp + fsync + os.replace — readers never observe
a torn or empty artifact."""

import json
import os


def publish_manifest(path, manifest):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def publish_report(report):
    publish_manifest("artifacts/report.json", report)
