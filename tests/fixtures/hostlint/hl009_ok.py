"""HL009 clean twin: the fleet_local discipline — its own session
(one killpg reaps the tree) and stderr to a file."""

import subprocess


def spawn(cmd, err_file):
    return subprocess.Popen(
        cmd,
        stdout=subprocess.DEVNULL,
        stderr=err_file,
        start_new_session=True,
    )
