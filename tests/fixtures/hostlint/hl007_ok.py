"""HL007 clean twin: kinds from the vocabulary with their minimum
keys; dynamic kinds and **kwargs are out of AST reach and unflagged."""


class Replica:
    def report(self, rid):
        self.emit(kind="heartbeat", replica=rid, seq=1)

    def fail_over(self, rid):
        self.emit_fleet(kind="failover", request_id=rid, latency_s=0.5)

    def boundary(self, batch_id):
        self.metrics.emit("serving_event", kind="batch_boundary",
                          batch_id=batch_id, chunk=1)

    def relay(self, kind, **fields):
        self.emit(kind=kind, **fields)
