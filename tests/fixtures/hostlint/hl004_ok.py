"""HL004 clean twin: one global acquisition order (health before
route), including through self-calls."""


class Supervisor:
    def heartbeat(self, rid):
        with self._health_lock:
            self._seen[rid] = True
            self._route(rid)

    def _route(self, rid):
        with self._route_lock:
            self._targets[rid] = rid

    def failover(self, rid):
        with self._health_lock:
            self._seen[rid] = False
            with self._route_lock:
                target = self._targets.get(rid)
        return target
