"""HL009 seeded violation: Popen without the group-kill + stderr
discipline."""

import subprocess


def spawn_orphan(cmd):
    return subprocess.Popen(cmd)  # expect: HL009


def spawn_wedgeable(cmd, out):
    return subprocess.Popen(  # expect: HL009
        cmd, stdout=out, start_new_session=False,
    )
