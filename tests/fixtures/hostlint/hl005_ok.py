"""HL005 clean twin: jsonl appends go through the one fsync'd
primitive; read-mode opens of jsonl files are fine."""

import os

from tpu_aerial_transport.obs import export as export_mod


def journal(run_dir, record):
    export_mod.jsonl_append(os.path.join(run_dir, "journal.jsonl"), record)


def replay(run_dir):
    with open(os.path.join(run_dir, "journal.jsonl")) as fh:
        return fh.readlines()
