"""HL001 clean twin: deadlines anchored on the monotonic clock; wall
time only stamps record fields."""

import time


def admit(deadline_s):
    deadline_at = time.monotonic() + deadline_s
    return deadline_at


def expired(deadline_at):
    return time.monotonic() >= deadline_at


def stamp(record):
    record["ts"] = time.time()
    return record
