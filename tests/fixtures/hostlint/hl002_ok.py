"""HL002 clean twin: every span ends on a path that survives
BaseException (finally, or an except BaseException re-raise), and a
span handed off to another owner is not this function's contract."""


def harvest(self, batch):
    hspan = self.tracer.begin("host_harvest", batch_id=batch.batch_id)
    try:
        rows = batch.collect()
    except BaseException:
        self.tracer.end(hspan, error=True)
        raise
    self.tracer.end(hspan, rows=len(rows))
    return rows


def snapshot(tracer, run_dir, carry):
    sspan = tracer.begin("snapshot", run_dir=run_dir)
    try:
        save(run_dir, carry)
    finally:
        tracer.end(sspan)


def handoff(self, rid):
    span = self.tracer.begin("failover", request_id=rid)
    self._spans[rid] = span  # delivered elsewhere: their end, not ours.


def save(run_dir, carry):
    return run_dir, carry
