"""HL001 seeded violation: wall-clock time flowing into deadline math
and compared against monotonic anchors."""

import time


def admit(deadline_s):
    deadline_at = time.time() + deadline_s  # expect: HL001
    return deadline_at


def expired(deadline_at):
    anchor = time.monotonic()
    return time.time() >= anchor  # expect: HL001


def remaining(timeout_s):
    timeout_at = time.time()  # expect: HL001
    return timeout_at
