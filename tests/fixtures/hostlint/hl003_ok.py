"""HL003 clean twin: state mutates under the lock; emits, sleeps, and
file work happen after release. A constant-separator str.join is not a
thread join."""

import time


class Registry:
    def record(self, event):
        with self._lock:
            self._events.append(event)
            depth = len(self._events)
        self.emit(kind="submitted", request_id=event, depth=depth)

    def flush(self, path):
        with self._lock:
            pending = list(self._events)
            self._events.clear()
        time.sleep(0.01)
        return ",".join(str(p) for p in pending), path

    def reap(self):
        with self._mu:
            proc = self._proc
            self._proc = None
        if proc is not None:
            proc.wait()
