"""HL010 seeded violation: the PR-15 tracer=False bug class,
reconstructed — truthiness gates on observability/guard parameters
where the zero-cost contract is `is not None`."""


def rollout_resumable(plan, tracer=None):
    if tracer:  # expect: HL010
        tracer.instant("resume", run_dir=plan)
    return plan


def make_server(metrics=None, guard=None):
    sink = metrics or (lambda **kw: None)  # expect: HL010
    if guard is True:  # expect: HL010
        guard = None
    return sink, guard


def chunk_driver(carry, telemetry=None):
    if not telemetry:  # expect: HL010
        return carry
    return telemetry.accumulate(carry)
