"""HL010 clean twin: `is None` / `is not None` gates — a
falsy-but-real sink still gets every event."""


def rollout_resumable(plan, tracer=None):
    if tracer is not None:
        tracer.instant("resume", run_dir=plan)
    return plan


def make_server(metrics=None, guard=None):
    sink = (lambda **kw: None) if metrics is None else metrics
    return sink, guard


def chunk_driver(carry, telemetry=None):
    if telemetry is None:
        return carry
    return telemetry.accumulate(carry)
