"""HL003 seeded violation: blocking syscalls inside `with <lock>`
bodies — every other thread serializes behind the disk/sleep."""

import subprocess
import time


class Registry:
    def record(self, event):
        with self._lock:
            self._events.append(event)
            self.emit(kind="submitted", request_id=event)  # expect: HL003

    def flush(self, path):
        with self._lock:
            time.sleep(0.01)  # expect: HL003
            return open(path)  # expect: HL003

    def reap(self):
        with self._mu:
            self._proc = subprocess.Popen(  # expect: HL003
                ["true"], start_new_session=True, stderr=None,
            )
            self._worker.join()  # expect: HL003
