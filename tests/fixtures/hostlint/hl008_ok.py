"""HL008 clean twin: registered knobs and non-knob env vars."""

import os

EFFORT_ENV = "TAT_EFFORT"


def effort():
    return os.environ.get(EFFORT_ENV, "auto")


def faults(env=None):
    return (env or os.environ).get("TAT_BACKEND_FAULTS", "")


def unrelated():
    return os.environ.get("HOME", "/")
