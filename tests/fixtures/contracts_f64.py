"""Seeded r02-class fixture entrypoint for the TC106 off-chip TPU
lowering gate (analysis/contracts.py ``run_lowering_gate``).

``build()`` matches the ``Contract.build`` protocol: an entrypoint whose
program smuggles an explicit ``convert_element_type`` to float64 into the
graph — the exact op class BENCH_r02 died under at first dispatch. Under
``jax.experimental.enable_x64`` (the configuration in which such a bug
actually survives canonicalization to the lowered program) the TPU-target
StableHLO contains f64 tensor types and TC106 must fail; the ``build_ok``
twin is the clean control. tests/test_jaxlint.py drives both, proving
r02-class bugs are now caught off-chip, on a CPU-only host, in tier-1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def build():
    """A small 'controller step' whose accumulator is silently promoted to
    f64 through an explicit convert_element_type (the seeded bug)."""

    def fn(x):
        acc = jax.lax.convert_element_type(x, np.dtype("float64"))
        return jax.lax.convert_element_type(acc * 2.0 + 1.0,
                                            jnp.float32)

    def make_args():
        return (jnp.ones((4,), jnp.float32),)

    return fn, make_args


def build_ok():
    """Clean twin: the same computation held in f32 end to end."""

    def fn(x):
        return x * 2.0 + 1.0

    def make_args():
        return (jnp.ones((4,), jnp.float32),)

    return fn, make_args
