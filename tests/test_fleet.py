"""Serving-fleet tier (ISSUE 16): replica supervision, consistent-hash
routing, per-tenant admission, failover re-dispatch, chaos plan.

Tier-1 tests are pure host logic on fake clocks — no subprocesses, no
device. The chaos acceptance e2e (slow) drives tools/fleet_local.py for
real: SIGKILL one replica mid-batch + wedge another, digests equal the
fault-free run's, retry segment on the original trace_id.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

from tpu_aerial_transport.obs import export as export_mod
from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.resilience import backend as backend_mod
from tpu_aerial_transport.serving import fleet as fleet_mod
from tpu_aerial_transport.serving import queue as queue_mod

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _supervisor(clock, ev, **kw):
    kw.setdefault("lease_s", 1.0)
    kw.setdefault("boot_grace_s", 10.0)
    return fleet_mod.ReplicaSupervisor(
        [0, 1], clock=clock, emit=lambda **f: ev.append(f), **kw
    )


# ---------------------------------------------------------------------
# Replica supervisor: the health machine.
# ---------------------------------------------------------------------

class TestSupervisor:
    def test_heartbeat_brings_starting_up(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev)
        assert sup.state(0) == fleet_mod.STARTING
        assert 0 in sup.routable()  # starting IS routable (inbox buffers).
        sup.heartbeat(0)
        assert sup.state(0) == fleet_mod.UP

    def test_missed_leases_suspect_then_down(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev)
        sup.heartbeat(0)
        sup.heartbeat(1)
        clock.t = 2.5  # >= 2 missed leases.
        assert sup.tick() == []
        assert sup.state(0) == fleet_mod.SUSPECT
        assert 0 in sup.routable()  # suspect stays routable.
        clock.t = 5.5  # >= 5 missed leases.
        actions = sup.tick()
        assert ("kill", 0) in actions and ("failover", 0) in actions
        assert sup.state(0) == fleet_mod.RESTARTING
        assert 0 not in sup.routable()
        # Both replicas went down in the same tick — order-independent.
        assert sup.state(1) == fleet_mod.RESTARTING

    def test_restart_spawns_after_backoff_and_recovers(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev)
        sup.heartbeat(0)
        clock.t = 6.0
        sup.tick()
        assert sup.state(0) == fleet_mod.RESTARTING
        assert sup.tick() == []  # backoff not elapsed.
        clock.t = 6.0 + sup.backoff.initial_s + 0.01
        acts = [a for a in sup.tick() if a[1] == 0]
        assert ("spawn", 0) in acts
        sup.heartbeat(0)  # the respawn's first pulse.
        assert sup.state(0) == fleet_mod.UP

    def test_exit_notification_declares_down(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev)
        sup.heartbeat(0)
        actions = sup.notify_exit(0, returncode=-9)
        assert ("failover", 0) in actions
        assert sup.state(0) == fleet_mod.RESTARTING
        # A second notification for the same death is a no-op.
        assert sup.notify_exit(0, returncode=-9) == []

    def test_boot_deadline_declares_down(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev, boot_grace_s=10.0)
        clock.t = 9.0
        assert sup.tick() == []  # still within boot grace.
        clock.t = 10.5
        actions = sup.tick()
        assert ("failover", 0) in actions and ("failover", 1) in actions

    def test_quarantine_after_k_restart_cycles(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev, quarantine_after=2)
        for cycle in range(3):
            sup.heartbeat(0)
            assert sup.state(0) == fleet_mod.UP
            actions = sup.notify_exit(0, returncode=1)
            if cycle < 2:
                assert sup.state(0) == fleet_mod.RESTARTING
                clock.t += 100.0
                sup.tick()  # spawn.
            else:
                assert ("quarantine", 0) in actions
        assert sup.state(0) == fleet_mod.QUARANTINED
        assert 0 not in sup.routable()
        # A zombie heartbeat cannot resurrect a quarantined replica.
        sup.heartbeat(0)
        assert sup.state(0) == fleet_mod.QUARANTINED
        assert any(e["kind"] == "quarantine" for e in ev)

    def test_infra_error_kinds_strike_breaker_compile_error_never(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev, breaker_threshold=3)
        sup.heartbeat(0)
        sup.heartbeat(1)
        # compile_error is a program bug, not replica sickness: NO
        # number of them may get a healthy replica killed.
        for _ in range(10):
            assert sup.report_error(0, "compile_error", "bad jaxpr") == []
        assert sup.state(0) == fleet_mod.UP
        # Infra kinds strike; the third opens the breaker -> down.
        assert sup.report_error(1, "device_crash") == []
        assert sup.report_error(1, "oom") == []
        actions = sup.report_error(1, "wedge_timeout")
        assert ("failover", 1) in actions
        assert sup.state(1) == fleet_mod.RESTARTING

    def test_transitions_emit_seq_ordered_fleet_events(self):
        clock, ev = FakeClock(), []
        sup = _supervisor(clock, ev)
        sup.heartbeat(0)
        clock.t = 6.0
        sup.tick()
        trans = [e for e in ev if e["kind"] == "transition"]
        assert [t["seq"] for t in trans] == sorted(
            t["seq"] for t in trans
        )
        assert trans[0]["from_state"] == fleet_mod.STARTING
        assert trans[0]["to_state"] == fleet_mod.UP
        path = [(t["from_state"], t["to_state"]) for t in trans
                if t["replica"] == 0]
        assert path == [("starting", "up"), ("up", "down"),
                        ("down", "restarting")]
        restart = [e for e in ev if e["kind"] == "restart"]
        assert restart and restart[0]["attempt"] == 1


# ---------------------------------------------------------------------
# Consistent-hash ring.
# ---------------------------------------------------------------------

class TestHashRing:
    def test_deterministic_and_total(self):
        ring = fleet_mod.HashRing([0, 1, 2])
        keys = [f"fam{i}:{b}" for i in range(8) for b in (8, 16, 32)]
        a = [ring.route(k) for k in keys]
        b = [fleet_mod.HashRing([0, 1, 2]).route(k) for k in keys]
        assert a == b
        assert set(a) <= {0, 1, 2}

    def test_node_loss_moves_only_its_keys(self):
        """THE consistent-hashing property the compiled-shape working
        set rides on: removing a replica relocates only the keys it
        owned — every other replica's shape set is undisturbed."""
        ring = fleet_mod.HashRing([0, 1, 2, 3])
        keys = [f"fam{i}:{b}" for i in range(32) for b in (8, 16, 32)]
        full = {k: ring.route(k) for k in keys}
        without_2 = {k: ring.route(k, alive={0, 1, 3}) for k in keys}
        for k in keys:
            if full[k] != 2:
                assert without_2[k] == full[k]
            else:
                assert without_2[k] != 2

    def test_empty_alive_set_returns_none(self):
        ring = fleet_mod.HashRing([0, 1])
        assert ring.route("k", alive=set()) is None


# ---------------------------------------------------------------------
# Chaos plan.
# ---------------------------------------------------------------------

class TestFaultPlan:
    def test_parse_to_spec_roundtrip(self):
        spec = "sigkill@1.5:r0,wedge@2:r1=3,error@2.5:r0=oom"
        plan = fleet_mod.FleetFaultPlan.parse(spec)
        assert plan.to_spec() == spec
        assert fleet_mod.FleetFaultPlan.parse(plan.to_spec()) == plan

    def test_seeded_plans_are_deterministic(self):
        a = fleet_mod.FleetFaultPlan.seeded(7, 3)
        b = fleet_mod.FleetFaultPlan.seeded(7, 3)
        assert a == b and a.actions
        assert fleet_mod.FleetFaultPlan.seeded(8, 3) != a

    def test_due_windows_partition_the_schedule(self):
        plan = fleet_mod.FleetFaultPlan.parse(
            "sigkill@1:r0,wedge@2:r1=3,sigterm@3:r0"
        )
        fired = []
        for lo, hi in [(0, 1.5), (1.5, 2.5), (2.5, 10)]:
            fired += plan.due(lo, hi)
        assert fired == list(plan.actions)

    def test_bad_tokens_raise(self):
        for bad in ("explode@1:r0", "sigkill@x:r0", "sigkill@1:q0"):
            with pytest.raises(ValueError):
                fleet_mod.FleetFaultPlan.parse(bad)

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv(fleet_mod.FLEET_FAULTS_ENV, "sigkill@1:r0")
        plan = fleet_mod.FleetFaultPlan.from_env()
        assert plan.actions[0].action == "sigkill"
        monkeypatch.delenv(fleet_mod.FLEET_FAULTS_ENV)
        assert fleet_mod.FleetFaultPlan.from_env().actions == ()


# ---------------------------------------------------------------------
# Per-tenant admission (queue hardening).
# ---------------------------------------------------------------------

def _req(i, tenant="default", family="f", horizon=4):
    return queue_mod.ScenarioRequest(
        family=family, horizon=horizon, request_id=f"t{i:03d}",
        tenant=tenant,
    )


def _queue(clock, tenants=None, capacity=64, emit=None):
    return queue_mod.AdmissionQueue(
        lambda fam: 2 if fam == "f" else None, capacity=capacity,
        clock=clock, tenants=tenants, emit=emit,
    )


class TestTenantAdmission:
    def test_token_bucket_rejects_structured_and_refills(self):
        clock = FakeClock()
        q = _queue(clock, tenants={
            "burst": queue_mod.TenantPolicy(rate_per_s=1.0, burst=2),
        })
        tickets = [q.submit(_req(i, "burst")) for i in range(3)]
        assert [t.status for t in tickets] == [
            queue_mod.PENDING, queue_mod.PENDING, queue_mod.REJECTED,
        ]
        assert tickets[2].reason == queue_mod.REASON_TENANT_RATE
        clock.t = 1.0  # one token refilled.
        assert q.submit(_req(3, "burst")).status == queue_mod.PENDING
        assert q.submit(_req(4, "burst")).status == queue_mod.REJECTED

    def test_rate_limit_never_masks_malformed_requests(self):
        """Admission order contract: a malformed request is rejected AS
        malformed and costs the tenant no tokens."""
        clock = FakeClock()
        q = _queue(clock, tenants={
            "burst": queue_mod.TenantPolicy(rate_per_s=0.0, burst=1),
        })
        bad = q.submit(queue_mod.ScenarioRequest(
            family="f", horizon=3, request_id="bad", tenant="burst",
        ))  # horizon off the chunk grid.
        assert bad.reason == queue_mod.REASON_BAD_HORIZON
        # The token survives for a well-formed request.
        assert q.submit(_req(0, "burst")).status == queue_mod.PENDING

    def test_default_tenant_is_unlimited_fifo(self):
        """Single-tenant backward compat: no policy table, plain FIFO —
        the pre-fleet AdmissionQueue behavior byte-for-byte."""
        clock = FakeClock()
        q = _queue(clock)
        ids = [q.submit(_req(i)).request.request_id for i in range(10)]
        taken = [t.request.request_id for t in q.take("f", 10)]
        assert taken == ids

    def test_weighted_fair_dequeue_shares(self):
        clock = FakeClock()
        q = _queue(clock, tenants={
            "heavy": queue_mod.TenantPolicy(weight=3.0),
            "light": queue_mod.TenantPolicy(weight=1.0),
        })
        for i in range(8):
            q.submit(_req(i, "heavy"))
            q.submit(_req(100 + i, "light"))
        taken = q.take("f", 8)
        by_tenant = {}
        for t in taken:
            by_tenant[t.request.tenant] = by_tenant.get(
                t.request.tenant, 0
            ) + 1
        assert by_tenant["heavy"] == 6 and by_tenant["light"] == 2

    def test_priority_class_dequeues_strictly_first(self):
        clock = FakeClock()
        q = _queue(clock, tenants={
            "ops": queue_mod.TenantPolicy(priority=1, weight=0.1),
            "batch": queue_mod.TenantPolicy(priority=0, weight=100.0),
        })
        for i in range(3):
            q.submit(_req(i, "batch"))
        for i in range(3):
            q.submit(_req(10 + i, "ops"))
        taken = [t.request.tenant for t in q.take("f", 6)]
        # Priority beats any weight: all ops first.
        assert taken == ["ops"] * 3 + ["batch"] * 3

    def test_tenant_survives_json_roundtrip(self):
        r = _req(0, tenant="pro")
        assert queue_mod.ScenarioRequest.from_json(r.to_json()).tenant \
            == "pro"
        # Default tenant stays off the wire (journal compat).
        assert "tenant" not in _req(1).to_json()

    def test_concurrent_submitters_thread_safety(self, tmp_path):
        """ISSUE 16 satellite: N threads hammering submit — no ticket
        id collisions, no lost rejections, schema-valid event stream
        (the jsonl_append concurrent-writer pin, queue edition)."""
        path = str(tmp_path / "subm.metrics.jsonl")
        writer = export_mod.MetricsWriter(path)
        clock = FakeClock()
        capacity = 40
        q = _queue(
            clock, capacity=capacity,
            emit=lambda **f: writer.emit("serving_event", **f),
        )
        n_threads, per_thread = 8, 10
        tickets: list = [None] * (n_threads * per_thread)
        barrier = threading.Barrier(n_threads)

        def hammer(k):
            barrier.wait()
            for j in range(per_thread):
                # Default-id path: the process-global ticket counter is
                # what must not collide under contention.
                tickets[k * per_thread + j] = q.submit(
                    queue_mod.ScenarioRequest(family="f", horizon=4)
                )

        threads = [threading.Thread(target=hammer, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ids = [t.request.request_id for t in tickets]
        assert len(set(ids)) == len(ids)  # no ticket id collisions.
        pending = [t for t in tickets if t.status == queue_mod.PENDING]
        rejected = [t for t in tickets if t.status == queue_mod.REJECTED]
        # No lost submissions: capacity admitted, the rest rejected
        # queue_full — EXACTLY (the lock makes the depth check atomic).
        assert len(pending) == capacity
        assert len(rejected) == n_threads * per_thread - capacity
        assert all(t.reason == queue_mod.REASON_QUEUE_FULL
                   for t in rejected)
        assert q.depth() == capacity
        # Drain sees every admitted ticket exactly once.
        assert len(q.take("f", 1000)) == capacity
        # The event stream stayed schema-valid under contention and
        # recorded every outcome.
        assert export_mod.validate_file(path) == []
        events = export_mod.read_events(path)
        kinds = {}
        for e in events:
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
        assert kinds["submitted"] == capacity
        assert kinds["rejected"] == len(rejected)


# ---------------------------------------------------------------------
# Fleet front: routing + failover + dedup.
# ---------------------------------------------------------------------

def _front(clock, sent, tracer=None, sink=None, tenants=None,
           replica_ids=(0, 1)):
    sup = fleet_mod.ReplicaSupervisor(
        list(replica_ids), lease_s=1.0, boot_grace_s=100.0,
        clock=clock, emit=sink,
    )
    for r in replica_ids:
        sup.heartbeat(r)
    front = fleet_mod.FleetFront(
        list(replica_ids), lambda fam: 2 if fam == "f" else None,
        send=lambda rid, op: sent.append((rid, op)),
        buckets=(4, 8), supervisor=sup, clock=clock,
        metrics=sink, tracer=tracer, tenants=tenants,
    )
    return front, sup


class TestFleetFront:
    def test_routing_is_sticky_per_family_bucket(self):
        clock, sent = FakeClock(), []
        front, _ = _front(clock, sent)
        for i in range(3):
            front.submit(_req(i))
        front.pump()
        owners = {op["request"]["request_id"]: rid for rid, op in sent}
        assert len(set(owners.values())) == 1  # one (family,bucket) key.
        # The same group shape routes to the same replica again.
        sent.clear()
        for i in range(10, 13):
            front.submit(_req(i))
        front.pump()
        again = {rid for rid, _ in sent}
        assert again == set(owners.values())

    def test_failover_redispatches_on_same_trace_id(self):
        rows = []

        class Sink:
            def emit(self, event, **kw):
                rows.append({"event": event, **kw})

        clock, sent = FakeClock(), []
        sink = Sink()
        tracer = trace_mod.Tracer(sink, track="front",
                                  clock_mono=lambda: clock.t)
        front, sup = _front(clock, sent, tracer=tracer, sink=sink)
        for i in range(4):
            front.submit(_req(i))
        front.pump()
        dead = sent[0][0]
        alive = 1 - dead
        trace_ids = {op["request"]["request_id"]: op["request"]["trace_id"]
                     for _, op in sent}
        sup.notify_exit(dead, returncode=-9)
        moved = front.failover(dead)
        assert sorted(moved) == [f"t{i:03d}" for i in range(4)]
        # Re-dispatch went to the healthy replica, SAME trace_id.
        redis = [(rid, op) for rid, op in sent if op["op"] == "submit"
                 and rid == alive]
        assert len(redis) == 4
        for rid, op in redis:
            assert op["request"]["trace_id"] == \
                trace_ids[op["request"]["request_id"]]
        # Best-effort cancels went to the dead replica's inbox.
        cancels = [op for rid, op in sent
                   if rid == dead and op["op"] == "cancel"]
        assert len(cancels) == 4
        fo = [r for r in rows if r.get("kind") == "failover"]
        assert len(fo) == 4
        assert all(r["trace_id"] == trace_ids[r["request_id"]]
                   for r in fo)

    def test_first_result_wins_duplicate_dropped(self):
        clock, sent = FakeClock(), []
        rows = []

        class Sink:
            def emit(self, event, **kw):
                rows.append({"event": event, **kw})

        front, _ = _front(clock, sent, sink=Sink())
        t = front.submit(_req(0))
        front.pump()
        assert front.deliver_result({
            "request_id": "t000", "status": "completed", "digest": "aa",
            "replica": 1,
        })
        assert t.status == queue_mod.COMPLETED and t.result == "aa"
        # The restarted replica re-serves and re-reports: dropped.
        assert not front.deliver_result({
            "request_id": "t000", "status": "completed", "digest": "aa",
            "replica": 0,
        })
        assert t.result == "aa"
        assert front.duplicates and front.stats()[
            "duplicates_dropped"] == 1
        assert any(r.get("kind") == "duplicate_result" for r in rows)

    def test_requests_hold_while_fleet_unroutable(self):
        clock, sent = FakeClock(), []
        front, sup = _front(clock, sent)
        for r in (0, 1):
            sup.notify_exit(r, returncode=1)
        front.submit(_req(0))
        assert front.pump() == 0 and sent == []  # held, not lost.
        sup.heartbeat(0)  # one replica recovers.
        assert front.pump() == 1
        assert sent[0][0] == 0

    def test_tenant_throttle_emits_fleet_event(self):
        rows = []

        class Sink:
            def emit(self, event, **kw):
                rows.append({"event": event, **kw})

        clock, sent = FakeClock(), []
        front, _ = _front(clock, sent, sink=Sink(), tenants={
            "burst": queue_mod.TenantPolicy(rate_per_s=0.0, burst=1),
        })
        front.submit(_req(0, tenant="burst"))
        t = front.submit(_req(1, tenant="burst"))
        assert t.status == queue_mod.REJECTED  # structured, no raise.
        throttles = [r for r in rows
                     if r.get("kind") == "tenant_rejected"]
        assert len(throttles) == 1
        assert throttles[0]["tenant"] == "burst"

    def test_failover_retry_segment_lands_on_original_trace(self):
        """The PR-15 composition: after a failover, the request's
        critical path shows an explicit retry segment — on the ORIGINAL
        trace_id — covering the re-served window (the front's
        guard_fallback span stays open until completion)."""
        clock, sent = FakeClock(), []
        tracer = trace_mod.Tracer(None, track="front",
                                  clock_mono=lambda: clock.t)
        front, sup = _front(clock, sent, tracer=tracer)
        t = front.submit(_req(0))
        tid = t.request.trace_id
        front.pump()
        dead = sent[0][0]
        clock.t = 5.0
        sup.notify_exit(dead, returncode=-9)
        front.failover(dead)
        # The surviving replica re-serves: its own request/queue spans
        # on the SAME trace (what a real replica's tracer would emit).
        rep = trace_mod.Tracer(None, track="r_alive",
                               clock_mono=lambda: clock.t)
        clock.t = 6.0
        root = rep.begin(trace_mod.REQUEST, parent=None, trace_id=tid,
                         request_id="t000")
        qs = rep.begin(trace_mod.QUEUE_WAIT, parent=root)
        clock.t = 6.5
        rep.end(qs)
        clock.t = 10.0
        rep.end(root, status="completed")
        front.deliver_result({"request_id": "t000",
                              "status": "completed", "digest": "d",
                              "replica": "x"})
        cp = trace_mod.critical_path(tracer.rows + rep.rows)
        mine = [q for q in cp["requests"] if q["trace_id"] == tid]
        assert len(mine) == 1  # deduped: the re-served span won.
        segs = mine[0]["segments"]
        # Window [6.5, 10] is fully inside the open failover span
        # [5, 10] -> the whole re-serve is retry time.
        assert segs["retry"] == pytest.approx(3.5)
        assert segs["batch_wait"] == pytest.approx(0.0)


# ---------------------------------------------------------------------
# Harness pieces (no subprocesses).
# ---------------------------------------------------------------------

class TestHarnessPieces:
    def test_parse_tenants_spec(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fleet_local

        policies = fleet_local.parse_tenants(
            "free:rate=2,burst=4;pro:weight=4,priority=1"
        )
        assert policies["free"].rate_per_s == 2.0
        assert policies["free"].burst == 4
        assert policies["pro"].weight == 4.0
        assert policies["pro"].priority == 1
        with pytest.raises(SystemExit):
            fleet_local.parse_tenants("x:bogus=1")

    def test_make_fleet_stream_deterministic(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fleet_local

        a = fleet_local.make_fleet_stream(
            8, ["f"], {"f": 2}, ["p", "q"], seed=3
        )
        b = fleet_local.make_fleet_stream(
            8, ["f"], {"f": 2}, ["p", "q"], seed=3
        )
        assert [(r.request_id, r.tenant, r.horizon) for r in a] == \
            [(r.request_id, r.tenant, r.horizon) for r in b]
        assert {r.tenant for r in a} == {"p", "q"}

    def test_bucket_hint_matches_batcher_rule(self):
        from tpu_aerial_transport.serving import batcher

        for pending in (1, 4, 8, 9, 40):
            assert fleet_mod.bucket_hint(pending, (4, 8)) == \
                batcher.bucket_for(pending, (4, 8))


# ---------------------------------------------------------------------
# Chaos acceptance e2e (subprocess; slow).
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_storm_digests_match_fault_free_run(tmp_path):
    """ISSUE 16 acceptance: under a fault plan that SIGKILLs one replica
    mid-batch and wedges the other, the fleet exits 0, every request
    completes with a digest equal to the fault-free run's, nothing is
    lost or double-completed, and the killed replica's requests carry a
    failover retry segment on their original trace_id."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(out, chaos=""):
        cmd = [
            sys.executable, os.path.join(REPO, "tools/fleet_local.py"),
            "--replicas", "2", "--force-multi", "--requests", "8",
            "--out-dir", str(tmp_path / out),
            "--results", str(tmp_path / f"{out}.json"),
            "--timeout", "300", "--seed", "5",
        ] + (["--chaos", chaos, "--lease", "1.0"] if chaos else [])
        proc = subprocess.run(
            cmd, capture_output=True, text=True, env=env, cwd=REPO,
            timeout=420,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        results = json.load(open(tmp_path / f"{out}.json"))
        return summary, results

    base_summary, base = run("fault_free")
    assert base_summary["ok"] and not base_summary["unresolved"]

    chaos_summary, chaos = run("storm", chaos="sigkill@6:r1,wedge@8:r0=2")
    assert chaos_summary["ok"], chaos_summary
    # No request lost or double-completed.
    assert not chaos_summary["unresolved"]
    assert chaos_summary["completed"] == 8

    # Bit-identical to the uninterrupted run (lane independence + full
    # replay): same ids, same digests.
    assert set(base) == set(chaos)
    for rid in base:
        assert base[rid]["status"] == chaos[rid]["status"] == "completed"
        assert base[rid]["digest"] == chaos[rid]["digest"], rid

    # The killed replica's requests show the failover as an explicit
    # retry segment on their ORIGINAL trace_id.
    events = export_mod.read_events(chaos_summary["metrics"])
    failed_over = {e["trace_id"] for e in events
                   if e.get("event") == "fleet_event"
                   and e.get("kind") == "failover"}
    if failed_over:  # chaos timing may catch the batch already done.
        cp = trace_mod.critical_path(
            trace_mod.stitch(trace_mod.trace_rows(events))
        )
        retried = {q["trace_id"] for q in cp["requests"]
                   if q["segments"]["retry"] > 0}
        assert failed_over & retried, (failed_over, retried)
    # Supervisor observed the kill and restarted the replica.
    kinds = {}
    for e in events:
        if e.get("event") == "fleet_event":
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    assert kinds.get("transition", 0) >= 3
    assert kinds.get("restart", 0) >= 1
