"""Differentiable-simulation harness (harness/diff.py): gradients through
the two-rate cascade exist and are useful, and jax.checkpoint
rematerialization changes memory, not values."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_aerial_transport.control import centralized
from tpu_aerial_transport.harness import diff, setup


def _problem(n=3, n_steps=20):
    params, col, state0 = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    xl_ref = state0.xl + jnp.array([0.4, 0.0, 0.3])
    loss = diff.make_rollout_loss(
        params, f_eq, xl_ref, n_steps=n_steps, remat=True
    )
    loss_noremat = diff.make_rollout_loss(
        params, f_eq, xl_ref, n_steps=n_steps, remat=False
    )
    gains = {"k_R": jnp.asarray(0.25), "k_Omega": jnp.asarray(0.075)}
    return loss, loss_noremat, gains, state0


def test_gradient_exists_and_is_finite():
    loss, _, gains, state0 = _problem()
    val, grad = jax.jit(jax.value_and_grad(loss))(gains, state0)
    assert np.isfinite(float(val))
    g = np.array([float(grad["k_R"]), float(grad["k_Omega"])])
    assert np.all(np.isfinite(g))
    assert np.any(np.abs(g) > 0), g


def test_remat_matches_no_remat():
    """jax.checkpoint trades FLOPs for memory; values and gradients must be
    identical (same graph re-executed, f32 determinism on one device)."""
    loss, loss_nr, gains, state0 = _problem()
    v1, g1 = jax.jit(jax.value_and_grad(loss))(gains, state0)
    v2, g2 = jax.jit(jax.value_and_grad(loss_nr))(gains, state0)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    for k in g1:
        np.testing.assert_allclose(
            float(g1[k]), float(g2[k]), rtol=1e-4, atol=1e-8
        )


def test_gradient_matches_finite_difference():
    loss, _, gains, state0 = _problem(n_steps=10)
    lj = jax.jit(loss)
    grad = jax.jit(jax.grad(loss))(gains, state0)
    eps = 1e-3
    for k in gains:
        gp = dict(gains)
        gp[k] = gains[k] + eps
        gm = dict(gains)
        gm[k] = gains[k] - eps
        fd = (float(lj(gp, state0)) - float(lj(gm, state0))) / (2 * eps)
        np.testing.assert_allclose(float(grad[k]), fd, rtol=0.05, atol=1e-5)


def test_tuning_reduces_loss():
    """A few projected-SGD steps from deliberately detuned gains must reduce
    the rollout loss and keep gains positive. The problem is made
    attitude-dependent (tilted initial quad attitudes + k_att alignment
    cost): near hover with aligned quads the position loss is flat in the
    attitude gains by physics, not by bug."""
    from tpu_aerial_transport.ops import lie

    params, col, state0 = setup.rqp_setup(3)
    f_eq = centralized.equilibrium_forces(params)
    # Tilt each quad 0.35 rad about a distinct axis.
    axes = jnp.array([[0.35, 0.0, 0.0], [0.0, 0.35, 0.0], [0.25, 0.25, 0.0]])
    R0 = jax.vmap(lie.expm_so3)(axes) @ state0.R
    state0 = state0.replace(R=R0)
    xl_ref = state0.xl + jnp.array([0.4, 0.0, 0.3])
    loss = diff.make_rollout_loss(
        params, f_eq, xl_ref, n_steps=15, remat=True, k_att=1.0
    )
    detuned = {"k_R": jnp.asarray(0.02), "k_Omega": jnp.asarray(0.2)}
    gains, hist = diff.tune_gains(loss, detuned, state0, lr=0.05, iters=10)
    hist = np.asarray(hist)
    assert np.all(np.isfinite(hist))
    assert hist[-1] < hist[0] * 0.98, hist
    assert float(gains["k_R"]) > 0 and float(gains["k_Omega"]) > 0


def test_sysid_recovers_payload_mass():
    """Gradient-based system identification: record a trajectory under the
    true payload mass, start the estimate 40% heavy, and descend
    make_sysid_loss — the recovered mass must land within 2% of truth."""
    params, col, state0 = setup.rqp_setup(3)
    f_eq = centralized.equilibrium_forces(params)
    xl_ref = state0.xl + jnp.array([0.5, 0.2, 0.3])
    gains = {"k_R": jnp.asarray(0.25), "k_Omega": jnp.asarray(0.075)}
    n_steps = 25

    # Record: closed-loop commands + observed payload trajectory (truth),
    # through the same substep_rollout the estimator replays.
    def mpc(state, _):
        f_des = diff.payload_pd_forces(params, f_eq, state, xl_ref)
        state = diff.substep_rollout(params, gains, state, f_des)
        return state, (f_des, state.xl, state.vl)

    _, (f_des_seq, xl_obs, vl_obs) = jax.jit(
        lambda s: jax.lax.scan(mpc, s, None, length=n_steps)
    )(state0)

    loss = diff.make_sysid_loss(
        params.m, params.J, params.Jl, params.r, gains,
        f_des_seq, xl_obs, vl_obs,
    )
    true_ml = float(params.ml)
    theta0 = {"log_ml": jnp.log(jnp.asarray(true_ml * 1.4))}

    # Sanity: loss at truth is ~0 and less than at the perturbed start.
    at_truth = float(jax.jit(loss)({"log_ml": jnp.log(params.ml)}, state0))
    at_start = float(jax.jit(loss)(theta0, state0))
    assert at_truth < 1e-8, at_truth
    assert at_start > 100 * max(at_truth, 1e-12), (at_start, at_truth)

    # lr derived from the basin curvature measured IN THIS RUN (loss is
    # ~quadratic in log-mass: c = at_start / delta0^2; GD contraction per
    # step is (1 - 2 c lr), so lr = 0.1 / c contracts ~0.8x per iteration
    # and 40 iterations reach <2% regardless of future constant changes).
    delta0 = float(np.log(1.4))
    curvature = at_start / delta0**2
    lr = 0.1 / curvature
    theta, hist = diff.tune_gains(
        loss, theta0, state0, lr=lr, iters=40, min_gain=None
    )
    hist = np.asarray(hist)
    assert np.all(np.isfinite(hist))
    assert hist[-1] < hist[0], hist  # descent actually happened.
    est = float(jnp.exp(theta["log_ml"]))
    assert abs(est - true_ml) / true_ml < 0.02, (est, true_ml)


def test_trajopt_improves_and_clears_obstacle():
    """Single-shooting optimal control through the cascade (Adam — the
    per-step plan's curvature spectrum spans ~1e5, see tune_gains): from a
    zero plan, descent must cut the objective substantially, move the
    payload meaningfully toward the goal, and route the path around the
    obstacle cylinder sitting on the straight line. Absolute goal capture
    is physics-limited on this short horizon (the SO(3) attitude loop
    low-passes lateral force commands), so the assertions check material
    improvement, not perfection."""
    params, col, state0 = setup.rqp_setup(3)
    f_eq = centralized.equilibrium_forces(params)
    goal = state0.xl + jnp.array([0.8, 0.0, 0.0])
    obs_xy = state0.xl[:2] + jnp.array([0.4, 0.0])
    n_steps = 60
    loss = diff.make_trajopt_loss(
        params, f_eq, goal, n_steps=n_steps,
        obstacle_xy=obs_xy, obstacle_radius=0.25, w_effort=1e-4,
    )
    plan0 = {"acc": jnp.zeros((n_steps, 3))}
    base = float(jax.jit(loss)(plan0, state0))
    plan, hist = diff.tune_gains(
        loss, plan0, state0, lr=0.5, iters=200, min_gain=None,
        optimizer="adam",
    )
    final = float(jax.jit(loss)(plan, state0))
    assert final < 0.75 * base, (final, base)

    # Replay the optimized plan through the SAME force law and rollout the
    # loss optimized (plan_share_forces + substep_rollout).
    gains = {"k_R": jnp.asarray(0.25), "k_Omega": jnp.asarray(0.075)}

    def mpc(state, acc):
        f_des = diff.plan_share_forces(params, f_eq, acc)
        state = diff.substep_rollout(params, gains, state, f_des)
        return state, state.xl

    _, xl_seq = jax.jit(
        lambda s, a: jax.lax.scan(mpc, s, a)
    )(state0, plan["acc"])
    xl_seq = np.asarray(xl_seq)
    init_dist = float(np.linalg.norm(np.asarray(goal - state0.xl)))
    term_err = float(np.linalg.norm(xl_seq[-1] - np.asarray(goal)))
    assert term_err < 0.85 * init_dist, (term_err, init_dist)
    clearance = np.linalg.norm(
        xl_seq[:, :2] - np.asarray(obs_xy)[None], axis=-1
    ).min()
    assert clearance > 0.15, clearance
