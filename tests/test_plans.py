"""Precomputed-plan tests.

Both distributed controllers accept a state-independent plan built once
outside the rollout (cadmm.make_plan / dd.make_dd_plan). Pinned here:
(1) the payload-frame DD QN precompute against an independently computed
    world-frame quasi-Newton step from the live state (the formulation the
    plan replaced) — the non-tautological oracle, incl. the rank-9 Woodbury
    leader correction; the C-ADMM plan's equivalent oracle is the
    reduced-vs-full-QP exactness test in tests/test_cadmm_schur.py;
(2) plan-vs-inline plumbing — explicitly passing the plan must not change
    results (guards the local-slice gather and rho-axis indexing);
(3) leader invariance of the consensus optimum: the tracking cost is carried
    exactly once whichever agent leads (reference rqp_cadmm.py:231-233
    scales k_f/k_m by 1/n), so switching leaders must not move the optimum."""

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.ops import lie

ACC = (jnp.array([0.5, 0.1, 0.0]), jnp.zeros(3))


def _state(n, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return rqp.rqp_state(
        R=lie.expm_so3(0.1 * jax.random.normal(ks[0], (n, 3))),
        w=0.1 * jax.random.normal(ks[1], (n, 3)),
        xl=jnp.zeros(3),
        vl=0.3 * jax.random.normal(ks[2], (3,)),
        Rl=lie.expm_so3(0.05 * jax.random.normal(ks[3], (3,))),
        wl=jnp.zeros(3),
    )


def test_dd_plan_qn_matches_world_frame():
    """Direct pin of the payload-frame QN precompute: the plan-based dual
    step (rotate violations in, apply qn_inv_base + rank-9 Woodbury leader
    correction, rotate the F-step out) must equal the quasi-Newton step
    computed entirely in the WORLD frame from the current state — the
    per-step formulation the plan replaced (reference rqp_dd.py:634-657).
    Non-default leader exercises the Woodbury path; k_smooth = 0 so the
    preconditioner is exact."""
    import numpy as np

    n = 4
    params, col, _ = setup.rqp_setup(n)
    cfg = dd.make_config(params, col.collision_radius, col.max_deceleration)
    cfg = cadmm.set_leader(cfg, 2)
    base = cfg.base
    state = _state(n, seed=5)
    dtype = jnp.float32

    # --- World-frame QN matrix from the live state.
    leaders_full = (jnp.arange(n) == base.leader_idx).astype(dtype)
    Q_w = jax.vmap(
        lambda r_i, R_i, w_i, ld: dd.strong_convexity_matrix(
            params, base, state, r_i, R_i, w_i, ld, cfg.sc_eps
        )
    )(params.r_com, state.R, state.w, leaders_full)
    Qinv_w = jnp.linalg.inv(Q_w)
    Ac_w = dd._consensus_matrix(params, state.Rl)
    Ac_blocks = Ac_w.reshape(6 * n, n, 9)
    AQinv = jnp.einsum("mnj,njk->mnk", Ac_blocks, Qinv_w).reshape(6 * n, 9 * n)
    qn_w = AQinv @ Ac_w.T + cfg.beta * jnp.eye(6 * n, dtype=dtype)
    grad_w = jax.random.normal(jax.random.PRNGKey(8), (n, 6))
    step_w = jnp.linalg.solve(
        qn_w, grad_w.reshape(-1)
    ).reshape(n, 6)

    # --- Plan path (mirrors dd.control's Woodbury block).
    plan = dd.make_dd_plan(params, cfg)
    li = int(base.leader_idx)
    A_l = plan.Ac[:, 9 * li : 9 * li + 9]
    Dl = plan.D[li]
    Pb = plan.qn_inv_base
    PA = Pb @ A_l
    K9 = jnp.eye(9, dtype=dtype) + Dl @ (A_l.T @ PA)
    qn_inv_p = Pb - PA @ jnp.linalg.solve(K9, Dl @ PA.T)
    grad_t = jnp.concatenate(
        [grad_w[:, :3] @ state.Rl, grad_w[:, 3:]], axis=1
    )
    step_t = (qn_inv_p @ grad_t.reshape(-1)).reshape(n, 6)
    step_p = jnp.concatenate(
        [step_t[:, :3] @ state.Rl.T, step_t[:, 3:]], axis=1
    )

    err = float(jnp.abs(step_p - step_w).max())
    scale = float(jnp.abs(step_w).max())
    assert err < 2e-3 * max(scale, 1.0), \
        f"plan QN step deviates from world-frame QN step: {err} (scale {scale})"
    assert np.isfinite(err)


def test_cadmm_plan_vs_inline():
    n = 5
    params, col, _ = setup.rqp_setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=40, inner_iters=60, res_tol=1e-3,
    )
    f_eq = centralized.equilibrium_forces(params)
    state = _state(n)
    a0 = cadmm.init_cadmm_state(params, cfg)
    f_inline, _, st_inline = cadmm.control(params, cfg, f_eq, a0, state, ACC)
    plan = cadmm.make_plan(params, cfg)
    assert plan is not None
    f_plan, _, st_plan = cadmm.control(
        params, cfg, f_eq, a0, state, ACC, plan=plan
    )
    assert float(jnp.abs(f_plan - f_inline).max()) < 1e-5
    assert int(st_plan.iters) == int(st_inline.iters)


def test_dd_plan_vs_inline():
    n = 4
    params, col, _ = setup.rqp_setup(n)
    cfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=40, inner_iters=60,
    )
    f_eq = centralized.equilibrium_forces(params)
    state = _state(n, seed=1)
    d0 = dd.init_dd_state(params, cfg)
    f_inline, _, st_inline = dd.control(params, cfg, f_eq, d0, state, ACC)
    plan = dd.make_dd_plan(params, cfg)
    f_plan, _, st_plan = dd.control(
        params, cfg, f_eq, d0, state, ACC, plan=plan
    )
    assert float(jnp.abs(f_plan - f_inline).max()) < 1e-5
    assert int(st_plan.iters) == int(st_inline.iters)


def test_leader_switch_reaches_same_optimum():
    """The tracking cost is carried exactly once whichever agent leads, so
    the consensus optimum is leader-invariant. For DD this exercises the
    rank-9 Woodbury correction at a non-default leader against the
    precomputed base QN inverse."""
    n = 5
    params, col, _ = setup.rqp_setup(n)
    f_eq = centralized.equilibrium_forces(params)
    state = _state(n, seed=2)

    acfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80, res_tol=1e-3,
    )
    a0 = cadmm.init_cadmm_state(params, acfg)
    f0, _, _ = cadmm.control(params, acfg, f_eq, a0, state, ACC)
    f1, _, st1 = cadmm.control(
        params, cadmm.set_leader(acfg, 3), f_eq, a0, state, ACC
    )
    assert int(st1.iters) <= acfg.max_iter
    assert float(jnp.abs(f1 - f0).max()) < 3e-2, "cadmm leader variance"

    dcfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=60, inner_iters=80,
    )
    plan = dd.make_dd_plan(params, dcfg)
    d0 = dd.init_dd_state(params, dcfg)
    g0, _, _ = dd.control(params, dcfg, f_eq, d0, state, ACC, plan=plan)
    g1, _, st2 = dd.control(
        params, cadmm.set_leader(dcfg, 3), f_eq, d0, state, ACC, plan=plan
    )
    assert int(st2.iters) <= dcfg.base.max_iter
    assert float(jnp.abs(g1 - g0).max()) < 3e-2, "dd leader variance"
