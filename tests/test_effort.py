"""Adaptive solver effort (ISSUE 13): in-kernel early exit, per-lane
convergence freezing, the consensus-level ``effort`` knob, and the
iteration-effort telemetry.

Oracles, strongest first:

1. **Bitwise per-lane semantics** of the tolerance-chunked path: lane i
   of the batched ``check_every/tol`` solve equals lane i of the batched
   FIXED-iteration solve run to lane i's own effective iteration count
   (``report_iters``) — each lane's result depends only on its own
   convergence schedule, never on how long the loop drains other lanes.
   (A truly unbatched program is NOT the bitwise oracle on XLA-CPU:
   batched and unbatched matmuls reduce in different orders — measured
   ~1e-7 — which is exactly why the per-lane contract is stated against
   the batched fixed-iteration program.)
2. **Bitwise kernel parity**: the in-kernel early-exit form
   (``fused="kernel_interpret"`` + check_every/tol) ≡ the scan path,
   solutions AND per-lane effective iteration counts, with and without
   the consensus-effort ``active`` gate — in ONE pallas_call.
3. **Zero-cost contract**: ``effort="fixed"`` compiles byte-identical
   HLO (every adaptive branch is Python-level); adaptive results match
   fixed within the paper's 1e-2 N consensus-residual tolerance,
   nominal AND alive-masked, cadmm AND dd.
4. **Telemetry/observability**: consensus-/inner-iteration histograms
   accumulate in-jit, roll up across lanes, and render in run_health's
   solver-effort section + bench-table columns.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_aerial_transport.control import cadmm, centralized, dd
from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.harness import setup
from tpu_aerial_transport.obs import telemetry as telemetry_mod
from tpu_aerial_transport.ops import socp
from tpu_aerial_transport.resilience import faults as faults_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------- problem builders --------------------------


def _problems(B=5, nv=8, n_box=6, soc=(4,), seed=0):
    rng = np.random.default_rng(seed)

    def one():
        L = rng.standard_normal((nv, nv))
        P = jnp.asarray(L @ L.T + np.eye(nv), jnp.float32)
        q = jnp.asarray(rng.standard_normal(nv), jnp.float32)
        m = n_box + sum(soc)
        A = jnp.asarray(rng.standard_normal((m, nv)) * 0.5, jnp.float32)
        lb = jnp.asarray(rng.uniform(-2.0, -0.5, n_box), jnp.float32)
        ub = jnp.asarray(rng.uniform(0.5, 2.0, n_box), jnp.float32)
        shift = jnp.zeros((m,), jnp.float32).at[n_box].set(3.0)
        return P, q, A, lb, ub, shift

    return [jnp.stack(x) for x in zip(*[one() for _ in range(B)])]


def _solve_batch(args, mode, iters=30, tol=0.0, check_every=0,
                 active=None, report_iters=False):
    Ps, qs, As, lbs, ubs, shifts = args

    def f(P_, q_, A_, lb_, ub_, s_, *act):
        return socp.solve_socp_padded(
            P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=iters,
            shift=s_, fused=mode, tol=tol, check_every=check_every,
            active=act[0] if act else None, report_iters=report_iters,
        )

    if active is not None:
        return jax.vmap(f)(Ps, qs, As, lbs, ubs, shifts, active)
    return jax.vmap(f)(Ps, qs, As, lbs, ubs, shifts)


def _assert_bitwise(out, ref, fields=("x", "y", "z", "prim_res",
                                      "dual_res")):
    for name in fields:
        a = np.asarray(getattr(out, name))
        b = np.asarray(getattr(ref, name))
        assert np.array_equal(a, b), (
            f"{name} differs (max abs {np.abs(a - b).max()})"
        )


# ------------------- per-lane tolerance-chunk semantics -----------------


def test_tol_chunked_per_lane_bitwise_vs_own_schedule():
    """Lane i of the batched tol-chunked solve == lane i of the batched
    fixed-iteration solve run to lane i's own effective count, BITWISE —
    the per-lane freezing never contaminates a converged lane while the
    loop drains stragglers (satellite: vmapped check_every/tol
    regression)."""
    args = _problems()
    sol, eff = _solve_batch(args, "scan", tol=1e-3, check_every=7,
                            report_iters=True)
    eff = np.asarray(eff)
    assert len(set(eff.tolist())) > 1, (
        "test problems must have an iteration-count spread"
    )
    for i, e in enumerate(eff.tolist()):
        ref = _solve_batch(args, "scan", iters=int(e))
        for name in ("x", "y", "z"):
            a = np.asarray(getattr(sol, name))[i]
            b = np.asarray(getattr(ref, name))[i]
            assert np.array_equal(a, b), (name, i, e)


def test_batched_while_runs_until_worst_lane():
    """The cost model the consensus tier attacks, documented: the batched
    tolerance-chunked while_loop runs until the WORST lane — but a
    fast-converging lane's result and effective count are bitwise
    independent of the stragglers sharing its batch (same-shape batch
    with the straggler replaced by a clone of the fast lane)."""
    args = _problems()
    _, eff = _solve_batch(args, "scan", tol=1e-3, check_every=7,
                          report_iters=True)
    eff = np.asarray(eff)
    fast = int(np.argmin(eff))
    slow = int(np.argmax(eff))
    assert eff[fast] < eff[slow], "need a straggler spread"
    # Wall-clock cost model: the while_loop's vmap batching keeps the
    # whole batch iterating while ANY lane is active, so the global chunk
    # count is max over lanes — eff[slow] here. Each lane only ACCUMULATES
    # its own eff[i] chunks (frozen selects after that), which is what the
    # histograms measure and the adaptive consensus tier exploits.
    clone = [
        jnp.stack([a[i] if i != slow else a[fast]
                   for i in range(a.shape[0])]) for a in args
    ]
    sol_mixed, eff_mixed = _solve_batch(args, "scan", tol=1e-3,
                                        check_every=7, report_iters=True)
    sol_clone, eff_clone = _solve_batch(clone, "scan", tol=1e-3,
                                        check_every=7, report_iters=True)
    assert int(np.asarray(eff_clone)[fast]) == int(eff[fast])
    for name in ("x", "y", "z"):
        a = np.asarray(getattr(sol_mixed, name))[fast]
        b = np.asarray(getattr(sol_clone, name))[fast]
        assert np.array_equal(a, b), name


# ------------------------- in-kernel early exit -------------------------


def test_kernel_earlyexit_bitwise_vs_scan():
    """The in-kernel early-exit form (interpret twin) ≡ the scan path's
    tolerance-chunked loop BITWISE: solutions, exit residuals, and the
    per-lane effective iteration counts."""
    args = _problems()
    ref, eff_ref = _solve_batch(args, "scan", tol=1e-3, check_every=7,
                                report_iters=True)
    out, eff_out = _solve_batch(args, "kernel_interpret", tol=1e-3,
                                check_every=7, report_iters=True)
    _assert_bitwise(out, ref)
    assert np.array_equal(np.asarray(eff_ref), np.asarray(eff_out))


def test_kernel_earlyexit_single_pallas_call():
    """A tolerance-chunked kernel solve stages exactly ONE pallas_call —
    the label-drift fix: before the in-kernel exit, the same config
    staged an XLA while_loop re-launching the kernel (re-streaming the
    operators from HBM) once per chunk."""
    Ps, qs, As, lbs, ubs, shifts = _problems(B=2)

    def fn(P_, q_, A_, lb_, ub_, s_):
        return socp.solve_socp_padded(
            P_, q_, A_, lb_, ub_, n_box=6, soc_dims=(4,), iters=30,
            shift=s_, fused="kernel_interpret", tol=1e-3, check_every=7,
        )

    jaxpr = str(jax.make_jaxpr(jax.vmap(fn))(Ps, qs, As, lbs, ubs, shifts))
    assert jaxpr.count("pallas_call") == 1
    # ... and the chunk loop lives INSIDE it: no XLA-side while wrapping
    # the kernel (the jaxpr's only while ops are within the kernel body,
    # which the count above already pins to one launch).


def test_kernel_earlyexit_active_gate_bitwise():
    """The consensus-effort gate: gated-off lanes are 0-effective-
    iteration pass-throughs on BOTH realizations, bitwise, and gated-on
    lanes are untouched by their gated-off neighbors."""
    args = _problems()
    act = jnp.array([True, False, True, False, True])
    ref, eff_ref = _solve_batch(args, "scan", tol=1e-3, check_every=7,
                                active=act, report_iters=True)
    out, eff_out = _solve_batch(args, "kernel_interpret", tol=1e-3,
                                check_every=7, active=act,
                                report_iters=True)
    _assert_bitwise(out, ref)
    eff = np.asarray(eff_out)
    assert np.array_equal(eff, np.asarray(eff_ref))
    assert eff[1] == 0 and eff[3] == 0 and eff[0] > 0
    # Gated-on lanes match the ungated solve bitwise (no cross-lane
    # contamination from the pass-through neighbors).
    full, eff_full = _solve_batch(args, "scan", tol=1e-3, check_every=7,
                                  report_iters=True)
    for i in (0, 2, 4):
        assert np.array_equal(np.asarray(out.x)[i], np.asarray(full.x)[i])
        assert eff[i] == np.asarray(eff_full)[i]


def test_compiled_earlyexit_form_matches_exact_f32():
    """The Mosaic-lowerable broadcast-reduce body of the early-exit
    kernel (exact_dot=False, run under the interpreter) agrees with the
    bitwise exact_dot body to f32 rounding — the PR-12 numerics contract
    extended to the while-loop form. Effective iteration counts must
    stay close (residual thresholds under different rounding may flip a
    lane by one chunk at most)."""
    from tpu_aerial_transport.ops import admm_kernel

    Ps, qs, As, lbs, ubs, shifts = _problems()
    B = Ps.shape[0]
    nv_p, n_box_p = socp.padded_dims(8, 6, (4,))
    m_p = n_box_p + 4
    pqps = jax.vmap(
        lambda P_, A_, lb_, ub_, s_: socp.padded_kkt_operator(
            P_, A_, lb_, ub_, s_, n_box=6, soc_dims=(4,)
        )
    )(Ps, As, lbs, ubs, shifts)
    qs_p = jnp.pad(qs, ((0, 0), (0, nv_p - 8)))
    z0 = jax.vmap(
        lambda lb_, ub_, s_: socp._project_cone(
            jnp.zeros((m_p,)), lb_, ub_, n_box_p, (4,), s_
        )
    )(pqps.lb, pqps.ub, pqps.shift)
    rho_v = jax.vmap(
        lambda lb_, ub_: socp.make_rho_vec(m_p, n_box_p, lb_, ub_, 0.4)
    )(pqps.lb, pqps.ub)

    def run(exact_dot):
        return admm_kernel.fused_solve_lanes(
            jnp.zeros((B, nv_p)), jnp.zeros((B, m_p)), z0,
            pqps.op.K2, pqps.op.Minv, pqps.A, pqps.P, qs_p, rho_v,
            pqps.lb, pqps.ub, pqps.shift, jnp.ones((B,), bool),
            nv=nv_p, n_box=n_box_p, soc_dims=(4,), iters=30, alpha=1.6,
            check_every=7, tol=1e-3, interpret=True, exact_dot=exact_dot,
        )

    exact, compiled = run(True), run(False)
    for a, b in zip(exact[:5], compiled[:5]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=1e-4
        )
    assert np.abs(
        np.asarray(exact[5]).astype(int) - np.asarray(compiled[5]).astype(int)
    ).max() <= 7  # at most one check_every chunk of threshold flip.


def test_active_requires_tol_path():
    """A fixed-iteration solve cannot express the pass-through: active=
    without check_every/tol is a clear ValueError on both entry points."""
    P, q, A, lb, ub, _ = [a[0] for a in _problems(B=1)]
    with pytest.raises(ValueError):
        socp.solve_socp(
            P, q, A, lb, ub, n_box=6, soc_dims=(4,), iters=8,
            active=jnp.ones((), bool),
        )
    from tpu_aerial_transport.ops import admm_kernel

    with pytest.raises(ValueError):
        admm_kernel.fused_solve_lanes(
            jnp.zeros((2, 8)), jnp.zeros((2, 10)), jnp.zeros((2, 10)),
            jnp.zeros((2, 18, 18)), jnp.zeros((2, 8, 8)),
            jnp.zeros((2, 10, 8)), jnp.zeros((2, 8, 8)), jnp.zeros((2, 8)),
            jnp.ones((2, 10)), jnp.zeros((2, 6)), jnp.ones((2, 6)),
            None, jnp.ones((2,), bool),
            nv=8, n_box=6, soc_dims=(4,), iters=8, alpha=1.6,
        )


# ----------------------- resolver + config plumbing ---------------------


def test_resolve_effort_gate(monkeypatch):
    """socp.resolve_effort: auto -> fixed (until the chip-round flip
    criterion), TAT_EFFORT env force, junk raises; the resolved value
    lands on the static field of BOTH controller configs."""
    monkeypatch.delenv("TAT_EFFORT", raising=False)
    assert socp.resolve_effort("auto") == "fixed"
    assert socp.resolve_effort(None) == "fixed"
    monkeypatch.setenv("TAT_EFFORT", "adaptive")
    assert socp.resolve_effort("auto") == "adaptive"
    assert socp.resolve_effort("fixed") == "fixed"  # explicit wins.
    monkeypatch.setenv("TAT_EFFORT", "lazy")
    with pytest.raises(ValueError):
        socp.resolve_effort("auto")
    with pytest.raises(ValueError):
        socp.resolve_effort("turbo")
    params, col, _ = setup.rqp_setup(4)
    monkeypatch.setenv("TAT_EFFORT", "adaptive")
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration
    )
    assert cfg.effort == "adaptive"
    dcfg = dd.make_config(
        params, col.collision_radius, col.max_deceleration, effort="fixed"
    )
    assert dcfg.base.effort == "fixed"


def test_runtime_fused_mode_takes_chunking():
    """The shared resolver accepts the solve's chunking mode (the
    label-drift fold): labels are stable across it today — both kernel
    forms exist — and a tol-chunked kernel config still resolves
    "kernel"-family, which now IS one pallas_call."""
    assert socp.runtime_fused_mode(
        "kernel_interpret", 16, 32, 24, check_every=10, tol=1e-3
    ) == "kernel_interpret"
    assert socp.runtime_fused_mode(
        "scan", 16, 32, 24, check_every=10, tol=1e-3
    ) == "scan"


# ----------------------- controller-level contracts ---------------------


def _step_hlo(ctrl, effort):
    params, col, state = setup.rqp_setup(4)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    f_eq = centralized.equilibrium_forces(params)
    mod = cadmm if ctrl == "cadmm" else dd
    cfg = mod.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=2, inner_iters=4, pad_operators=True, effort=effort,
    )
    init = (cadmm.init_cadmm_state if ctrl == "cadmm"
            else dd.init_dd_state)
    cs = init(params, cfg)
    return jax.jit(
        lambda a, s: mod.control(params, cfg, f_eq, a, s, acc_des)
    ).lower(cs, state).as_text()


@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_effort_fixed_identical_hlo(ctrl):
    """The zero-cost contract (the no_faults()/telemetry=None pattern):
    effort="fixed" and the knob-default "auto" config lower byte-
    identical HLO — every adaptive branch is Python-level, so shipping
    the knob cannot perturb a fixed deployment — while "adaptive"
    genuinely changes the program (sanity that the knob is live)."""
    fixed = _step_hlo(ctrl, "fixed")
    assert fixed == _step_hlo(ctrl, "auto")
    assert fixed != _step_hlo(ctrl, "adaptive")


def _run_ctrl_batch(ctrl, effort, health):
    n = 4
    params, col, state = setup.rqp_setup(n)
    acc_des = (jnp.array([0.3, 0.0, 0.1]), jnp.zeros(3))
    mod = cadmm if ctrl == "cadmm" else dd
    cfg = mod.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=8, inner_iters=20, inner_check_every=5,
        pad_operators=True, effort=effort,
    )
    f_eq = centralized.equilibrium_forces(
        params, alive=None if health is None else health.alive
    )
    if ctrl == "cadmm":
        cs = cadmm.init_cadmm_state(params, cfg)
        if health is not None:
            cs = cs.replace(held=cs.f)
    else:
        cs = dd.init_dd_state(params, cfg)
        if health is not None:
            cs = cs.replace(held_f=cs.f, held_lam_F=cs.lam_F,
                            held_lam_M=cs.lam_M)
    vls = jnp.stack([
        jnp.array([0.2, 0.1, 0.0]), jnp.array([-0.1, 0.3, 0.1]),
        jnp.array([0.0, 0.0, -0.2]),
    ])
    states = jax.vmap(lambda v: state.replace(vl=v))(vls)
    css = jax.vmap(lambda _: cs)(vls)

    def one(ast, st):
        return mod.control(
            params, cfg, f_eq, ast, st, acc_des, health=health
        )

    f, _, stats = jax.jit(jax.vmap(one))(css, states)
    return np.asarray(f), stats


_HEALTH = faults_mod.FaultStep(
    alive=jnp.array([False, True, True, True]),
    thrust_scale=jnp.array([0.0, 1.0, 1.0, 1.0], jnp.float32),
    msg_ok=jnp.array([False, True, False, True]),
)


@pytest.mark.parametrize("masked", [False, True],
                         ids=["nominal", "alive-masked"])
@pytest.mark.parametrize("ctrl", ["cadmm", "dd"])
def test_adaptive_matches_fixed_within_res_bar(ctrl, masked):
    """Acceptance: per-lane adaptive results match the fixed-iteration
    solve within the paper's 1e-2 N consensus-residual tolerance —
    nominal AND alive-masked, cadmm AND dd — and the adaptive arm's
    effort accounting is populated and bounded by the static budget."""
    health = _HEALTH if masked else None
    f_fix, st_fix = _run_ctrl_batch(ctrl, "fixed", health)
    f_ada, st_ada = _run_ctrl_batch(ctrl, "adaptive", health)
    # Equal-quality bar: the adaptive arm converges to the same consensus
    # tolerance (its residual under the paper's bar wherever fixed's is),
    # and the applied forces agree within that bar.
    res_a = np.asarray(st_ada.solve_res)
    res_f = np.asarray(st_fix.solve_res)
    assert np.all(res_a[res_f < 1e-2] < 1e-2)
    assert np.abs(f_ada - f_fix).max() < 1e-2
    # Effort accounting: populated scalar per lane, positive, and never
    # above the static worst case (n agents x inner budget x outer
    # iterations actually run).
    inner = np.asarray(st_ada.inner_iters)
    assert inner.shape == (3,)
    iters = np.asarray(st_ada.iters)
    assert np.all(inner > 0)
    assert np.all(inner <= 4 * 20 * np.maximum(iters, 1))
    # Fixed stays on the "not tracked" sentinel — no accounting staged.
    assert st_fix.inner_iters.shape == (3, 0)


# ----------------------------- telemetry --------------------------------


def _stats(iters, inner=None):
    return SolverStats(
        iters=jnp.asarray(iters, jnp.int32),
        solve_res=jnp.asarray(1e-3, jnp.float32),
        collision=jnp.zeros((), bool),
        min_env_dist=jnp.asarray(1.0, jnp.float32),
        ok_frac=jnp.ones(()),
        **({} if inner is None
           else {"inner_iters": jnp.asarray(inner, jnp.int32)}),
    )


def test_telemetry_effort_histograms():
    """The consensus-/inner-iteration histograms accumulate in-jit with
    the documented bucket semantics and render in summary()'s effort
    block."""
    cfg = telemetry_mod.TelemetryConfig()
    tel = telemetry_mod.init_telemetry(cfg)
    for iters, inner in ((3, 60), (3, 30), (17, 340), (1, 4)):
        tel = telemetry_mod.update(cfg, tel, _stats(iters, inner))
    hist = np.asarray(tel.consensus_hist)
    # Buckets (1, 2, 4, 8, 16, 32, ...): 3 -> "<=4" (idx 2) twice,
    # 17 -> "<=32" (idx 5), 1 -> "<=1" (idx 0).
    assert hist[2] == 2 and hist[5] == 1 and hist[0] == 1
    assert hist.sum() == 4
    assert int(tel.inner_iters_sum) == 60 + 30 + 340 + 4
    # Inner histogram buckets inner/consensus-iter: 20, 10, 20, 4 —
    # "<=32" (idx 5) twice, "<=16" (idx 4) once, "<=4" (idx 2) once.
    ih = np.asarray(tel.inner_hist)
    assert ih.sum() == 4
    assert ih[5] == 2 and ih[4] == 1 and ih[2] == 1
    s = telemetry_mod.summary(tel)
    eff = s["effort"]
    assert eff["consensus_hist"] == [int(v) for v in hist]
    assert eff["iters_mean"] == pytest.approx((3 + 3 + 17 + 1) / 4)
    assert eff["iters_p99"] == 32  # bucket-edge upper bound.
    assert eff["inner_iters_sum"] == 434
    # n_agents defaulted 0 -> per-solve normalizer 1.
    assert eff["inner_per_solve_mean"] == pytest.approx(434 / 24)
    # Per-agent normalization: the same stream at n_agents=10 buckets
    # per-SOLVE values (2, 1, 2, 0.4) instead of saturating large-fleet
    # totals, and the overflow bucket's percentile is None (JSON-safe),
    # never Infinity.
    tel10 = telemetry_mod.init_telemetry(cfg, n_agents=10)
    for iters, inner in ((3, 60), (3, 30), (17, 340), (1, 4)):
        tel10 = telemetry_mod.update(cfg, tel10, _stats(iters, inner))
    ih10 = np.asarray(tel10.inner_hist)
    assert ih10[1] == 2 and ih10[0] == 2  # <=2 twice, <=1 twice.
    assert telemetry_mod.hist_percentile(
        [0] * (telemetry_mod.N_ITER_BUCKETS - 1) + [5], 0.99
    ) is None


def test_telemetry_sentinel_iters_excluded_and_host_hist_aligned():
    """The centralized controller's iters = -1 sentinel never lands in
    the consensus histogram (a centralized rollout must not render a
    bogus solver-effort section), and the HOST-side bucketing
    (iter_histogram — what bench cells and the example print) places
    edge values in the SAME right-closed buckets as the in-jit
    accumulator."""
    cfg = telemetry_mod.TelemetryConfig()
    tel = telemetry_mod.init_telemetry(cfg)
    tel = telemetry_mod.update(cfg, tel, _stats(-1))
    assert int(np.asarray(tel.consensus_hist).sum()) == 0
    # run_health's section guard keys on a non-empty histogram.
    assert sum(telemetry_mod.summary(tel)["effort"]["consensus_hist"]) == 0
    # Edge values: host and in-jit bucketing agree (np.histogram's
    # left-closed bins would shift every power-of-two observation).
    for v in (1, 2, 4, 8, 16, 17, 3000):
        host = int(np.argmax(telemetry_mod.iter_histogram([v])))
        injit = int(telemetry_mod.iter_bucket_index(jnp.asarray(v)))
        assert host == injit, v


def test_telemetry_effort_untracked_and_rollup():
    """Untracked stats (the (0,) sentinel) leave the inner accumulators
    alone; the batched cross-lane roll-up sums histograms and recomputes
    the means."""
    cfg = telemetry_mod.TelemetryConfig()
    tel = telemetry_mod.init_telemetry(cfg)
    tel = telemetry_mod.update(cfg, tel, _stats(5))
    assert int(np.asarray(tel.inner_hist).sum()) == 0
    assert int(tel.inner_iters_sum) == 0
    assert "inner_iters_sum" not in telemetry_mod.summary(tel)["effort"]

    def lane(iters, inner):
        t = telemetry_mod.init_telemetry(cfg)
        return telemetry_mod.update(cfg, t, _stats(iters, inner))

    batched = jax.tree.map(
        lambda *xs: jnp.stack(xs), lane(3, 60), lane(17, 340)
    )
    s = telemetry_mod.summary(batched)
    assert s["lanes"] == 2
    eff = s["effort"]
    assert sum(eff["consensus_hist"]) == 2
    assert eff["inner_iters_sum"] == 400
    assert eff["iters_mean"] == pytest.approx(10.0)


def test_run_health_effort_section_and_columns(tmp_path):
    """run_health renders the solver-effort telemetry section and the
    bench table's effort + iters columns from plain v4 cell fields."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import run_health

    from tpu_aerial_transport.obs import export as export_mod

    path = str(tmp_path / "rh.metrics.jsonl")
    w = export_mod.MetricsWriter(path)
    w.emit("bench_cell", cell="cadmm_n16_effort_adaptive",
           value={"rung": "cpu-tagged", "effort": "adaptive",
                  "effort_resolved": "adaptive", "iters_mean": 5.25,
                  "iters_p99": 9.0})
    w.emit("bench_cell", cell="cadmm_n16_effort_fixed",
           value={"rung": "cpu-tagged", "effort": "auto",
                  "effort_resolved": "fixed", "iters_mean": 5.25})
    s = run_health.summarize(export_mod.read_events(path))
    rows = {r[0]: r for r in s["backend"]["rungs"]}
    row = rows["cadmm_n16_effort_adaptive"]
    assert row[4] == "adaptive" and row[5] == "5.2/9"
    assert rows["cadmm_n16_effort_fixed"][4] == "auto(fixed)"
    # The telemetry effort section renders without crashing and carries
    # the histogram line (capsys-free: render to stdout via capsys would
    # couple to pytest plugins; summarize()'s dict is the contract and
    # render() is exercised on it below).
    cfg = telemetry_mod.TelemetryConfig()
    tel = telemetry_mod.update(
        cfg, telemetry_mod.init_telemetry(cfg), _stats(3, 60)
    )
    w.emit("rollout_summary",
           logs={"steps": 1, "rung_hist": [1, 0, 0, 0],
                 "min_env_dist": 1.0, "collision_steps": 0,
                 "residual": {"max": None}},
           telemetry=telemetry_mod.summary(tel))
    s = run_health.summarize(export_mod.read_events(path))
    assert s["telemetry"]["effort"]["consensus_hist"][2] == 1
    run_health.render(s)  # must not raise on the new sections.


def test_logs_summary_consensus_iters():
    """obs.export.logs_summary carries the exact consensus-iteration
    digest (additive fields, schema-legal)."""
    from tpu_aerial_transport.obs import export as export_mod

    class Logs:
        fallback_rung = np.zeros((4,), np.int32)
        solve_res = np.full((4,), 1e-3, np.float32)
        min_env_dist = np.ones((4,), np.float32)
        collision = np.zeros((4,), bool)
        quarantined = np.zeros((4,), bool)
        iters = np.array([2, 4, 9, -1], np.int32)

    out = export_mod.logs_summary(Logs())
    ci = out["consensus_iters"]
    assert ci["count"] == 3  # centralized's -1 excluded.
    assert ci["mean"] == pytest.approx(5.0)
    assert ci["max"] == 9


# ----------------------------- bench cell -------------------------------


def test_bench_effort_ab_cell(monkeypatch):
    """bench._effort_ab_cell records the effort/effort_resolved pair, the
    iteration-histogram fields, the residual quality bar, and — adaptive
    arm only — the inner-effort fields (monkeypatched measurement, the
    bf16-gate test idiom)."""
    sys.path.insert(0, REPO)
    import bench

    iters_seq = np.array([[3, 9], [3, 17]], np.int32)
    inner_seq = np.array([[60, 180], [60, 340]], np.int32)

    def fake_measure(controller, n, ns, effort, n_steps=10):
        inner = inner_seq if effort == "adaptive" else None
        return 1000.0, 1.0, iters_seq, inner, 2e-3

    monkeypatch.setattr(bench, "_effort_measure", fake_measure)
    v = bench._effort_ab_cell("cadmm", 16, 8, "adaptive")
    assert v["effort"] == "adaptive"
    assert v["effort_resolved"] == "adaptive"
    # The solve label rides the ONE shared resolver with the chunking
    # folded in ("auto" resolves to scan on this CPU host).
    assert v["fused_resolved"] == "scan"
    assert v["final_consensus_res"] == 2e-3 and v["res_bar"] == 1e-2
    assert v["iters_mean"] == pytest.approx(iters_seq.mean())
    assert v["iters_p99"] >= 9
    assert sum(v["iters_hist"]) == 4
    assert v["inner_iters_mean_per_step"] == pytest.approx(inner_seq.mean())
    assert "inner_hist" in v and "inner_per_solve_mean" in v
    v = bench._effort_ab_cell("dd", 16, 8, "fixed")
    assert v["effort_resolved"] == "fixed"
    assert "inner_hist" not in v
